"""Tracing & metrics plane (SURVEY.md §5: the reference has NONE — its
closest facility is per-round mix timing logs).

Three layers:

- **Span histograms** (always on, ~O(100 ns)/record): every RPC dispatch
  and every mix round records into a fixed-size log-bucketed histogram
  (quarter-octave buckets, ~19% relative quantile error) per span name,
  so ``trace_status()`` reports TRUE p50/p90/p99/max — not the
  count/mean/max "p50-ish" aggregates this module used to serve.
  Monotonic **counters** (rpc errors, mix failures, bytes shipped) ride
  the same registry. Histograms expose a mergeable ``snapshot()`` so
  ``jubactl metrics`` can fold every member's buckets into one exact
  cluster-wide quantile view, and a Prometheus text exposition
  (``prometheus_text``) served by utils/metrics_http.py.
- **Trace context** (request-scoped): a thread-local (trace_id, span_id)
  pair propagated through the RPC envelope (rpc/client.py attaches it,
  rpc/server.py adopts it), so a proxied call shows up as ONE trace — the
  proxy hop and the backend hop record the same trace_id into their own
  registries (``trace.<name>.last_trace_id`` in get_status).
- **Span store** (ISSUE 4): every registry keeps a bounded ring of span
  records INDEXED BY trace_id (parent/child edges from the envelope's
  ``{"t","s"}`` element), served over the ``get_spans`` RPC so ``jubactl
  -c trace TRACE_ID`` can assemble one cross-node span tree. Tail-based
  slow-request capture rides the same record path: a span at/above a
  configurable quantile of its own histogram lands in the slow-log ring
  (utils/slowlog.py) and stamps a Prometheus exemplar on its bucket.
- **XLA device traces** (opt-in): ``device_trace()`` wraps
  ``jax.profiler.trace`` when ``JUBATUS_TPU_TRACE_DIR`` is set (or a dir
  is passed), capturing TensorBoard-viewable TPU timelines of the jitted
  update/mix kernels. A no-op otherwise — zero cost in production.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from jubatus_tpu.utils.events import EventJournal
from jubatus_tpu.utils.slowlog import SlowLog

# -- histogram geometry -------------------------------------------------------
# Quarter-octave log buckets from 2^-20 s (~1 us) to 2^7 s (128 s) plus an
# overflow bucket: 109 fixed slots, bucket index is one log2 + one
# multiply — cheap enough for the RPC dispatch hot path.
_LOG2_MIN = -20
_SUB = 4                       # buckets per octave (2^(1/4) ~ 1.19x width)
_OCTAVES = 27
_OVERFLOW = _OCTAVES * _SUB    # index of the overflow bucket
_NBUCKETS = _OVERFLOW + 1
_MIN_S = 2.0 ** _LOG2_MIN
#: upper bound (seconds) of each finite bucket
_BOUNDS = [2.0 ** (_LOG2_MIN + (i + 1) / _SUB) for i in range(_OVERFLOW)]
#: geometric-midpoint factor: bucket value = upper_bound * 2^(-1/(2*SUB))
_MID = 2.0 ** (-0.5 / _SUB)


def bucket_index(seconds: float) -> int:
    """Histogram slot for a duration (clamped to [0, overflow])."""
    if seconds <= _MIN_S:
        return 0
    i = int((math.log2(seconds) - _LOG2_MIN) * _SUB)
    return i if i < _OVERFLOW else _OVERFLOW


class Histogram:
    """One span name's fixed-size log-bucketed latency histogram.

    Not internally locked — the owning Registry serializes access (one
    registry lock per record beats per-histogram locks at our fan-in).
    """

    __slots__ = ("counts", "count", "total_s", "max_s", "last_s",
                 "last_trace_id", "exemplars", "slow_threshold_s")

    def __init__(self) -> None:
        self.counts = [0] * _NBUCKETS
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.last_s = 0.0
        self.last_trace_id = ""
        #: bucket index -> (trace_id, seconds, unix_ts) of the most recent
        #: SLOW request that landed there (Prometheus exemplars: the
        #: p99-spike bucket links straight to a trace)
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}
        #: cached slow-log quantile threshold (refreshed every 64 records
        #: so the hot path pays one compare, not a bucket walk)
        self.slow_threshold_s: Optional[float] = None

    def record(self, seconds: float) -> None:
        self.counts[bucket_index(seconds)] += 1
        self.count += 1
        self.total_s += seconds
        self.last_s = seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def quantile(self, q: float) -> Optional[float]:
        """q-quantile in seconds (geometric bucket midpoint, clamped to
        the observed max); None when empty."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            cum += c
            if cum >= target:
                if i >= _OVERFLOW:
                    return self.max_s
                return min(_BOUNDS[i] * _MID, self.max_s)
        return self.max_s

    def state(self) -> Dict[str, Any]:
        """Wire/JSON-safe mergeable state (sparse buckets)."""
        return {
            "buckets": {i: c for i, c in enumerate(self.counts) if c},
            "count": self.count,
            "total_s": self.total_s,
            "max_s": self.max_s,
            "last_s": self.last_s,
            "last_trace_id": self.last_trace_id,
        }


def merge_hist_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold histogram ``state()`` dicts from N nodes into one (bucket-wise
    sum — quantiles of the merge are exact at bucket resolution). Bucket
    keys may arrive as strings (JSON round trips)."""
    out: Dict[str, Any] = {"buckets": {}, "count": 0, "total_s": 0.0,
                           "max_s": 0.0, "last_s": 0.0, "last_trace_id": ""}
    for st in states:
        for k, c in (st.get("buckets") or {}).items():
            i = int(k)
            out["buckets"][i] = out["buckets"].get(i, 0) + int(c)
        out["count"] += int(st.get("count", 0))
        out["total_s"] += float(st.get("total_s", 0.0))
        out["max_s"] = max(out["max_s"], float(st.get("max_s", 0.0)))
        out["last_s"] = float(st.get("last_s", out["last_s"]))
        out["last_trace_id"] = st.get("last_trace_id") or out["last_trace_id"]
    return out


def state_quantile(state: Dict[str, Any], q: float) -> Optional[float]:
    """Quantile (seconds) from a histogram ``state()``/merged state."""
    count = int(state.get("count", 0))
    if count == 0:
        return None
    target = max(1, math.ceil(q * count))
    cum = 0
    max_s = float(state.get("max_s", 0.0))
    buckets = {int(k): int(v)
               for k, v in (state.get("buckets") or {}).items()}
    for i in sorted(buckets):
        cum += buckets[i]
        if cum >= target:
            if i >= _OVERFLOW:
                return max_s
            return min(_BOUNDS[i] * _MID, max_s)
    return max_s


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold N registry ``snapshot()`` dicts into one cluster-wide view."""
    hist_states: Dict[str, List[Dict[str, Any]]] = {}
    counters: Dict[str, int] = {}
    for snap in snaps:
        for name, st in (snap.get("hists") or {}).items():
            hist_states.setdefault(str(name), []).append(st)
        for name, v in (snap.get("counters") or {}).items():
            counters[str(name)] = counters.get(str(name), 0) + int(v)
    return {"hists": {n: merge_hist_states(sts)
                      for n, sts in hist_states.items()},
            "counters": counters}


# -- trace context ------------------------------------------------------------

class TraceContext:
    """One hop's identity inside a distributed trace. ``peer`` is the
    remote address the request arrived from (best-effort: the Python
    transport stamps it per connection; the C++ transport does not
    surface it) — it rides into slow-log records, not the wire."""

    __slots__ = ("trace_id", "span_id", "parent_id", "peer")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str = "", peer: str = "") -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.peer = peer


_tls = threading.local()
_id_seq = itertools.count(1)
_PROC = os.urandom(4).hex()


def _new_id() -> str:
    # process-unique prefix + atomic counter: ~200 ns, no urandom per call
    return f"{_PROC}{next(_id_seq) & 0xFFFFFFFF:08x}"


def current_trace() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def swap_trace(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as this thread's trace context; returns the
    previous one (restore it in a finally — dispatch pool threads are
    reused, a leaked context would mislabel the next request)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


@contextlib.contextmanager
def use_trace(ctx: Optional[TraceContext]) -> Iterator[None]:
    prev = swap_trace(ctx)
    try:
        yield
    finally:
        swap_trace(prev)


def from_wire(wire: Any) -> TraceContext:
    """Adopt a wire trace element ({"t": trace_id, "s": caller span}) as a
    child context, or start a fresh root when the caller sent none."""
    if isinstance(wire, dict):
        tid = wire.get("t")
        if isinstance(tid, bytes):
            tid = tid.decode("utf-8", "replace")
        parent = wire.get("s", "")
        if isinstance(parent, bytes):
            parent = parent.decode("utf-8", "replace")
        if tid:
            return TraceContext(str(tid), _new_id(), str(parent))
    return TraceContext(_new_id(), _new_id(), "")


def to_wire(ctx: TraceContext) -> Dict[str, str]:
    return {"t": ctx.trace_id, "s": ctx.span_id}


def child_of(ctx: TraceContext) -> TraceContext:
    """A fresh child span of ``ctx`` (same trace, new span id): the
    identity an outbound client call records under, so the receiving
    hop's parent edge points at the CALL, not the whole dispatch."""
    return TraceContext(ctx.trace_id, _new_id(), ctx.span_id)


def new_root() -> TraceContext:
    """A fresh root context (e.g. a mix round starting its own trace)."""
    return TraceContext(_new_id(), _new_id(), "")


# -- the registry -------------------------------------------------------------

#: span records kept per registry, ring-evicted oldest-first and INDEXED
#: by trace_id so get_spans(trace_id) is an O(spans-in-trace) lookup
_SPAN_RING = 512


class _SpanHandle:
    """Yielded by ``Registry.span``: ``seconds`` is the measured duration
    (set at scope exit), ``cancel()`` suppresses the record — the raw
    fast path's RAW_FALLBACK must not double-count with the generic
    handler's own span."""

    __slots__ = ("cancelled", "seconds")

    def __init__(self) -> None:
        self.cancelled = False
        self.seconds = 0.0

    def cancel(self) -> None:
        self.cancelled = True


class Registry:
    """One node's metrics: span histograms + counters + gauges + the
    trace-indexed span store + the slow-request log.

    Each server owns its own so multi-server processes (tests, embedded
    clusters) attribute spans per node; the module-level functions use a
    process default.
    """

    def __init__(self, span_capacity: int = _SPAN_RING) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._span_cap = span_capacity
        self._spans: deque = deque()
        self._by_trace: Dict[str, List[Dict[str, Any]]] = {}
        #: tail-based slow-request ring (utils/slowlog.py); servers tune
        #: it from --slowlog-* flags via slowlog.configure()
        self.slowlog = SlowLog()
        #: cluster event journal (utils/events.py, ISSUE 14): typed,
        #: HLC-stamped state-transition events served over get_events;
        #: counts event.emitted/event.dropped into this registry
        self.events = EventJournal(counter=self.count)
        #: span store + slow log master switch (histograms stay on):
        #: bench_serving.py's overhead A/B flips it
        self._forensics = True
        #: usage-ledger tap (utils/usage.py, ISSUE 19): every recorded
        #: span duration is offered to the ledger, which attributes it
        #: to the dispatch thread's principal. Called OUTSIDE the
        #: registry lock (the sink takes its own).
        self.usage_sink: Optional[Callable[[str, float], None]] = None

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[_SpanHandle]:
        t0 = time.perf_counter()
        h = _SpanHandle()
        try:
            yield h
        finally:
            h.seconds = time.perf_counter() - t0
            if not h.cancelled:
                self.record(name, h.seconds)

    def set_forensics(self, enabled: bool) -> None:
        """Toggle the span store + slow log (histograms/counters stay on)."""
        self._forensics = bool(enabled)

    def record(self, name: str, seconds: float) -> None:
        ctx = getattr(_tls, "ctx", None)
        slow_thr: Optional[float] = None
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.record(seconds)
            forensics = self._forensics
            if forensics:
                sl = self.slowlog
                if sl.capacity > 0 and h.count >= sl.min_count:
                    # cached threshold: a 109-bucket quantile walk per
                    # record would tax the dispatch hot path; refresh
                    # every 64 samples tracks the distribution closely
                    # enough for tail capture
                    thr = h.slow_threshold_s
                    if thr is None or (h.count & 63) == 0:
                        thr = h.slow_threshold_s = h.quantile(sl.quantile)
                    if thr is not None and seconds >= thr:
                        slow_thr = thr
                        h.exemplars[bucket_index(seconds)] = (
                            ctx.trace_id if ctx is not None else "",
                            seconds, time.time())
            if ctx is not None:
                h.last_trace_id = ctx.trace_id
                if forensics:
                    if len(self._spans) >= self._span_cap:
                        old = self._spans.popleft()
                        lst = self._by_trace.get(old["trace_id"])
                        if lst:
                            if lst[0] is old:
                                lst.pop(0)
                            else:  # defensive; eviction is FIFO per trace
                                try:
                                    lst.remove(old)
                                except ValueError:
                                    pass
                            if not lst:
                                del self._by_trace[old["trace_id"]]
                    rec = {
                        "trace_id": ctx.trace_id, "span_id": ctx.span_id,
                        "parent_id": ctx.parent_id, "name": name,
                        "duration_ms": round(seconds * 1e3, 3),
                        "ts": time.time() - seconds}
                    self._spans.append(rec)
                    self._by_trace.setdefault(ctx.trace_id, []).append(rec)
        if slow_thr is not None:
            self._capture_slow(name, seconds, slow_thr, ctx)
        sink = self.usage_sink
        if sink is not None:
            sink(name, seconds)

    def _capture_slow(self, name: str, seconds: float, threshold: float,
                      ctx: Optional[TraceContext]) -> None:
        """Build + ring one slow-request record (outside the registry
        lock — the slow path may consult the deadline plane)."""
        rec: Dict[str, Any] = {
            "method": name,
            "duration_ms": round(seconds * 1e3, 3),
            "threshold_ms": round(threshold * 1e3, 3),
            "trace_id": ctx.trace_id if ctx is not None else "",
            "span_id": ctx.span_id if ctx is not None else "",
            "peer": ctx.peer if ctx is not None else "",
            "ts": round(time.time() - seconds, 3),
        }
        rem = _deadline_remaining()
        if rem is not None:
            rec["deadline_remaining_ms"] = round(rem * 1e3, 3)
        self.slowlog.add(rec)

    def count(self, name: str, n: int = 1) -> None:
        """Bump a monotonic counter (rpc errors, retries, bytes, ...)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauge(self, name: str, value: float) -> None:
        """Set a point-in-time gauge (runtime telemetry: RSS, FDs,
        compile counts, ...) — exported on /metrics, not merged."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def recent_spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def get_spans(self, trace_id: str) -> List[Dict[str, Any]]:
        """All retained span records of one trace, oldest-first — the
        per-node half of the cross-node trace assembly (``get_spans``
        RPC -> jubactl -c trace)."""
        with self._lock:
            return [dict(r) for r in self._by_trace.get(str(trace_id), [])]

    def trace_status(self, prefix: str = "trace") -> Dict[str, Any]:
        """Flattened metrics for get_status maps: trace.<name>.{count,
        mean_ms, p50_ms, p90_ms, p99_ms, max_ms, last_ms[, last_trace_id]}
        plus trace.counter.<name> for the monotonic counters."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, h in self._hists.items():
                n = h.count or 1
                out[f"{prefix}.{name}.count"] = h.count
                out[f"{prefix}.{name}.mean_ms"] = round(h.total_s / n * 1e3, 3)
                for qname, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
                    v = h.quantile(q)
                    out[f"{prefix}.{name}.{qname}_ms"] = \
                        round((v or 0.0) * 1e3, 3)
                out[f"{prefix}.{name}.max_ms"] = round(h.max_s * 1e3, 3)
                out[f"{prefix}.{name}.last_ms"] = round(h.last_s * 1e3, 3)
                if h.last_trace_id:
                    out[f"{prefix}.{name}.last_trace_id"] = h.last_trace_id
            for name, v in self._counters.items():
                out[f"{prefix}.counter.{name}"] = v
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Mergeable raw state for get_metrics / jubactl metrics.
        ``gauges`` ride along for single-node views; merge_snapshots
        ignores them (point-in-time per-process values don't sum)."""
        with self._lock:
            return {"hists": {n: h.state() for n, h in self._hists.items()},
                    "counters": dict(self._counters),
                    "gauges": dict(self._gauges)}

    def prometheus_text(self,
                        labels: Optional[Dict[str, str]] = None) -> str:
        """Prometheus text exposition (format 0.0.4) of every histogram,
        counter, and gauge. Bucket lines are emitted only at occupied
        bucket boundaries (+Inf always) — valid cumulative histograms,
        compact wire. Buckets holding a slow-request capture carry an
        OpenMetrics-style exemplar (``# {trace_id="..."} value ts``) so
        a p99 spike on a dashboard links straight to a trace; scrapers
        that only speak 0.0.4 ignore text after ``#``."""
        base = "".join(f',{k}="{_esc(v)}"'
                       for k, v in sorted((labels or {}).items()))
        lines = [
            "# TYPE jubatus_span_duration_seconds histogram",
            "# HELP jubatus_span_duration_seconds "
            "Span latency by name (log-bucketed).",
        ]
        with self._lock:
            hists = [(n, h.counts[:], h.count, h.total_s, h.max_s,
                      dict(h.exemplars))
                     for n, h in sorted(self._hists.items())]
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
        for name, counts, count, total_s, max_s, exemplars in hists:
            sel = f'span="{_esc(name)}"{base}'
            cum = 0
            for i, c in enumerate(counts):
                if not c or i >= _OVERFLOW:
                    continue
                cum += c
                line = (f"jubatus_span_duration_seconds_bucket{{{sel},"
                        f'le="{_BOUNDS[i]:.9g}"}} {cum}')
                ex = exemplars.get(i)
                if ex is not None and ex[0]:
                    line += (f' # {{trace_id="{_esc(ex[0])}"}} '
                             f"{ex[1]:.9g} {ex[2]:.3f}")
                lines.append(line)
            lines.append(
                f'jubatus_span_duration_seconds_bucket{{{sel},le="+Inf"}} '
                f"{count}")
            lines.append(
                f"jubatus_span_duration_seconds_sum{{{sel}}} {total_s:.9g}")
            lines.append(
                f"jubatus_span_duration_seconds_count{{{sel}}} {count}")
        lines.append("# TYPE jubatus_span_max_seconds gauge")
        for name, _counts, _count, _total, max_s, _ex in hists:
            lines.append(
                f'jubatus_span_max_seconds{{span="{_esc(name)}"{base}}} '
                f"{max_s:.9g}")
        lines.append("# TYPE jubatus_events_total counter")
        for name, v in counters:
            lines.append(
                f'jubatus_events_total{{event="{_esc(name)}"{base}}} {v}')
        if gauges:
            lines.append("# TYPE jubatus_runtime_gauge gauge")
            lines.append("# HELP jubatus_runtime_gauge "
                         "Process runtime telemetry (sampler).")
            for name, v in gauges:
                lines.append(
                    f'jubatus_runtime_gauge{{key="{_esc(name)}"{base}}} '
                    f"{v:.9g}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()
            self._by_trace.clear()
        self.slowlog.clear()
        self.events.clear()


def _esc(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n")


_deadline_mod = None


def _deadline_remaining() -> Optional[float]:
    """Remaining deadline budget for slow-log records. Lazy module cache:
    utils must not import the rpc package at import time (rpc imports
    tracing), and the lookup only runs on the slow-capture cold path."""
    global _deadline_mod
    if _deadline_mod is None:
        from jubatus_tpu.rpc import deadline as _d

        _deadline_mod = _d
    return _deadline_mod.remaining()


_default = Registry()


def default_registry() -> Registry:
    return _default


def span(name: str):
    return _default.span(name)


def record(name: str, seconds: float) -> None:
    _default.record(name, seconds)


def count(name: str, n: int = 1) -> None:
    _default.count(name, n)


def trace_status(prefix: str = "trace") -> Dict[str, Any]:
    return _default.trace_status(prefix)


def reset() -> None:
    _default.reset()


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """XLA/TPU profiler capture around a block — TensorBoard format.
    No-op unless a directory is given or JUBATUS_TPU_TRACE_DIR is set."""
    trace_dir = trace_dir or os.environ.get("JUBATUS_TPU_TRACE_DIR", "")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
