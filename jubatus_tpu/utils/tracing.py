"""Tracing & profiling (SURVEY.md §5: the reference has NONE — its closest
facility is per-round mix timing logs. This subsystem is the first-class
improvement the survey calls for).

Two layers:

- **Span aggregates** (always on, ~100 ns/span): every RPC dispatch and
  every mix round records into per-name aggregates (count / total / max /
  last seconds). ``trace_status()`` flattens them into the ``get_status``
  map, so operators see p50-ish latencies per method cluster-wide through
  the same RPC the reference exposes counters on.
- **XLA device traces** (opt-in): ``device_trace()`` wraps
  ``jax.profiler.trace`` when ``JUBATUS_TPU_TRACE_DIR`` is set (or a dir
  is passed), capturing TensorBoard-viewable TPU timelines of the jitted
  update/mix kernels. A no-op otherwise — zero cost in production.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

class Registry:
    """One set of span aggregates. Each server owns its own so multi-server
    processes (tests, embedded clusters) attribute spans per node; the
    module-level functions use a process default."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._aggregates: Dict[str, Dict[str, float]] = {}

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            agg = self._aggregates.get(name)
            if agg is None:
                agg = self._aggregates[name] = {
                    "count": 0, "total_s": 0.0, "max_s": 0.0, "last_s": 0.0}
            agg["count"] += 1
            agg["total_s"] += seconds
            agg["last_s"] = seconds
            if seconds > agg["max_s"]:
                agg["max_s"] = seconds

    def trace_status(self, prefix: str = "trace") -> Dict[str, Any]:
        """Flattened aggregates for get_status maps: trace.<name>.{count,
        mean_ms,max_ms,last_ms}."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, agg in self._aggregates.items():
                n = int(agg["count"]) or 1
                out[f"{prefix}.{name}.count"] = int(agg["count"])
                out[f"{prefix}.{name}.mean_ms"] = round(agg["total_s"] / n * 1e3, 3)
                out[f"{prefix}.{name}.max_ms"] = round(agg["max_s"] * 1e3, 3)
                out[f"{prefix}.{name}.last_ms"] = round(agg["last_s"] * 1e3, 3)
        return out

    def reset(self) -> None:
        with self._lock:
            self._aggregates.clear()


_default = Registry()


def default_registry() -> Registry:
    return _default


def span(name: str):
    return _default.span(name)


def record(name: str, seconds: float) -> None:
    _default.record(name, seconds)


def trace_status(prefix: str = "trace") -> Dict[str, Any]:
    return _default.trace_status(prefix)


def reset() -> None:
    _default.reset()


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """XLA/TPU profiler capture around a block — TensorBoard format.
    No-op unless a directory is given or JUBATUS_TPU_TRACE_DIR is set."""
    trace_dir = trace_dir or os.environ.get("JUBATUS_TPU_TRACE_DIR", "")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
