"""Tenant-level resource attribution & capacity accounting (ISSUE 19).

Six observability PRs measure *how fast* the system is; this module
measures *who is spending it*. Every dispatched request is attributed
to a **principal** (the tenant id riding the envelope's optional 7th
element, ``rpc/principal.py``); per principal × method the ledger
accounts:

- request / error / retry counts,
- **CPU-thread-seconds** from the span plane: the registry's
  ``usage_sink`` feeds every ``rpc.<method>`` span duration here while
  the dispatch thread still holds the request's principal,
- **coalescer residency**: queue-wait seconds plus device-batch time
  amortized by rows contributed per flush (``server/microbatch.py``
  tickets carry the principal),
- bytes in / out.

Traffic that names no principal folds into ``(untagged)``; the
system's own work (mix, telemetry, store, migration) into
``(system)`` — the books always close, which the bench proves with a
**conservation gate**: per-principal accounted CPU sums to within 10%
of the process's span-plane total (``e2e_usage_attribution_err_frac``).

Cardinality is bounded two ways (zipf users must not blow the ledger
up): an EXACT table for the first ``top`` (64) principals with the
long tail folded into ``(other)``, plus a :class:`CategoricalSketch`
heavy-hitter lane that keeps identifying heavy principals even past
the cap and merges exactly across the fleet (PR 17's machinery).

The **capacity model** layers on top: per tick, per-principal demand
(rows/s and CPU-share deltas) is compared against the replica's
measured flush throughput — the same signal the autoscaler uses — and
published as ``usage.<principal>.*`` / ``capacity.*`` gauges, SLO-able
via the existing ``gauge:`` grammar. ``capacity.saturation``
(demand/capacity, alarms HIGH — the ``gauge:`` grammar fires on high
means) is the SLO form; ``capacity.headroom`` is its up-good
complement for operators and benches.

``server/base.py`` ticks the ledger from the telemetry thread and
ships ``snapshot()`` through the idempotent ``get_usage`` RPC;
``merge_usage`` is the proxy/CLI fold (table sum + sketch merge —
never gauge averaging).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jubatus_tpu.rpc import principal as principals
from jubatus_tpu.utils import sketches

#: exact-table row fields, in wire order (snapshot rows are lists —
#: compact on the wire, summed element-wise in the fold)
FIELDS = ("requests", "errors", "retries", "cpu_seconds",
          "queue_seconds", "device_seconds", "rows",
          "bytes_in", "bytes_out")
_NFIELDS = len(FIELDS)
_IDX = {f: i for i, f in enumerate(FIELDS)}

#: the ledger row the exact table's long tail folds into once ``top``
#: distinct principals exist (the sketch lane still sees everyone)
OVERFLOW = "(other)"

#: a request with no principal on an un-tenanted method is the
#: system's own work: mix rounds, telemetry/forensics reads, store
#: uploads, migration/drain, autoscaler actuation. Anything else
#: untagged is user traffic from a client that never stamped a tenant.
_SYSTEM_METHOD_RE = re.compile(
    r"^(mix|do_mix|get_|put_|take_|save|load|clear|store|migrate|"
    r"drain|rebalance|rollback|restore|warm|snapshot|diff|iterate|"
    r"profile|bootstrap|name|version)")

#: gauge keys must stay shell/dot safe; tenant ids are operator input
_SANITIZE_RE = re.compile(r"[^A-Za-z0-9_-]")


def classify(principal: Optional[str], method: str) -> str:
    """Resolve the ledger row a request bills to: its wire principal,
    else ``(system)`` for the fleet's own methods, else
    ``(untagged)``."""
    if principal:
        return principal
    if _SYSTEM_METHOD_RE.match(method or ""):
        return principals.SYSTEM
    return principals.UNTAGGED


def sanitize(principal: str) -> str:
    """A principal as a gauge-key segment (dots would splice into the
    metric namespace, so every non-word char folds to ``_``)."""
    return _SANITIZE_RE.sub("_", principal) or "_"


class UsageLedger:
    """Per-process principal × method resource ledger. All entry
    points are thread-safe (one lock; record paths are O(1) dict
    bumps) and every accumulator is mergeable across the fleet."""

    def __init__(self, *, top: int = 64, gauge_principals: int = 8,
                 registry: Any = None) -> None:
        self.top = max(1, int(top))
        self.gauge_principals = max(1, int(gauge_principals))
        self.registry = registry
        self._lock = threading.Lock()
        #: principal -> method -> [FIELDS...] (exact, bounded)
        self._table: Dict[str, Dict[str, List[float]]] = {}
        #: heavy-hitter lane: observes EVERY principal by rows+requests
        #: weight, so heavy tenants stay identifiable past the cap
        self._sketch = sketches.CategoricalSketch()
        self._capacity = 0.0
        self._last_ts: Optional[float] = None
        self._last_rows: Dict[str, float] = {}
        self._last_cpu: Dict[str, float] = {}
        self._demand: Dict[str, float] = {}
        self._cpu_share: Dict[str, float] = {}

    # -- recording -----------------------------------------------------------
    def _row_locked(self, principal: str, method: str) -> List[float]:
        by_m = self._table.get(principal)
        if by_m is None:
            if len(self._table) >= self.top and principal not in \
                    (principals.UNTAGGED, principals.SYSTEM, OVERFLOW):
                principal = OVERFLOW
                by_m = self._table.get(principal)
                if by_m is None:
                    by_m = self._table[principal] = {}
            else:
                by_m = self._table[principal] = {}
        row = by_m.get(method)
        if row is None:
            row = by_m[method] = [0.0] * _NFIELDS
        return row

    def account(self, method: str, *, principal: Optional[str] = None,
                resolve: bool = True, **amounts: float) -> None:
        """The core accumulator: bump ``FIELDS`` amounts for one
        principal × method cell. ``principal=None`` with ``resolve``
        reads the dispatch thread's principal and classifies."""
        if principal is None and resolve:
            principal = principals.current()
        p = classify(principal, method)
        with self._lock:
            row = self._row_locked(p, method)
            for k, v in amounts.items():
                row[_IDX[k]] += v
            if amounts.get("requests") or amounts.get("rows"):
                self._sketch.observe(
                    p, int(amounts.get("requests", 0))
                    + int(amounts.get("rows", 0)))

    def span_sink(self, name: str, seconds: float) -> None:
        """Registry ``usage_sink`` hook: every completed span lands
        here. Server dispatch spans are ``rpc.<method>`` and fire while
        the dispatch thread still holds the request's principal — each
        one is one request plus its CPU-thread-seconds. Client-side
        spans (``rpc.client.*``) are the same work seen from the
        caller; counting them would double-bill, so they're skipped."""
        if not name.startswith("rpc.") or name.startswith("rpc.client."):
            return
        self.account(name[4:], requests=1, cpu_seconds=float(seconds))

    def note_error(self, method: str) -> None:
        self.account(method, errors=1)

    def note_retry(self, method: str) -> None:
        self.account(method, retries=1)

    def note_bytes(self, method: str, bytes_in: int = 0,
                   bytes_out: int = 0) -> None:
        self.account(method, bytes_in=float(bytes_in),
                     bytes_out=float(bytes_out))

    def record_batch(self, principal: Optional[str], method: str,
                     rows: float, queue_seconds: float,
                     device_seconds: float) -> None:
        """Coalescer completion hook: one ticket's share of a device
        flush — ``rows`` it contributed, its queue residency, and the
        flush's device time amortized by rows (microbatch carries the
        submitting thread's principal on the ticket)."""
        self.account(method, principal=principal, rows=float(rows),
                     queue_seconds=float(queue_seconds),
                     device_seconds=float(device_seconds))

    # -- capacity model ------------------------------------------------------
    def tick(self, capacity_rows_per_sec: float = 0.0,
             now: Optional[float] = None) -> Dict[str, float]:
        """One telemetry tick: recompute per-principal demand from the
        deltas since the last tick, compare against the replica's
        measured capacity, publish the ``usage.*`` / ``capacity.*``
        gauges. Returns the gauge dict (tests read it directly)."""
        now = time.time() if now is None else float(now)
        if capacity_rows_per_sec > 0.0:
            self._capacity = float(capacity_rows_per_sec)
        with self._lock:
            rows_now: Dict[str, float] = {}
            cpu_now: Dict[str, float] = {}
            for p, by_m in self._table.items():
                rows_now[p] = sum(
                    r[_IDX["rows"]] + r[_IDX["requests"]]
                    for r in by_m.values())
                cpu_now[p] = sum(r[_IDX["cpu_seconds"]]
                                 for r in by_m.values())
            dt = 0.0 if self._last_ts is None else now - self._last_ts
            if dt > 0.0:
                self._demand = {
                    p: max(0.0, (v - self._last_rows.get(p, 0.0)) / dt)
                    for p, v in rows_now.items()}
                self._cpu_share = {
                    p: max(0.0, (v - self._last_cpu.get(p, 0.0)) / dt)
                    for p, v in cpu_now.items()}
            self._last_ts = now
            self._last_rows = rows_now
            self._last_cpu = cpu_now
            demand = dict(self._demand)
            cpu_share = dict(self._cpu_share)
            nprincipals = len(self._table)
            cap = self._capacity
        gauges: Dict[str, float] = {"usage.principals": float(nprincipals)}
        # top-N principals by current demand (CPU-share breaks ties):
        # the gauge namespace stays bounded no matter the tenant count
        ranked = sorted(demand,
                        key=lambda p: (demand.get(p, 0.0),
                                       cpu_share.get(p, 0.0)),
                        reverse=True)[:self.gauge_principals]
        for p in ranked:
            s = sanitize(p)
            gauges[f"usage.{s}.demand_rows_per_sec"] = \
                round(demand.get(p, 0.0), 3)
            gauges[f"usage.{s}.cpu_share"] = \
                round(cpu_share.get(p, 0.0), 6)
        total_demand = sum(demand.values())
        if cap > 0.0:
            sat = total_demand / cap
            gauges["capacity.rows_per_sec"] = round(cap, 1)
            gauges["capacity.saturation"] = round(sat, 4)
            gauges["capacity.headroom"] = round(max(0.0, 1.0 - sat), 4)
        reg = self.registry
        if reg is not None:
            for k, v in gauges.items():
                reg.gauge(k, v)
        return gauges

    # -- views ---------------------------------------------------------------
    def totals(self) -> Dict[str, float]:
        """Process-wide sums over every principal × method cell — the
        side of the books the conservation gate compares against the
        span plane."""
        with self._lock:
            out = {f: 0.0 for f in FIELDS}
            for by_m in self._table.values():
                for row in by_m.values():
                    for i, f in enumerate(FIELDS):
                        out[f] += row[i]
            return out

    def snapshot(self) -> Dict[str, Any]:
        """This node's mergeable usage doc — the ``get_usage`` RPC
        payload (exact table, sketch state, capacity + last demand)."""
        with self._lock:
            return {
                "top": self.top,
                "table": {p: {m: list(r) for m, r in by_m.items()}
                          for p, by_m in self._table.items()},
                "sketch": self._sketch.state(),
                "capacity_rows_per_sec": self._capacity,
                "demand": {p: round(v, 3)
                           for p, v in self._demand.items()},
                "cpu_share": {p: round(v, 6)
                              for p, v in self._cpu_share.items()},
                "ts": time.time(),
            }

    def incident_doc(self) -> Dict[str, Any]:
        """The forensic slice an incident bundle captures: who was
        spending the replica when it breached — top principals by CPU
        with their full rows, plus the capacity picture."""
        with self._lock:
            cpu = {p: sum(r[_IDX["cpu_seconds"]] for r in by_m.values())
                   for p, by_m in self._table.items()}
            top = sorted(cpu, key=lambda p: cpu[p],
                         reverse=True)[:self.gauge_principals]
            doc: Dict[str, Any] = {
                "capacity_rows_per_sec": self._capacity,
                "demand": {p: round(v, 3)
                           for p, v in self._demand.items()},
                "top_principals": {
                    p: {m: dict(zip(FIELDS, r))
                        for m, r in self._table[p].items()}
                    for p in top},
            }
            return doc

    def stats(self) -> Dict[str, Any]:
        """Flat stat rows for get_status (``usage.*`` keys)."""
        with self._lock:
            cpu = {p: sum(r[_IDX["cpu_seconds"]] for r in by_m.values())
                   for p, by_m in self._table.items()}
            reqs = sum(r[_IDX["requests"]] for by_m in self._table.values()
                       for r in by_m.values())
            demand = dict(self._demand)
            cap = self._capacity
        # the watch column wants ONE name: the principal currently
        # demanding the most (CPU breaks the no-demand-yet tie)
        top = max(demand or cpu, key=lambda p: (demand.get(p, 0.0),
                                                cpu.get(p, 0.0)),
                  default="")
        out: Dict[str, Any] = {
            "principals": len(cpu),
            "requests": int(reqs),
            "cpu_seconds": round(sum(cpu.values()), 3),
            "top_principal": top,
            "top_demand_rows_per_sec": round(demand.get(top, 0.0), 1),
        }
        if cap > 0.0:
            sat = sum(demand.values()) / cap
            out["capacity_rows_per_sec"] = round(cap, 1)
            out["headroom"] = round(max(0.0, 1.0 - sat), 4)
        return out


# -- client-retry fan-in ----------------------------------------------------

#: ledgers attached for retry attribution: the RPC *client* sees the
#: retry (the server just sees another request), so the client layer
#:  notes it into whatever ledgers this process runs
_ATTACHED: List[UsageLedger] = []
_ATTACH_LOCK = threading.Lock()


def attach(ledger: UsageLedger) -> None:
    with _ATTACH_LOCK:
        if ledger not in _ATTACHED:
            _ATTACHED.append(ledger)


def detach(ledger: UsageLedger) -> None:
    with _ATTACH_LOCK:
        if ledger in _ATTACHED:
            _ATTACHED.remove(ledger)


def note_retry(method: str) -> None:
    """Client-layer hook: one retried attempt on ``method`` (billed to
    the calling thread's principal in every attached ledger)."""
    with _ATTACH_LOCK:
        targets = list(_ATTACHED)
    for led in targets:
        led.note_retry(method)


# -- fleet fold -------------------------------------------------------------

def merge_usage(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node ``get_usage`` docs into one fleet view: sum the
    exact tables cell-wise (re-folding the long tail once the union
    passes the cap), MERGE the heavy-hitter sketches (that is what
    mergeable sketches buy — fleet heavy hitters are exact, not an
    average of node top-ks), and SUM capacity/demand across replicas
    (capacity is additive over a fleet; headroom is recomputed from
    the sums, never averaged)."""
    table: Dict[str, Dict[str, List[float]]] = {}
    demand: Dict[str, float] = {}
    cpu_share: Dict[str, float] = {}
    states: List[Dict[str, Any]] = []
    cap = 0.0
    top = 64
    for d in docs:
        if not d:
            continue
        top = max(top, int(d.get("top", 0)))
        cap += float(d.get("capacity_rows_per_sec", 0.0))
        for p, v in (d.get("demand") or {}).items():
            demand[p] = demand.get(p, 0.0) + float(v)
        for p, v in (d.get("cpu_share") or {}).items():
            cpu_share[p] = cpu_share.get(p, 0.0) + float(v)
        if d.get("sketch"):
            states.append(d["sketch"])
        for p, by_m in (d.get("table") or {}).items():
            dst = table.setdefault(p, {})
            for m, row in by_m.items():
                acc = dst.setdefault(m, [0.0] * _NFIELDS)
                for i in range(min(_NFIELDS, len(row))):
                    acc[i] += float(row[i])
    if len(table) > top:  # union overflow: re-fold the smallest tails
        cpu = {p: sum(r[_IDX["cpu_seconds"]] + r[_IDX["requests"]]
                      for r in by_m.values())
               for p, by_m in table.items()}
        keep = set(sorted(
            cpu, key=lambda p: cpu[p], reverse=True)[:top]) \
            | {principals.UNTAGGED, principals.SYSTEM, OVERFLOW}
        fold = table.setdefault(OVERFLOW, {})
        for p in [p for p in table if p not in keep and p != OVERFLOW]:
            for m, row in table.pop(p).items():
                acc = fold.setdefault(m, [0.0] * _NFIELDS)
                for i in range(_NFIELDS):
                    acc[i] += row[i]
    total_demand = sum(demand.values())
    out: Dict[str, Any] = {
        "nodes": len([d for d in docs if d]),
        "top": top,
        "table": table,
        "sketch": sketches.merge_categorical_states(states),
        "capacity_rows_per_sec": round(cap, 1),
        "demand": {p: round(v, 3) for p, v in demand.items()},
        "cpu_share": {p: round(v, 6) for p, v in cpu_share.items()},
    }
    if cap > 0.0:
        sat = total_demand / cap
        out["saturation"] = round(sat, 4)
        out["headroom"] = round(max(0.0, 1.0 - sat), 4)
    return out


def principal_rows(doc: Dict[str, Any]) -> List[Tuple[str, Dict[str, float]]]:
    """A (merged or single-node) usage doc as per-principal summary
    rows sorted by CPU-seconds — the ``jubactl -c usage`` render
    order."""
    out: List[Tuple[str, Dict[str, float]]] = []
    for p, by_m in (doc.get("table") or {}).items():
        agg = {f: 0.0 for f in FIELDS}
        for row in by_m.values():
            for i, f in enumerate(FIELDS):
                agg[f] += float(row[i]) if i < len(row) else 0.0
        agg["methods"] = float(len(by_m))
        agg["demand_rows_per_sec"] = float(
            (doc.get("demand") or {}).get(p, 0.0))
        out.append((p, agg))
    out.sort(key=lambda kv: (kv[1]["cpu_seconds"], kv[1]["requests"]),
             reverse=True)
    return out
