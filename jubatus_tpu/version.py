"""Framework version.

The reference declares VERSION='1.0.2' in wscript:7; we keep an independent
version for the new framework plus the reference compatibility version used
in the checkpoint envelope (framework/save_load.py).
"""

VERSION = "0.1.0"
__version__ = VERSION

# Version of the jubatus API surface we are compatible with (reference
# wscript:7). Embedded in saved model headers for tool parity.
COMPAT_JUBATUS_VERSION = (1, 0, 2)
