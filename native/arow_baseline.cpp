// Faithful C++ per-example AROW baseline for bench.py's vs_baseline.
//
// The reference publishes no benchmark figures (SURVEY.md §6) and its hot
// loop is the per-datum C++ driver update under a write lock
// (classifier_serv.cpp:127-146; the math lives in jubatus_core's
// arow.cpp). Round 1 compared against a per-example numpy loop, which
// undersells a real C++ deployment; this file is the same sequential
// per-example AROW (binary, dense [2, D] weight + inverse-precision
// tables, sparse examples) compiled with -O3 — the closest measurable
// stand-in for the reference's single-core serving thread.
//
// ABI: double jt_arow_baseline(const int32_t* idx, const float* val,
//                              const int32_t* labels, int n, int k,
//                              int64_t dim, float r)
// returns examples/second over the n examples (timed internally so the
// ctypes call overhead is excluded).

#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

double jt_arow_baseline(const int32_t* idx, const float* val,
                        const int32_t* labels, int n, int k, int64_t dim,
                        float r) {
  std::vector<float> w(2 * size_t(dim), 0.0f);
  std::vector<float> sigma(2 * size_t(dim), 1.0f);
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < n; ++i) {
    const int32_t* ii = idx + size_t(i) * k;
    const float* vv = val + size_t(i) * k;
    int y = labels[i];
    int o = 1 - y;
    float* wy = w.data() + size_t(y) * dim;
    float* wo = w.data() + size_t(o) * dim;
    float* sy = sigma.data() + size_t(y) * dim;
    float* so = sigma.data() + size_t(o) * dim;
    // margin = s[y] - s[o]
    float s_y = 0.0f, s_o = 0.0f;
    for (int j = 0; j < k; ++j) {
      s_y += wy[ii[j]] * vv[j];
      s_o += wo[ii[j]] * vv[j];
    }
    float margin = s_y - s_o;
    float loss = 1.0f - margin;
    if (loss <= 0.0f) continue;
    float variance = 0.0f;
    for (int j = 0; j < k; ++j) {
      float x2 = vv[j] * vv[j];
      variance += (sy[ii[j]] + so[ii[j]]) * x2;
    }
    float beta = 1.0f / (variance + r);
    float alpha = loss * beta;
    for (int j = 0; j < k; ++j) {
      float x = vv[j];
      wy[ii[j]] += alpha * sy[ii[j]] * x;
      wo[ii[j]] -= alpha * so[ii[j]] * x;
      float prec_inc = x * x / r;
      sy[ii[j]] = 1.0f / (1.0f / sy[ii[j]] + prec_inc);
      so[ii[j]] = 1.0f / (1.0f / so[ii[j]] + prec_inc);
    }
  }
  auto dt = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - t0)
                .count();
  // keep the tables alive past the timer (defeats dead-code elimination)
  volatile float sink = w[0] + sigma[size_t(dim)];
  (void)sink;
  return dt > 0.0 ? double(n) / dt : 0.0;
}

}  // extern "C"
