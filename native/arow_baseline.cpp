// Faithful C++ per-example AROW baseline for bench.py's vs_baseline.
//
// The reference publishes no benchmark figures (SURVEY.md §6) and its hot
// loop is the per-datum C++ driver update under a write lock
// (classifier_serv.cpp:127-146; the math lives in jubatus_core's
// arow.cpp). Round 1 compared against a per-example numpy loop, which
// undersells a real C++ deployment; this file is the same sequential
// per-example AROW (binary, dense [2, D] weight + inverse-precision
// tables, sparse examples) compiled with -O3 — the closest measurable
// stand-in for the reference's single-core serving thread.
//
// ABI: double jt_arow_baseline(const int32_t* idx, const float* val,
//                              const int32_t* labels, int n, int k,
//                              int64_t dim, float r)
// returns examples/second over the n examples (timed internally so the
// ctypes call overhead is excluded).

#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// One multiclass AROW example: score all L labels, update the correct
// row and the best wrong row (jubatus_core's multiclass arow shape —
// the per-example cost the reference pays is LINEAR in L because every
// label's row is gathered for the scores).
inline void arow_example_multi(float* w, float* sigma, const int32_t* ii,
                               const float* vv, int y, int k, int64_t dim,
                               int L, float r, float* scores) {
  for (int l = 0; l < L; ++l) {
    const float* wl = w + size_t(l) * dim;
    float s = 0.0f;
    for (int j = 0; j < k; ++j) s += wl[ii[j]] * vv[j];
    scores[l] = s;
  }
  int o = y == 0 ? 1 : 0;
  for (int l = 0; l < L; ++l)
    if (l != y && scores[l] > scores[o]) o = l;
  float margin = scores[y] - scores[o];
  float loss = 1.0f - margin;
  if (loss <= 0.0f) return;
  float* wy = w + size_t(y) * dim;
  float* wo = w + size_t(o) * dim;
  float* sy = sigma + size_t(y) * dim;
  float* so = sigma + size_t(o) * dim;
  float variance = 0.0f;
  for (int j = 0; j < k; ++j) {
    float x2 = vv[j] * vv[j];
    variance += (sy[ii[j]] + so[ii[j]]) * x2;
  }
  float beta = 1.0f / (variance + r);
  float alpha = loss * beta;
  for (int j = 0; j < k; ++j) {
    float x = vv[j];
    wy[ii[j]] += alpha * sy[ii[j]] * x;
    wo[ii[j]] -= alpha * so[ii[j]] * x;
    float prec_inc = x * x / r;
    sy[ii[j]] = 1.0f / (1.0f / sy[ii[j]] + prec_inc);
    so[ii[j]] = 1.0f / (1.0f / so[ii[j]] + prec_inc);
  }
}

}  // namespace

extern "C" {

double jt_arow_baseline(const int32_t* idx, const float* val,
                        const int32_t* labels, int n, int k, int64_t dim,
                        float r) {
  std::vector<float> w(2 * size_t(dim), 0.0f);
  std::vector<float> sigma(2 * size_t(dim), 1.0f);
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < n; ++i) {
    const int32_t* ii = idx + size_t(i) * k;
    const float* vv = val + size_t(i) * k;
    int y = labels[i];
    int o = 1 - y;
    float* wy = w.data() + size_t(y) * dim;
    float* wo = w.data() + size_t(o) * dim;
    float* sy = sigma.data() + size_t(y) * dim;
    float* so = sigma.data() + size_t(o) * dim;
    // margin = s[y] - s[o]
    float s_y = 0.0f, s_o = 0.0f;
    for (int j = 0; j < k; ++j) {
      s_y += wy[ii[j]] * vv[j];
      s_o += wo[ii[j]] * vv[j];
    }
    float margin = s_y - s_o;
    float loss = 1.0f - margin;
    if (loss <= 0.0f) continue;
    float variance = 0.0f;
    for (int j = 0; j < k; ++j) {
      float x2 = vv[j] * vv[j];
      variance += (sy[ii[j]] + so[ii[j]]) * x2;
    }
    float beta = 1.0f / (variance + r);
    float alpha = loss * beta;
    for (int j = 0; j < k; ++j) {
      float x = vv[j];
      wy[ii[j]] += alpha * sy[ii[j]] * x;
      wo[ii[j]] -= alpha * so[ii[j]] * x;
      float prec_inc = x * x / r;
      sy[ii[j]] = 1.0f / (1.0f / sy[ii[j]] + prec_inc);
      so[ii[j]] = 1.0f / (1.0f / so[ii[j]] + prec_inc);
    }
  }
  auto dt = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - t0)
                .count();
  // keep the tables alive past the timer (defeats dead-code elimination)
  volatile float sink = w[0] + sigma[size_t(dim)];
  (void)sink;
  return dt > 0.0 ? double(n) / dt : 0.0;
}

// Multiclass sequential AROW: the reference's cost model is linear in L
// (score gather touches every label row). Returns examples/second.
double jt_arow_baseline_multi(const int32_t* idx, const float* val,
                              const int32_t* labels, int n, int k,
                              int64_t dim, int L, float r) {
  std::vector<float> w(size_t(L) * dim, 0.0f);
  std::vector<float> sigma(size_t(L) * dim, 1.0f);
  std::vector<float> scores(L);
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < n; ++i)
    arow_example_multi(w.data(), sigma.data(), idx + size_t(i) * k,
                       val + size_t(i) * k, labels[i], k, dim, L, r,
                       scores.data());
  auto dt = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - t0)
                .count();
  volatile float sink = w[0] + sigma[size_t(dim)];
  (void)sink;
  return dt > 0.0 ? double(n) / dt : 0.0;
}

// Concurrent-serving shape: nthreads ingest threads share ONE model
// under one write lock (the reference's JWLOCK_ around every update,
// classifier_serv.cpp:127-146). Returns aggregate examples/second —
// updates serialize on the lock, so added threads buy contention, not
// throughput (the chip's answer is batching, not locking).
double jt_arow_baseline_locked(const int32_t* idx, const float* val,
                               const int32_t* labels, int n, int k,
                               int64_t dim, int L, float r, int nthreads) {
  std::vector<float> w(size_t(L) * dim, 0.0f);
  std::vector<float> sigma(size_t(L) * dim, 1.0f);
  std::mutex mu;
  std::atomic<int> next{0};
  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> ts;
  for (int t = 0; t < nthreads; ++t) {
    ts.emplace_back([&] {
      std::vector<float> scores(L);
      while (true) {
        int i = next.fetch_add(1);
        if (i >= n) return;
        std::lock_guard<std::mutex> g(mu);
        arow_example_multi(w.data(), sigma.data(), idx + size_t(i) * k,
                           val + size_t(i) * k, labels[i], k, dim, L, r,
                           scores.data());
      }
    });
  }
  for (auto& t : ts) t.join();
  auto dt = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - t0)
                .count();
  volatile float sink = w[0] + sigma[size_t(dim)];
  (void)sink;
  return dt > 0.0 ? double(n) / dt : 0.0;
}

}  // extern "C"
