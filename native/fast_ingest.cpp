// jt_ingest — native train-request parser: raw msgpack bytes -> hashed
// sparse batch, bypassing Python object churn on the ingest hot path.
//
// The reference's hot loop is C++ end to end (per-datum fv convert +
// driver update, classifier_serv.cpp:127-146); round 1's measurement put
// the TPU port's serving ceiling at the Python host path (msgpack decode
// -> Datum -> fv convert under the GIL), an order of magnitude under the
// device kernel. This parser walks the train request's msgpack
// ([name, [[label, datum], ...]]) in place, applies the converter's
// num/string rules, hashes feature names with the zlib-identical CRC-32,
// and emits padded [B, K] index/value arrays plus label byte spans — the
// exact input of ops/classifier.train_batch. Python's remaining work per
// request is label-vocab lookup and one device_put.
//
// Supported converter subset (service.py checks eligibility and falls
// back to the Python converter otherwise): num rules {num, log, str},
// num filters, string rules with {str, space, ngram} splitters,
// sample_weight {bin, tf, log_tf}, global_weight {bin, idf}, and
// combination rules (mul/add; not combinable with idf); no string
// filters, no "weight" global weight, no plugins.
// Semantics mirror core/fv/converter.py: feature names
//   "<key>@<type>"                      (num/log)
//   "<key>$<fmt(value)>@<type>"         (num str)
//   "<key>$<term>@<type>#<sw>/<gw>"     (string rules)
// accumulate by name, then by hashed index (crc32 & mask, 0 -> 1), per
// example sorted by index — bit-identical to FeatureHasher + convert().
//
// ABI (ctypes, see jubatus_tpu/native/__init__.py):
//   void* jt_ingest_create(const char* spec)   rules, one per line:
//       "num\t<kind>\t<pattern>"
//       "str\t<splitter>\t<sample_weight>\t<global_weight>\t<type>\t<pattern>"
//       "nf\t<kind>\t<a>\t<b>\t<pattern>\t<suffix>"
//       "combo\t<mul|add>\t<key_left>\t<key_right>"
//   int jt_ingest_parse(handle, buf, len, mask, JtIngestOut*)  0 = ok
//   void jt_ingest_free_out(JtIngestOut*)       frees the arrays
//   void jt_ingest_destroy(handle)
//
// Thread-safe: parse allocates per-call buffers; handles are immutable
// after create.

#include <algorithm>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <locale>
#include <sstream>
#include <cstring>
#include <deque>
#include <unordered_map>
#include <string>
#include <vector>

namespace {

// ---- zlib-compatible CRC-32 (same table algorithm as jt_native.cpp) ----
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable kCrc;

// Locale-independent f64 parse for spec literals (create-time only).
// from_chars where the toolchain has it (GCC 11+); classic-locale
// istringstream otherwise — never plain strtod, which honors LC_NUMERIC.
inline double parse_spec_f64(const std::string& s) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  double v = 0.0;
  std::from_chars(s.data(), s.data() + s.size(), v);
  return v;
#else
  std::istringstream is(s);
  is.imbue(std::locale::classic());
  double v = 0.0;
  is >> v;
  return v;
#endif
}

inline uint32_t crc32_update(uint32_t c, const uint8_t* p, size_t n) {
  for (size_t i = 0; i < n; ++i) c = kCrc.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c;
}

// ---- key matchers: "*", "prefix*", "*suffix", exact --------------------
struct Matcher {
  enum Kind { ALL, PREFIX, SUFFIX, EXACT } kind = ALL;
  std::string pat;

  static Matcher make(const std::string& p) {
    Matcher m;
    if (p == "*") {
      m.kind = ALL;
    } else if (!p.empty() && p.back() == '*') {
      m.kind = PREFIX;
      m.pat = p.substr(0, p.size() - 1);
    } else if (!p.empty() && p.front() == '*') {
      m.kind = SUFFIX;
      m.pat = p.substr(1);
    } else {
      m.kind = EXACT;
      m.pat = p;
    }
    return m;
  }

  bool match(const uint8_t* s, size_t n) const {
    switch (kind) {
      case ALL:
        return true;
      case PREFIX:
        return n >= pat.size() && 0 == memcmp(s, pat.data(), pat.size());
      case SUFFIX:
        return n >= pat.size() &&
               0 == memcmp(s + n - pat.size(), pat.data(), pat.size());
      case EXACT:
        return n == pat.size() && 0 == memcmp(s, pat.data(), n);
    }
    return false;
  }
};

struct NumRule {
  enum Kind { NUM, LOG, STR } kind = NUM;
  Matcher m;
  std::string at_type;  // "@num" / "@log" / "@str" (rule's type name)
};

struct StrRule {
  enum Split { WHOLE, SPACE, NGRAM } split = WHOLE;
  enum Sw { BIN, TF, LOG_TF } sw = BIN;
  bool idf = false;  // global_weight idf: value *= log(ndocs/df) at parse
  int ngram_n = 0;   // code points per ngram token (split == NGRAM)
  Matcher m;
  std::string suffix;  // "@<type>#<sw>/<gw>"
};

struct NumFilter {
  // ≙ converter.py _build_num_filter: pure f64 math, so parity with the
  // Python lambdas is exact (same libm)
  enum Kind { ADD, LINEAR, GAUSS, SIGMOID } kind = ADD;
  double a = 0.0, b = 0.0;  // add: (value, -) linear: (lo, hi)
                            // gauss: (mean, std) sigmoid: (gain, bias)
  Matcher m;
  std::string suffix;  // appended key = key + suffix

  // *ok = false only where the PYTHON path would raise instead of
  // producing a value: math.exp raises OverflowError on +inf (CPython
  // checks isinf of the libm result), so a sigmoid whose exp overflows
  // must abort the fast path and let the converter raise the same error
  // — silently emitting 0.0 here would make the two paths disagree.
  double apply(double x, bool* ok) const {
    switch (kind) {
      case ADD:
        return x + a;
      case LINEAR:
        return (std::min(std::max(x, a), b) - a) / (b - a);
      case GAUSS:
        return (x - a) / b;
      case SIGMOID: {
        double e = std::exp(-a * (x - b));
        if (e == HUGE_VAL) {
          *ok = false;
          return 0.0;
        }
        return 1.0 / (1.0 + e);
      }
    }
    return x;
  }
};

struct ComboRule {
  // ≙ converter.py combination rules: the cross product of the example's
  // NAMED features (pre-hash), each unordered pair once in canonical
  // name order, value mul/add, name "<a>&<b>"
  enum Op { MUL, ADD } op = MUL;
  Matcher left, right;
};

struct Parser {
  std::vector<NumFilter> num_filters;
  std::vector<NumRule> num_rules;
  std::vector<StrRule> str_rules;
  std::vector<ComboRule> combos;
};

// ---- minimal msgpack reader (modern + legacy raw families) -------------
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint8_t peek() {
    if (p >= end) {
      fail = true;
      return 0xC1;
    }
    return *p;
  }
  uint8_t take() {
    if (p >= end) {
      fail = true;
      return 0xC1;
    }
    return *p++;
  }
  bool need(size_t n) {
    if (size_t(end - p) < n) {
      fail = true;
      return false;
    }
    return true;
  }
  uint64_t be(int n) {
    if (!need(n)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 8) | *p++;
    return v;
  }

  // array header; -1 on mismatch
  int64_t array_len() {
    uint8_t t = take();
    if ((t & 0xF0) == 0x90) return t & 0x0F;
    if (t == 0xDC) return int64_t(be(2));
    if (t == 0xDD) return int64_t(be(4));
    fail = true;
    return -1;
  }

  // raw/str/bin span (legacy fixraw/raw16/raw32 + modern str8/bin*)
  bool raw(const uint8_t** out, size_t* n) {
    uint8_t t = take();
    size_t len;
    if ((t & 0xE0) == 0xA0) {
      len = t & 0x1F;
    } else if (t == 0xD9 || t == 0xC4) {
      len = size_t(be(1));
    } else if (t == 0xDA || t == 0xC5) {
      len = size_t(be(2));
    } else if (t == 0xDB || t == 0xC6) {
      len = size_t(be(4));
    } else {
      fail = true;
      return false;
    }
    if (!need(len)) return false;
    *out = p;
    *n = len;
    p += len;
    return true;
  }

  // any int/float as double
  bool number(double* out) {
    uint8_t t = take();
    if (t <= 0x7F) {
      *out = t;
      return true;
    }
    if (t >= 0xE0) {
      *out = int8_t(t);
      return true;
    }
    switch (t) {
      case 0xCA: {
        uint32_t u = uint32_t(be(4));
        float f;
        memcpy(&f, &u, 4);
        *out = f;
        return true;
      }
      case 0xCB: {
        uint64_t u = be(8);
        double d;
        memcpy(&d, &u, 8);
        *out = d;
        return true;
      }
      case 0xCC:
        *out = double(be(1));
        return true;
      case 0xCD:
        *out = double(be(2));
        return true;
      case 0xCE:
        *out = double(be(4));
        return true;
      case 0xCF:
        *out = double(be(8));
        return true;
      case 0xD0:
        *out = double(int8_t(be(1)));
        return true;
      case 0xD1:
        *out = double(int16_t(be(2)));
        return true;
      case 0xD2:
        *out = double(int32_t(be(4)));
        return true;
      case 0xD3:
        *out = double(int64_t(be(8)));
        return true;
      default:
        fail = true;
        return false;
    }
  }

  // skip any object (for the binary_values slot)
  void skip() {
    uint8_t t = take();
    if (t <= 0x7F || t >= 0xE0 || t == 0xC0 || t == 0xC2 || t == 0xC3) return;
    if ((t & 0xE0) == 0xA0) {
      size_t n = t & 0x1F;
      if (need(n)) p += n;
      return;
    }
    if ((t & 0xF0) == 0x90) {
      for (int i = t & 0x0F; i > 0 && !fail; --i) skip();
      return;
    }
    if ((t & 0xF0) == 0x80) {
      for (int i = (t & 0x0F) * 2; i > 0 && !fail; --i) skip();
      return;
    }
    switch (t) {
      case 0xCC:
      case 0xD0:
        p += need(1) ? 1 : 0;
        return;
      case 0xCD:
      case 0xD1:
        p += need(2) ? 2 : 0;
        return;
      case 0xCA:
      case 0xCE:
      case 0xD2:
        p += need(4) ? 4 : 0;
        return;
      case 0xCB:
      case 0xCF:
      case 0xD3:
        p += need(8) ? 8 : 0;
        return;
      case 0xD9:
      case 0xC4: {
        size_t n = size_t(be(1));
        if (need(n)) p += n;
        return;
      }
      case 0xDA:
      case 0xC5: {
        size_t n = size_t(be(2));
        if (need(n)) p += n;
        return;
      }
      case 0xDB:
      case 0xC6: {
        size_t n = size_t(be(4));
        if (need(n)) p += n;
        return;
      }
      case 0xDC: {
        int64_t n = int64_t(be(2));
        for (int64_t i = 0; i < n && !fail; ++i) skip();
        return;
      }
      case 0xDD: {
        int64_t n = int64_t(be(4));
        for (int64_t i = 0; i < n && !fail; ++i) skip();
        return;
      }
      case 0xDE: {
        int64_t n = int64_t(be(2)) * 2;
        for (int64_t i = 0; i < n && !fail; ++i) skip();
        return;
      }
      case 0xDF: {
        int64_t n = int64_t(be(4)) * 2;
        for (int64_t i = 0; i < n && !fail; ++i) skip();
        return;
      }
      default:
        fail = true;  // ext or reserved: not part of this wire
    }
  }
};

// Decode one code point at txt[i] exactly like CPython's UTF-8 decoder
// under surrogateescape: *adv = bytes consumed. A sequence is one code
// point ONLY if it is shortest-form UTF-8 encoding a scalar value
// (no overlongs — lead 0xC0/0xC1, 0xE0 with 2nd byte < 0xA0, 0xF0 with
// 2nd byte < 0x90; no surrogates — 0xED with 2nd byte > 0x9F; nothing
// past U+10FFFF — leads 0xF5+, 0xF4 with 2nd byte > 0x8F); any invalid,
// truncated, or malformed byte decodes as ONE surrogate (adv 1, cp 0).
// Both splitters slide in these units, or they diverge from the Python
// converter on hostile bytes.
inline bool utf8_decode(const uint8_t* txt, size_t n, size_t i,
                        uint32_t* cp_out, size_t* adv) {
  uint8_t b = txt[i];
  *cp_out = 0;
  *adv = 1;
  if (b < 0x80) {
    *cp_out = b;
    return true;
  }
  size_t len;
  uint32_t cp;
  uint8_t lo = 0x80, hi = 0xBF;  // valid range of the SECOND byte
  if (b >= 0xC2 && b <= 0xDF) {
    len = 2;
    cp = b & 0x1F;
  } else if (b >= 0xE0 && b <= 0xEF) {
    len = 3;
    cp = b & 0x0F;
    if (b == 0xE0) lo = 0xA0;        // overlong
    if (b == 0xED) hi = 0x9F;        // surrogate range
  } else if (b >= 0xF0 && b <= 0xF4) {
    len = 4;
    cp = b & 0x07;
    if (b == 0xF0) lo = 0x90;        // overlong
    if (b == 0xF4) hi = 0x8F;        // > U+10FFFF
  } else {
    return false;  // stray continuation, 0xC0/0xC1 overlong, 0xF5+ lead
  }
  if (i + len > n) return false;  // truncated
  if (txt[i + 1] < lo || txt[i + 1] > hi) return false;
  cp = (cp << 6) | (txt[i + 1] & 0x3F);
  for (size_t k = 2; k < len; ++k) {
    if ((txt[i + k] & 0xC0) != 0x80) return false;
    cp = (cp << 6) | (txt[i + k] & 0x3F);
  }
  *adv = len;
  *cp_out = cp;
  return true;
}

// Python str.split() splits on Unicode whitespace (str.isspace): ASCII
// 0x09-0x0d, 0x1c-0x1f, 0x20, plus NEL/NBSP and the Unicode space
// separators. Invalid sequences decode as non-space surrogates.
inline bool is_py_space(const uint8_t* txt, size_t n, size_t i,
                        size_t* adv) {
  uint32_t cp;
  if (!utf8_decode(txt, n, i, &cp, adv)) return false;
  if (cp < 0x80)
    return (cp >= 0x09 && cp <= 0x0D) || (cp >= 0x1C && cp <= 0x1F) ||
           cp == 0x20;
  return cp == 0x85 || cp == 0xA0 || cp == 0x1680 ||
         (cp >= 0x2000 && cp <= 0x200A) || cp == 0x2028 || cp == 0x2029 ||
         cp == 0x202F || cp == 0x205F || cp == 0x3000;
}

// Byte length of the code point at txt[i] under Python's surrogateescape
// view of the bytes (utf8_decode rules): the ngram splitter slides in
// exactly these units to match converter.py's text[i:i+n].
inline size_t utf8_adv(const uint8_t* txt, size_t n, size_t i) {
  uint32_t cp;
  size_t adv;
  utf8_decode(txt, n, i, &cp, &adv);
  return adv;
}

// Python _format_num (converter.py:485-486): str(int(v)) when integral,
// else repr(v). repr = shortest round-trip digits, FIXED notation when
// the decimal exponent is in [-4, 16), scientific otherwise with a
// >=2-digit exponent — std::to_chars' default "shortest overall" picks
// scientific earlier (e.g. -1e-04 vs Python's -0.0001), so the rendering
// is reassembled here from the scientific digits. Returns 0 on values
// the exact Python rendering can't be reproduced for (integral beyond
// long long) — caller aborts the fast path and Python converts.
size_t format_num(double v, char* buf) {
  if (v == std::floor(v) && std::fabs(v) < 9.2e18) {
    long long i = (long long)v;
    auto r = std::to_chars(buf, buf + 32, i);
    return size_t(r.ptr - buf);
  }
  if (v == std::floor(v) && std::isfinite(v)) return 0;  // huge integral
  if (!std::isfinite(v)) return 0;  // nan/inf: Python renders differently
  char sci[48];
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto tcr = std::to_chars(sci, sci + 48, v, std::chars_format::scientific);
  char* sci_end = tcr.ptr;
#else
  // libstdc++ < 11 has no floating-point to_chars: produce the same
  // shortest-round-trip scientific digits by minimal-precision printf +
  // strtod round-trip check (both are correctly rounded, so the digit
  // string is identical for the shortest precision that round-trips)
  char* sci_end = sci;
  for (int prec = 0; prec <= 17; ++prec) {
    int n = snprintf(sci, sizeof sci, "%.*e", prec, v);
    if (n <= 0) return 0;
    if (std::strtod(sci, nullptr) == v) {
      sci_end = sci + n;
      break;
    }
  }
  if (sci_end == sci) return 0;
#endif
  // parse "[-]d[.ddd]e±EE"
  char* p = sci;
  char* out = buf;
  if (*p == '-') {
    *out++ = '-';
    ++p;
  }
  char digits[40];
  size_t nd = 0;
  digits[nd++] = *p++;
  if (*p == '.') {
    ++p;
    while (p < sci_end && *p != 'e') digits[nd++] = *p++;
  }
  int exp10 = 0;
  {
    bool neg = false;
    ++p;  // 'e'
    if (*p == '-') {
      neg = true;
      ++p;
    } else if (*p == '+') {
      ++p;
    }
    while (p < sci_end) exp10 = exp10 * 10 + (*p++ - '0');
    if (neg) exp10 = -exp10;
  }
  if (-4 <= exp10 && exp10 < 16) {  // fixed
    if (exp10 >= 0) {
      // non-integral guarantees nd > exp10 + 1
      for (int i = 0; i <= exp10; ++i) *out++ = digits[i];
      *out++ = '.';
      for (size_t i = size_t(exp10) + 1; i < nd; ++i) *out++ = digits[i];
    } else {
      *out++ = '0';
      *out++ = '.';
      for (int i = 0; i < -exp10 - 1; ++i) *out++ = '0';
      for (size_t i = 0; i < nd; ++i) *out++ = digits[i];
    }
  } else {  // scientific, Python style: d[.ddd]e±EE (exponent >= 2 digits)
    *out++ = digits[0];
    if (nd > 1) {
      *out++ = '.';
      for (size_t i = 1; i < nd; ++i) *out++ = digits[i];
    }
    *out++ = 'e';
    *out++ = exp10 < 0 ? '-' : '+';
    int ae = exp10 < 0 ? -exp10 : exp10;
    char eb[8];
    auto er = std::to_chars(eb, eb + 8, ae);
    if (er.ptr - eb < 2) *out++ = '0';
    for (char* q = eb; q < er.ptr; ++q) *out++ = *q;
  }
  return size_t(out - buf);
}

struct Feature {
  int32_t idx;
  double val;  // accumulate in double, cast to f32 once at pack time
               // (matches the Python converter's f64 sums -> f32 arrays)
  uint8_t idf;  // produced by an idf-weighted rule (scaled pre-merge)
};

}  // namespace

extern "C" {

struct JtIngestOut {
  int32_t batch;       // examples parsed
  int32_t width;       // padded nnz per row (pow2, >= 8)
  int32_t labels_numeric;  // 1: targets[] is set (regression), 0: labels
  int32_t* idx;        // [batch, width], 0-padded
  float* val;          // [batch, width], 0-padded
  uint8_t* labels;     // concatenated DISTINCT label bytes
  int32_t* label_off;  // uniq + 1 offsets into labels
  float* targets;      // [batch] numeric targets (regression train)
  int32_t uniq;        // distinct labels in labels/label_off
  int32_t* label_idx;  // [batch] row -> distinct-label index
};

void* jt_ingest_create(const char* spec) {
  auto* ps = new Parser();
  std::string s(spec ? spec : "");
  size_t pos = 0;
  while (pos < s.size()) {
    size_t nl = s.find('\n', pos);
    if (nl == std::string::npos) nl = s.size();
    std::string line = s.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.empty()) continue;
    std::vector<std::string> f;
    size_t start = 0;
    while (true) {
      size_t tab = line.find('\t', start);
      if (tab == std::string::npos) {
        f.push_back(line.substr(start));
        break;
      }
      f.push_back(line.substr(start, tab - start));
      start = tab + 1;
    }
    if (f[0] == "nf" && f.size() == 6) {
      // "nf\t<kind>\t<a>\t<b>\t<pattern>\t<suffix>"
      NumFilter nf;
      if (f[1] == "add")
        nf.kind = NumFilter::ADD;
      else if (f[1] == "linear")
        nf.kind = NumFilter::LINEAR;
      else if (f[1] == "gauss")
        nf.kind = NumFilter::GAUSS;
      else if (f[1] == "sigmoid")
        nf.kind = NumFilter::SIGMOID;
      else {
        delete ps;
        return nullptr;
      }
      // from_chars: locale-INDEPENDENT ("5.5" must not parse as 5.0
      // under an LC_NUMERIC with a comma separator smuggled in by some
      // other module in the host process)
      nf.a = parse_spec_f64(f[2]);
      nf.b = parse_spec_f64(f[3]);
      nf.m = Matcher::make(f[4]);
      nf.suffix = f[5];
      ps->num_filters.push_back(std::move(nf));
    } else if (f[0] == "num" && f.size() == 3) {
      NumRule r;
      if (f[1] == "num")
        r.kind = NumRule::NUM;
      else if (f[1] == "log")
        r.kind = NumRule::LOG;
      else if (f[1] == "str")
        r.kind = NumRule::STR;
      else {
        delete ps;
        return nullptr;
      }
      r.at_type = "@" + f[1];
      r.m = Matcher::make(f[2]);
      ps->num_rules.push_back(std::move(r));
    } else if (f[0] == "str" && f.size() == 6) {
      StrRule r;
      if (f[1] == "str")
        r.split = StrRule::WHOLE;
      else if (f[1] == "space")
        r.split = StrRule::SPACE;
      else if (f[1].rfind("ngram:", 0) == 0) {
        r.split = StrRule::NGRAM;
        r.ngram_n = atoi(f[1].c_str() + 6);
        if (r.ngram_n < 1) {
          delete ps;
          return nullptr;
        }
      } else {
        delete ps;
        return nullptr;
      }
      if (f[2] == "bin")
        r.sw = StrRule::BIN;
      else if (f[2] == "tf")
        r.sw = StrRule::TF;
      else if (f[2] == "log_tf")
        r.sw = StrRule::LOG_TF;
      else {
        delete ps;
        return nullptr;
      }
      if (f[3] == "idf") {
        // idf needs the WeightManager's df table: the caller passes it
        // into jt_ingest_parse_w; the unweighted entry points refuse
        // specs carrying idf rules
        r.idf = true;
      } else if (f[3] != "bin") {  // "weight" needs the user-weight map
        delete ps;
        return nullptr;
      }
      r.suffix = "@" + f[4] + "#" + f[2] + "/" + f[3];
      r.m = Matcher::make(f[5]);
      ps->str_rules.push_back(std::move(r));
    } else if (f[0] == "combo" && f.size() == 4) {
      // "combo\t<mul|add>\t<key_left>\t<key_right>"
      ComboRule cr;
      if (f[1] == "mul")
        cr.op = ComboRule::MUL;
      else if (f[1] == "add")
        cr.op = ComboRule::ADD;
      else {
        delete ps;
        return nullptr;
      }
      cr.left = Matcher::make(f[2]);
      cr.right = Matcher::make(f[3]);
      ps->combos.push_back(std::move(cr));
    } else {
      delete ps;
      return nullptr;
    }
  }
  // combos iterate the pre-hash NAMED features; the idf path weights
  // hashed indices pre-merge — composing them here would need the full
  // name->weight pipeline, so such specs stay on the Python converter
  if (!ps->combos.empty())
    for (const StrRule& r : ps->str_rules)
      if (r.idf) {
        delete ps;
        return nullptr;
      }
  return ps;
}

void jt_ingest_destroy(void* h) { delete static_cast<Parser*>(h); }

void jt_ingest_free_out(JtIngestOut* out) {
  free(out->idx);
  free(out->val);
  free(out->labels);
  free(out->label_off);
  free(out->targets);
  free(out->label_idx);
  out->idx = nullptr;
  out->val = nullptr;
  out->labels = nullptr;
  out->label_off = nullptr;
  out->targets = nullptr;
  out->label_idx = nullptr;
}

//: idf weighting context, or null dfm for the unweighted path. Mirrors
//: converter.convert's order EXACTLY: per document, observe the distinct
//: idf feature indices FIRST (df += 1 once per index, ndocs += 1), then
//: value *= log(ndocs/df) (<=0 guards -> 1.0), THEN merge by index.
//: ``observe`` is 0 on the query path (classify/estimate read idf
//: without recording the document).
struct IdfCtx {
  const float* dfm = nullptr;  // df master (read)
  float* dfd = nullptr;        // df diff (incremented on train)
  double ndocs_m = 0.0;
  double* ndocs_d = nullptr;   // incremented on train
  int observe = 0;
};

static int parse_impl(void* h, const uint8_t* buf, int64_t len,
                      uint32_t mask, int with_labels, const IdfCtx* idf,
                      JtIngestOut* out) {
  const Parser& ps = *static_cast<Parser*>(h);
  Reader rd{buf, buf + len};
  bool has_idf_rule = false;
  for (const StrRule& r : ps.str_rules) has_idf_rule |= r.idf;
  if (has_idf_rule && (idf == nullptr || idf->dfm == nullptr))
    return 5;  // spec needs weight state the caller did not supply

  int64_t top = rd.array_len();  // [name, data]
  if (rd.fail || top != 2) return 1;
  rd.skip();  // cluster name
  int64_t n = rd.array_len();
  if (rd.fail || n < 0) return 1;

  std::vector<Feature> feats;       // all examples, concatenated
  std::vector<int64_t> offsets(1, 0);
  std::vector<uint8_t> labels;      // distinct label bytes, concatenated
  std::vector<int32_t> label_off(1, 0);  // uniq + 1 offsets
  std::vector<int32_t> label_idx;   // row -> distinct-label index
  std::vector<std::pair<size_t, size_t>> uniq_spans;  // (off, len) in labels
  std::vector<float> targets;       // regression: numeric first slot
  int labels_numeric = -1;          // unknown until the first example
  std::string name;                 // scratch feature-name buffer
  std::vector<std::pair<const uint8_t*, size_t>> terms;  // scratch
  std::vector<int32_t> idf_scratch;  // distinct idf indices per example
  // filter-appended keys (per-example scratch; the schema cache owns
  // copies of any key bytes it keeps)
  std::deque<std::string> key_arena;
  // string-rule scratch: hash-count slots, per-occurrence counts,
  // first-seen order, and the per-request (rule, key, term)->idx memo
  std::vector<int32_t> tslot;
  std::vector<int32_t> tcnt;
  std::vector<size_t> distinct;
  std::string lookup_key;
  std::vector<std::unordered_map<std::string, int32_t>> term_memo{
      ps.str_rules.size()};
  char numbuf[40];

  // Schema cache for num rules: real ingest streams repeat one key schema
  // (f0..fK in the same order every datum), so the (rule, position)->
  // hashed-index outcome from the previous datum usually holds — one
  // memcmp replaces name assembly + CRC-32 per feature. state: -1 unset,
  // 0 no-match, 1 emit idx with v, 2 emit idx with log(max(1,v)),
  // 3 value-dependent name (num "str" rule) — recompute.
  // entries OWN their key bytes (copied on miss): filter-appended keys
  // live in a per-example arena, so a borrowed pointer would dangle into
  // the previous example's scratch
  struct PosEntry {
    std::string key;
    int8_t state = -1;
    int32_t idx = 0;
  };
  std::vector<PosEntry> poscache;
  size_t pos_stride = 0;  // kv slots per rule; grows to max nnv seen

  // combo mode: features accumulate by NAME first (converter.py
  // _named_features dict), the combination cross product runs over that
  // map, and only then is everything hashed. The term/pos memos are
  // bypassed (they exist to skip name assembly, which combos need).
  const bool combo_mode = !ps.combos.empty();
  std::vector<std::pair<std::string, double>> named;  // insertion order
  std::unordered_map<std::string, size_t> named_ix;

  // combo plan (round 5, VERDICT r4 #3): the cross product's pair
  // structure, names and hashes are a pure function of the BASE
  // feature-name schema, which repeats across a feed's datums (fixed
  // key schemas are the production shape). On a schema hit the whole
  // name-assembly + map + crc32 stage is replayed as (slot -> hashed
  // idx, bilinear terms over base positions): per datum only the
  // multiplies/adds and feature pushes remain. Per parse call (one
  // request) like the term/pos memos, so thread-safety is free.
  struct ComboTerm {
    int32_t a, b;
    uint8_t op;  // 1 mul, 2 add
  };
  struct ComboPlan {
    bool valid = false;
    size_t base_n = 0;
    std::vector<std::string> base_names;
    std::vector<int32_t> slot_idx;   // hashed index per output slot
    std::vector<uint32_t> t_off;     // terms span per slot (slots + 1)
    std::vector<ComboTerm> terms;
  } combo_plan;
  std::vector<std::vector<ComboTerm>> slot_terms;  // recording scratch

  auto add_named = [&](const std::string& nm, double v) {
    auto it = named_ix.find(nm);
    if (it == named_ix.end()) {
      named_ix.emplace(nm, named.size());
      named.push_back({nm, v});
    } else {
      named[it->second].second += v;
    }
  };

  auto hash_push = [&](const std::string& nm, double v, bool idf) {
    uint32_t c = crc32_update(0xFFFFFFFFu,
                              reinterpret_cast<const uint8_t*>(nm.data()),
                              nm.size()) ^
                 0xFFFFFFFFu;
    uint32_t i = c & mask;
    if (i == 0) i = 1;  // padding slot is reserved
    feats.push_back({int32_t(i), v, uint8_t(idf)});
  };

  auto emit = [&](const std::string& nm, double v, bool idf = false) {
    if (combo_mode)
      add_named(nm, v);  // idf+combos declined at create
    else
      hash_push(nm, v, idf);
  };

  for (int64_t e = 0; e < n; ++e) {
    if (with_labels) {
      int64_t pair = rd.array_len();  // [label, datum] / [target, datum]
      if (rd.fail || pair != 2) return 1;
      uint8_t lt = rd.peek();
      bool is_raw = (lt & 0xE0) == 0xA0 || lt == 0xD9 || lt == 0xC4 ||
                    lt == 0xDA || lt == 0xC5 || lt == 0xDB || lt == 0xC6;
      if (labels_numeric == -1) labels_numeric = is_raw ? 0 : 1;
      if (is_raw != (labels_numeric == 0)) return 1;  // mixed: not this wire
      if (is_raw) {
        const uint8_t* lb;
        size_t lbn;
        if (!rd.raw(&lb, &lbn)) return 1;
        // dedup: linear scan over the distinct set (classification label
        // sets are small); past 256 distinct, stop scanning and append —
        // label_idx stays correct, rows just stop sharing entries
        int32_t li = -1;
        if (uniq_spans.size() <= 256) {
          for (size_t u = 0; u < uniq_spans.size(); ++u) {
            if (uniq_spans[u].second == lbn &&
                0 == memcmp(labels.data() + uniq_spans[u].first, lb, lbn)) {
              li = int32_t(u);
              break;
            }
          }
        }
        if (li < 0) {
          li = int32_t(uniq_spans.size());
          uniq_spans.push_back({labels.size(), lbn});
          labels.insert(labels.end(), lb, lb + lbn);
          label_off.push_back(int32_t(labels.size()));
        }
        label_idx.push_back(li);
      } else {
        double t;
        if (!rd.number(&t)) return 1;
        targets.push_back(float(t));
      }
    } else {
      labels_numeric = 0;  // classify/estimate: bare datum list, no labels
    }

    int64_t dlen = rd.array_len();  // [sv, nv, (bv)]
    if (rd.fail || dlen < 2 || dlen > 3) return 1;

    // string_values — bound claimed lengths by remaining bytes before any
    // allocation (a ~20-byte request claiming 2^32 pairs must produce an
    // error reply, not a bad_alloc/terminate)
    int64_t nsv = rd.array_len();
    if (rd.fail || nsv < 0 || nsv > rd.end - rd.p) return 1;
    // remember the sv spans (rules iterate over all kvs per rule)
    std::vector<std::pair<std::pair<const uint8_t*, size_t>,
                          std::pair<const uint8_t*, size_t>>>
        svs{size_t(nsv)};
    for (int64_t i = 0; i < nsv; ++i) {
      int64_t kv = rd.array_len();
      if (rd.fail || kv != 2) return 1;
      if (!rd.raw(&svs[i].first.first, &svs[i].first.second)) return 1;
      if (!rd.raw(&svs[i].second.first, &svs[i].second.second)) return 1;
    }
    // num_values
    int64_t nnv = rd.array_len();
    if (rd.fail || nnv < 0 || nnv > rd.end - rd.p) return 1;
    std::vector<std::pair<std::pair<const uint8_t*, size_t>, double>> nvs{
        size_t(nnv)};
    for (int64_t i = 0; i < nnv; ++i) {
      int64_t kv = rd.array_len();
      if (rd.fail || kv != 2) return 1;
      if (!rd.raw(&nvs[i].first.first, &nvs[i].first.second)) return 1;
      if (!rd.number(&nvs[i].second)) return 1;
    }
    if (dlen == 3) rd.skip();  // binary_values: no binary rules here

    // num filters (converter.py _apply_filters): each rule snapshots the
    // CURRENT list and appends (key+suffix, f(value)) — later filters see
    // earlier filters' output, exactly like the Python loop. Appended
    // keys live in a deque (stable addresses) for the whole parse call.
    key_arena.clear();  // per-example scratch (cache entries own copies)
    if (combo_mode) {
      named.clear();
      named_ix.clear();
    }
    for (const NumFilter& nf : ps.num_filters) {
      size_t cur = nvs.size();
      for (size_t fi = 0; fi < cur; ++fi) {
        auto kv = nvs[fi];  // by value: push_back below may reallocate
        if (!nf.m.match(kv.first.first, kv.first.second)) continue;
        key_arena.emplace_back();
        std::string& nk = key_arena.back();
        nk.assign(reinterpret_cast<const char*>(kv.first.first),
                  kv.first.second);
        nk += nf.suffix;
        bool ok = true;
        double fv = nf.apply(kv.second, &ok);
        if (!ok) return 3;  // Python path raises here: fall back to it
        nvs.push_back(
            {{reinterpret_cast<const uint8_t*>(nk.data()), nk.size()},
             fv});
      }
    }
    nnv = int64_t(nvs.size());

    // string rules (converter.py:346-366)
    for (const StrRule& r : ps.str_rules) {
      for (auto& kv : svs) {
        const uint8_t* key = kv.first.first;
        size_t keyn = kv.first.second;
        if (!r.m.match(key, keyn)) continue;
        const uint8_t* txt = kv.second.first;
        size_t txtn = kv.second.second;
        terms.clear();
        if (r.split == StrRule::WHOLE) {
          if (txtn) terms.push_back({txt, txtn});
        } else if (r.split == StrRule::SPACE) {
          // SPACE: Unicode whitespace runs (str.split())
          size_t i = 0;
          while (i < txtn) {
            size_t adv;
            while (i < txtn && is_py_space(txt, txtn, i, &adv)) i += adv;
            size_t s = i;
            while (i < txtn && !is_py_space(txt, txtn, i, &adv)) i += adv;
            if (i > s) terms.push_back({txt + s, i - s});
          }
        } else {  // NGRAM: sliding window of n CODE POINTS (converter.py
          // _make_ngram slides over a surrogateescape-decoded str)
          std::vector<size_t> cps;  // byte offset of each code point
          size_t i = 0;
          while (i < txtn) {
            cps.push_back(i);
            i += utf8_adv(txt, txtn, i);
          }
          cps.push_back(txtn);
          size_t n_cp = cps.size() - 1;
          for (size_t a = 0; a + size_t(r.ngram_n) <= n_cp; ++a)
            terms.push_back(
                {txt + cps[a], cps[a + size_t(r.ngram_n)] - cps[a]});
        }
        // tf counts per distinct term: open-addressing hash count in
        // FIRST-SEEN order (the Python dict's insertion order) — the old
        // quadratic memcmp dedup was ~35% of text-parse time at 32
        // tokens/datum
        size_t T = terms.size();
        if (T == 0) continue;
        size_t cap = 4;
        while (cap < 2 * T) cap <<= 1;
        tslot.assign(cap, -1);
        tcnt.assign(T, 0);
        distinct.clear();
        for (size_t ti = 0; ti < T; ++ti) {
          const uint8_t* tp = terms[ti].first;
          size_t tn = terms[ti].second;
          uint64_t h = 1469598103934665603ull;  // FNV-1a
          for (size_t bi = 0; bi < tn; ++bi)
            h = (h ^ tp[bi]) * 1099511628211ull;
          size_t slot = size_t(h) & (cap - 1);
          while (true) {
            int32_t occ = tslot[slot];
            if (occ < 0) {
              tslot[slot] = int32_t(ti);
              tcnt[ti] = 1;
              distinct.push_back(ti);
              break;
            }
            if (terms[size_t(occ)].second == tn &&
                0 == memcmp(terms[size_t(occ)].first, tp, tn)) {
              ++tcnt[size_t(occ)];
              break;
            }
            slot = (slot + 1) & (cap - 1);
          }
        }
        // (rule, key, term) -> hashed index memo across the request:
        // repeated vocabulary skips name assembly + CRC-32 entirely.
        // The key is LENGTH-PREFIXED (raw keys/terms may contain any
        // byte, so a separator could collide "a\0b"+"c" with "a"+"b\0c");
        // it is built once per kv and resized per term; the memo is
        // size-capped so high-cardinality text (unique ngrams) degrades
        // to plain misses instead of unbounded per-request allocation.
        auto& memo = term_memo[size_t(&r - ps.str_rules.data())];
        uint32_t klen32 = uint32_t(keyn);
        lookup_key.assign(reinterpret_cast<const char*>(&klen32), 4);
        lookup_key.append(reinterpret_cast<const char*>(key), keyn);
        size_t prefix_len = lookup_key.size();
        for (size_t di : distinct) {
          int tf = tcnt[di];
          double sw = r.sw == StrRule::BIN  ? 1.0
                      : r.sw == StrRule::TF ? double(tf)
                                            : std::log(1.0 + tf);
          if (!combo_mode) {
            lookup_key.resize(prefix_len);
            lookup_key.append(
                reinterpret_cast<const char*>(terms[di].first),
                terms[di].second);
            auto it = memo.find(lookup_key);
            if (it != memo.end()) {
              feats.push_back({it->second, sw, uint8_t(r.idf)});
              continue;
            }
          }
          name.assign(reinterpret_cast<const char*>(key), keyn);
          name += '$';
          name.append(reinterpret_cast<const char*>(terms[di].first),
                      terms[di].second);
          name += r.suffix;
          emit(name, sw, r.idf);
          if (!combo_mode && memo.size() < (1u << 16))
            memo.emplace(lookup_key, feats.back().idx);
        }
      }
    }
    // num rules (converter.py:369-388), schema-cached per (rule, position)
    if (size_t(nnv) > pos_stride) {
      // re-stride: invalidate (entries would alias across rules)
      pos_stride = size_t(nnv);
      poscache.assign(ps.num_rules.size() * pos_stride, PosEntry{});
    }
    for (size_t ri = 0; ri < ps.num_rules.size(); ++ri) {
      const NumRule& r = ps.num_rules[ri];
      PosEntry* row = poscache.data() + ri * pos_stride;
      for (int64_t ki = 0; ki < nnv; ++ki) {
        auto& kv = nvs[size_t(ki)];
        const uint8_t* key = kv.first.first;
        size_t keyn = kv.first.second;
        PosEntry& pe = row[ki];
        if (!combo_mode && pe.state >= 0 && pe.key.size() == keyn &&
            0 == memcmp(pe.key.data(), key, keyn)) {
          switch (pe.state) {
            case 0:
              continue;
            case 1:
              feats.push_back({pe.idx, kv.second});
              continue;
            case 2:
              feats.push_back({pe.idx, std::log(std::max(1.0, kv.second))});
              continue;
            default:
              break;  // state 3: value-dependent, fall through
          }
        } else {
          pe.key.assign(reinterpret_cast<const char*>(key), keyn);
          if (!r.m.match(key, keyn)) {
            pe.state = 0;
            continue;
          }
          pe.state = r.kind == NumRule::NUM   ? 1
                     : r.kind == NumRule::LOG ? 2
                                              : 3;
          if (pe.state != 3) {
            name.assign(reinterpret_cast<const char*>(key), keyn);
            name += r.at_type;
            emit(name, pe.state == 1 ? kv.second
                                     : std::log(std::max(1.0, kv.second)));
            if (!combo_mode)  // emit() owns the name->index rule
              pe.idx = feats.back().idx;
            continue;
          }
        }
        // NumRule::STR — the term is the formatted value; uncacheable
        size_t fn = format_num(kv.second, numbuf);
        if (fn == 0) return 3;  // unrepresentable: Python path converts
        name.assign(reinterpret_cast<const char*>(key), keyn);
        name += '$';
        name.append(numbuf, fn);
        name += r.at_type;
        emit(name, 1.0);
      }
    }

    // combinations (converter.py:412-432): cross product over the BASE
    // named-feature snapshot, each unordered pair once per rule in
    // canonical (bytewise == codepoint) name order, "<a>&<b>", values
    // accumulating into the same name map; then hash everything
    if (combo_mode) {
      size_t base_n = named.size();
      bool plan_hit =
          combo_plan.valid && combo_plan.base_n == base_n;
      if (plan_hit) {
        for (size_t i2 = 0; i2 < base_n; ++i2) {
          if (named[i2].first != combo_plan.base_names[i2]) {
            plan_hit = false;
            break;
          }
        }
      }
      if (plan_hit) {
        // replay: no strings, no maps, no crc32 — just the bilinear
        // terms over this example's base values
        size_t nslots = combo_plan.slot_idx.size();
        for (size_t j = 0; j < nslots; ++j) {
          double v = j < combo_plan.base_n ? named[j].second : 0.0;
          for (uint32_t t = combo_plan.t_off[j];
               t < combo_plan.t_off[j + 1]; ++t) {
            const ComboTerm& tm = combo_plan.terms[t];
            v += tm.op == 1 ? named[tm.a].second * named[tm.b].second
                            : named[tm.a].second + named[tm.b].second;
          }
          feats.push_back({combo_plan.slot_idx[j], v, 0});
        }
      } else {
        // slow pass — and record the plan for the rest of the request.
        // frozen base values (Python's `base = list(features.items())`
        // snapshot): a combined name colliding with a base name must
        // not change later pairs' inputs
        slot_terms.assign(base_n, {});
        std::vector<double> base_val(base_n);
        for (size_t i2 = 0; i2 < base_n; ++i2)
          base_val[i2] = named[i2].second;
        std::string cname;
        for (const ComboRule& cr : ps.combos) {
          auto lm = [&](size_t i2) {
            const std::string& s2 = named[i2].first;
            return cr.left.match(
                reinterpret_cast<const uint8_t*>(s2.data()), s2.size());
          };
          auto rm = [&](size_t i2) {
            const std::string& s2 = named[i2].first;
            return cr.right.match(
                reinterpret_cast<const uint8_t*>(s2.data()), s2.size());
          };
          for (size_t li = 0; li < base_n; ++li) {
            if (!lm(li)) continue;
            for (size_t ri = 0; ri < base_n; ++ri) {
              if (li == ri || !rm(ri)) continue;
              // once per unordered pair per rule WITHOUT a seen-set (an
              // allocating tree insert per candidate pair would dominate
              // the hot path): each pair is visited at most twice; emit
              // on the canonical visit, or on either visit when the
              // mirror does not qualify. Values are symmetric (mul/add).
              if (li > ri && lm(ri) && rm(li)) continue;
              double cval = cr.op == ComboRule::MUL
                                ? base_val[li] * base_val[ri]
                                : base_val[li] + base_val[ri];
              size_t a = li, b = ri;
              if (named[b].first < named[a].first) std::swap(a, b);
              cname = named[a].first;
              cname += '&';
              cname += named[b].first;
              // add_named + record which (a, b, op) fed which slot
              size_t s;
              auto it = named_ix.find(cname);
              if (it == named_ix.end()) {
                s = named.size();
                named_ix.emplace(cname, s);
                named.push_back({cname, cval});
                slot_terms.emplace_back();
              } else {
                s = it->second;
                named[s].second += cval;
              }
              slot_terms[s].push_back(
                  {int32_t(li), int32_t(ri),
                   uint8_t(cr.op == ComboRule::MUL ? 1 : 2)});
            }
          }
        }
        combo_plan.valid = true;
        combo_plan.base_n = base_n;
        combo_plan.base_names.assign(base_n, std::string());
        for (size_t i2 = 0; i2 < base_n; ++i2)
          combo_plan.base_names[i2] = named[i2].first;
        combo_plan.slot_idx.clear();
        combo_plan.terms.clear();
        combo_plan.t_off.assign(1, 0);
        for (size_t j = 0; j < named.size(); ++j) {
          hash_push(named[j].first, named[j].second, false);
          combo_plan.slot_idx.push_back(feats.back().idx);
          for (const ComboTerm& tm : slot_terms[j])
            combo_plan.terms.push_back(tm);
          combo_plan.t_off.push_back(uint32_t(combo_plan.terms.size()));
        }
      }
    }

    // idf (converter.py convert(): observe distinct indices, then scale,
    // BEFORE the merge — a post-merge scale would mis-weight hash
    // collisions between idf and non-idf features)
    if (has_idf_rule) {
      size_t start = size_t(offsets.back());
      idf_scratch.clear();
      for (size_t fi = start; fi < feats.size(); ++fi)
        if (feats[fi].idf) idf_scratch.push_back(feats[fi].idx);
      if (!idf_scratch.empty()) {
        std::sort(idf_scratch.begin(), idf_scratch.end());
        idf_scratch.erase(
            std::unique(idf_scratch.begin(), idf_scratch.end()),
            idf_scratch.end());
        if (idf->observe) {
          for (int32_t ix : idf_scratch) idf->dfd[ix] += 1.0f;
          *idf->ndocs_d += 1.0;
        }
        double n = idf->ndocs_m + (idf->ndocs_d ? *idf->ndocs_d : 0.0);
        for (size_t fi = start; fi < feats.size(); ++fi) {
          if (!feats[fi].idf) continue;
          int32_t ix = feats[fi].idx;
          // f32 addition FIRST (then widen): WeightManager.idf does
          // float(master[i] + diff[i]) — a double-precision sum here
          // would diverge from the Python path once df saturates f32
          double df = double(idf->dfm[ix] +
                             (idf->dfd ? idf->dfd[ix] : 0.0f));
          double w = (n <= 0.0 || df <= 0.0) ? 1.0 : std::log(n / df);
          feats[fi].val *= w;
        }
      }
    }

    // per-example: sort by index, merge duplicates (convert() semantics)
    auto begin = feats.begin() + offsets.back();
    std::sort(begin, feats.end(),
              [](const Feature& a, const Feature& b) { return a.idx < b.idx; });
    size_t start = size_t(offsets.back());
    size_t w = start;
    for (size_t rdi = start; rdi < feats.size(); ++rdi) {
      if (w > start && feats[rdi].idx == feats[w - 1].idx) {
        feats[w - 1].val += feats[rdi].val;
      } else {
        feats[w] = feats[rdi];
        ++w;
      }
    }
    feats.resize(w);
    offsets.push_back(int64_t(feats.size()));
  }
  if (rd.fail) return 1;

  // pack to [batch, width] with the SparseBatch width bucket (pow2, >= 8)
  int64_t max_nnz = 1;
  for (size_t e = 0; e + 1 < offsets.size(); ++e)
    max_nnz = std::max(max_nnz, offsets[e + 1] - offsets[e]);
  int32_t width = 8;
  while (width < max_nnz) width *= 2;

  size_t uniq = uniq_spans.size();
  out->batch = int32_t(n);
  out->width = width;
  out->labels_numeric = labels_numeric == 1 ? 1 : 0;
  out->uniq = int32_t(uniq);
  out->idx = static_cast<int32_t*>(calloc(size_t(n) * width, 4));
  out->val = static_cast<float*>(calloc(size_t(n) * width, 4));
  out->labels = static_cast<uint8_t*>(malloc(labels.size() ? labels.size() : 1));
  out->label_off = static_cast<int32_t*>(malloc((uniq + 1) * 4));
  out->targets = static_cast<float*>(malloc((size_t(n) + 1) * 4));
  out->label_idx = static_cast<int32_t*>(malloc((size_t(n) + 1) * 4));
  if (!out->idx || !out->val || !out->labels || !out->label_off ||
      !out->targets || !out->label_idx) {
    jt_ingest_free_out(out);
    return 2;
  }
  memcpy(out->labels, labels.data(), labels.size());
  if (labels_numeric == 1) {
    memcpy(out->targets, targets.data(), targets.size() * 4);
    out->label_off[0] = 0;
  } else {
    memcpy(out->label_off, label_off.data(), (uniq + 1) * 4);
    memcpy(out->label_idx, label_idx.data(), label_idx.size() * 4);
  }
  for (int64_t e = 0; e < n; ++e) {
    int64_t s = offsets[e], cnt = offsets[e + 1] - offsets[e];
    for (int64_t j = 0; j < cnt; ++j) {
      out->idx[e * width + j] = feats[size_t(s + j)].idx;
      out->val[e * width + j] = float(feats[size_t(s + j)].val);
    }
  }
  return 0;
}

int jt_ingest_parse(void* h, const uint8_t* buf, int64_t len, uint32_t mask,
                    JtIngestOut* out) {
  // no exception may cross the C ABI: an allocation failure (hostile
  // lengths, memory pressure) must surface as a parse error the caller
  // turns into an RPC error reply, never std::terminate
  try {
    return parse_impl(h, buf, len, mask, 1, nullptr, out);
  } catch (...) {
    return 4;
  }
}

// classify/estimate wire: [name, [datum, ...]] — no label slot; only the
// idx/val arrays of the result are meaningful
int jt_ingest_parse_datums(void* h, const uint8_t* buf, int64_t len,
                           uint32_t mask, JtIngestOut* out) {
  try {
    return parse_impl(h, buf, len, mask, 0, nullptr, out);
  } catch (...) {
    return 4;
  }
}

// idf-weighted variants: the caller supplies the WeightManager's dense
// df tables (master read-only, diff incremented per observed document)
// and ndocs counters. ``observe`` 1 = train path (record documents),
// 0 = query path (read-only idf lookup). The caller owns locking —
// these mutate dfd/ndocs_d in place.
int jt_ingest_parse_w(void* h, const uint8_t* buf, int64_t len,
                      uint32_t mask, const float* dfm, float* dfd,
                      double ndocs_m, double* ndocs_d, int observe,
                      JtIngestOut* out) {
  try {
    IdfCtx ctx{dfm, dfd, ndocs_m, ndocs_d, observe};
    return parse_impl(h, buf, len, mask, 1, &ctx, out);
  } catch (...) {
    return 4;
  }
}

int jt_ingest_parse_datums_w(void* h, const uint8_t* buf, int64_t len,
                             uint32_t mask, const float* dfm, float* dfd,
                             double ndocs_m, double* ndocs_d,
                             JtIngestOut* out) {
  try {
    IdfCtx ctx{dfm, dfd, ndocs_m, ndocs_d, 0};
    return parse_impl(h, buf, len, mask, 0, &ctx, out);
  } catch (...) {
    return 4;
  }
}

}  // extern "C"
