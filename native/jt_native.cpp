// jubatus_tpu native runtime helpers.
//
// The reference's entire serving stack is C++; in this framework the
// device plane is XLA and the wire plane is msgpack (already C), so the
// profitable native surface is the host-side ingest hot loop: hashing
// feature-name batches into the fixed 2^k index space (the hashing
// trick replacing core::fv_converter's string-keyed sfv maps).
//
// CRC-32 here is bit-identical to zlib's (IEEE reflected, poly
// 0xEDB88320) so native and Python paths may be mixed freely — the
// checkpoint envelope (framework/save_load.py) and FeatureHasher
// (core/fv/hashing.py) both depend on this exact function.
//
// Build: `make -C native` → build/libjt_native.so; loaded via ctypes by
// jubatus_tpu/native/__init__.py (no pybind11 in this image).

#include <cstdint>

namespace {

struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1u) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
      }
      t[i] = c;
    }
  }
};

const Crc32Table kTable;

inline uint32_t crc32_update(uint32_t c, const uint8_t* p, int64_t len) {
  for (int64_t i = 0; i < len; ++i) {
    c = kTable.t[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
  }
  return c;
}

}  // namespace

extern "C" {

// zlib-compatible one-shot CRC-32.
uint32_t jt_crc32(const uint8_t* data, int64_t len) {
  return crc32_update(0xFFFFFFFFu, data, len) ^ 0xFFFFFFFFu;
}

// Hash a batch of utf-8 feature names (concatenated in `buf`, delimited by
// `offsets`, length n+1) into [1, mask] — crc32 & mask with the zero slot
// remapped to 1 (index 0 is the padding slot, core/fv/hashing.py).
void jt_hash_names(const char* buf, const int64_t* offsets, int64_t n,
                   uint32_t mask, uint32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(buf) + offsets[i];
    uint32_t c =
        crc32_update(0xFFFFFFFFu, p, offsets[i + 1] - offsets[i]) ^ 0xFFFFFFFFu;
    uint32_t h = c & mask;
    out[i] = h ? h : 1u;
  }
}

}  // extern "C"
