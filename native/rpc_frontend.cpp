// Native MessagePack-RPC server front-end.
//
// The reference's transport plane is C++ (msgpack-rpc on the mpio event
// loop, SURVEY.md §2.2); this is the equivalent for jubatus_tpu: sockets,
// connection buffering, msgpack framing, and request-envelope parsing all
// run in C++ threads. Only dispatch crosses into Python — a ctypes
// callback receives (conn, msgid, method, raw params span) and later
// hands back a fully-packed response buffer for the C++ side to write.
//
// ABI (consumed by jubatus_tpu/rpc/native_server.py):
//   handle = jt_rpc_create(request_cb)
//   port   = jt_rpc_listen(handle, port, backlog)   // 0 = ephemeral
//   jt_rpc_respond(handle, conn_id, buf, len)       // any thread
//   jt_rpc_stop(handle); jt_rpc_destroy(handle)
//
// The callback runs on a per-connection reader thread; ctypes acquires
// the GIL for it. Malformed frames close the connection. The msgpack
// parser here only SKIPS values (to find span boundaries) — decoding
// happens in Python, so the full type zoo stays in one place.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- msgpack
// Skip one msgpack object. Returns pointer past it, nullptr if the buffer
// ends mid-object (caller waits for more bytes), (uint8_t*)-1 on garbage.

const uint8_t* kIncomplete = nullptr;
inline const uint8_t* malformed() { return reinterpret_cast<const uint8_t*>(-1); }

const uint8_t* skip_object(const uint8_t* p, const uint8_t* end, int depth) {
  if (depth > 64) return malformed();
  if (p >= end) return kIncomplete;
  uint8_t b = *p++;
  auto need = [&](int64_t n) -> const uint8_t* {
    return (end - p >= n) ? p + n : kIncomplete;
  };
  auto be16 = [&](const uint8_t* q) {
    return (uint32_t(q[0]) << 8) | q[1];
  };
  auto be32 = [&](const uint8_t* q) {
    return (uint32_t(q[0]) << 24) | (uint32_t(q[1]) << 16) |
           (uint32_t(q[2]) << 8) | q[3];
  };

  if (b <= 0x7f || b >= 0xe0) return p;                 // fix ints
  if (b >= 0xa0 && b <= 0xbf) return need(b & 0x1f);    // fixstr
  if (b >= 0x80 && b <= 0x8f) {                         // fixmap
    int64_t n = 2 * int64_t(b & 0x0f);
    for (int64_t i = 0; i < n; ++i) {
      p = skip_object(p, end, depth + 1);
      if (p == kIncomplete || p == malformed()) return p;
    }
    return p;
  }
  if (b >= 0x90 && b <= 0x9f) {                         // fixarray
    int64_t n = b & 0x0f;
    for (int64_t i = 0; i < n; ++i) {
      p = skip_object(p, end, depth + 1);
      if (p == kIncomplete || p == malformed()) return p;
    }
    return p;
  }
  switch (b) {
    case 0xc0: case 0xc2: case 0xc3: return p;          // nil/false/true
    case 0xcc: case 0xd0: return need(1);
    case 0xcd: case 0xd1: return need(2);
    case 0xce: case 0xd2: case 0xca: return need(4);
    case 0xcf: case 0xd3: case 0xcb: return need(8);
    case 0xd4: return need(2);                           // fixext1
    case 0xd5: return need(3);
    case 0xd6: return need(5);
    case 0xd7: return need(9);
    case 0xd8: return need(17);
    case 0xc4: case 0xd9: {                              // bin8/str8
      if (end - p < 1) return kIncomplete;
      int64_t n = *p;
      return need(1 + n);
    }
    case 0xc5: case 0xda: {                              // bin16/str16
      if (end - p < 2) return kIncomplete;
      int64_t n = be16(p);
      return need(2 + n);
    }
    case 0xc6: case 0xdb: {                              // bin32/str32
      if (end - p < 4) return kIncomplete;
      int64_t n = be32(p);
      return need(4 + n);
    }
    case 0xc7: {                                         // ext8
      if (end - p < 2) return kIncomplete;
      int64_t n = *p;
      return need(2 + n);
    }
    case 0xc8: {
      if (end - p < 3) return kIncomplete;
      int64_t n = be16(p);
      return need(3 + n);
    }
    case 0xc9: {
      if (end - p < 5) return kIncomplete;
      int64_t n = be32(p);
      return need(5 + n);
    }
    case 0xdc: case 0xdd: {                              // array16/32
      int hdr = (b == 0xdc) ? 2 : 4;
      if (end - p < hdr) return kIncomplete;
      int64_t n = (b == 0xdc) ? be16(p) : be32(p);
      p += hdr;
      for (int64_t i = 0; i < n; ++i) {
        p = skip_object(p, end, depth + 1);
        if (p == kIncomplete || p == malformed()) return p;
      }
      return p;
    }
    case 0xde: case 0xdf: {                              // map16/32
      int hdr = (b == 0xde) ? 2 : 4;
      if (end - p < hdr) return kIncomplete;
      int64_t n = (b == 0xde) ? be16(p) : be32(p);
      p += hdr;
      for (int64_t i = 0; i < 2 * n; ++i) {
        p = skip_object(p, end, depth + 1);
        if (p == kIncomplete || p == malformed()) return p;
      }
      return p;
    }
    default:
      return malformed();
  }
}

// Parse a positive int at *p (for type / msgid). False on non-int.
bool read_uint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if (b <= 0x7f) { *out = b; return true; }
  auto rd = [&](int n) -> bool {
    if (end - p < n) return false;
    uint64_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 8) | *p++;
    *out = v;
    return true;
  };
  switch (b) {
    case 0xcc: return rd(1);
    case 0xcd: return rd(2);
    case 0xce: return rd(4);
    case 0xcf: return rd(8);
    default: return false;
  }
}

// Parse a str header; sets (data, len). False on non-str.
bool read_str(const uint8_t*& p, const uint8_t* end,
              const uint8_t** data, int64_t* len) {
  if (p >= end) return false;
  uint8_t b = *p++;
  int64_t n;
  if (b >= 0xa0 && b <= 0xbf) {
    n = b & 0x1f;
  } else if (b == 0xd9) {
    if (end - p < 1) return false;
    n = *p++;
  } else if (b == 0xda) {
    if (end - p < 2) return false;
    n = (int64_t(p[0]) << 8) | p[1];
    p += 2;
  } else if (b == 0xdb) {
    if (end - p < 4) return false;
    n = (int64_t(p[0]) << 24) | (int64_t(p[1]) << 16) |
        (int64_t(p[2]) << 8) | p[3];
    p += 4;
  } else {
    return false;
  }
  if (end - p < n) return false;
  *data = p;
  *len = n;
  p += n;
  return true;
}

// ---------------------------------------------------------------- server

// envelope_modern: 1 when the envelope itself proves a post-2013 client
// (the method name arrived as str8 — fixraw/raw16/raw32 are the only
// encodings a vendored-msgpack client can emit). The Python layer ORs it
// into the wire-era fingerprint; without it, clients that deliberately
// pin the era via a str8 method name (RpcClient.call_raw) would be
// fingerprinted from the params span alone.
typedef void (*request_cb)(uint64_t conn_id, uint64_t msgid,
                           const char* method, int64_t method_len,
                           const uint8_t* params, int64_t params_len,
                           int32_t envelope_modern);

// msgid sentinel announcing a connection CLOSED (method/params empty):
// lets the Python side drop per-connection state (wire-era fingerprints)
// deterministically instead of guessing with an eviction cap.
// (~0ull is already taken by the notification sentinel.)
constexpr uint64_t kCloseId = ~0ull - 1;

struct Conn {
  int fd;
  std::mutex write_mu;
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> running{false};
  request_cb cb = nullptr;
  std::thread accept_thread;
  // readers are DETACHED (connection churn must not accumulate joinable
  // threads); stop() waits for this count to reach zero instead of joining
  std::atomic<int64_t> active_readers{0};
  std::mutex conns_mu;
  std::map<uint64_t, std::shared_ptr<Conn>> conns;
  std::atomic<uint64_t> next_conn{1};
};

// msgid sentinel for notifications (no response expected).
const uint64_t kNotifyMsgid = ~uint64_t(0);

// Array header of any spec-legal width (fixarray/array16/array32 — the
// Python transport accepts non-minimal encodings, so must this one).
bool read_array_header(const uint8_t*& p, const uint8_t* end, int64_t* n) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if (b >= 0x90 && b <= 0x9f) {
    *n = b & 0x0f;
    return true;
  }
  if (b == 0xdc) {
    if (end - p < 2) return false;
    *n = (int64_t(p[0]) << 8) | p[1];
    p += 2;
    return true;
  }
  if (b == 0xdd) {
    if (end - p < 4) return false;
    *n = (int64_t(p[0]) << 24) | (int64_t(p[1]) << 16) |
         (int64_t(p[2]) << 8) | p[3];
    p += 4;
    return true;
  }
  return false;
}

// One complete frame: request [0, msgid, method, params] (4 elements) or
// notification [2, method, params] (3 elements); params is everything from
// the last element to the frame end. Returns end-of-frame, kIncomplete, or
// malformed().
const uint8_t* parse_frame(Server* s, uint64_t conn_id, const uint8_t* p,
                           const uint8_t* end) {
  const uint8_t* frame_end = skip_object(p, end, 0);
  if (frame_end == kIncomplete || frame_end == malformed()) return frame_end;
  const uint8_t* q = p;
  int64_t count = 0;
  if (!read_array_header(q, frame_end, &count)) return malformed();
  uint64_t type = 0, msgid = kNotifyMsgid;
  const uint8_t* mdata;
  int64_t mlen;
  if (count == 4) {  // request
    if (!read_uint(q, frame_end, &type) || type != 0) return malformed();
    // both sentinels are reserved: a wire msgid equal to kCloseId would
    // spoof a connection-close notification into the Python layer
    if (!read_uint(q, frame_end, &msgid) || msgid == kNotifyMsgid ||
        msgid == kCloseId)
      return malformed();
  } else if (count == 3) {  // notification
    if (!read_uint(q, frame_end, &type) || type != 2) return malformed();
  } else {
    return malformed();
  }
  const int32_t envelope_modern = (q < frame_end && *q == 0xd9) ? 1 : 0;
  if (!read_str(q, frame_end, &mdata, &mlen)) return malformed();
  s->cb(conn_id, msgid, reinterpret_cast<const char*>(mdata), mlen, q,
        frame_end - q, envelope_modern);
  return frame_end;
}

void reader_loop(Server* s, uint64_t conn_id, std::shared_ptr<Conn> conn) {
  struct Guard {
    std::atomic<int64_t>* n;
    ~Guard() { n->fetch_sub(1); }
  } guard{&s->active_readers};
  std::vector<uint8_t> buf;
  buf.reserve(1 << 16);
  uint8_t chunk[1 << 16];
  while (s->running.load()) {
    ssize_t n = ::recv(conn->fd, chunk, sizeof(chunk), 0);
    if (n <= 0) break;
    buf.insert(buf.end(), chunk, chunk + n);
    const uint8_t* p = buf.data();
    const uint8_t* end = p + buf.size();
    while (p < end) {
      const uint8_t* next = parse_frame(s, conn_id, p, end);
      if (next == kIncomplete) break;
      if (next == malformed()) {
        ::shutdown(conn->fd, SHUT_RDWR);
        goto done;
      }
      p = next;
    }
    buf.erase(buf.begin(), buf.begin() + (p - buf.data()));
  }
done:
  // erase BEFORE closing: once the fd is closed the kernel may recycle
  // its number, and a stale map entry would let jt_rpc_stop shutdown()
  // some unrelated socket that got the recycled fd
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    s->conns.erase(conn_id);
  }
  ::close(conn->fd);
  // after the fd is gone: no response can race this notification
  s->cb(conn_id, kCloseId, "", 0, nullptr, 0, 0);
}

void accept_loop(Server* s) {
  while (s->running.load()) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!s->running.load()) return;
      // EMFILE/ENFILE etc. fail instantly — back off instead of
      // busy-spinning a core until fds free up
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_shared<Conn>();
    conn->fd = fd;
    uint64_t id = s->next_conn.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(s->conns_mu);
      s->conns[id] = conn;
    }
    s->active_readers.fetch_add(1);
    std::thread(reader_loop, s, id, conn).detach();
  }
}

}  // namespace

extern "C" {

void* jt_rpc_create(request_cb cb) {
  Server* s = new Server();
  s->cb = cb;
  return s;
}

// Returns the bound port, or -errno.
int jt_rpc_listen(void* handle, const char* host, int port, int backlog) {
  Server* s = static_cast<Server*>(handle);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  addr.sin_addr.s_addr = INADDR_ANY;
  if (host && *host) {
    // getaddrinfo, not inet_addr: "-b localhost" must work like the
    // Python transport's socket.bind
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      ::close(fd);
      return -EADDRNOTAVAIL;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  if (::listen(fd, backlog > 0 ? backlog : 128) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->listen_fd = fd;
  s->running.store(true);
  s->accept_thread = std::thread(accept_loop, s);
  return ntohs(addr.sin_port);
}

// Write a fully-packed msgpack-rpc response on the connection. Thread-safe.
// Returns 0 on success, -1 if the connection is gone.
int jt_rpc_respond(void* handle, uint64_t conn_id, const uint8_t* data,
                   int64_t len) {
  Server* s = static_cast<Server*>(handle);
  std::shared_ptr<Conn> conn;
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end()) return -1;
    conn = it->second;
  }
  std::lock_guard<std::mutex> g(conn->write_mu);
  int64_t off = 0;
  while (off < len) {
    ssize_t n = ::send(conn->fd, data + off, size_t(len - off), MSG_NOSIGNAL);
    if (n <= 0) return -1;
    off += n;
  }
  return 0;
}

void jt_rpc_stop(void* handle) {
  Server* s = static_cast<Server*>(handle);
  if (!s->running.exchange(false)) return;
  // shutdown unblocks accept(); close only AFTER the accept thread exits
  // so it can never accept() on a recycled fd number
  ::shutdown(s->listen_fd, SHUT_RDWR);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  ::close(s->listen_fd);
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    for (auto& kv : s->conns) ::shutdown(kv.second->fd, SHUT_RDWR);
  }
  // wait for detached readers to drain: no callback may run after stop
  // returns (the Python side may be torn down next)
  while (s->active_readers.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void jt_rpc_destroy(void* handle) {
  jt_rpc_stop(handle);
  delete static_cast<Server*>(handle);
}

}  // extern "C"
