// Native MessagePack-RPC server front-end.
//
// The reference's transport plane is C++ (msgpack-rpc on the mpio event
// loop, SURVEY.md §2.2); this is the equivalent for jubatus_tpu: sockets,
// connection buffering, msgpack framing, and request-envelope parsing all
// run in C++ threads. Only dispatch crosses into Python — a ctypes
// callback receives (conn, msgid, method, raw params span) and later
// hands back a fully-packed response buffer for the C++ side to write.
//
// ABI (consumed by jubatus_tpu/rpc/native_server.py):
//   handle = jt_rpc_create(request_cb)
//   port   = jt_rpc_listen(handle, port, backlog)   // 0 = ephemeral
//   jt_rpc_respond(handle, conn_id, buf, len)       // any thread
//   jt_rpc_stop(handle); jt_rpc_destroy(handle)
//
// The callback runs on a per-connection reader thread; ctypes acquires
// the GIL for it. Malformed frames close the connection. The msgpack
// parser here only SKIPS values (to find span boundaries) — decoding
// happens in Python, so the full type zoo stays in one place.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- msgpack
// Skip one msgpack object. Returns pointer past it, nullptr if the buffer
// ends mid-object (caller waits for more bytes), (uint8_t*)-1 on garbage.

const uint8_t* kIncomplete = nullptr;
inline const uint8_t* malformed() { return reinterpret_cast<const uint8_t*>(-1); }

const uint8_t* skip_object(const uint8_t* p, const uint8_t* end, int depth) {
  if (depth > 64) return malformed();
  if (p >= end) return kIncomplete;
  uint8_t b = *p++;
  auto need = [&](int64_t n) -> const uint8_t* {
    return (end - p >= n) ? p + n : kIncomplete;
  };
  auto be16 = [&](const uint8_t* q) {
    return (uint32_t(q[0]) << 8) | q[1];
  };
  auto be32 = [&](const uint8_t* q) {
    return (uint32_t(q[0]) << 24) | (uint32_t(q[1]) << 16) |
           (uint32_t(q[2]) << 8) | q[3];
  };

  if (b <= 0x7f || b >= 0xe0) return p;                 // fix ints
  if (b >= 0xa0 && b <= 0xbf) return need(b & 0x1f);    // fixstr
  if (b >= 0x80 && b <= 0x8f) {                         // fixmap
    int64_t n = 2 * int64_t(b & 0x0f);
    for (int64_t i = 0; i < n; ++i) {
      p = skip_object(p, end, depth + 1);
      if (p == kIncomplete || p == malformed()) return p;
    }
    return p;
  }
  if (b >= 0x90 && b <= 0x9f) {                         // fixarray
    int64_t n = b & 0x0f;
    for (int64_t i = 0; i < n; ++i) {
      p = skip_object(p, end, depth + 1);
      if (p == kIncomplete || p == malformed()) return p;
    }
    return p;
  }
  switch (b) {
    case 0xc0: case 0xc2: case 0xc3: return p;          // nil/false/true
    case 0xcc: case 0xd0: return need(1);
    case 0xcd: case 0xd1: return need(2);
    case 0xce: case 0xd2: case 0xca: return need(4);
    case 0xcf: case 0xd3: case 0xcb: return need(8);
    case 0xd4: return need(2);                           // fixext1
    case 0xd5: return need(3);
    case 0xd6: return need(5);
    case 0xd7: return need(9);
    case 0xd8: return need(17);
    case 0xc4: case 0xd9: {                              // bin8/str8
      if (end - p < 1) return kIncomplete;
      int64_t n = *p;
      return need(1 + n);
    }
    case 0xc5: case 0xda: {                              // bin16/str16
      if (end - p < 2) return kIncomplete;
      int64_t n = be16(p);
      return need(2 + n);
    }
    case 0xc6: case 0xdb: {                              // bin32/str32
      if (end - p < 4) return kIncomplete;
      int64_t n = be32(p);
      return need(4 + n);
    }
    case 0xc7: {                                         // ext8
      if (end - p < 2) return kIncomplete;
      int64_t n = *p;
      return need(2 + n);
    }
    case 0xc8: {
      if (end - p < 3) return kIncomplete;
      int64_t n = be16(p);
      return need(3 + n);
    }
    case 0xc9: {
      if (end - p < 5) return kIncomplete;
      int64_t n = be32(p);
      return need(5 + n);
    }
    case 0xdc: case 0xdd: {                              // array16/32
      int hdr = (b == 0xdc) ? 2 : 4;
      if (end - p < hdr) return kIncomplete;
      int64_t n = (b == 0xdc) ? be16(p) : be32(p);
      p += hdr;
      for (int64_t i = 0; i < n; ++i) {
        p = skip_object(p, end, depth + 1);
        if (p == kIncomplete || p == malformed()) return p;
      }
      return p;
    }
    case 0xde: case 0xdf: {                              // map16/32
      int hdr = (b == 0xde) ? 2 : 4;
      if (end - p < hdr) return kIncomplete;
      int64_t n = (b == 0xde) ? be16(p) : be32(p);
      p += hdr;
      for (int64_t i = 0; i < 2 * n; ++i) {
        p = skip_object(p, end, depth + 1);
        if (p == kIncomplete || p == malformed()) return p;
      }
      return p;
    }
    default:
      return malformed();
  }
}

// Parse a positive int at *p (for type / msgid). False on non-int.
bool read_uint(const uint8_t*& p, const uint8_t* end, uint64_t* out) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if (b <= 0x7f) { *out = b; return true; }
  auto rd = [&](int n) -> bool {
    if (end - p < n) return false;
    uint64_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 8) | *p++;
    *out = v;
    return true;
  };
  switch (b) {
    case 0xcc: return rd(1);
    case 0xcd: return rd(2);
    case 0xce: return rd(4);
    case 0xcf: return rd(8);
    default: return false;
  }
}

// Parse a str header; sets (data, len). False on non-str.
bool read_str(const uint8_t*& p, const uint8_t* end,
              const uint8_t** data, int64_t* len) {
  if (p >= end) return false;
  uint8_t b = *p++;
  int64_t n;
  if (b >= 0xa0 && b <= 0xbf) {
    n = b & 0x1f;
  } else if (b == 0xd9) {
    if (end - p < 1) return false;
    n = *p++;
  } else if (b == 0xda) {
    if (end - p < 2) return false;
    n = (int64_t(p[0]) << 8) | p[1];
    p += 2;
  } else if (b == 0xdb) {
    if (end - p < 4) return false;
    n = (int64_t(p[0]) << 24) | (int64_t(p[1]) << 16) |
        (int64_t(p[2]) << 8) | p[3];
    p += 4;
  } else {
    return false;
  }
  if (end - p < n) return false;
  *data = p;
  *len = n;
  p += n;
  return true;
}

// ---------------------------------------------------------------- server

// envelope_flags bit 0 (modern): the envelope itself proves a post-2013
// client (the method name arrived as str8 — fixraw/raw16/raw32 are the
// only encodings a vendored-msgpack client can emit). The Python layer
// ORs it into the wire-era fingerprint; without it, clients that
// deliberately pin the era via a str8 method name (RpcClient.call_raw)
// would be fingerprinted from the params span alone.
// bit 1 (extended): the request arrived as the 5/6-element envelope
// [0, msgid, method, params, trace[, deadline]] — the params span handed
// to the callback then ends with the trailing element(s), which the
// Python layer splits off (rpc/server.py split_extras).
typedef void (*request_cb)(uint64_t conn_id, uint64_t msgid,
                           const char* method, int64_t method_len,
                           const uint8_t* params, int64_t params_len,
                           int32_t envelope_flags);

// msgid sentinel announcing a connection CLOSED (method/params empty):
// lets the Python side drop per-connection state (wire-era fingerprints)
// deterministically instead of guessing with an eviction cap.
// (~0ull is already taken by the notification sentinel.)
constexpr uint64_t kCloseId = ~0ull - 1;

// ------------------------------------------------------------- C++ relay
// The proxy's RANDOM-routed hot methods never enter Python at all: the
// client's request frame is forwarded VERBATIM to a backend over a
// per-(client-connection, cluster) pipe, and a pump thread streams the
// backend's response frames back to the client — the reference proxy's
// C++ forwarding shape (proxy.hpp:64-186), with Python keeping the
// routing table fresh (jt_rpc_relay_config) and serving every declined
// case (unknown cluster, pipe failure, non-relay methods) through the
// ordinary callback path. msgids pass through UNCHANGED: a pipe carries
// exactly one client's traffic, so no correlation rewrite is needed, and
// the backend's wire-era autodetection sees that one client's bytes.

struct RelayPipe {
  int fd = -1;
  std::string target;             // "host:port" this pipe is stuck to
  uint64_t generation = 0;        // config generation at creation
  std::mutex wmu;                 // serialize request forwards
  std::mutex omu;                 // guards outstanding
  std::deque<uint64_t> outstanding;
  std::atomic<bool> dead{false};
  // the fd closes ONLY here, when the last referent (forwarder, pump,
  // conn map) lets go — live paths use shutdown(), so a recycled fd
  // number can never be written by a stale holder
  ~RelayPipe() {
    if (fd >= 0) ::close(fd);
  }
};

// Immutable routing snapshot, swapped wholesale by jt_rpc_relay_config
// and read lock-free (atomic shared_ptr load) on every frame — the relay
// decision must not serialize all reader threads on one mutex. Method
// entries carry pointers to PERSISTENT per-method counters (owned by
// RelayCfg, never erased), so counting a relayed request is one
// fetch_add, not a lock.
struct RelayTable {
  std::map<std::string, std::atomic<uint64_t>*> methods;
  // cluster -> [(host, port, "host:port"), ...]
  std::map<std::string,
           std::vector<std::pair<std::pair<std::string, int>, std::string>>>
      clusters;
  double timeout_s = 10.0;
  double idle_expire_s = 60.0;
  uint64_t generation = 0;
};

struct RelayCfg {
  std::atomic<bool> enabled{false};  // lock-free gate for plain servers
  std::mutex mu;                     // guards swaps + the counter map
  std::shared_ptr<const RelayTable> table;
  std::map<std::string, std::unique_ptr<std::atomic<uint64_t>>> counts;
  std::atomic<uint64_t> errors{0};   // synthesized backend-loss responses
  std::atomic<uint64_t> rr{0};
};

struct Conn {
  int fd;
  std::mutex write_mu;
  std::mutex pipes_mu;
  std::map<std::string, std::shared_ptr<RelayPipe>> pipes;  // by cluster
  // like RelayPipe: live paths (reader teardown, stop) only shutdown();
  // the LAST referent — possibly a relay pump mid-write — closes, so a
  // recycled fd number can never be written by a stale holder
  ~Conn() {
    if (fd >= 0) ::close(fd);
  }
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> running{false};
  request_cb cb = nullptr;
  std::thread accept_thread;
  // readers are DETACHED (connection churn must not accumulate joinable
  // threads); stop() waits for this count to reach zero instead of joining
  std::atomic<int64_t> active_readers{0};
  std::atomic<int64_t> active_pumps{0};
  std::mutex conns_mu;
  std::map<uint64_t, std::shared_ptr<Conn>> conns;
  std::atomic<uint64_t> next_conn{1};
  RelayCfg relay;
};

// msgid sentinel for notifications (no response expected).
const uint64_t kNotifyMsgid = ~uint64_t(0);

// Array header of any spec-legal width (fixarray/array16/array32 — the
// Python transport accepts non-minimal encodings, so must this one).
bool read_array_header(const uint8_t*& p, const uint8_t* end, int64_t* n) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if (b >= 0x90 && b <= 0x9f) {
    *n = b & 0x0f;
    return true;
  }
  if (b == 0xdc) {
    if (end - p < 2) return false;
    *n = (int64_t(p[0]) << 8) | p[1];
    p += 2;
    return true;
  }
  if (b == 0xdd) {
    if (end - p < 4) return false;
    *n = (int64_t(p[0]) << 24) | (int64_t(p[1]) << 16) |
         (int64_t(p[2]) << 8) | p[3];
    p += 4;
    return true;
  }
  return false;
}

// ---- relay plumbing ----------------------------------------------------

// pack one positive msgpack uint; returns encoded length (<= 9)
size_t pack_uint(uint64_t v, uint8_t* b) {
  if (v <= 0x7f) { b[0] = uint8_t(v); return 1; }
  if (v <= 0xff) { b[0] = 0xcc; b[1] = uint8_t(v); return 2; }
  if (v <= 0xffff) {
    b[0] = 0xcd; b[1] = uint8_t(v >> 8); b[2] = uint8_t(v);
    return 3;
  }
  if (v <= 0xffffffffull) {
    b[0] = 0xce;
    for (int i = 0; i < 4; ++i) b[1 + i] = uint8_t(v >> (24 - 8 * i));
    return 5;
  }
  b[0] = 0xcf;
  for (int i = 0; i < 8; ++i) b[1 + i] = uint8_t(v >> (56 - 8 * i));
  return 9;
}

bool send_all_fd(int fd, const uint8_t* p, int64_t n) {
  int64_t off = 0;
  while (off < n) {
    ssize_t m = ::send(fd, p + off, size_t(n - off), MSG_NOSIGNAL);
    if (m <= 0) return false;
    off += m;
  }
  return true;
}

bool send_all(int fd, std::mutex& mu, const uint8_t* p, int64_t n) {
  std::lock_guard<std::mutex> g(mu);
  return send_all_fd(fd, p, n);
}

// Backend -> client pump: frame-split the backend stream (responses must
// not interleave MID-FRAME with Python-path responses on the client
// socket) and forward each frame verbatim. On backend loss/timeout every
// outstanding msgid gets a synthesized msgpack-rpc error so no client
// call hangs. The pipe's fd is only shutdown() here — the RelayPipe
// destructor closes it once every referent is gone, so a recycled fd
// number can never be written by a stale forwarder.
void relay_pump(Server* s, std::shared_ptr<Conn> conn,
                std::shared_ptr<RelayPipe> pipe, double timeout_s,
                double idle_expire_s) {
  struct Guard {
    std::atomic<int64_t>* n;
    ~Guard() { n->fetch_sub(1); }
  } guard{&s->active_pumps};
  std::vector<uint8_t> buf;
  uint8_t chunk[1 << 16];
  double idle = 0.0;
  double quiet = 0.0;
  while (s->running.load() && !pipe->dead.load()) {
    ssize_t n = ::recv(pipe->fd, chunk, sizeof(chunk), 0);
    if (n == 0) break;
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
        bool waiting;
        {
          std::lock_guard<std::mutex> g(pipe->omu);
          waiting = !pipe->outstanding.empty();
        }
        if (!waiting) {
          // idle-pipe expiry (≙ the session pool's --pool_expire): a
          // connection that stopped sending relayed traffic should not
          // hold a backend socket forever
          quiet += 0.2;
          if (quiet >= idle_expire_s) break;
          idle = 0.0;
          continue;
        }
        quiet = 0.0;
        idle += 0.2;  // SO_RCVTIMEO tick
        if (idle >= timeout_s) break;  // backend stalled mid-request
        continue;
      }
      break;
    }
    idle = 0.0;
    buf.insert(buf.end(), chunk, chunk + n);
    const uint8_t* p = buf.data();
    const uint8_t* end = p + buf.size();
    bool broken = false;
    while (p < end) {
      const uint8_t* next = skip_object(p, end, 0);
      if (next == kIncomplete) break;
      if (next == malformed()) {
        broken = true;
        break;
      }
      const uint8_t* q = p;
      int64_t cnt = 0;
      uint64_t type = 0, mid = 0;
      if (read_array_header(q, next, &cnt) && cnt == 4 &&
          read_uint(q, next, &type) && type == 1 &&
          read_uint(q, next, &mid)) {
        std::lock_guard<std::mutex> g(pipe->omu);
        for (auto it = pipe->outstanding.begin();
             it != pipe->outstanding.end(); ++it) {
          if (*it == mid) {
            pipe->outstanding.erase(it);
            break;
          }
        }
      }
      if (!send_all(conn->fd, conn->write_mu, p, next - p)) {
        broken = true;
        break;
      }
      p = next;
    }
    if (broken) break;
    buf.erase(buf.begin(), buf.begin() + (p - buf.data()));
  }
  pipe->dead.store(true);
  ::shutdown(pipe->fd, SHUT_RDWR);
  // fail whatever never got its reply
  std::deque<uint64_t> orphans;
  {
    std::lock_guard<std::mutex> g(pipe->omu);
    orphans.swap(pipe->outstanding);
  }
  // fixraw (0xa0|len), not str8: valid in BOTH msgpack eras, so a
  // legacy-era client being relayed still parses its error cleanly
  static const char kErr[] = "relay: backend connection lost";
  static_assert(sizeof(kErr) - 1 <= 31, "fixraw limit");
  for (uint64_t id : orphans) {
    uint8_t frame[64];
    size_t off = 0;
    frame[off++] = 0x94;
    frame[off++] = 0x01;
    off += pack_uint(id, frame + off);
    frame[off++] = uint8_t(0xa0 | (sizeof(kErr) - 1));
    memcpy(frame + off, kErr, sizeof(kErr) - 1);
    off += sizeof(kErr) - 1;
    frame[off++] = 0xc0;
    send_all(conn->fd, conn->write_mu, frame, int64_t(off));
    s->relay.errors.fetch_add(1, std::memory_order_relaxed);
  }
}

// Try to relay one request frame. Returns true when the frame was handed
// to a backend pipe (a response WILL reach the client — from the backend
// or synthesized); false = caller dispatches through Python as usual.
bool relay_try(Server* s, const std::shared_ptr<Conn>& conn,
               const uint8_t* frame, const uint8_t* frame_end,
               uint64_t msgid, const uint8_t* mdata, int64_t mlen,
               const uint8_t* params) {
  // lock-free config snapshot; method check FIRST (method names are
  // short — SSO, no heap) so non-relayed traffic pays almost nothing
  std::shared_ptr<const RelayTable> table =
      std::atomic_load(&s->relay.table);
  if (!table) return false;
  std::string method(reinterpret_cast<const char*>(mdata), size_t(mlen));
  auto mit = table->methods.find(method);
  if (mit == table->methods.end()) return false;
  // cluster name = first element of the params array
  std::string cluster;
  {
    const uint8_t* q = params;
    int64_t pcnt = 0;
    const uint8_t* cd;
    int64_t cl;
    if (!read_array_header(q, frame_end, &pcnt) || pcnt < 1 ||
        !read_str(q, frame_end, &cd, &cl))
      return false;
    cluster.assign(reinterpret_cast<const char*>(cd), size_t(cl));
  }
  auto cit = table->clusters.find(cluster);
  if (cit == table->clusters.end() || cit->second.empty()) return false;
  const auto& tv = cit->second;
  const double timeout_s = table->timeout_s;
  const double idle_expire_s = table->idle_expire_s;
  const uint64_t gen = table->generation;
  const auto& t = tv[s->relay.rr.fetch_add(1) % tv.size()];
  const std::pair<std::string, int>& target = t.first;
  const std::string& target_key = t.second;
  std::shared_ptr<RelayPipe> pipe;
  {
    std::lock_guard<std::mutex> g2(conn->pipes_mu);
    auto pit = conn->pipes.find(cluster);
    if (pit != conn->pipes.end()) {
      pipe = pit->second;
      if (pipe->dead.load()) {
        conn->pipes.erase(pit);
        pipe.reset();
      } else if (pipe->generation != gen) {
        bool still = false;
        for (auto& cand : tv)
          if (cand.second == pipe->target) {
            still = true;
            break;
          }
        if (still) {
          pipe->generation = gen;
        } else {  // backend no longer routed: retire, re-pick below
          pipe->dead.store(true);
          ::shutdown(pipe->fd, SHUT_RDWR);
          conn->pipes.erase(pit);
          pipe.reset();
        }
      }
    }
  }
  if (!pipe) {
    // connect OUTSIDE the config lock (a slow backend must not stall
    // other connections' relay decisions or config pushes), NON-BLOCKING
    // with a bounded budget: this runs on the client's reader thread, so
    // a blackholed backend must cost at most a couple of seconds — after
    // which the request falls back to the Python path (whose session
    // pool has its own timeout discipline) — never the kernel's ~2 min
    // SYN patience, which would also wedge jt_rpc_stop behind the reader
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return false;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(uint16_t(target.second));
    if (::inet_pton(AF_INET, target.first.c_str(), &addr.sin_addr) != 1) {
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (::getaddrinfo(target.first.c_str(), nullptr, &hints, &res) != 0 ||
          res == nullptr) {
        ::close(fd);
        return false;
      }
      addr.sin_addr =
          reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
    int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      double budget = timeout_s < 2.0 ? timeout_s : 2.0;
      rc = ::poll(&pfd, 1, int(budget * 1000));
      int soerr = 0;
      socklen_t slen = sizeof(soerr);
      if (rc == 1)
        ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
      if (rc != 1 || soerr != 0) {
        ::close(fd);
        return false;
      }
    } else if (rc < 0) {
      ::close(fd);
      return false;
    }
    // back to blocking: pumps and forwards rely on blocking send/recv
    int fl = ::fcntl(fd, F_GETFL, 0);
    if (fl >= 0) ::fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    timeval tv{};
    tv.tv_usec = 200000;  // pump tick; timeout accounting is in the pump
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    pipe = std::make_shared<RelayPipe>();
    pipe->fd = fd;
    pipe->target = target_key;
    pipe->generation = gen;
    bool raced = false;
    {
      std::lock_guard<std::mutex> g(conn->pipes_mu);
      auto pit = conn->pipes.find(cluster);
      if (pit != conn->pipes.end() && !pit->second->dead.load()) {
        raced = true;  // another request built the pipe first
      } else {
        conn->pipes[cluster] = pipe;
      }
    }
    if (raced) {  // drop ours (destructor closes the fd); use the winner
      std::lock_guard<std::mutex> g(conn->pipes_mu);
      auto pit = conn->pipes.find(cluster);
      if (pit == conn->pipes.end()) return false;
      pipe = pit->second;
    } else {
      s->active_pumps.fetch_add(1);
      std::thread(relay_pump, s, conn, pipe, timeout_s, idle_expire_s)
          .detach();
    }
  }
  {
    std::lock_guard<std::mutex> g(pipe->omu);
    pipe->outstanding.push_back(msgid);
  }
  bool sent;
  {
    std::lock_guard<std::mutex> g(pipe->wmu);
    sent = !pipe->dead.load() &&
           send_all_fd(pipe->fd, frame, frame_end - frame);
  }
  if (!sent) {
    // whether WE still own the msgid decides who answers: if the pump
    // already swept it into its orphan set (backend died between our
    // enqueue and send), a synthesized error response is on its way to
    // the client — falling back to Python here would produce a SECOND
    // response and a double-applied request on client retry
    bool owned = false;
    {
      std::lock_guard<std::mutex> g(pipe->omu);
      for (auto it = pipe->outstanding.begin();
           it != pipe->outstanding.end(); ++it)
        if (*it == msgid) {
          pipe->outstanding.erase(it);
          owned = true;
          break;
        }
    }
    pipe->dead.store(true);
    ::shutdown(pipe->fd, SHUT_RDWR);
    if (owned) return false;  // no response went out: Python serves it
    return true;              // the pump's synthesized error answers it
  }
  mit->second->fetch_add(1, std::memory_order_relaxed);
  return true;
}

// One complete frame: request [0, msgid, method, params] (4 elements) or
// notification [2, method, params] (3 elements); params is everything from
// the last element to the frame end. Returns end-of-frame, kIncomplete, or
// malformed().
const uint8_t* parse_frame(Server* s, uint64_t conn_id,
                           const std::shared_ptr<Conn>& conn,
                           const uint8_t* p, const uint8_t* end) {
  const uint8_t* frame_end = skip_object(p, end, 0);
  if (frame_end == kIncomplete || frame_end == malformed()) return frame_end;
  const uint8_t* q = p;
  int64_t count = 0;
  if (!read_array_header(q, frame_end, &count)) return malformed();
  uint64_t type = 0, msgid = kNotifyMsgid;
  const uint8_t* mdata;
  int64_t mlen;
  // request; 5 = traced envelope, 6 = traced + deadline envelope, 7 =
  // traced + deadline + principal envelope (the trailing elements are
  // split off by the Python layer / the receiving backend — this framer
  // only needs to not reject them)
  if (count >= 4 && count <= 7) {
    if (!read_uint(q, frame_end, &type) || type != 0) return malformed();
    // both sentinels are reserved: a wire msgid equal to kCloseId would
    // spoof a connection-close notification into the Python layer
    if (!read_uint(q, frame_end, &msgid) || msgid == kNotifyMsgid ||
        msgid == kCloseId)
      return malformed();
  } else if (count == 3) {  // notification
    if (!read_uint(q, frame_end, &type) || type != 2) return malformed();
  } else {
    return malformed();
  }
  int32_t envelope_flags = (q < frame_end && *q == 0xd9) ? 1 : 0;
  // trailing trace [+ deadline [+ principal]]
  if (count >= 5) envelope_flags |= 2;
  if (!read_str(q, frame_end, &mdata, &mlen)) return malformed();
  // relay hot path: configured methods forward to a backend without ever
  // entering Python (the frame is consumed when relay_try returns true).
  // Extended (5/6/7-element) frames forward verbatim too — the trailing
  // elements ride through to the backend, which splits them off itself.
  if (count >= 4 && count <= 7 &&
      s->relay.enabled.load(std::memory_order_relaxed) &&
      relay_try(s, conn, p, frame_end, msgid, mdata, mlen, q))
    return frame_end;
  s->cb(conn_id, msgid, reinterpret_cast<const char*>(mdata), mlen, q,
        frame_end - q, envelope_flags);
  return frame_end;
}

void reader_loop(Server* s, uint64_t conn_id, std::shared_ptr<Conn> conn) {
  struct Guard {
    std::atomic<int64_t>* n;
    ~Guard() { n->fetch_sub(1); }
  } guard{&s->active_readers};
  std::vector<uint8_t> buf;
  buf.reserve(1 << 16);
  uint8_t chunk[1 << 16];
  while (s->running.load()) {
    ssize_t n = ::recv(conn->fd, chunk, sizeof(chunk), 0);
    if (n <= 0) break;
    buf.insert(buf.end(), chunk, chunk + n);
    const uint8_t* p = buf.data();
    const uint8_t* end = p + buf.size();
    while (p < end) {
      const uint8_t* next = parse_frame(s, conn_id, conn, p, end);
      if (next == kIncomplete) break;
      if (next == malformed()) {
        ::shutdown(conn->fd, SHUT_RDWR);
        goto done;
      }
      p = next;
    }
    buf.erase(buf.begin(), buf.begin() + (p - buf.data()));
  }
done:
  // erase BEFORE teardown: a stale map entry would let jt_rpc_stop
  // shutdown() an unrelated socket on a recycled fd number
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    s->conns.erase(conn_id);
  }
  // retire this connection's relay pipes so their pumps exit; the conn
  // fd itself is shutdown() only — the Conn destructor closes it once
  // every pump (which may be mid-write) has let go
  {
    std::map<std::string, std::shared_ptr<RelayPipe>> pipes;
    {
      std::lock_guard<std::mutex> g(conn->pipes_mu);
      pipes.swap(conn->pipes);
    }
    for (auto& kv : pipes) {
      kv.second->dead.store(true);
      ::shutdown(kv.second->fd, SHUT_RDWR);
    }
  }
  ::shutdown(conn->fd, SHUT_RDWR);
  // the fd can no longer produce traffic: no response races this
  s->cb(conn_id, kCloseId, "", 0, nullptr, 0, 0);
}

void accept_loop(Server* s) {
  while (s->running.load()) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!s->running.load()) return;
      // EMFILE/ENFILE etc. fail instantly — back off instead of
      // busy-spinning a core until fds free up
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_shared<Conn>();
    conn->fd = fd;
    uint64_t id = s->next_conn.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(s->conns_mu);
      s->conns[id] = conn;
    }
    s->active_readers.fetch_add(1);
    std::thread(reader_loop, s, id, conn).detach();
  }
}

}  // namespace

extern "C" {

void* jt_rpc_create(request_cb cb) {
  Server* s = new Server();
  s->cb = cb;
  return s;
}

// Returns the bound port, or -errno.
int jt_rpc_listen(void* handle, const char* host, int port, int backlog) {
  Server* s = static_cast<Server*>(handle);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  addr.sin_addr.s_addr = INADDR_ANY;
  if (host && *host) {
    // getaddrinfo, not inet_addr: "-b localhost" must work like the
    // Python transport's socket.bind
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr) {
      ::close(fd);
      return -EADDRNOTAVAIL;
    }
    addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
    ::freeaddrinfo(res);
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  if (::listen(fd, backlog > 0 ? backlog : 128) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->listen_fd = fd;
  s->running.store(true);
  s->accept_thread = std::thread(accept_loop, s);
  return ntohs(addr.sin_port);
}

// Write a fully-packed msgpack-rpc response on the connection. Thread-safe.
// Returns 0 on success, -1 if the connection is gone.
int jt_rpc_respond(void* handle, uint64_t conn_id, const uint8_t* data,
                   int64_t len) {
  Server* s = static_cast<Server*>(handle);
  std::shared_ptr<Conn> conn;
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    auto it = s->conns.find(conn_id);
    if (it == s->conns.end()) return -1;
    conn = it->second;
  }
  std::lock_guard<std::mutex> g(conn->write_mu);
  int64_t off = 0;
  while (off < len) {
    ssize_t n = ::send(conn->fd, data + off, size_t(len - off), MSG_NOSIGNAL);
    if (n <= 0) return -1;
    off += n;
  }
  return 0;
}

void jt_rpc_stop(void* handle) {
  Server* s = static_cast<Server*>(handle);
  if (!s->running.exchange(false)) return;
  // shutdown unblocks accept(); close only AFTER the accept thread exits
  // so it can never accept() on a recycled fd number
  ::shutdown(s->listen_fd, SHUT_RDWR);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  ::close(s->listen_fd);
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    for (auto& kv : s->conns) {
      {
        std::lock_guard<std::mutex> g2(kv.second->pipes_mu);
        for (auto& pk : kv.second->pipes) {
          pk.second->dead.store(true);
          ::shutdown(pk.second->fd, SHUT_RDWR);
        }
      }
      ::shutdown(kv.second->fd, SHUT_RDWR);
    }
  }
  // wait for detached readers AND relay pumps to drain: no callback may
  // run after stop returns (the Python side may be torn down next), and
  // no pump may outlive the server it counts against
  while (s->active_readers.load() > 0 || s->active_pumps.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void jt_rpc_destroy(void* handle) {
  jt_rpc_stop(handle);
  delete static_cast<Server*>(handle);
}

// Configure (or reconfigure) the C++ relay plane. methods_nl: relayable
// method names, one per line. clusters_spec: "cluster\thost:port[,...]"
// lines — the CURRENT routing table (replaced wholesale; generation
// bumps retire pipes stuck to de-routed backends). timeout_s: backend
// stall budget per pipe. Passing empty methods or clusters disables the
// fast path (every request falls back to the Python callback).
int jt_rpc_relay_config(void* handle, const char* methods_nl,
                        const char* clusters_spec, double timeout_s,
                        double idle_expire_s) {
  Server* s = static_cast<Server*>(handle);
  std::set<std::string> methods;
  std::map<std::string,
           std::vector<std::pair<std::pair<std::string, int>, std::string>>>
      clusters;
  std::string m(methods_nl ? methods_nl : "");
  size_t pos = 0;
  while (pos < m.size()) {
    size_t nl = m.find('\n', pos);
    if (nl == std::string::npos) nl = m.size();
    if (nl > pos) methods.insert(m.substr(pos, nl - pos));
    pos = nl + 1;
  }
  std::string c(clusters_spec ? clusters_spec : "");
  pos = 0;
  while (pos < c.size()) {
    size_t nl = c.find('\n', pos);
    if (nl == std::string::npos) nl = c.size();
    std::string line = c.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.empty()) continue;
    size_t tab = line.find('\t');
    if (tab == std::string::npos) return -1;
    std::string cluster = line.substr(0, tab);
    auto& vec = clusters[cluster];
    size_t tpos = tab + 1;
    while (tpos <= line.size()) {
      size_t comma = line.find(',', tpos);
      if (comma == std::string::npos) comma = line.size();
      std::string hp = line.substr(tpos, comma - tpos);
      tpos = comma + 1;
      if (hp.empty()) continue;
      size_t colon = hp.rfind(':');
      if (colon == std::string::npos) return -1;
      int port = atoi(hp.c_str() + colon + 1);
      if (port <= 0 || port > 65535) return -1;
      vec.push_back({{hp.substr(0, colon), port}, hp});
    }
  }
  bool on = !methods.empty() && !clusters.empty();
  {
    std::lock_guard<std::mutex> g(s->relay.mu);
    auto next = std::make_shared<RelayTable>();
    for (const std::string& name : methods) {
      auto& slot = s->relay.counts[name];
      if (!slot) slot.reset(new std::atomic<uint64_t>(0));
      next->methods[name] = slot.get();
    }
    next->clusters.swap(clusters);
    next->timeout_s = timeout_s > 0 ? timeout_s : 10.0;
    next->idle_expire_s = idle_expire_s > 0 ? idle_expire_s : 60.0;
    next->generation =
        (s->relay.table ? s->relay.table->generation : 0) + 1;
    std::atomic_store(&s->relay.table,
                      std::shared_ptr<const RelayTable>(next));
  }
  s->relay.enabled.store(on, std::memory_order_relaxed);
  return 0;
}

// Dump per-method relayed-request counts as "method\tcount\n" lines,
// plus a "__errors__" line counting synthesized backend-loss responses.
// Returns bytes written, or -(bytes needed) when cap is too small.
int64_t jt_rpc_relay_stats(void* handle, char* buf, int64_t cap) {
  Server* s = static_cast<Server*>(handle);
  std::string out;
  {
    std::lock_guard<std::mutex> g(s->relay.mu);
    for (auto& kv : s->relay.counts) {
      out += kv.first;
      out += '\t';
      out += std::to_string(kv.second->load(std::memory_order_relaxed));
      out += '\n';
    }
  }
  out += "__errors__\t";
  out += std::to_string(s->relay.errors.load(std::memory_order_relaxed));
  out += '\n';
  if (int64_t(out.size()) > cap) return -int64_t(out.size());
  memcpy(buf, out.data(), out.size());
  return int64_t(out.size());
}

}  // extern "C"
