// Sample native fv_converter splitter plugin — the C ABI counterpart of
// the reference's dlopen'd word splitters (plugin/src/fv_converter,
// extern "C" create pattern, mecab_splitter.cpp:203-230).
//
// ABI (consumed by jubatus_tpu.native.load_native_splitter via ctypes):
//
//   void* jt_splitter_create(const char* const* keys,
//                            const char* const* vals, int n);
//   int64_t jt_splitter_split(void* handle, const char* text, int64_t len,
//                             int64_t* begins, int64_t* ends, int64_t cap);
//       → number of tokens found; writes up to cap byte ranges. If the
//         return value exceeds cap the caller retries with a larger buffer.
//   void jt_splitter_destroy(void* handle);
//
// This sample emits byte n-grams (param "char_num", default 1) — ASCII
// text only; a production tokenizer would walk utf-8 boundaries.
//
// Build: `make -C native` → build/libsample_ngram_splitter.so

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {
struct Ngram {
  int64_t n;
};
}  // namespace

extern "C" {

void* jt_splitter_create(const char* const* keys, const char* const* vals,
                         int n) {
  Ngram* s = new Ngram{1};
  for (int i = 0; i < n; ++i) {
    if (std::strcmp(keys[i], "char_num") == 0) {
      long v = std::strtol(vals[i], nullptr, 10);
      if (v < 1) {
        delete s;
        return nullptr;
      }
      s->n = v;
    }
  }
  return s;
}

int64_t jt_splitter_split(void* handle, const char* text, int64_t len,
                          int64_t* begins, int64_t* ends, int64_t cap) {
  const Ngram* s = static_cast<const Ngram*>(handle);
  int64_t count = len - s->n + 1;
  if (count < 0) count = 0;
  int64_t emit = count < cap ? count : cap;
  for (int64_t i = 0; i < emit; ++i) {
    begins[i] = i;
    ends[i] = i + s->n;
  }
  return count;
}

void jt_splitter_destroy(void* handle) {
  delete static_cast<Ngram*>(handle);
}

}  // extern "C"
