"""Test bootstrap: force an 8-device virtual CPU mesh before jax imports.

Mirrors the reference's test strategy (SURVEY.md §4): distributed logic is
tested without a cluster — here, multi-chip sharding/collectives run on
virtual CPU devices via --xla_force_host_platform_device_count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# subprocesses spawned by tests (jubavisor children) must not touch the
# real TPU tunnel: their sitecustomize re-pins JAX_PLATFORMS=axon, so the
# server main honors this override instead (server/__main__.py)
os.environ["JUBATUS_TPU_PLATFORM"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sandbox pins JAX_PLATFORMS=axon via sitecustomize before conftest
# runs; the config update wins regardless of import order.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: process-level integration tests (forked servers)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(0)
