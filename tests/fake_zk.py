"""Minimal in-process ZooKeeper server for protocol-level tests.

Speaks the same jute wire as coord/zk.py's client: session handshake
(including SESSION RESUMPTION: a ConnectRequest carrying a known live
sessionId reattaches that session to the new socket, like a real
ensemble member), create (persistent/ephemeral/sequence), delete,
exists, getData, setData, getChildren, one-shot watches, ping,
closeSession. By default a closed/dead connection drops its ephemerals
and fires watches immediately (the historical behavior most tests
rely on); setting ``session_grace`` to a number of seconds keeps an
abruptly-disconnected session alive for that long awaiting resumption —
the knob the reconnect chaos tests use. ``expire_session`` force-expires
one. Enough ZooKeeper to prove the client's encoding, watch re-arm, and
session semantics without a live quorum — the real-ZK integration tests
gate on JUBATUS_TPU_ZK.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple


def _rd_i32(b, off):
    return struct.unpack_from(">i", b, off)[0], off + 4


def _rd_i64(b, off):
    return struct.unpack_from(">q", b, off)[0], off + 8


def _rd_str(b, off):
    n, off = _rd_i32(b, off)
    if n < 0:
        return "", off
    return b[off:off + n].decode(), off + n


def _rd_buf(b, off):
    n, off = _rd_i32(b, off)
    if n < 0:
        return b"", off
    return bytes(b[off:off + n]), off + n


def _w_str(s):
    raw = s.encode()
    return struct.pack(">i", len(raw)) + raw


def _w_buf(v):
    return struct.pack(">i", len(v)) + v


def _w_stat(version=0, ephemeral_owner=0, num_children=0, data_len=0):
    return (struct.pack(">qqqq", 0, 0, 0, 0)
            + struct.pack(">iii", version, 0, 0)
            + struct.pack(">q", ephemeral_owner)
            + struct.pack(">ii", data_len, num_children)
            + struct.pack(">q", 0))


class _Node:
    __slots__ = ("data", "owner", "version")

    def __init__(self, data=b"", owner=0):
        self.data = data
        self.owner = owner
        self.version = 0


class FakeZkServer:
    ZOK, ZNONODE, ZNODEEXISTS, ZNOTEMPTY = 0, -101, -110, -111

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.nodes: Dict[str, _Node] = {"/": _Node()}
        self.seq = 0
        #: (path, kind) -> list of (conn, wlock); kind "data" | "child"
        self._watches: Dict[Tuple[str, str], List] = {}
        self._sock: Optional[socket.socket] = None
        self._next_session = 1
        self.port: Optional[int] = None
        self._running = False
        #: sid -> {"token": <current connection's marker>, "timer": Timer?,
        #:          "timeout": negotiated ms}
        self.sessions: Dict[int, dict] = {}
        #: None: abrupt disconnect expires the session at once (historic
        #: behavior). A float: the session survives that many seconds
        #: awaiting resumption — the real-ZK model, for reconnect tests.
        self.session_grace: Optional[float] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self, port: int = 0) -> int:
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))
        s.listen(16)
        self._sock = s
        self.port = s.getsockname()[1]
        self._running = True
        threading.Thread(target=self._accept, daemon=True,
                         name="fakezk-accept").start()
        return self.port

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    # -- wire -----------------------------------------------------------------
    def _accept(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True,
                             name="fakezk-conn").start()

    @staticmethod
    def _read_frame(conn) -> bytes:
        hdr = b""
        while len(hdr) < 4:
            c = conn.recv(4 - len(hdr))
            if not c:
                raise OSError("closed")
            hdr += c
        (n,) = struct.unpack(">i", hdr)
        body = b""
        while len(body) < n:
            c = conn.recv(n - len(body))
            if not c:
                raise OSError("closed")
            body += c
        return body

    def _serve(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        session: Optional[int] = None
        token = object()
        clean = False
        try:
            req = self._read_frame(conn)
            off = 0
            _, off = _rd_i32(req, off)       # protocolVersion
            _, off = _rd_i64(req, off)       # lastZxid
            timeout, off = _rd_i32(req, off)
            want_sid = 0
            if len(req) > off:
                want_sid, off = _rd_i64(req, off)
            with self._lock:
                if want_sid:
                    sess = self.sessions.get(want_sid)
                    if sess is None:
                        # expired: answer session 0 / timeOut 0 and hang up
                        resp = (struct.pack(">ii", 0, 0)
                                + struct.pack(">q", 0)
                                + struct.pack(">i", 16) + b"\x00" * 16)
                        with wlock:
                            conn.sendall(struct.pack(">i", len(resp)) + resp)
                        return
                    t = sess.pop("timer", None)
                    if t is not None:
                        t.cancel()
                    sess["token"] = token
                    session = want_sid
                    timeout = sess["timeout"]
                else:
                    session = self._next_session
                    self._next_session += 1
                    self.sessions[session] = {"token": token,
                                              "timeout": timeout}
            resp = (struct.pack(">i", 0) + struct.pack(">i", timeout)
                    + struct.pack(">q", session)
                    + struct.pack(">i", 16) + b"\x00" * 16)
            with wlock:
                conn.sendall(struct.pack(">i", len(resp)) + resp)
            while True:
                frame = self._read_frame(conn)
                xid, off = _rd_i32(frame, 0)
                op, off = _rd_i32(frame, off)
                if op == 11:                 # ping
                    self._reply(conn, wlock, -2, 0, b"")
                    continue
                if op == -11:                # closeSession
                    clean = True
                    self._reply(conn, wlock, xid, 0, b"")
                    return
                err, payload = self._dispatch(op, frame, off, session,
                                              conn, wlock)
                self._reply(conn, wlock, xid, err, payload)
        except OSError:
            pass
        finally:
            if session is not None:
                with self._lock:
                    sess = self.sessions.get(session)
                    owner = sess is not None and sess.get("token") is token
                if owner:
                    if clean or self.session_grace is None:
                        self.expire_session(session, token)
                    else:
                        t = threading.Timer(self.session_grace,
                                            self.expire_session,
                                            args=(session, token))
                        t.daemon = True
                        with self._lock:
                            sess["timer"] = t
                        t.start()
            # a real ensemble's watches die with the connection (clients
            # re-arm on resume); prune this conn's entries so watch-table
            # growth in tests measures CLIENT leaks, not dead sockets
            with self._lock:
                for key in list(self._watches):
                    kept = [t for t in self._watches[key] if t[0] is not conn]
                    if kept:
                        self._watches[key] = kept
                    else:
                        del self._watches[key]
            try:
                conn.close()
            except OSError:
                pass

    def expire_session(self, session: int, token=None) -> None:
        """Expire ``session`` now (test hook; also the grace-timer body).
        With ``token``, only if that connection still owns the session —
        a resumed session must not be killed by its dead predecessor."""
        with self._lock:
            sess = self.sessions.get(session)
            if sess is None:
                return
            if token is not None and sess.get("token") is not token:
                return
            t = sess.pop("timer", None)
            if t is not None:
                t.cancel()
            del self.sessions[session]
        self._drop_session(session)

    @staticmethod
    def _reply(conn, wlock, xid, err, payload) -> None:
        frame = struct.pack(">iqi", xid, 0, err) + payload
        try:
            with wlock:
                conn.sendall(struct.pack(">i", len(frame)) + frame)
        except OSError:
            pass

    def _notify(self, path: str, kind: str, ev_type: int) -> None:
        with self._lock:
            targets = self._watches.pop((path, kind), [])
        ev = (struct.pack(">iqi", -1, 0, 0)
              + struct.pack(">ii", ev_type, 3) + _w_str(path))
        for conn, wlock in targets:
            try:
                with wlock:
                    conn.sendall(struct.pack(">i", len(ev)) + ev)
            except OSError:
                pass

    def _fire_for(self, path: str, ev_type: int) -> None:
        self._notify(path, "data", ev_type)
        parent = path.rsplit("/", 1)[0] or "/"
        self._notify(parent, "child", 4)

    # -- ops ------------------------------------------------------------------
    def _dispatch(self, op, frame, off, session, conn, wlock):
        if op == 1:                          # create
            path, off = _rd_str(frame, off)
            data, off = _rd_buf(frame, off)
            nacl, off = _rd_i32(frame, off)
            for _ in range(nacl):
                _, off = _rd_i32(frame, off)
                _, off = _rd_str(frame, off)
                _, off = _rd_str(frame, off)
            flags, off = _rd_i32(frame, off)
            with self._lock:
                parent = path.rsplit("/", 1)[0] or "/"
                if parent not in self.nodes:
                    return self.ZNONODE, b""
                if flags & 2:                # sequence
                    path = f"{path}{self.seq:010d}"
                    self.seq += 1
                if path in self.nodes:
                    return self.ZNODEEXISTS, b""
                self.nodes[path] = _Node(
                    data, session if flags & 1 else 0)
            self._fire_for(path, 1)
            return 0, _w_str(path)
        if op == 2:                          # delete
            path, off = _rd_str(frame, off)
            with self._lock:
                if path not in self.nodes:
                    return self.ZNONODE, b""
                prefix = path + "/"
                if any(p.startswith(prefix) for p in self.nodes):
                    return self.ZNOTEMPTY, b""
                del self.nodes[path]
            self._fire_for(path, 2)
            return 0, b""
        if op == 3:                          # exists
            path, off = _rd_str(frame, off)
            watch = frame[off] != 0
            with self._lock:
                node = self.nodes.get(path)
                if watch:
                    self._watches.setdefault((path, "data"), []).append(
                        (conn, wlock))
            if node is None:
                return self.ZNONODE, b""
            return 0, _w_stat(node.version, node.owner,
                              data_len=len(node.data))
        if op == 4:                          # getData
            path, off = _rd_str(frame, off)
            watch = frame[off] != 0
            with self._lock:
                node = self.nodes.get(path)
                if node is not None and watch:
                    self._watches.setdefault((path, "data"), []).append(
                        (conn, wlock))
            if node is None:
                return self.ZNONODE, b""
            return 0, _w_buf(node.data) + _w_stat(node.version, node.owner,
                                                  data_len=len(node.data))
        if op == 5:                          # setData
            path, off = _rd_str(frame, off)
            data, off = _rd_buf(frame, off)
            with self._lock:
                node = self.nodes.get(path)
                if node is None:
                    return self.ZNONODE, b""
                node.data = data
                node.version += 1
                version = node.version
            self._notify(path, "data", 3)
            return 0, _w_stat(version, 0, data_len=len(data))
        if op == 8:                          # getChildren
            path, off = _rd_str(frame, off)
            watch = frame[off] != 0
            with self._lock:
                if path not in self.nodes:
                    return self.ZNONODE, b""
                prefix = path.rstrip("/") + "/"
                kids = sorted({p[len(prefix):].split("/", 1)[0]
                               for p in self.nodes if p.startswith(prefix)})
                if watch:
                    self._watches.setdefault((path, "child"), []).append(
                        (conn, wlock))
            out = struct.pack(">i", len(kids))
            for k in kids:
                out += _w_str(k)
            return 0, out
        return -6, b""                       # unimplemented

    def _drop_session(self, session: int) -> None:
        with self._lock:
            mine = [p for p, n in self.nodes.items() if n.owner == session]
            for p in mine:
                del self.nodes[p]
        for p in mine:
            self._fire_for(p, 2)
