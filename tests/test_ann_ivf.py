"""IVF approximate-NN tier tests (ISSUE 16): ops-level kernel
correctness, the cell-arena layout, flat + mesh backend parity against
the exact scan (tie-aware — equal-distance groups at the k boundary
may legally order differently), the --ann off bit-identity contract,
checkpoint/reshard centroid persistence, migration zero-loss with the
tier armed, and online cell re-splits."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from jubatus_tpu.models._nn_backend import NNBackend
from jubatus_tpu.ops import ivf, knn

DIM = 64


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.asarray(jax.devices()[:8]), axis_names=("shard",))


def _vec(rng, nnz=6):
    idx = rng.integers(1, DIM, size=nnz)
    val = rng.normal(size=nnz)
    return [(int(i), float(v)) for i, v in zip(idx, val)]


def tie_equal(got, want, atol=1e-5):
    """Approximate-vs-exact result comparison that is robust to tie
    groups at the k boundary: distance sequences must match, and id
    sets must match below the boundary distance (ties AT the boundary
    may resolve to different members)."""
    gd = [d for _, d in got]
    wd = [d for _, d in want]
    np.testing.assert_allclose(gd, wd, atol=atol, rtol=1e-5)
    if not wd:
        return
    bound = wd[-1] - atol
    g_ids = {r for r, d in got if d < bound}
    w_ids = {r for r, d in want if d < bound}
    assert g_ids == w_ids


# -- ops-level kernels -------------------------------------------------------

def test_lsh_embedding_is_exact_hamming(rng):
    """The lsh probe embedding (unpacked ±1 bits) makes squared
    euclidean distance EXACTLY 4x the bit-hamming distance — cell
    assignment ranks identically to the signature metric."""
    W, hash_num = 2, 64
    sigs = jnp.asarray(rng.integers(0, 2**32, size=(32, W), dtype=np.uint32))
    emb = ivf.embed_signatures(sigs, method="lsh", hash_num=hash_num)
    d2 = np.asarray(ivf.pairwise_sq_dists(emb, emb))
    ham = np.asarray(knn._hamming_distances_batch_xla(
        sigs, sigs, hash_num=hash_num)) * hash_num  # bits
    np.testing.assert_allclose(d2, 4.0 * ham, atol=1e-3)


def test_auto_cells_sqrt_scaling():
    assert ivf.auto_cells(0) == 8
    assert ivf.auto_cells(100) == 8
    assert ivf.auto_cells(10_000) == 128       # pow2 near sqrt(1e4)=100
    assert ivf.auto_cells(1_000_000) == 1024
    # always a power of two
    for n in (5, 500, 77_000, 3_000_000):
        c = ivf.auto_cells(n)
        assert c & (c - 1) == 0


@pytest.mark.parametrize("method", ["lsh", "minhash", "euclid_lsh"])
def test_candidate_kernels_match_batch_kernels(method, rng):
    """candidate_sig_distances over gathered rows == the arena-wide
    batch kernel's values for those rows (euclid_lsh carries ~5e-4
    float32 accumulation-order noise vs the expansion kernel)."""
    B, C, hash_num = 3, 64, 64
    if method == "lsh":
        q = jnp.asarray(rng.integers(0, 2**32, size=(B, 2), dtype=np.uint32))
        rows = jnp.asarray(
            rng.integers(0, 2**32, size=(C, 2), dtype=np.uint32))
        full = knn._hamming_distances_batch_xla(q, rows, hash_num=hash_num)
    elif method == "minhash":
        q = jnp.asarray(
            rng.integers(0, 2**32, size=(B, hash_num), dtype=np.uint32))
        rows = jnp.asarray(
            rng.integers(0, 2**32, size=(C, hash_num), dtype=np.uint32))
        full = knn._minhash_distances_batch_xla(q, rows)
    else:
        q = jnp.asarray(rng.normal(size=(B, hash_num)).astype(np.float32))
        rows = jnp.asarray(rng.normal(size=(C, hash_num)).astype(np.float32))
        full = knn.euclid_lsh_distances_batch(q, rows, hash_num=hash_num)
    cand = jnp.tile(jnp.arange(C), (B, 1))    # every row as candidate
    d = ivf.candidate_sig_distances(q, rows[cand], method=method,
                                    hash_num=hash_num)
    np.testing.assert_allclose(np.asarray(d), np.asarray(full), atol=1e-3)


def test_ivf_topk_full_probe_matches_exact(rng):
    """Probing EVERY cell reduces IVF to the exact scan — distances
    must match the brute-force top-k bit for bit (tie-aware on ids)."""
    C, n_cells, k, hash_num = 200, 4, 10, 64
    sigs = jnp.asarray(rng.integers(0, 2**32, size=(C, 2), dtype=np.uint32))
    emb = ivf.embed_signatures(sigs, method="lsh", hash_num=hash_num)
    cen = ivf.train_centroids(np.asarray(emb), n_cells, seed=1)
    cells = np.asarray(ivf.assign_cells(emb, jnp.asarray(cen)))
    cap = int(np.bincount(cells, minlength=n_cells).max())
    slots = np.full((n_cells, cap), -1, np.int32)
    fill = np.zeros(n_cells, np.int64)
    for slot, c in enumerate(cells):
        slots[c, fill[c]] = slot
        fill[c] += 1
    q = sigs[:3]
    d, s = ivf.ivf_topk(q, emb[:3], sigs, jnp.asarray(cen),
                        jnp.asarray(slots), method="lsh",
                        hash_num=hash_num, k=k, nprobe=n_cells)
    full = np.asarray(knn._hamming_distances_batch_xla(q, sigs,
                                                       hash_num=hash_num))
    want = np.sort(full, axis=1)[:, :k]
    np.testing.assert_allclose(np.asarray(d), want, atol=1e-5)


def test_hierarchical_assignment_agrees_with_flat(rng):
    """The two-level (super-cell) assignment used by the 1e8-row build
    path agrees with the flat argmin on the vast majority of rows."""
    n, n_cells = 4000, 16
    # clustered data (the regime the tier serves): planted centers +
    # small noise — uniform gaussian has no cell structure to agree on
    centers = rng.normal(size=(n_cells, DIM)) * 4.0
    emb = jnp.asarray(
        (centers[rng.integers(0, n_cells, size=n)]
         + rng.normal(size=(n, DIM))).astype(np.float32))
    cen = jnp.asarray(ivf.train_centroids(np.asarray(emb), n_cells, seed=0))
    flat = np.asarray(ivf.assign_cells(emb, cen))
    supers, members = ivf.build_super(np.asarray(cen), n_super=4, seed=0)
    hier = np.asarray(ivf.assign_cells_hier(
        emb, cen, jnp.asarray(supers), jnp.asarray(members), top_supers=2))
    assert (flat == hier).mean() > 0.9
    # the host-side bulk-build path is the same assignment, grouped
    # into per-super BLAS gemms — identical answers, no gather tensor
    grouped = ivf.assign_cells_grouped(np.asarray(emb), np.asarray(cen),
                                       supers, members, top_supers=2)
    assert (grouped == hier).mean() > 0.999


# -- cell arenas -------------------------------------------------------------

def test_cell_arenas_assign_move_remove_tables():
    from jubatus_tpu.core.row_store import RowStore
    from jubatus_tpu.parallel.row_store import CellArenas

    store = RowStore()
    for i in range(6):
        store.set_row(f"r{i}", [(1, 1.0)])
    a = CellArenas(store, 2)
    for i in range(6):
        a.assign(f"r{i}", i % 2)
    assert a.sizes() == [3, 3]
    a.assign("r0", 1)                        # move across cells
    assert a.cell_of("r0") == 1 and a.sizes() == [2, 4]
    a.remove("r5")
    tab, cap = a.device_tables()
    assert tab.shape[0] == 2 and cap >= 3
    live = np.asarray(tab)
    assert (live >= 0).sum() == 5            # r5 gone, padding is -1
    c = a.add_cell()
    assert c == 2 and a.n_cells == 3
    # removing a store row invalidates lazily: dead ids pruned on build
    store.remove_row("r1")
    tab2, _ = a.device_tables()
    assert (np.asarray(tab2) >= 0).sum() == 4


# -- backend: flat -----------------------------------------------------------

@pytest.mark.parametrize("method", ["lsh", "minhash", "euclid_lsh"])
def test_flat_full_probe_parity(method, rng):
    exact = NNBackend(method, dim=DIM, hash_num=64)
    ann = NNBackend(method, dim=DIM, hash_num=64)
    ann.configure_ann("ivf", cells=4, nprobe=4)   # full probe
    for i in range(150):
        v = _vec(rng)
        exact.set_row(f"r{i}", v)
        ann.set_row(f"r{i}", v)
    for _ in range(4):
        q = _vec(rng)
        tie_equal(ann.neighbors(q, 8), exact.neighbors(q, 8), atol=1e-3)


@pytest.mark.parametrize("method", ["inverted_index", "euclid"])
def test_flat_exact_methods_rescore_is_exact(method, rng):
    """Exact engines (cosine/euclid) under IVF: the probe is hashed but
    the rescore is the TRUE metric, so full-probe answers are exact."""
    exact = NNBackend(method, dim=DIM, hash_num=64)
    ann = NNBackend(method, dim=DIM, hash_num=64)
    ann.configure_ann("ivf", cells=4, nprobe=4)
    for i in range(120):
        v = _vec(rng)
        exact.set_row(f"r{i}", v)
        ann.set_row(f"r{i}", v)
    q = _vec(rng)
    tie_equal(ann.neighbors(q, 8), exact.neighbors(q, 8), atol=1e-4)


def test_ann_off_is_bit_identical(rng):
    """--ann off IS the seed path: toggling the tier on and back off
    returns byte-for-byte the exact scan's answers."""
    base = NNBackend("lsh", dim=DIM, hash_num=64)
    toggled = NNBackend("lsh", dim=DIM, hash_num=64)
    toggled.configure_ann("ivf", cells=4, nprobe=2)
    for i in range(100):
        v = _vec(rng)
        base.set_row(f"r{i}", v)
        toggled.set_row(f"r{i}", v)
    q = _vec(rng)
    toggled.neighbors(q, 5)                  # builds the index
    toggled.configure_ann("off")
    assert toggled.neighbors(q, 5) == base.neighbors(q, 5)
    assert toggled.ann_stats() == {}


def test_online_insert_lands_in_a_cell(rng):
    b = NNBackend("lsh", dim=DIM, hash_num=64)
    b.configure_ann("ivf", cells=4, nprobe=4)
    for i in range(140):
        b.set_row(f"r{i}", _vec(rng))
    b.neighbors(_vec(rng), 5)                # build
    v = _vec(rng)
    b.set_row("fresh", v)
    res = b.neighbors(v, 140)                # flushes + assigns
    assert b._ann_arenas.cell_of("fresh") is not None
    assert "fresh" in [r for r, _ in res]


def test_resplit_grows_cells_and_keeps_answers(rng):
    b = NNBackend("lsh", dim=DIM, hash_num=64)
    b.configure_ann("ivf", cells=2, nprobe=64)
    b.ann_split_width = 24                   # force overflow re-splits
    exact = NNBackend("lsh", dim=DIM, hash_num=64)
    for i in range(160):
        v = _vec(rng)
        b.set_row(f"r{i}", v)
        exact.set_row(f"r{i}", v)
    q = _vec(rng)
    got = b.neighbors(q, 8)
    st = b.ann_stats()
    assert st["resplits"] > 0 and st["cells"] > 2
    assert st["rows_indexed"] == 160
    tie_equal(got, exact.neighbors(q, 8), atol=1e-3)


# -- backend: mesh -----------------------------------------------------------

@pytest.mark.parametrize("method", ["lsh", "minhash", "euclid_lsh"])
def test_mesh_full_probe_parity(method, mesh, rng):
    exact = NNBackend(method, dim=DIM, hash_num=64)
    ann = NNBackend(method, dim=DIM, hash_num=64)
    ann.configure_ann("ivf", cells=4, nprobe=4)
    for i in range(170):
        v = _vec(rng)
        exact.set_row(f"r{i}", v)
        ann.set_row(f"r{i}", v)
    ann.attach_mesh(mesh)
    for _ in range(3):
        q = _vec(rng)
        tie_equal(ann.neighbors(q, 8), exact.neighbors(q, 8), atol=1e-3)
    st = ann.ann_stats()
    assert st["built"] and st["probed_cells"] >= 1


def test_mesh_remove_row_masks_ann(mesh, rng):
    b = NNBackend("lsh", dim=DIM, hash_num=64)
    b.configure_ann("ivf", cells=4, nprobe=4)
    for i in range(150):
        b.set_row(f"r{i}", _vec(rng))
    b.attach_mesh(mesh)
    q = _vec(rng)
    first = b.neighbors(q, 3)[0][0]
    b.remove_row(first)
    after = [r for r, _ in b.neighbors(q, 149)]
    assert first not in after


# -- persistence / reshard ---------------------------------------------------

def test_pack_unpack_preserves_centroids(rng):
    b = NNBackend("lsh", dim=DIM, hash_num=64)
    b.configure_ann("ivf", cells=4, nprobe=4)
    rows = {f"r{i}": _vec(rng) for i in range(140)}
    for rid, v in rows.items():
        b.set_row(rid, v)
    q = _vec(rng)
    want = b.neighbors(q, 8)                 # builds + answers
    cen = b._ann_centroids.copy()

    b2 = NNBackend("lsh", dim=DIM, hash_num=64)
    b2.configure_ann("ivf", cells=4, nprobe=4)
    b2.unpack(b.pack())
    assert b2._ann_centroids is not None
    np.testing.assert_array_equal(b2._ann_centroids, cen)
    got = b2.neighbors(q, 8)                 # re-partitions on flush
    tie_equal(got, want, atol=1e-3)
    assert b2.ann_stats()["cells"] == 4


def test_restore_onto_mesh_reshards_cells(mesh, rng):
    """Checkpoint written flat, restored onto an 8-shard mesh: rows
    re-partition through the STORED centroids over the new layout."""
    flat = NNBackend("lsh", dim=DIM, hash_num=64)
    flat.configure_ann("ivf", cells=4, nprobe=4)
    for i in range(160):
        flat.set_row(f"r{i}", _vec(rng))
    q = _vec(rng)
    want = flat.neighbors(q, 8)
    blob = flat.pack()

    sharded = NNBackend("lsh", dim=DIM, hash_num=64)
    sharded.configure_ann("ivf", cells=4, nprobe=4)
    sharded.attach_mesh(mesh)
    sharded.unpack(blob)
    got = sharded.neighbors(q, 8)
    tie_equal(got, want, atol=1e-3)
    assert sharded.ann_stats()["rows_indexed"] == 160


# -- migration ---------------------------------------------------------------

def test_migration_with_ann_loses_zero_rows(rng):
    """Row handoff between two ANN-armed backends (the drain/migrate
    wire path): every row survives, lands in a cell on the target, and
    stays queryable there."""
    from jubatus_tpu.models.nearest_neighbor import NearestNeighborDriver

    conf = {"method": "lsh",
            "converter": {"num_rules": [{"key": "*", "type": "num"}]},
            "parameter": {"hash_num": 64}}
    from jubatus_tpu.core.datum import Datum

    src = NearestNeighborDriver(conf)
    dst = NearestNeighborDriver(conf)
    src.backend.configure_ann("ivf", cells=4, nprobe=4)
    dst.backend.configure_ann("ivf", cells=4, nprobe=4)
    rng2 = np.random.default_rng(3)
    for i in range(130):
        src.set_row(f"r{i}", Datum(
            {f"f{j}": float(rng2.random()) for j in range(8)}))
    src.neighbor_row_from_id("r0", 5)        # build source index
    ids = src.row_ids()
    moved = dst.put_rows(src.get_rows(ids))
    assert moved == 130
    for rid in ids:
        rid = rid.decode() if isinstance(rid, bytes) else rid
        src.backend.remove_row(rid)
    assert len(dst.backend.store) == 130 and len(src.backend.store) == 0
    res = dst.neighbor_row_from_id("r7", 130)
    assert len(res) == 130                   # zero loss, all queryable
    assert dst.backend.ann_stats()["rows_indexed"] == 130
