"""Asynchronous staleness-bounded mix tests (ISSUE 11): fold-weight
math, diff inbox semantics, the streaming round on a live 3-member
cluster, the drift-parity gate vs the sync plane, the straggler chaos
drill (delayed member decays instead of stalling), snapshot
double-buffering under concurrent train/classify, and the master-side
staleness ledger's epoch rebase."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from jubatus_tpu.framework.async_mixer import (
    AsyncLinearMixer,
    DiffInbox,
    fold_weight,
    scale_tree,
)
from jubatus_tpu.utils import faults

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- pure units ---------------------------------------------------------------


def test_fold_weight_decay_and_drop():
    assert fold_weight(0, 4) == 1.0
    assert fold_weight(1, 4) == 0.5
    assert fold_weight(3, 4) == 0.125
    assert fold_weight(4, 4) == 2.0 ** -4  # at the bound: decayed, kept
    assert fold_weight(5, 4) == 0.0        # past the bound: dropped
    assert fold_weight(-2, 4) == 1.0       # future-stamped clamps fresh
    assert fold_weight(1, 0) == 0.0        # bound 0: only fresh folds


def test_scale_tree_preserves_dtypes():
    diff = {"w": np.ones((4,), np.float32) * 8.0,
            "counts": np.array([4, 8], np.int64),
            "s": 2.0}
    out = scale_tree(diff, 0.5)
    assert out["w"].dtype == np.float32
    np.testing.assert_allclose(out["w"], 4.0)
    # integer leaves stay integral (truncation IS the down-weighting)
    assert out["counts"].dtype == np.int64
    np.testing.assert_array_equal(out["counts"], [2, 4])
    assert out["s"] == 1.0
    # identity weight returns the tree untouched (no copy)
    assert scale_tree(diff, 1.0) is diff


def test_inbox_latest_wins_and_drain():
    inbox = DiffInbox()
    inbox.submit("a", {"version": 1, "diffs": {"x": 1}})
    inbox.submit("b", {"version": 2, "diffs": {"x": 2}})
    inbox.submit("a", {"version": 3, "diffs": {"x": 30}})  # supersedes
    assert inbox.depth() == 2
    assert inbox.submits == 3
    entries = inbox.drain()
    assert set(entries) == {"a", "b"}
    assert entries["a"]["version"] == 3
    assert entries["a"]["payload"]["diffs"]["x"] == 30
    # drain consumes: a silent member does not replay its last delta
    assert inbox.depth() == 0
    assert inbox.drain() == {}


def test_staleness_ledger_rebases_on_epoch_bump():
    """ISSUE 11 satellite fix: a drained-and-rejoined node must not
    inherit the staleness its past incarnation accrued while gone."""
    from jubatus_tpu.coord.base import NodeInfo
    from jubatus_tpu.framework.linear_mixer import RpcLinearMixer

    class FakeComm:
        epoch = 1

        def membership_epoch(self):
            return self.epoch

    class FakeDriver:
        lock = threading.Lock()

    comm = FakeComm()
    mixer = RpcLinearMixer(FakeDriver(), comm)
    a, b = NodeInfo("h", 1), NodeInfo("h", 2)
    assert mixer._staleness_update([a, b], {a.name, b.name})[
        "staleness_max"] == 0
    # b stops contributing for two rounds
    for _ in range(2):
        health = mixer._staleness_update([a, b], {a.name})
    assert health["staleness"][b.name] == 2
    # b drains away; the epoch bumps; rounds continue without it
    comm.epoch = 2
    for _ in range(3):
        health = mixer._staleness_update([a], {a.name})
    assert b.name not in health["staleness"]
    assert b.name not in mixer._member_last_contrib
    # b rejoins under the SAME name; epoch bumps again: it is seeded
    # fresh (staleness 1 = "not in this round yet"), not 5+ from its
    # past life
    comm.epoch = 3
    health = mixer._staleness_update([a, b], {a.name})
    assert health["staleness"][b.name] == 1
    # same epoch, still silent: staleness now grows normally
    health = mixer._staleness_update([a, b], {a.name})
    assert health["staleness"][b.name] == 2


def test_create_mixer_async_wiring():
    from jubatus_tpu.framework.push_mixer import create_mixer

    class FakeDriver:
        lock = threading.Lock()

    m = create_mixer("linear_mixer", FakeDriver(), None, mix_async=True,
                     mix_staleness_bound=3)
    assert isinstance(m, AsyncLinearMixer)
    assert m.staleness_bound == 3
    assert m._scheduler.fire_idle is True
    with pytest.raises(ValueError):
        create_mixer("random_mixer", FakeDriver(), None, mix_async=True)
    with pytest.raises(ValueError):
        create_mixer("collective_mixer", FakeDriver(), None,
                     mix_async=True)


def test_server_args_flags():
    from jubatus_tpu.server.args import parse_server_args

    args = parse_server_args(
        ["classifier", "-f", "/dev/null", "--mix-async",
         "--mix-staleness-bound", "6",
         "--fault", "mix.put_diff:error@1",
         "--fault", "migration.pull:delay:0.1"])
    assert args.mix_async is True
    assert args.mix_staleness_bound == 6
    assert args.fault == ["mix.put_diff:error@1",
                          "migration.pull:delay:0.1"]
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--mix-async", "-x", "random_mixer"])
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--mix-staleness-bound", "-1"])
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--fault", "nonsense-rule"])


# -- live cluster -------------------------------------------------------------


def _boot_cluster(tmp_path, sub, *, mix_async=True, bound=3, n=3,
                  interval=1e9):
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / sub)
    servers = []
    for _ in range(n):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator=coord_dir,
                            name="am", listen_addr="127.0.0.1",
                            interval_sec=interval,
                            interval_count=1 << 30,
                            telemetry_interval=0,
                            mix_async=mix_async,
                            mix_staleness_bound=bound))
        srv.start(0)
        servers.append(srv)
    return servers


def _train(srv, rows):
    from jubatus_tpu.client import ClassifierClient, Datum

    c = ClassifierClient("127.0.0.1", srv.args.rpc_port, "am")
    c.train([[label, Datum(d)] for label, d in rows])
    c.close()


def _classify(srv, d):
    from jubatus_tpu.client import ClassifierClient, Datum

    c = ClassifierClient("127.0.0.1", srv.args.rpc_port, "am")
    out = c.classify([Datum(d)])
    c.close()
    return out


@pytest.fixture()
def async_cluster(tmp_path):
    servers = _boot_cluster(tmp_path, "coord")
    yield servers
    faults.disarm_all()
    for s in servers:
        s.stop()


def test_async_round_streams_and_gauges(async_cluster):
    """One fold tick consumes whatever arrived — no gather barrier, no
    quorum abort — and the convergence/async gauges land on every
    member through the broadcast, like the sync plane's."""
    servers = async_cluster
    for i, s in enumerate(servers):
        _train(s, [(f"l{i % 2}", {"x": float(i + 1)})])
    # server 0 wins the master lock, publishes the hint, folds its own
    # diff (nobody else has submitted yet): round completes with ONE
    # contributor — the sync plane would have gathered and possibly
    # aborted instead
    r1 = servers[0].mixer.mix_now()
    assert r1 is not None and r1["mode"] == "async"
    assert r1["contributors"] == 1
    assert "quorum" not in str(r1)
    # members now push; the next fold consumes both submissions
    assert servers[1].mixer.submit_now() is True
    assert servers[2].mixer.submit_now() is True
    assert servers[0].mixer.inbox.depth() == 2
    r2 = servers[0].mixer.mix_now()
    assert r2["contributors"] == 2
    assert all(w == 1.0 for w in r2["weights"].values())  # all fresh
    assert r2["base_version"] == 1
    assert r2["acked"] == 3
    for s in servers:
        g = s.rpc.trace.gauges()
        assert g["mix.model_version"] == 2.0
        assert g["mix.apply_stall_ms"] >= 0
        assert s.mixer.model_version == 2
    g0 = servers[0].rpc.trace.gauges()
    assert g0["mix.async_fold_weight_min"] == 1.0
    assert g0["mix.async_inbox_depth"] == 0.0
    assert servers[0].rpc.trace.counters()["mix.async_rounds"] == 2
    # flight records carry the async mode + weights
    recs = [r for r in servers[0].mixer.flight.snapshot()
            if r["mode"] == "async"]
    assert len(recs) == 2 and recs[-1]["contributors"] == 2
    # the member-side lag gauge came from the submit ack
    st = next(iter(servers[1].get_status().values()))
    assert st["mixer.async_mode"] is True
    assert st["mixer.async_lag_rounds"] == 0
    assert st["mixer.staleness_bound"] == 3


def test_async_status_rpc_and_idempotency():
    from jubatus_tpu.framework.idl import (EFFECTFUL_BUILTINS,
                                           IDEMPOTENT_BUILTINS)

    assert "mix_async_status" in IDEMPOTENT_BUILTINS
    assert "mix_submit_diff" in EFFECTFUL_BUILTINS


def test_async_status_over_the_wire(async_cluster):
    from jubatus_tpu.rpc.client import RpcClient

    servers = async_cluster
    _train(servers[0], [("l0", {"x": 1.0})])
    servers[0].mixer.mix_now()
    with RpcClient("127.0.0.1", servers[0].args.rpc_port, 5.0) as c:
        doc = c.call("mix_async_status", "am")
    doc = {(k.decode() if isinstance(k, bytes) else k): v
           for k, v in doc.items()}
    assert doc["rounds"] == 1
    assert doc["staleness_bound"] == 3
    assert doc["model_version"] == 1


def test_stale_submission_decays_then_drops(async_cluster):
    """The bounded-staleness governor itself: a payload snapshot k
    folds ago folds at weight 2**-k and is dropped past the bound."""
    from jubatus_tpu.framework.linear_mixer import pack_mix
    from jubatus_tpu.rpc.client import RpcClient

    servers = async_cluster
    straggler = servers[2]
    # both members know both labels up front so every snapshot carries
    # the same schema (schema churn is its own test below)
    _train(straggler, [("l1", {"x": -3.0}), ("l0", {"x": 0.25})])
    _train(servers[0], [("l0", {"x": 0.5}), ("l1", {"x": -0.5})])
    # snapshot the straggler's diff NOW (version 0) but hold it back,
    # like a 10x-delayed submit would
    held = straggler.mixer.local_diff_obj()
    # two rounds stream past it
    for k in range(2):
        _train(servers[0], [("l0", {"x": float(k + 1)})])
        assert servers[0].mixer.mix_now() is not None
    assert servers[0].mixer.model_version == 2
    # the held payload finally arrives: staleness 2 -> weight 0.25
    with RpcClient("127.0.0.1", servers[0].args.rpc_port, 5.0) as c:
        c.call("mix_submit_diff", "am",
               straggler.self_nodeinfo().name, pack_mix(held))
        _train(servers[0], [("l0", {"x": 9.0})])
        r = servers[0].mixer.mix_now()
        assert r["weights"][straggler.self_nodeinfo().name] == 0.25
        assert not r["dropped_stale"]
        # one more round streams past (base 4), then the same stale
        # payload arrives again: staleness 4 > bound 3 — dropped, and
        # the round continues without it
        _train(servers[0], [("l0", {"x": 4.0})])
        assert servers[0].mixer.mix_now() is not None
        c.call("mix_submit_diff", "am",
               straggler.self_nodeinfo().name, pack_mix(held))
        _train(servers[0], [("l0", {"x": 2.0})])
        r = servers[0].mixer.mix_now()
    assert r is not None
    assert r["dropped_stale"] == 1
    assert straggler.self_nodeinfo().name not in r["weights"]
    assert servers[0].rpc.trace.counters()["mix.async_dropped_stale"] == 1


def test_straggler_chaos_decays_not_stalls(tmp_path):
    """ISSUE 11 satellite: one member's submissions delayed ~10x the
    fold cadence under load — rounds keep completing at cadence, the
    straggler's contribution decays/drops instead of aborting, and the
    serving path stays responsive throughout."""
    servers = _boot_cluster(tmp_path, "chaos", bound=2)
    try:
        straggler = servers[2]
        name = straggler.self_nodeinfo().name
        # aligned label vocabulary everywhere + the master hint
        for s in servers:
            _train(s, [("l0", {"x": 1.0}), ("l1", {"x": -1.0})])
        assert servers[0].mixer.mix_now() is not None
        # the straggler's submit path sleeps ~10 fold intervals
        faults.arm(f"mix.async.submit.{name}:delay:1.0")
        _train(straggler, [("l1", {"x": -5.0})])
        sub = threading.Thread(target=straggler.mixer.submit_now,
                               daemon=True)
        sub.start()
        # rounds stream at ~0.1s cadence while the straggler sleeps;
        # serving keeps answering between folds
        rounds = 0
        serving_ok = 0
        for k in range(6):
            _train(servers[0], [("l0", {"x": float(k)})])
            _train(servers[1], [("l0", {"x": float(k) + 0.5})])
            servers[1].mixer.submit_now()
            if servers[0].mixer.mix_now() is not None:
                rounds += 1
            out = _classify(servers[0], {"x": 1.0})
            serving_ok += bool(out)
            time.sleep(0.1)
        sub.join(timeout=10.0)
        assert not sub.is_alive()
        assert rounds >= 5  # the fleet never waited for the straggler
        assert serving_ok == 6
        # no sync-plane quorum machinery fired
        reasons = [r.get("reason", "") for r in
                   servers[0].mixer.flight.snapshot()]
        assert not any("quorum" in r for r in reasons)
        assert servers[0].rpc.trace.counters().get(
            "mix.quorum_aborted", 0) == 0
        # the straggler's held-back payload arrived rounds late: it was
        # decayed (weight < 1) or dropped past the bound — never a stall
        _train(servers[0], [("l0", {"x": 7.0})])
        r = servers[0].mixer.mix_now()
        assert r is not None
        w = r["weights"].get(name)
        dropped_total = servers[0].rpc.trace.counters().get(
            "mix.async_dropped_stale", 0)
        assert (w is not None and w < 1.0) or dropped_total >= 1
        # the flight records show every round completed without it
        # stalling the fold phase: fold times stay ~ms
        for rec in servers[0].mixer.flight.snapshot():
            if rec["mode"] == "async" and rec.get("phases"):
                assert rec["phases"]["fold_ms"] < 1000
    finally:
        faults.disarm_all()
        for s in servers:
            s.stop()


def test_drift_parity_async_vs_sync(tmp_path):
    """The drift-parity gate (ISSUE 11 acceptance): N rounds of async
    mix with fresh contributors produce the same folded model and the
    same convergence telemetry as the sync plane on identical traffic —
    the async plane learns as well as the one it replaces."""
    sync = _boot_cluster(tmp_path, "sync", mix_async=False)
    async_ = _boot_cluster(tmp_path, "async", mix_async=True)
    try:
        rows = [
            [("l0", {"x": 1.0, "y": -0.5}), ("l1", {"x": -1.0, "y": 2.0})],
            [("l0", {"x": 0.5, "y": -2.0}), ("l1", {"x": -0.25, "y": 1.0})],
            [("l1", {"x": -2.0, "y": 0.75}), ("l0", {"x": 2.0, "y": -1.0})],
        ]
        # prime the async plane: the first fold tick elects the master
        # and publishes the hint members submit to (zero-diff round)
        assert async_[0].mixer.mix_now() is not None
        div_sync, div_async = [], []
        for rnd in range(3):
            for i in range(3):
                _train(sync[i], rows[i])
                _train(async_[i], rows[i])
            rs = sync[0].mixer.mix_now()
            assert rs is not None
            div_sync.append(rs["health"]["premix_divergence_mean"])
            # async: everyone submits fresh, then the master folds
            for s in async_[1:]:
                assert s.mixer.submit_now() is True
            ra = async_[0].mixer.mix_now()
            assert ra is not None and ra["contributors"] == 3
            div_async.append(ra["health"]["premix_divergence_mean"])
            # rotate the traffic so later rounds genuinely diverge
            rows = rows[1:] + rows[:1]
        # identical contributions, all-fresh weights: the telemetry
        # agrees to float tolerance round by round
        np.testing.assert_allclose(div_async, div_sync, rtol=1e-5)
        # and the folded MODELS agree: same scores on a probe
        probe = {"x": 0.8, "y": -0.3}
        out_s = _classify(sync[0], probe)
        out_a = _classify(async_[0], probe)
        ss = {e[0]: e[1] for e in out_s[0]}
        sa = {e[0]: e[1] for e in out_a[0]}
        assert set(ss) == set(sa)
        for label in ss:
            assert sa[label] == pytest.approx(ss[label], rel=1e-5)
        # the async run never held the model lock for long: the whole
        # measured train-path stall is ~ms per round
        for s in async_:
            g = s.rpc.trace.gauges()
            assert g["mix.apply_stall_ms"] < 500
    finally:
        for s in sync + async_:
            s.stop()


def test_double_buffer_concurrent_train_classify(async_cluster):
    """ISSUE 11 satellite: concurrent train/classify during in-flight
    background rounds see a consistent (model, version) pair — the
    version gauge is monotone and no reader ever errors on a torn
    model."""
    servers = async_cluster
    stop = threading.Event()
    errors: list = []
    versions: list = []

    def hammer_train(idx):
        k = 0
        while not stop.is_set():
            try:
                _train(servers[idx], [(f"l{k % 2}", {"x": float(k % 7)})])
            except Exception as e:  # noqa: BLE001 — the assertion
                errors.append(e)
                return
            k += 1

    def hammer_read(idx):
        while not stop.is_set():
            try:
                out = _classify(servers[idx], {"x": 1.0})
                # version read under the SAME lock discipline the apply
                # bumps it under: the pair can never be torn
                with servers[idx].driver.lock:
                    versions.append(servers[idx].mixer.model_version)
                assert out is not None
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer_train, args=(i,))
               for i in range(3)]
    threads += [threading.Thread(target=hammer_read, args=(0,))]
    for t in threads:
        t.start()
    # background rounds stream while the hammers run
    deadline = time.monotonic() + 1.5
    rounds = 0
    while time.monotonic() < deadline:
        for s in servers[1:]:
            s.mixer.submit_now()
        if servers[0].mixer.mix_now() is not None:
            rounds += 1
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not errors, errors
    assert rounds >= 3
    # the version gauge never moved backwards on the reader
    assert versions == sorted(versions)
    assert versions[-1] >= rounds - 1
    g = servers[0].rpc.trace.gauges()
    assert g["mix.model_version"] == float(servers[0].mixer.model_version)


def test_submit_faults_drop_and_inbox(async_cluster):
    servers = async_cluster
    _train(servers[0], [("l0", {"x": 1.0})])
    assert servers[0].mixer.mix_now() is not None  # master + hint
    _train(servers[1], [("l1", {"x": 2.0})])
    me = servers[1].self_nodeinfo().name
    # drop at the SENDER: the submit never leaves the member
    with faults.armed(f"mix.async.submit.{me}:drop"):
        assert servers[1].mixer.submit_now() is False
    assert servers[0].mixer.inbox.depth() == 0
    # drop at the RECEIVER's inbox: the submit is refused, told so
    with faults.armed("mix.async.inbox.*:drop"):
        assert servers[1].mixer.submit_now() is False
    assert servers[0].mixer.inbox.depth() == 0
    # clean path lands it
    assert servers[1].mixer.submit_now() is True
    assert servers[0].mixer.inbox.depth() == 1


def test_schema_churn_prefix_folds_nonprefix_defers(async_cluster):
    """Row-alignment gate: a payload whose sorted vocabulary is a
    PREFIX of the union folds as-is (trailing rows pad with zeros); a
    non-prefix payload (a novel EARLY-sorting label appeared
    elsewhere) cannot be realigned after the fact — it defers one
    tick while the union broadcast realigns its owner."""
    servers = async_cluster
    _train(servers[0], [("l0", {"x": 1.0})])
    assert servers[0].mixer.mix_now() is not None  # master + hint
    # member 1 trains a novel label sorting BEFORE l0: member 2's
    # ["l0"] payload is no longer a prefix of the union ["a0","l0"]
    _train(servers[1], [("a0", {"x": -2.0})])
    _train(servers[2], [("l0", {"x": 3.0})])
    assert servers[1].mixer.submit_now() is True
    assert servers[2].mixer.submit_now() is True
    r = servers[0].mixer.mix_now()
    assert r is not None
    deferred = r.get("deferred_schema") or 0
    assert deferred >= 1
    assert servers[0].rpc.trace.counters()[
        "mix.async_schema_deferred"] >= 1
    # after the union broadcast every member's vocabulary agrees;
    # fresh snapshots fold cleanly
    _train(servers[1], [("a0", {"x": -1.0})])
    _train(servers[2], [("l0", {"x": 2.0})])
    assert servers[1].mixer.submit_now() is True
    assert servers[2].mixer.submit_now() is True
    r = servers[0].mixer.mix_now()
    assert r is not None and not r.get("deferred_schema")
    assert r["contributors"] == 2


def test_nonconcontributor_apply_captures_pending_updates(async_cluster):
    """Loss-window closure: a fold's broadcast resets EVERY member's
    accumulation (reference put_diff semantics), including members
    whose diffs weren't in the fold — the bootstrap case: training
    done before the first master election must survive the first
    broadcast and reach the cluster via the capture."""
    servers = async_cluster
    # members 0 and 1 train DISJOINT labels before any round exists
    _train(servers[0], [("l0", {"x": 2.0}), ("l1", {"x": -0.1})])
    _train(servers[1], [("l1", {"x": -2.0}), ("l0", {"x": 0.1})])
    # first fold: only the master's own diff is in it; the broadcast
    # apply would have silently destroyed member 1's training
    r1 = servers[0].mixer.mix_now()
    assert r1 is not None and r1["contributors"] == 1
    assert servers[1].rpc.trace.counters().get("mix.async_captures") == 1
    # member 1's next submit carries the captured accumulation
    assert servers[1].mixer.submit_now() is True
    r2 = servers[0].mixer.mix_now()
    assert r2["contributors"] == 1
    # replica 2 never trained: it must now know BOTH members' lessons
    out = _classify(servers[2], {"x": 2.0})
    scores = {(e[0].decode() if isinstance(e[0], bytes) else e[0]): e[1]
              for e in out[0]}
    assert scores["l0"] > scores["l1"]
    out = _classify(servers[2], {"x": -2.0})
    scores = {(e[0].decode() if isinstance(e[0], bytes) else e[0]): e[1]
              for e in out[0]}
    assert scores["l1"] > scores["l0"]
    # contributors never capture: their accumulator content was folded
    assert not servers[0].rpc.trace.counters().get("mix.async_captures")


def test_merge_delta_tree_keeps_normalization_scalars():
    from jubatus_tpu.framework.async_mixer import _merge_delta_tree

    a = {"dw": np.ones((2, 4), np.float32), "count": np.float32(1.0)}
    b = {"dw": np.full((3, 4), 2.0, np.float32), "count": np.float32(1.0)}
    out = _merge_delta_tree(a, b)
    # arrays add with the trailing-row pad; the equal replica-count
    # scalar stays 1 (one member's two deltas = ONE replica)
    assert out["dw"].shape == (3, 4)
    np.testing.assert_allclose(out["dw"][:2], 3.0)
    np.testing.assert_allclose(out["dw"][2], 2.0)
    assert float(out["count"]) == 1.0
    # genuinely different scalars still add
    out = _merge_delta_tree({"n": 2.0}, {"n": 3.0})
    assert float(out["n"]) == 5.0


def test_watch_row_shows_async_lag():
    from jubatus_tpu.cmd.jubactl import _watch_node_row

    row = _watch_node_row("n1", {"status": {
        "health.status": "ok", "mixer.async_mode": True,
        "mixer.async_lag_rounds": 2, "mixer.async_inbox_depth": 3,
        "mixer.model_version": 7}, "error": ""}, active=True)
    assert "lag 2" in row
    assert "inbox 3" in row
    assert "v7" in row
