"""Autoscaling control plane + fleet simulation (ISSUE 12).

Covers the new subsystem at three altitudes:

- **Controller core** (pure, clock-injected): hysteresis confirm
  streaks on synthetic burn/queue timelines, flap suppression,
  cooldown, min/max bounds, floor restore bypassing both, least-loaded
  scale-in victim selection.
- **Control loop**: journal + counters, the ``autoscale.spawn`` /
  ``autoscale.drain`` fault sites — a failing actuation must record
  ``blocked`` and back off exponentially, never hot-loop — dry-run
  mode, signal folding from timeseries points, the
  ``get_autoscale_status`` RPC + registry, the jubactl frame renderer.
- **Cluster**: a live fleet losing a replica has its floor restored by
  the loop without operator input (the ISSUE 12 slow drill's in-proc
  twin).
- **Traffic model** (tools/fleet_sim.py): seeded replayability, distinct
  per-client streams, nproc-invariant offered load, flash-crowd rate
  engagement, zipf hot-key skew, tenant mix, and the violation/recovery
  clock helpers the fleet bench computes its keys with.
"""

from __future__ import annotations

import os
import sys
import time
from argparse import Namespace

import pytest

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.autoscaler import (AutoscaleConfig, Autoscaler,
                                          AutoscalerCore, FleetSnapshot,
                                          HookActuator, ReplicaStats,
                                          _stats_from_points, poll_fleet)
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.utils import faults

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import fleet_sim  # noqa: E402


def snap(n, burn=0.0, queue=0.0, t=0.0, firing=None, queues=None,
         rates=None):
    reps = []
    for i in range(n):
        reps.append(ReplicaStats(
            f"127.0.0.1_{9300 + i}",
            burn_max=burn,
            firing=(burn >= 2.0) if firing is None else firing,
            queue_depth=(queues[i] if queues else queue),
            req_per_sec=(rates[i] if rates else 0.0)))
    return FleetSnapshot(ts=t, replicas=reps)


def cfg(**kw):
    base = dict(min_replicas=1, max_replicas=4, poll_interval_s=1.0,
                scale_out_confirm=2, scale_in_confirm=3, cooldown_s=10.0,
                queue_hot=1000.0, burn_hot=2.0)
    base.update(kw)
    return AutoscaleConfig(**base)


# -- controller core ----------------------------------------------------------

def test_config_validation_rejects_nonsense():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=0).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=4, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(scale_out_confirm=0).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(backoff_initial_s=5.0, backoff_max_s=1.0).validate()


def test_scale_out_only_on_sustained_burn():
    core = AutoscalerCore(cfg())
    # one hot poll is a blip, not a trend
    assert core.observe(snap(2, burn=5.0, t=1.0)).action == "hold"
    d = core.observe(snap(2, burn=5.0, t=2.0))
    assert (d.action, d.reason, d.count) == ("scale_out", "sustained_hot", 1)


def test_queue_depth_alone_counts_hot():
    core = AutoscalerCore(cfg())
    core.observe(snap(2, queue=2000.0, t=1.0))
    d = core.observe(snap(2, queue=2000.0, t=2.0))
    assert d.action == "scale_out"


def test_flap_suppression_alternating_signals_never_actuate():
    core = AutoscalerCore(cfg(scale_out_confirm=2, scale_in_confirm=2,
                              cooldown_s=0.0))
    for t in range(40):
        s = snap(2, burn=5.0 if t % 2 == 0 else 0.0, t=float(t))
        assert core.observe(s).action == "hold"


def test_cooldown_blocks_back_to_back_scaleouts():
    core = AutoscalerCore(cfg(cooldown_s=10.0))
    core.observe(snap(2, burn=5.0, t=1.0))
    assert core.observe(snap(2, burn=5.0, t=2.0)).action == "scale_out"
    # still hot 3 s later: confirm streak is satisfied again but the
    # cooldown window holds the fleet steady
    core.observe(snap(3, burn=5.0, t=4.0))
    d = core.observe(snap(3, burn=5.0, t=5.0))
    assert (d.action, d.reason) == ("hold", "cooldown")
    # past the cooldown the next poll fires — the hot streak kept
    # building through the cooldown, so no re-confirmation is needed
    assert core.observe(snap(3, burn=5.0, t=13.0)).action == "scale_out"


def test_max_and_min_bounds_are_honored():
    core = AutoscalerCore(cfg(max_replicas=3, cooldown_s=0.0))
    core.observe(snap(3, burn=9.0, t=1.0))
    d = core.observe(snap(3, burn=9.0, t=2.0))
    assert (d.action, d.reason) == ("hold", "hot_at_max")
    core = AutoscalerCore(cfg(min_replicas=2, scale_in_confirm=2,
                              cooldown_s=0.0))
    core.observe(snap(2, t=1.0))
    d = core.observe(snap(2, t=2.0))
    assert (d.action, d.reason) == ("hold", "cold_at_min")


def test_floor_restore_bypasses_confirm_and_cooldown():
    core = AutoscalerCore(cfg(min_replicas=2, cooldown_s=100.0))
    core.observe(snap(2, burn=5.0, t=1.0))
    assert core.observe(snap(2, burn=5.0, t=2.0)).action == "scale_out"
    # a replica dies 1 s into the cooldown: restore NOW, count exact
    d = core.observe(snap(1, t=3.0))
    assert (d.action, d.reason, d.count) == \
        ("scale_out", "below_min_floor", 1)
    # ...but a REPEAT restore while the spawn is still booting is
    # spaced by cooldown_s — re-spawning every poll is a spawn storm
    d = core.observe(FleetSnapshot(ts=4.0, replicas=[]), now=4.0)
    assert (d.action, d.reason) == ("hold", "floor_restore_pending")
    d = core.observe(FleetSnapshot(ts=104.0, replicas=[]), now=104.0)
    assert (d.action, d.count) == ("scale_out", 2)


def test_scale_in_after_sustained_cold_picks_least_loaded():
    core = AutoscalerCore(cfg(min_replicas=1, scale_in_confirm=3,
                              cooldown_s=0.0))
    s = snap(3, t=0.0, queues=[50.0, 5.0, 200.0],
             rates=[10.0, 1.0, 30.0])
    for t in range(2):
        assert core.observe(s, now=float(t)).action == "hold"
    d = core.observe(s, now=2.0)
    assert (d.action, d.target) == ("scale_in", "127.0.0.1_9301")


def test_draining_members_do_not_count_as_capacity():
    s = snap(3, burn=0.0)
    s.replicas[0].draining = True
    assert s.size == 2
    core = AutoscalerCore(cfg(min_replicas=3))
    d = core.observe(s, now=1.0)
    assert (d.action, d.reason) == ("scale_out", "below_min_floor")


def test_synthetic_burn_timeline_end_to_end():
    """The drill's shape as a pure timeline: quiet -> sustained burn ->
    scale to max -> burn clears -> sustained cold -> scale back in."""
    core = AutoscalerCore(cfg(min_replicas=1, max_replicas=3,
                              scale_out_confirm=2, scale_in_confirm=4,
                              cooldown_s=2.0))
    n, t, actions = 1, 0.0, []
    timeline = [0.0] * 3 + [8.0] * 12 + [0.0] * 14
    for burn in timeline:
        t += 1.0
        d = core.observe(snap(n, burn=burn, t=t))
        actions.append(d.action)
        if d.action == "scale_out":
            n += d.count
        elif d.action == "scale_in":
            n -= 1
    assert n == 1
    assert actions.count("scale_out") == 2      # 1 -> 3 under burn
    assert actions.count("scale_in") == 2       # 3 -> 1 once quiescent
    first_out = actions.index("scale_out")
    assert first_out >= 4  # 3 quiet polls + confirm streak


# -- control loop: journal, counters, fault sites, backoff --------------------

def hook(spawned, drained):
    return HookActuator(lambda n: spawned.append(n),
                        lambda t: drained.append(t))


def mk_scaler(actuator, **kw):
    base = dict(min_replicas=1, max_replicas=4, poll_interval_s=0.05,
                scale_out_confirm=1, scale_in_confirm=2, cooldown_s=0.0,
                backoff_initial_s=0.25, backoff_max_s=2.0)
    base.update(kw)
    return Autoscaler(MemoryCoordinator(_Store()), "classifier", "c1",
                      actuator, config=AutoscaleConfig(**base))


def test_tick_journals_decisions_and_counts():
    spawned, drained = [], []
    sc = mk_scaler(hook(spawned, drained))
    sc.tick(snap(1, t=100.0))                    # steady -> hold
    sc.tick(snap(1, burn=9.0, t=101.0))          # hot x1 (confirm=1)
    assert spawned == [1]
    for t in range(2):
        sc.tick(snap(2, t=102.0 + t))            # cold streak
    assert drained and drained[0].startswith("127.0.0.1_")
    c = sc.registry.counters()
    assert c["autoscale.decisions"] == 4
    assert c["autoscale.spawns"] == 1
    assert c["autoscale.drains"] == 1
    acts = [j["action"] for j in sc.journal]
    assert acts == ["hold", "scale_out", "hold", "scale_in"]
    assert all("signals" in j for j in sc.journal)
    g = sc.registry.gauges()
    assert "autoscale.replicas" in g and "autoscale.burn_max" in g


def test_blocked_spawn_backs_off_and_never_hot_loops():
    spawned, drained = [], []
    calls = []

    def failing_spawn(n):
        calls.append(n)
        raise RuntimeError("spawn path down")

    sc = mk_scaler(HookActuator(failing_spawn, drained.append))
    with faults.armed():  # no-op scope; the hook itself fails
        t = 200.0
        for i in range(60):
            sc.tick(snap(1, burn=9.0, t=t))
            t += 0.01  # 60 polls in 0.6 s of model time
    # exponential backoff: 0.25 + 0.5 = 0.75 s of backoff inside 0.6 s
    # of polls -> at most 2 attempts ever reach the actuator
    assert len(calls) <= 2
    recs = list(sc.journal)
    blocked = [j for j in recs if j["action"] == "blocked"]
    assert blocked and blocked[0]["error"]
    assert blocked[0]["backoff_s"] == 0.25
    assert sc.registry.counters()["autoscale.blocked"] == len(calls)
    assert any(j["reason"] == "backoff" for j in recs)
    # the actuator recovers: next eligible tick (past backoff) spawns
    sc.actuator = hook(spawned, drained)
    sc.tick(snap(1, burn=9.0, t=t + 10.0))
    assert spawned == [1]
    assert sc.backoff_until == 0.0


def test_autoscale_spawn_fault_site_blocks_with_backoff():
    spawned, drained = [], []
    sc = mk_scaler(hook(spawned, drained))
    with faults.armed("autoscale.spawn:error"):
        rec = sc.tick(snap(1, burn=9.0, t=300.0))
    assert rec["action"] == "blocked"
    assert "FaultInjected" in rec["error"]
    assert spawned == []                      # site fires BEFORE actuation
    assert sc.backoff_until > 300.0
    # after the armed window + backoff expiry, actuation proceeds
    rec = sc.tick(snap(1, burn=9.0, t=310.0))
    assert rec["action"] == "scale_out" and spawned == [1]


def test_autoscale_drain_fault_site_blocks():
    spawned, drained = [], []
    sc = mk_scaler(hook(spawned, drained), min_replicas=1,
                   scale_in_confirm=1)
    with faults.armed("autoscale.drain:error"):
        rec = sc.tick(snap(2, t=400.0))
    assert rec["action"] == "blocked" and drained == []
    assert sc.registry.counters()["autoscale.blocked"] == 1


def test_dry_run_journals_intent_without_actuating():
    spawned, drained = [], []
    sc = mk_scaler(hook(spawned, drained), dry_run=True)
    rec = sc.tick(snap(1, burn=9.0, t=500.0))
    assert rec["action"] == "scale_out" and rec["dry_run"] is True
    assert spawned == []
    c = sc.registry.counters()
    assert c["autoscale.decisions"] == 1
    assert c.get("autoscale.spawns", 0) == 0


# -- signal folding -----------------------------------------------------------

def test_stats_from_points_reads_gauges_and_slo_burn():
    points = [
        {"ts": 100.0, "hists": {}, "counters": {}, "gauges": {}},
        {"ts": 110.0, "hists": {}, "counters": {},
         "gauges": {"microbatch.queue_depth": 1500.0,
                    "microbatch.arrival_per_sec": 800.0,
                    "slo.rpc.train.p99.burn_fast": 4.2,
                    "slo.rpc.train.p99.firing": 1.0,
                    "slo.other.burn_fast": 0.1}},
    ]
    r = _stats_from_points("127.0.0.1_9300", points, 60.0)
    assert r.queue_depth == 1500.0
    assert r.arrival_per_sec == 800.0
    assert r.burn_max == 4.2
    assert r.firing is True


def test_poll_fleet_counts_unreachable_members():
    store = _Store()
    coord = MemoryCoordinator(store)
    # a registered active that answers no RPC (nothing listening)
    membership.register_active(coord, "classifier", "c1",
                               "127.0.0.1", 1)
    s = poll_fleet(coord, "classifier", "c1", timeout=0.5)
    assert s.size == 1 and not s.replicas[0].reachable
    assert s.errors


# -- status / RPC / rendering -------------------------------------------------

def test_serve_status_rpc_and_registry():
    from jubatus_tpu.rpc.client import RpcClient

    store = _Store()
    spawned, drained = [], []
    sc = Autoscaler(MemoryCoordinator(store), "classifier", "c1",
                    hook(spawned, drained),
                    config=AutoscaleConfig(scale_out_confirm=1,
                                           cooldown_s=0.0))
    try:
        port = sc.serve(0)
        assert [n.name for n in membership.get_autoscalers(
            MemoryCoordinator(store))] == [f"127.0.0.1_{port}"]
        sc.tick(snap(1, burn=9.0, t=600.0))
        with RpcClient("127.0.0.1", port, timeout=10.0) as c:
            per_node = c.call("get_autoscale_status", "c1", 8)
        doc = next(iter(per_node.values()))
        assert doc["counters"]["autoscale.spawns"] == 1
        assert doc["journal"][-1]["action"] == "scale_out"
        assert doc["config"]["max_replicas"] == 8
        assert doc["fleet"]["replicas"] == 1
    finally:
        sc.stop()


def test_get_autoscale_status_is_idempotent_builtin():
    from jubatus_tpu.framework.idl import IDEMPOTENT_BUILTINS

    assert "get_autoscale_status" in IDEMPOTENT_BUILTINS


def test_render_autoscale_frame():
    from jubatus_tpu.cmd.jubactl import render_autoscale_frame

    spawned, drained = [], []
    sc = mk_scaler(hook(spawned, drained))
    sc.tick(snap(2, burn=9.0, t=700.0, queues=[10.0, 20.0]))
    frame = render_autoscale_frame(sc.status())
    assert "classifier/c1 autoscaler" in frame
    assert "fleet 2 replica(s)" in frame
    assert "scale_out" in frame
    assert "127.0.0.1_9300" in frame
    assert "spawns 1" in frame


def test_jubactl_autoscale_once_dry_runs(capsys, monkeypatch):
    """--once with no registered autoscaler: one observe-only tick
    rendered — and nothing actuated (dry_run is forced)."""
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.coord import autoscaler as as_mod

    coord = MemoryCoordinator(_Store())
    membership.register_active(coord, "classifier", "c1",
                               "127.0.0.1", 1)
    monkeypatch.setattr(
        as_mod, "poll_fleet",
        lambda *a, **k: snap(1, burn=9.0, t=time.time()))
    ns = Namespace(watch=False, once=True, interval=2.0, window=30.0,
                   as_min=1, as_max=4, autoscale_interval=0.5,
                   cooldown=0.0, scale_out_confirm=1,
                   scale_in_confirm=2, burn_hot=2.0, queue_hot=1000.0,
                   autoscale_port=0, dry_run=False, thread=2,
                   timeout=10, datadir="/tmp", logdir="", mixer="linear",
                   interval_sec=16, interval_count=512)
    rc = jubactl.run_autoscale(coord, "classifier", "c1", ns)
    assert rc == 0
    out = capsys.readouterr().out
    assert "autoscaler" in out and "scale_out" in out
    assert "[dry-run]" in out


# -- cluster: the floor-restore drill -----------------------------------------

ENGINE = "nearest_neighbor"
NN_CONF = {"method": "lsh", "parameter": {"hash_num": 8},
           "converter": {"num_rules": [{"key": "*", "type": "num"}]}}


def _boot_nn(store):
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        ENGINE, NN_CONF,
        args=ServerArgs(engine=ENGINE, coordinator="(shared)", name="as",
                        listen_addr="127.0.0.1", interval_sec=1e9,
                        interval_count=1 << 30, telemetry_interval=0.5),
        coord=MemoryCoordinator(store))
    srv.start(0)
    return srv


def test_cluster_replica_death_restores_floor():
    """Kill a replica of a live fleet: the loop's next poll sees the
    fleet below min_replicas and spawns a replacement without operator
    input — ISSUE 12's unattended-recovery contract in-process."""
    store = _Store()
    servers = [_boot_nn(store), _boot_nn(store)]

    def spawn(n):
        for _ in range(int(n)):
            servers.append(_boot_nn(store))

    sc = Autoscaler(
        MemoryCoordinator(store), ENGINE, "as",
        HookActuator(spawn, lambda t: None),
        config=AutoscaleConfig(min_replicas=2, max_replicas=3,
                               poll_interval_s=0.2, window_s=10.0,
                               scale_in_confirm=10_000,
                               cooldown_s=5.0))
    try:
        coord = MemoryCoordinator(store)
        assert len(membership.get_all_actives(coord, ENGINE, "as")) == 2
        sc.start()
        servers[0].stop()  # hard kill: ephemeral registrations vanish
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if len(membership.get_all_actives(coord, ENGINE, "as")) >= 2 \
                    and len(servers) == 3:
                break
            time.sleep(0.1)
        assert len(servers) == 3, "autoscaler did not spawn a replacement"
        assert len(membership.get_all_actives(coord, ENGINE, "as")) >= 2
        restore = [j for j in sc.journal
                   if j["action"] == "scale_out"
                   and j["reason"] == "below_min_floor"]
        assert restore, "floor restore not journaled"
    finally:
        sc.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass


# -- traffic model (tools/fleet_sim.py) ---------------------------------------

def _model(**kw):
    base = dict(seed=7, base_rate=100.0, diurnal_period_s=60.0,
                diurnal_amplitude=0.25, zipf_s=1.2, n_users=10_000,
                flash=((8.0, 6.0, 5.0),))
    base.update(kw)
    return fleet_sim.TrafficModel(**base)


def test_trace_replayable_and_streams_distinct():
    m = _model()
    a = fleet_sim.summarize_trace(m, 0, 4, 20.0)
    assert a == fleet_sim.summarize_trace(m, 0, 4, 20.0)
    assert a != fleet_sim.summarize_trace(m, 1, 4, 20.0)
    assert a != fleet_sim.summarize_trace(_model(seed=8), 0, 4, 20.0)
    assert a["events"] > 100


def test_offered_load_invariant_across_nproc():
    m = _model(flash=())
    totals = {}
    for nproc in (4, 8):
        totals[nproc] = sum(
            fleet_sim.summarize_trace(m, i, nproc, 30.0)["events"]
            for i in range(nproc))
    assert abs(totals[4] - totals[8]) / totals[4] < 0.15


def test_flash_crowd_engages_rate_curve():
    m = _model()
    per_sec = fleet_sim.summarize_trace(m, 0, 2, 20.0)["per_sec"]
    base = sum(per_sec[2:8]) / 6.0
    flash = sum(per_sec[9:13]) / 4.0
    assert 3.0 < flash / base < 7.5  # nominal 5x


def test_zipf_skew_and_tenant_mix():
    m = _model(flash=(), zipf_s=1.3)
    doc = fleet_sim.summarize_trace(m, 0, 2, 60.0)
    # hot head: top-10 users of 10k carry far more than uniform would
    assert doc["top10_user_share"] > 0.2
    mix = doc["tenants"]
    total = sum(mix.values())
    assert abs(mix.get("checkout", 0) / total - 0.5) < 0.1
    assert abs(mix.get("ads", 0) / total - 0.2) < 0.1


def test_rate_at_composes_diurnal_and_flash():
    m = _model(base_rate=100.0, diurnal_amplitude=0.0)
    assert m.rate_at(1.0) == pytest.approx(100.0)
    assert m.rate_at(9.0) == pytest.approx(500.0)
    assert m.rate_at(15.0) == pytest.approx(100.0)
    assert m.max_rate() == pytest.approx(500.0)
    m2 = _model(diurnal_amplitude=0.5, flash=())
    assert m2.rate_at(15.0) == pytest.approx(150.0)  # sin peak at T/4


def test_model_json_round_trip():
    m = _model()
    m2 = fleet_sim.TrafficModel.from_json(m.to_json())
    assert m2 == m


def test_violation_and_recovery_helpers():
    per_sec = {
        "done": [100] * 20, "bad": [0] * 20, "shed": [0] * 20,
        "errors": [0] * 20,
    }
    for s in range(8, 14):
        per_sec["bad"][s] = 50            # 50% bad through the flash
    viol = fleet_sim.violation_seconds(per_sec)
    assert viol == list(range(8, 14))
    rec = fleet_sim.recovery_second(viol, onset=8, horizon=20)
    assert rec == 14.0
    # never recovers inside the horizon
    viol_all = list(range(8, 21))
    assert fleet_sim.recovery_second(viol_all, onset=8,
                                     horizon=18) is None
    # zero-traffic seconds don't count as violations
    per_sec["done"][3] = 0
    per_sec["bad"][3] = 0
    assert 3 not in fleet_sim.violation_seconds(per_sec)


def test_warm_spawn_flag_rides_scale_out_journal():
    """ISSUE 18: when the actuator spawns replicas with --store-dir
    (they warm-boot from the shared model store), the scale_out journal
    record says so — the operator can tell warm capacity from cold."""
    from jubatus_tpu.coord.autoscaler import VisorActuator

    # VisorActuator derives the flag from the spawn argv it will pass
    warm = VisorActuator(MemoryCoordinator(_Store()), "classifier", "c1",
                         server_argv={"store_dir": "/mnt/models"})
    cold = VisorActuator(MemoryCoordinator(_Store()), "classifier", "c1",
                         server_argv={})
    assert warm.warm_spawn and not cold.warm_spawn

    spawned, drained = [], []
    actuator = hook(spawned, drained)
    actuator.warm_spawn = True
    sc = mk_scaler(actuator)
    sc.tick(snap(1, t=400.0))                    # hold
    rec = sc.tick(snap(1, burn=9.0, t=401.0))    # scale_out
    assert rec["action"] == "scale_out" and spawned == [1]
    assert rec["warm_spawn"] is True
    # a cold actuator's record carries no warm_spawn claim
    sc2 = mk_scaler(hook(spawned, drained))
    sc2.tick(snap(1, t=410.0))
    rec2 = sc2.tick(snap(1, burn=9.0, t=411.0))
    assert rec2["action"] == "scale_out"
    assert "warm_spawn" not in rec2
