"""tools/bench_compare.py tests (ISSUE 8): the perf trajectory's
mechanical regression gate — direction inference, tolerance (global +
per-key), boolean gates, bench-shape flattening, latest-two glob
selection, and exit codes over canned fixtures."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools", "bench_compare.py"))
bc = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bc)


OLD = {
    "e2e_rpc_train_samples_per_sec_native": 100000.0,
    "e2e_rpc_classify_p99_ms_native": 10.0,
    "e2e_tracing_overhead_p50_ratio": 1.01,
    "e2e_profiling_overhead_ok": True,
    "collective_wire_mb_per_round": 480.0,
    "e2e_fv_overlap_fraction": 0.8,
    "bench_platform_note": "cpu",   # non-numeric: ignored by flatten
    "e2e_clients": 16,              # no direction: info only
}


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_direction_inference():
    assert bc.direction("e2e_rpc_train_samples_per_sec_native") == "higher"
    assert bc.direction("e2e_fv_overlap_fraction") == "higher"
    assert bc.direction("collective_round_int8_vs_bf16_speedup") == "higher"
    assert bc.direction("e2e_rpc_classify_p99_ms_native") == "lower"
    assert bc.direction("e2e_tracing_overhead_p50_ratio") == "lower"
    assert bc.direction("collective_wire_mb_per_round") == "lower"
    assert bc.direction("collective_round_drift_vs_f32") == "lower"
    assert bc.direction("e2e_profiling_overhead_ok") == "bool"
    assert bc.direction("mix_under_1s_target") == "bool"
    # async mix plane (ISSUE 11): serving-path stall and rounds-behind
    # are down-good; the drift-parity gate is boolean
    assert bc.direction("e2e_train_stall_during_mix_ms") == "lower"
    assert bc.direction("e2e_async_mix_lag_rounds") == "lower"
    assert bc.direction("e2e_async_mix_drift_parity_ok") == "bool"
    assert bc.direction("e2e_clients") is None


def test_direction_inference_autoscale_keys():
    """ISSUE 12 autoscaling plane: recovery wall time and seconds in
    SLO violation gate down-good (a slower control loop is a
    regression), capacity absorbed per serving replica up-good, the
    autoscaled-beats-static verdict is a boolean gate."""
    assert bc.direction("e2e_scaleout_recovery_s") == "lower"
    assert bc.direction("e2e_autoscale_slo_violation_s") == "lower"
    assert bc.direction("e2e_static_slo_violation_s") == "lower"
    assert bc.direction("e2e_capacity_per_replica") == "higher"
    assert bc.direction("e2e_autoscale_beats_static_ok") == "bool"
    # neighbors that must NOT accidentally gate
    assert bc.direction("e2e_autoscale_final_replicas") is None
    assert bc.direction("e2e_fleet_seed") is None


def test_direction_inference_usage_keys():
    """ISSUE 19 usage-attribution plane: the conservation gap gates
    down-good (growth = requests escaping attribution), capacity
    headroom up-good (shrinkage at the same load = costlier replica),
    the overhead verdicts ride the existing _ratio/_ok patterns."""
    assert bc.direction("e2e_usage_attribution_err_frac") == "lower"
    assert bc.direction("e2e_capacity_headroom") == "higher"
    assert bc.direction("e2e_usage_overhead_mean_ratio") == "lower"
    assert bc.direction("e2e_usage_overhead_p50_ratio") == "lower"
    assert bc.direction("e2e_usage_overhead_ok") == "bool"
    assert bc.direction("e2e_usage_attribution_ok") == "bool"
    assert bc.direction("e2e_usage_tenants_distinct_ok") == "bool"
    # neighbors that must NOT accidentally gate
    assert bc.direction("e2e_usage_tenants_seen") is None
    assert bc.direction("e2e_usage_driven_done") is None


def test_usage_keys_gate_over_fixtures():
    """The err_frac/headroom directions drive real verdicts: a grown
    conservation gap and a shrunken headroom each REGRESS; the gap
    shrinking and headroom growing each count as improvements."""
    old = {"e2e_usage_attribution_err_frac": 0.02,
           "e2e_capacity_headroom": 0.9,
           "e2e_usage_overhead_ok": True}
    new = {"e2e_usage_attribution_err_frac": 0.09,
           "e2e_capacity_headroom": 0.4,
           "e2e_usage_overhead_ok": False}
    rows, regs = bc.compare(old, new, tolerance=0.05)
    assert {r["key"] for r in regs} == \
        {"e2e_usage_attribution_err_frac", "e2e_capacity_headroom",
         "e2e_usage_overhead_ok"}
    better = {"e2e_usage_attribution_err_frac": 0.01,
              "e2e_capacity_headroom": 0.95,
              "e2e_usage_overhead_ok": True}
    rows, regs = bc.compare(old, better, tolerance=0.05)
    assert regs == []
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_usage_attribution_err_frac"] == "improved"
    assert verdicts["e2e_capacity_headroom"] == "improved"


def test_direction_inference_sharded_keys():
    """ISSUE 13 feature-sharding plane: train throughput at d26 gates
    up-good per shard count, classify/KNN query p99 down-good — single-
    AND multi-shard spellings, at both row-count scales."""
    assert bc.direction("sharded_train_samples_per_sec_d26_1shard") \
        == "higher"
    assert bc.direction("sharded_train_samples_per_sec_d26_8shard") \
        == "higher"
    assert bc.direction("sharded_classify_p99_ms_d26_1shard") == "lower"
    assert bc.direction("sharded_classify_p99_ms_d26_8shard") == "lower"
    assert bc.direction("knn_query_p99_ms_rows1e6_1shard") == "lower"
    assert bc.direction("knn_query_p99_ms_rows1e6_8shard") == "lower"
    assert bc.direction("knn_query_p99_ms_rows1e8_1shard") == "lower"
    assert bc.direction("knn_query_p99_ms_rows1e8_8shard") == "lower"
    # neighbors that must NOT accidentally gate
    assert bc.direction("sharded_train_shards") is None
    assert bc.direction("knn_query_rows_rows1e6") is None


def test_direction_inference_quality_keys():
    """ISSUE 17 data-quality plane: PSI drift scores gate down-good,
    prequential/holdout accuracy and ANN recall gate up-good, the
    overhead and tracks-holdout verdicts are boolean gates."""
    assert bc.direction("e2e_drift_baseline_psi") == "lower"
    assert bc.direction("e2e_quality_overhead_mean_ratio") == "lower"
    assert bc.direction("e2e_prequential_accuracy") == "higher"
    assert bc.direction("e2e_holdout_accuracy") == "higher"
    assert bc.direction("e2e_ann_recall") == "higher"
    assert bc.direction("e2e_prequential_tracks_holdout_ok") == "bool"
    assert bc.direction("e2e_quality_overhead_ok") == "bool"
    # the drill verdicts carry "drift" (a bare _LOWER pattern) but the
    # _ok suffix must win: a fired drift alarm in the drill is GOOD
    assert bc.direction("e2e_drift_detected_ok") == "bool"
    assert bc.direction("e2e_drift_slo_fired_ok") == "bool"
    assert bc.direction("e2e_drift_incident_ok") == "bool"
    # neighbors that must NOT accidentally gate
    assert bc.direction("e2e_shift_peak_score") is None
    assert bc.direction("e2e_quality_sample") is None
    assert bc.direction("e2e_recalled_total") is None  # no _recall edge


def test_quality_keys_gate_in_compare():
    old = {"e2e_drift_baseline_psi": 0.03,
           "e2e_prequential_accuracy": 0.90,
           "e2e_ann_recall": 0.80,
           "e2e_prequential_tracks_holdout_ok": True}
    new = {"e2e_drift_baseline_psi": 0.40,     # false alarms leaked: bad
           "e2e_prequential_accuracy": 0.70,   # accuracy fell: bad
           "e2e_ann_recall": 0.99,             # improved
           "e2e_prequential_tracks_holdout_ok": False}  # gate flip
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_drift_baseline_psi"] == "REGRESSED"
    assert verdicts["e2e_prequential_accuracy"] == "REGRESSED"
    assert verdicts["e2e_ann_recall"] == "improved"
    assert verdicts["e2e_prequential_tracks_holdout_ok"] == "REGRESSED"
    assert len(regs) == 3


def test_direction_inference_durable_model_keys():
    """ISSUE 18 durable model plane: coldstart-to-serving wall time and
    killall model loss gate down-good (the loss contract is zero rows
    beyond the diff-chain tail), warm-boot recovery rides the existing
    ``_recovery_s`` pattern, warm-beats-cold is a boolean gate."""
    assert bc.direction("e2e_fleet_coldstart_to_serving_s") == "lower"
    assert bc.direction("e2e_killall_model_loss_rows") == "lower"
    assert bc.direction("e2e_warmboot_recovery_s") == "lower"
    assert bc.direction("e2e_warmboot_beats_cold_ok") == "bool"
    # neighbors that must NOT accidentally gate: raw diagnostics
    assert bc.direction("e2e_killall_tail_window_rows") is None
    assert bc.direction("e2e_warmboot_chain_len") is None
    assert bc.direction("e2e_killall_acked_rows") is None


def test_durable_model_keys_gate_in_compare():
    old = {"e2e_fleet_coldstart_to_serving_s": 9.0,
           "e2e_warmboot_recovery_s": 1.5,
           "e2e_killall_model_loss_rows": 0,
           "e2e_warmboot_beats_cold_ok": True}
    new = {"e2e_fleet_coldstart_to_serving_s": 14.0,  # slower: regression
           "e2e_warmboot_recovery_s": 1.2,            # improved
           "e2e_killall_model_loss_rows": 120,        # durability loss
           "e2e_warmboot_beats_cold_ok": False}       # gate flip
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_fleet_coldstart_to_serving_s"] == "REGRESSED"
    assert verdicts["e2e_killall_model_loss_rows"] == "REGRESSED"
    assert verdicts["e2e_warmboot_beats_cold_ok"] == "REGRESSED"
    assert verdicts["e2e_warmboot_recovery_s"] == "improved"
    assert len(regs) == 3


def test_sharded_keys_gate_in_compare():
    old = {"sharded_train_samples_per_sec_d26_8shard": 50000.0,
           "sharded_classify_p99_ms_d26_8shard": 40.0,
           "knn_query_p99_ms_rows1e8_8shard": 900.0,
           "knn_query_p99_ms_rows1e6_1shard": 8.0}
    new = {"sharded_train_samples_per_sec_d26_8shard": 42000.0,  # regressed
           "sharded_classify_p99_ms_d26_8shard": 36.0,           # improved
           "knn_query_p99_ms_rows1e8_8shard": 1100.0,            # regressed
           "knn_query_p99_ms_rows1e6_1shard": 8.1}               # within tol
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["sharded_train_samples_per_sec_d26_8shard"] \
        == "REGRESSED"
    assert verdicts["sharded_classify_p99_ms_d26_8shard"] == "improved"
    assert verdicts["knn_query_p99_ms_rows1e8_8shard"] == "REGRESSED"
    assert verdicts["knn_query_p99_ms_rows1e6_1shard"] == "ok"
    assert len(regs) == 2


def test_autoscale_keys_gate_in_compare(tmp_path):
    old = {"e2e_scaleout_recovery_s": 10.0,
           "e2e_autoscale_slo_violation_s": 12.0,
           "e2e_capacity_per_replica": 1200.0,
           "e2e_autoscale_beats_static_ok": True}
    new = {"e2e_scaleout_recovery_s": 18.0,       # slower: regression
           "e2e_autoscale_slo_violation_s": 11.0,  # improved
           "e2e_capacity_per_replica": 900.0,      # shrank: regression
           "e2e_autoscale_beats_static_ok": False}  # gate flip
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_scaleout_recovery_s"] == "REGRESSED"
    assert verdicts["e2e_capacity_per_replica"] == "REGRESSED"
    assert verdicts["e2e_autoscale_beats_static_ok"] == "REGRESSED"
    assert verdicts["e2e_autoscale_slo_violation_s"] == "improved"
    assert len(regs) == 3


def test_direction_inference_poison_keys():
    """ISSUE 15 model-integrity plane: the poison drill arms a KNOWN
    poisoner, so quarantined counts gate up-good (falling means the
    guard stopped catching it), drift vs the clean twin and rollback
    recovery wall time gate down-good, the load-bearing verdicts are
    boolean gates."""
    assert bc.direction("e2e_poison_quarantined_total") == "higher"
    assert bc.direction("e2e_poison_nan_quarantined") == "higher"
    assert bc.direction("e2e_poison_drift_vs_clean") == "lower"
    assert bc.direction("e2e_rollback_recovery_s") == "lower"
    assert bc.direction("e2e_poison_guard_load_bearing_ok") == "bool"
    assert bc.direction("e2e_poison_zero_nonfinite_applied_ok") == "bool"
    # neighbors that must NOT accidentally gate
    assert bc.direction("e2e_poison_unguarded_corrupted") is None


def test_poison_keys_gate_in_compare():
    old = {"e2e_poison_quarantined_total": 12,
           "e2e_poison_drift_vs_clean": 0.0001,
           "e2e_rollback_recovery_s": 0.1,
           "e2e_poison_guard_load_bearing_ok": True}
    new = {"e2e_poison_quarantined_total": 4,     # guard missing: regression
           "e2e_poison_drift_vs_clean": 0.02,     # drifted: regression
           "e2e_rollback_recovery_s": 0.08,       # improved
           "e2e_poison_guard_load_bearing_ok": False}  # gate flip
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_poison_quarantined_total"] == "REGRESSED"
    assert verdicts["e2e_poison_drift_vs_clean"] == "REGRESSED"
    assert verdicts["e2e_rollback_recovery_s"] == "improved"
    assert verdicts["e2e_poison_guard_load_bearing_ok"] == "REGRESSED"
    assert len(regs) == 3


def test_direction_inference_ann_keys():
    """ISSUE 16 ANN tier: recall@k against the exact scan gates
    up-good (falling recall = wrong neighbors), index build throughput
    rides the existing _per_sec pattern, the IVF query p99 gates
    down-good via _p99_ms like every latency key."""
    assert bc.direction("ann_recall_at_10_rows1e8") == "higher"
    assert bc.direction("ann_recall_at_10_rows1e6") == "higher"
    assert bc.direction("ann_build_rows_per_sec") == "higher"
    assert bc.direction("knn_query_p99_ms_rows1e8_8shard_ivf") == "lower"
    # neighbors that must NOT accidentally gate
    assert bc.direction("ann_nprobe") is None
    assert bc.direction("ann_cells_rows1e8") is None


def test_ann_keys_gate_in_compare():
    old = {"ann_recall_at_10_rows1e8": 0.97,
           "ann_build_rows_per_sec": 500000.0,
           "knn_query_p99_ms_rows1e8_8shard_ivf": 40.0,
           "ann_nprobe": 8}
    new = {"ann_recall_at_10_rows1e8": 0.80,              # recall fell: bad
           "ann_build_rows_per_sec": 650000.0,            # improved
           "knn_query_p99_ms_rows1e8_8shard_ivf": 55.0,   # slower: bad
           "ann_nprobe": 16}                              # info only
    rows, regs = bc.compare(bc.flatten(old), bc.flatten(new))
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["ann_recall_at_10_rows1e8"] == "REGRESSED"
    assert verdicts["ann_build_rows_per_sec"] == "improved"
    assert verdicts["knn_query_p99_ms_rows1e8_8shard_ivf"] == "REGRESSED"
    assert verdicts["ann_nprobe"] == "info"
    assert len(regs) == 2


def test_direction_inference_scaling_keys():
    """ISSUE 9 scaling plane: wire bytes per HOST gate down-good (the
    hierarchical reduce's whole claim), the reduction factor up-good —
    and the factor must win over the _per_host substring it contains."""
    assert bc.direction("collective_wire_bytes_per_host_nproc8_d24") \
        == "lower"
    assert bc.direction(
        "collective_wire_bytes_per_host_nproc8_d24_hier") == "lower"
    assert bc.direction("collective_phase_wire_bytes_per_host_d24") \
        == "lower"
    assert bc.direction("collective_wire_per_host_reduction_nproc8") \
        == "higher"
    assert bc.direction("collective_round_ms_nproc16_d24_hier") == "lower"


def test_nproc16_default_tolerance():
    """The nproc16 wall times swing on scheduler noise (16 gloo
    processes, however few cores): their built-in tolerance is loose,
    the deterministic wire-byte keys keep the tight default, and an
    explicit --key-tolerance still wins."""
    assert bc.default_tolerance_for(
        "collective_round_ms_nproc16_d24", 0.05) == 0.30
    assert bc.default_tolerance_for(
        "collective_round_ms_nproc16_d24_hier", 0.05) == 0.30
    assert bc.default_tolerance_for(
        "collective_round_ms_nproc8_d24", 0.05) == 0.05
    assert bc.default_tolerance_for(
        "collective_wire_bytes_per_host_nproc16_d24", 0.05) == 0.05
    old = {"collective_round_ms_nproc16_d24": 4000.0,
           "collective_wire_bytes_per_host_nproc16_d24": 100663296}
    new = {"collective_round_ms_nproc16_d24": 4800.0,  # +20% < 30%
           "collective_wire_bytes_per_host_nproc16_d24": 100663296}
    _rows, regs = bc.compare(old, new, tolerance=0.05)
    assert regs == []
    new["collective_round_ms_nproc16_d24"] = 5600.0   # +40% > 30%
    _rows, regs = bc.compare(old, new, tolerance=0.05)
    assert [r["key"] for r in regs] == ["collective_round_ms_nproc16_d24"]
    # wire bytes growing is a regression at the tight default: the
    # hierarchical claim IS that this number stays put
    new["collective_round_ms_nproc16_d24"] = 4000.0
    new["collective_wire_bytes_per_host_nproc16_d24"] = 201326592
    _rows, regs = bc.compare(old, new, tolerance=0.05)
    assert [r["key"] for r in regs] == \
        ["collective_wire_bytes_per_host_nproc16_d24"]
    # explicit per-key override still beats the built-in default
    old2 = {"collective_round_ms_nproc16_d24": 4000.0}
    new2 = {"collective_round_ms_nproc16_d24": 4800.0}
    _rows, regs = bc.compare(
        old2, new2, tolerance=0.05,
        key_tolerance={"collective_round_ms_nproc16_d24": 0.10})
    assert len(regs) == 1


def test_flatten_collapses_round_envelopes():
    envelope = {"n": 5, "rc": 0, "tail": "…",
                "parsed": {"metric": "x", "value": 2.0,
                           "extra": {"e2e_a_samples_per_sec": 10.0,
                                     "nested": {"k_ms": 1.0}}}}
    flat = bc.flatten(envelope)
    # parsed/extra collapse WITHOUT a prefix; other dicts keep one
    assert flat["e2e_a_samples_per_sec"] == 10.0
    assert flat["value"] == 2.0
    assert flat["nested.k_ms"] == 1.0
    assert "tail" not in flat
    # flat maps (bench_serving / profile_flush output) pass through
    assert bc.flatten({"a_ms": 1.5})["a_ms"] == 1.5


def test_regressions_flagged_beyond_tolerance():
    new = dict(OLD)
    new["e2e_rpc_train_samples_per_sec_native"] = 80000.0   # -20%: bad
    new["e2e_rpc_classify_p99_ms_native"] = 13.0            # +30%: bad
    new["e2e_profiling_overhead_ok"] = False                # flip: bad
    new["collective_wire_mb_per_round"] = 120.0             # -75%: good
    rows, regs = bc.compare(OLD, new, tolerance=0.05)
    bad = {r["key"] for r in regs}
    assert bad == {"e2e_rpc_train_samples_per_sec_native",
                   "e2e_rpc_classify_p99_ms_native",
                   "e2e_profiling_overhead_ok"}
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["collective_wire_mb_per_round"] == "improved"
    assert verdicts["e2e_clients"] == "info"


def test_within_tolerance_is_clean():
    new = dict(OLD)
    new["e2e_rpc_train_samples_per_sec_native"] = 96500.0   # -3.5% < 5%
    new["e2e_rpc_classify_p99_ms_native"] = 10.4            # +4%  < 5%
    _rows, regs = bc.compare(OLD, new, tolerance=0.05)
    assert regs == []


def test_per_key_tolerance_override():
    new = dict(OLD)
    new["e2e_rpc_classify_p99_ms_native"] = 14.0            # +40%
    _r, regs = bc.compare(OLD, new, tolerance=0.05)
    assert len(regs) == 1
    _r, regs = bc.compare(
        OLD, new, tolerance=0.05,
        key_tolerance={"e2e_rpc_classify_p99_ms_native": 0.5})
    assert regs == []


def test_added_removed_keys_never_gate():
    new = dict(OLD)
    del new["collective_wire_mb_per_round"]
    new["brand_new_ms"] = 5.0
    rows, regs = bc.compare(OLD, new)
    assert regs == []
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["collective_wire_mb_per_round"] == "removed"
    assert verdicts["brand_new_ms"] == "added"


def test_main_exit_codes_over_fixtures(tmp_path, capsys):
    old_p = _write(tmp_path, "BENCH_r01.json", OLD)
    good = dict(OLD)
    good["e2e_rpc_train_samples_per_sec_native"] = 120000.0
    good_p = _write(tmp_path, "BENCH_r02.json", good)
    bad = dict(OLD)
    bad["e2e_rpc_train_samples_per_sec_native"] = 50000.0
    bad_p = _write(tmp_path, "BENCH_r03.json", bad)

    assert bc.main([old_p, good_p]) == 0
    out = capsys.readouterr().out
    assert "improved" in out and "0 regressed" in out
    assert bc.main([old_p, bad_p]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    # round envelopes flatten the same way end to end
    env_old = _write(tmp_path, "env_old.json",
                     {"parsed": {"extra": OLD}, "rc": 0})
    assert bc.main([env_old, bad_p]) == 1
    capsys.readouterr()
    # usage errors
    assert bc.main([]) == 2
    assert bc.main([old_p, "/nonexistent.json"]) == 2
    assert bc.main([old_p, good_p, "--key-tolerance", "nonsense"]) == 2


def test_glob_picks_latest_two(tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json", OLD)
    mid = dict(OLD)
    mid["e2e_rpc_train_samples_per_sec_native"] = 50000.0
    _write(tmp_path, "BENCH_r02.json", mid)
    new = dict(mid)
    new["e2e_rpc_train_samples_per_sec_native"] = 51000.0
    _write(tmp_path, "BENCH_r03.json", new)
    # latest two = r02 -> r03 (within tolerance); the r01 drop is not
    # in the window
    assert bc.main(["--glob", str(tmp_path / "BENCH_r*.json")]) == 0
    out = capsys.readouterr().out
    assert "BENCH_r02.json" in out and "BENCH_r03.json" in out
    with pytest.raises(ValueError):
        bc.pick_latest_two(str(tmp_path / "nope*.json"))


def test_direction_inference_tune_keys():
    """ISSUE 20 self-tuning plane: regret (tuned-vs-hand-tuned round
    time) rides the _ratio pattern; the rounds-to-converge count is its
    own down-good pattern (growth = the search got slower); the
    observe-mode A/B overhead rides _ratio too."""
    assert bc.direction("e2e_tune_regret_ratio") == "lower"
    assert bc.direction("e2e_tune_converge_rounds") == "lower"
    assert bc.direction("e2e_tune_observe_overhead_ratio") == "lower"
    # neighbors that must NOT accidentally gate
    assert bc.direction("e2e_tune_rounds_total") is None
    assert bc.direction("e2e_tune_plans_scored") is None


def test_tune_keys_gate_over_fixtures():
    """The regret/converge directions drive real verdicts: regret
    drifting up or the search needing more rounds each REGRESS; both
    shrinking count as improvements."""
    old = {"e2e_tune_regret_ratio": 1.10,
           "e2e_tune_converge_rounds": 8}
    worse = {"e2e_tune_regret_ratio": 1.40,
             "e2e_tune_converge_rounds": 14}
    rows, regs = bc.compare(old, worse, tolerance=0.05)
    assert {r["key"] for r in regs} == \
        {"e2e_tune_regret_ratio", "e2e_tune_converge_rounds"}
    better = {"e2e_tune_regret_ratio": 1.02,
              "e2e_tune_converge_rounds": 5}
    rows, regs = bc.compare(old, better, tolerance=0.05)
    assert regs == []
    verdicts = {r["key"]: r["verdict"] for r in rows}
    assert verdicts["e2e_tune_regret_ratio"] == "improved"
    assert verdicts["e2e_tune_converge_rounds"] == "improved"
