"""The compact bench summary must survive a last-2000-chars stdout window.

Round 4's artifact of record lost its own headline because the driver
keeps only the tail of stdout and the headline keys printed first
(VERDICT r4 "What's weak" #1). benchlib.summarize() is the fix: one compact
JSON line, headline-first key priority, hard byte budget.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import benchlib  # noqa: E402


def _payload(n_extra=0, **extra):
    e = dict(extra)
    for i in range(n_extra):
        e[f"mix_round_ms_padding_key_with_a_long_name_{i:04d}"] = 123.456789
    return {"metric": "classifier_train_samples_per_sec_arow_d2^20",
            "value": 321654.9, "unit": "samples/s", "vs_baseline": 0.62,
            "extra": e}


def test_summary_fits_budget_under_heavy_extra():
    s = benchlib.summarize(_payload(200, bench_platform="tpu"), "BENCH_FULL_r05.json")
    assert len(json.dumps(s)) <= benchlib.SUMMARY_BYTES
    assert s["keys_dropped"] > 0


def test_headline_and_platform_always_survive():
    s = benchlib.summarize(
        _payload(500, bench_platform="tpu",
                 baseline_samples_per_sec=522000.0,
                 **{"tpu_d2^24_samples_per_sec": 238000.0}),
        "BENCH_FULL_r05.json")
    assert s["metric"] == "classifier_train_samples_per_sec_arow_d2^20"
    assert s["value"] == 321654.9
    assert s["extra"]["bench_platform"] == "tpu"
    assert s["extra"]["tpu_d2^24_samples_per_sec"] == 238000.0
    assert s["full"] == "BENCH_FULL_r05.json"


def test_priority_order_beats_insertion_order():
    # a key listed in SUMMARY_EXACT must win over earlier-inserted noise
    e = {}
    for i in range(300):
        e[f"aaa_noise_{i:04d}"] = "x" * 40
    e["e2e_proxy_vs_direct"] = 0.83
    s = benchlib.summarize(_payload(0, **e), "f.json")
    assert s["extra"]["e2e_proxy_vs_direct"] == 0.83


def test_no_truncation_when_small():
    s = benchlib.summarize(_payload(0, bench_platform="cpu"), "f.json")
    assert s["keys_dropped"] == 0
    assert s["extra"] == {"bench_platform": "cpu"}


def test_round_trip_is_valid_json_line():
    s = benchlib.summarize(_payload(50, bench_platform="cpu"), "f.json")
    line = json.dumps(s)
    assert "\n" not in line
    assert json.loads(line)["unit"] == "samples/s"
