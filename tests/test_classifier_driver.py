"""Classifier driver tests — API parity with the reference classifier service
(train/classify/get_labels/set_label/delete_label/clear/save/load) and the
distributed mix with label-schema sync.

Mirrors the black-box coverage of
/root/reference/client_test/classifier_test.cpp (train/classify round trips,
save/load) without the RPC layer (that layer has its own tests).
"""

import json

import numpy as np
import pytest

from jubatus_tpu.core import Datum
from jubatus_tpu.framework import load_model, save_model
from jubatus_tpu.framework.save_load import SaveLoadError
from jubatus_tpu.models import ClassifierDriver
from jubatus_tpu.parallel import LocalMixGroup

CFG = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "tf", "global_weight": "bin"}
        ],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}

SPAM = [
    Datum({"t": "buy cheap pills now"}),
    Datum({"t": "cheap pills discount buy now"}),
    Datum({"t": "discount pills buy"}),
]
HAM = [
    Datum({"t": "meeting notes for tuesday"}),
    Datum({"t": "tuesday agenda and meeting notes"}),
    Datum({"t": "agenda for the meeting"}),
]


def trained_driver(dim_bits=12):
    d = ClassifierDriver(CFG, dim_bits=dim_bits)
    data = [("spam", x) for x in SPAM] + [("ham", x) for x in HAM]
    for _ in range(3):
        d.train(data)
    return d


def top_label(result):
    return max(result, key=lambda kv: kv[1])[0]


def test_train_classify_roundtrip():
    d = trained_driver()
    res = d.classify([Datum({"t": "cheap discount pills"}), Datum({"t": "notes for agenda"})])
    assert top_label(res[0]) == "spam"
    assert top_label(res[1]) == "ham"
    # classify returns a score for every live label
    assert {lab for lab, _ in res[0]} == {"spam", "ham"}


def test_get_labels_counts_and_set_delete():
    d = trained_driver()
    labels = d.get_labels()
    assert labels == {"spam": 9, "ham": 9}
    assert d.set_label("eggs") is True
    assert d.set_label("eggs") is False
    assert set(d.get_labels()) == {"spam", "ham", "eggs"}
    assert d.get_labels()["eggs"] == 0
    assert d.delete_label("eggs") is True
    assert d.delete_label("eggs") is False
    assert set(d.get_labels()) == {"spam", "ham"}


def test_deleted_label_slot_reuse_is_clean():
    d = trained_driver()
    d.delete_label("spam")
    d.set_label("other")
    res = d.classify([Datum({"t": "cheap discount pills"})])
    scores = dict(res[0])
    assert scores["other"] == 0.0


def test_train_returns_count_and_empty_ok():
    d = ClassifierDriver(CFG, dim_bits=10)
    assert d.train([]) == 0
    assert d.train([("a", Datum({"t": "x y"})), ("b", Datum({"t": "z w"}))]) == 2
    assert d.classify([]) == []


def test_clear_resets():
    d = trained_driver()
    d.clear()
    assert d.get_labels() == {}
    assert d.classify([Datum({"t": "anything"})]) == [[]]
    assert d.update_count == 0


def test_label_capacity_growth():
    d = ClassifierDriver(CFG, dim_bits=10)
    for i in range(20):
        d.train([(f"label{i:02d}", Datum({"t": f"word{i} tok{i}"}))])
    assert len(d.get_labels()) == 20
    assert d.capacity >= 20


def test_unknown_method_raises():
    with pytest.raises(ValueError):
        ClassifierDriver({"method": "SVM"})


def test_save_load_roundtrip(tmp_path):
    d = trained_driver()
    path = str(tmp_path / "model.jubatus")
    save_model(path, d, model_id="c0", config=d.config_json)
    before = d.classify([Datum({"t": "cheap discount pills"})])

    d2 = ClassifierDriver(CFG, dim_bits=12)
    system, ver = load_model(path, d2, expected_config=d2.config_json)
    assert system["type"] == "classifier"
    after = d2.classify([Datum({"t": "cheap discount pills"})])
    assert sorted(dict(before[0])) == sorted(dict(after[0]))
    np.testing.assert_allclose(
        sorted(v for _, v in before[0]), sorted(v for _, v in after[0]), atol=1e-6
    )
    assert d2.get_labels() == d.get_labels()


def test_load_validates_type_crc_and_config(tmp_path):
    d = trained_driver()
    path = str(tmp_path / "model.jubatus")
    save_model(path, d, config=d.config_json)

    # wrong engine type
    from jubatus_tpu.models import RegressionDriver

    r = RegressionDriver({"method": "PA1"}, dim_bits=10)
    with pytest.raises(SaveLoadError, match="type"):
        load_model(path, r)

    # config mismatch (semantic compare — whitespace-only diffs are fine)
    with pytest.raises(SaveLoadError, match="config"):
        load_model(path, ClassifierDriver(CFG, dim_bits=12),
                   expected_config=json.dumps({"method": "PA"}))
    spaced = json.dumps(json.loads(d.config_json), indent=3)
    load_model(path, ClassifierDriver(CFG, dim_bits=12), expected_config=spaced)

    # corruption -> CRC failure
    raw = bytearray(open(path, "rb").read())
    raw[60] ^= 0xFF
    bad = str(tmp_path / "bad.jubatus")
    open(bad, "wb").write(bytes(raw))
    with pytest.raises(SaveLoadError, match="CRC32"):
        load_model(bad, ClassifierDriver(CFG, dim_bits=12))


EGGS = [
    Datum({"t": "fresh organic eggs from the farm"}),
    Datum({"t": "farm eggs organic dozen"}),
    Datum({"t": "dozen fresh eggs"}),
]


def test_mix_two_replicas_with_distinct_labels():
    """Replicas see different label sets ({spam,ham} vs {eggs,ham}); after mix
    both know all three labels and classify each other's classes — the
    schema-sync + psum path."""
    d0 = ClassifierDriver(CFG, dim_bits=12)
    d1 = ClassifierDriver(CFG, dim_bits=12)
    for _ in range(3):
        d0.train([("spam", x) for x in SPAM] + [("ham", x) for x in HAM])
        d1.train([("eggs", x) for x in EGGS] + [("ham", x) for x in HAM])
    group = LocalMixGroup([d0, d1])
    group.mix()
    assert d0.get_schema() == d1.get_schema() == ["eggs", "ham", "spam"]
    assert d0.get_labels() == d1.get_labels() == {"spam": 9, "ham": 18, "eggs": 9}
    for d in (d0, d1):
        res = d.classify([
            Datum({"t": "cheap discount pills"}),
            Datum({"t": "meeting agenda notes"}),
            Datum({"t": "organic farm eggs"}),
        ])
        assert top_label(res[0]) == "spam"
        assert top_label(res[1]) == "ham"
        assert top_label(res[2]) == "eggs"
    # post-mix: local diffs are cleared; another mix is a no-op on weights
    w_before = np.asarray(d0.state.w).copy()
    group.mix()
    np.testing.assert_allclose(np.asarray(d0.state.w), w_before, atol=1e-6)


def test_mix_replicas_equivalent_over_device_mesh():
    """Same mix through a real 4-device mesh collective must equal host fold."""
    from jubatus_tpu.parallel import replica_mesh

    ds = [ClassifierDriver(CFG, dim_bits=10) for _ in range(4)]
    data = [("spam", x) for x in SPAM] + [("ham", x) for x in HAM]
    for i, d in enumerate(ds):
        d.train(data[i::2] if i % 2 == 0 else data[1::2])

    host = [ClassifierDriver(CFG, dim_bits=10) for _ in range(4)]
    for i, d in enumerate(host):
        d.train(data[i::2] if i % 2 == 0 else data[1::2])

    LocalMixGroup(ds, mesh=replica_mesh(4)).mix()
    LocalMixGroup(host).mix()
    np.testing.assert_allclose(
        np.asarray(ds[0].state.w), np.asarray(host[0].state.w), rtol=1e-5, atol=1e-6
    )
