"""Instance-based classifier tests (methods NN / cosine / euclidean —
config/classifier/{nn,cosine,euclidean}.json).
"""

from __future__ import annotations

import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.models.classifier_nn import ClassifierNNDriver
from jubatus_tpu.server.factory import create_driver

CONV = {"num_rules": [{"key": "*", "type": "num"}]}


def _conf(clf_method, **param):
    base = {"nearest_neighbor_num": 3, "local_sensitivity": 0.5}
    base.update(param)
    return {"method": clf_method, "converter": CONV, "parameter": base}


TRAIN = [
    ("pos", Datum({"x": 1.0, "y": 1.0})),
    ("pos", Datum({"x": 0.9, "y": 0.8})),
    ("pos", Datum({"x": 1.1, "y": 0.9})),
    ("neg", Datum({"x": -1.0, "y": -1.0})),
    ("neg", Datum({"x": -0.8, "y": -1.1})),
    ("neg", Datum({"x": -1.2, "y": -0.9})),
]


@pytest.mark.parametrize("method,param", [
    ("cosine", {}),
    ("euclidean", {}),
    ("NN", {"method": "euclid_lsh", "parameter": {"hash_num": 128}}),
    ("NN", {"method": "lsh", "parameter": {"hash_num": 128}}),
])
def test_classify_separable(method, param):
    d = ClassifierNNDriver(_conf(method, **param))
    assert d.train(TRAIN) == 6
    results = d.classify([Datum({"x": 1.0, "y": 0.9}),
                          Datum({"x": -1.0, "y": -0.95})])
    assert max(results[0], key=lambda s: s[1])[0] == "pos"
    assert max(results[1], key=lambda s: s[1])[0] == "neg"
    # scores exist for every known label
    assert {lab for lab, _ in results[0]} == {"pos", "neg"}


def test_factory_routes_nn_methods():
    d = create_driver("classifier", _conf("cosine"))
    assert isinstance(d, ClassifierNNDriver)


def test_labels_and_delete():
    d = ClassifierNNDriver(_conf("euclidean"))
    d.train(TRAIN)
    assert d.get_labels() == {"pos": 3, "neg": 3}
    assert d.set_label("zzz") is True
    assert d.set_label("zzz") is False  # already known
    assert d.get_labels()["zzz"] == 0
    assert d.delete_label("pos") is True
    assert "pos" not in d.get_labels()
    (res,) = d.classify([Datum({"x": 1.0, "y": 1.0})])
    assert {lab for lab, _ in res} == {"neg", "zzz"}
    assert d.delete_label("ghost") is False


def test_clear():
    d = ClassifierNNDriver(_conf("cosine"))
    d.train(TRAIN)
    d.clear()
    assert d.get_labels() == {}
    (res,) = d.classify([Datum({"x": 1.0})])
    assert res == []


def test_pack_unpack_roundtrip():
    d = ClassifierNNDriver(_conf("euclidean"))
    d.train(TRAIN)
    d.set_label("extra")
    from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

    blob = pack_obj(d.pack())
    d2 = ClassifierNNDriver(_conf("euclidean"))
    d2.unpack(unpack_obj(blob))
    assert d2.get_labels() == {"pos": 3, "neg": 3, "extra": 0}
    (res,) = d2.classify([Datum({"x": 1.0, "y": 1.0})])
    assert max(res, key=lambda s: s[1])[0] == "pos"


def test_mix_merges_examples():
    """Two nodes train different classes; folding their row diffs teaches
    both (the linear-mix seam, like the linear classifier's weight mix)."""
    a = ClassifierNNDriver(_conf("euclidean"))
    b = ClassifierNNDriver(_conf("euclidean"))
    a.train([(lab, d) for lab, d in TRAIN if lab == "pos"])
    b.train([(lab, d) for lab, d in TRAIN if lab == "neg"])
    ma, mb = a.get_mixables()["rows"], b.get_mixables()["rows"]
    folded = ma.mix(ma.get_diff(), mb.get_diff())
    ma.put_diff(folded)
    mb.put_diff(folded)
    for drv in (a, b):
        res = drv.classify([Datum({"x": 1.0, "y": 1.0}),
                            Datum({"x": -1.0, "y": -1.0})])
        assert max(res[0], key=lambda s: s[1])[0] == "pos"
        assert max(res[1], key=lambda s: s[1])[0] == "neg"


def _mix_labels(x, y):
    """One label-mix round between two drivers (both apply the fold)."""
    mx, my = x.get_mixables()["labels"], y.get_mixables()["labels"]
    folded = mx.mix(mx.get_diff(), my.get_diff())
    mx.put_diff(folded)
    my.put_diff(folded)


def test_set_label_propagates_via_mix():
    """A label registered on one replica (no examples yet) reaches the
    other through the labels mixable."""
    a = ClassifierNNDriver(_conf("cosine"))
    b = ClassifierNNDriver(_conf("cosine"))
    a.set_label("early")
    _mix_labels(a, b)
    assert b.get_labels() == {"early": 0}


def test_label_diff_is_not_destructive():
    """get_diff ships full state: a failed exchange loses nothing and the
    next round still delivers (the delta design dropped labels on peer
    failure)."""
    a = ClassifierNNDriver(_conf("cosine"))
    a.set_label("x")
    m = a.get_mixables()["labels"]
    first = m.get_diff()
    second = m.get_diff()  # e.g. retry after a dead peer
    assert first == second and "x" in second


def test_delete_label_tombstone_beats_stale_registration():
    """A cluster-wide delete is not resurrected by an idle replica that
    still ships the old registration in its full-state diff."""
    a = ClassifierNNDriver(_conf("cosine"))
    b = ClassifierNNDriver(_conf("cosine"))
    a.set_label("spam")
    _mix_labels(a, b)  # both replicas now know 'spam'
    assert b.get_labels() == {"spam": 0}
    a.delete_label("spam")  # higher epoch tombstone on a
    _mix_labels(a, b)  # b's stale alive-state must lose
    assert a.get_labels() == {} and b.get_labels() == {}
    # and further idle rounds keep it dead
    _mix_labels(b, a)
    assert a.get_labels() == {}


def test_label_propagates_transitively():
    """Full-state diffs gossip transitively: a → b, then b → c, without a
    ever talking to c."""
    a, b, c = (ClassifierNNDriver(_conf("cosine")) for _ in range(3))
    a.set_label("relay")
    _mix_labels(a, b)
    _mix_labels(b, c)
    assert c.get_labels() == {"relay": 0}


def test_local_sensitivity_sharpness():
    """Smaller local_sensitivity concentrates weight on the closest
    neighbor; scores must still rank correctly near the boundary."""
    sharp = ClassifierNNDriver(_conf("euclidean", local_sensitivity=0.05))
    sharp.train(TRAIN)
    (res,) = sharp.classify([Datum({"x": 0.95, "y": 0.9})])
    assert max(res, key=lambda s: s[1])[0] == "pos"


def test_bad_config_rejected():
    with pytest.raises(ValueError):
        ClassifierNNDriver(_conf("cosine", nearest_neighbor_num=0))
    with pytest.raises(ValueError):
        ClassifierNNDriver(_conf("cosine", local_sensitivity=0))
    with pytest.raises(ValueError):
        ClassifierNNDriver({"method": "what", "converter": CONV})


def test_server_e2e_nn_classifier():
    """Full wire path: EngineServer + client over a real socket."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer

    srv = EngineServer("classifier", _conf("cosine"))
    port = srv.start(0)
    try:
        c = ClassifierClient("127.0.0.1", port, "")
        assert c.train([[lab, d] for lab, d in TRAIN]) == 6
        (res,) = c.classify([Datum({"x": 1.0, "y": 1.0})])
        assert max(res, key=lambda s: s[1])[0] == "pos"
        assert c.get_labels() == {"pos": 3, "neg": 3}
        c.close()
    finally:
        srv.stop()
