"""Classifier kernel tests: learning behavior of every method + mix semantics.

Mirrors the reference's test intent for classifier algorithms and the
mix-fold associativity assertion in linear_mixer_test.cpp:156-169 — here the
stronger property holds: diffs are additive so any mix order is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jubatus_tpu.core.sparse import SparseBatch
from jubatus_tpu.ops import classifier as C

DIM = 1 << 12
L = 4


def make_blobs(rng, n, n_features=16, n_classes=3, sep=3.0):
    """Sparse-ish synthetic multiclass data in the hashed index space."""
    centers = rng.normal(size=(n_classes, n_features)) * sep
    labels = rng.integers(0, n_classes, size=n)
    dense = centers[labels] + rng.normal(size=(n, n_features))
    # map features to fixed distinct hash indices (avoid 0, the padding slot)
    feat_idx = rng.choice(np.arange(1, DIM), size=n_features, replace=False)
    vectors = [
        [(int(feat_idx[j]), float(dense[i, j])) for j in range(n_features)]
        for i in range(n)
    ]
    return vectors, labels


def batchify(vectors, labels):
    sb = SparseBatch.from_vectors(vectors)
    return (
        jnp.asarray(sb.idx),
        jnp.asarray(sb.val),
        jnp.asarray(labels, jnp.int32),
    )


def accuracy(state, idx, val, labels, mask):
    s = C.scores(state, idx, val, mask)
    return float(jnp.mean(jnp.argmax(s, axis=1) == labels))


@pytest.mark.parametrize("mode", ["parallel", "sequential"])
@pytest.mark.parametrize("method", C.METHODS)
def test_method_learns_separable_data(method, mode, rng):
    vectors, labels = make_blobs(rng, 300)
    idx, val, y = batchify(vectors, labels)
    mask = jnp.array([True, True, True, False])
    state = C.init_state(L, DIM, method in C.CONFIDENCE_METHODS)
    param = 1.0
    for _ in range(3):
        state = C.train_batch(state, idx, val, y, mask, param, method=method, mode=mode)
    acc = accuracy(state, idx, val, y, mask)
    assert acc > 0.9, f"{method}/{mode} failed to learn: acc={acc}"


def test_parallel_matches_sequential_on_batch_of_one(rng):
    """With B=1 the snapshot semantics coincide: both paths must agree."""
    vectors, labels = make_blobs(rng, 20)
    mask = jnp.array([True, True, True, False])
    s_par = C.init_state(L, DIM, True)
    s_seq = C.init_state(L, DIM, True)
    for vec, lab in zip(vectors, labels):
        sb = SparseBatch.from_vectors([vec])
        args = (jnp.asarray(sb.idx), jnp.asarray(sb.val),
                jnp.asarray([lab], jnp.int32), mask, 1.0)
        s_par = C.train_batch(s_par, *args, method="AROW", mode="parallel")
        s_seq = C.train_batch(s_seq, *args, method="AROW", mode="sequential")
    np.testing.assert_allclose(np.asarray(s_par.dw), np.asarray(s_seq.dw),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(s_par.dprec), np.asarray(s_seq.dprec),
                               rtol=1e-5, atol=1e-6)


def test_dead_labels_never_predicted(rng):
    vectors, labels = make_blobs(rng, 100, n_classes=2)
    idx, val, y = batchify(vectors, labels)
    mask = jnp.array([True, True, False, False])
    state = C.init_state(L, DIM, False)
    state = C.train_batch(state, idx, val, y, mask, 1.0, method="PA")
    s = C.scores(state, idx, val, mask)
    assert int(jnp.max(jnp.argmax(s, axis=1))) <= 1


def test_single_label_still_learns(rng):
    """With one live label the rival score is 0 (jubatus_core calc_margin
    initializes the incorrect score to 0 when no other label exists), so the
    correct row still gets its update — and nothing lands on dead slots."""
    vectors, labels = make_blobs(rng, 10, n_classes=1)
    idx, val, y = batchify(vectors, labels)
    mask = jnp.array([True, False, False, False])
    state = C.init_state(L, DIM, False)
    state = C.train_batch(state, idx, val, y, mask, 1.0, method="PA")
    dw = np.asarray(state.dw)
    assert np.abs(dw[0]).max() > 0.0       # the live label learned
    assert np.abs(dw[1:]).max() == 0.0     # dead slots untouched


def test_padding_is_noop(rng):
    """Padded entries (idx 0, val 0) must not perturb the model."""
    vectors, labels = make_blobs(rng, 50)
    mask = jnp.array([True, True, True, False])
    sb_narrow = SparseBatch.from_vectors(vectors, min_width=16)
    sb_wide = SparseBatch.from_vectors(vectors, min_width=64)
    y = jnp.asarray(labels, jnp.int32)
    s1 = C.init_state(L, DIM, True)
    s2 = C.init_state(L, DIM, True)
    s1 = C.train_batch(s1, jnp.asarray(sb_narrow.idx), jnp.asarray(sb_narrow.val),
                       y, mask, 1.0, method="AROW")
    s2 = C.train_batch(s2, jnp.asarray(sb_wide.idx), jnp.asarray(sb_wide.val),
                       y, mask, 1.0, method="AROW")
    np.testing.assert_allclose(np.asarray(s1.dw), np.asarray(s2.dw), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1.dprec), np.asarray(s2.dprec), atol=1e-5)


def test_mix_diff_additive_and_order_free(rng):
    """Two replicas train on disjoint halves; mixing their diffs in either
    order gives the identical master — the exact-psum property that replaces
    the reference's sequential fold (linear_mixer.cpp:481-499)."""
    vectors, labels = make_blobs(rng, 200)
    half = 100
    mask = jnp.array([True, True, True, False])
    states = []
    for lo, hi in ((0, half), (half, 200)):
        idx, val, y = batchify(vectors[lo:hi], labels[lo:hi])
        st = C.init_state(L, DIM, True)
        st = C.train_batch(st, idx, val, y, mask, 1.0, method="AROW")
        states.append(st)
    d0, d1 = C.get_diff(states[0]), C.get_diff(states[1])
    m01 = C.mix_diffs(d0, d1)
    m10 = C.mix_diffs(d1, d0)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        , m01, m10)
    assert float(m01["count"]) == 2.0

    mixed0 = C.put_diff(states[0], m01)
    mixed1 = C.put_diff(states[1], m10)
    np.testing.assert_allclose(np.asarray(mixed0.w), np.asarray(mixed1.w), atol=1e-6)
    # post-mix local diffs are cleared
    assert float(jnp.abs(mixed0.dw).max()) == 0.0
    # mixed model still classifies the full set well
    idx, val, y = batchify(vectors, labels)
    acc = accuracy(mixed0, idx, val, y, mask)
    assert acc > 0.85


def test_grow_labels_preserves_model(rng):
    vectors, labels = make_blobs(rng, 100)
    idx, val, y = batchify(vectors, labels)
    mask = jnp.array([True, True, True, False])
    state = C.init_state(L, DIM, True)
    state = C.train_batch(state, idx, val, y, mask, 1.0, method="AROW")
    grown = C.grow_labels(state, 6)
    assert grown.w.shape == (6, DIM)
    np.testing.assert_allclose(np.asarray(grown.w[:L]), np.asarray(state.w))
    mask6 = jnp.concatenate([mask, jnp.array([False, False])])
    acc = accuracy(grown, idx, val, y, mask6)
    assert acc > 0.9
