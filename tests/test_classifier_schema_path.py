"""Uniform-schema dense train/score path ≡ the sparse path.

A fixed key schema (every datum hashes to the same index vector) lets
the serving plane run the classifier step as dense matmuls over the
[L, K] submatrix instead of B*K-element gathers/scatters
(ops.classifier.train_batch_schema / scores_schema). Same semantics as
train_batch_parallel — batch-start snapshot, updates land together —
different execution plan, so agreement is to tolerance, not bitwise.
Reference semantics: classifier_serv.cpp:127-146's per-datum update,
microbatched per SURVEY.md §7 hard part (b).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from jubatus_tpu.ops import classifier as C

D = 1 << 14
L = 3
K = 16
B = 64


def _mk(seed=0, k=K, b=B, dup_pad=False):
    rng = np.random.default_rng(seed)
    uidx = rng.choice(np.arange(1, D), size=k, replace=False).astype(np.int32)
    if dup_pad:  # width padding: trailing zero index columns, zero vals
        uidx = np.concatenate([uidx[:-2], np.zeros(2, np.int32)])
    val = rng.normal(size=(b, k)).astype(np.float32)
    if dup_pad:
        val[:, -2:] = 0.0
    labels = rng.integers(0, L, size=b).astype(np.int32)
    return uidx, val, labels


@pytest.mark.parametrize("method", ["AROW", "CW", "NHERD", "PA", "PA1",
                                    "perceptron"])
def test_schema_train_matches_parallel(method):
    uidx, val, labels = _mk()
    mask = jnp.ones(L, dtype=bool)
    conf = method in C.CONFIDENCE_METHODS
    st_a = C.init_state(L, D, confidence=conf)
    st_b = C.init_state(L, D, confidence=conf)
    tiled = jnp.asarray(np.broadcast_to(uidx, (B, K)).copy())
    for step in range(3):
        v = jnp.asarray(val * (1.0 + 0.1 * step))
        st_a = C.train_batch_parallel(st_a, tiled, v, jnp.asarray(labels),
                                      mask, 1.0, method=method)
        st_b = C.train_batch_schema(st_b, jnp.asarray(uidx), v,
                                    jnp.asarray(labels), mask, 1.0,
                                    method=method)
    np.testing.assert_allclose(np.asarray(st_a.dw), np.asarray(st_b.dw),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_a.dprec), np.asarray(st_b.dprec),
                               rtol=2e-4, atol=1e-5)


def test_schema_scores_match_sparse():
    uidx, val, labels = _mk(seed=1)
    mask = jnp.ones(L, dtype=bool)
    st = C.init_state(L, D, confidence=True)
    st = C.train_batch_schema(st, jnp.asarray(uidx), jnp.asarray(val),
                              jnp.asarray(labels), mask, 1.0, method="AROW")
    tiled = jnp.asarray(np.broadcast_to(uidx, (B, K)).copy())
    s_sparse = np.asarray(C.scores(st, tiled, jnp.asarray(val), mask))
    s_dense = np.asarray(C.scores_schema(st, jnp.asarray(uidx),
                                         jnp.asarray(val), mask))
    np.testing.assert_allclose(s_sparse, s_dense, rtol=1e-5, atol=1e-6)


def test_schema_duplicate_pad_columns_are_noops():
    """Width-pad columns (index 0, val 0) must not corrupt slot 0."""
    uidx, val, labels = _mk(seed=2, dup_pad=True)
    mask = jnp.ones(L, dtype=bool)
    st_a = C.init_state(L, D, confidence=True)
    st_b = C.init_state(L, D, confidence=True)
    tiled = jnp.asarray(np.broadcast_to(uidx, (B, K)).copy())
    st_a = C.train_batch_parallel(st_a, tiled, jnp.asarray(val),
                                  jnp.asarray(labels), mask, 1.0,
                                  method="AROW")
    st_b = C.train_batch_schema(st_b, jnp.asarray(uidx), jnp.asarray(val),
                                jnp.asarray(labels), mask, 1.0, method="AROW")
    np.testing.assert_allclose(np.asarray(st_a.dw), np.asarray(st_b.dw),
                               rtol=2e-4, atol=1e-5)
    assert float(jnp.sum(jnp.abs(st_b.dw[:, 0]))) == 0.0


def test_schema_zero_rows_are_noops():
    """Row padding (val all-zero) must produce no update (alpha gating)."""
    uidx, val, labels = _mk(seed=3)
    val[B // 2:] = 0.0
    mask = jnp.ones(L, dtype=bool)
    st_full = C.init_state(L, D, confidence=True)
    st_half = C.init_state(L, D, confidence=True)
    st_full = C.train_batch_schema(st_full, jnp.asarray(uidx),
                                   jnp.asarray(val), jnp.asarray(labels),
                                   mask, 1.0, method="AROW")
    st_half = C.train_batch_schema(
        st_half, jnp.asarray(uidx), jnp.asarray(val[: B // 2]),
        jnp.asarray(labels[: B // 2]), mask, 1.0, method="AROW")
    np.testing.assert_allclose(np.asarray(st_full.dw), np.asarray(st_half.dw),
                               rtol=1e-5, atol=1e-6)


def test_single_label_no_rival_matches_parallel():
    uidx, val, _ = _mk(seed=4)
    mask = jnp.array([True, False, False])
    labels = np.zeros(B, np.int32)
    st_a = C.init_state(L, D, confidence=True)
    st_b = C.init_state(L, D, confidence=True)
    tiled = jnp.asarray(np.broadcast_to(uidx, (B, K)).copy())
    st_a = C.train_batch_parallel(st_a, tiled, jnp.asarray(val),
                                  jnp.asarray(labels), mask, 1.0,
                                  method="AROW")
    st_b = C.train_batch_schema(st_b, jnp.asarray(uidx), jnp.asarray(val),
                                jnp.asarray(labels), mask, 1.0, method="AROW")
    np.testing.assert_allclose(np.asarray(st_a.dw), np.asarray(st_b.dw),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_a.dprec),
                               np.asarray(st_b.dprec), rtol=2e-4, atol=1e-5)
