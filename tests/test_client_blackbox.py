"""Black-box cluster tests (≙ client_test/*.cpp driven by jubatest env
vars, SURVEY.md §4 tier 6).

Run against ANY live deployment — standalone server, cluster member, or
proxy — selected entirely by environment variables, exactly like the
reference's harness (client_test/util.hpp:24-55):

    JUBATUS_HOST=127.0.0.1 JUBATUS_PORT=9199 JUBATUS_CLUSTER_NAME=c1 \\
        python -m pytest tests/test_client_blackbox.py -q

Skipped when JUBATUS_HOST/JUBATUS_PORT are unset (CI runs the in-process
suites instead). Standalone vs cluster switches on an empty cluster name
(util.hpp:52-54). JUBATUS_ENGINE picks the engine under test (default
classifier).
"""

from __future__ import annotations

import os
import uuid

import pytest

from jubatus_tpu.client import CLIENT_CLASSES, Datum

HOST = os.environ.get("JUBATUS_HOST", "")
PORT = int(os.environ.get("JUBATUS_PORT", "0") or 0)
NAME = os.environ.get("JUBATUS_CLUSTER_NAME", "")
ENGINE = os.environ.get("JUBATUS_ENGINE", "classifier")
TIMEOUT = float(os.environ.get("JUBATUS_TIMEOUT", "10"))

pytestmark = pytest.mark.skipif(
    not HOST or not PORT,
    reason="set JUBATUS_HOST/JUBATUS_PORT to run black-box cluster tests",
)


@pytest.fixture()
def client():
    c = CLIENT_CLASSES[ENGINE](HOST, PORT, NAME, timeout=TIMEOUT)
    yield c
    c.close()


def test_get_config_is_json(client):
    import json

    conf = json.loads(client.get_config())
    assert isinstance(conf, dict)


def test_get_status_shape(client):
    st = client.get_status()
    assert st, "empty status map"
    for node, entries in st.items():
        assert "_" in node  # "<ip>_<port>"
        assert "uptime" in entries


def test_save_returns_path_map(client):
    model_id = f"bb_{uuid.uuid4().hex[:8]}"
    paths = client.save(model_id)
    assert paths and all(model_id in p for p in paths.values())


@pytest.mark.skipif(ENGINE != "classifier", reason="classifier-only flow")
def test_classifier_train_classify_roundtrip(client):
    """≙ client_test/classifier_test.cpp:26-66 train/classify round trip."""
    lab_a, lab_b = f"a_{uuid.uuid4().hex[:6]}", f"b_{uuid.uuid4().hex[:6]}"
    n = client.train([[lab_a, Datum({"bbx": 1.0})],
                      [lab_b, Datum({"bbx": -1.0})]])
    assert n == 2
    labels = client.get_labels()
    assert lab_a in labels and lab_b in labels
    (res,) = client.classify([Datum({"bbx": 1.0})])
    assert {lab for lab, _ in res} >= {lab_a, lab_b}
