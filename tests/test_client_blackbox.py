"""Black-box cluster tests (≙ client_test/*.cpp driven by jubatest env
vars, SURVEY.md §4 tier 6).

Run against ANY live deployment — standalone server, cluster member, or
proxy — selected entirely by environment variables, exactly like the
reference's harness (client_test/util.hpp:24-55):

    JUBATUS_HOST=127.0.0.1 JUBATUS_PORT=9199 JUBATUS_CLUSTER_NAME=c1 \\
        python -m pytest tests/test_client_blackbox.py -q

Skipped when JUBATUS_HOST/JUBATUS_PORT are unset (CI runs the in-process
suites instead). Standalone vs cluster switches on an empty cluster name
(util.hpp:52-54). JUBATUS_ENGINE picks the engine under test (default
classifier).
"""

from __future__ import annotations

import os
import uuid

import pytest

from jubatus_tpu.client import CLIENT_CLASSES, Datum

HOST = os.environ.get("JUBATUS_HOST", "")
PORT = int(os.environ.get("JUBATUS_PORT", "0") or 0)
NAME = os.environ.get("JUBATUS_CLUSTER_NAME", "")
ENGINE = os.environ.get("JUBATUS_ENGINE", "classifier")
TIMEOUT = float(os.environ.get("JUBATUS_TIMEOUT", "10"))

pytestmark = pytest.mark.skipif(
    not HOST or not PORT,
    reason="set JUBATUS_HOST/JUBATUS_PORT to run black-box cluster tests",
)


@pytest.fixture()
def client():
    c = CLIENT_CLASSES[ENGINE](HOST, PORT, NAME, timeout=TIMEOUT)
    yield c
    c.close()


def test_get_config_is_json(client):
    import json

    conf = json.loads(client.get_config())
    assert isinstance(conf, dict)


def test_get_status_shape(client):
    st = client.get_status()
    assert st, "empty status map"
    for node, entries in st.items():
        assert "_" in node  # "<ip>_<port>"
        assert "uptime" in entries


def test_save_returns_path_map(client):
    model_id = f"bb_{uuid.uuid4().hex[:8]}"
    paths = client.save(model_id)
    assert paths and all(model_id in p for p in paths.values())


@pytest.mark.skipif(ENGINE != "classifier", reason="classifier-only flow")
def test_classifier_train_classify_roundtrip(client):
    """≙ client_test/classifier_test.cpp:26-66 train/classify round trip."""
    lab_a, lab_b = f"a_{uuid.uuid4().hex[:6]}", f"b_{uuid.uuid4().hex[:6]}"
    n = client.train([[lab_a, Datum({"bbx": 1.0})],
                      [lab_b, Datum({"bbx": -1.0})]])
    assert n == 2
    labels = client.get_labels()
    assert lab_a in labels and lab_b in labels
    (res,) = client.classify([Datum({"bbx": 1.0})])
    assert {lab for lab, _ in res} >= {lab_a, lab_b}


@pytest.mark.skipif(ENGINE != "regression", reason="regression-only flow")
def test_regression_train_estimate_roundtrip(client):
    """≙ client_test/regression_test.cpp train/estimate round trip."""
    n = client.train([[2.0, Datum({"bbx": 1.0, "bbb": 1.0})],
                      [0.0, Datum({"bbx": -1.0, "bbb": 1.0})]])
    assert n == 2
    (est,) = client.estimate([Datum({"bbx": 1.0, "bbb": 1.0})])
    assert isinstance(est, float)


@pytest.mark.skipif(ENGINE != "recommender", reason="recommender-only flow")
def test_recommender_row_roundtrip(client):
    """≙ client_test/recommender_test.cpp update/similar/decode."""
    rid = f"bb_{uuid.uuid4().hex[:8]}"
    assert client.update_row(rid, Datum({"bbx": 1.0, "bby": 0.5}))
    assert rid in client.get_all_rows()
    sim = client.similar_row_from_id(rid, 5)
    assert any(r == rid for r, _ in sim)
    decoded = Datum.from_msgpack(client.decode_row(rid))
    assert dict(decoded.num_values)["bbx"] == 1.0
    assert client.clear_row(rid)


@pytest.mark.skipif(ENGINE != "nearest_neighbor",
                    reason="nearest_neighbor-only flow")
def test_nearest_neighbor_row_roundtrip(client):
    """≙ client_test/nearest_neighbor_test.cpp set/neighbor round trip."""
    rid = f"bb_{uuid.uuid4().hex[:8]}"
    assert client.set_row(rid, Datum({"bbx": 1.0, "bby": -1.0}))
    assert rid in client.get_all_rows()
    near = client.neighbor_row_from_id(rid, 5)
    assert any(r == rid for r, _ in near)


@pytest.mark.skipif(ENGINE != "anomaly", reason="anomaly-only flow")
def test_anomaly_add_score_roundtrip(client):
    """≙ client_test/anomaly_test.cpp add/calc_score."""
    rid, score = client.add(Datum({"bbx": 0.0, "bby": 0.0}))
    assert rid
    s = client.calc_score(Datum({"bbx": 0.1, "bby": 0.0}))
    assert isinstance(s, float)
    assert rid in client.get_all_rows()


@pytest.mark.skipif(ENGINE != "stat", reason="stat-only flow")
def test_stat_push_aggregates(client):
    """≙ client_test/stat_test.cpp push/sum/max/min."""
    key = f"bb_{uuid.uuid4().hex[:8]}"
    for v in (1.0, 2.0, 3.0):
        assert client.push(key, v)
    assert client.sum(key) == 6.0
    assert client.max(key) == 3.0
    assert client.min(key) == 1.0


@pytest.mark.skipif(ENGINE != "clustering", reason="clustering-only flow")
def test_clustering_push_revision(client):
    """≙ client_test/clustering_test.cpp push/get_revision."""
    before = client.get_revision()
    pts = [[f"bb_{uuid.uuid4().hex[:6]}_{i}", Datum({"bbx": float(i % 3)})]
           for i in range(12)]
    assert client.push(pts)
    assert client.get_revision() >= before


@pytest.mark.skipif(ENGINE != "graph", reason="graph-only flow")
def test_graph_node_edge_roundtrip(client):
    """≙ client_test/graph_test.cpp node/edge lifecycle."""
    a = client.create_node()
    b = client.create_node()
    assert client.update_node(a, {"side": "l"})
    assert client.update_node(b, {"side": "r"})
    # edge wire shape: [property map, source, target] (graph.idl:38-42)
    eid = client.create_edge(a, [{"w": "1"}, a, b])
    assert eid is not None  # 0 is a valid first edge id
    edge = client.get_edge(a, eid)
    assert edge[1] == a and edge[2] == b
    node = client.get_node(a)
    assert node  # [properties, in_edges, out_edges]
    assert client.remove_node(b)
