"""Clustering, burst, and graph engine tests (API parity with
clustering.idl / burst.idl / graph.idl; kernels checked on separable data)."""

import jax.numpy as jnp
import numpy as np
import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.models import BurstDriver, ClusteringDriver, GraphDriver
from jubatus_tpu.models.clustering import NotClusteredError
from jubatus_tpu.ops import clustering as cops
from jubatus_tpu.parallel import LocalMixGroup

CONV = {
    "string_rules": [{"key": "*", "type": "str", "sample_weight": "bin",
                      "global_weight": "bin"}],
    "num_rules": [{"key": "*", "type": "num"}],
}


# ---------------------------------------------------------------------------
# clustering kernels
# ---------------------------------------------------------------------------
def _three_blobs(rng, n_per=30):
    centers = np.array([[0.0, 0.0], [10.0, 0.0], [0.0, 10.0]], np.float32)
    pts = np.concatenate([
        c + rng.normal(scale=0.5, size=(n_per, 2)).astype(np.float32)
        for c in centers
    ])
    return pts, centers


def test_kmeans_recovers_blobs(rng):
    x, true_centers = _three_blobs(rng)
    w = np.ones(len(x), np.float32)
    centers, assign = cops.kmeans_fit(jnp.asarray(x), jnp.asarray(w), k=3, seed=1)
    centers = np.asarray(centers)
    # every true center has a fitted center within 1.0
    for tc in true_centers:
        assert np.min(np.linalg.norm(centers - tc, axis=1)) < 1.0
    # assignment is consistent within blobs
    a = np.asarray(assign)
    for b in range(3):
        blob = a[b * 30:(b + 1) * 30]
        assert (blob == np.bincount(blob).argmax()).mean() > 0.9


def test_gmm_recovers_blobs(rng):
    x, true_centers = _three_blobs(rng)
    w = np.ones(len(x), np.float32)
    state, assign = cops.gmm_fit(jnp.asarray(x), jnp.asarray(w), k=3, seed=0)
    means = np.asarray(state.means)
    for tc in true_centers:
        assert np.min(np.linalg.norm(means - tc, axis=1)) < 1.0
    assert np.asarray(state.pi).sum() == pytest.approx(1.0, abs=1e-5)


def test_dbscan_labels_blobs_and_noise(rng):
    x, _ = _three_blobs(rng)
    x = np.vstack([x, np.array([[100.0, 100.0]], np.float32)])  # noise point
    w = np.ones(len(x), np.float32)
    labels = np.asarray(cops.dbscan_fit(jnp.asarray(x), jnp.asarray(w), 2.0,
                                        min_core_point=3))
    assert labels[-1] == -1  # isolated point = noise
    # three distinct clusters among the blobs
    blob_labels = {int(np.bincount(labels[i*30:(i+1)*30][labels[i*30:(i+1)*30] >= 0]).argmax())
                   for i in range(3)}
    assert len({l for l in labels[:90] if l >= 0}) >= 3 or len(blob_labels) == 3


# ---------------------------------------------------------------------------
# clustering engine
# ---------------------------------------------------------------------------
def _push_blobs(d, rng, n_per=20):
    i = 0
    pts = []
    for cx, cy in [(0, 0), (30, 0), (0, 30)]:
        for _ in range(n_per):
            pts.append((f"p{i}", Datum({"x": cx + float(rng.normal()),
                                        "y": cy + float(rng.normal())})))
            i += 1
    d.push(pts)


def test_clustering_kmeans_engine(rng):
    cfg = {"converter": CONV, "method": "kmeans",
           "parameter": {"k": 3, "seed": 0},
           "compressor_method": "simple",
           "compressor_parameter": {"bucket_size": 60}}
    d = ClusteringDriver(cfg, dim_bits=12)
    with pytest.raises(NotClusteredError):
        d.get_k_center()
    assert d.get_revision() == 0
    _push_blobs(d, rng)
    assert d.get_revision() == 1
    centers = d.get_k_center()
    assert len(centers) == 3
    near = d.get_nearest_center(Datum({"x": 30.0, "y": 0.0}))
    nv = dict(near.num_values)
    assert nv["x"] == pytest.approx(30.0, abs=2.0)
    members = d.get_nearest_members_light(Datum({"x": 0.0, "y": 30.0}))
    ids = {rid for _, rid in members}
    assert ids & {f"p{i}" for i in range(40, 60)}
    core = d.get_core_members()
    assert sum(len(c) for c in core) == 60
    d.clear()
    assert d.get_revision() == 0


def test_clustering_dbscan_engine(rng):
    cfg = {"converter": CONV, "method": "dbscan",
           "parameter": {"eps": 3.0, "min_core_point": 3},
           "compressor_method": "simple",
           "compressor_parameter": {"bucket_size": 60}}
    d = ClusteringDriver(cfg, dim_bits=12)
    _push_blobs(d, rng)
    centers = d.get_k_center()
    assert len(centers) >= 3


def test_clustering_compressive_caps_points(rng):
    cfg = {"converter": CONV, "method": "kmeans",
           "parameter": {"k": 2, "seed": 0},
           "compressor_method": "compressive",
           "compressor_parameter": {"bucket_size": 20,
                                    "compressed_bucket_size": 30}}
    d = ClusteringDriver(cfg, dim_bits=12)
    for batch in range(5):
        d.push([(f"b{batch}_{i}", Datum({"x": float(rng.normal(batch * 5))}))
                for i in range(20)])
    st = d.get_status()
    assert st["num_points"] <= 30
    # total weight is conserved through downsampling
    total_w = sum(w for mem in d.get_core_members_light() for w, _ in mem)
    assert total_w == pytest.approx(100.0)


def test_clustering_mix_replicates_points(rng):
    cfg = {"converter": CONV, "method": "kmeans",
           "parameter": {"k": 2, "seed": 0},
           "compressor_method": "simple",
           "compressor_parameter": {"bucket_size": 10}}
    a = ClusteringDriver(cfg, dim_bits=12)
    b = ClusteringDriver(cfg, dim_bits=12)
    a.push([(f"a{i}", Datum({"x": float(i)})) for i in range(5)])
    b.push([(f"b{i}", Datum({"x": float(100 + i)})) for i in range(5)])
    LocalMixGroup([a, b]).mix()
    assert a.get_status()["num_points"] == 10
    assert b.get_status()["num_points"] == 10


def test_clustering_save_load(rng):
    cfg = {"converter": CONV, "method": "kmeans",
           "parameter": {"k": 2, "seed": 0},
           "compressor_method": "simple",
           "compressor_parameter": {"bucket_size": 10}}
    d = ClusteringDriver(cfg, dim_bits=12)
    d.push([(f"p{i}", Datum({"x": float(i % 2 * 50)})) for i in range(10)])
    d2 = ClusteringDriver(cfg, dim_bits=12)
    d2.unpack(d.pack())
    assert d2.get_revision() == d.get_revision()
    assert len(d2.get_k_center()) == 2


# ---------------------------------------------------------------------------
# burst engine
# ---------------------------------------------------------------------------
BURST_CFG = {"parameter": {"window_batch_size": 5, "batch_interval": 10,
                           "max_reuse_batch_num": 5, "costcut_threshold": -1,
                           "result_window_rotate_size": 5}}


def test_burst_detects_burst_window():
    b = BurstDriver(BURST_CFG)
    assert b.add_keyword("fire", scaling_param=2.0, gamma=1.0)
    assert not b.add_keyword("fire", scaling_param=2.0, gamma=1.0)
    # 5 batches of 20 docs; background keyword rate 10%, batch 3 bursts at 90%
    docs = []
    for batch in range(5):
        for i in range(20):
            relevant = (i < 18) if batch == 3 else (i < 2)
            docs.append((batch * 10 + 0.5,
                         "fire alarm" if relevant else "calm day"))
    assert b.add_documents(docs) == 100
    win = b.get_result("fire")
    assert win["start_pos"] == 0.0
    assert len(win["batches"]) == 5
    assert win["batches"][3]["relevant_data_count"] == 18
    assert win["batches"][3]["burst_weight"] > 0
    assert win["batches"][0]["burst_weight"] == 0.0
    allres = b.get_all_bursted_results()
    assert "fire" in allres
    kws = b.get_all_keywords()
    assert kws[0]["keyword"] == "fire"


def test_burst_result_at_and_remove():
    b = BurstDriver(BURST_CFG)
    b.add_keyword("x", 2.0, 1.0)
    b.add_documents([(p, "x") for p in range(0, 100, 2)])
    win = b.get_result_at("x", 45.0)
    assert win["start_pos"] == 0.0
    win2 = b.get_result_at("x", 95.0)
    assert win2["start_pos"] == 50.0
    assert b.remove_keyword("x")
    with pytest.raises(KeyError):
        b.get_result("x")
    b.add_keyword("y", 2.0, 1.0)
    b.remove_all_keywords()
    assert b.get_all_keywords() == []


def test_burst_mix_merges_broadcast_counts():
    """Documents are BROADCAST to every replica (burst.idl routing), so
    replicas hold duplicate counts and the mix is a max-merge — counts
    must converge, never double (the reference's keep-the-larger-window
    mixable semantics)."""
    a = BurstDriver(BURST_CFG)
    b = BurstDriver(BURST_CFG)
    for d in (a, b):
        d.add_keyword("k", 2.0, 1.0)
    docs = [(5.0, "k here")] * 3 + [(5.0, "nothing")] * 2
    a.add_documents(docs)
    b.add_documents(docs)
    LocalMixGroup([a, b]).mix()
    for d in (a, b):
        last = d.get_result("k")["batches"][-1]
        assert last["all_data_count"] == 5
        assert last["relevant_data_count"] == 3
    # idempotent: a second mix must not change anything
    LocalMixGroup([a, b]).mix()
    assert a.get_result("k")["batches"][-1]["all_data_count"] == 5
    # a replica that missed part of the broadcast (late joiner) back-fills
    c = BurstDriver(BURST_CFG)
    c.add_keyword("k", 2.0, 1.0)
    c.add_documents(docs[:2])
    LocalMixGroup([a, c]).mix()
    last = c.get_result("k")["batches"][-1]
    assert last["all_data_count"] == 5
    assert last["relevant_data_count"] == 3


def test_burst_assignment_partitions_processing():
    """With a CHT assignment installed, a replica counts only its own
    keywords; reassignment drops the moved keyword's counts and the next
    mix back-fills the new owner (burst_serv.cpp:225-239, 264-290)."""
    a = BurstDriver(BURST_CFG)
    b = BurstDriver(BURST_CFG)
    for d in (a, b):
        d.add_keyword("k1", 2.0, 1.0)
        d.add_keyword("k2", 2.0, 1.0)
    a.set_assignment(lambda kw: kw == "k1")
    b.set_assignment(lambda kw: kw == "k2")
    docs = [(5.0, "k1 and k2 both")] * 4
    a.add_documents(docs)
    b.add_documents(docs)
    assert a._rel_d["k1"] and not a._rel_d.get("k2")
    assert b._rel_d["k2"] and not b._rel_d.get("k1")
    # each owner answers for its keyword; the other holds no counts
    assert a.get_result("k1")["batches"][-1]["relevant_data_count"] == 4
    assert b.get_result("k2")["batches"][-1]["relevant_data_count"] == 4
    LocalMixGroup([a, b]).mix()
    # partitioning survives the mix: non-owners still hold nothing
    assert not a._rel_m.get("k2") and not b._rel_m.get("k1")
    # membership change: k2 moves to a; counts back-fill at the next mix
    a.set_assignment(lambda kw: True)
    b.set_assignment(lambda kw: kw == "k2")
    LocalMixGroup([a, b]).mix()
    assert a.get_result("k2")["batches"][-1]["relevant_data_count"] == 4


def test_burst_save_load():
    b = BurstDriver(BURST_CFG)
    b.add_keyword("k", 2.0, 1.0)
    b.add_documents([(5.0, "k")] * 5)
    b2 = BurstDriver(BURST_CFG)
    b2.unpack(b.pack())
    assert b2.get_result("k")["batches"][-1]["relevant_data_count"] == 5


# ---------------------------------------------------------------------------
# graph engine
# ---------------------------------------------------------------------------
GRAPH_CFG = {"method": "graph_wo_index",
             "parameter": {"damping_factor": 0.9, "landmark_num": 5}}
EMPTY_Q = ([], [])


def _diamond():
    """a -> b -> d, a -> c -> d plus a hub z pointed at by everyone."""
    g = GraphDriver(GRAPH_CFG)
    ids = {}
    for name in "abcdz":
        ids[name] = g.create_node()
        g.update_node(ids[name], {"name": name})
    for s, t in [("a", "b"), ("b", "d"), ("a", "c"), ("c", "d"),
                 ("a", "z"), ("b", "z"), ("c", "z"), ("d", "z")]:
        g.create_edge(ids[s], ids[s], ids[t])
    return g, ids


def test_graph_crud_and_get_node_edge():
    g, ids = _diamond()
    node = g.get_node(ids["a"])
    assert node["property"] == {"name": "a"}
    assert len(node["out_edges"]) == 3
    eid = node["out_edges"][0]
    e = g.get_edge(ids["a"], eid)
    assert e["source"] == ids["a"]
    g.update_edge(ids["a"], eid, {"w": "2"})
    assert g.get_edge(ids["a"], eid)["property"] == {"w": "2"}
    assert g.remove_edge(ids["a"], eid)
    assert len(g.get_node(ids["a"])["out_edges"]) == 2
    assert g.remove_node(ids["b"])
    with pytest.raises(KeyError):
        g.get_node(ids["b"])
    # edges touching b are gone
    assert all(ids["b"] not in (e[0], e[1]) for e in
               [(s, t) for (s, t, _) in g.edges.values()])


def test_graph_pagerank_centrality():
    g, ids = _diamond()
    g.add_centrality_query(EMPTY_Q)
    g.update_index()
    z = g.get_centrality(ids["z"], 0, EMPTY_Q)
    a = g.get_centrality(ids["a"], 0, EMPTY_Q)
    assert z > a  # everyone points at z
    with pytest.raises(ValueError):
        g.get_centrality(ids["z"], 0, ([], [("name", "a")]))


def test_graph_shortest_path_bounded():
    g, ids = _diamond()
    g.add_shortest_path_query(EMPTY_Q)
    path = g.get_shortest_path(ids["a"], ids["d"], 10, EMPTY_Q)
    assert path[0] == ids["a"] and path[-1] == ids["d"]
    assert len(path) == 3
    assert g.get_shortest_path(ids["d"], ids["a"], 10, EMPTY_Q) == []
    assert g.get_shortest_path(ids["a"], ids["d"], 1, EMPTY_Q) == []


def test_graph_preset_query_filters():
    g = GraphDriver(GRAPH_CFG)
    n1, n2, n3 = (g.create_node() for _ in range(3))
    g.update_node(n1, {"kind": "x"})
    g.update_node(n2, {"kind": "x"})
    g.update_node(n3, {"kind": "y"})
    g.create_edge(n1, n1, n2, {"rel": "f"})
    g.create_edge(n2, n2, n3, {"rel": "f"})
    q = ([], [("kind", "x")])
    g.add_shortest_path_query(q)
    # n3 filtered out -> no path to it
    assert g.get_shortest_path(n1, n3, 5, q) == []
    assert g.get_shortest_path(n1, n2, 5, q) == [n1, n2]


def test_graph_internal_rpcs_and_mix():
    a = GraphDriver(GRAPH_CFG)
    b = GraphDriver(GRAPH_CFG)
    assert a.create_node_here("100")
    a.update_node("100", {"k": "v"})
    nb = b.create_node()
    b.update_node(nb, {"k2": "v2"})
    LocalMixGroup([a, b]).mix()
    assert "100" in a.nodes and "100" in b.nodes
    assert b.nodes["100"] == {"k": "v"}
    assert nb in a.nodes
    # node created after mix gets an id that doesn't collide with "100"
    fresh = a.create_node()
    assert int(fresh) > 100


def test_graph_save_load():
    g, ids = _diamond()
    g.add_centrality_query(EMPTY_Q)
    g2 = GraphDriver(GRAPH_CFG)
    g2.unpack(g.pack())
    assert g2.get_node(ids["a"])["property"] == {"name": "a"}
    assert len(g2.edges) == len(g.edges)
    g2.update_index()
    # same scores as the pre-save graph
    assert g2.get_centrality(ids["z"], 0, EMPTY_Q) == pytest.approx(
        g.get_centrality(ids["z"], 0, EMPTY_Q))
