"""Ops tooling tests (≙ the reference's jubavisor_test + manual CLI flows).

jubaconfig/jubaconv run fully in-process; the jubavisor/jubactl integration
boots a REAL visor which forks a REAL server subprocess (the reference's
process-level test tier, clustering_test.cpp fork_process pattern).
"""

from __future__ import annotations

import io
import json
import sys
import time

import pytest

from jubatus_tpu.cmd import jubaconfig, jubaconv
from jubatus_tpu.coord import create_coordinator, membership

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- jubaconfig ---------------------------------------------------------------


def test_jubaconfig_roundtrip(tmp_path, capsys):
    conf_file = tmp_path / "conf.json"
    conf_file.write_text(json.dumps(CONF))
    coord_dir = str(tmp_path / "coord")
    base = ["-z", coord_dir, "-t", "classifier", "-n", "c1"]
    assert jubaconfig.main(["-c", "write", "-f", str(conf_file)] + base) == 0
    assert jubaconfig.main(["-c", "read"] + base) == 0
    out = capsys.readouterr().out
    assert '"method": "PA"' in out
    assert jubaconfig.main(["-c", "list", "-z", coord_dir]) == 0
    assert "classifier/c1" in capsys.readouterr().out
    assert jubaconfig.main(["-c", "delete"] + base) == 0
    assert jubaconfig.main(["-c", "read"] + base) == 1  # gone


def test_jubaconfig_rejects_bad_json(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    rc = jubaconfig.main(["-c", "write", "-f", str(bad), "-z",
                          str(tmp_path / "coord"), "-t", "classifier", "-n", "x"])
    assert rc == 1


def test_jubaconfig_rejects_unknown_engine(tmp_path):
    f = tmp_path / "ok.json"
    f.write_text("{}")
    rc = jubaconfig.main(["-c", "write", "-f", str(f), "-z",
                          str(tmp_path / "coord"), "-t", "nonsense", "-n", "x"])
    assert rc == 1


def test_jubaconfig_rejects_semantically_bad_config(tmp_path, capsys):
    """Valid JSON, known engine, but the driver refuses it (the dry-
    construct validation jubaconfig.cpp does via jsonconfig)."""
    f = tmp_path / "bad.json"
    f.write_text(json.dumps({"method": "WARP_DRIVE", "converter": {}}))
    rc = jubaconfig.main(["-c", "write", "-f", str(f), "-z",
                          str(tmp_path / "coord"), "-t", "classifier",
                          "-n", "x"])
    assert rc == 1
    assert "rejected" in capsys.readouterr().err


# -- jubaconv -----------------------------------------------------------------


def test_jubaconv_json_to_datum():
    out = io.StringIO()
    rc = jubaconv.main(["-o", "datum"],
                       stdin=io.StringIO('{"user": "alice", "age": 31, '
                                         '"tags": ["a", "b"], '
                                         '"meta": {"ok": true}}'),
                       stdout=out)
    assert rc == 0
    doc = json.loads(out.getvalue())
    assert ["user", "alice"] in doc["string_values"]
    assert ["tags[0]", "a"] in doc["string_values"]
    assert ["age", 31.0] in doc["num_values"]
    assert ["meta/ok", 1.0] in doc["num_values"]


def test_jubaconv_datum_to_fv(tmp_path):
    conf = tmp_path / "conv.json"
    conf.write_text(json.dumps(CONF))
    out = io.StringIO()
    rc = jubaconv.main(["-i", "datum", "-o", "fv", "-c", str(conf)],
                       stdin=io.StringIO('{"num_values": [["x", 2.0]]}'),
                       stdout=out)
    assert rc == 0
    assert "x" in out.getvalue()
    assert "2" in out.getvalue()


def test_jubaconv_fv_requires_conf():
    rc = jubaconv.main(["-o", "fv"], stdin=io.StringIO("{}"),
                       stdout=io.StringIO())
    assert rc == 1


# -- jubavisor + jubactl (process-level integration) --------------------------


@pytest.mark.slow
def test_visor_spawns_and_jubactl_controls(tmp_path):
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.cmd.jubavisor import Jubavisor

    coord_dir = str(tmp_path / "coord")
    conf_file = tmp_path / "conf.json"
    conf_file.write_text(json.dumps(CONF))
    assert jubaconfig.main(["-c", "write", "-f", str(conf_file),
                            "-z", coord_dir, "-t", "classifier", "-n", "v1"]) == 0

    visor = Jubavisor(coord_dir, port=0, max_children=3,
                      logfile=str(tmp_path / "children.log"))
    visor.start(0)
    try:
        view = create_coordinator(coord_dir)
        # jubactl start → visor spawns one real server subprocess
        rc = jubactl.main(["-c", "start", "-t", "classifier",
                           "-s", "jubaclassifier", "-n", "v1", "-N", "1",
                           "-z", coord_dir, "-S", "1000000", "-I", "1000000000",
                           "-D", str(tmp_path)])
        assert rc == 0
        assert visor.status() == {"jubaclassifier/v1": [visor.port + 1]}
        # wait for the child to boot and register (jax import is slow)
        deadline = time.time() + 60
        while time.time() < deadline:
            if membership.get_all_nodes(view, "classifier", "v1"):
                break
            time.sleep(0.5)
        nodes = membership.get_all_nodes(view, "classifier", "v1")
        assert len(nodes) == 1, "server child never registered"

        # train through it, then jubactl save
        from jubatus_tpu.client import ClassifierClient, Datum

        with ClassifierClient(nodes[0].host, nodes[0].port, "v1",
                              timeout=30.0) as c:
            assert c.train([["pos", Datum({"x": 1.0})]]) == 1
        assert jubactl.main(["-c", "save", "-t", "classifier", "-n", "v1",
                             "-z", coord_dir, "-i", "snap"]) == 0
        saved = list(tmp_path.glob("*_classifier_snap.jubatus"))
        assert len(saved) == 1

        # jubactl status shows the node
        assert jubactl.main(["-c", "status", "-t", "classifier", "-n", "v1",
                             "-z", coord_dir]) == 0

        # jubactl stop → visor kills the child, port recycled
        assert jubactl.main(["-c", "stop", "-t", "classifier",
                             "-s", "jubaclassifier", "-n", "v1",
                             "-z", coord_dir]) == 0
        assert visor.status() == {}
        view.close()
    finally:
        visor.stop()


# -- jubactl restore (durable model plane, ISSUE 18) --------------------------


def test_jubactl_restore_point_in_time(tmp_path):
    """`jubactl -c restore` drives every registered member through the
    store_restore RPC: the model rewinds to the newest store snapshot
    at-or-before --at (default latest), and a malformed --at is a
    usage error, not a crash."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator=coord_dir,
                        name="v1", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0,
                        store_dir=str(tmp_path / "store"),
                        store_interval=30.0))
    srv.start(0)
    try:
        with ClassifierClient("127.0.0.1", srv.rpc.port, "v1",
                              timeout=30.0) as c:
            assert c.train([["pos", Datum({"x": 1.0})],
                            ["neg", Datum({"x": -1.0})]]) == 2
        # snapshot the model into the store, then train PAST it: the
        # restore must visibly rewind to the snapshot moment
        srv.store_uploader.tick(srv.driver, int(srv.driver.update_count))
        probe = Datum({"x": 0.5})
        at_snapshot = srv.driver.classify([probe])
        with ClassifierClient("127.0.0.1", srv.rpc.port, "v1",
                              timeout=30.0) as c:
            c.train([["neg", Datum({"x": 1.0})]] * 8)
        assert srv.driver.classify([probe]) != at_snapshot
        assert jubactl.main(["-c", "restore", "-t", "classifier",
                             "-n", "v1", "-z", coord_dir]) == 0
        assert srv.driver.classify([probe]) == at_snapshot
        assert srv.rpc.trace.counters().get("store.restores", 0) == 1
        # malformed --at: usage error before any RPC goes out
        assert jubactl.main(["-c", "restore", "-t", "classifier",
                             "-n", "v1", "-z", coord_dir,
                             "--at", "yesterday"]) == 1
        assert srv.rpc.trace.counters().get("store.restores", 0) == 1
    finally:
        srv.stop()
