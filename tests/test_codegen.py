"""Codegen tests: parse the REAL reference .idl files and cross-validate the
checked-in routing table (framework/idl.py SERVICES) against them — the
parity check that replaces the reference's build-time jenerator step.
"""

from __future__ import annotations

import os

import pytest

from jubatus_tpu.codegen import (
    emit_python_client,
    emit_rst,
    emit_service_table,
    parse_idl,
    to_methods,
)
from jubatus_tpu.codegen.parser import parse_reference_idls
from jubatus_tpu.framework.idl import SERVICES

REFERENCE_IDL_DIR = "/root/reference/jubatus/server/server"

needs_reference = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_IDL_DIR), reason="reference tree not mounted"
)

SAMPLE = """
message labeled_datum {
  0: string label
  1: datum data
}

service classifier {
  #- doc line
  #@random #@nolock #@pass
  int train(0: list<labeled_datum> data)

  #@cht(1) #@update #@all_and
  bool push(0: string key, 1: double value)

  #@cht #@analysis #@pass
  map<string, ulong> get_labels()

  #@broadcast #@update #@all_and
  bool clear()
}
"""


def test_parse_sample():
    idl = parse_idl(SAMPLE)
    assert [m.name for m in idl.messages] == ["labeled_datum"]
    assert idl.messages[0].fields[1].type == "datum"
    svc = idl.service("classifier")
    train, push, get_labels, clear = svc.methods
    assert (train.routing, train.lock, train.aggregator) == ("random", "nolock", "pass")
    assert train.return_type == "int"
    assert train.args[0].type == "list<labeled_datum>"
    assert (push.routing, push.cht_n) == ("cht", 1)
    assert get_labels.cht_n == 2  # bare #@cht defaults to 2
    assert get_labels.return_type == "map<string, ulong>"
    assert (clear.routing, clear.aggregator) == ("broadcast", "all_and")


def test_message_alias():
    idl = parse_idl('message node("jubatus::core::graph::node_info") {\n'
                    "  0: string prop\n}\n")
    assert idl.messages[0].alias == "jubatus::core::graph::node_info"


def test_to_methods_and_emit():
    idl = parse_idl(SAMPLE)
    methods = to_methods(idl.service("classifier"))
    assert methods[0].name == "train"
    assert methods[1].routing == "cht"
    table = emit_service_table(idl.service("classifier"))
    assert '"classifier": (' in table
    assert '_m("push", ("key", "value"), CHT, 1' in table


def test_emit_python_client_compiles():
    idl = parse_idl(SAMPLE)
    src = emit_python_client(idl, "classifier")
    ns: dict = {}
    exec(compile(src, "<generated>", "exec"), ns)  # noqa: S102 — own output
    cls = ns["ClassifierClient"]
    assert cls.ENGINE == "classifier"
    assert hasattr(cls, "train") and hasattr(cls, "clear")


def test_emit_rst_includes_docs():
    idl = parse_idl(
        "service s {\n"
        "  #- Trains the thing.\n"
        "  #@random #@nolock #@pass\n"
        "  int train(0: string x)\n"
        "}\n"
    )
    assert idl.service("s").methods[0].docs == ["Trains the thing."]
    rst = emit_rst(idl, "s")
    assert ".. function:: int train(string x)" in rst
    assert ":routing: random" in rst
    assert "Trains the thing." in rst


@needs_reference
def test_emit_rst_all_reference_services():
    for engine, idl in parse_reference_idls(REFERENCE_IDL_DIR).items():
        rst = emit_rst(idl, engine)
        assert f"{engine} API" in rst
        assert ".. function::" in rst


# -- parity with the reference ------------------------------------------------


@needs_reference
def test_all_reference_idls_parse():
    idls = parse_reference_idls(REFERENCE_IDL_DIR)
    assert set(idls) == set(SERVICES)
    for engine, idl in idls.items():
        assert idl.service(engine).methods, engine


@needs_reference
def test_checked_in_table_matches_reference_idls():
    """Every method in framework/idl.py must match the reference .idl:
    same name set, same arity, same routing class, same cht fan-out, same
    aggregator. (Lock decorators intentionally differ: our table records
    model-lock semantics, the IDL's #@nolock is an RPC-layer detail.)"""
    idls = parse_reference_idls(REFERENCE_IDL_DIR)
    mismatches = []
    for engine, methods in SERVICES.items():
        ref = {d.name: d for d in idls[engine].service(engine).methods}
        ours = {m.name: m for m in methods}
        if set(ref) != set(ours):
            mismatches.append(f"{engine}: methods {set(ref) ^ set(ours)}")
            continue
        for name, d in ref.items():
            m = ours[name]
            if len(d.args) != len(m.args):
                mismatches.append(f"{engine}.{name}: arity {len(d.args)} != {len(m.args)}")
            if d.routing != m.routing:
                mismatches.append(f"{engine}.{name}: routing {d.routing} != {m.routing}")
            if d.routing == "cht" and d.cht_n != m.cht_n:
                mismatches.append(f"{engine}.{name}: cht_n {d.cht_n} != {m.cht_n}")
            if d.routing in ("broadcast", "cht") and d.aggregator != m.aggregator:
                mismatches.append(
                    f"{engine}.{name}: agg {d.aggregator} != {m.aggregator}")
    assert not mismatches, "\n".join(mismatches)
