"""C++ client codegen tests (≙ jenerator cpp.ml client backend, SURVEY.md §2.7).

Three tiers:
  1. every reference .idl generates a client header that *compiles* (g++);
  2. the embedded msgpack codec round-trips against the Python msgpack lib;
  3. a compiled C++ driver binary runs a full train/classify/save/load
     session against a live EngineServer over the wire (the strongest
     cross-language parity check: reference clients are C++ too).
"""

from __future__ import annotations

import os
import shutil
import subprocess

import msgpack
import pytest

from jubatus_tpu.codegen.emit_cpp import emit_cpp_client, runtime_header
from jubatus_tpu.codegen.parser import parse_reference_idls

REFERENCE_IDL_DIR = "/root/reference/jubatus/server/server"

gxx = shutil.which("g++")
pytestmark = pytest.mark.skipif(gxx is None, reason="g++ not available")


def _write_files(tmp_path, files):
    for fn, src in files.items():
        (tmp_path / fn).write_text(src)


@pytest.fixture(scope="module")
def idls():
    if not os.path.isdir(REFERENCE_IDL_DIR):
        pytest.skip("reference IDLs not present")
    return parse_reference_idls(REFERENCE_IDL_DIR)


def test_all_engines_generate_and_compile(idls, tmp_path):
    for engine, idl in idls.items():
        files = emit_cpp_client(idl, engine)
        assert f"{engine}_client.hpp" in files
        assert "jubatus_tpu_client.hpp" in files
        _write_files(tmp_path, files)
        r = subprocess.run(
            [gxx, "-std=c++11", "-fsyntax-only", "-Wall", "-Wextra",
             "-x", "c++", str(tmp_path / f"{engine}_client.hpp")],
            capture_output=True, text=True)
        assert r.returncode == 0, f"{engine}: {r.stderr[:2000]}"


def test_generated_client_mirrors_reference_api(idls):
    src = emit_cpp_client(idls["classifier"], "classifier")["classifier_client.hpp"]
    # the reference's generated surface (classifier_client.hpp:19-60)
    assert "namespace classifier {" in src
    assert "class classifier : public jubatus_tpu::client::common::client" in src
    for method in ("train", "classify", "get_labels", "set_label", "clear",
                   "delete_label"):
        assert f" {method}(" in src
    assert "struct estimate_result" in src
    assert "struct labeled_datum" in src


def test_msgpack_codec_roundtrip(tmp_path):
    """The embedded C++ codec must agree byte-level with python-msgpack:
    C++ packs a torture-test value; Python unpacks it; Python packs it
    back; C++ parses that and re-packs to the identical bytes."""
    (tmp_path / "jubatus_tpu_client.hpp").write_text(runtime_header())
    main = r"""
#include "jubatus_tpu_client.hpp"
#include <cstdio>
using namespace jubatus_tpu;
int main(int argc, char** argv) {
  if (argc > 1 && std::string(argv[1]) == "reencode") {
    std::string in, chunk;
    char buf[4096]; size_t n;
    while ((n = fread(buf, 1, sizeof(buf), stdin)) > 0) in.append(buf, n);
    size_t pos = 0; mp::value v;
    if (!mp::parse(in, pos, v) || pos != in.size()) return 2;
    // the no-alloc completeness scan must agree with the real parser
    size_t spos = 0;
    if (!mp::skip(in, spos) || spos != pos) return 3;
    std::string out; mp::pack(out, v);
    fwrite(out.data(), 1, out.size(), stdout);
    return 0;
  }
  bool legacy = (argc > 1 && std::string(argv[1]) == "legacy");
  mp::value v = mp::v_arr();
  v.a.push_back(mp::v_nil());
  v.a.push_back(mp::v_bool(true));
  v.a.push_back(mp::v_int(-7));
  v.a.push_back(mp::v_int(-300));
  v.a.push_back(mp::v_int(-70000));
  v.a.push_back(mp::v_uint(0));
  v.a.push_back(mp::v_uint(200));
  v.a.push_back(mp::v_uint(70000));
  v.a.push_back(mp::v_uint(1ULL << 40));
  v.a.push_back(mp::v_double(3.25));
  v.a.push_back(mp::v_str("hello"));
  v.a.push_back(mp::v_str(std::string(300, 'x')));
  v.a.push_back(mp::v_bin(std::string("\x00\x01\xff", 3)));
  mp::value m = mp::v_map();
  m.m.push_back(std::make_pair(mp::v_str("k"), mp::v_int(1)));
  v.a.push_back(m);
  std::string out; mp::pack(out, v, legacy);
  fwrite(out.data(), 1, out.size(), stdout);
  return 0;
}
"""
    (tmp_path / "codec.cpp").write_text(main)
    exe = tmp_path / "codec"
    r = subprocess.run([gxx, "-std=c++11", "-O0", "-o", str(exe),
                        str(tmp_path / "codec.cpp")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[:2000]

    blob = subprocess.run([str(exe)], capture_output=True).stdout
    decoded = msgpack.unpackb(blob, raw=False, strict_map_key=False)
    assert decoded[:11] == [None, True, -7, -300, -70000, 0, 200, 70000,
                            1 << 40, 3.25, "hello"]
    assert decoded[11] == "x" * 300
    assert decoded[12] == b"\x00\x01\xff"
    assert decoded[13] == {"k": 1}

    # Python → C++ → bytes must survive (C++ parse of foreign encodings)
    py_blob = msgpack.packb(decoded, use_bin_type=True)
    r2 = subprocess.run([str(exe), "reencode"], input=py_blob,
                        capture_output=True)
    assert r2.returncode == 0
    assert msgpack.unpackb(r2.stdout, raw=False) == decoded

    # legacy mode (reference servers' pre-2.0 msgpack): no str8/bin type
    # bytes anywhere — with this controlled payload none can occur in data
    legacy = subprocess.run([str(exe), "legacy"], capture_output=True).stdout
    for forbidden in (0xd9, 0xc4, 0xc5, 0xc6):
        assert bytes([forbidden]) not in legacy, hex(forbidden)
    relaxed = msgpack.unpackb(legacy, raw=True, strict_map_key=False)
    assert relaxed[10] == b"hello"          # strings arrive as raw
    assert relaxed[12] == b"\x00\x01\xff"   # binary arrives as raw too


CPP_SESSION = r"""
#include "classifier_client.hpp"
#include <cassert>
#include <cstdio>
#include <cstdlib>
using namespace jubatus_tpu;
using classifier::labeled_datum;
using classifier::estimate_result;

int main(int argc, char** argv) {
  assert(argc == 3);
  int port = atoi(argv[1]);
  classifier::client::classifier c("127.0.0.1", port, "cpp_e2e", 10.0);

  // train two separable classes
  std::vector<labeled_datum> batch;
  for (int i = 0; i < 50; ++i) {
    labeled_datum pos, neg;
    pos.label = "pos";
    pos.data.add_number("x", 1.0 + 0.01 * i).add_string("tag", "p");
    neg.label = "neg";
    neg.data.add_number("x", -1.0 - 0.01 * i).add_string("tag", "n");
    batch.push_back(pos);
    batch.push_back(neg);
  }
  int64_t trained = c.train(batch);
  assert(trained == 100);

  std::vector<datum> queries;
  datum q1, q2;
  q1.add_number("x", 0.9).add_string("tag", "p");
  q2.add_number("x", -0.9).add_string("tag", "n");
  queries.push_back(q1);
  queries.push_back(q2);
  std::vector<std::vector<estimate_result> > res = c.classify(queries);
  assert(res.size() == 2);
  std::string best1, best2;
  double s1 = -1e30, s2 = -1e30;
  for (size_t j = 0; j < res[0].size(); ++j)
    if (res[0][j].score > s1) { s1 = res[0][j].score; best1 = res[0][j].label; }
  for (size_t j = 0; j < res[1].size(); ++j)
    if (res[1][j].score > s2) { s2 = res[1][j].score; best2 = res[1][j].label; }
  assert(best1 == "pos");
  assert(best2 == "neg");

  std::map<std::string, uint64_t> labels = c.get_labels();
  assert(labels.size() == 2);
  assert(labels.count("pos") == 1 && labels.count("neg") == 1);

  assert(c.set_label("extra"));
  labels = c.get_labels();
  assert(labels.size() == 3);
  assert(c.delete_label("extra"));

  // built-ins over the common base
  std::string conf = c.get_config();
  assert(conf.find("AROW") != std::string::npos);
  std::map<std::string, std::string> saved = c.save(argv[2]);
  assert(saved.size() == 1);
  assert(c.load(argv[2]));
  std::map<std::string, std::map<std::string, std::string> > st = c.get_status();
  assert(st.size() == 1);
  assert(st.begin()->second.count("uptime") == 1);

  // error taxonomy: unknown method must throw, connection must survive
  bool threw = false;
  try {
    c.get_client().call("no_such_method", std::vector<mp::value>());
  } catch (const rpc_error&) {
    threw = true;
  }
  assert(threw);
  assert(c.do_mix() == false);  // standalone: no-op

  printf("CPP_E2E_OK\n");
  return 0;
}
"""


@pytest.mark.slow
def test_cpp_client_end_to_end(idls, tmp_path):
    from jubatus_tpu.server import EngineServer

    conf = {
        "method": "AROW",
        "parameter": {"regularization_weight": 1.0},
        "converter": {
            "string_rules": [{"key": "*", "type": "str",
                              "sample_weight": "bin", "global_weight": "bin"}],
            "num_rules": [{"key": "*", "type": "num"}],
        },
    }
    _write_files(tmp_path, emit_cpp_client(idls["classifier"], "classifier"))
    (tmp_path / "session.cpp").write_text(CPP_SESSION)
    exe = tmp_path / "session"
    r = subprocess.run(
        [gxx, "-std=c++11", "-O0", "-I", str(tmp_path), "-o", str(exe),
         str(tmp_path / "session.cpp")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[:3000]

    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer("classifier", conf,
                       args=ServerArgs(engine="classifier", datadir=str(tmp_path)))
    port = srv.start(0)
    try:
        run = subprocess.run([str(exe), str(port), "cppmodel"],
                             capture_output=True, text=True, timeout=60)
        assert run.returncode == 0, f"stdout={run.stdout}\nstderr={run.stderr}"
        assert "CPP_E2E_OK" in run.stdout
    finally:
        srv.stop()
