"""Ruby/Java/Go client emitter tests (≙ jenerator's 5-language client
output, SURVEY.md §2.7 — C++ and Python are covered by their own test
files; these three are structurally validated: every engine IDL emits a
client with all RPC methods, message types, and balanced block structure."""

from __future__ import annotations

import os
import re
import subprocess
import sys

import pytest

from jubatus_tpu.codegen.emit_clients import (
    emit_go_client,
    emit_java_client,
    emit_ruby_client,
)
from jubatus_tpu.codegen.parser import parse_reference_idls

REFERENCE_IDL_DIR = "/root/reference/jubatus/server/server"


@pytest.fixture(scope="module")
def idls():
    if not os.path.isdir(REFERENCE_IDL_DIR):
        pytest.skip("reference IDLs not present")
    return parse_reference_idls(REFERENCE_IDL_DIR)


def _camel(name):
    return "".join(p.title() for p in name.split("_"))


def test_ruby_clients_all_engines(idls):
    for engine, idl in idls.items():
        files = emit_ruby_client(idl, engine)
        src = files[f"{engine}_client.rb"]
        assert "jubatus_common" in src
        for d in idl.service(engine).methods:
            assert f"def {d.name}(" in src or f"def {d.name}\n" in src, \
                f"{engine}.{d.name} missing"
        for msg in idl.messages:
            assert f"{_camel(msg.name)} = Struct.new(" in src
        # block structure: every do/def/module/class closes
        opens = len(re.findall(
            r"^\s*(?:module|class|def)\b|\bdo\b\s*$", src, re.M))
        ends = len(re.findall(r"^\s*end\b", src, re.M))
        assert opens == ends, f"{engine}: {opens} opens vs {ends} ends"


def test_ruby_common_runtime_is_selfcontained(idls):
    common = emit_ruby_client(idls["stat"], "stat")["jubatus_common.rb"]
    assert 'require "msgpack"' in common
    assert "class ClientBase" in common
    for builtin in ("get_config", "save", "load", "get_status", "do_mix"):
        assert builtin in common


def test_java_clients_all_engines(idls):
    for engine, idl in idls.items():
        files = emit_java_client(idl, engine)
        cls = f"{_camel(engine)}Client"
        src = files[f"{cls}.java"]
        assert f"public class {cls} extends ClientBase" in src
        assert src.count("{") == src.count("}"), f"{engine}: unbalanced braces"
        for msg in idl.messages:
            # one PUBLIC top-level class per file, or user code can't name
            # the types that appear in the client's public signatures
            msrc = files[f"{_camel(msg.name)}.java"]
            assert f"public class {_camel(msg.name)}" in msrc
            assert "@Message" in msrc
            assert msrc.count("{") == msrc.count("}")
        # common runtime classes ship alongside
        common = ("ClientBase.java", "Datum.java", "Tuple.java",
                  "TupleTemplate.java")
        for fn in common:
            assert fn in files
            assert files[fn].count("{") == files[fn].count("}")
        # typed decoding goes through explicit msgpack Templates
        assert "callTyped(" in src
        assert "Class.class" not in src
        assert "getProxyStatus" in files["ClientBase.java"]


def test_go_clients_all_engines(idls):
    for engine, idl in idls.items():
        files = emit_go_client(idl, engine)
        src = files[f"{engine}_client.go"]
        assert "package jubatus_tpu" in src
        assert src.count("{") == src.count("}"), f"{engine}: unbalanced braces"
        cls = f"{_camel(engine)}Client"
        assert f"type {cls} struct" in src
        assert f"func New{cls}(" in src
        for d in idl.service(engine).methods:
            assert f"func (c *{cls}) {_camel(d.name)}(" in src
        for msg in idl.messages:
            assert f"type {_camel(msg.name)} struct" in src
            assert 'msgpack:",as_array"' in src
        assert "client.go" in files


def test_java_reserved_message_name_rejected():
    """A message whose camel-cased name collides with a runtime file must
    error loudly instead of silently clobbering Datum.java et al."""
    from jubatus_tpu.codegen.parser import parse_idl

    idl = parse_idl(
        "message datum {\n  0: string x\n}\n"
        "service foo {\n  #@random #@nolock #@pass\n  bool ping()\n}\n")
    with pytest.raises(ValueError, match="collides"):
        emit_java_client(idl, "foo")


def test_cli_lang_flag_writes_files(idls, tmp_path):
    idl_path = os.path.join(REFERENCE_IDL_DIR, "classifier.idl")
    for lang, expect in (("cpp", "classifier_client.hpp"),
                        ("ruby", "classifier_client.rb"),
                        ("go", "classifier_client.go"),
                        ("java", "ClassifierClient.java")):
        out = tmp_path / lang
        r = subprocess.run(
            [sys.executable, "-m", "jubatus_tpu.codegen", idl_path,
             "--client", "classifier", "--lang", lang, "--out", str(out)],
            capture_output=True, text=True,
            cwd="/root/repo")
        assert r.returncode == 0, r.stderr[:1500]
        assert (out / expect).exists()


# -- toolchain-gated checks (ADVICE round 1: structural validation alone
# -- lets type-mapping bugs ship; compile/parse when the tools exist, like
# -- the g++-gated C++ client tests) -----------------------------------------

def _which(tool):
    import shutil

    return shutil.which(tool)


@pytest.mark.skipif(not _which("gofmt"), reason="gofmt not installed")
def test_go_clients_parse_with_gofmt(idls, tmp_path):
    """gofmt -e is a full Go parser (no dependency resolution needed):
    any syntax error in the emitted source fails loudly."""
    for engine, idl in idls.items():
        for fn, src in emit_go_client(idl, engine).items():
            p = tmp_path / f"{engine}_{fn}"
            p.write_text(src)
            r = subprocess.run(["gofmt", "-e", "-l", str(p)],
                               capture_output=True, text=True)
            assert r.returncode == 0 and not r.stderr, \
                f"{engine}/{fn}: {r.stderr[:1500]}"


@pytest.mark.skipif(not _which("go"), reason="go toolchain not installed")
def test_go_clients_vet(idls, tmp_path):
    """go vet over a throwaway module; needs the msgpack dependency to be
    resolvable (vendored or cached) — skips cleanly when it is not."""
    mod = tmp_path / "vetmod"
    mod.mkdir()
    (mod / "go.mod").write_text("module vetcheck\n\ngo 1.20\n")
    for fn, src in emit_go_client(idls["stat"], "stat").items():
        (mod / fn).write_text(src)
    env = {**os.environ, "GOFLAGS": "-mod=mod"}
    # dependency resolution is an environment property, not a codegen
    # property: if the msgpack module can't be fetched/tidied (offline,
    # GOPROXY=off, empty cache), skip rather than fail
    dl = subprocess.run(["go", "mod", "tidy"], cwd=mod,
                        capture_output=True, text=True, env=env)
    if dl.returncode != 0:
        pytest.skip(f"go deps unresolvable offline: {dl.stderr[:200]}")
    r = subprocess.run(["go", "vet", "./..."], cwd=mod,
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr[:2000]


@pytest.mark.skipif(
    not (_which("javac") and os.environ.get("JUBATUS_TPU_JAVA_CLASSPATH")),
    reason="javac + JUBATUS_TPU_JAVA_CLASSPATH (msgpack jars) required")
def test_java_clients_compile(idls, tmp_path):
    """javac with the msgpack-java/msgpack-rpc jars on the classpath
    (point JUBATUS_TPU_JAVA_CLASSPATH at them); catches type-mapping
    errors structural checks cannot."""
    srcdir = tmp_path / "java"
    for engine, idl in idls.items():
        d = srcdir / engine / "us" / "jubatus_tpu" / "common"
        d.mkdir(parents=True, exist_ok=True)
        for fn, src in emit_java_client(idl, engine).items():
            (d / fn).write_text(src)
        files = [str(p) for p in d.glob("*.java")]
        r = subprocess.run(
            ["javac", "-cp", os.environ["JUBATUS_TPU_JAVA_CLASSPATH"],
             "-d", str(tmp_path / "classes"), *files],
            capture_output=True, text=True)
        assert r.returncode == 0, f"{engine}: {r.stderr[:2000]}"
