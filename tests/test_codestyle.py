"""Style gate (≙ tools/codestyle cpplint pre-commit hook): the repo's own
mechanical checker must pass clean over all Python sources."""

from __future__ import annotations

import pathlib
import subprocess
import sys


def test_codestyle_clean():
    repo = pathlib.Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "tools" / "codestyle" / "check.py")],
        capture_output=True, text=True, cwd=str(repo))
    assert r.returncode == 0, \
        f"style problems:\n{r.stdout[-4000:]}\n{r.stderr[-2000:]}"
