"""Style gate (≙ tools/codestyle cpplint pre-commit hook): the repo's own
mechanical checker must pass clean over all Python sources."""

from __future__ import annotations

import pathlib
import subprocess
import sys


def test_codestyle_clean():
    repo = pathlib.Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "tools" / "codestyle" / "check.py")],
        capture_output=True, text=True, cwd=str(repo))
    assert r.returncode == 0, \
        f"style problems:\n{r.stdout[-4000:]}\n{r.stderr[-2000:]}"


def test_host_cast_gate_fires_and_pragma_opts_out(tmp_path):
    """The parallel/ host-cast rule (ISSUE 6): a host-side numpy dtype
    cast in a collective hot path is flagged; the # host-cast-ok pragma
    and jnp (device) casts are not."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "parallel"
    d.mkdir(parents=True)
    bad = d / "victim.py"
    bad.write_text(
        '"""doc."""\n'
        "import numpy as np\n"
        "x = a.astype(np.float16)\n"                       # flagged
        "y = a.astype(ml_dtypes.bfloat16)\n"               # flagged
        "z = a.astype(np.float16)  # host-cast-ok - tiny\n"  # pragma
        "w = a.astype(jnp.bfloat16)\n",                    # device cast
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    cast_hits = [p for p in problems if "host-side numpy dtype cast" in p]
    assert len(cast_hits) == 2, problems
    assert ":3:" in cast_hits[0] and ":4:" in cast_hits[1]


def test_full_gather_gate_fires_and_pragma_opts_out(tmp_path):
    """The parallel/+models/ full-gather rule (ISSUE 13): a full-matrix
    jax.device_get / process_allgather of sharded leaves in a
    sharded-layout hot path is flagged; the # full-gather-ok pragma and
    per-shard chunk reads are not."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    for sub in ("parallel", "models"):
        d = tmp_path / "jubatus_tpu" / sub
        d.mkdir(parents=True)
        bad = d / "victim.py"
        bad.write_text(
            '"""doc."""\n'
            "import jax\n"
            "x = jax.device_get(state.w)\n"                       # flagged
            "y = multihost_utils.process_allgather(state.w)\n"    # flagged
            "z = jax.device_get(tot)  # full-gather-ok - total\n"  # pragma
            "w = sharded_model.shard_chunks(state.dw)\n",   # per-shard path
            encoding="utf-8")
        problems = codestyle.check_file(str(bad))
        hits = [p for p in problems if "full-matrix device_get" in p]
        assert len(hits) == 2, problems
        assert ":3:" in hits[0] and ":4:" in hits[1]
    # outside the gated dirs the rule stays silent
    other = tmp_path / "jubatus_tpu" / "framework"
    other.mkdir(parents=True)
    ok = other / "fine.py"
    ok.write_text('"""doc."""\nimport jax\nx = jax.device_get(a)\n',
                  encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(ok))
                if "full-matrix device_get" in p]


def test_full_scan_gate_fires_and_pragma_opts_out(tmp_path):
    """The ANN query-path rule (ISSUE 16): an arena-wide distance sweep
    inside an ivf module is flagged; the # full-scan-ok pragma and the
    candidate-only rescore kernels are not, and non-ivf modules are
    exempt."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "ops"
    d.mkdir(parents=True)
    bad = d / "ivf_extra.py"
    bad.write_text(
        '"""doc."""\n'
        "from jubatus_tpu.ops import knn\n"
        "a = knn._hamming_distances_batch_xla(q, rows, hash_num=64)\n"  # hit
        "b = knn.cosine_scores(ri, rv, qd)\n"                           # hit
        "c = sharded_distances(mesh, q, rows)\n"                        # hit
        "d = knn.cosine_scores(ri, rv, qd)  # full-scan-ok - probe\n"
        "e = candidate_sig_distances(qs, cand, method=m, hash_num=h)\n",
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    hits = [p for p in problems if "arena-wide distance sweep" in p]
    assert len(hits) == 3, problems
    assert ":3:" in hits[0] and ":4:" in hits[1] and ":5:" in hits[2]
    # the same sweep OUTSIDE an ivf module stays legal (it IS the
    # exact path there)
    ok = d / "knn_like.py"
    ok.write_text(
        '"""doc."""\n'
        "a = knn.cosine_scores(ri, rv, qd)\n", encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(ok))
                if "arena-wide distance sweep" in p]


def test_quality_coverage_gate_fires_and_pragma_opts_out(tmp_path):
    """The server/ train-registration rule (ISSUE 17): a function that
    registers a "train" handler without referencing the quality
    recorder is flagged; routing through the _quality_observe_* helpers
    (or server.quality) and the # no-quality pragma are not, and files
    outside server/ are exempt."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "server"
    d.mkdir(parents=True)
    bad = d / "victim.py"
    bad.write_text(
        '"""doc."""\n'
        "def _bind_bad(server, rpc):\n"
        "    rpc.register(\"train\", lambda n, d: 0, arity=2)\n"  # flagged
        "def _bind_raw_bad(server, rpc):\n"
        "    rpc.register_raw(\"train\", h)\n"                    # flagged
        "def _bind_ok(server, rpc):\n"
        "    def train(name, data):\n"
        "        _quality_observe_pairs(server, data)\n"
        "        return 0\n"
        "    rpc.register(\"train\", train, arity=2)\n"           # routed
        "def _bind_pragma(server, rpc):\n"
        "    rpc.register(\"train\", h, arity=2)"
        "  # no-quality - scored upstream\n",
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    hits = [p for p in problems if "quality-recorder" in p]
    assert len(hits) == 2, problems
    assert ":3:" in hits[0] and ":5:" in hits[1]
    # outside server/ the rule stays silent
    other = tmp_path / "jubatus_tpu" / "framework"
    other.mkdir(parents=True)
    ok = other / "fine.py"
    ok.write_text(
        '"""doc."""\n'
        "def _bind(rpc):\n"
        "    rpc.register(\"train\", h, arity=2)\n", encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(ok))
                if "quality-recorder" in p]


def test_usage_coverage_gate_fires_and_pragma_opts_out(tmp_path):
    """The server/ train/classify-registration rule (ISSUE 19): a
    function that registers a "train" or "classify" handler without
    referencing the usage recorder is flagged; routing through
    server.usage (or any usage-named helper) and the # no-usage pragma
    are not, and files outside server/ are exempt."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "server"
    d.mkdir(parents=True)
    bad = d / "victim.py"
    bad.write_text(
        '"""doc."""\n'
        "def _bind_bad(server, rpc):\n"
        "    rpc.register(\"classify\", lambda n, d: 0, arity=2)\n"  # hit
        "def _bind_raw_bad(server, rpc):\n"
        "    rpc.register_raw(\"train\", h)\n"                       # hit
        "def _bind_ok(server, rpc):\n"
        "    co.usage_hook = _usage_batch_hook(server, \"train\")\n"
        "    rpc.register(\"train\", h, arity=2)\n"                  # billed
        "def _bind_pragma(server, rpc):\n"
        "    rpc.register(\"classify\", h, arity=2)"
        "  # no-usage - span-billed\n",
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    hits = [p for p in problems if "usage-recorder" in p]
    assert len(hits) == 2, problems
    assert ":3:" in hits[0] and ":5:" in hits[1]
    # outside server/ the rule stays silent
    other = tmp_path / "jubatus_tpu" / "framework"
    other.mkdir(parents=True)
    ok = other / "fine.py"
    ok.write_text(
        '"""doc."""\n'
        "def _bind(rpc):\n"
        "    rpc.register(\"classify\", h, arity=2)\n", encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(ok))
                if "usage-recorder" in p]


def test_store_crc_gate_fires_and_pragma_opts_out(tmp_path):
    """The model-store write rule (ISSUE 18): a backend put/put_blob
    site in a model_store module whose enclosing function shows no
    envelope evidence is flagged; pack_envelope/read_envelope in the
    function and the # no-crc pragma are not, and files without
    model_store in the name are exempt."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "framework"
    d.mkdir(parents=True)
    bad = d / "model_store_extra.py"
    bad.write_text(
        '"""doc."""\n'
        "def unstamped(self, key, data):\n"
        "    self.backend.put(key, data)\n"                       # flagged
        "def unstamped_blob(self, blob):\n"
        "    return self.put_blob(blob, kind=\"full\")\n"         # flagged
        "def stamped(self, system, payload):\n"
        "    blob = pack_envelope(system, payload)\n"
        "    self.backend.put(self._key(), blob)\n"               # stamped
        "def verified(self, blob):\n"
        "    read_envelope(blob, \"store:full\")\n"
        "    self.backend.put(self._key(), blob)\n"               # verified
        "def pragma(self, blob):\n"
        "    self.put_blob(blob)  # no-crc - stamped by caller\n",
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    hits = [p for p in problems if "CRC-envelope" in p]
    assert len(hits) == 2, problems
    assert ":3:" in hits[0] and ":5:" in hits[1]
    # the same write OUTSIDE a model_store module stays legal (dict
    # .put()-alikes, queue puts, unrelated backends)
    ok = d / "row_store.py"
    ok.write_text(
        '"""doc."""\n'
        "def write(self, key, data):\n"
        "    self.backend.put(key, data)\n", encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(ok))
                if "CRC-envelope" in p]


def test_metrics_docs_catalog_clean():
    """The metric-catalog gate (ISSUE 7): every literal counter/gauge
    key exported through the tracing registry must appear in the
    OBSERVABILITY.md catalog — codestyle fails on undocumented keys."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "tools" / "check_metrics_docs.py")],
        capture_output=True, text=True, cwd=str(repo))
    assert r.returncode == 0, \
        f"undocumented metric keys:\n{r.stdout[-4000:]}\n{r.stderr[-2000:]}"


def test_knob_gate_fires_and_pragma_opts_out(tmp_path):
    """The tuner-knob rule (ISSUE 20): a hard-coded numeric for a
    tuner-actuated knob (chunk size, coalescer depth, mix cadence)
    inside an actuated module is flagged — it is a second source of
    truth the runtime tuner would silently fight; the # knob-ok pragma,
    reads of the live attribute, and non-gated modules are not."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "framework"
    d.mkdir(parents=True)
    bad = d / "mixer.py"
    bad.write_text(
        '"""doc."""\n'
        "self.chunk_mb = 8.0\n"                                # flagged
        "CHUNK_MB = 4.0\n"                                     # flagged
        "self.interval_sec = 16\n"                             # flagged
        "depth = co.max_batch\n"                               # a read
        "self.chunk_mb = max(0.25, float(v))\n"                # computed
        "self.chunk_mb = 2.0  # knob-ok - compat default\n",   # pragma
        encoding="utf-8")
    problems = codestyle.check_file(str(bad))
    hits = [p for p in problems if "tuner knob constant" in p]
    assert len(hits) == 3, problems
    assert ":2:" in hits[0] and ":3:" in hits[1] and ":4:" in hits[2]
    # the SAME text in a module the tuner does not actuate is clean
    other = tmp_path / "jubatus_tpu" / "framework" / "other.py"
    other.write_text('"""doc."""\nself.chunk_mb = 8.0\n', encoding="utf-8")
    assert not [p for p in codestyle.check_file(str(other))
                if "tuner knob constant" in p]


def test_controller_journal_is_event_covered():
    """The EVENT_SITES gate follows the journal: the shared controller
    core (coord/controller.py) owns the decision-journal append the
    autoscaler used to — the marker must still be registered there and
    the real file must pass (record() emits into the event plane)."""
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    gated = [s for s, _, _ in codestyle.EVENT_SITES]
    assert "jubatus_tpu/coord/controller.py" in gated
    assert "jubatus_tpu/coord/autoscaler.py" not in gated
    real = repo / "jubatus_tpu" / "coord" / "controller.py"
    assert codestyle.check_file(str(real)) == []
