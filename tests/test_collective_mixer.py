"""Collective mixer (VERDICT r1 item 9): the production mix as a device
collective across processes, with the RPC fan-out as fallback.

The real thing needs one jax.distributed world spanning the replica
processes — the multi-process test spawns 3 processes on the CPU backend
(1 virtual device each), each running a full EngineServer with
--mixer collective_mixer over a shared file coordinator, and proves the
diff payload crossed via the psum (collective_rounds == 1, no fallback)
and that every replica converged on the mixed model.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

import pytest

from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NAME = "cm"
CONF = {"method": "PA", "parameter": {"regularization_weight": 1.0},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]}}


def test_world_mismatch_falls_back_to_rpc_mix():
    """Two replicas in ONE process (jax world of 1) cannot span a
    collective — the round must fall back to the RPC mix and still
    produce a correct, converged model."""
    store = _Store()
    servers = []
    for _ in range(2):
        args = ServerArgs(engine="classifier", coordinator="(shared)",
                          name=NAME, listen_addr="127.0.0.1",
                          mixer="collective_mixer",
                          interval_sec=1e9, interval_count=1 << 30)
        s = EngineServer("classifier", CONF, args,
                         coord=MemoryCoordinator(store))
        s.start(0)
        servers.append(s)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        c1 = ClassifierClient("127.0.0.1", servers[1].args.rpc_port, NAME)
        for _ in range(4):
            c0.train([["pos", Datum({"a": 1.0})]])
            c1.train([["neg", Datum({"b": 1.0})]])
        assert c0.do_mix() is True
        st = next(iter(servers[0].get_status().values()))
        assert st["mixer.fallback_rounds"] >= 1
        assert st["mixer.collective_rounds"] == 0
        # both replicas know both labels' features after the fallback mix
        (r1,) = c1.classify([Datum({"a": 1.0})])
        scores = dict(r1)
        assert scores["pos"] > scores["neg"]
        c0.close()
        c1.close()
    finally:
        for s in servers:
            s.stop()


_CHILD = r"""
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port, coord_dir = sys.argv[3], sys.argv[4]
# CPU worlds need the gloo collectives backend or every psum raises
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
assert jax.process_count() == n

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

CONF = {"method": "PA", "parameter": {"regularization_weight": 1.0},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
mode = sys.argv[5] if len(sys.argv) > 5 else "off"
topo = sys.argv[6] if len(sys.argv) > 6 else ""
args = ServerArgs(engine="classifier", coordinator=coord_dir, name="cm",
                  listen_addr="127.0.0.1", mixer="collective_mixer",
                  interval_sec=1e9, interval_count=1 << 30,
                  mix_compress=mode, mix_topology=topo)
srv = EngineServer("classifier", CONF, args)
port = srv.start(0)

# every replica trains a DISJOINT feature; after one collective round all
# replicas must score with everyone's weights
me = f"x{pid}"
c = ClassifierClient("127.0.0.1", port, "cm", timeout=60)
for _ in range(4):
    c.train([["pos", Datum({me: 1.0})], ["neg", Datum({me: -1.0})]])

# wait for full membership
deadline = time.time() + 60
while time.time() < deadline:
    nodes = membership.get_all_nodes(srv.coord, "classifier", "cm")
    if len(nodes) == n:
        break
    time.sleep(0.2)
assert len(membership.get_all_nodes(srv.coord, "classifier", "cm")) == n

if pid == 0:
    time.sleep(1.0)  # let every replica finish its training calls
    out = srv.mixer.mix_now()
    assert out and out.get("collective") is True, out
    if topo:
        # hierarchical round: the master reports the tier shape and the
        # deterministic per-host representative election
        assert out.get("topology") == topo, out
        hosts = int(topo.split("x")[0])
        assert len(out.get("representatives", [])) == hosts, out
    print("MASTER-ROUND", out, flush=True)
else:
    # wait until the master's commit raised our model version
    while time.time() < deadline:
        if srv.mixer.model_version >= 1:
            break
        time.sleep(0.2)
assert srv.mixer.model_version >= 1, "round never applied here"
if pid == 0:
    st = srv.mixer.get_status()
    assert st["collective_rounds"] == 1 and st["fallback_rounds"] == 0, st

# cross-replica knowledge: a feature trained ONLY on another process
other = f"x{(pid + 1) % n}"
(res,) = c.classify([Datum({other: 1.0})])
scores = dict(res)
assert scores["pos"] > 0.0 > scores["neg"], (other, scores)

# flight recorder: every member logged its collective entry with the
# per-phase breakdown, and the record is queryable over the RPC
from jubatus_tpu.rpc.client import RpcClient
with RpcClient("127.0.0.1", port, timeout=30) as hc:
    hist = hc.call("get_mix_history", "cm")
col = [r for r in hist if r.get("mode") == "collective" and r.get("ok")]
assert col, hist
for key in ("ship_ms", "reduce_ms", "readback_ms", "chunks", "quant",
            "wire_mb", "topo"):
    assert key in (col[-1].get("phases") or {}), (key, col[-1])
assert col[-1]["phases"]["quant"] == mode, col[-1]
assert col[-1]["phases"]["topo"] == (topo or "flat"), col[-1]
if topo:
    assert srv.mixer.get_status()["mix_topology"] == topo
c.close()
srv.stop()
print(f"CHILD-{pid}-OK", flush=True)
"""


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["off", "bf16", "int8"])
def test_multiprocess_collective_mix(mode):
    # one harness owns port pick / env scrub / concurrent pipe drain /
    # cleanup for every jax.distributed multi-process launch. bf16/int8
    # exercise --mix-compress: the psum ships compressed diffs, and the
    # cross-replica knowledge assertions prove the compressed totals
    # still train the cluster; the flight record stamps the quant mode
    import bench_mix

    n = 3
    outs, rcs = bench_mix.run_jax_world(
        _CHILD, n, timeout=180, extra_args=(mode,))
    for i, (out, rc) in enumerate(zip(outs, rcs)):
        assert rc == 0, f"child {i} exit {rc}:\n{out[-3000:]}"
        assert f"CHILD-{i}-OK" in out, f"child {i}:\n{out[-3000:]}"
    assert any("MASTER-ROUND" in o for o in outs)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["off", "int8"])
def test_multiprocess_hierarchical_collective_mix(mode):
    """The full production stack over a REAL 4-process world with
    --mix-topology 2x2: every member signs |topo=2x2, the round runs
    the two-tier reduce (topo stamped in the flight record's phases),
    the master reports the per-host representative election, and the
    cross-replica knowledge assertions prove the hierarchical totals
    still train the cluster — in the exact f32 mode and through the
    int8 transport whose residuals live per host."""
    import bench_mix

    n = 4
    outs, rcs = bench_mix.run_jax_world(
        _CHILD, n, timeout=240, extra_args=(mode, "2x2"))
    for i, (out, rc) in enumerate(zip(outs, rcs)):
        assert rc == 0, f"child {i} exit {rc}:\n{out[-3000:]}"
        assert f"CHILD-{i}-OK" in out, f"child {i}:\n{out[-3000:]}"
    assert any("MASTER-ROUND" in o for o in outs)


@pytest.mark.slow
def test_multiprocess_int8_drift_probe():
    """The quantized transport across a REAL 4-process world: every
    replica reads back the identical dequantized totals, multi-round
    drift vs f32 stays small with error feedback, and the no-feedback
    drift is measurably worse (the EF telescoping survives the
    scatter/gather ring, not just the world-of-1 round trip)."""
    import bench_mix

    out = bench_mix.drift_probe(n=4, dim_bits=18, rounds=4)
    assert "collective_round_drift_vs_f32" in out, out
    drift = out["collective_round_drift_vs_f32"]
    noef = out["collective_round_drift_vs_f32_noef"]
    assert 0 < drift < 0.02, out
    assert noef > drift, out
    assert out["collective_wire_mb_per_round"] > 0


def test_prepared_member_discards_stage_without_go(monkeypatch):
    """A member whose round never receives the GO marker must discard its
    staged diff and never enter a collective (code-review: the commit-RPC
    design could wedge live members inside the psum)."""
    import jubatus_tpu.framework.collective_mixer as cm

    monkeypatch.setattr(cm, "GO_WAIT_SEC", 0.4)
    store = _Store()
    # the effective wait is max(GO_WAIT_SEC, 3 * interconnect timeout)
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer", interconnect_timeout=0.1,
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        entered = []
        srv.mixer._enter_collective = \
            lambda *a, **k: entered.append(a) or False
        version, sig = srv.mixer.local_prepare("ghost-round", [])
        assert sig != "unsupported"
        assert "ghost-round" in srv.mixer._staged
        deadline = time.time() + 5
        while time.time() < deadline and srv.mixer._staged:
            time.sleep(0.05)
        assert not srv.mixer._staged, "staged diff not discarded"
        assert not entered, "entered a collective without GO"
        # and an aborted round exits the waiter immediately
        srv.mixer.local_prepare("aborted-round", [])
        assert srv.mixer.local_abort("aborted-round") is True
        assert not srv.mixer._staged
        c.close()
    finally:
        srv.stop()


def test_go_timeout_with_unreadable_coordinator_tears_world_down(monkeypatch):
    """Bounded entry (round-2 advisor): if the GO window expires and the
    coordinator cannot even be READ, peers may be sitting inside the psum
    already — the member must kill its jax world (erroring them out)
    rather than discard silently, and must route later rounds to RPC."""
    import jubatus_tpu.framework.collective_mixer as cm

    monkeypatch.setattr(cm, "GO_WAIT_SEC", 0.4)
    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer", interconnect_timeout=0.1,
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        entered, killed = [], []
        srv.mixer._enter_collective = \
            lambda *a, **k: entered.append(a) or False
        monkeypatch.setattr(srv.mixer, "_kill_world",
                            lambda: killed.append(1) or setattr(
                                srv.mixer, "collective_dead", True))

        def dead_read(path):
            raise RuntimeError("coordinator unreachable")

        monkeypatch.setattr(srv.mixer.comm.coord, "read", dead_read)
        srv.mixer.local_prepare("dark-round", [])
        deadline = time.time() + 5
        while time.time() < deadline and not killed:
            time.sleep(0.05)
        assert killed, "world not torn down on unverifiable GO absence"
        assert not entered
        assert not srv.mixer._staged
        assert srv.mixer.collective_dead
        # later rounds must refuse the collective plane up front
        version, sig = srv.mixer.local_prepare("next-round", [])
        assert sig == "unsupported"
        srv.mixer.local_abort("next-round")
        c.close()
    finally:
        srv.stop()


def test_go_observed_only_at_final_check_still_enters(monkeypatch):
    """Every in-window poll failing but GO being present at the final
    verification read means peers ARE waiting: the member enters late
    instead of discarding."""
    import jubatus_tpu.framework.collective_mixer as cm
    from jubatus_tpu.utils.serialization import pack_obj

    monkeypatch.setattr(cm, "GO_WAIT_SEC", 0.4)
    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer", interconnect_timeout=0.1,
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        entered = []
        srv.mixer._enter_collective = \
            lambda rid, base, *a: entered.append((rid, base)) or True
        go = pack_obj({"rid": "late-round", "base": 7})
        # zero window: the waiter skips straight to the final verification
        # read, which is exactly the path under test
        srv.mixer._go_wait = lambda: 0.0
        monkeypatch.setattr(srv.mixer.comm.coord, "read", lambda p: go)
        srv.mixer.local_prepare("late-round", [])
        deadline = time.time() + 5
        while time.time() < deadline and not entered:
            time.sleep(0.05)
        assert entered and entered[0] == ("late-round", 7)
        assert not srv.mixer.collective_dead
        c.close()
    finally:
        srv.stop()


def test_64bit_diff_signature_stays_bare_unsupported():
    """The '|bf16=N' signature suffix must never decorate the
    'unsupported' SENTINEL: the master's fallback check matches the
    sentinel exactly, and a suffixed one would route a 64-bit round into
    a collective that raises on every member (review r4)."""
    import numpy as np

    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    srv = EngineServer(
        "classifier",
        {"method": "PA", "parameter": {"regularization_weight": 1.0},
         "converter": {"num_rules": [{"key": "*", "type": "num"}]}},
        ServerArgs(engine="classifier", coordinator="(shared)", name="sb",
                   listen_addr="127.0.0.1", mixer="collective_mixer",
                   interval_sec=1e9, interval_count=1 << 30, mix_bf16=True),
        coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        # supported diffs: signature carries the compress flag AND the
        # chunk plan (a mixed-chunk-size cluster would issue mismatched
        # collective sequences and wedge the world)
        from jubatus_tpu.parallel.collective import DEFAULT_CHUNK_MB

        _v, sig = srv.mixer.local_prepare("r1", [])
        assert sig.endswith(f"|bf16=1|chunk={DEFAULT_CHUNK_MB}"), sig
        srv.mixer.local_abort("r1")
        # force a 64-bit leaf into the diff: sentinel must stay bare
        mixable = srv.driver.get_mixables()["classifier"]
        orig = mixable.__class__.get_diff

        def poisoned(self):
            d = orig(self)
            d["poison"] = np.zeros(4, np.float64)
            return d

        import unittest.mock as um
        with um.patch.object(mixable.__class__, "get_diff", poisoned):
            _v, sig = srv.mixer.local_prepare("r2", [])
        assert sig == "unsupported", sig
        srv.mixer.local_abort("r2")
    finally:
        srv.stop()


def test_psum_pytree_phase_instrumentation():
    """psum_pytree(phases=) fills the per-round phase log (VERDICT r4
    item 5): cast/ship/reduce/readback wall times plus payload and
    ring-model wire bytes — and compress=True records HALF the payload
    bytes for f32 leaves (the --mix-bf16 wire claim as arithmetic)."""
    import numpy as np

    from jubatus_tpu.parallel.collective import psum_pytree

    diff = {"w": np.ones((512, 512), np.float32),
            "b": np.arange(32, dtype=np.float32)}
    phases: dict = {}
    total = psum_pytree(diff, phases=phases)
    # world of 1: psum is identity
    np.testing.assert_allclose(total["w"], diff["w"])
    np.testing.assert_allclose(total["b"], diff["b"])
    for k in ("cast_ms", "ship_ms", "reduce_ms", "readback_ms",
              "payload_mb", "wire_mb_ring_model"):
        assert k in phases and phases[k] >= 0.0, (k, phases)
    f32_payload = phases["payload_mb"]
    assert f32_payload == round((512 * 512 + 32) * 4 / 2**20, 2)

    bf16_phases: dict = {}
    total_c = psum_pytree(diff, compress=True, phases=bf16_phases)
    assert total_c["w"].dtype == np.float32  # handed back f32
    np.testing.assert_allclose(total_c["w"], diff["w"], rtol=1e-2)
    assert bf16_phases["payload_mb"] == round(f32_payload / 2, 2)


def test_prepare_signature_per_compress_mode():
    """The three wire modes produce three distinct prepare signatures —
    so a mixed-mode cluster mismatches at prepare and falls back to the
    RPC mix instead of wedging half the world inside a collective it
    built differently. off/bf16 keep the exact legacy "|bf16=N|chunk=M"
    format (old peers interoperate); int8 inserts a "|quant=" component
    no old peer ever produces."""
    from jubatus_tpu.parallel.collective import DEFAULT_CHUNK_MB, QUANT_BLOCK

    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer",
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        sigs = {}
        for mode in ("off", "bf16", "int8"):
            srv.mixer.compress = mode
            _v, sigs[mode] = srv.mixer.local_prepare(f"r-{mode}", [])
            srv.mixer.local_abort(f"r-{mode}")
        assert sigs["off"].endswith(f"|bf16=0|chunk={DEFAULT_CHUNK_MB}")
        assert sigs["bf16"].endswith(f"|bf16=1|chunk={DEFAULT_CHUNK_MB}")
        assert sigs["int8"].endswith(
            f"|bf16=0|quant=int8:{QUANT_BLOCK}|chunk={DEFAULT_CHUNK_MB}")
        assert len(set(sigs.values())) == 3
        # bool compat: True still signs exactly like the bf16 enum
        srv.mixer.compress = True
        _v, sig_bool = srv.mixer.local_prepare("r-bool", [])
        srv.mixer.local_abort("r-bool")
        assert sig_bool == sigs["bf16"]
        c.close()
    finally:
        srv.stop()


def test_topology_rides_prepare_signature():
    """Hierarchical rounds sign their tier shape: a flat member's
    signature is byte-identical to the legacy format (old peers
    interoperate), a topology member appends '|topo=NxM', and distinct
    topologies produce distinct signatures — the master's sig-set check
    then routes a heterogeneous fleet to the RPC mix instead of wedging
    a skewed two-tier collective."""
    store = _Store()
    sigs = {}
    for topo in ("", "2x4", "4x2", "auto"):
        args = ServerArgs(engine="classifier", coordinator="(shared)",
                          name=NAME, listen_addr="127.0.0.1",
                          mixer="collective_mixer",
                          interval_sec=1e9, interval_count=1 << 30,
                          mix_topology=topo)
        srv = EngineServer("classifier", CONF, args,
                           coord=MemoryCoordinator(store))
        srv.start(0)
        try:
            from jubatus_tpu.client import ClassifierClient, Datum

            c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
            c.train([["pos", Datum({"a": 1.0})]])
            _v, sigs[topo] = srv.mixer.local_prepare(f"r-{topo or 'flat'}",
                                                     [])
            srv.mixer.local_abort(f"r-{topo or 'flat'}")
            c.close()
        finally:
            srv.stop()
    from jubatus_tpu.parallel.collective import DEFAULT_CHUNK_MB

    assert sigs[""].endswith(f"|bf16=0|chunk={DEFAULT_CHUNK_MB}")
    assert "|topo=" not in sigs[""]
    assert sigs["2x4"] == sigs[""] + "|topo=2x4"
    assert sigs["4x2"] == sigs[""] + "|topo=4x2"
    # auto on the 8-virtual-device single-process world derives 1x8
    assert sigs["auto"] == sigs[""] + "|topo=1x8"
    assert len(set(sigs.values())) == 4


def test_topology_mismatch_falls_back_to_rpc_mix(monkeypatch):
    """Two members resolving DIFFERENT tier shapes (heterogeneous
    fleet / stale flag) must mismatch at prepare and complete the round
    over the RPC mix. The world-size gate is forced open so the
    signature check is provably what routes the fallback."""
    import jax

    store = _Store()
    servers = []
    for topo in ("2x4", ""):
        args = ServerArgs(engine="classifier", coordinator="(shared)",
                          name=NAME, listen_addr="127.0.0.1",
                          mixer="collective_mixer",
                          interval_sec=1e9, interval_count=1 << 30,
                          mix_topology=topo)
        s = EngineServer("classifier", CONF, args,
                         coord=MemoryCoordinator(store))
        s.start(0)
        servers.append(s)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        c1 = ClassifierClient("127.0.0.1", servers[1].args.rpc_port, NAME)
        for _ in range(4):
            c0.train([["pos", Datum({"a": 1.0})]])
            c1.train([["neg", Datum({"b": 1.0})]])
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        assert c0.do_mix() is True
        st = next(iter(servers[0].get_status().values()))
        assert st["mixer.fallback_rounds"] >= 1
        assert st["mixer.collective_rounds"] == 0
        rec = [r for r in servers[0].mixer.flight.snapshot()
               if r.get("mode") == "collective" and not r.get("ok")]
        assert rec and "prepare_not_viable" in rec[-1]["reason"], rec
        # the fallback still produced a correct converged model
        (r1,) = c1.classify([Datum({"a": 1.0})])
        scores = dict(r1)
        assert scores["pos"] > scores["neg"]
        c0.close()
        c1.close()
    finally:
        for s in servers:
            s.stop()


def test_representative_election_deterministic_and_degraded_stable():
    """elect_representatives derives one front per host from the FULL
    registered member list + topology alone — same inputs, same fronts,
    regardless of round order or which members a degraded round lost —
    and refuses fleets whose member count fits no tier layout."""
    from jubatus_tpu.framework.collective_mixer import elect_representatives
    from jubatus_tpu.parallel.mesh import host_topology

    topo = host_topology(override="2x4")
    names = [f"m{i}:920{i}" for i in range(8)]
    # one process per (host, local) slot: group's first name fronts it
    reps = elect_representatives(names, topo)
    assert reps == {0: "m0:9200", 1: "m4:9204"}
    # list order must not matter (a round that discovers members in a
    # different order cannot reshuffle the wire)
    assert elect_representatives(list(reversed(names)), topo) == reps
    # a degraded round passes the SAME registered list (participation
    # is not an input): election is identical
    assert elect_representatives(names, topo) == reps
    # one process per host (M local devices each)
    assert elect_representatives(names[:2], topo) == \
        {0: "m0:9200", 1: "m1:9201"}
    # no viable layout -> empty (the same fleets that mismatch at
    # prepare); flat -> empty
    assert elect_representatives(names[:5], topo) == {}
    assert elect_representatives(names, None) == {}


def test_status_reports_topology_and_local_devices():
    """jubactl-facing plumbing: get_status carries the resolved tier
    shape and the runtime capabilities (local_devices + derived
    topology) so a fleet is diagnosable BEFORE rounds fall back."""
    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer",
                      interval_sec=1e9, interval_count=1 << 30,
                      mix_topology="2x4")
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        st = srv.mixer.get_status()
        assert st["mix_topology"] == "2x4"
        assert st["mix_caps_local_devices"] == 8
        assert st["mix_caps_topology"] == "1x8"
        assert st["mix_caps_world"] == 1
    finally:
        srv.stop()


def test_unresolvable_topology_degrades_to_flat():
    """A member whose topology cannot resolve (flag asks for more
    devices than the runtime has) must log, stay flat, and sign the
    legacy format — its signature then mismatches correctly-resolved
    hierarchical peers and the round routes to RPC, instead of the
    member crashing at prepare."""
    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer",
                      interval_sec=1e9, interval_count=1 << 30,
                      mix_topology="64x64")
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        _v, sig = srv.mixer.local_prepare("r-big", [])
        srv.mixer.local_abort("r-big")
        assert "|topo=" not in sig
        assert srv.mixer.get_status()["mix_topology"] == "flat"
        c.close()
    finally:
        srv.stop()


def test_ef_residual_survives_failed_collective_entry(monkeypatch):
    """The error-feedback residual advances only on a SUCCESSFUL
    collective entry: a psum that dies (world torn down mid-stream, a
    degraded round, an abort) must leave the residual of the last good
    round intact — otherwise the next round feeds back a corrupted
    error and the unbiasedness guarantee is gone."""
    import jubatus_tpu.parallel.collective as collective

    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer",
                      interval_sec=1e9, interval_count=1 << 30,
                      mix_compress="int8")
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        assert srv.mixer.get_status()["mix_compress"] == "int8"
        ef = collective.ErrorFeedback()
        ef.rounds = 3
        ef.key = ("sentinel",)
        srv.mixer.ef = ef
        # an abort discards the stage without touching the residual
        srv.mixer.local_prepare("r-abort", [])
        assert srv.mixer.local_abort("r-abort") is True
        assert ef.rounds == 3 and ef.key == ("sentinel",)
        # a psum that raises mid-entry leaves it intact too
        def boom(*a, **k):
            raise RuntimeError("world torn down")

        monkeypatch.setattr(collective, "psum_pytree", boom)
        srv.mixer.local_prepare("r-fail", [])
        with pytest.raises(RuntimeError, match="world torn down"):
            srv.mixer._enter_collective("r-fail", 0)
        assert ef.rounds == 3 and ef.key == ("sentinel",)
        c.close()
    finally:
        srv.stop()


def test_set_wire_plan_resigns_prepare_and_stages_plan():
    """The tuner's actuator (ISSUE 20): ``set_wire_plan`` re-signs the
    NEXT prepare with the new ``|chunk=``/``|quant=`` components, and a
    round snapshots its plan at prepare — a plan change landing between
    prepare and GO must not diverge the signed plan from the entered
    plan (the staged snapshot, not the live knob, enters the psum)."""
    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      mixer="collective_mixer",
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, NAME)
        c.train([["pos", Datum({"a": 1.0})]])
        _v, sig_a = srv.mixer.local_prepare("r-a", [])
        srv.mixer.local_abort("r-a")

        st = srv.mixer.set_wire_plan(chunk_mb=2.0, compress="bf16")
        assert st == {"chunk_mb": 2.0, "compress": "bf16",
                      "plan_version": 1}
        _v, sig_b = srv.mixer.local_prepare("r-b", [])
        assert sig_b.endswith("|bf16=1|chunk=2.0"), sig_b
        assert sig_b != sig_a
        staged = srv.mixer._staged["r-b"]["plan"]
        assert staged == {"mode": "bf16", "chunk_mb": 2.0}
        # a plan change BETWEEN prepare and GO leaves the staged round
        # on the plan it signed
        srv.mixer.set_wire_plan(chunk_mb=16.0, compress="int8")
        assert srv.mixer._staged["r-b"]["plan"] == \
            {"mode": "bf16", "chunk_mb": 2.0}
        srv.mixer.local_abort("r-b")
        # ...and the round AFTER it signs the new plan
        from jubatus_tpu.parallel.collective import QUANT_BLOCK

        _v, sig_c = srv.mixer.local_prepare("r-c", [])
        srv.mixer.local_abort("r-c")
        assert sig_c.endswith(
            f"|bf16=0|quant=int8:{QUANT_BLOCK}|chunk=16.0"), sig_c
        # jubactl-facing: the live plan is visible in get_status
        status = srv.mixer.get_status()
        assert status["mix_chunk_mb"] == 16.0
        assert status["mix_plan_version"] == 2
        c.close()
    finally:
        srv.stop()


_CHILD_PLAN_CHANGE = r"""
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port, coord_dir = sys.argv[3], sys.argv[4]
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
assert jax.process_count() == n

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.coord import membership
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

CONF = {"method": "PA", "parameter": {"regularization_weight": 1.0},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
args = ServerArgs(engine="classifier", coordinator=coord_dir, name="cm",
                  listen_addr="127.0.0.1", mixer="collective_mixer",
                  interval_sec=1e9, interval_count=1 << 30)
srv = EngineServer("classifier", CONF, args)
port = srv.start(0)

mark = lambda tag: open(coord_dir.rstrip("/") + "." + tag, "w").close()
def wait_mark(tag, deadline):
    path = coord_dir.rstrip("/") + "." + tag
    while time.time() < deadline:
        if os.path.exists(path):
            return
        time.sleep(0.1)
    raise AssertionError("timed out waiting for " + tag)

me = f"x{pid}"
c = ClassifierClient("127.0.0.1", port, "cm", timeout=60)
for _ in range(4):
    c.train([["pos", Datum({me: 1.0})], ["neg", Datum({me: -1.0})]])

deadline = time.time() + 120
while time.time() < deadline:
    if len(membership.get_all_nodes(srv.coord, "classifier", "cm")) == n:
        break
    time.sleep(0.2)

if pid == 0:
    time.sleep(1.0)  # let every replica finish its training calls
    # round 1: whole fleet on plan A -> collective
    out = srv.mixer.mix_now()
    assert out and out.get("collective") is True, out
    # STAGGERED transition: only the master has applied plan B when
    # round 2 runs -> prepare signatures mismatch -> exactly one
    # RPC-fallback round; the round still completes (never wedges)
    srv.mixer.set_wire_plan(chunk_mb=2.0, compress="bf16")
    out2 = srv.mixer.mix_now()
    assert out2 and not out2.get("collective"), out2
    st = srv.mixer.get_status()
    assert st["collective_rounds"] == 1, st
    assert st["fallback_rounds"] == 1, st
    mark("plan_b")  # now let the stragglers catch up
    for p in range(1, n):
        wait_mark(f"ack{p}", deadline)
    # round 3: whole fleet on plan B -> collective again, under the
    # NEW plan (chunk 2.0, bf16 on the wire)
    out3 = srv.mixer.mix_now()
    assert out3 and out3.get("collective") is True, out3
    st = srv.mixer.get_status()
    assert st["collective_rounds"] == 2, st
    assert st["fallback_rounds"] == 1, st
    recs = srv.mixer.flight.snapshot()
    col_ok = [r for r in recs
              if r.get("mode") == "collective" and r.get("ok")]
    col_bad = [r for r in recs
               if r.get("mode") == "collective" and not r.get("ok")]
    # the one fallback was a clean prepare mismatch, not a failed round
    assert len(col_bad) == 1, recs
    assert "prepare_not_viable" in col_bad[0]["reason"], col_bad
    # the post-change collective really ran the new plan
    ph = col_ok[-1].get("phases") or {}
    assert ph.get("quant") == "bf16", col_ok[-1]
    assert ph.get("chunk_mb") == 2.0, col_ok[-1]
    mark("done")
else:
    wait_mark("plan_b", deadline)
    srv.mixer.set_wire_plan(chunk_mb=2.0, compress="bf16")
    mark(f"ack{pid}")
    wait_mark("done", deadline)
    # both collective rounds applied here (fallback pushed via RPC too)
    assert srv.mixer.model_version >= 2, srv.mixer.model_version

# model stayed correct through the transition: a feature trained ONLY
# on another process scores here
other = f"x{(pid + 1) % n}"
(res,) = c.classify([Datum({other: 1.0})])
scores = dict(res)
assert scores["pos"] > 0.0 > scores["neg"], (other, scores)
c.close()
srv.stop()
print(f"CHILD-{pid}-OK", flush=True)
"""


@pytest.mark.slow
def test_multiprocess_plan_change_coherence():
    """ISSUE 20 acceptance: a wire-plan change rolling through a REAL
    3-process world costs AT MOST one RPC-fallback round. Fleet on plan
    A mixes collectively; the master applies plan B first (staggered) —
    that round mismatches at prepare and completes over the RPC mix
    (never a wedged or failed round); once every member applies B, the
    next round is collective again and its flight record proves the new
    chunk/wire actually hit the psum."""
    import bench_mix

    n = 3
    outs, rcs = bench_mix.run_jax_world(_CHILD_PLAN_CHANGE, n, timeout=240)
    for i, (out, rc) in enumerate(zip(outs, rcs)):
        assert rc == 0, f"child {i} exit {rc}:\n{out[-3000:]}"
        assert f"CHILD-{i}-OK" in out, f"child {i}:\n{out[-3000:]}"
