"""Parity tests for the pipelined mix data plane (parallel/collective.py).

The chunked double-buffered stream must be BIT-identical to the
unchunked path for f32 (chunking only re-tiles the psum, it must never
change the arithmetic) and must keep the established bf16 contract under
``compress=True``. World of 1 (psum = identity) keeps the tests
single-process while still driving the full chunk planner, the padded
tail, the batched small-leaf collective, and the device-resident
zero-staging path.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jubatus_tpu.parallel.collective import psum_pytree

RNG = np.random.default_rng(7)


def _chunked_vs_unchunked(diff, **kw):
    """Run the same tree through a forced-chunking plan (tiny chunk) and
    a never-chunking plan (huge chunk); return both results."""
    chunked = psum_pytree(diff, chunk_mb=0.25, **kw)
    unchunked = psum_pytree(diff, chunk_mb=1 << 20, **kw)
    return chunked, unchunked


def test_chunked_f32_bit_identical_to_unchunked():
    # 700_001 f32 elements per row: NOT a multiple of any chunk size —
    # exercises the zero-padded ragged tail
    diff = {
        "w": RNG.normal(size=(3, 700_001)).astype(np.float32),
        "b": RNG.normal(size=(64,)).astype(np.float32),
    }
    phases: dict = {}
    chunked = psum_pytree(diff, chunk_mb=0.25, phases=phases)
    unchunked = psum_pytree(diff, chunk_mb=1 << 20)
    assert phases["chunks"] > 1  # the plan really split
    # world of 1: the total IS the input, and chunking must be bit-exact
    assert np.array_equal(chunked["w"], diff["w"])
    assert np.array_equal(chunked["w"], unchunked["w"])
    assert chunked["w"].dtype == np.float32
    assert np.array_equal(chunked["b"], unchunked["b"])


def test_chunk_exact_multiple_no_tail():
    # leaf bytes an exact multiple of the chunk: no padded tail branch
    elems = (1 << 16)  # 256 KiB of f32 = exactly 4 chunks of 64 KiB
    diff = {"w": RNG.normal(size=(elems,)).astype(np.float32)}
    phases: dict = {}
    out = psum_pytree(diff, chunk_mb=64 / 1024, phases=phases)
    assert phases["chunks"] == 4
    assert np.array_equal(out["w"], diff["w"])


def test_chunked_bf16_matches_compress_contract():
    """compress=True must produce the same values chunked and unchunked,
    equal to one f32→bf16→f32 round trip (world of 1), handed back f32."""
    diff = {"w": RNG.normal(size=(2, 300_000)).astype(np.float32)}
    chunked, unchunked = _chunked_vs_unchunked(diff, compress=True)
    expect = np.asarray(
        jnp.asarray(diff["w"]).astype(jnp.bfloat16).astype(jnp.float32))
    assert chunked["w"].dtype == np.float32
    assert np.array_equal(chunked["w"], unchunked["w"])
    assert np.array_equal(chunked["w"], expect)


def test_compress_halves_reported_payload():
    diff = {"w": np.ones((256, 1024), np.float32)}
    ph_f32: dict = {}
    ph_bf16: dict = {}
    psum_pytree(diff, phases=ph_f32, chunk_mb=0.25)
    psum_pytree(diff, compress=True, phases=ph_bf16, chunk_mb=0.25)
    assert ph_bf16["payload_mb"] == round(ph_f32["payload_mb"] / 2, 2)


def test_non_f32_dtype_rides_chunks_exactly():
    diff = {"idx": np.arange(200_000, dtype=np.int32)}
    out = psum_pytree(diff, chunk_mb=0.25)
    assert out["idx"].dtype == np.int32
    assert np.array_equal(out["idx"], diff["idx"])
    # compress must leave non-f32 leaves untouched
    out_c = psum_pytree(diff, compress=True, chunk_mb=0.25)
    assert np.array_equal(out_c["idx"], diff["idx"])


def test_scalar_and_empty_pytrees():
    # scalar leaves ride the batched small-leaf collective
    out = psum_pytree({"c": np.float32(2.5), "d": jnp.float32(1.25)})
    assert float(out["c"]) == 2.5
    assert float(out["d"]) == 1.25
    # empty pytree: no collective at all, phases still well-formed
    phases: dict = {}
    assert psum_pytree({}, phases=phases) == {}
    assert phases["chunks"] == 0
    assert phases["overlap_ms_saved"] == 0.0


def test_device_resident_fast_path_world_of_1():
    """jax.Array leaves enter with zero host staging; prefer_device hands
    device arrays back and the values match the host path bit-for-bit."""
    host = {
        "w": RNG.normal(size=(2, 400_000)).astype(np.float32),
        "b": RNG.normal(size=(16,)).astype(np.float32),
    }
    dev = {k: jnp.asarray(v) for k, v in host.items()}
    out_dev = psum_pytree(dev, chunk_mb=0.25, prefer_device=True)
    assert isinstance(out_dev["w"], jax.Array)
    assert isinstance(out_dev["b"], jax.Array)
    out_host = psum_pytree(host, chunk_mb=0.25)
    assert np.array_equal(np.asarray(out_dev["w"]), out_host["w"])
    assert np.array_equal(np.asarray(out_dev["b"]), out_host["b"])
    # device in / host out (default) also matches
    out_mixed = psum_pytree(dev, chunk_mb=0.25)
    assert isinstance(out_mixed["w"], np.ndarray)
    assert np.array_equal(out_mixed["w"], out_host["w"])


def test_mixed_host_device_tree_parity():
    """One tree mixing device-resident and host leaves (the real AROW
    diff shape: jax dw/dprec + numpy df) stays bit-exact chunked."""
    diff = {
        "dw": jnp.asarray(RNG.normal(size=(2, 350_001)).astype(np.float32)),
        "df": RNG.normal(size=(250_000,)).astype(np.float32),
        "count": jnp.float32(1.0),
    }
    phases: dict = {}
    out = psum_pytree(diff, chunk_mb=0.25, phases=phases)
    assert phases["chunks"] >= 2
    assert np.array_equal(out["dw"], np.asarray(diff["dw"]))
    assert np.array_equal(out["df"], diff["df"])
    assert float(out["count"]) == 1.0


def test_64bit_leaves_still_refused():
    with pytest.raises(ValueError, match="64-bit"):
        psum_pytree({"x": np.zeros(4, np.float64)})
    with pytest.raises(ValueError, match="64-bit"):
        psum_pytree({"x": np.zeros(1 << 18, np.int64)}, chunk_mb=0.25)


def test_phase_accounting_keys_present():
    diff = {"w": RNG.normal(size=(1 << 18,)).astype(np.float32)}
    phases: dict = {}
    psum_pytree(diff, chunk_mb=0.25, phases=phases)
    for k in ("cast_ms", "ship_ms", "reduce_ms", "readback_ms",
              "payload_mb", "wire_mb_ring_model", "chunks", "chunk_mb",
              "overlap_ms_saved"):
        assert k in phases, (k, phases)
        assert phases[k] >= 0
    assert phases["chunk_mb"] == 0.25
