"""Parity tests for the pipelined mix data plane (parallel/collective.py).

The chunked double-buffered stream must be BIT-identical to the
unchunked path for f32 (chunking only re-tiles the psum, it must never
change the arithmetic) and must keep the established bf16 contract under
``compress=True``. World of 1 (psum = identity) keeps the tests
single-process while still driving the full chunk planner, the padded
tail, the batched small-leaf collective, and the device-resident
zero-staging path — and, for the int8 transport, exactly one
block-quantize round trip per chunk, which is what the error-feedback
drift gates measure: with the residual carried the multi-round
averaged-weight drift is BOUNDED (telescoping), without it the per-round
bias random-walks as sqrt(rounds).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jubatus_tpu.parallel.collective import (
    QUANT_BLOCK,
    ErrorFeedback,
    _norm_compress,
    psum_pytree,
)

RNG = np.random.default_rng(7)


def _chunked_vs_unchunked(diff, **kw):
    """Run the same tree through a forced-chunking plan (tiny chunk) and
    a never-chunking plan (huge chunk); return both results."""
    chunked = psum_pytree(diff, chunk_mb=0.25, **kw)
    unchunked = psum_pytree(diff, chunk_mb=1 << 20, **kw)
    return chunked, unchunked


def test_chunked_f32_bit_identical_to_unchunked():
    # 700_001 f32 elements per row: NOT a multiple of any chunk size —
    # exercises the zero-padded ragged tail
    diff = {
        "w": RNG.normal(size=(3, 700_001)).astype(np.float32),
        "b": RNG.normal(size=(64,)).astype(np.float32),
    }
    phases: dict = {}
    chunked = psum_pytree(diff, chunk_mb=0.25, phases=phases)
    unchunked = psum_pytree(diff, chunk_mb=1 << 20)
    assert phases["chunks"] > 1  # the plan really split
    # world of 1: the total IS the input, and chunking must be bit-exact
    assert np.array_equal(chunked["w"], diff["w"])
    assert np.array_equal(chunked["w"], unchunked["w"])
    assert chunked["w"].dtype == np.float32
    assert np.array_equal(chunked["b"], unchunked["b"])


def test_chunk_exact_multiple_no_tail():
    # leaf bytes an exact multiple of the chunk: no padded tail branch
    elems = (1 << 16)  # 256 KiB of f32 = exactly 4 chunks of 64 KiB
    diff = {"w": RNG.normal(size=(elems,)).astype(np.float32)}
    phases: dict = {}
    out = psum_pytree(diff, chunk_mb=64 / 1024, phases=phases)
    assert phases["chunks"] == 4
    assert np.array_equal(out["w"], diff["w"])


def test_chunked_bf16_matches_compress_contract():
    """compress=True must produce the same values chunked and unchunked,
    equal to one f32→bf16→f32 round trip (world of 1), handed back f32."""
    diff = {"w": RNG.normal(size=(2, 300_000)).astype(np.float32)}
    chunked, unchunked = _chunked_vs_unchunked(diff, compress=True)
    expect = np.asarray(
        jnp.asarray(diff["w"]).astype(jnp.bfloat16).astype(jnp.float32))
    assert chunked["w"].dtype == np.float32
    assert np.array_equal(chunked["w"], unchunked["w"])
    assert np.array_equal(chunked["w"], expect)


def test_compress_halves_reported_payload():
    diff = {"w": np.ones((256, 1024), np.float32)}
    ph_f32: dict = {}
    ph_bf16: dict = {}
    psum_pytree(diff, phases=ph_f32, chunk_mb=0.25)
    psum_pytree(diff, compress=True, phases=ph_bf16, chunk_mb=0.25)
    assert ph_bf16["payload_mb"] == round(ph_f32["payload_mb"] / 2, 2)


def test_non_f32_dtype_rides_chunks_exactly():
    diff = {"idx": np.arange(200_000, dtype=np.int32)}
    out = psum_pytree(diff, chunk_mb=0.25)
    assert out["idx"].dtype == np.int32
    assert np.array_equal(out["idx"], diff["idx"])
    # compress must leave non-f32 leaves untouched
    out_c = psum_pytree(diff, compress=True, chunk_mb=0.25)
    assert np.array_equal(out_c["idx"], diff["idx"])


def test_scalar_and_empty_pytrees():
    # scalar leaves ride the batched small-leaf collective
    out = psum_pytree({"c": np.float32(2.5), "d": jnp.float32(1.25)})
    assert float(out["c"]) == 2.5
    assert float(out["d"]) == 1.25
    # empty pytree: no collective at all, phases still well-formed
    phases: dict = {}
    assert psum_pytree({}, phases=phases) == {}
    assert phases["chunks"] == 0
    assert phases["overlap_ms_saved"] == 0.0


def test_device_resident_fast_path_world_of_1():
    """jax.Array leaves enter with zero host staging; prefer_device hands
    device arrays back and the values match the host path bit-for-bit."""
    host = {
        "w": RNG.normal(size=(2, 400_000)).astype(np.float32),
        "b": RNG.normal(size=(16,)).astype(np.float32),
    }
    dev = {k: jnp.asarray(v) for k, v in host.items()}
    out_dev = psum_pytree(dev, chunk_mb=0.25, prefer_device=True)
    assert isinstance(out_dev["w"], jax.Array)
    assert isinstance(out_dev["b"], jax.Array)
    out_host = psum_pytree(host, chunk_mb=0.25)
    assert np.array_equal(np.asarray(out_dev["w"]), out_host["w"])
    assert np.array_equal(np.asarray(out_dev["b"]), out_host["b"])
    # device in / host out (default) also matches
    out_mixed = psum_pytree(dev, chunk_mb=0.25)
    assert isinstance(out_mixed["w"], np.ndarray)
    assert np.array_equal(out_mixed["w"], out_host["w"])


def test_mixed_host_device_tree_parity():
    """One tree mixing device-resident and host leaves (the real AROW
    diff shape: jax dw/dprec + numpy df) stays bit-exact chunked."""
    diff = {
        "dw": jnp.asarray(RNG.normal(size=(2, 350_001)).astype(np.float32)),
        "df": RNG.normal(size=(250_000,)).astype(np.float32),
        "count": jnp.float32(1.0),
    }
    phases: dict = {}
    out = psum_pytree(diff, chunk_mb=0.25, phases=phases)
    assert phases["chunks"] >= 2
    assert np.array_equal(out["dw"], np.asarray(diff["dw"]))
    assert np.array_equal(out["df"], diff["df"])
    assert float(out["count"]) == 1.0


def test_64bit_leaves_still_refused():
    with pytest.raises(ValueError, match="64-bit"):
        psum_pytree({"x": np.zeros(4, np.float64)})
    with pytest.raises(ValueError, match="64-bit"):
        psum_pytree({"x": np.zeros(1 << 18, np.int64)}, chunk_mb=0.25)


def test_phase_accounting_keys_present():
    diff = {"w": RNG.normal(size=(1 << 18,)).astype(np.float32)}
    phases: dict = {}
    psum_pytree(diff, chunk_mb=0.25, phases=phases)
    for k in ("cast_ms", "ship_ms", "reduce_ms", "readback_ms",
              "payload_mb", "wire_mb", "wire_mb_ring_model", "chunks",
              "chunk_mb", "overlap_ms_saved"):
        assert k in phases, (k, phases)
        assert phases[k] >= 0
    assert phases["chunk_mb"] == 0.25
    assert phases["quant"] == "off"


# -- int8 quantized transport + error feedback ------------------------------

def test_compress_mode_enum_and_bool_compat():
    """The historical bool and the off|bf16|int8 enum resolve to the
    same modes; junk is rejected loudly (a typo'd flag must never
    silently ship f32)."""
    assert _norm_compress(False) == "off"
    assert _norm_compress(True) == "bf16"
    assert _norm_compress("off") == "off"
    assert _norm_compress("bf16") == "bf16"
    assert _norm_compress("int8") == "int8"
    with pytest.raises(ValueError, match="compress mode"):
        _norm_compress("int4")
    diff = {"w": RNG.normal(size=(2, 100_000)).astype(np.float32)}
    a = psum_pytree(diff, compress=True, chunk_mb=0.25)
    b = psum_pytree(diff, compress="bf16", chunk_mb=0.25)
    assert np.array_equal(a["w"], b["w"])


def test_int8_block_quant_error_bounded():
    """Per-element int8 error is bounded by its 256-block's scale/2
    (symmetric absmax scaling): one outlier only poisons its own
    block, never the tensor — the EQuARX block-wise property."""
    w = RNG.normal(size=(2, 350_001)).astype(np.float32)
    w[0, 123] = 80.0  # an outlier: its block coarsens, others must not
    out = psum_pytree({"w": w}, compress="int8", chunk_mb=0.25)
    err = np.abs(out["w"] - w).reshape(-1)
    flat = w.reshape(-1)
    pad = (-flat.size) % QUANT_BLOCK
    blocks = np.pad(flat, (0, pad)).reshape(-1, QUANT_BLOCK)
    bound = np.abs(blocks).max(axis=1) / 127.0 * 0.5 + 1e-6
    errp = np.pad(err, (0, pad)).reshape(-1, QUANT_BLOCK)
    assert (errp <= bound[:, None]).all()
    # blocks away from the outlier keep fine resolution
    assert err[-QUANT_BLOCK:].max() <= 4.0 / 127.0


def test_int8_exact_for_small_and_non_f32_leaves():
    """int8 quantizes only the CHUNKED f32 leaves: scalars/counters and
    integer tables must never drift."""
    diff = {
        "w": RNG.normal(size=(2, 200_000)).astype(np.float32),
        "idx": np.arange(200_000, dtype=np.int32),
        "count": np.float32(17.0),
    }
    out = psum_pytree(diff, compress="int8", chunk_mb=0.25)
    assert np.array_equal(out["idx"], diff["idx"])
    assert float(out["count"]) == 17.0
    assert not np.array_equal(out["w"], diff["w"])  # quantized


def test_int8_payload_accounting_near_4x():
    diff = {"w": np.ones((1 << 22,), np.float32)}  # 16 MB, no padding
    ph32: dict = {}
    ph8: dict = {}
    psum_pytree(diff, phases=ph32, chunk_mb=1.0)
    psum_pytree(diff, compress="int8", phases=ph8, chunk_mb=1.0)
    assert ph8["quant"] == "int8"
    ratio = ph32["payload_mb"] / ph8["payload_mb"]
    # 1 byte/elem + 4/QUANT_BLOCK scale bytes = 3.94x at block 256
    assert 3.5 <= ratio <= 4.0, (ratio, ph32, ph8)


def test_int8_error_feedback_drift_gate():
    """THE parity gate: accumulate R rounds of mixed totals. With the
    error-feedback residual the drift vs f32 telescopes — round R's
    cumulative drift equals ONE round's quantization error, it does not
    compound. Without the residual the same transport fails this gate
    (sqrt(R) random walk) — proving the gate has teeth and the residual
    is load-bearing, not decorative."""
    rng = np.random.default_rng(3)
    shape = (2, 200_000)
    rounds = 16
    ef = ErrorFeedback()
    s32 = np.zeros(shape, np.float32)
    s8 = np.zeros(shape, np.float32)
    s8n = np.zeros(shape, np.float32)
    drift_ef = []
    drift_noef = []
    for _ in range(rounds):
        x = {"w": rng.normal(size=shape).astype(np.float32)}
        s32 += psum_pytree(x, chunk_mb=0.25)["w"]
        s8 += psum_pytree(x, compress="int8", chunk_mb=0.25,
                          feedback=ef)["w"]
        s8n += psum_pytree(x, compress="int8", chunk_mb=0.25)["w"]
        drift_ef.append(float(np.linalg.norm(s8 - s32)))
        drift_noef.append(float(np.linalg.norm(s8n - s32)))
    assert ef.rounds == rounds
    # the GATE: bounded and non-compounding (empirically the ratio is
    # ~1.00; 1.5 allows residual-magnitude noise)
    assert drift_ef[-1] <= 1.5 * drift_ef[0], drift_ef
    # ...which the no-feedback transport demonstrably FAILS
    # (empirically ~sqrt(16) = 4.0x round 1's drift)
    assert drift_noef[-1] > 1.5 * drift_noef[0], drift_noef
    assert drift_noef[-1] > 2.0 * drift_ef[-1]


def test_int8_residual_commits_only_on_success():
    """A round that dies mid-stream must leave the residual state of the
    last successful round intact — a degraded/aborted round would
    otherwise corrupt the error the next round feeds back."""
    rng = np.random.default_rng(11)
    x = {"w": rng.normal(size=(2, 200_000)).astype(np.float32)}
    ef = ErrorFeedback()
    psum_pytree(x, compress="int8", chunk_mb=0.25, feedback=ef)
    assert ef.rounds == 1
    key_before = ef.key
    res_before = dict(ef.total)
    # 64-bit leaves are refused at the planner — before any chunk runs
    with pytest.raises(ValueError, match="64-bit"):
        psum_pytree({"w": np.zeros((1 << 18,), np.float64)},
                    compress="int8", chunk_mb=0.25, feedback=ef)
    assert ef.rounds == 1 and ef.key == key_before
    assert all(ef.total[k] is res_before[k] for k in res_before)


def test_int8_residual_resets_on_plan_change():
    """Shape/chunk churn invalidates carried residuals (they are
    positional); the transport must reset rather than misapply them."""
    rng = np.random.default_rng(12)
    ef = ErrorFeedback()
    psum_pytree({"w": rng.normal(size=(2, 200_000)).astype(np.float32)},
                compress="int8", chunk_mb=0.25, feedback=ef)
    n_keys = len(ef.contrib)
    assert n_keys > 0
    psum_pytree({"w": rng.normal(size=(2, 300_000)).astype(np.float32)},
                compress="int8", chunk_mb=0.25, feedback=ef)
    # old keys are gone, new plan's keys are in
    assert ef.rounds == 2
    assert len(ef.contrib) != n_keys or ef.key is not None


# -- hierarchical two-tier reduce (topology=) --------------------------------
#
# World of 1 with the conftest's 8 virtual CPU devices: an "HxM" override
# regrids the local devices, so the two-tier reduce (intra-host psum,
# lane-segmented inter-host ring, intra-host rebuild) runs for REAL over
# the (host, local) mesh while the process total stays the identity —
# every grouping must therefore be bit-identical to the flat path.

@pytest.mark.parametrize("topo", ["1x1", "8x1", "1x8", "2x4", "4x2"])
def test_hier_chunked_bit_identical_to_flat(topo):
    diff = {
        "w": RNG.normal(size=(3, 700_001)).astype(np.float32),
        "b": RNG.normal(size=(64,)).astype(np.float32),
    }
    phases: dict = {}
    flat = psum_pytree({k: v.copy() for k, v in diff.items()},
                       chunk_mb=0.25)
    hier = psum_pytree({k: v.copy() for k, v in diff.items()},
                       chunk_mb=0.25, topology=topo, phases=phases)
    assert phases["chunks"] > 1
    assert phases["topo"] == topo
    assert np.array_equal(hier["w"], flat["w"])
    assert np.array_equal(hier["b"], flat["b"])
    assert hier["w"].dtype == np.float32


def test_hier_bf16_matches_flat_bf16():
    """bf16 composes with the two-tier reduce: the cast happens after
    the exact intra fold, and at world 1 (host sum == the input) the
    values must equal the flat bf16 round trip bit-for-bit."""
    diff = {"w": RNG.normal(size=(2, 300_000)).astype(np.float32)}
    flat = psum_pytree({"w": diff["w"].copy()}, compress="bf16",
                       chunk_mb=0.25)
    hier = psum_pytree({"w": diff["w"].copy()}, compress="bf16",
                       chunk_mb=0.25, topology="2x4")
    assert np.array_equal(hier["w"], flat["w"])


def test_hier_phase_keys_and_wire_per_host_model():
    """Hierarchical phases stamp the tier split and the scaling gate's
    key: ``wire_bytes_per_host`` follows the ring model — the chunked
    payload crosses the inter-host wire 2(H-1)/H times per HOST (not per
    device), so for one fleet size, fewer hosts on the wire = fewer
    bytes per round in flight between hosts."""
    elems = 1 << 18  # 1 MiB f32, exact multiple of every plan below
    diff = {"w": np.ones((elems,), np.float32)}
    per_host = {}
    for topo in ("8x1", "4x2", "2x4"):
        ph: dict = {}
        psum_pytree(diff, chunk_mb=0.25, topology=topo, phases=ph)
        for k in ("intra_ms", "inter_ms", "wire_bytes_per_host"):
            assert k in ph and ph[k] >= 0, (k, ph)
        h = int(topo.split("x")[0])
        assert ph["wire_bytes_per_host"] == \
            int(elems * 4 * 2 * (h - 1) / h), (topo, ph)
        per_host[topo] = ph["wire_bytes_per_host"]
    # grouping 8 lanes as 2 hosts x 4 devices vs 8 flat "hosts" cuts
    # inter-host bytes per host by 1.75x; the TOTAL inter-host traffic
    # (sum over hosts) falls 8*1.75 / 2*1.0 = 7x — >= the local factor 4
    assert per_host["2x4"] < per_host["4x2"] < per_host["8x1"]
    assert 8 * per_host["8x1"] >= 4 * (2 * per_host["2x4"])
    # flat mode on a world of 1 ships nothing (no peer); the key exists
    ph_flat: dict = {}
    psum_pytree(diff, chunk_mb=0.25, phases=ph_flat)
    assert ph_flat["topo"] == "flat"
    assert ph_flat["wire_bytes_per_host"] == 0
    assert ph_flat["intra_ms"] == 0.0


def test_hier_small_leaves_stay_flat_and_exact():
    """Leaves below the chunk threshold keep the flat batched
    collective even in hierarchical mode (their wire share is noise);
    values stay exact and the tier timings stay zero."""
    diff = {"b": RNG.normal(size=(64,)).astype(np.float32),
            "c": np.float32(3.0)}
    ph: dict = {}
    out = psum_pytree(diff, topology="2x4", phases=ph)
    assert np.array_equal(out["b"], diff["b"])
    assert float(out["c"]) == 3.0
    assert ph["topo"] == "2x4"
    assert ph["intra_ms"] == 0.0 and ph["chunks"] == 0


def test_hier_int8_error_feedback_drift_gate():
    """The EF telescoping survives the two-tier transport: residuals
    correct the HOST sum (one chain per (host, lane) segment), and the
    multi-round drift vs f32 stays bounded exactly like the flat gate —
    while the no-feedback transport demonstrably random-walks."""
    rng = np.random.default_rng(5)
    shape = (2, 200_000)
    rounds = 12
    ef = ErrorFeedback()
    s32 = np.zeros(shape, np.float32)
    s8 = np.zeros(shape, np.float32)
    s8n = np.zeros(shape, np.float32)
    drift_ef = []
    drift_noef = []
    for _ in range(rounds):
        x = {"w": rng.normal(size=shape).astype(np.float32)}
        s32 += psum_pytree(x, chunk_mb=0.25)["w"]
        s8 += psum_pytree(x, compress="int8", chunk_mb=0.25,
                          feedback=ef, topology="2x4")["w"]
        s8n += psum_pytree(x, compress="int8", chunk_mb=0.25,
                           topology="2x4")["w"]
        drift_ef.append(float(np.linalg.norm(s8 - s32)))
        drift_noef.append(float(np.linalg.norm(s8n - s32)))
    assert ef.rounds == rounds
    assert drift_ef[-1] <= 1.5 * drift_ef[0], drift_ef
    assert drift_noef[-1] > 1.5 * drift_noef[0], drift_noef
    assert drift_noef[-1] > 2.0 * drift_ef[-1]


def test_hier_int8_matches_flat_int8_on_first_round():
    """Round 1 (no carried residual yet) of the hierarchical int8
    transport quantizes the identical host totals the flat transport
    does at world 1 — same blocks, same scales, bit-equal output."""
    diff = {"w": RNG.normal(size=(2, 350_001)).astype(np.float32)}
    flat = psum_pytree({"w": diff["w"].copy()}, compress="int8",
                       chunk_mb=0.25)
    hier = psum_pytree({"w": diff["w"].copy()}, compress="int8",
                       chunk_mb=0.25, topology="1x8")
    assert np.array_equal(hier["w"], flat["w"])


def test_hier_int8_residual_resets_on_topology_change():
    """The topology signature rides the EF plan key: regrouping the
    fleet (or toggling flat<->hier) repositions every carried residual,
    so the transport must reset instead of misapplying them."""
    rng = np.random.default_rng(13)
    x = {"w": rng.normal(size=(2, 200_000)).astype(np.float32)}
    ef = ErrorFeedback()
    psum_pytree(x, compress="int8", chunk_mb=0.25, feedback=ef,
                topology="2x4")
    key_24 = ef.key
    assert ef.rounds == 1 and key_24 is not None
    psum_pytree(x, compress="int8", chunk_mb=0.25, feedback=ef,
                topology="4x2")
    assert ef.key != key_24
    assert ef.rounds == 2  # reset then committed under the new plan
    psum_pytree(x, compress="int8", chunk_mb=0.25, feedback=ef)
    assert ef.key != key_24  # flat keys differ from every topology


def test_hier_prefer_device_and_device_resident_leaves():
    host = RNG.normal(size=(2, 400_000)).astype(np.float32)
    dev = {"w": jnp.asarray(host)}
    out = psum_pytree(dev, chunk_mb=0.25, topology="2x4",
                      prefer_device=True)
    assert isinstance(out["w"], jax.Array)
    assert np.array_equal(np.asarray(out["w"]), host)


def test_hier_rejects_bad_topology():
    diff = {"w": np.zeros((1 << 18,), np.float32)}
    with pytest.raises(ValueError, match="topology"):
        psum_pytree(diff, topology="junk")
    with pytest.raises(ValueError, match="devices"):
        psum_pytree(diff, topology="4x4")  # needs 16 of the 8


def test_int8_device_resident_leaves_and_prefer_device():
    """The zero-staging jax.Array path rides the quantized transport
    too, and prefer_device hands device totals back."""
    host = RNG.normal(size=(2, 300_000)).astype(np.float32)
    dev = {"w": jnp.asarray(host)}
    ef = ErrorFeedback()
    out = psum_pytree(dev, compress="int8", chunk_mb=0.25,
                      prefer_device=True, feedback=ef)
    assert isinstance(out["w"], jax.Array)
    err = np.abs(np.asarray(out["w"]) - host)
    assert err.max() > 0  # quantized
    assert err.max() < np.abs(host).max() / 64  # but sane
    assert ef.rounds == 1


# -- cross-round streaming (ISSUE 11) ----------------------------------------

def test_psum_pytree_start_streams_back_to_back_rounds():
    """psum_pytree_start returns a handle immediately; two back-to-back
    rounds overlap (round N+1's ship/reduce dispatch while round N's
    readback drains) under the dispatch gate, and both totals are
    bit-identical to the serial path."""
    from jubatus_tpu.parallel.collective import psum_pytree_start

    a = {"w": RNG.normal(size=(1 << 18,)).astype(np.float32)}
    b = {"w": RNG.normal(size=(1 << 18,)).astype(np.float32)}
    pa, pb = {}, {}
    ra = psum_pytree_start(a, chunk_mb=0.25, phases=pa)
    rb = psum_pytree_start(b, chunk_mb=0.25, phases=pb)  # queues on the gate
    out_b = rb.result()  # collectable out of order (world of 1)
    out_a = ra.result()
    assert ra.done() and rb.done()
    np.testing.assert_array_equal(out_a["w"],
                                  psum_pytree(a, chunk_mb=0.25)["w"])
    np.testing.assert_array_equal(out_b["w"],
                                  psum_pytree(b, chunk_mb=0.25)["w"])
    # the gate accounting is stamped per round
    assert "dispatch_gate_ms" in pa and "dispatch_gate_ms" in pb
    assert pa["dispatch_gate_ms"] >= 0 and pb["dispatch_gate_ms"] >= 0


def test_psum_pytree_start_propagates_errors():
    from jubatus_tpu.parallel.collective import psum_pytree_start

    bad = psum_pytree_start({"x": np.zeros(4, np.float64)})
    with pytest.raises(ValueError, match="64-bit"):
        bad.result()
    # the gate was released on the error path: a clean round still runs
    out = psum_pytree({"w": np.ones((1 << 16,), np.float32)},
                      chunk_mb=0.25)
    np.testing.assert_array_equal(out["w"], 1.0)


def test_dispatch_gate_serializes_many_concurrent_rounds():
    """A pile of concurrent rounds (the 10x-cadence shape) all complete
    with correct totals — the gate totally orders their collective
    dispatch, so none can interleave and wedge."""
    from jubatus_tpu.parallel.collective import psum_pytree_start

    diffs = [{"w": np.full((1 << 16,), float(i + 1), np.float32)}
             for i in range(6)]
    handles = [psum_pytree_start(d, chunk_mb=0.0625) for d in diffs]
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(h.result()["w"], float(i + 1))
