"""Every shipped config example must construct a working driver — the
--config_test contract (server_util.hpp:142-152) applied to our own
config/ tree, plus spot checks that a trained flow runs on each engine's
default config.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.server.factory import create_driver

CONFIG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "config")

ALL_CONFIGS = sorted(glob.glob(os.path.join(CONFIG_ROOT, "*", "*.json")))


def test_config_tree_covers_all_engines():
    engines = {os.path.basename(os.path.dirname(p)) for p in ALL_CONFIGS}
    assert engines == {
        "anomaly", "bandit", "burst", "classifier", "clustering", "graph",
        "nearest_neighbor", "recommender", "regression", "stat", "weight",
    }


@pytest.mark.parametrize("path", ALL_CONFIGS,
                         ids=[os.path.relpath(p, CONFIG_ROOT) for p in ALL_CONFIGS])
def test_config_constructs_driver(path):
    engine = os.path.basename(os.path.dirname(path))
    with open(path) as f:
        config = json.load(f)
    driver = create_driver(engine, config)
    assert driver.get_status() is not None


def _default(engine):
    with open(os.path.join(CONFIG_ROOT, engine, "default.json")) as f:
        return json.load(f)


def test_classifier_default_flow():
    d = create_driver("classifier", _default("classifier"))
    d.train([("pos", Datum({"x": 1.0})), ("neg", Datum({"x": -1.0}))])
    (scores,) = d.classify([Datum({"x": 1.0})])
    assert max(scores, key=lambda s: s[1])[0] == "pos"


def test_recommender_default_flow():
    d = create_driver("recommender", _default("recommender"))
    d.update_row("a", Datum({"x": 1.0, "y": 0.0}))
    d.update_row("b", Datum({"x": 0.9, "y": 0.1}))
    sim = d.similar_row_from_id("a", 2)
    assert sim and sim[0][0] in ("a", "b")


def test_stat_default_flow():
    d = create_driver("stat", _default("stat"))
    d.push("k", 2.0)
    d.push("k", 4.0)
    assert d.sum("k") == 6.0
