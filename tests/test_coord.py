"""Coordination tests (≙ common/*_test.cpp tier 1 + the ZK mock the
reference never wrote, SURVEY.md §4)."""

from __future__ import annotations

import threading

import pytest

from jubatus_tpu.coord import (
    CHT,
    FileCoordinator,
    IdGenerator,
    MemoryCoordinator,
    NodeInfo,
    membership,
)
from jubatus_tpu.coord.cht import shard_for


@pytest.fixture(params=["memory", "file"])
def coord_factory(request, tmp_path):
    """Yields a factory producing sessions on one shared store."""
    if request.param == "memory":
        from jubatus_tpu.coord.memory import _Store

        store = _Store()
        yield lambda: MemoryCoordinator(store)
    else:
        root = str(tmp_path / "cluster")
        made = []

        def make():
            c = FileCoordinator(root)
            made.append(c)
            return c

        yield make
        for c in made:
            c.close()


def test_crud(coord_factory):
    c = coord_factory()
    assert c.create("/a/b/c", b"hello")
    assert not c.create("/a/b/c", b"again")
    assert c.read("/a/b/c") == b"hello"
    assert c.exists("/a/b/c")
    assert c.set("/a/b/c", b"world")
    assert c.read("/a/b/c") == b"world"
    assert "b" in c.list("/a")
    assert c.list("/a/b") == ["c"]
    assert c.remove("/a/b/c")
    assert not c.exists("/a/b/c")
    assert c.read("/a/b/c") is None


def test_ephemeral_dies_with_session(coord_factory):
    s1, s2 = coord_factory(), coord_factory()
    s1.create("/eph/node1", b"x", ephemeral=True)
    s1.create("/perm", b"y")
    assert s2.exists("/eph/node1")
    s1.close()
    assert not s2.exists("/eph/node1")
    assert s2.exists("/perm")


def test_locks(coord_factory):
    s1, s2 = coord_factory(), coord_factory()
    assert s1.try_lock("/jubatus/m/master_lock")
    assert not s2.try_lock("/jubatus/m/master_lock")
    assert s1.try_lock("/jubatus/m/master_lock")  # reentrant for holder
    assert s1.unlock("/jubatus/m/master_lock")
    assert s2.try_lock("/jubatus/m/master_lock")
    s2.unlock("/jubatus/m/master_lock")


def test_lock_released_on_close(coord_factory):
    s1, s2 = coord_factory(), coord_factory()
    assert s1.try_lock("/lk")
    s1.close()
    assert s2.try_lock("/lk")


def test_create_id_unique_across_sessions(coord_factory):
    sessions = [coord_factory() for _ in range(4)]
    ids = []
    lock = threading.Lock()

    def mint(c):
        got = [c.create_id("/idg") for _ in range(25)]
        with lock:
            ids.extend(got)

    threads = [threading.Thread(target=mint, args=(s,)) for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ids) == 100
    assert len(set(ids)) == 100  # cluster-unique (global_id_generator_zk)


def test_membership_registry(coord_factory):
    c1, c2 = coord_factory(), coord_factory()
    membership.register_actor(c1, "classifier", "cl", "10.0.0.1", 9199)
    membership.register_actor(c2, "classifier", "cl", "10.0.0.2", 9199)
    membership.register_active(c1, "classifier", "cl", "10.0.0.1", 9199)
    nodes = membership.get_all_nodes(c1, "classifier", "cl")
    assert {n.name for n in nodes} == {"10.0.0.1_9199", "10.0.0.2_9199"}
    actives = membership.get_all_actives(c2, "classifier", "cl")
    assert [n.name for n in actives] == ["10.0.0.1_9199"]
    # session death removes the member (ZK ephemeral semantics)
    c1.close()
    nodes = membership.get_all_nodes(c2, "classifier", "cl")
    assert {n.name for n in nodes} == {"10.0.0.2_9199"}


def test_watch_delete(coord_factory):
    import time

    c1, c2 = coord_factory(), coord_factory()
    c1.create("/watched", b"")
    fired = threading.Event()
    c2.watch_delete("/watched", lambda p: fired.set())
    c1.remove("/watched")
    assert fired.wait(3.0)  # file backend polls at 0.5s
    del time


def test_cht_ring_properties():
    members = [NodeInfo(f"10.0.0.{i}", 9199) for i in range(5)]
    ring = CHT(members)
    # deterministic: same members → same assignment
    assert ring.find("key1", 2) == CHT(members).find("key1", 2)
    # n distinct successors, primary first
    found = ring.find("key1", 3)
    assert len(found) == 3
    assert len({f.name for f in found}) == 3
    # single-node ring returns that node
    assert CHT(members[:1]).find("anything", 2) == [members[0]]
    # empty ring
    assert CHT([]).find("x", 1) == []


def test_cht_stability_under_member_change():
    """Removing one member only remaps keys owned by it (the consistent-
    hashing property the reference relies on for low churn)."""
    members = [NodeInfo(f"10.0.0.{i}", 9199) for i in range(8)]
    ring_a = CHT(members)
    ring_b = CHT(members[:-1])  # drop one
    moved = 0
    total = 200
    for i in range(total):
        key = f"key-{i}"
        pa = ring_a.primary(key)
        if pa.name == members[-1].name:
            continue  # owned by removed node — must move
        if ring_b.primary(key).name != pa.name:
            moved += 1
    assert moved == 0  # keys not owned by the removed node never move


def test_shard_for_static_mesh():
    assert shard_for("k", 8) == shard_for("k", 8)
    assert 0 <= shard_for("k", 8) < 8
    spread = {shard_for(f"key{i}", 8) for i in range(100)}
    assert len(spread) == 8  # all shards hit


def test_idgen_standalone_vs_coordinated(coord_factory):
    standalone = IdGenerator()
    assert [standalone.generate() for _ in range(3)] == [1, 2, 3]
    c = coord_factory()
    g1, g2 = IdGenerator(c, "/g"), IdGenerator(coord_factory(), "/g")
    assert g1.generate() != g2.generate()
