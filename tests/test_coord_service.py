"""Coordination service tests — the framework's own ZooKeeper-role daemon
(coord/server.py + coord/remote.py). The reference never shipped a ZK
mock (zk.hpp:36 TODO); here the real service IS testable in-process:
session expiry, ephemeral cleanup, locks, watches, and a full engine
cluster coordinating over tcp://.
"""

from __future__ import annotations

import time

import pytest

from jubatus_tpu.coord import create_coordinator
from jubatus_tpu.coord.remote import RemoteCoordinator
from jubatus_tpu.coord.server import CoordServer


@pytest.fixture()
def service():
    srv = CoordServer(lease_sec=1.5)
    port = srv.start(0, host="127.0.0.1")
    yield srv, port
    srv.stop()


def _client(port) -> RemoteCoordinator:
    return RemoteCoordinator("127.0.0.1", port)


def test_locator_parsing(service):
    _srv, port = service
    c = create_coordinator(f"tcp://127.0.0.1:{port}")
    assert isinstance(c, RemoteCoordinator)
    c.close()
    c = create_coordinator(f"127.0.0.1:{port}")
    assert isinstance(c, RemoteCoordinator)
    c.close()


def test_crud_roundtrip(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.create("/x/y", b"payload")
        assert not b.create("/x/y")          # already exists
        assert b.read("/x/y") == b"payload"
        assert b.exists("/x/y")
        assert a.set("/x/y", b"v2") and b.read("/x/y") == b"v2"
        a.create("/x/z")
        assert b.list("/x") == ["y", "z"]
        assert b.remove("/x/y") and not b.exists("/x/y")
    finally:
        a.close(), b.close()


def test_ephemerals_die_with_session(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        a.create("/e/one", ephemeral=True)
        assert b.exists("/e/one")
        a.close()
        assert not b.exists("/e/one")
    finally:
        b.close()


def test_session_lease_expiry(service):
    srv, port = service
    a, b = _client(port), _client(port)
    try:
        a.create("/lease/node", ephemeral=True)
        a._hb_stop.set()  # simulate client death: heartbeats stop
        deadline = time.time() + 6
        while time.time() < deadline and b.exists("/lease/node"):
            time.sleep(0.2)
        assert not b.exists("/lease/node"), "lease never expired"
    finally:
        b.close()
        a._closed = True
        a._client.close()


def test_locks_are_session_scoped(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.try_lock("/locks/m")
        assert not b.try_lock("/locks/m")
        assert not b.unlock("/locks/m")  # not the owner
        assert a.unlock("/locks/m")
        assert b.try_lock("/locks/m")
    finally:
        a.close(), b.close()


def test_lock_released_on_session_close(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.try_lock("/locks/n")
        a.close()
        assert b.try_lock("/locks/n")
    finally:
        b.close()


def test_create_id_monotonic_across_sessions(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        ids = [a.create_id("/ids/g"), b.create_id("/ids/g"),
               a.create_id("/ids/g")]
        assert ids == sorted(set(ids)), "ids must be unique and increasing"
    finally:
        a.close(), b.close()


def test_watch_children_and_delete(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    fired = {"child": 0, "delete": 0}
    try:
        a.create("/w/seed")
        a.watch_children("/w", lambda _p: fired.__setitem__(
            "child", fired["child"] + 1))
        a.watch_delete("/w/seed", lambda _p: fired.__setitem__(
            "delete", fired["delete"] + 1))
        b.create("/w/new")
        b.remove("/w/seed")
        deadline = time.time() + 5
        while time.time() < deadline and (not fired["child"] or not fired["delete"]):
            time.sleep(0.1)
        assert fired["child"] >= 1
        assert fired["delete"] == 1
    finally:
        a.close(), b.close()


@pytest.mark.slow
def test_engine_cluster_over_tcp_coordinator(service):
    """Full stack: 2 classifier servers + proxy coordinate over tcp://,
    train through the proxy, mix, classify."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    _srv, port = service
    locator = f"tcp://127.0.0.1:{port}"
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    servers = []
    for _ in range(2):
        args = ServerArgs(engine="classifier", coordinator=locator, name="tc",
                          listen_addr="127.0.0.1", interval_sec=1e9,
                          interval_count=1 << 30)
        s = EngineServer("classifier", conf, args)
        s.start(0)
        servers.append(s)
    proxy = Proxy(ProxyArgs(engine="classifier", coordinator=locator,
                            listen_addr="127.0.0.1"))
    proxy.start(0)
    try:
        c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, "tc")
        for _ in range(10):
            c.train([["pos", Datum({"x": 1.0})]])
            c.train([["neg", Datum({"x": -1.0})]])
        assert len(c.get_status()) == 2  # both backends via tcp membership
        assert c.do_mix() is True
        res = c.classify([Datum({"x": 1.0}), Datum({"x": -1.0})])
        assert [max(r, key=lambda s: s[1])[0] for r in res] == ["pos", "neg"]
        c.close()
    finally:
        proxy.stop()
        for s in servers:
            s.stop()


# -- durability + session resumption (VERDICT r1 item 10) ---------------------


def test_journal_recovers_configs_and_counters(tmp_path):
    jpath = str(tmp_path / "coord.journal")
    srv = CoordServer(lease_sec=1.0, journal_path=jpath)
    port = srv.start(0, "127.0.0.1")
    rc = RemoteCoordinator("127.0.0.1", port)
    rc.set("/jubatus/config/classifier/c1", b'{"method": "PA"}')
    ids = [rc.create_id("/jubatus/actors/classifier/c1/id_generator")
           for _ in range(5)]
    rc.create("/jubatus/actors/classifier/c1/nodes/h_1", b"", ephemeral=True)
    rc.close()
    srv.stop()

    srv2 = CoordServer(lease_sec=1.0, journal_path=jpath)
    port2 = srv2.start(0, "127.0.0.1")
    rc2 = RemoteCoordinator("127.0.0.1", port2)
    try:
        # persistent config survived; the ephemeral did not
        assert rc2.read("/jubatus/config/classifier/c1") == b'{"method": "PA"}'
        assert not rc2.exists("/jubatus/actors/classifier/c1/nodes/h_1")
        # counters resume past the reservation — never reissue an id
        nxt = rc2.create_id("/jubatus/actors/classifier/c1/id_generator")
        assert nxt > max(ids)
    finally:
        rc2.close()
        srv2.stop()


def test_journal_compaction_bounds_growth(tmp_path):
    import os

    jpath = str(tmp_path / "coord.journal")
    srv = CoordServer(journal_path=jpath)
    for i in range(50):
        srv._root.set("/jubatus/config/x", b"v%d" % i)
    srv.stop()
    size_before = os.path.getsize(jpath)
    srv2 = CoordServer(journal_path=jpath)  # compacts at open
    srv2.stop()
    assert os.path.getsize(jpath) < size_before
    srv3 = CoordServer(journal_path=jpath)
    assert srv3.store.nodes["/jubatus/config/x"][0] == b"v49"
    srv3.stop()


def test_session_resumes_across_coordd_restart(tmp_path):
    """Kill/restart coordd mid-cluster: the client must re-open its session
    and re-create its ephemerals — no membership loss, no suicide."""
    jpath = str(tmp_path / "coord.journal")
    srv = CoordServer(lease_sec=1.0, journal_path=jpath)
    port = srv.start(0, "127.0.0.1")
    rc = RemoteCoordinator("127.0.0.1", port, resume_window_sec=20.0)
    suicided = []
    member = "/jubatus/actors/classifier/c1/nodes/host_9199"
    assert rc.create(member, b"", ephemeral=True)
    rc.watch_delete(member, lambda p: suicided.append(p))
    srv.stop()  # the "crash"

    time.sleep(2.5)  # heartbeats fail while coordd is down
    srv2 = CoordServer(lease_sec=1.0, journal_path=jpath)
    srv2.start(port, "127.0.0.1")  # same port, recovered store
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            if srv2._root.exists(member):
                break
            time.sleep(0.2)
        assert srv2._root.exists(member), "ephemeral was not re-created"
        assert not suicided, "delete watcher fired despite successful resume"
        assert not rc._closed
        # the resumed session is fully functional
        assert rc.create(member + "_b", b"", ephemeral=True)
    finally:
        rc.close()
        srv2.stop()


def test_session_lost_after_resume_window(tmp_path):
    """coordd gone for longer than the resume window -> the original
    cleanup contract: delete watchers fire, client closes."""
    srv = CoordServer(lease_sec=0.6)
    port = srv.start(0, "127.0.0.1")
    rc = RemoteCoordinator("127.0.0.1", port, resume_window_sec=1.0)
    fired = []
    assert rc.create("/jubatus/actors/x/n/nodes/h", b"", ephemeral=True)
    rc.watch_delete("/jubatus/actors/x/n/nodes/h", lambda p: fired.append(p))
    srv.stop()
    deadline = time.time() + 15
    while time.time() < deadline and not rc._closed:
        time.sleep(0.2)
    assert rc._closed
    assert fired == ["/jubatus/actors/x/n/nodes/h"]


@pytest.mark.slow
def test_engine_server_survives_coordd_restart(tmp_path):
    """Full stack under a coordd kill/restart: the engine server's session
    resumes, membership re-registers, the suicide watcher does NOT fire,
    and a client keeps training — config served from the recovered
    journal."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.coord import membership
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    jpath = str(tmp_path / "coord.journal")
    coordd = CoordServer(lease_sec=1.0, journal_path=jpath)
    port = coordd.start(0, "127.0.0.1")
    locator = f"tcp://127.0.0.1:{port}"
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    # config in the store, like jubaconfig would write it
    import json

    coordd._root.set(membership.config_path("classifier", "rs"),
                     json.dumps(conf).encode())
    args = ServerArgs(engine="classifier", coordinator=locator, name="rs",
                      listen_addr="127.0.0.1", interval_sec=1e9,
                      interval_count=1 << 30)
    srv = EngineServer.from_args(args)
    sport = srv.start(0)
    try:
        c = ClassifierClient("127.0.0.1", sport, "rs")
        assert c.train([["pos", Datum({"x": 1.0})]]) == 1

        coordd.stop()          # crash
        time.sleep(2.0)        # heartbeats fail meanwhile
        coordd2 = CoordServer(lease_sec=1.0, journal_path=jpath)
        coordd2.start(port, "127.0.0.1")
        try:
            node_dir = membership.actor_path("classifier", "rs") + "/nodes"
            deadline = time.time() + 15
            while time.time() < deadline:
                if coordd2._root.list(node_dir):
                    break
                time.sleep(0.2)
            assert coordd2._root.list(node_dir), "membership not re-created"
            # recovered journal still serves the config
            assert coordd2._root.read(
                membership.config_path("classifier", "rs")) is not None
            # server alive and serving (suicide watcher did not fire)
            assert c.train([["neg", Datum({"x": -1.0})]]) == 1
            res = c.classify([Datum({"x": 1.0})])
            assert res
        finally:
            coordd2.stop()
        c.close()
    finally:
        srv.stop()


def test_close_during_outage_does_not_fire_suicide():
    """Intentional shutdown while coordd is down must NOT run the
    session-lost suicide path (code-review: close() during _try_resume
    fell through to _session_lost)."""
    srv = CoordServer(lease_sec=0.6)
    port = srv.start(0, "127.0.0.1")
    rc = RemoteCoordinator("127.0.0.1", port, resume_window_sec=30.0)
    fired = []
    assert rc.create("/jubatus/actors/x/n/nodes/h", b"", ephemeral=True)
    rc.watch_delete("/jubatus/actors/x/n/nodes/h", lambda p: fired.append(p))
    srv.stop()
    time.sleep(1.5)  # let heartbeats fail into the resume loop
    rc.close()       # operator shutdown during the outage
    rc._hb.join(timeout=10)
    assert not rc._hb.is_alive()
    assert fired == [], "suicide watcher fired on intentional close"
