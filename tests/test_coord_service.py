"""Coordination service tests — the framework's own ZooKeeper-role daemon
(coord/server.py + coord/remote.py). The reference never shipped a ZK
mock (zk.hpp:36 TODO); here the real service IS testable in-process:
session expiry, ephemeral cleanup, locks, watches, and a full engine
cluster coordinating over tcp://.
"""

from __future__ import annotations

import time

import pytest

from jubatus_tpu.coord import create_coordinator
from jubatus_tpu.coord.remote import RemoteCoordinator
from jubatus_tpu.coord.server import CoordServer


@pytest.fixture()
def service():
    srv = CoordServer(lease_sec=1.5)
    port = srv.start(0, host="127.0.0.1")
    yield srv, port
    srv.stop()


def _client(port) -> RemoteCoordinator:
    return RemoteCoordinator("127.0.0.1", port)


def test_locator_parsing(service):
    _srv, port = service
    c = create_coordinator(f"tcp://127.0.0.1:{port}")
    assert isinstance(c, RemoteCoordinator)
    c.close()
    c = create_coordinator(f"127.0.0.1:{port}")
    assert isinstance(c, RemoteCoordinator)
    c.close()


def test_crud_roundtrip(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.create("/x/y", b"payload")
        assert not b.create("/x/y")          # already exists
        assert b.read("/x/y") == b"payload"
        assert b.exists("/x/y")
        assert a.set("/x/y", b"v2") and b.read("/x/y") == b"v2"
        a.create("/x/z")
        assert b.list("/x") == ["y", "z"]
        assert b.remove("/x/y") and not b.exists("/x/y")
    finally:
        a.close(), b.close()


def test_ephemerals_die_with_session(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        a.create("/e/one", ephemeral=True)
        assert b.exists("/e/one")
        a.close()
        assert not b.exists("/e/one")
    finally:
        b.close()


def test_session_lease_expiry(service):
    srv, port = service
    a, b = _client(port), _client(port)
    try:
        a.create("/lease/node", ephemeral=True)
        a._hb_stop.set()  # simulate client death: heartbeats stop
        deadline = time.time() + 6
        while time.time() < deadline and b.exists("/lease/node"):
            time.sleep(0.2)
        assert not b.exists("/lease/node"), "lease never expired"
    finally:
        b.close()
        a._closed = True
        a._client.close()


def test_locks_are_session_scoped(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.try_lock("/locks/m")
        assert not b.try_lock("/locks/m")
        assert not b.unlock("/locks/m")  # not the owner
        assert a.unlock("/locks/m")
        assert b.try_lock("/locks/m")
    finally:
        a.close(), b.close()


def test_lock_released_on_session_close(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        assert a.try_lock("/locks/n")
        a.close()
        assert b.try_lock("/locks/n")
    finally:
        b.close()


def test_create_id_monotonic_across_sessions(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    try:
        ids = [a.create_id("/ids/g"), b.create_id("/ids/g"),
               a.create_id("/ids/g")]
        assert ids == sorted(set(ids)), "ids must be unique and increasing"
    finally:
        a.close(), b.close()


def test_watch_children_and_delete(service):
    _srv, port = service
    a, b = _client(port), _client(port)
    fired = {"child": 0, "delete": 0}
    try:
        a.create("/w/seed")
        a.watch_children("/w", lambda _p: fired.__setitem__(
            "child", fired["child"] + 1))
        a.watch_delete("/w/seed", lambda _p: fired.__setitem__(
            "delete", fired["delete"] + 1))
        b.create("/w/new")
        b.remove("/w/seed")
        deadline = time.time() + 5
        while time.time() < deadline and (not fired["child"] or not fired["delete"]):
            time.sleep(0.1)
        assert fired["child"] >= 1
        assert fired["delete"] == 1
    finally:
        a.close(), b.close()


@pytest.mark.slow
def test_engine_cluster_over_tcp_coordinator(service):
    """Full stack: 2 classifier servers + proxy coordinate over tcp://,
    train through the proxy, mix, classify."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    _srv, port = service
    locator = f"tcp://127.0.0.1:{port}"
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    servers = []
    for _ in range(2):
        args = ServerArgs(engine="classifier", coordinator=locator, name="tc",
                          listen_addr="127.0.0.1", interval_sec=1e9,
                          interval_count=1 << 30)
        s = EngineServer("classifier", conf, args)
        s.start(0)
        servers.append(s)
    proxy = Proxy(ProxyArgs(engine="classifier", coordinator=locator,
                            listen_addr="127.0.0.1"))
    proxy.start(0)
    try:
        c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, "tc")
        for _ in range(10):
            c.train([["pos", Datum({"x": 1.0})]])
            c.train([["neg", Datum({"x": -1.0})]])
        assert len(c.get_status()) == 2  # both backends via tcp membership
        assert c.do_mix() is True
        res = c.classify([Datum({"x": 1.0}), Datum({"x": -1.0})])
        assert [max(r, key=lambda s: s[1])[0] for r in res] == ["pos", "neg"]
        c.close()
    finally:
        proxy.stop()
        for s in servers:
            s.stop()
