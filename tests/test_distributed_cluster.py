"""Distributed cluster tests: N real servers + coordinator + RPC mix.

The reference's highest test tier (client_test via jubatest + the
linear_mixer stub tests) in-process: servers share a MemoryCoordinator
store, register membership, elect a mix master, and average models over
the wire (framework/linear_mixer.py).
"""

from __future__ import annotations

import pytest

from jubatus_tpu.client import ClassifierClient, Datum, StatClient
from jubatus_tpu.coord import membership
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.framework.linear_mixer import (
    LinearCommunication,
    RpcLinearMixer,
)
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}

NAME = "cl"


def _cluster(engine, conf, n, store):
    servers = []
    for _ in range(n):
        args = ServerArgs(
            engine=engine, coordinator="(shared)", name=NAME,
            listen_addr="127.0.0.1", interval_sec=1e9, interval_count=1 << 30,
        )
        srv = EngineServer(engine, conf, args, coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    return servers


@pytest.fixture()
def cluster():
    store = _Store()
    servers = _cluster("classifier", CONF, 3, store)
    yield servers, store
    for s in servers:
        s.stop()


def test_membership_registered(cluster):
    servers, store = cluster
    view = MemoryCoordinator(store)
    nodes = membership.get_all_nodes(view, "classifier", NAME)
    assert len(nodes) == 3
    assert {n.port for n in nodes} == {s.args.rpc_port for s in servers}


def test_mix_averages_models(cluster):
    servers, _ = cluster
    # each node trains a DIFFERENT class — only mixing can teach them both
    c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
    c1 = ClassifierClient("127.0.0.1", servers[1].args.rpc_port, NAME)
    c2 = ClassifierClient("127.0.0.1", servers[2].args.rpc_port, NAME)
    for _ in range(10):
        c0.train([["pos", Datum({"x": 1.0, "y": 0.2})]])
        c1.train([["neg", Datum({"x": -1.0, "y": -0.2})]])
    # before mix: node 2 has never seen any data
    assert c2.get_labels() == {}
    assert c2.do_mix() is True
    labels2 = c2.get_labels()
    assert set(labels2) == {"pos", "neg"}
    # after mix every node classifies both classes correctly
    for c in (c0, c1, c2):
        (res,) = c.classify([Datum({"x": 1.0, "y": 0.2})])
        assert max(res, key=lambda ls: ls[1])[0] == "pos"
        (res,) = c.classify([Datum({"x": -1.0, "y": -0.2})])
        assert max(res, key=lambda ls: ls[1])[0] == "neg"
    for c in (c0, c1, c2):
        c.close()


def test_mix_counts_updates(cluster):
    servers, _ = cluster
    c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
    c0.train([["a", Datum({"x": 1.0})]])
    st = c0.get_status()
    (node_st,) = st.values()
    assert node_st["mixer.counter"] >= 1  # update reached the mixer
    c0.do_mix()
    st = c0.get_status()
    (node_st,) = st.values()
    assert node_st["mixer.mix_count"] == 1
    assert node_st["mixer.counter"] == 0  # reset by the round
    c0.close()


def test_stat_cluster_mix():
    """Engines with dict-shaped sparse diffs mix over RPC too."""
    store = _Store()
    servers = _cluster("stat", {"window_size": 64}, 2, store)
    try:
        s0 = StatClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        s1 = StatClient("127.0.0.1", servers[1].args.rpc_port, NAME)
        for v in (1.0, 2.0):
            s0.push("k", v)
        for v in (3.0, 4.0):
            s1.push("k", v)
        s0.do_mix()
        # stat's mix shares cluster-wide counts; local windows stay local
        assert s0.sum("k") == pytest.approx(3.0)
        assert s1.sum("k") == pytest.approx(7.0)
        s0.close()
        s1.close()
    finally:
        for s in servers:
            s.stop()


class _StubComm(LinearCommunication):
    """The reference's linear_communication_stub (linear_mixer_test.cpp:65-112):
    canned get_diff payloads, captured put_diff."""

    def __init__(self, canned):
        self.canned = canned
        self.put = []

    def update_members(self):
        from jubatus_tpu.coord.base import NodeInfo

        return [NodeInfo("s", i) for i in range(len(self.canned))]

    def try_lock(self):
        return True

    def unlock(self):
        pass

    def get_diff(self):
        from jubatus_tpu.coord.base import NodeInfo

        return [(NodeInfo("s", i), p) for i, p in enumerate(self.canned)]

    def put_diff(self, packed):
        self.put.append(packed)
        return {f"s_{i}": True for i in range(len(self.canned))}

    def get_model(self, member):
        raise AssertionError("not used")


def test_late_joiner_recovers_full_model():
    """A node joining AFTER the cluster has mixed is version-obsolete: the
    next round's delta fold cannot teach it (peers' knowledge lives in
    their master arrays), so it must pull a full model from a peer
    (linear_mixer.cpp:598-632)."""
    import time

    store = _Store()
    servers = _cluster("classifier", CONF, 2, store)
    try:
        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        for _ in range(10):
            c0.train([["pos", Datum({"x": 1.0, "y": 0.2})]])
            c0.train([["neg", Datum({"x": -1.0, "y": -0.2})]])
        assert c0.do_mix() is True  # cluster now at model version 1
        # late joiner: fresh model, version 0
        servers += _cluster("classifier", CONF, 1, store)
        late = servers[-1]
        assert c0.do_mix() is True  # marks the joiner obsolete
        cl = ClassifierClient("127.0.0.1", late.args.rpc_port, NAME)
        deadline = time.time() + 20
        top = None
        while time.time() < deadline:
            (res,) = cl.classify([Datum({"x": 1.0, "y": 0.2})])
            if res:
                top = max(res, key=lambda s: s[1])[0]
                if top == "pos":
                    break
            time.sleep(0.2)
        assert top == "pos", "late joiner never recovered the full model"
        (st,) = cl.get_status().values()
        assert st["mixer.model_version"] >= 1
        assert st["mixer.obsolete"] is False
        c0.close(), cl.close()
    finally:
        for s in servers:
            s.stop()


def test_mixer_fold_with_stub():
    """Mix rounds run against canned diffs — no sockets, no coordinator."""
    from jubatus_tpu.framework.linear_mixer import PROTOCOL_VERSION, unpack_mix
    from jubatus_tpu.server.factory import create_driver
    from jubatus_tpu.utils.serialization import pack_obj

    import numpy as np

    driver = create_driver("stat", {"window_size": 8})
    driver.push("k", 5.0)
    local = driver.get_mixables()["stat"].get_diff()
    remote = {"counts": np.asarray([2.0], dtype=np.float32)}
    canned = [
        pack_obj({"protocol": PROTOCOL_VERSION, "schema": ["k"], "diffs": {"stat": local}}),
        pack_obj({"protocol": PROTOCOL_VERSION, "schema": ["k"], "diffs": {"stat": remote}}),
    ]
    comm = _StubComm(canned)
    mixer = RpcLinearMixer(driver, comm)
    result = mixer.mix_now()
    assert result is not None
    assert len(comm.put) == 1
    folded = unpack_mix(comm.put[0])["diffs"]["stat"]
    # stat diff = {"counts": per-key window counts}; 1 (local) + 2 (canned)
    assert folded["counts"][0] == pytest.approx(3.0)


def test_anomaly_direct_add_replicates_before_mix():
    """Server-side replicated write (anomaly_serv.cpp:155-211): a
    direct-to-server add must land on BOTH its CHT(2) nodes immediately —
    not at the next mix round (mix intervals here are effectively off)."""
    store = _Store()
    conf = {"method": "lof",
            "parameter": {"nearest_neighbor_num": 3,
                          "reverse_nearest_neighbor_num": 6,
                          "method": "euclid_lsh",
                          "parameter": {"hash_num": 8}},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    servers = _cluster("anomaly", conf, 3, store)
    try:
        from jubatus_tpu.client import AnomalyClient, Datum

        c = AnomalyClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        ids = []
        for i in range(6):
            rid, _score = c.add(Datum({"x": float(i), "y": float(-i)}))
            ids.append(rid)
        c.close()
        from jubatus_tpu.coord.cht import CHT

        cht = CHT.from_coordinator(MemoryCoordinator(store), "anomaly", NAME)
        by_name = {s.self_nodeinfo().name: s for s in servers}
        for rid in ids:
            owners = [n.name for n in cht.find(rid, 2)]
            assert len(owners) == 2
            for owner in owners:
                rows = by_name[owner].driver.get_all_rows()
                assert rid in rows, (
                    f"row {rid} missing on {owner} before any mix")
        # and nowhere else (CHT placement, not broadcast)
        for rid in ids:
            owners = {n.name for n in cht.find(rid, 2)}
            for nm, srv in by_name.items():
                if nm not in owners:
                    assert rid not in srv.driver.get_all_rows()
    finally:
        for s in servers:
            s.stop()


def test_graph_direct_create_node_replicates_before_mix():
    """graph_serv.cpp:181-228: create_node lands on its CHT(2) nodes via
    direct peer RPC (create_node_here), visible before any mix."""
    store = _Store()
    conf = {"method": "graph_wo_index", "parameter": {}}
    servers = _cluster("graph", conf, 3, store)
    try:
        from jubatus_tpu.client import GraphClient

        c = GraphClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        nids = [c.create_node() for _ in range(6)]
        c.close()
        from jubatus_tpu.coord.cht import CHT

        cht = CHT.from_coordinator(MemoryCoordinator(store), "graph", NAME)
        by_name = {s.self_nodeinfo().name: s for s in servers}
        for nid in nids:
            owners = [n.name for n in cht.find(nid, 2)]
            for owner in owners:
                assert nid in by_name[owner].driver.nodes, (
                    f"node {nid} missing on {owner} before any mix")
    finally:
        for s in servers:
            s.stop()
