"""Elastic membership (ISSUE 10): epoch-versioned CHT, live resharding,
drain under traffic.

Covers the churn acceptance story in-process:

- membership epoch bumps on ACTUAL join/leave only;
- the proxy's ring cache rebuilds only on membership change (the
  per-request ``CHT(actives)`` fix) and the double-dispatch window
  leaves no key with zero owners;
- drain rejects new effectful work with the retryable ``NodeDraining``
  (wire code 4) while finishing in-flight work, then hands every row
  to its new ring owners;
- migration pulls resume/fail over when a source dies mid-stream;
- a full join -> migrate -> drain cycle loses zero rows (row-count
  parity for the get_rows/put_rows driver hooks).
"""

from __future__ import annotations

import threading
import time

import pytest

from jubatus_tpu.coord import membership
from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.coord.cht import CHT, ring_key
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.core.datum import Datum
from jubatus_tpu.framework import migration
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.errors import (
    EPOCH_MISMATCH_ERROR,
    NODE_DRAINING_ERROR,
    EpochMismatch,
    NodeDraining,
    error_to_wire,
    is_retryable,
    wire_to_error,
)
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.server.proxy import Proxy, ProxyArgs, _RingCache

ENGINE = "nearest_neighbor"
NAME = "nn"
CONF = {"method": "lsh", "parameter": {"hash_num": 8},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]}}


def _boot(store, auto_rebalance=True, drain_grace=0.2):
    args = ServerArgs(engine=ENGINE, coordinator="(shared)", name=NAME,
                      listen_addr="127.0.0.1", interval_sec=1e9,
                      interval_count=1 << 30,
                      auto_rebalance=auto_rebalance,
                      drain_grace=drain_grace)
    srv = EngineServer(ENGINE, CONF, args, coord=MemoryCoordinator(store))
    srv.start(0)
    return srv


def _client(srv) -> RpcClient:
    return RpcClient("127.0.0.1", srv.args.rpc_port, timeout=30.0)


def _datum(i: int) -> Datum:
    return Datum({"f0": float(i) + 1.0, "f1": float(i % 7) + 1.0})


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _drain_state(cli) -> str:
    st = cli.call("drain_status", NAME)
    state = st.get("state")
    return state.decode() if isinstance(state, bytes) else state


# -- epoch protocol -----------------------------------------------------------


def test_epoch_bumps_on_actual_join_and_leave_only():
    store = _Store()
    c = MemoryCoordinator(store)
    assert membership.get_epoch(c, ENGINE, NAME) == 0
    membership.register_active(c, ENGINE, NAME, "127.0.0.1", 9000)
    assert membership.get_epoch(c, ENGINE, NAME) == 1
    # re-registration (the post-put_diff self-promotion path) is NOT a
    # membership change
    membership.register_active(c, ENGINE, NAME, "127.0.0.1", 9000)
    assert membership.get_epoch(c, ENGINE, NAME) == 1
    membership.register_active(c, ENGINE, NAME, "127.0.0.1", 9001)
    assert membership.get_epoch(c, ENGINE, NAME) == 2
    membership.unregister_active(c, ENGINE, NAME, "127.0.0.1", 9000)
    assert membership.get_epoch(c, ENGINE, NAME) == 3
    # removing an absent member is not a change either
    membership.unregister_active(c, ENGINE, NAME, "127.0.0.1", 9000)
    assert membership.get_epoch(c, ENGINE, NAME) == 3
    ring = CHT.from_coordinator(c, ENGINE, NAME)
    assert ring.epoch == 3
    assert ring.key == ring_key(ring.members)


def test_epoch_bumps_when_servers_join_and_drain():
    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    try:
        view = MemoryCoordinator(store)
        assert membership.get_epoch(view, ENGINE, NAME) == 2
        assert s1.get_epoch() == 2
        cli = _client(s1)
        cli.call("drain", NAME, False)
        assert _wait(lambda: _drain_state(cli) == "drained")
        # drain = one leave -> one bump; the drained member is marked
        # then cleared
        assert membership.get_epoch(view, ENGINE, NAME) == 3
        assert membership.get_draining(view, ENGINE, NAME) == []
        cli.close()
    finally:
        s1.stop()
        s2.stop()


def test_wire_codes_round_trip_and_are_retryable():
    assert error_to_wire(NodeDraining()) == NODE_DRAINING_ERROR
    assert error_to_wire(EpochMismatch()) == EPOCH_MISMATCH_ERROR
    nd = wire_to_error(NODE_DRAINING_ERROR, "set_row")
    em = wire_to_error(EPOCH_MISMATCH_ERROR, "migrate_range")
    assert isinstance(nd, NodeDraining) and is_retryable(nd)
    assert isinstance(em, EpochMismatch) and is_retryable(em)


def test_migrate_range_rejects_stale_epoch():
    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    try:
        cli = _client(s1)
        good = cli.call("migrate_range", NAME, s1.get_epoch(),
                        s2.self_nodeinfo().name, "", 1 << 20)
        assert good.get("done") is True
        with pytest.raises(EpochMismatch):
            cli.call("migrate_range", NAME, s1.get_epoch() + 17,
                     s2.self_nodeinfo().name, "", 1 << 20)
        cli.close()
    finally:
        s1.stop()
        s2.stop()


# -- ring cache + double-dispatch window -------------------------------------


def test_ring_cache_rebuilds_only_on_membership_change():
    rings = _RingCache(handoff_window=60.0)
    a = [NodeInfo("10.0.0.1", 1), NodeInfo("10.0.0.2", 2)]
    r1, prev = rings.get("c", a)
    assert prev is None and rings.builds == 1
    for _ in range(50):
        r, prev = rings.get("c", list(reversed(a)))  # order-insensitive
        assert r is r1 and prev is None
    assert rings.builds == 1 and rings.hits == 50


def test_ring_cache_handoff_window_and_expiry():
    rings = _RingCache(handoff_window=0.2)
    a = [NodeInfo("10.0.0.1", 1), NodeInfo("10.0.0.2", 2)]
    b = a + [NodeInfo("10.0.0.3", 3)]
    r_old, _ = rings.get("c", a)
    r_new, prev = rings.get("c", b)
    assert prev is r_old and r_new is not r_old
    assert rings.stats()["in_handoff"] == 1
    time.sleep(0.25)
    _, prev = rings.get("c", b)
    assert prev is None  # window over: old ring forgotten
    assert rings.stats()["in_handoff"] == 0


def test_double_dispatch_union_leaves_no_key_without_owners():
    """For any single join/leave, every key's dispatch set during the
    handoff window (union of old+new owners) contains at least one
    member of BOTH rings — no zero-owner window, and always a live
    (new-ring) owner."""
    base = [NodeInfo("10.0.0.1", 1), NodeInfo("10.0.0.2", 2),
            NodeInfo("10.0.0.3", 3)]
    scenarios = [
        (base, base + [NodeInfo("10.0.0.4", 4)]),       # join
        (base, base[:-1]),                               # leave
        (base, base[:-1] + [NodeInfo("10.0.0.5", 5)]),   # replace
    ]
    for old_members, new_members in scenarios:
        old, new = CHT(old_members), CHT(new_members)
        live = {m.name for m in new_members}
        stale = {m.name for m in old_members}
        for i in range(200):
            key = f"k{i}"
            union = {n.name for n in new.find(key, 2)} \
                | {n.name for n in old.find(key, 2)}
            assert union & live, f"key {key}: no live owner in union"
            assert union & stale, f"key {key}: old owners dropped"


# -- drain under traffic ------------------------------------------------------


def test_drain_rejects_new_effectful_finishes_inflight():
    store = _Store()
    s1 = _boot(store, drain_grace=0.5)
    s2 = _boot(store)
    cli = _client(s1)
    cli2 = _client(s1)
    try:
        cli.call("set_row", NAME, "pre", _datum(0).to_msgpack())
        # make the NEXT set_row slow: it will be in flight when the
        # drain gate flips, and must still complete successfully
        release = threading.Event()
        entered = threading.Event()
        real = s1.driver.set_row

        def slow_set_row(rid, datum):
            entered.set()
            release.wait(20.0)
            return real(rid, datum)

        s1.driver.set_row = slow_set_row
        result: dict = {}

        def inflight():
            try:
                result["ok"] = cli2.call("set_row", NAME, "inflight",
                                         _datum(1).to_msgpack())
            except Exception as e:  # noqa: BLE001 — asserted below
                result["err"] = e

        t = threading.Thread(target=inflight, daemon=True)
        t.start()
        assert entered.wait(10.0)
        # drain while the call is in flight
        cli.call("drain", NAME, False)
        assert _wait(lambda: _drain_state(cli) in ("draining", "handoff",
                                                   "drained"))
        # NEW effectful work is rejected with the retryable NodeDraining
        with pytest.raises(NodeDraining):
            cli.call("set_row", NAME, "rejected", _datum(2).to_msgpack())
        assert s1.rpc.trace.counters().get("rpc.drain_rejected", 0) >= 1
        # reads keep serving
        assert isinstance(cli.call("get_all_rows", NAME), list)
        # the in-flight call finishes (drain waits; handoff needs the
        # driver lock the slow call holds)
        release.set()
        t.join(15.0)
        assert result.get("ok") is True
        assert _wait(lambda: _drain_state(cli) == "drained")
        # ... and the row it wrote was handed off to the survivor
        c2 = _client(s2)
        ids = {i.decode() if isinstance(i, bytes) else i
               for i in c2.call("get_all_rows", NAME)}
        assert {"pre", "inflight"} <= ids
        c2.close()
    finally:
        cli.close()
        cli2.close()
        s1.stop()
        s2.stop()


def test_proxy_reroutes_during_drain_no_client_errors():
    """The zero-error-spike story in miniature: effectful CHT-routed
    writes through the proxy keep succeeding while a backend drains
    (NodeDraining -> ring refresh -> re-route, double-dispatch window
    covering the swap)."""
    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    proxy = Proxy(ProxyArgs(engine=ENGINE, listen_addr="127.0.0.1",
                            interconnect_timeout=30.0),
                  coord=MemoryCoordinator(store))
    pport = proxy.start(0)
    pcli = RpcClient("127.0.0.1", pport, timeout=30.0)
    cli1 = _client(s1)
    try:
        for i in range(10):
            assert pcli.call("set_row", NAME, f"r{i}",
                             _datum(i).to_msgpack()) is True
        cli1.call("drain", NAME, False)
        # no error spike: every write during and after the drain lands
        for i in range(10, 30):
            assert pcli.call("set_row", NAME, f"r{i}",
                             _datum(i).to_msgpack()) is True
        assert _wait(lambda: _drain_state(cli1) == "drained")
        for i in range(30, 40):
            assert pcli.call("set_row", NAME, f"r{i}",
                             _datum(i).to_msgpack()) is True
        # reads during the window resolve too
        for i in range(0, 40, 7):
            assert isinstance(
                pcli.call("neighbor_row_from_id", NAME, f"r{i}", 3), list)
        # every row survives on the remaining member
        c2 = _client(s2)
        ids = {i.decode() if isinstance(i, bytes) else i
               for i in c2.call("get_all_rows", NAME)}
        assert {f"r{i}" for i in range(40)} <= ids
        c2.close()
    finally:
        pcli.close()
        cli1.close()
        proxy.stop()
        s1.stop()
        s2.stop()


# -- migration data plane -----------------------------------------------------


def test_serve_range_cursor_resume_and_chunking():
    from jubatus_tpu.models.nearest_neighbor import NearestNeighborDriver

    d = NearestNeighborDriver(CONF)
    for i in range(50):
        d.set_row(f"row{i:03d}", _datum(i))
    members = [NodeInfo("10.0.0.1", 1), NodeInfo("10.0.0.2", 2)]
    ring = CHT(members)
    target = "10.0.0.1_1"
    owned = [rid for rid in sorted(d.row_ids())
             if migration.row_owned_by(ring, rid, target)]
    # walk with a tiny byte budget: strictly increasing cursors, exact
    # coverage, no duplicates
    got, cursor, chunks = [], "", 0
    while True:
        doc = migration.serve_range(d, ring, target, cursor,
                                    limit_bytes=1)
        got.extend(r[0] for r in doc["rows"])
        chunks += 1
        if doc["done"]:
            break
        assert doc["cursor"] > cursor
        cursor = doc["cursor"]
    assert got == owned
    assert chunks >= len(owned)  # 1-byte budget = 1 row per chunk
    # resume from any midpoint re-serves exactly the tail
    if len(owned) > 2:
        mid = owned[len(owned) // 2]
        doc = migration.serve_range(d, ring, target, mid,
                                    limit_bytes=1 << 20)
        assert [r[0] for r in doc["rows"]] == \
            [rid for rid in owned if rid > mid]


def test_migration_resumes_after_midstream_source_crash():
    """Kill a source after its first chunk: the puller fails over and
    total coverage still holds because rows are CHT(2)-replicated onto
    the dead source's ring successor."""
    store = _Store()
    servers = [_boot(store, auto_rebalance=False) for _ in range(3)]
    joiner = _boot(store, auto_rebalance=False)
    try:
        nodes = [s.self_nodeinfo() for s in servers]
        ring = CHT(nodes)
        clients = {s.self_nodeinfo().name: _client(s) for s in servers}
        # CHT-correct placement: each row lands on BOTH its ring owners
        all_rows = [f"row{i:03d}" for i in range(60)]
        for rid in all_rows:
            for owner in ring.find(rid, 2):
                clients[owner.name].call(
                    "set_row", NAME, rid,
                    _datum(int(rid[3:])).to_msgpack())
        me = joiner.self_nodeinfo()
        victim = servers[0]
        victim_name = victim.self_nodeinfo().name
        chunk_log = []

        def apply_rows(rows):
            chunk_log.append(len(rows))
            with joiner.driver.lock:
                n = joiner.driver.put_rows(rows)
            if len(chunk_log) == 1:
                victim.stop()  # mid-stream crash after the first chunk
            return n

        puller = migration.RangePuller(
            NAME, me.name, apply_rows,
            client_factory=joiner.peer_client, stats=joiner.migration,
            chunk_bytes=64,  # force many chunks
            epoch_of=lambda: joiner.get_epoch())
        # victim first, so the crash happens mid-pull
        out = puller.pull([victim.self_nodeinfo()] + nodes[1:])
        assert out["sources_failed"] == [victim_name]
        assert joiner.migration.snapshot()["failovers"] >= 1
        # coverage: every row the joiner owns under the POST-JOIN ring
        # arrived, despite the dead source
        new_ring = CHT(nodes[1:] + [me])
        expected = {rid for rid in all_rows
                    if migration.row_owned_by(new_ring, rid, me.name)}
        have = set(joiner.driver.row_ids())
        assert expected <= have
        for c in clients.values():
            c.close()
    finally:
        for s in servers + [joiner]:
            s.stop()


@pytest.mark.slow
def test_full_cycle_join_migrate_leave_row_parity():
    """Acceptance: zero rows lost across a join -> migrate -> leave
    cycle, with row-count parity between get_rows and put_rows."""
    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    servers = [s1, s2]
    try:
        c1, c2 = _client(s1), _client(s2)
        total = 80
        for i in range(total):
            (c1 if i % 2 == 0 else c2).call(
                "set_row", NAME, f"row{i:03d}", _datum(i).to_msgpack())
        # driver-hook parity: a get_rows/put_rows round trip is exact
        with s1.driver.lock:
            rows = s1.driver.get_rows()
        from jubatus_tpu.models.nearest_neighbor import \
            NearestNeighborDriver

        scratch = NearestNeighborDriver(CONF)
        assert scratch.put_rows(rows) == len(rows) == len(s1.driver.row_ids())
        assert sorted(scratch.row_ids()) == sorted(s1.driver.row_ids())
        # join: the new member pulls its owned ranges automatically
        s3 = _boot(store)
        servers.append(s3)
        assert _wait(lambda: s3.migration.snapshot()["pulls"] >= 1
                     and s3.migration.snapshot()["active"] == 0)
        # leave: drain the most loaded original member
        c1.call("drain", NAME, False)
        assert _wait(lambda: _drain_state(c1) == "drained")
        union = set()
        for s in servers[1:]:
            c = _client(s)
            union |= {i.decode() if isinstance(i, bytes) else i
                      for i in c.call("get_all_rows", NAME)}
            c.close()
        expect = {f"row{i:03d}" for i in range(total)}
        assert expect - union == set(), "rows lost across the cycle"
        c1.close()
        c2.close()
    finally:
        for s in servers:
            s.stop()


# -- quorum + ops surface -----------------------------------------------------


def test_mixer_quorum_excludes_draining_members():
    from jubatus_tpu.framework.linear_mixer import RpcLinearCommunication

    store = _Store()
    c = MemoryCoordinator(store)
    for port in (9000, 9001, 9002):
        membership.register_actor(c, ENGINE, NAME, "127.0.0.1", port)
        membership.register_active(c, ENGINE, NAME, "127.0.0.1", port)
    comm = RpcLinearCommunication(MemoryCoordinator(store), ENGINE, NAME)
    assert len(comm.update_members()) == 3
    assert comm.membership_epoch() == 3
    membership.mark_draining(c, ENGINE, NAME, "127.0.0.1", 9000)
    members = comm.update_members()
    assert len(members) == 2
    assert "127.0.0.1_9000" not in {m.name for m in members}
    comm.close()


def test_jubactl_drain_and_rebalance(capsys, monkeypatch):
    from jubatus_tpu.cmd import jubactl

    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    servers = [s1, s2]
    try:
        c1 = _client(s1)
        for i in range(20):
            c1.call("set_row", NAME, f"row{i:03d}", _datum(i).to_msgpack())
        c1.close()
        view = MemoryCoordinator(store)
        # status shows the epoch
        assert jubactl.show_status(view, ENGINE, NAME) == 0
        out = capsys.readouterr().out
        assert "epoch 2" in out
        # rebalance pulls rows onto the under-replicated member
        assert jubactl.rebalance_cluster(view, ENGINE, NAME) == 0
        out = capsys.readouterr().out
        assert "rebalance complete" in out
        # drain via the CLI entry point
        target = s1.self_nodeinfo().name
        assert jubactl.drain_member(view, ENGINE, NAME, target) == 0
        out = capsys.readouterr().out
        assert "drained" in out
        # bad target is a usage error
        assert jubactl.drain_member(view, ENGINE, NAME, "") == 1
        assert jubactl.drain_member(view, ENGINE, NAME, "nope") == 1
    finally:
        for s in servers:
            s.stop()


def test_status_and_watch_carry_epoch_and_drain_state():
    store = _Store()
    s1 = _boot(store)
    s2 = _boot(store)
    try:
        cli = _client(s1)
        st = cli.call("get_status", NAME)
        doc = next(iter(st.values()))
        assert doc.get("cluster.epoch") == 2
        assert doc.get("drain.state") == "active"
        assert "migration.rows_moved" in doc
        cli.close()
        from jubatus_tpu.cmd.jubactl import collect_watch, \
            render_watch_frame

        view = MemoryCoordinator(store)
        frame = render_watch_frame(collect_watch(view, ENGINE, NAME, 5.0))
        assert "epoch 2" in frame
    finally:
        s1.stop()
        s2.stop()
