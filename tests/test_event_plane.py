"""Cluster event plane + incident bundles (ISSUE 14): HLC monotonicity
and skewed-clock merge ordering, journal ring bounds, per-subsystem
emission (one test per emitting site), get_events envelope compat on
both transports, proxy fold, --follow cursor semantics, the codestyle
event-coverage gate, and the live 3-member acceptance: an induced SLO
breach produces one incident bundle with correlated trace_ids."""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

import pytest

from jubatus_tpu.utils import events, tracing
from jubatus_tpu.utils.events import (EventJournal, HLCClock, hlc_now,
                                      hlc_wall_s, merge_events, wall_to_hlc)
from jubatus_tpu.utils.incidents import IncidentManager

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- HLC ----------------------------------------------------------------------


def test_hlc_monotonic_within_one_process():
    c = HLCClock()
    stamps = [c.now() for _ in range(1000)]
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == len(stamps)  # strictly monotonic


def test_hlc_observe_orders_across_skewed_clocks():
    """Node B's wall clock runs BEHIND node A's. Without observation
    B's events would sort before A's; after B receives a message
    carrying A's HLC, B's subsequent events sort after it."""
    ahead, behind = HLCClock(), HLCClock()
    # simulate skew: push 'ahead' far into the future
    future = wall_to_hlc(time.time() + 3600)
    ahead.observe(future)
    a1 = ahead.now()
    b_pre = behind.now()
    assert b_pre < a1  # skew: B's un-connected events sort first
    behind.observe(a1)  # message from A arrives at B
    b_post = behind.now()
    assert b_post > a1  # causality restored despite the hour of skew
    # observe() of an older stamp must not move the clock backwards
    behind.observe(b_pre)
    assert behind.now() > b_post


def test_hlc_wall_roundtrip_and_since_filter():
    t = time.time()
    h = wall_to_hlc(t)
    assert abs(hlc_wall_s(h) - t) < 0.001
    j = EventJournal()
    early = j.emit("t", "early")
    late = j.emit("t", "late")
    assert [r["type"] for r in j.snapshot(since=early["hlc"])] == ["late"]
    assert j.snapshot(since=late["hlc"]) == []


def test_merge_events_skewed_nodes_causal_order():
    """Cross-node merge: a mix master on a fast clock broadcasts its
    HLC; the member's post-apply event sorts after the master's fold
    even though the member's wall clock is behind."""
    master, member = HLCClock(), HLCClock()
    master.observe(wall_to_hlc(time.time() + 1800))  # 30 min ahead
    fold = {"hlc": master.now(), "node": "A", "subsystem": "mix",
            "type": "round"}
    member.observe(fold["hlc"])  # put_diff payload carries it
    applied = {"hlc": member.now(), "node": "B", "subsystem": "mix",
               "type": "applied"}
    merged = merge_events([[applied], [fold]])
    assert [r["type"] for r in merged] == ["round", "applied"]


def test_merge_events_dedups_same_record():
    j = EventJournal()
    j.node = "n1"
    rec = j.emit("t", "x")
    merged = merge_events([[rec], [dict(rec)]])
    assert len(merged) == 1


# -- journal ring -------------------------------------------------------------


def test_journal_ring_bounds_and_eviction():
    reg = tracing.Registry()
    reg.events.set_capacity(5)
    for i in range(12):
        reg.events.emit("t", f"e{i}")
    st = reg.events.stats()
    assert st["emitted"] == 12 and st["retained"] == 5
    assert [r["type"] for r in reg.events.snapshot()] == \
        [f"e{i}" for i in range(7, 12)]
    counters = reg.counters()
    assert counters["event.emitted"] == 12
    assert counters["event.dropped"] == 7  # evictions past capacity


def test_journal_capacity_zero_disables_emission():
    j = EventJournal(capacity=0)
    assert not j.enabled
    assert j.emit("t", "x") is None
    assert j.snapshot() == [] and j.stats()["emitted"] == 0


def test_journal_grep_and_trace_capture():
    j = EventJournal()
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        j.emit("breaker", "open", backend="10.0.0.1:9199")
    j.emit("slo", "firing", name="lat.p99")
    assert [r["type"] for r in j.snapshot(grep="10.0.0.1")] == ["open"]
    assert j.snapshot(grep="nomatch") == []
    rec = j.snapshot(grep="open")[0]
    assert rec["trace_id"] == ctx.trace_id


# -- per-subsystem emission ---------------------------------------------------


def test_membership_epoch_bump_emits():
    from jubatus_tpu.coord import create_coordinator, membership

    coord = create_coordinator("memory")
    before = events.default_journal().stats()["emitted"]
    cur = hlc_now()
    membership.register_active(coord, "classifier", "evt", "127.0.0.1", 1)
    recs = [r for r in events.default_journal().snapshot(since=cur)
            if r["subsystem"] == "membership"]
    assert recs and recs[-1]["type"] == "epoch_bump"
    assert recs[-1]["epoch"] == 1
    assert events.default_journal().stats()["emitted"] > before
    coord.close()


def test_breaker_transitions_emit():
    from jubatus_tpu.rpc.breaker import BreakerBoard

    reg = tracing.Registry()
    b = BreakerBoard(registry=reg, failure_threshold=2, cooldown_sec=0.0,
                     counter_prefix="proxy.breaker")
    b.record("h:1", False)
    b.record("h:1", False)   # trips open
    assert b.allow("h:1")    # cooldown 0 -> half-open probe admitted
    b.record("h:1", True)    # probe success closes
    kinds = [r["type"] for r in reg.events.snapshot()
             if r["subsystem"] == "breaker"]
    assert kinds == ["open", "half_open", "close"]
    opened = [r for r in reg.events.snapshot() if r["type"] == "open"][0]
    assert opened["severity"] == "warning"
    assert opened["backend"] == "h:1"
    assert opened["plane"] == "proxy.breaker"


def test_slo_fire_and_clear_emit():
    from jubatus_tpu.utils.slo import SloEngine, parse_slo
    from jubatus_tpu.utils.timeseries import TimeSeriesRing

    reg = tracing.Registry()
    ring = TimeSeriesRing(capacity=16)
    eng = SloEngine([parse_slo("latency:rpc.x:p99:50")], ring, reg,
                    fast_window_s=10.0, slow_window_s=20.0)
    fired = []
    eng.on_fire = lambda name, st: fired.append(name)
    for _ in range(10):
        reg.record("rpc.x", 0.001)
    ring.sample(reg.snapshot(), ts=0.0)
    for _ in range(50):
        reg.record("rpc.x", 0.5)
    ring.sample(reg.snapshot(), ts=5.0)
    eng.evaluate(now=5.0)
    kinds = [r["type"] for r in reg.events.snapshot()
             if r["subsystem"] == "slo"]
    assert kinds == ["firing"]
    assert fired == ["rpc.x.p99"]  # incident hook ran exactly once
    # recovery clears -> resolved edge, no second on_fire
    for _ in range(2000):
        reg.record("rpc.x", 0.001)
    ring.sample(reg.snapshot(), ts=10.0)
    ring.sample(reg.snapshot(), ts=15.0)
    eng.evaluate(now=15.0)
    kinds = [r["type"] for r in reg.events.snapshot()
             if r["subsystem"] == "slo"]
    assert kinds == ["firing", "resolved"]
    assert fired == ["rpc.x.p99"]


def test_mixer_round_events_and_flight_cross_link():
    from jubatus_tpu.framework.mixer import IntervalMixer

    reg = tracing.Registry()
    m = IntervalMixer(lambda: {"mode": "rpc", "members": 3,
                               "contributors": 3})
    m.trace = reg
    m.mix_now()
    evs = [r for r in reg.events.snapshot() if r["subsystem"] == "mix"]
    assert [r["type"] for r in evs] == ["round_start", "round"]
    flight = m.flight.snapshot()[-1]
    # satellite: the flight record cross-links the round event's id AND
    # carries the HLC-derived stamp instead of an ad-hoc wall clock
    assert flight["event_hlc"] == evs[-1]["hlc"]
    assert flight["hlc"] > 0
    assert abs(flight["ts"] - hlc_wall_s(flight["hlc"])) < 0.002


def test_mixer_round_error_event():
    from jubatus_tpu.framework.mixer import IntervalMixer

    reg = tracing.Registry()

    def boom():
        raise RuntimeError("kaput")

    m = IntervalMixer(boom)
    m.trace = reg
    with pytest.raises(RuntimeError):
        m.mix_now()
    evs = [r for r in reg.events.snapshot() if r["subsystem"] == "mix"]
    assert [r["type"] for r in evs] == ["round_start", "round_error"]
    assert evs[-1]["severity"] == "error"
    assert m.flight.snapshot()[-1]["event_hlc"] == evs[-1]["hlc"]


def test_fault_arm_and_fire_emit():
    from jubatus_tpu.utils import faults

    cur = hlc_now()
    with faults.armed("evtest.site:error@1"):
        with pytest.raises(faults.FaultInjected):
            faults.fire("evtest.site")
    recs = [r for r in events.default_journal().snapshot(since=cur)
            if r["subsystem"] == "faults"]
    assert [r["type"] for r in recs] == ["armed", "fired"]
    assert recs[0]["rules"] == ["evtest.site:error@1"]
    assert recs[1]["site"] == "evtest.site"
    assert recs[1]["action"] == "error"


def test_autoscaler_journal_hlc_and_event_cross_link():
    from jubatus_tpu.coord.autoscaler import (AutoscaleConfig, Autoscaler,
                                              FleetSnapshot, HookActuator,
                                              ReplicaStats)

    reg = tracing.Registry()
    spawned = []
    scaler = Autoscaler(
        None, "classifier", "evt",
        HookActuator(lambda n: spawned.append(n), lambda t: None),
        config=AutoscaleConfig(min_replicas=1, max_replicas=4,
                               scale_out_confirm=1, cooldown_s=0.0),
        registry=reg)
    hot = FleetSnapshot(ts=100.0, replicas=[
        ReplicaStats("n1", burn_max=5.0, queue_depth=0.0)])
    rec = scaler.tick(hot, now=100.0)
    assert rec["action"] == "scale_out" and spawned == [1]
    # satellite: journal rides the HLC helper + cross-links the event
    assert rec["hlc"] > 0 and rec["event_hlc"] > 0
    evs = [r for r in reg.events.snapshot()
           if r["subsystem"] == "autoscale"]
    assert [r["type"] for r in evs] == ["scale_out"]
    assert evs[0]["hlc"] == rec["event_hlc"]
    # holds are journaled but NOT events (a 5 s poll cadence of holds
    # would drown the timeline)
    steady = FleetSnapshot(ts=200.0, replicas=[
        ReplicaStats("n1", burn_max=1.5)])
    rec2 = scaler.tick(steady, now=200.0)
    assert rec2["action"] == "hold" and "event_hlc" not in rec2
    assert len([r for r in reg.events.snapshot()
                if r["subsystem"] == "autoscale"]) == 1


def test_checkpoint_save_restore_emit(tmp_path):
    import jax.numpy as jnp

    from jubatus_tpu.framework.sharded_checkpoint import (load_sharded,
                                                          save_sharded)

    state = {"w": jnp.arange(8, dtype=jnp.float32)}
    cur = hlc_now()
    save_sharded(str(tmp_path / "ck"), state, engine_type="classifier",
                 model_id="m1", config="{}")
    system, restored = load_sharded(str(tmp_path / "ck"), state,
                                    expected_type="classifier")
    recs = [r for r in events.default_journal().snapshot(since=cur)
            if r["subsystem"] == "checkpoint"]
    assert [r["type"] for r in recs] == ["save", "restore"]
    assert recs[0]["model_id"] == "m1"


def test_drain_phase_events_via_server(tmp_path):
    """The drain state machine's phase edges land in the server's
    journal (draining -> handoff -> drained)."""
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator=coord_dir,
                        name="evd", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0, drain_grace=0.05))
    srv.start(0)
    try:
        srv.drain_ctl.start()
        deadline = time.monotonic() + 20
        while srv.drain_ctl.state != "drained" and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.drain_ctl.state == "drained"
        kinds = [r["type"] for r in srv.rpc.trace.events.snapshot()
                 if r["subsystem"] == "drain"]
        assert kinds == ["draining", "handoff", "drained"]
    finally:
        srv.stop()


# -- get_events / get_incidents over the wire ---------------------------------


@pytest.mark.parametrize("native", [False, True])
def test_get_events_envelope_compat(monkeypatch, native):
    """get_events / get_incidents answer plain AND traced/deadlined
    envelopes on both transports, and the since-cursor filters."""
    from jubatus_tpu.rpc import native_server
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer

    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1" if native else "0")
    srv = EngineServer("classifier", CONF)
    port = srv.start(0)
    try:
        marker = srv.rpc.trace.events.emit("t", "wire_probe", n=1)
        with RpcClient("127.0.0.1", port) as rc:
            # plain 4-element envelope
            doc = rc.call("get_events", "", 0, "")
            (d,) = doc.values()
            assert any(r["type"] == "wire_probe" for r in d["events"])
            assert d["hlc_now"] > marker["hlc"]
            # cursor: nothing strictly after the newest hlc
            newest = max(r["hlc"] for r in d["events"])
            empty = rc.call("get_events", "", newest, "")
            (d2,) = empty.values()
            assert d2["events"] == []
            # server-side grep
            g = rc.call("get_events", "", 0, "wire_probe")
            (dg,) = g.values()
            assert [r["type"] for r in dg["events"]] == ["wire_probe"]
            inc = rc.call("get_incidents", "", "")
            (di,) = inc.values()
            assert "incidents" in di and "stats" in di
        # traced + deadlined (5/6-element) envelope
        from jubatus_tpu.rpc import deadline as deadlines

        ctx = tracing.new_root()
        with tracing.use_trace(ctx), deadlines.deadline_after(30.0):
            with RpcClient("127.0.0.1", port) as rc:
                doc = rc.call("get_events", "", 0, "")
        (d3,) = doc.values()
        assert any(r["type"] == "wire_probe" for r in d3["events"])
    finally:
        srv.stop()


def test_incident_manager_debounce_cap_and_pull(tmp_path):
    reg = tracing.Registry()
    mgr = IncidentManager(reg, lambda: {"events": [], "extra": "x"},
                          lambda: str(tmp_path / "inc"), window_s=300.0,
                          capacity=3, journal=reg.events)
    first = mgr.trigger("slo_firing:a", trace_ids=["t1", "t2"])
    assert first is not None and first["id"].startswith("inc-")
    # debounced inside the window
    assert mgr.trigger("slo_firing:a") is None
    st = mgr.stats()
    assert st["captured"] == 1 and st["suppressed"] == 1
    assert reg.counters()["incident.captured"] == 1
    assert reg.counters()["incident.suppressed"] == 1
    # the capture itself is a timeline event
    assert [r["type"] for r in reg.events.snapshot()
            if r["subsystem"] == "incident"] == ["captured"]
    # force captures pierce the window; the dir cap prunes oldest
    ids = [first["id"]]
    for i in range(4):
        doc = mgr.trigger(f"manual:{i}", force=True)
        ids.append(doc["id"])
    listing = mgr.list()
    kept = [m["id"] for m in listing["incidents"]]
    assert len(kept) == 3 and kept == ids[-3:]
    # pull returns the full doc with the correlated trace ids
    pulled = mgr.get(ids[-1])
    assert pulled["reason"] == "manual:3" and pulled["extra"] == "x"
    assert "error" in mgr.get("inc-nope")
    assert "error" in mgr.get("../evil")


def test_follow_cursor_semantics_collect_events(tmp_path):
    """collect_events advances per-node HLC cursors: a second poll
    returns ONLY events emitted since the first (the --follow loop)."""
    from jubatus_tpu.cmd.jubactl import collect_events
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator=coord_dir,
                        name="evf", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0))
    srv.start(0)
    try:
        from jubatus_tpu.coord import create_coordinator

        coord = create_coordinator(coord_dir)
        cursors: dict = {}
        first = collect_events(coord, "classifier", "evf",
                               cursors=cursors)
        assert first  # boot produced membership events at least
        assert cursors  # cursor advanced to the max hlc seen
        again = collect_events(coord, "classifier", "evf",
                               cursors=cursors)
        assert again == []  # nothing new
        srv.rpc.trace.events.emit("t", "fresh_one")
        third = collect_events(coord, "classifier", "evf",
                               cursors=cursors)
        assert [r["type"] for r in third] == ["fresh_one"]
        coord.close()
    finally:
        srv.stop()


def test_proxy_folds_events_and_incidents(tmp_path):
    """One get_events/get_incidents against the proxy returns backend
    AND proxy views (broadcast + own fold)."""
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    for _ in range(2):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator=coord_dir,
                            name="evp", listen_addr="127.0.0.1",
                            interval_sec=1e9, interval_count=1 << 30,
                            telemetry_interval=0))
        srv.start(0)
        servers.append(srv)
    proxy = Proxy(ProxyArgs(engine="classifier", coordinator=coord_dir,
                            listen_addr="127.0.0.1",
                            telemetry_interval=0))
    pport = proxy.start(0)
    try:
        for i, s in enumerate(servers):
            s.rpc.trace.events.emit("t", f"backend{i}")
        proxy.rpc.trace.events.emit("t", "proxyown")
        with RpcClient("127.0.0.1", pport) as c:
            doc = c.call("get_events", "evp", 0, "")
        assert len(doc) == 3  # 2 backends + the proxy's own view
        all_types = {r["type"] for d in doc.values()
                     for r in (d.get("events") or [])}
        assert {"backend0", "backend1", "proxyown"} <= all_types
        with RpcClient("127.0.0.1", pport) as c:
            inc = c.call("get_incidents", "evp", "")
        assert len(inc) == 3
        assert all("incidents" in d for d in inc.values())
        # proxy-only views
        with RpcClient("127.0.0.1", pport) as c:
            own = c.call("get_proxy_events", "evp", 0, "")
            assert len(own) == 1
            (d,) = own.values()
            assert any(r["type"] == "proxyown" for r in d["events"])
            pinc = c.call("get_proxy_incidents", "evp", "")
            assert len(pinc) == 1
    finally:
        proxy.stop()
        for s in servers:
            s.stop()


# -- live cluster acceptance --------------------------------------------------


def test_cluster_slo_breach_captures_one_correlated_bundle(tmp_path,
                                                           capsys):
    """ISSUE 14 acceptance: on a live 3-member cluster, an induced
    latency SLO breach produces (a) a timeline interleaving the breach
    and mix events from all nodes in causal order, and (b) exactly ONE
    auto-captured incident bundle whose event window, slow-log entries,
    and flight records share the breaching trace_ids."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    for i in range(3):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator=coord_dir,
                            name="evc", listen_addr="127.0.0.1",
                            datadir=str(tmp_path / f"data{i}"),
                            interval_sec=1e9, interval_count=1 << 30,
                            telemetry_interval=0,
                            slo=["latency:rpc.classify:p99:50"],
                            slo_fast_window=1.0, slo_slow_window=2.5,
                            incident_window=300.0))
        srv.start(0)
        servers.append(srv)
    try:
        c = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, "evc")
        c.train([["a", Datum({"x": 1.0})], ["b", Datum({"x": -1.0})]])
        c.close()
        servers[0].mixer.mix_now()
        # healthy baseline, ticked into the ring
        srv0 = servers[0]
        reg = srv0.rpc.trace
        reg.slowlog.configure(min_count=1, quantile=0.5)
        for _ in range(100):
            reg.record("rpc.classify", 0.001)
        srv0._model_health_tick()
        time.sleep(0.3)
        # the breach: slow requests recorded UNDER a trace context so
        # the slow log captures the breaching trace ids
        breach_ctx = tracing.new_root()
        with tracing.use_trace(breach_ctx):
            for _ in range(40):
                reg.record("rpc.classify", 0.5)
        srv0._model_health_tick()
        assert len(srv0.slo.alerts()) >= 1
        # (b) exactly ONE bundle, despite the healthz trigger also
        # seeing the degradation on the same tick
        srv0._model_health_tick()
        st = srv0.incidents.stats()
        assert st["captured"] == 1, st
        listing = srv0.incidents.list()
        assert len(listing["incidents"]) == 1
        bundle = srv0.incidents.get(listing["incidents"][0]["id"])
        assert bundle["reason"].startswith("slo_firing:")
        # correlation: the bundle's trigger trace_ids, its slow-log
        # entries, and its event window agree on the breaching trace
        assert breach_ctx.trace_id in bundle["trace_ids"]
        slow_ids = {r.get("trace_id") for r in bundle["slow_log"]}
        assert breach_ctx.trace_id in slow_ids
        ev_types = [(r["subsystem"], r["type"]) for r in bundle["events"]]
        assert ("slo", "firing") in ev_types
        # exactly ONE firing edge: the incident collector's _health()
        # read must not re-enter the tick and double-emit the edge
        journal_firing = [r for r in reg.events.snapshot()
                          if r["subsystem"] == "slo"
                          and r["type"] == "firing"]
        assert len(journal_firing) == 1, journal_firing
        assert ("mix", "round") in ev_types  # the round rode along
        assert ("membership", "epoch_bump") in ev_types
        # the mix flight records ride the bundle too
        assert bundle["mix_history"]
        # (a) the timeline interleaves breach + mix + membership events
        # from the cluster in causal order
        rc = jubactl.main(["-c", "timeline", "-t", "classifier",
                           "-n", "evc", "-z", coord_dir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "slo.firing" in out and "mix.round" in out
        assert "membership.epoch_bump" in out
        assert "incident.captured" in out
        lines = [ln for ln in out.splitlines() if ln.strip()]
        # epoch bumps (boot) precede the slo firing edge in the render
        first_epoch = next(i for i, ln in enumerate(lines)
                           if "epoch_bump" in ln)
        firing_line = next(i for i, ln in enumerate(lines)
                           if "slo.firing" in ln)
        assert first_epoch < firing_line
        # incident listing renders across the cluster
        rc = jubactl.main(["-c", "incident", "-t", "classifier",
                           "-n", "evc", "-z", coord_dir])
        out = capsys.readouterr().out
        assert rc == 0 and "slo_firing:" in out
        # watch frame shows the last_event column + inline slo edge
        rc = jubactl.main(["-c", "watch", "--once", "-t", "classifier",
                           "-n", "evc", "-z", coord_dir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "last_event" in out
        assert "last event" in out.splitlines()[0]  # membership age
        assert "slo firing" in out
    finally:
        for s in servers:
            s.stop()


# -- status / gates -----------------------------------------------------------


def test_event_and_incident_stats_in_get_status(tmp_path):
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        telemetry_interval=0, event_capacity=128))
    srv.start(0)
    try:
        (st,) = srv.get_status("").values()
        assert st["events.capacity"] == 128
        assert st["incident.window_s"] == 300.0
        assert "events.emitted" in st and "incident.captured" in st
    finally:
        srv.stop()


def test_codestyle_event_gate_detects_and_passes(tmp_path):
    sys.path.insert(0, str(REPO / "tools" / "codestyle"))
    try:
        import check as codestyle
    finally:
        sys.path.pop(0)
    # a transition without an emit in the enclosing function is flagged
    bad = tmp_path / "jubatus_tpu" / "framework"
    bad.mkdir(parents=True)
    f = bad / "migration.py"
    f.write_text('"""Doc."""\n\n\nclass D:\n'
                 '    def set_state(self, s):\n'
                 '        self.state = s\n')
    problems = codestyle.check_file(str(f))
    assert any("events.emit" in p for p in problems)
    # an emit in the function satisfies the gate
    f.write_text('"""Doc."""\n\n\nclass D:\n'
                 '    def set_state(self, s):\n'
                 '        self.state = s\n'
                 '        self.trace.events.emit("drain", s)\n')
    assert not any("events.emit" in p
                   for p in codestyle.check_file(str(f)))
    # the pragma opts out
    f.write_text('"""Doc."""\n\n\nclass D:\n'
                 '    def set_state(self, s):\n'
                 '        self.state = s  # no-event — surfaced upstream\n')
    assert not any("events.emit" in p
                   for p in codestyle.check_file(str(f)))
    # and the real tree is clean
    for suffix, _pat, _d in codestyle.EVENT_SITES:
        real = REPO / suffix
        assert not [p for p in codestyle.check_file(str(real))
                    if "events.emit" in p], suffix


def test_bench_compare_infers_event_plane_keys():
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import bench_compare as bc
    finally:
        sys.path.pop(0)
    assert bc.direction("e2e_event_emit_us") == "lower"
    assert bc.direction("e2e_event_plane_overhead_p50_ratio") == "lower"
    assert bc.direction("e2e_event_plane_overhead_ok") == "bool"
    rows, regressions = bc.compare(
        {"e2e_event_emit_us": 3.0, "e2e_event_plane_overhead_ok": True},
        {"e2e_event_emit_us": 9.0, "e2e_event_plane_overhead_ok": False})
    assert {r["key"] for r in regressions} == \
        {"e2e_event_emit_us", "e2e_event_plane_overhead_ok"}
