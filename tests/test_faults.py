"""Fault-injection tests (utils/faults.py) — deterministic failures in
the RPC/mix planes, exercising the tolerance paths SURVEY.md §5 lists
(mix skips failed hosts, aborts only when all fail, demotes on put_diff
failure) that the reference could only probe by killing processes."""

from __future__ import annotations

import time

import pytest

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.coord import membership
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.rpc.errors import RpcError
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.utils import faults

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}
NAME = "chaos"


# ---------------------------------------------------------------- registry --
def test_rule_parsing_and_matching():
    r = faults.parse_rule("rpc.call.mix_get_diff.*:error@2")
    assert r.pattern == "rpc.call.mix_get_diff.*"
    assert r.action == "error" and r.remaining == 2
    r = faults.parse_rule("coord.*:delay:0.25")
    assert r.action == "delay" and r.arg == 0.25
    with pytest.raises(ValueError):
        faults.parse_rule("no-action")
    with pytest.raises(ValueError):
        faults.parse_rule("site:explode")


def test_fire_noop_when_disarmed():
    faults.fire("anything.at.all")  # must not raise


def test_armed_scope_and_count_limit():
    with faults.armed("x.y:error@2"):
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.y")
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.y")
        faults.fire("x.y")  # budget exhausted
        assert faults.stats()["x.y"] == 2
    faults.fire("x.y")  # disarmed on exit


def test_delay_rule():
    with faults.armed("slow.*:delay:0.05"):
        t0 = time.monotonic()
        faults.fire("slow.op")
        assert time.monotonic() - t0 >= 0.05


# ------------------------------------------------------------- mix chaos ---
def _cluster(n, store, mixer="linear_mixer"):
    servers = []
    for _ in range(n):
        args = ServerArgs(
            engine="classifier", coordinator="(shared)", name=NAME,
            mixer=mixer, listen_addr="127.0.0.1", interval_sec=1e9,
            interval_count=1 << 30,
        )
        srv = EngineServer("classifier", CONF, args,
                           coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    return servers


@pytest.fixture()
def cluster():
    store = _Store()
    servers = _cluster(3, store)
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
               for s in servers]
    yield servers, clients, store
    faults.disarm_all()
    for c in clients:
        c.close()
    for s in servers:
        s.stop()


def _train_disjoint(clients):
    for _ in range(10):
        clients[0].train([["pos", Datum({"x": 1.0})]])
        clients[1].train([["neg", Datum({"x": -1.0})]])


@pytest.mark.slow
def test_mix_survives_one_get_diff_failure(cluster):
    """One member's diff pull fails: the round proceeds with the rest
    (linear_mixer.cpp:470-504 — abort only if ALL fail)."""
    servers, clients, _ = cluster
    _train_disjoint(clients)
    port1 = servers[1].args.rpc_port
    with faults.armed(f"rpc.call.mix_get_diff.*:{port1}:error@1"):
        assert clients[2].do_mix() is True
    # node 1's contribution was skipped this round, node 0's landed
    labels2 = clients[2].get_labels()
    assert "pos" in labels2
    # the next, fault-free round folds node 1 back in
    assert clients[2].do_mix() is True
    assert set(clients[2].get_labels()) == {"pos", "neg"}


@pytest.mark.slow
def test_mix_aborts_when_all_get_diffs_fail(cluster):
    servers, clients, _ = cluster
    _train_disjoint(clients)
    with faults.armed("rpc.call.mix_get_diff.*:error"):
        assert clients[2].do_mix() is False
    # phase-1 schema sync precedes get_diff, so label NAMES may have
    # propagated — but no diff was applied: all counts are zero
    assert all(v == 0 for v in clients[2].get_labels().values())
    assert clients[2].do_mix() is True    # recovers once faults clear
    labels = clients[2].get_labels()
    assert set(labels) == {"pos", "neg"}
    assert sum(labels.values()) > 0


@pytest.mark.slow
def test_put_diff_failure_demotes_then_recovers(cluster):
    """A member that misses the broadcast is demoted from actives by the
    master (linear_mixer.cpp:658-681) and promotes itself after the next
    successful round."""
    servers, clients, store = cluster
    _train_disjoint(clients)
    view = MemoryCoordinator(store)
    port1 = servers[1].args.rpc_port

    def active_ports():
        return {n.port for n in membership.get_all_actives(
            view, "classifier", NAME)}

    # a successful round first, so everyone is active
    assert clients[2].do_mix() is True
    assert port1 in active_ports()

    with faults.armed(f"rpc.call.mix_put_diff.*:{port1}:error@1"):
        assert clients[2].do_mix() is True
    assert port1 not in active_ports()

    # node 1 missed the broadcast but doesn't KNOW yet — the next round's
    # put_diff (base ahead of its version) marks it obsolete and starts
    # async full-model recovery (linear_mixer.cpp:404-424,644-652)
    assert clients[2].do_mix() is True
    assert port1 not in active_ports()  # still stale this round
    deadline = time.time() + 10
    while time.time() < deadline and \
            servers[1].mixer.model_version < servers[2].mixer.model_version:
        time.sleep(0.1)
    assert servers[1].mixer.model_version == servers[2].mixer.model_version

    # recovered: the round after promotes it back into actives
    assert clients[2].do_mix() is True
    assert port1 in active_ports()


@pytest.mark.slow
def test_mix_completes_under_injected_latency(cluster):
    servers, clients, _ = cluster
    _train_disjoint(clients)
    with faults.armed("rpc.call.mix_get_diff.*:delay:0.1"):
        t0 = time.monotonic()
        assert clients[2].do_mix() is True
        assert time.monotonic() - t0 >= 0.1
    assert set(clients[2].get_labels()) == {"pos", "neg"}


@pytest.mark.slow
def test_client_sees_connect_fault_as_io_error(cluster):
    """Injected connect faults surface through the SAME taxonomy a real
    refused connection would (RpcIoError), so callers' error handling is
    exercised faithfully."""
    servers, _, _ = cluster
    port = servers[0].args.rpc_port
    with faults.armed(f"rpc.connect.*:{port}:error"):
        c = ClassifierClient("127.0.0.1", port, NAME)
        try:
            with pytest.raises(RpcError):
                c.get_status()
        finally:
            c.close()


@pytest.mark.slow
def test_proxy_broadcast_tolerates_injected_backend_failure(cluster):
    """Broadcast-with-reducer through the proxy folds the surviving
    hosts when one backend's calls fail (proxy.hpp:325-392), and the
    forward-error counter records the loss."""
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    servers, clients, store = cluster
    _train_disjoint(clients)
    assert clients[2].do_mix() is True
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    pport = proxy.start(0)
    pc = ClassifierClient("127.0.0.1", pport, NAME)
    try:
        port0 = servers[0].args.rpc_port
        # baseline broadcast across all 3
        assert len(pc.get_status()) == 3
        with faults.armed(f"rpc.call.get_status.*:{port0}:error"):
            st = pc.get_status()  # merged map from the 2 survivors
            assert len(st) == 2
            # specifically the faulted backend's entry is the missing one
            assert f"127.0.0.1_{port0}" not in st
        stats = pc.get_proxy_status()
        (pstat,) = stats.values()
        assert int(pstat["forward_errors"]) >= 1
        # faults cleared: full fan-in returns
        assert len(pc.get_status()) == 3
    finally:
        pc.close()
        proxy.stop()


@pytest.mark.slow
def test_push_gossip_shrugs_off_failed_peer():
    """Gossip (broadcast push mixer) skips a peer whose exchange fails —
    the round still succeeds against the reachable peer, and the dead one
    catches up once its faults clear (push_mixer.cpp's per-candidate
    tolerance, tested deterministically)."""
    store = _Store()
    servers = _cluster(3, store, mixer="broadcast_mixer")
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
               for s in servers]
    try:
        for _ in range(5):
            clients[0].train([["pos", Datum({"x": 1.0})]])
            clients[1].train([["neg", Datum({"x": -1.0})]])
        port1 = servers[1].args.rpc_port
        with faults.armed(f"rpc.call.mix_get_schema.*:{port1}:error",
                          f"rpc.call.mix_get_diff.*:{port1}:error"):
            assert clients[0].do_mix() is True  # node1 unreachable, node2 ok
        # the reachable pair exchanged: node2 got node0's class — but
        # "neg" lives only on the skipped peer, so it went nowhere
        assert set(clients[2].get_labels()) == {"pos"}
        assert "pos" not in clients[1].get_labels()  # skipped peer untouched
        # faults cleared: node 1's own round spreads its class and pulls
        # in what it missed
        assert clients[1].do_mix() is True
        assert set(clients[1].get_labels()) == {"pos", "neg"}
        assert set(clients[2].get_labels()) == {"pos", "neg"}
    finally:
        faults.disarm_all()
        for c in clients:
            c.close()
        for s in servers:
            s.stop()


# --------------------------------------------------------- coord chaos ----
def test_heartbeat_loss_fires_suicide_watchers():
    """Heartbeat failure AND failed session resumption = ZK session loss:
    the client fires its delete watchers (the suicide path,
    server_helper.cpp:91-94) and the server side expires the session's
    ephemerals. coord_open must be faulted too — with a reachable
    coordinator the client now legitimately RESUMES instead of dying
    (coord/remote.py _try_resume; test_coord_service covers that path)."""
    from jubatus_tpu.coord.remote import RemoteCoordinator
    from jubatus_tpu.coord.server import CoordServer

    srv = CoordServer(lease_sec=1.0)
    port = srv.start(0)
    b = None
    try:
        a = RemoteCoordinator("127.0.0.1", port, resume_window_sec=2.0)
        a.create("/chaos/me", ephemeral=True)
        died = []
        a.watch_delete("/chaos/me", lambda p: died.append(p))
        # the pattern hits EVERY session's heartbeats on this port, so the
        # observer client is created only after the fault window closes
        with faults.armed(f"rpc.call.coord_heartbeat.*:{port}:error",
                          f"rpc.call.coord_open.*:{port}:error"):
            deadline = time.time() + 20
            while time.time() < deadline and not died:
                time.sleep(0.1)
        assert died == ["/chaos/me"], "suicide watcher never fired"
        b = RemoteCoordinator("127.0.0.1", port)
        deadline = time.time() + 10
        while time.time() < deadline and b.exists("/chaos/me"):
            time.sleep(0.1)
        assert not b.exists("/chaos/me"), "ephemeral outlived its session"
    finally:
        if b is not None:
            b.close()
        srv.stop()


def test_heartbeat_delay_below_lease_is_harmless():
    """Latency under the lease doesn't expire anything."""
    from jubatus_tpu.coord.remote import RemoteCoordinator
    from jubatus_tpu.coord.server import CoordServer

    srv = CoordServer(lease_sec=1.5)
    port = srv.start(0)
    a = b = None
    try:
        a = RemoteCoordinator("127.0.0.1", port)
        b = RemoteCoordinator("127.0.0.1", port)
        a.create("/slow/me", ephemeral=True)
        with faults.armed("rpc.call.coord_heartbeat.*:delay:0.2"):
            time.sleep(3.0)  # two lease periods of delayed heartbeats
        assert b.exists("/slow/me")
    finally:
        for c in (a, b):
            if c is not None:
                c.close()
        srv.stop()


def test_armed_scopes_compose():
    """Nested/outer rules survive an inner scope's exit; empty arming
    never flips the hot-path flag."""
    assert not faults.is_armed()
    faults.arm()  # zero rules: stays disarmed
    assert not faults.is_armed()
    with faults.armed("outer.site:error"):
        with faults.armed("inner.site:error"):
            with pytest.raises(faults.FaultInjected):
                faults.fire("inner.site")
        # inner scope closed: outer rule still live
        with pytest.raises(faults.FaultInjected):
            faults.fire("outer.site")
        faults.fire("inner.site")  # inner rule gone
    assert not faults.is_armed()
