"""Fault-injection tests (utils/faults.py) — deterministic failures in
the RPC/mix planes, exercising the tolerance paths SURVEY.md §5 lists
(mix skips failed hosts, aborts only when all fail, demotes on put_diff
failure) that the reference could only probe by killing processes."""

from __future__ import annotations

import time

import pytest

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.coord import membership
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.rpc.errors import RpcError
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.utils import faults

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}
NAME = "chaos"


# ---------------------------------------------------------------- registry --
def test_rule_parsing_and_matching():
    r = faults.parse_rule("rpc.call.mix_get_diff.*:error@2")
    assert r.pattern == "rpc.call.mix_get_diff.*"
    assert r.action == "error" and r.remaining == 2
    r = faults.parse_rule("coord.*:delay:0.25")
    assert r.action == "delay" and r.arg == 0.25
    with pytest.raises(ValueError):
        faults.parse_rule("no-action")
    with pytest.raises(ValueError):
        faults.parse_rule("site:explode")


def test_fire_noop_when_disarmed():
    faults.fire("anything.at.all")  # must not raise


def test_armed_scope_and_count_limit():
    with faults.armed("x.y:error@2"):
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.y")
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.y")
        faults.fire("x.y")  # budget exhausted
        assert faults.stats()["x.y"] == 2
    faults.fire("x.y")  # disarmed on exit


def test_delay_rule():
    with faults.armed("slow.*:delay:0.05"):
        t0 = time.monotonic()
        faults.fire("slow.op")
        assert time.monotonic() - t0 >= 0.05


# ------------------------------------------------------------- mix chaos ---
def _cluster(n, store, mixer="linear_mixer"):
    servers = []
    for _ in range(n):
        args = ServerArgs(
            engine="classifier", coordinator="(shared)", name=NAME,
            mixer=mixer, listen_addr="127.0.0.1", interval_sec=1e9,
            interval_count=1 << 30,
        )
        srv = EngineServer("classifier", CONF, args,
                           coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    return servers


@pytest.fixture()
def cluster():
    store = _Store()
    servers = _cluster(3, store)
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
               for s in servers]
    yield servers, clients, store
    faults.disarm_all()
    for c in clients:
        c.close()
    for s in servers:
        s.stop()


def _train_disjoint(clients):
    for _ in range(10):
        clients[0].train([["pos", Datum({"x": 1.0})]])
        clients[1].train([["neg", Datum({"x": -1.0})]])


@pytest.mark.slow
def test_mix_survives_one_get_diff_failure(cluster):
    """One member's diff pull fails: the round proceeds with the rest
    (linear_mixer.cpp:470-504 — abort only if ALL fail)."""
    servers, clients, _ = cluster
    _train_disjoint(clients)
    port1 = servers[1].args.rpc_port
    with faults.armed(f"rpc.call.mix_get_diff.*:{port1}:error@1"):
        assert clients[2].do_mix() is True
    # node 1's contribution was skipped this round, node 0's landed
    labels2 = clients[2].get_labels()
    assert "pos" in labels2
    # the next, fault-free round folds node 1 back in
    assert clients[2].do_mix() is True
    assert set(clients[2].get_labels()) == {"pos", "neg"}


@pytest.mark.slow
def test_mix_aborts_when_all_get_diffs_fail(cluster):
    servers, clients, _ = cluster
    _train_disjoint(clients)
    with faults.armed("rpc.call.mix_get_diff.*:error"):
        assert clients[2].do_mix() is False
    # phase-1 schema sync precedes get_diff, so label NAMES may have
    # propagated — but no diff was applied: all counts are zero
    assert all(v == 0 for v in clients[2].get_labels().values())
    assert clients[2].do_mix() is True    # recovers once faults clear
    labels = clients[2].get_labels()
    assert set(labels) == {"pos", "neg"}
    assert sum(labels.values()) > 0


@pytest.mark.slow
def test_put_diff_failure_demotes_then_recovers(cluster):
    """A member that misses the broadcast is demoted from actives by the
    master (linear_mixer.cpp:658-681) and promotes itself after the next
    successful round."""
    servers, clients, store = cluster
    _train_disjoint(clients)
    view = MemoryCoordinator(store)
    port1 = servers[1].args.rpc_port

    def active_ports():
        return {n.port for n in membership.get_all_actives(
            view, "classifier", NAME)}

    # a successful round first, so everyone is active
    assert clients[2].do_mix() is True
    assert port1 in active_ports()

    with faults.armed(f"rpc.call.mix_put_diff.*:{port1}:error@1"):
        assert clients[2].do_mix() is True
    assert port1 not in active_ports()

    # node 1 missed the broadcast but doesn't KNOW yet — the next round's
    # put_diff (base ahead of its version) marks it obsolete and starts
    # async full-model recovery (linear_mixer.cpp:404-424,644-652)
    assert clients[2].do_mix() is True
    assert port1 not in active_ports()  # still stale this round
    deadline = time.time() + 10
    while time.time() < deadline and \
            servers[1].mixer.model_version < servers[2].mixer.model_version:
        time.sleep(0.1)
    assert servers[1].mixer.model_version == servers[2].mixer.model_version

    # recovered: the round after promotes it back into actives
    assert clients[2].do_mix() is True
    assert port1 in active_ports()


@pytest.mark.slow
def test_mix_completes_under_injected_latency(cluster):
    servers, clients, _ = cluster
    _train_disjoint(clients)
    with faults.armed("rpc.call.mix_get_diff.*:delay:0.1"):
        t0 = time.monotonic()
        assert clients[2].do_mix() is True
        assert time.monotonic() - t0 >= 0.1
    assert set(clients[2].get_labels()) == {"pos", "neg"}


@pytest.mark.slow
def test_client_sees_connect_fault_as_io_error(cluster):
    """Injected connect faults surface through the SAME taxonomy a real
    refused connection would (RpcIoError), so callers' error handling is
    exercised faithfully."""
    servers, _, _ = cluster
    port = servers[0].args.rpc_port
    with faults.armed(f"rpc.connect.*:{port}:error"):
        c = ClassifierClient("127.0.0.1", port, NAME)
        try:
            with pytest.raises(RpcError):
                c.get_status()
        finally:
            c.close()


@pytest.mark.slow
def test_proxy_broadcast_tolerates_injected_backend_failure(cluster):
    """Broadcast-with-reducer through the proxy folds the surviving
    hosts when one backend's calls fail (proxy.hpp:325-392), and the
    forward-error counter records the loss."""
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    servers, clients, store = cluster
    _train_disjoint(clients)
    assert clients[2].do_mix() is True
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    pport = proxy.start(0)
    pc = ClassifierClient("127.0.0.1", pport, NAME)
    try:
        port0 = servers[0].args.rpc_port
        # baseline broadcast across all 3
        assert len(pc.get_status()) == 3
        with faults.armed(f"rpc.call.get_status.*:{port0}:error"):
            st = pc.get_status()  # merged map from the 2 survivors
            assert len(st) == 2
            # specifically the faulted backend's entry is the missing one
            assert f"127.0.0.1_{port0}" not in st
        stats = pc.get_proxy_status()
        (pstat,) = stats.values()
        assert int(pstat["forward_errors"]) >= 1
        # faults cleared: full fan-in returns
        assert len(pc.get_status()) == 3
    finally:
        pc.close()
        proxy.stop()


@pytest.mark.slow
def test_push_gossip_shrugs_off_failed_peer():
    """Gossip (broadcast push mixer) skips a peer whose exchange fails —
    the round still succeeds against the reachable peer, and the dead one
    catches up once its faults clear (push_mixer.cpp's per-candidate
    tolerance, tested deterministically)."""
    store = _Store()
    servers = _cluster(3, store, mixer="broadcast_mixer")
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
               for s in servers]
    try:
        for _ in range(5):
            clients[0].train([["pos", Datum({"x": 1.0})]])
            clients[1].train([["neg", Datum({"x": -1.0})]])
        port1 = servers[1].args.rpc_port
        with faults.armed(f"rpc.call.mix_get_schema.*:{port1}:error",
                          f"rpc.call.mix_get_diff.*:{port1}:error"):
            assert clients[0].do_mix() is True  # node1 unreachable, node2 ok
        # the reachable pair exchanged: node2 got node0's class — but
        # "neg" lives only on the skipped peer, so it went nowhere
        assert set(clients[2].get_labels()) == {"pos"}
        assert "pos" not in clients[1].get_labels()  # skipped peer untouched
        # faults cleared: node 1's own round spreads its class and pulls
        # in what it missed
        assert clients[1].do_mix() is True
        assert set(clients[1].get_labels()) == {"pos", "neg"}
        assert set(clients[2].get_labels()) == {"pos", "neg"}
    finally:
        faults.disarm_all()
        for c in clients:
            c.close()
        for s in servers:
            s.stop()


# --------------------------------------------------------- coord chaos ----
def test_heartbeat_loss_fires_suicide_watchers():
    """Heartbeat failure AND failed session resumption = ZK session loss:
    the client fires its delete watchers (the suicide path,
    server_helper.cpp:91-94) and the server side expires the session's
    ephemerals. coord_open must be faulted too — with a reachable
    coordinator the client now legitimately RESUMES instead of dying
    (coord/remote.py _try_resume; test_coord_service covers that path)."""
    from jubatus_tpu.coord.remote import RemoteCoordinator
    from jubatus_tpu.coord.server import CoordServer

    srv = CoordServer(lease_sec=1.0)
    port = srv.start(0)
    b = None
    try:
        a = RemoteCoordinator("127.0.0.1", port, resume_window_sec=2.0)
        a.create("/chaos/me", ephemeral=True)
        died = []
        a.watch_delete("/chaos/me", lambda p: died.append(p))
        # the pattern hits EVERY session's heartbeats on this port, so the
        # observer client is created only after the fault window closes
        with faults.armed(f"rpc.call.coord_heartbeat.*:{port}:error",
                          f"rpc.call.coord_open.*:{port}:error"):
            deadline = time.time() + 20
            while time.time() < deadline and not died:
                time.sleep(0.1)
        assert died == ["/chaos/me"], "suicide watcher never fired"
        b = RemoteCoordinator("127.0.0.1", port)
        deadline = time.time() + 10
        while time.time() < deadline and b.exists("/chaos/me"):
            time.sleep(0.1)
        assert not b.exists("/chaos/me"), "ephemeral outlived its session"
    finally:
        if b is not None:
            b.close()
        srv.stop()


def test_heartbeat_delay_below_lease_is_harmless():
    """Latency under the lease doesn't expire anything."""
    from jubatus_tpu.coord.remote import RemoteCoordinator
    from jubatus_tpu.coord.server import CoordServer

    srv = CoordServer(lease_sec=1.5)
    port = srv.start(0)
    a = b = None
    try:
        a = RemoteCoordinator("127.0.0.1", port)
        b = RemoteCoordinator("127.0.0.1", port)
        a.create("/slow/me", ephemeral=True)
        with faults.armed("rpc.call.coord_heartbeat.*:delay:0.2"):
            time.sleep(3.0)  # two lease periods of delayed heartbeats
        assert b.exists("/slow/me")
    finally:
        for c in (a, b):
            if c is not None:
                c.close()
        srv.stop()


# ----------------------------------------------- self-healing plane -------
def test_breaker_trips_after_n_failures_and_half_open_readmits():
    """CircuitBreaker state machine, deterministically: N failures in the
    window open it, the cooldown admits exactly one half-open probe, a
    probe success closes it (window cleared), a probe failure re-opens."""
    from jubatus_tpu.rpc.breaker import CircuitBreaker

    b = CircuitBreaker(failure_threshold=3, cooldown_sec=0.15,
                       window_sec=30.0)
    assert b.allow() and b.state == "closed"
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"          # under threshold
    assert b.record_failure() is True   # trips
    assert b.state == "open" and not b.allow()
    time.sleep(0.16)
    assert b.state == "half_open"
    assert b.allow() is True            # the one probe
    assert b.allow() is False           # serialized: second probe refused
    assert b.record_failure() is True   # probe failed: re-open
    assert not b.allow()
    time.sleep(0.16)
    assert b.allow() is True
    assert b.record_success() is True   # probe succeeded: closed
    assert b.state == "closed" and b.allow()
    assert b.opened_total == 2


def test_retry_budget_exhausts_under_sustained_faults():
    """The token bucket caps retry amplification: with every call
    failing, withdrawals stop once the budget is dry and the client
    counts rpc.retry_budget_exhausted instead of hammering the backend."""
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc.retry import RetryBudget
    from jubatus_tpu.utils.tracing import Registry

    reg = Registry()
    budget = RetryBudget(ratio=0.01, max_tokens=2.0)
    c = RpcClient("127.0.0.1", 1, retry_budget=budget, registry=reg)
    with faults.armed("rpc.connect.127.0.0.1:1:error"):
        for _ in range(10):
            with pytest.raises(RpcError):
                c.call("get_status", "x")
    c.close()
    counters = reg.counters()
    # 2 initial tokens + 10 * 0.01 deposits < 3: at most 2-3 retries ever
    # happen, the rest are denied
    assert counters.get("rpc.retries", 0) <= 3
    assert counters.get("rpc.retry_budget_exhausted", 0) >= 7
    assert budget.status()["denials"] >= 7


def test_expired_deadline_rejected_at_dispatch(cluster):
    """A call whose propagated budget dies in the server's queue (here: a
    200 ms injected dispatch delay vs a 50 ms deadline) is rejected at
    dispatch — DeadlineExceeded to the caller in bounded time, counted by
    the server, handler never invoked."""
    from jubatus_tpu.rpc import deadline
    from jubatus_tpu.rpc.errors import DeadlineExceeded

    servers, clients, _ = cluster
    before = servers[0].driver.update_count
    with faults.armed("rpc.dispatch.train:delay:0.2"):
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            with deadline.deadline_after(0.05):
                clients[0].train([["pos", Datum({"x": 1.0})]])
        assert time.monotonic() - t0 < 1.0  # bounded, not the 10 s timeout
    # the server finishes its delayed dispatch, then REJECTS (no apply)
    deadline_counter = None
    deadline_end = time.time() + 5
    while time.time() < deadline_end:
        counters = servers[0].rpc.trace.counters()
        if counters.get("rpc.deadline_rejected"):
            deadline_counter = counters["rpc.deadline_rejected"]
            break
        time.sleep(0.05)
    assert deadline_counter == 1
    assert servers[0].driver.update_count == before  # never applied


def test_quorum_degraded_round_recorded(cluster):
    """One member's diffs unreachable: the round proceeds above quorum
    but is stamped DEGRADED in the flight recorder and counted."""
    servers, clients, _ = cluster
    _train_disjoint(clients)
    port1 = servers[1].args.rpc_port
    with faults.armed(f"rpc.call.mix_get_diff.*:{port1}:error"):
        assert clients[2].do_mix() is True
    recs = servers[2].mixer.flight.snapshot()
    degraded = [r for r in recs if r.get("degraded")]
    assert degraded and degraded[-1]["contributors"] == 2
    assert servers[2].rpc.trace.counters().get("mix.quorum_degraded") == 1


def test_quorum_abort_below_fraction(cluster):
    """Two of three members unreachable: 1/3 < the 0.5 quorum — the
    round aborts instead of broadcasting a one-node fold as everyone's
    new base."""
    servers, clients, _ = cluster
    _train_disjoint(clients)
    p0, p1 = servers[0].args.rpc_port, servers[1].args.rpc_port
    with faults.armed(f"rpc.call.mix_get_diff.*:{p0}:error",
                      f"rpc.call.mix_get_diff.*:{p1}:error"):
        assert clients[2].do_mix() is False
    recs = servers[2].mixer.flight.snapshot()
    assert any("quorum_not_met" in r.get("reason", "") for r in recs)
    assert clients[2].do_mix() is True  # recovers once faults clear


@pytest.mark.slow
def test_chaos_idempotent_failover_and_breaker_lifecycle(monkeypatch):
    """The ISSUE 3 acceptance chaos matrix: with IO errors injected on
    one of three backends, (a) idempotent calls through the proxy
    succeed >= 99% via breaker skip + failover, (b) the failing backend's
    breaker OPENS during the fault window and RE-CLOSES after faults are
    disarmed (half-open probe), and (c) effectful train calls are never
    silently re-forwarded — the failed call surfaces and its examples
    are applied zero times, not two."""
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    # python transport end to end: the C++ relay plane would bypass the
    # (python-level) fault injection sites after its first refresh tick
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "0")
    store = _Store()
    servers = _cluster(3, store)
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
               for s in servers]
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1",
                            breaker_failures=3, breaker_cooldown=1.0),
                  coord=MemoryCoordinator(store))
    pport = proxy.start(0)
    pc = ClassifierClient("127.0.0.1", pport, NAME)
    try:
        _train_disjoint(clients)
        bad_port = servers[0].args.rpc_port
        bad_key = f"('127.0.0.1', {bad_port})"
        # (a)+(b) idempotent plane under faults
        ok = 0
        with faults.armed(f"rpc.call.get_labels.*:{bad_port}:error"):
            for _ in range(100):
                try:
                    pc.get_labels()
                    ok += 1
                except RpcError:
                    pass
            snap_during = proxy.breakers.snapshot()
        assert ok >= 99, f"only {ok}/100 idempotent calls survived"
        assert snap_during.get(bad_key, {}).get("state") == "open"
        assert proxy.rpc.trace.counters().get("proxy.breaker_open", 0) >= 1
        # (b) faults disarmed: cooldown passes, a half-open probe
        # re-admits the backend and its breaker closes again
        deadline_end = time.time() + 10
        while time.time() < deadline_end:
            pc.get_labels()
            if proxy.breakers.snapshot()[bad_key]["state"] == "closed":
                break
            time.sleep(0.2)
        assert proxy.breakers.snapshot()[bad_key]["state"] == "closed"
        # (c) effectful plane: a train forward that dies in transport
        # SURFACES (no silent re-forward) and applies nothing anywhere.
        # Faults target the proxy->backend hops only (one rule per
        # backend port) — whichever replica the proxy picks fails once.
        labels_before = pc.get_labels()
        rules = [f"rpc.call.train.*:{s.args.rpc_port}:error@1"
                 for s in servers]
        with faults.armed(*rules):
            with pytest.raises(RpcError):
                pc.train([["pos", Datum({"x": 1.0})],
                          ["pos", Datum({"x": 2.0})]])
        labels_after = pc.get_labels()
        assert labels_after == labels_before, "train was re-forwarded"
    finally:
        faults.disarm_all()
        pc.close()
        for c in clients:
            c.close()
        for s in servers:
            s.stop()
        proxy.stop()


def _envelope_roundtrip(port: int) -> None:
    """Drive one server through every envelope generation a peer might
    send: legacy 4-element, traced 5-element, deadlined 6-element (with
    real and nil trace) — all must round-trip."""
    import socket as _socket

    import msgpack
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc import deadline

    sock = _socket.create_connection(("127.0.0.1", port), timeout=10.0)
    unp = msgpack.Unpacker(raw=False)

    def send_frame(env):
        sock.sendall(msgpack.packb(env, use_bin_type=True))
        while True:
            try:
                return unp.unpack()
            except msgpack.OutOfData:
                data = sock.recv(65536)
                assert data, "server closed on an envelope variant"
                unp.feed(data)

    # legacy 4-element (what every deployed msgpack-rpc client sends)
    msg = send_frame([0, 7, "get_status", ["x"]])
    assert msg[0] == 1 and msg[1] == 7 and msg[2] is None
    # traced 5-element
    msg = send_frame([0, 8, "get_status", ["x"], {"t": "abc", "s": "def"}])
    assert msg[1] == 8 and msg[2] is None
    # deadlined 6-element, nil trace
    msg = send_frame([0, 9, "get_status", ["x"], None, 5.0])
    assert msg[1] == 9 and msg[2] is None
    # deadlined 6-element, real trace
    msg = send_frame([0, 10, "get_status", ["x"], {"t": "abc", "s": "d"},
                      2.5])
    assert msg[1] == 10 and msg[2] is None
    sock.close()
    # the typed client across generations: plain, then deadline-bearing
    c = RpcClient("127.0.0.1", port)
    assert c.call("get_status", "x")
    with deadline.deadline_after(5.0):
        assert c.call("get_status", "x")
    c.close()


def _deadline_bound_check(srv, port: int) -> None:
    """ISSUE 3 acceptance: a 50 ms deadline against a dispatch delayed
    200 ms fails with DeadlineExceeded in bounded time (not the 10 s flat
    timeout), and the server counts the dispatch-side rejection once its
    delayed worker reaches the gate."""
    from jubatus_tpu.rpc import deadline
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc.errors import DeadlineExceeded

    c = RpcClient("127.0.0.1", port)
    with faults.armed("rpc.dispatch.get_status:delay:0.2"):
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            with deadline.deadline_after(0.05):
                c.call("get_status", "x")
        assert time.monotonic() - t0 < 1.0
    c.close()
    deadline_end = time.time() + 5
    while time.time() < deadline_end:
        if srv.trace.counters().get("rpc.deadline_rejected"):
            break
        time.sleep(0.05)
    assert srv.trace.counters().get("rpc.deadline_rejected", 0) >= 1


def test_envelope_compat_python_transport():
    from jubatus_tpu.rpc.server import RpcServer

    srv = RpcServer()
    srv.register("get_status", lambda name: {"node": {"ok": 1}}, arity=1)
    port = srv.serve_background(0, host="127.0.0.1")
    try:
        _envelope_roundtrip(port)
        assert not srv.trace.counters().get("rpc.deadline_rejected")
        _deadline_bound_check(srv, port)
    finally:
        srv.stop()


def test_envelope_compat_native_transport():
    from jubatus_tpu.rpc import native_server

    if not native_server.available():
        pytest.skip("native transport unavailable")
    srv = native_server.NativeRpcServer()
    srv.register("get_status", lambda name: {"node": {"ok": 1}}, arity=1)
    port = srv.serve_background(0, host="127.0.0.1")
    try:
        _envelope_roundtrip(port)
        assert not srv.trace.counters().get("rpc.deadline_rejected")
        _deadline_bound_check(srv, port)
    finally:
        srv.stop()


def test_armed_scopes_compose():
    """Nested/outer rules survive an inner scope's exit; empty arming
    never flips the hot-path flag."""
    assert not faults.is_armed()
    faults.arm()  # zero rules: stays disarmed
    assert not faults.is_armed()
    with faults.armed("outer.site:error"):
        with faults.armed("inner.site:error"):
            with pytest.raises(faults.FaultInjected):
                faults.fire("inner.site")
        # inner scope closed: outer rule still live
        with pytest.raises(faults.FaultInjected):
            faults.fire("outer.site")
        faults.fire("inner.site")  # inner rule gone
    assert not faults.is_armed()


def test_drop_mode_parse_and_fire():
    """ISSUE 11: the ``drop`` mode — fire() reports True and drop-aware
    sites silently lose the operation; error/delay behavior unchanged."""
    r = faults.parse_rule("mix.comm.put_diff:drop")
    assert r.action == "drop" and r.prob == 1.0
    r = faults.parse_rule("mix.comm.*:drop:0.5")
    assert r.action == "drop" and r.prob == 0.5
    r = faults.parse_rule("mix.put_diff:drop@2")
    assert r.remaining == 2
    with faults.armed("some.site:drop@1"):
        assert faults.fire("some.site") is True   # dropped once
        assert faults.fire("some.site") is False  # budget spent
    with faults.armed("err.site:error"):
        with pytest.raises(faults.FaultInjected):
            faults.fire("err.site")
    assert faults.fire("anything") is False  # disarmed: plain False


def test_drop_mode_loses_mix_broadcast():
    """A dropped put_diff broadcast = no member acks; the sync master
    demotes nobody it can blame and the next round retries."""
    from jubatus_tpu.framework.linear_mixer import RpcLinearCommunication

    class _NoMc(RpcLinearCommunication):
        def __init__(self):  # no coordinator: only the drop path runs
            self.name = NAME

    comm = _NoMc()
    with faults.armed("mix.comm.put_diff:drop"):
        assert comm.put_diff(b"payload") == {}
    with faults.armed("mix.comm.get_diff:drop"):
        assert comm.get_diff() == []


def test_fault_flag_arms_at_server_boot(tmp_path):
    """--fault SITE:MODE:ARG rules arm when the server constructs —
    the operator's chaos-drill lever (same registry as the env var)."""
    faults.disarm_all()
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier",
                        fault=["mix.put_diff:error@1"],
                        telemetry_interval=0))
    try:
        assert faults.is_armed()
        with pytest.raises(faults.FaultInjected):
            faults.fire("mix.put_diff")
        faults.fire("mix.put_diff")  # @1 budget spent
    finally:
        srv.stop()
        faults.disarm_all()


def test_ann_rebuild_fault_degrades_to_exact_scan():
    """ISSUE 16 fault site ``ann.rebuild``: an injected index-build
    failure degrades the ANN tier to the exact scan — counted, evented,
    and NEVER wrong-answering (the degraded tier's results match a
    backend that never armed ANN at all)."""
    import numpy as np

    from jubatus_tpu.models._nn_backend import NNBackend
    from jubatus_tpu.utils import events

    rng = np.random.default_rng(7)

    def vec():
        idx = rng.integers(1, 64, size=6)
        val = rng.normal(size=6)
        return [(int(i), float(v)) for i, v in zip(idx, val)]

    rows = {f"r{i}": vec() for i in range(160)}
    plain = NNBackend("lsh", dim=64, hash_num=64)
    ann = NNBackend("lsh", dim=64, hash_num=64)
    ann.configure_ann("ivf", cells=4, nprobe=2)
    for rid, v in rows.items():
        plain.set_row(rid, v)
        ann.set_row(rid, v)

    j = events.default_journal()
    cursor = max([r["hlc"] for r in j.snapshot()] or [0])
    q = vec()
    with faults.armed("ann.rebuild:error"):
        got = ann.neighbors(q, 5)          # build attempt fires the fault
    want = plain.neighbors(q, 5)
    assert got == want                      # degraded == exact, not wrong
    st = ann.ann_stats()
    assert st["degraded"] is True and st["built"] is False
    assert st["rebuild_failed"] == 1
    evs = j.snapshot(since=cursor, grep="ann")
    assert any(e["type"] == "degraded" and e["subsystem"] == "ann"
               for e in evs)
    # the latch is sticky: later queries stay exact with no retry storm
    q2 = vec()
    assert ann.neighbors(q2, 5) == plain.neighbors(q2, 5)
    assert ann.ann_stats()["rebuild_failed"] == 1
    # and explicit re-configure re-arms the tier cleanly
    ann.configure_ann("ivf", cells=4, nprobe=4)
    assert ann.ann_stats()["degraded"] is False
    res = ann.neighbors(q2, 5)
    assert ann.ann_stats()["built"] is True
    assert [r for r, _ in res]              # non-empty approximate answer
