"""Highest-fidelity integration tier: every component a REAL OS process —
coordination daemon (jubacoordd), two engine servers, one proxy — glued
only by the tcp:// locator and the wire protocol, driven by a client.
(The reference needs the external jubatest harness plus ZooKeeper for
this; here it runs self-contained.)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.rpc.client import RpcClient

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


def _spawn(args, log_path):
    out = open(log_path, "ab")
    try:
        return subprocess.Popen([sys.executable, "-m"] + args,
                                stdout=out, stderr=out)
    finally:
        out.close()


def _wait_port(port, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with RpcClient("127.0.0.1", port, timeout=2.0) as c:
                c.call("coord_exists", "/")
            return True
        except Exception:  # noqa: BLE001
            time.sleep(0.3)
    return False


@pytest.mark.slow
def test_processes_cluster_end_to_end(tmp_path):
    env_port = 21990 + (os.getpid() % 500)
    locator = f"tcp://127.0.0.1:{env_port}"
    procs = []
    try:
        # 1. coordination daemon
        procs.append(_spawn(["jubatus_tpu.coord.server", "-p", str(env_port),
                             "-b", "127.0.0.1"], tmp_path / "coordd.log"))
        assert _wait_port(env_port), "coordination daemon never came up"

        # 2. cluster config via jubaconfig (its own process too)
        conf_file = tmp_path / "conf.json"
        conf_file.write_text(json.dumps(CONF))
        rc = subprocess.run(
            [sys.executable, "-m", "jubatus_tpu.cmd.jubaconfig", "-c", "write",
             "-t", "classifier", "-n", "fs", "-f", str(conf_file),
             "-z", locator], capture_output=True, timeout=60)
        assert rc.returncode == 0, rc.stderr

        # 3. two servers + proxy
        sport0, sport1, pport = env_port + 1, env_port + 2, env_port + 3
        for sp in (sport0, sport1):
            procs.append(_spawn(
                ["jubatus_tpu.server", "classifier", "-z", locator, "-n", "fs",
                 "-p", str(sp), "-b", "127.0.0.1", "-d", str(tmp_path),
                 "-s", "1000000", "-i", "1000000000"],
                tmp_path / f"server{sp}.log"))
        procs.append(_spawn(
            ["jubatus_tpu.server.proxy", "classifier", "-z", locator,
             "-p", str(pport), "-b", "127.0.0.1"], tmp_path / "proxy.log"))

        # wait for both servers to register (proxy routes only to actives)
        c = ClassifierClient("127.0.0.1", pport, "fs", timeout=20.0)
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                if len(c.get_status()) == 2:
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        assert len(c.get_status()) == 2, "servers never joined via tcp coord"

        # 4. the actual workload through the proxy
        for _ in range(10):
            c.train([["pos", Datum({"x": 1.0})]])
            c.train([["neg", Datum({"x": -1.0})]])
        assert c.do_mix() is True
        res = c.classify([Datum({"x": 1.0}), Datum({"x": -1.0})])
        assert [max(r, key=lambda s: s[1])[0] for r in res] == ["pos", "neg"]

        # 5. kill one server: ephemeral membership must shrink and the
        #    proxy must keep answering from the survivor
        procs[1].send_signal(signal.SIGTERM)
        procs[1].wait(timeout=20)
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(c.get_status()) == 1:
                break
            time.sleep(0.5)
        assert len(c.get_status()) == 1, "dead server stuck in membership"
        (res,) = c.classify([Datum({"x": 1.0})])
        assert max(res, key=lambda s: s[1])[0] == "pos"

        # 6. restart it (the reference's clustering_test kill/restart tier):
        #    it rejoins membership fresh, and a mix round teaches it the
        #    surviving replica's model
        procs.append(_spawn(
            ["jubatus_tpu.server", "classifier", "-z", locator, "-n", "fs",
             "-p", str(sport0), "-b", "127.0.0.1", "-d", str(tmp_path),
             "-s", "1000000", "-i", "1000000000"],
            tmp_path / "server_restarted.log"))
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                if len(c.get_status()) == 2:
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        assert len(c.get_status()) == 2, "restarted server never rejoined"
        # the round marks the fresh node obsolete (version gate) and it
        # pulls a full model from the survivor ASYNCHRONOUSLY — poll
        assert c.do_mix() is True
        with ClassifierClient("127.0.0.1", sport0, "fs", timeout=20.0) as d:
            deadline = time.time() + 30
            top = None
            while time.time() < deadline:
                (res,) = d.classify([Datum({"x": 1.0})])
                if res:
                    top = max(res, key=lambda s: s[1])[0]
                    if top == "pos":
                        break
                time.sleep(0.5)
            assert top == "pos", "restarted node never recovered the model"
        c.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
