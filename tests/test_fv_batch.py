"""Batch feature-extraction pipeline tests (ISSUE 5).

Parity suite: ``convert_batch`` must reproduce per-datum ``convert``
(indices, values, idf weights, combination rules, num filters) across
every converter block shipped in config/, plus CSR packing, memo-cache
correctness under weight updates, the reverse-map capacity bound on the
batch hash paths, and the vectorized WeightManager lookups.
"""

from __future__ import annotations

import glob
import json
import math
import os

import numpy as np
import pytest

from jubatus_tpu.core import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.fv.hashing import FeatureHasher
from jubatus_tpu.core.fv.weight_manager import WeightManager
from jubatus_tpu.core.sparse import CSRBatch, SparseBatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: converter blocks exercising every weighting/combination axis directly
SYNTH_CONFIGS = {
    "num": {"num_rules": [{"key": "*", "type": "num"}]},
    "text_tf": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "bin"}]},
    "text_log_tf_idf": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "log_tf",
         "global_weight": "idf"}]},
    "ngram_idf": {
        "string_types": {"bigram": {"method": "ngram", "char_num": "2"}},
        "string_rules": [
            {"key": "*", "type": "bigram", "sample_weight": "bin",
             "global_weight": "idf"}]},
    "combo_mul": {
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_rules": [
            {"key_left": "*", "key_right": "*", "type": "mul"}]},
    "combo_add_matchers": {
        "num_rules": [{"key": "*", "type": "num"}],
        "string_rules": [
            {"key": "*", "type": "str", "sample_weight": "bin",
             "global_weight": "bin"}],
        "combination_types": {"plus": {"method": "add"}},
        "combination_rules": [
            {"key_left": "f*", "key_right": "*", "type": "plus"},
            {"key_left": "*", "key_right": "*str*", "type": "mul"}]},
    "filters": {
        "string_filter_types": {
            "detag": {"method": "regexp", "pattern": "<[^>]*>",
                      "replace": ""}},
        "string_filter_rules": [
            {"key": "t*", "type": "detag", "suffix": "-detag"}],
        "num_filter_types": {
            "add5": {"method": "add", "value": "5"},
            "lin": {"method": "linear_normalization", "min": "0",
                    "max": "10"}},
        "num_filter_rules": [
            {"key": "f*", "type": "add5", "suffix": "+5"},
            {"key": "f*", "type": "lin", "suffix": "_lin"}],
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "tf",
             "global_weight": "bin"}],
        "num_rules": [{"key": "*", "type": "num"}]},
    "user_weight": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "bin",
         "global_weight": "weight"}]},
}

WORDS = ["alpha", "beta", "gamma", "delta", "eps", "zeta", "eta", "theta"]


def _mk_datum(rng, i):
    sv = [("t", " ".join(rng.choice(WORDS)
                         for _ in range(rng.randint(0, 9)))),
          ("title", "<p>%s</p>" % rng.choice(WORDS))]
    nv = [("f%d" % j, rng.uniform(-3, 3)) for j in range(rng.randint(0, 4))]
    if rng.random() < 0.3:
        nv.append(("count", float(rng.randint(0, 50))))
    return Datum(string_values=sv, num_values=nv)


def _assert_csr_equals_vectors(csr, vectors, tag=""):
    ref = CSRBatch.from_vectors(vectors)
    np.testing.assert_array_equal(csr.row_offsets, ref.row_offsets, err_msg=tag)
    np.testing.assert_array_equal(csr.indices, ref.indices, err_msg=tag)
    np.testing.assert_array_equal(csr.values, ref.values, err_msg=tag)


@pytest.mark.parametrize("name", sorted(SYNTH_CONFIGS))
def test_convert_batch_parity_synthetic(name):
    import random

    rng = random.Random(hash(name) & 0xFFFF)
    conf = SYNTH_CONFIGS[name]
    per = make_fv_converter(conf, dim_bits=16)
    bat = make_fv_converter(conf, dim_bits=16)
    if name == "user_weight":
        for w, c in ((per, 2.5), (bat, 2.5)):
            idx = w.hasher.index("t$alpha@space#bin/weight")
            w.weights.set_user_weight(idx, c)
    data = [_mk_datum(rng, i) for i in range(30)]
    vectors = [per.convert(d) for d in data]
    csr = bat.convert_batch(data)
    _assert_csr_equals_vectors(csr, vectors, name)
    # repeat: memo caches must not change anything
    csr2 = bat.convert_batch(data)
    _assert_csr_equals_vectors(csr2, vectors, name + "/memo")


def test_convert_batch_parity_every_shipped_config():
    """Every converter block in config/ (the shipped reference configs,
    incl. idf global weights and combination rules) must produce
    identical output through both pipelines."""
    import random

    rng = random.Random(5)
    paths = sorted(glob.glob(os.path.join(REPO, "config", "*", "*.json")))
    assert paths
    data = [_mk_datum(rng, i) for i in range(12)]
    checked = 0
    for path in paths:
        with open(path) as f:
            cfg = json.load(f)
        if "converter" not in cfg:
            continue
        per = make_fv_converter(cfg["converter"], dim_bits=12)
        bat = make_fv_converter(cfg["converter"], dim_bits=12)
        vectors = [per.convert(d) for d in data]
        csr = bat.convert_batch(data)
        _assert_csr_equals_vectors(csr, vectors, path)
        checked += 1
    assert checked >= 10


def test_convert_batch_idf_update_semantics():
    """update_weights=True observes the WHOLE batch first (the idf
    batch-collapse fix), so every row's idf reflects the full batch —
    equal to per-datum 'observe all, then convert' and to per-datum
    sequential convert for batch size 1."""
    import random

    rng = random.Random(9)
    conf = SYNTH_CONFIGS["text_log_tf_idf"]
    data = [_mk_datum(rng, i) for i in range(14)]

    bat = make_fv_converter(conf, dim_bits=16)
    csr = bat.convert_batch(data, update_weights=True)

    ref = make_fv_converter(conf, dim_bits=16)
    for d in data:  # observe phase (per-datum convert's df bookkeeping)
        named = ref.convert_named(d)
        idf_idx = {ref.hasher.index(n) for n in named if n.endswith("/idf")}
        if idf_idx:
            ref.weights.observe(idf_idx)
    vectors = [ref.convert(d) for d in data]
    _assert_csr_equals_vectors(csr, vectors, "idf-batch")
    # ndocs counts only documents that carried idf features
    assert bat.weights.ndocs == ref.weights.ndocs
    np.testing.assert_array_equal(bat.weights._df_diff, ref.weights._df_diff)

    # batch size 1 == per-datum sequential, document by document
    seq = make_fv_converter(conf, dim_bits=16)
    one = make_fv_converter(conf, dim_bits=16)
    for d in data:
        v = seq.convert(d, update_weights=True)
        c = one.convert_batch([d], update_weights=True)
        _assert_csr_equals_vectors(c, [v], "b1")


def test_memo_cache_never_serves_stale_idf():
    """The memo caches hold tokenizations/hashes only — after the df
    state moves (more documents observed), the SAME input string must
    come out with the NEW idf weighting."""
    conf = {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "bin",
         "global_weight": "idf"}]}
    conv = make_fv_converter(conf, dim_bits=16)
    d = Datum({"t": "common rare"})
    first = conv.convert_batch([d], update_weights=True)
    # shift the weights: 'common' appears in 3 more docs
    for _ in range(3):
        conv.convert_batch([Datum({"t": "common"})], update_weights=True)
    again = conv.convert_batch([d])  # same string, memoized tokenization
    name_c = "t$common@space#bin/idf"
    name_r = "t$rare@space#bin/idf"
    ic, ir = conv.hasher.index(name_c), conv.hasher.index(name_r)
    vals = dict(zip(again.indices.tolist(), again.values.tolist()))
    assert vals[ic] == pytest.approx(math.log(4 / 4))
    assert vals[ir] == pytest.approx(math.log(4 / 1))
    # and the first conversion saw the then-current (1-doc) state
    vals0 = dict(zip(first.indices.tolist(), first.values.tolist()))
    assert vals0[ic] == pytest.approx(math.log(1 / 1))
    # user weights too: set after first conversion, must apply at once
    conf_w = {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "bin",
         "global_weight": "weight"}]}
    cw = make_fv_converter(conf_w, dim_bits=16)
    dw = Datum({"t": "x"})
    before = cw.convert_batch([dw])
    iw = cw.hasher.index("t$x@space#bin/weight")
    cw.weights.set_user_weight(iw, 7.0)
    after = cw.convert_batch([dw])
    assert dict(zip(before.indices.tolist(),
                    before.values.tolist()))[iw] == 1.0
    assert dict(zip(after.indices.tolist(),
                    after.values.tolist()))[iw] == 7.0


def test_cache_disabled_still_correct():
    conf = SYNTH_CONFIGS["text_tf"]
    a = make_fv_converter(conf, dim_bits=16, cache_size=0)
    b = make_fv_converter(conf, dim_bits=16)
    d = [Datum({"t": "a b a c"}), Datum({"t": "a b a c"})]
    ca, cb = a.convert_batch(d), b.convert_batch(d)
    np.testing.assert_array_equal(ca.indices, cb.indices)
    np.testing.assert_array_equal(ca.values, cb.values)
    assert not a._token_memo and not a._name_memo


def test_cache_bound_holds():
    conv = make_fv_converter(SYNTH_CONFIGS["text_tf"], dim_bits=16)
    conv.set_cache_size(8)
    for i in range(100):
        conv.convert_batch([Datum({"t": "tok%d" % i})])
    assert len(conv._token_memo) <= 8
    assert len(conv._name_memo) <= 8


# -- hasher batch paths ------------------------------------------------------

def test_index_array_matches_index():
    h = FeatureHasher(dim_bits=14)
    names = ["feat%d" % i for i in range(200)] + ["éא", ""]
    arr = h.index_array(names)
    assert arr.dtype == np.int32
    assert [h.index(n) for n in names] == arr.tolist()
    assert (arr != 0).all()


def test_reverse_capacity_bound_on_batch_paths():
    """Regression (ISSUE 5 satellite): every batch hash path must honor
    reverse_capacity — one oversized batch must not blow past the
    bound."""
    for method in ("index_many", "index_array"):
        h = FeatureHasher(dim_bits=16, reverse_capacity=10)
        getattr(h, method)(["n%d" % i for i in range(500)])
        assert len(h._reverse) <= 10
        # and remember=False records nothing
        h2 = FeatureHasher(dim_bits=16, reverse_capacity=10)
        getattr(h2, method)(["n%d" % i for i in range(50)],
                            remember=False)
        assert not h2._reverse


# -- vectorized weight manager ----------------------------------------------

def test_weight_manager_vectorized_lookups():
    wm = WeightManager(1 << 10)
    wm.observe([3, 5])
    wm.observe([5])
    wm.observe([7])
    idx = np.array([3, 5, 7, 9])
    np.testing.assert_allclose(
        wm.idf_many(idx), [wm.idf(3), wm.idf(5), wm.idf(7), wm.idf(9)])
    wm.set_user_weight(9, 4.0)
    np.testing.assert_allclose(
        wm.user_weight_many(idx), [1.0, 1.0, 1.0, 4.0])


def test_observe_batch_dedups_per_document():
    a = WeightManager(1 << 10)
    b = WeightManager(1 << 10)
    docs = [[3, 5, 3], [5, 5], [7]]
    for d in docs:
        a.observe(set(d))
    flat = np.concatenate([np.asarray(d) for d in docs])
    rows = np.concatenate([np.full(len(d), i) for i, d in enumerate(docs)])
    b.observe_batch(flat, rows)
    np.testing.assert_array_equal(a._df_diff, b._df_diff)
    assert a.ndocs == b.ndocs == 3


def test_observe_rows_skips_padding():
    wm = WeightManager(1 << 10)
    idx = np.array([[3, 5, 0, 0], [5, 0, 0, 0]], dtype=np.int32)
    wm.observe_rows(idx)
    assert wm._df_diff[0] == 0.0
    assert wm._df_diff[3] == 1.0 and wm._df_diff[5] == 2.0
    assert wm.ndocs == 2


# -- CSR packing -------------------------------------------------------------

def test_csr_to_padded_matches_from_vectors():
    import random

    rng = random.Random(3)
    vecs = []
    for _ in range(23):
        k = rng.randint(0, 9)
        vecs.append(sorted((rng.randint(1, 1000), rng.uniform(-1, 1))
                           for _ in range(k)))
    csr = CSRBatch.from_vectors(vecs)
    for bucket in (1, 16):
        a = csr.to_padded(batch_bucket=bucket)
        b = SparseBatch.from_vectors(vecs, batch_bucket=bucket)
        np.testing.assert_array_equal(a.idx, b.idx)
        np.testing.assert_array_equal(a.val, b.val)
    assert csr.rows() == [[(i, pytest.approx(v, abs=1e-6)) for i, v in vec]
                          for vec in vecs]


def test_csr_uniform_row_detection():
    uni = CSRBatch.from_vectors([[(3, 1.0), (9, 2.0)]] * 4)
    np.testing.assert_array_equal(uni.uniform_row(), [3, 9])
    ragged = CSRBatch.from_vectors([[(3, 1.0)], [(3, 1.0), (9, 2.0)]])
    assert ragged.uniform_row() is None
    mixed = CSRBatch.from_vectors([[(3, 1.0)], [(4, 1.0)]])
    assert mixed.uniform_row() is None
    assert CSRBatch.from_vectors([]).uniform_row() is None


# -- drivers on the batch API ------------------------------------------------

def test_classifier_train_classify_batch_native():
    from jubatus_tpu.models.classifier import ClassifierDriver

    conf = {"method": "AROW", "parameter": {"regularization_weight": 1.0},
            "converter": {"string_rules": [
                {"key": "*", "type": "space", "sample_weight": "tf",
                 "global_weight": "idf"}]}}
    d = ClassifierDriver(conf, dim_bits=12)
    data = [("spam", Datum({"t": "win money now"})),
            ("ham", Datum({"t": "meet at noon"}))] * 3
    assert d.train(data) == 6
    out = d.classify([Datum({"t": "money money"}),
                      Datum({"t": "noon meet"})])
    assert len(out) == 2
    assert max(out[0], key=lambda p: p[1])[0] == "spam"
    assert max(out[1], key=lambda p: p[1])[0] == "ham"
    # featurize/apply split (the pipelined coalescer's two stages)
    labels, idx, val = d.featurize_train(data)
    assert len(labels) == 6 and idx.shape == val.shape
    assert d.train_hashed(labels, idx, val) == 6


def test_regression_batch_native():
    from jubatus_tpu.models.regression import RegressionDriver

    conf = {"method": "PA1",
            "parameter": {"sensitivity": 0.1, "regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    d = RegressionDriver(conf, dim_bits=12)
    data = [(float(x), Datum({"x": float(x)})) for x in range(1, 9)]
    assert d.train(data) == 8
    est = d.estimate([Datum({"x": 4.0})])
    assert len(est) == 1 and est[0] != 0.0
