"""fv_converter tests — config parsing, extraction rules, weighting, hashing.

Models the converter blocks used across /root/reference/config/*/*.json.
"""

import math

import pytest

from jubatus_tpu.core import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.fv.converter import ConverterError

DEFAULT = {
    "string_rules": [
        {"key": "*", "type": "str", "sample_weight": "bin", "global_weight": "bin"}
    ],
    "num_rules": [{"key": "*", "type": "num"}],
}


def test_default_converter_str_and_num():
    conv = make_fv_converter(DEFAULT, dim_bits=16)
    named = conv.convert_named(Datum({"title": "hello", "age": 25}))
    assert named == {"title$hello@str#bin/bin": 1.0, "age@num": 25.0}


def test_hashed_output_stable_and_padded_slot_free():
    conv = make_fv_converter(DEFAULT, dim_bits=16)
    fv1 = conv.convert(Datum({"title": "hello", "age": 25}))
    fv2 = conv.convert(Datum({"title": "hello", "age": 25}))
    assert fv1 == fv2
    assert all(i != 0 for i, _ in fv1)  # index 0 reserved for padding
    assert all(0 < i < conv.dim for i, _ in fv1)


def test_ngram_splitter_tf_weighting():
    cfg = {
        "string_types": {"bigram": {"method": "ngram", "char_num": "2"}},
        "string_rules": [
            {"key": "*", "type": "bigram", "sample_weight": "tf", "global_weight": "bin"}
        ],
        "num_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"t": "aaa"}))
    # bigrams of "aaa" = ["aa", "aa"] -> tf 2
    assert named == {"t$aa@bigram#tf/bin": 2.0}


def test_space_splitter_log_tf():
    cfg = {
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "log_tf", "global_weight": "bin"}
        ],
        "num_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"t": "a b a"}))
    assert named["t$a@space#log_tf/bin"] == pytest.approx(math.log(3.0))
    assert named["t$b@space#log_tf/bin"] == pytest.approx(math.log(2.0))


def test_num_log_and_str_types():
    cfg = {
        "num_types": {"mylog": {"method": "log"}},
        "num_rules": [
            {"key": "a", "type": "mylog"},
            {"key": "b", "type": "str"},
        ],
        "string_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"a": 100.0, "b": 42}))
    assert named["a@mylog"] == pytest.approx(math.log(100.0))
    assert named["b$42@str"] == 1.0


def test_idf_global_weight():
    cfg = {
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "bin", "global_weight": "idf"}
        ],
        "num_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    # document 1: "common rare", documents 2..4: "common"
    conv.convert(Datum({"t": "common rare"}), update_weights=True)
    for _ in range(3):
        conv.convert(Datum({"t": "common"}), update_weights=True)
    named = conv.convert_named(Datum({"t": "common rare"}))
    assert named["t$common@space#bin/idf"] == pytest.approx(math.log(4 / 4))
    assert named["t$rare@space#bin/idf"] == pytest.approx(math.log(4 / 1))


def test_string_filter_regexp():
    cfg = {
        "string_filter_types": {
            "detag": {"method": "regexp", "pattern": "<[^>]*>", "replace": ""}
        },
        "string_filter_rules": [{"key": "*", "type": "detag", "suffix": "-detagged"}],
        "string_rules": [
            {"key": "*-detagged", "type": "str", "sample_weight": "bin", "global_weight": "bin"}
        ],
        "num_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"html": "<p>hi</p>"}))
    assert named == {"html-detagged$hi@str#bin/bin": 1.0}


def test_num_filters():
    cfg = {
        "num_filter_types": {
            "add5": {"method": "add", "value": "5"},
            "lin": {"method": "linear_normalization", "min": "0", "max": "100"},
            "sig": {"method": "sigmoid_normalization", "gain": "1", "bias": "0"},
        },
        "num_filter_rules": [
            {"key": "x", "type": "add5", "suffix": "+5"},
            {"key": "x", "type": "lin", "suffix": "_lin"},
            {"key": "x", "type": "sig", "suffix": "_sig"},
        ],
        "num_rules": [{"key": "*", "type": "num"}],
        "string_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"x": 50.0}))
    assert named["x@num"] == 50.0
    assert named["x+5@num"] == 55.0
    assert named["x_lin@num"] == pytest.approx(0.5)
    assert named["x_sig@num"] == pytest.approx(1 / (1 + math.exp(-50)))


def test_combination_rules_mul():
    cfg = {
        "string_rules": [],
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_rules": [{"key_left": "*", "key_right": "*", "type": "mul"}],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"a": 2.0, "b": 3.0}))
    assert named["a@num"] == 2.0 and named["b@num"] == 3.0
    assert named["a@num&b@num"] == 6.0


def test_key_matchers_prefix_suffix_exact():
    cfg = {
        "num_rules": [
            {"key": "pre*", "type": "num"},
            {"key": "*fix", "type": "log"},
            {"key": "exact", "type": "num"},
        ],
        "string_rules": [],
    }
    conv = make_fv_converter(cfg, dim_bits=16)
    named = conv.convert_named(Datum({"pre_a": 1.0, "suf_fix": 2.0, "exact": 3.0, "no": 4.0}))
    assert set(named) == {"pre_a@num", "suf_fix@log", "exact@num"}


def test_revert_feature():
    conv = make_fv_converter(DEFAULT, dim_bits=16)
    fv = conv.convert(Datum({"title": "hello"}))
    (idx, _), = fv
    assert conv.revert_feature(idx) == ("title", "hello")


def test_invalid_configs_raise():
    with pytest.raises(ConverterError):
        make_fv_converter({"string_rules": [{"key": "*", "type": "nope"}]})
    with pytest.raises(ConverterError):
        make_fv_converter(
            {"string_rules": [
                {"key": "*", "type": "str", "sample_weight": "huh", "global_weight": "bin"}
            ]}
        )
    with pytest.raises(ConverterError):
        make_fv_converter({"num_types": {"x": {"method": "wat"}}, "num_rules": []})


def test_reference_config_files_parse():
    """Every converter block shipped in config/ (this repo's copy of the
    reference's per-engine example configs) must parse. The old absolute
    /root/reference path only existed on the original capture host — the
    repo's own config/ tree is the durable copy of the same files."""
    import glob
    import json
    import os

    base = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "config")
    paths = glob.glob(os.path.join(base, "*", "*.json"))
    assert paths, "reference configs not found"
    for path in paths:
        with open(path) as f:
            cfg = json.load(f)
        if "converter" in cfg:
            make_fv_converter(cfg["converter"], dim_bits=10)


def test_hash_max_size_caps_dimension():
    """"hash_max_size" in the converter block (reference core's
    converter_config optional member) pins the hashed feature space,
    overriding the driver-side dim_bits default; non-power-of-two caps
    round DOWN (the memory cap the option exists for must hold)."""
    conv = make_fv_converter(
        {"num_rules": [{"key": "*", "type": "num"}],
         "hash_max_size": 1 << 14},
        dim_bits=20)
    assert conv.hasher.dim == 1 << 14
    fv = conv.convert(Datum({"x": 2.0}))
    assert all(0 < i < (1 << 14) for i, _ in fv)
    # non-power-of-two: capped below, never above
    conv2 = make_fv_converter({"hash_max_size": 1000})
    assert conv2.hasher.dim == 512
    with pytest.raises(ConverterError):
        make_fv_converter({"hash_max_size": 4})


def test_hash_max_size_flows_through_driver():
    from jubatus_tpu.models.classifier import ClassifierDriver

    d = ClassifierDriver(
        {"method": "PA", "parameter": {"regularization_weight": 1.0},
         "converter": {"num_rules": [{"key": "*", "type": "num"}],
                       "hash_max_size": 1 << 12}})
    assert d.converter.dim == 1 << 12
    assert d.state.w.shape[-1] == 1 << 12
