"""fv_converter plugin system tests (≙ plugin/src/fv_converter/*_test.cpp).

Covers: path-based loading (the dlopen seam), builtin-name resolution,
ux_splitter trie extraction, binary rules, error paths, module caching.
mecab/image plugins are exercised only if their backing libraries exist
(same gating as the reference's optional plugin builds).
"""

from __future__ import annotations

import json

import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import plugins
from jubatus_tpu.core.fv.converter import ConverterError, make_fv_converter


@pytest.fixture(autouse=True)
def _clear_plugin_cache():
    plugins.clear_cache()
    yield
    plugins.clear_cache()


def test_load_plugin_from_path(tmp_path):
    plug = tmp_path / "shout_splitter.py"
    plug.write_text(
        "def create(params):\n"
        "    suffix = params.get('suffix', '!')\n"
        "    return lambda text: [w + suffix for w in text.split()]\n"
    )
    conf = {
        "string_types": {
            "shout": {"method": "dynamic", "path": str(plug),
                      "function": "create", "suffix": "!!"},
        },
        "string_rules": [{"key": "*", "type": "shout",
                          "sample_weight": "bin", "global_weight": "bin"}],
    }
    conv = make_fv_converter(conf)
    named = conv.convert_named(Datum({"msg": "hello world"}))
    assert any("hello!!" in k for k in named)
    assert any("world!!" in k for k in named)


def test_plugin_object_with_split_method(tmp_path):
    plug = tmp_path / "obj_splitter.py"
    plug.write_text(
        "class S:\n"
        "    def split(self, text):\n"
        "        return list(text)\n"
        "def create(params):\n"
        "    return S()\n"
    )
    conf = {
        "string_types": {"chars": {"method": "dynamic", "path": str(plug)}},
        "string_rules": [{"key": "*", "type": "chars",
                          "sample_weight": "tf", "global_weight": "bin"}],
    }
    named = make_fv_converter(conf).convert_named(Datum({"k": "aab"}))
    tf = {k: v for k, v in named.items()}
    assert any(v == 2.0 for v in tf.values())  # 'a' twice


def test_ux_splitter_builtin_by_name(tmp_path):
    kw = tmp_path / "kw.txt"
    kw.write_text("jubatus\ntpu\nbat\n")
    conf = {
        "string_types": {
            "ux": {"method": "dynamic", "path": "ux_splitter",
                   "function": "create", "dict_path": str(kw)},
        },
        "string_rules": [{"key": "*", "type": "ux",
                          "sample_weight": "bin", "global_weight": "bin"}],
    }
    named = make_fv_converter(conf).convert_named(
        Datum({"t": "jubatus on tpu"}))
    terms = {k.split("$")[1].split("@")[0] for k in named}
    assert terms == {"jubatus", "tpu", "bat"}  # 'bat' inside 'jubatus'


def test_num_plugin(tmp_path):
    plug = tmp_path / "squarer.py"
    plug.write_text(
        "def create(params):\n"
        "    return lambda key, value: [(key + '@sq', value * value)]\n"
    )
    conf = {
        "num_types": {"sq": {"method": "dynamic", "path": str(plug)}},
        "num_rules": [{"key": "*", "type": "sq"}],
    }
    named = make_fv_converter(conf).convert_named(Datum({"x": 3.0}))
    assert named["x@sq"] == 9.0


def test_binary_plugin(tmp_path):
    plug = tmp_path / "bytecount.py"
    plug.write_text(
        "def create(params):\n"
        "    return lambda key, data: [(key + '$len', float(len(data)))]\n"
    )
    conf = {
        "binary_types": {"len": {"method": "dynamic", "path": str(plug)}},
        "binary_rules": [{"key": "*", "type": "len"}],
    }
    d = Datum()
    d.add("blob", b"12345")
    named = make_fv_converter(conf).convert_named(d)
    assert named["blob$len"] == 5.0


def test_missing_plugin_path_raises():
    conf = {
        "string_types": {"x": {"method": "dynamic", "path": "/nope/missing.py"}},
        "string_rules": [{"key": "*", "type": "x"}],
    }
    with pytest.raises(ConverterError, match="not found"):
        make_fv_converter(conf)


def test_plugin_without_factory_raises(tmp_path):
    plug = tmp_path / "empty.py"
    plug.write_text("x = 1\n")
    conf = {"string_types": {"x": {"method": "dynamic", "path": str(plug)}},
            "string_rules": [{"key": "*", "type": "x"}]}
    with pytest.raises(ConverterError, match="factory"):
        make_fv_converter(conf)


def test_module_cache_reused(tmp_path):
    plug = tmp_path / "counted.py"
    plug.write_text(
        "CALLS = []\n"
        "def create(params):\n"
        "    CALLS.append(1)\n"
        "    return lambda text: [text]\n"
    )
    p = {"method": "dynamic", "path": str(plug)}
    s1 = plugins.load_string_plugin(dict(p))
    s2 = plugins.load_string_plugin(dict(p))
    assert s1("a") == s2("a") == ["a"]
    mod = plugins._load_module(str(plug))
    assert len(mod.CALLS) == 2  # two factory calls, ONE module import


def test_binary_rule_unknown_type_rejected():
    conf = {"binary_rules": [{"key": "*", "type": "ghost"}]}
    with pytest.raises(ConverterError, match="binary rule"):
        make_fv_converter(conf)


def test_mecab_plugin_if_available():
    pytest.importorskip("MeCab")
    from jubatus_tpu.plugins.mecab_splitter import create

    sp = create({"ngram": "1", "base": "false"})
    assert isinstance(sp.split("これはテストです"), list)


def test_image_plugin_if_available(tmp_path):
    cv2 = pytest.importorskip("cv2")
    import numpy as np

    from jubatus_tpu.plugins.image_feature import create

    img = (np.random.default_rng(0).random((32, 32)) * 255).astype("uint8")
    ok, buf = cv2.imencode(".png", img)
    assert ok
    feats = list(create({"algorithm": "dense", "resize": "true",
                         "width": "8", "height": "8"}).extract("im", buf.tobytes()))
    assert len(feats) == 64
