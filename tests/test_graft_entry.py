"""The driver's entry points must work in the DRIVER environment.

The driver imports __graft_entry__ under the real accelerator platform
(one chip) — not under tests/conftest.py's virtual 8-CPU mesh. Round 1's
multichip gate failed precisely because dryrun_multichip assumed someone
else had provisioned virtual devices. These tests run the entry points in
a fresh subprocess WITHOUT conftest's env so what is tested is what the
driver actually runs.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _driver_env():
    """A copy of the environment with conftest's virtual-mesh vars removed,
    pinned to a single CPU device — the shape of the driver's world (one
    real device, no bootstrap help)."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JUBATUS_TPU_PLATFORM",
                     "_JUBATUS_TPU_DRYRUN_CHILD")
    }
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import bench_mix

    env = bench_mix.scrub_child_env(env)  # repo on path, axon plugin off
    # driver shape: plain JAX_PLATFORMS, no JUBATUS_TPU_PLATFORM override
    env.pop("JUBATUS_TPU_PLATFORM", None)
    env["JAX_PLATFORMS"] = "cpu"  # no accelerator in the test sandbox
    return env


def _run(prog: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", prog], env=_driver_env(), cwd=REPO,
        capture_output=True, text=True, timeout=900)


@pytest.mark.slow
def test_dryrun_multichip_bootstraps_from_one_device():
    """dryrun_multichip(8) with only 1 visible device must self-provision
    virtual CPU devices in a child process and succeed (VERDICT round 1:
    the gate crashed with 'mesh 4x2 needs 8 devices, have 1')."""
    proc = _run(
        "import jax\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('PARENT-OK')\n"
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "PARENT-OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_multichip_odd_device_count():
    """Replica-only (1-D mesh) branch must bootstrap too."""
    proc = _run(
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(3)\n"
        "print('PARENT-OK')\n"
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "PARENT-OK" in proc.stdout


@pytest.mark.slow
def test_entry_compiles_single_device():
    proc = _run(
        "import jax, __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('ENTRY-OK')\n"
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "ENTRY-OK" in proc.stdout
