"""jubadump tests (≙ the reference's model-dump tool, man/en/jubadump.1)."""

from __future__ import annotations

import json

import pytest

from jubatus_tpu.cmd import jubadump
from jubatus_tpu.core.datum import Datum
from jubatus_tpu.framework import save_model
from jubatus_tpu.server.factory import create_driver

STAT_CFG = {"window_size": 16}

CLASSIFIER_CFG = {
    "method": "PA",
    "parameter": {},
    "converter": {
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "bin", "global_weight": "bin"}],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}


@pytest.fixture()
def model_file(tmp_path):
    d = create_driver("classifier", CLASSIFIER_CFG)
    d.train([("spam", Datum({"w": "buy pills now"})),
             ("ham", Datum({"w": "lunch at noon"}))])
    path = str(tmp_path / "m.jubatus")
    save_model(path, d, model_id="snap1", config=json.dumps(CLASSIFIER_CFG))
    return path


def test_dump_full(model_file):
    out = jubadump.dump_file(model_file)
    assert out["header"]["crc32_ok"] is True
    assert out["header"]["format_version"] == 1
    assert out["system"]["type"] == "classifier"
    assert out["system"]["id"] == "snap1"
    # config comes back structured, not as an escaped string
    assert out["system"]["config"]["method"] == "PA"
    assert "user_data" in out
    json.dumps(out)  # fully JSON-serializable


def test_dump_summary_digests_arrays(model_file):
    out = jubadump.dump_file(model_file, summary=True)
    blob = json.dumps(out)
    # weight tables (2^20-ish floats) must be digested, not dumped
    assert len(blob) < 100_000
    assert "__array__" in blob


def test_dump_rejects_non_model(tmp_path):
    p = tmp_path / "junk.bin"
    p.write_bytes(b"\x00" * 100)
    with pytest.raises(ValueError, match="bad magic"):
        jubadump.dump_file(str(p))
    (tmp_path / "short.bin").write_bytes(b"xy")
    with pytest.raises(ValueError, match="truncated"):
        jubadump.dump_file(str(tmp_path / "short.bin"))


def test_dump_detects_corruption(model_file):
    raw = bytearray(open(model_file, "rb").read())
    raw[-1] ^= 0xFF
    open(model_file, "wb").write(bytes(raw))
    out = jubadump.dump_file(model_file)
    assert out["header"]["crc32_ok"] is False


def test_dump_undecodable_body_keeps_header_report(tmp_path):
    """A body that passes the size check but is not valid msgpack must
    produce a JSON report with the header + an error, not a traceback."""
    import struct
    import zlib

    from jubatus_tpu.framework.save_load import _HEADER, MAGIC

    body = b"\xc1" * 32 + b"\xc1" * 16  # 0xc1 is the one invalid msgpack byte
    header = _HEADER.pack(MAGIC, 1, 1, 0, 2,
                          zlib.crc32(body) & 0xFFFFFFFF, 32, 16)
    p = tmp_path / "garbage.jubatus"
    p.write_bytes(header + body)
    out = jubadump.dump_file(str(p))
    assert out["header"]["crc32_ok"] is True
    assert "system_error" in out
    import json as _json
    _json.dumps(out)


def test_cli_main(model_file, capsys):
    assert jubadump.main(["-i", model_file, "--summary"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["system"]["type"] == "classifier"
    assert jubadump.main(["-i", model_file + ".nope"]) == 1


def test_genman_renders_all_pages(tmp_path):
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "docs" / "gen_man.py"), str(tmp_path)],
        capture_output=True, text=True, cwd=str(repo))
    assert r.returncode == 0, r.stderr[:1500]
    pages = sorted(p.name for p in tmp_path.iterdir())
    assert "jubadump.1" in pages
    assert "jubactl.8" in pages
    assert "jubatus_server.8" in pages
    for p in tmp_path.iterdir():
        txt = p.read_text()
        assert txt.startswith(".TH ")
        assert ".SH SYNOPSIS" in txt and ".SH OPTIONS" in txt
        # exactly one OPTIONS section (argparse groups merge into it)
        assert txt.count(".SH OPTIONS") == 1
        # DESCRIPTION present only with body text, never empty
        if ".SH DESCRIPTION" in txt:
            after = txt.split(".SH DESCRIPTION", 1)[1].lstrip().splitlines()
            assert after and not after[0].startswith(".SH")


def test_dump_sharded_sidecar_reports_null_user_data(tmp_path, capsys):
    """A sharded-checkpoint sidecar (usize == 0) is a valid dump target:
    user_data must be null, not a spurious user_data_error (ADVICE r1)."""
    import numpy as np

    from jubatus_tpu.framework import sharded_checkpoint as sc

    state = {"w": np.zeros((2, 8), np.float32)}
    d = str(tmp_path / "ckpt")
    sc.save_sharded(d, state, engine_type="classifier", model_id="s1",
                    config=json.dumps(CLASSIFIER_CFG))
    out = jubadump.dump_file(str(tmp_path / "ckpt" / "system.jubatus"))
    assert "user_data_error" not in out
    assert out["user_data"] is None
    assert out["system"]["sharded"] is True
