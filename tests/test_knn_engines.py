"""NN kernel + nearest_neighbor / recommender / anomaly engine tests.

Kernel properties are checked against numpy references; engine APIs against
the reference IDL surfaces (nearest_neighbor.idl, recommender.idl,
anomaly.idl) with real config shapes from /root/reference/config/."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.row_store import RowStore
from jubatus_tpu.core.sparse import SparseBatch
from jubatus_tpu.models import (AnomalyDriver, NearestNeighborDriver,
                                RecommenderDriver)
from jubatus_tpu.ops import knn
from jubatus_tpu.parallel import LocalMixGroup

CONV = {
    "string_rules": [{"key": "*", "type": "str", "sample_weight": "bin",
                      "global_weight": "bin"}],
    "num_rules": [{"key": "*", "type": "num"}],
}


def _nn_cfg(method, **param):
    return {"converter": CONV, "method": method,
            "parameter": {"hash_num": 64, **param}}


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------
def test_lsh_signature_deterministic_and_similarity_ordering(rng):
    k, h = 16, 128
    base = rng.normal(size=512).astype(np.float32)
    idx = jnp.asarray(rng.integers(1, 512, size=(3, k), dtype=np.int32))
    # row 0 and row 1 share indices/values (identical); row 2 differs
    idx = idx.at[1].set(idx[0])
    val = jnp.asarray(rng.normal(size=(3, k)).astype(np.float32))
    val = val.at[1].set(val[0])
    sigs = knn.lsh_signature(idx, val, hash_num=h)
    assert sigs.shape == (3, knn.packed_words(h))
    d = knn.hamming_distances(sigs[0], sigs, hash_num=h)
    assert d[0] == 0.0 and d[1] == 0.0
    assert 0.0 < float(d[2]) <= 1.0


def test_lsh_close_vectors_closer_than_random(rng):
    h, k = 256, 32
    idx = rng.integers(1, 4096, size=(3, k), dtype=np.int32)
    idx[1] = idx[0]  # same support
    v0 = rng.normal(size=k).astype(np.float32)
    val = np.stack([v0, v0 + 0.01 * rng.normal(size=k).astype(np.float32),
                    rng.normal(size=k).astype(np.float32)])
    sigs = knn.lsh_signature(jnp.asarray(idx), jnp.asarray(val), hash_num=h)
    d = knn.hamming_distances(sigs[0], sigs, hash_num=h)
    assert float(d[1]) < float(d[2])


def test_minhash_jaccard_estimate(rng):
    h = 512
    # sets: A={1..20}, B={1..10, 101..110} -> weighted jaccard = 10/30
    a = [(i, 1.0) for i in range(1, 21)]
    b = [(i, 1.0) for i in range(1, 11)] + [(i, 1.0) for i in range(101, 111)]
    sb = SparseBatch.from_vectors([a, b])
    sigs = knn.minhash_signature(jnp.asarray(sb.idx), jnp.asarray(sb.val),
                                 hash_num=h)
    d = knn.minhash_distances(sigs[0], sigs)
    assert d[0] == 0.0
    assert float(d[1]) == pytest.approx(1 - 10 / 30, abs=0.08)


def test_euclid_lsh_distance_estimate(rng):
    h = 512
    x = rng.normal(size=64).astype(np.float32)
    y = x + rng.normal(size=64).astype(np.float32) * 0.5
    ids = np.arange(1, 65, dtype=np.int32)
    sb = SparseBatch.from_vectors(
        [[(int(i), float(v)) for i, v in zip(ids, x)],
         [(int(i), float(v)) for i, v in zip(ids, y)]])
    p = knn.euclid_projection(jnp.asarray(sb.idx), jnp.asarray(sb.val), hash_num=h)
    d = knn.euclid_lsh_distances(p[0], p, hash_num=h)
    true = float(np.linalg.norm(x - y))
    assert float(d[0]) == pytest.approx(0.0, abs=1e-4)
    assert float(d[1]) == pytest.approx(true, rel=0.25)


def test_exact_cosine_and_euclid_kernels(rng):
    dim = 1 << 10
    rows = rng.normal(size=(5, 8)).astype(np.float32)
    ids = rng.integers(1, dim, size=(5, 8)).astype(np.int32)
    q = np.zeros(dim, np.float32)
    qi = ids[0]
    q[qi] = rows[0]
    d_cos = knn.cosine_scores(jnp.asarray(ids), jnp.asarray(rows), jnp.asarray(q))
    assert float(d_cos[0]) == pytest.approx(1.0, abs=1e-5)
    d_euc = knn.euclid_distances(jnp.asarray(ids), jnp.asarray(rows), jnp.asarray(q))
    assert float(d_euc[0]) == pytest.approx(0.0, abs=1e-3)


def test_batched_distance_kernels_match_single(rng):
    h = 64
    sb = SparseBatch.from_vectors(
        [[(int(i), float(v)) for i, v in
          zip(rng.integers(1, 256, 12), rng.normal(size=12))] for _ in range(6)])
    idx, val = jnp.asarray(sb.idx), jnp.asarray(sb.val)
    sigs = knn.lsh_signature(idx, val, hash_num=h)
    batch = knn.hamming_distances_batch(sigs, sigs, hash_num=h)
    for i in range(6):
        single = knn.hamming_distances(sigs[i], sigs, hash_num=h)
        np.testing.assert_allclose(np.asarray(batch[i]), np.asarray(single))
    proj = knn.euclid_projection(idx, val, hash_num=h)
    pb = knn.euclid_lsh_distances_batch(proj, proj, hash_num=h)
    for i in range(6):
        single = knn.euclid_lsh_distances(proj[i], proj, hash_num=h)
        # batch kernel uses the MXU-friendly ||q||^2 - 2q.r + ||r||^2
        # expansion, which loses ~1e-3 absolute precision in f32
        np.testing.assert_allclose(np.asarray(pb[i]), np.asarray(single),
                                   rtol=1e-4, atol=5e-3)


# ---------------------------------------------------------------------------
# row store
# ---------------------------------------------------------------------------
def test_row_store_set_get_remove_grow():
    rs = RowStore()
    for i in range(200):  # force capacity growth past 64
        rs.set_row(f"r{i}", [(i + 1, 1.0)])
    assert len(rs) == 200
    assert rs.get_row("r5") == [(6, 1.0)]
    assert rs.remove_row("r5")
    assert not rs.remove_row("r5")
    assert "r5" not in rs
    # width growth
    rs.set_row("wide", [(i, 1.0) for i in range(1, 40)])
    assert rs.width >= 40
    assert len(rs.get_row("wide")) == 39


def test_row_store_lru_eviction():
    rs = RowStore(max_size=3)
    for i in range(3):
        rs.set_row(f"r{i}", [(i + 1, 1.0)])
    rs.touch("r0")  # refresh r0; r1 is now LRU
    rs.set_row("r3", [(10, 1.0)])
    assert "r1" not in rs
    assert "r0" in rs and "r2" in rs and "r3" in rs


def test_row_store_pack_unpack():
    rs = RowStore()
    rs.set_row("a", [(3, 1.5), (7, -2.0)])
    rs2 = RowStore()
    rs2.unpack(rs.pack())
    assert rs2.get_row("a") == [(3, 1.5), (7, -2.0)]


# ---------------------------------------------------------------------------
# nearest_neighbor engine
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("method", ["lsh", "minhash", "euclid_lsh"])
def test_nn_engine_finds_identical_row(method):
    d = NearestNeighborDriver(_nn_cfg(method), dim_bits=12)
    d.set_row("x", Datum({"f1": 1.0, "f2": 2.0}))
    d.set_row("y", Datum({"f1": -5.0, "f3": 9.0}))
    d.set_row("z", Datum({"f4": 3.3}))
    res = d.neighbor_row_from_datum(Datum({"f1": 1.0, "f2": 2.0}), 2)
    assert res[0][0] == "x"
    assert res[0][1] == pytest.approx(0.0, abs=1e-5)
    res_id = d.neighbor_row_from_id("x", 3)
    assert res_id[0][0] == "x"
    assert len(res_id) == 3
    sim = d.similar_row_from_id("x", 2)
    assert sim[0][0] == "x"
    assert sorted(d.get_all_rows()) == ["x", "y", "z"]


def test_nn_engine_unlearner_caps_rows():
    cfg = _nn_cfg("lsh", unlearner="lru",
                  unlearner_parameter={"max_size": 4})
    d = NearestNeighborDriver(cfg, dim_bits=12)
    for i in range(10):
        d.set_row(f"r{i}", Datum({"f": float(i)}))
    assert len(d.get_all_rows()) == 4


def test_nn_engine_mix_replicates_rows():
    a = NearestNeighborDriver(_nn_cfg("lsh"), dim_bits=12)
    b = NearestNeighborDriver(_nn_cfg("lsh"), dim_bits=12)
    a.set_row("only_a", Datum({"f1": 1.0}))
    b.set_row("only_b", Datum({"f2": 2.0}))
    LocalMixGroup([a, b]).mix()
    assert sorted(a.get_all_rows()) == ["only_a", "only_b"]
    assert sorted(b.get_all_rows()) == ["only_a", "only_b"]


def test_nn_engine_save_load():
    d = NearestNeighborDriver(_nn_cfg("euclid_lsh"), dim_bits=12)
    d.set_row("a", Datum({"f1": 1.0}))
    d.set_row("b", Datum({"f1": 1.1}))
    d2 = NearestNeighborDriver(_nn_cfg("euclid_lsh"), dim_bits=12)
    d2.unpack(d.pack())
    assert sorted(d2.get_all_rows()) == ["a", "b"]
    assert d2.neighbor_row_from_id("a", 1)[0][0] == "a"


# ---------------------------------------------------------------------------
# recommender engine
# ---------------------------------------------------------------------------
def _rec_cfg(method, **param):
    cfg = {"converter": CONV, "method": method}
    if param or method not in ("inverted_index", "inverted_index_euclid"):
        cfg["parameter"] = param
    return cfg


def test_recommender_inverted_index_similarity():
    r = RecommenderDriver(_rec_cfg("inverted_index"), dim_bits=12)
    r.update_row("u1", Datum({"item_a": 1.0, "item_b": 1.0}))
    r.update_row("u2", Datum({"item_a": 1.0, "item_b": 1.0}))
    r.update_row("u3", Datum({"item_z": 1.0}))
    sims = r.similar_row_from_id("u1", 3)
    assert sims[0][1] == pytest.approx(1.0, abs=1e-5)  # u1 or u2 (tied)
    ids = [s[0] for s in sims[:2]]
    assert set(ids) == {"u1", "u2"}
    # orthogonal row scores ~0
    assert dict(sims).get("u3", 0.0) == pytest.approx(0.0, abs=1e-5)
    assert r.calc_similarity(Datum({"a": 1.0}), Datum({"a": 1.0})) == pytest.approx(1.0)
    assert r.calc_l2norm(Datum({"a": 3.0, "b": 4.0})) == pytest.approx(5.0)


def test_recommender_complete_and_decode_row():
    r = RecommenderDriver(_rec_cfg("inverted_index"), dim_bits=12)
    r.update_row("u1", Datum({"x": 2.0, "y": 4.0}))
    r.update_row("u2", Datum({"x": 2.0, "z": 8.0}))
    dec = r.decode_row("u1")
    assert dict(dec.num_values) == {"x": 2.0, "y": 4.0}
    comp = r.complete_row_from_datum(Datum({"x": 2.0}))
    nv = dict(comp.num_values)
    assert nv.get("x", 0) > 0
    # y and z both get partially filled from the similar rows
    assert "y" in nv and "z" in nv
    # update_row merges keys into the existing row
    r.update_row("u1", Datum({"y": 9.0}))
    assert dict(r.decode_row("u1").num_values) == {"x": 2.0, "y": 9.0}


def test_recommender_clear_row_and_get_all():
    r = RecommenderDriver(_rec_cfg("lsh", hash_num=64), dim_bits=12)
    r.update_row("a", Datum({"f": 1.0}))
    r.update_row("b", Datum({"f": 2.0}))
    assert r.clear_row("a")
    assert r.get_all_rows() == ["b"]
    r.clear()
    assert r.get_all_rows() == []


def test_recommender_nn_recommender_method():
    cfg = {"converter": CONV, "method": "nearest_neighbor_recommender",
           "parameter": {"method": "euclid_lsh",
                         "parameter": {"hash_num": 128}}}
    r = RecommenderDriver(cfg, dim_bits=12)
    r.update_row("a", Datum({"f1": 1.0}))
    r.update_row("b", Datum({"f1": 1.05}))
    r.update_row("c", Datum({"f1": 30.0}))
    sims = r.similar_row_from_id("a", 2)
    assert [s[0] for s in sims] == ["a", "b"]


def test_recommender_save_load_keeps_datums():
    r = RecommenderDriver(_rec_cfg("inverted_index"), dim_bits=12)
    r.update_row("a", Datum({"x": 1.0}))
    r2 = RecommenderDriver(_rec_cfg("inverted_index"), dim_bits=12)
    r2.unpack(r.pack())
    assert dict(r2.decode_row("a").num_values) == {"x": 1.0}


# ---------------------------------------------------------------------------
# anomaly engine
# ---------------------------------------------------------------------------
ANOMALY_CFG = {
    "converter": CONV,
    "method": "lof",
    "parameter": {"nearest_neighbor_num": 3,
                  "reverse_nearest_neighbor_num": 9,
                  "method": "euclid_lsh",
                  "parameter": {"hash_num": 256}},
}


def test_anomaly_outlier_scores_higher(rng):
    a = AnomalyDriver(ANOMALY_CFG, dim_bits=12)
    for i in range(20):
        a.add(Datum({"x": float(rng.normal()), "y": float(rng.normal())}))
    inlier = a.calc_score(Datum({"x": 0.0, "y": 0.0}))
    outlier = a.calc_score(Datum({"x": 40.0, "y": 40.0}))
    assert outlier > inlier
    assert outlier > 1.5


def test_anomaly_add_update_overwrite_clear():
    a = AnomalyDriver(ANOMALY_CFG, dim_bits=12)
    rid, score = a.add(Datum({"x": 1.0}))
    assert rid == "0"
    rid2, _ = a.add(Datum({"x": 1.1}))
    assert rid2 == "1"
    s = a.update(rid, Datum({"x": 1.05}))
    assert isinstance(s, float)
    with pytest.raises(KeyError):
        a.update("nope", Datum({"x": 0.0}))
    a.overwrite("77", Datum({"x": 2.0}))  # overwrite may create
    assert "77" in a.get_all_rows()
    assert a.clear_row("77")
    a.clear()
    assert a.get_all_rows() == []


def test_anomaly_save_load():
    a = AnomalyDriver(ANOMALY_CFG, dim_bits=12)
    for i in range(5):
        a.add(Datum({"x": float(i)}))
    a2 = AnomalyDriver(ANOMALY_CFG, dim_bits=12)
    a2.unpack(a.pack())
    assert sorted(a2.get_all_rows()) == sorted(a.get_all_rows())
    # id generator resumes past loaded rows
    rid, _ = a2.add(Datum({"x": 9.0}))
    assert rid == "5"
