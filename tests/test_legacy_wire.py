"""Legacy wire compatibility: an unmodified old jubatus client must parse
every response (VERDICT round 1 gap — the reference's vendored msgpack
predates str8/bin and REJECTS those type bytes;
client/common/client.hpp:30-87).

The "legacy client" here is a raw socket speaking old-format msgpack-rpc
plus jubatus_tpu.rpc.legacy.unpackb — a faithful reimplementation of the
pre-2013 unpacker including its rejection of post-2013 type bytes.
"""

from __future__ import annotations

import socket

import msgpack
import pytest

from jubatus_tpu.rpc import legacy
from jubatus_tpu.rpc.server import RpcServer, build_response
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

NAME = "legacy"
# get_config must round-trip a config whose JSON is far beyond 31 bytes —
# the exact case that breaks old clients when packed as str8/raw-modern
CLASSIFIER_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_rules": [
            {"key": "*", "type": "str", "sample_weight": "bin",
             "global_weight": "bin"}
        ],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}


class LegacyClient:
    """Old-format msgpack-rpc: requests packed use_bin_type=False (raw
    family only — byte-identical to what a pre-2013 client emits), responses
    decoded with the legacy unpacker that rejects str8/bin/ext."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.msgid = 0
        self.buf = b""

    def close(self):
        self.sock.close()

    def call(self, method, *params):
        self.msgid += 1
        req = msgpack.packb([0, self.msgid, method, list(params)],
                            use_bin_type=False)
        self.sock.sendall(req)
        return self._read_response()

    def _read_response(self):
        # frame by attempting a legacy decode over the accumulated bytes
        while True:
            if self.buf:
                try:
                    obj, off = legacy._decode(memoryview(self.buf), 0)
                except legacy.LegacyFormatError as e:
                    if "truncated" not in str(e):
                        raise  # forbidden type byte — the actual assertion
                else:
                    self.buf = self.buf[off:]
                    kind, msgid, error, result = obj
                    assert kind == 1 and msgid == self.msgid
                    if error is not None:
                        raise RuntimeError(f"rpc error: {error!r}")
                    return result
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed")
            self.buf += chunk


def _datum(pairs_str, pairs_num):
    # wire-format datum: ([[k, v]...], [[k, v]...])
    return [list(map(list, pairs_str)), list(map(list, pairs_num))]


@pytest.fixture(params=["forced", "autodetect"])
def legacy_server(tmp_path, request):
    """Old client against (a) a server FORCED legacy with --legacy-wire,
    and (b) a server started with NO flags — per-connection autodetection
    (VERDICT r2 item 5) must make the same full session pass."""
    srv = EngineServer(
        "classifier", CLASSIFIER_CONF,
        args=ServerArgs(engine="classifier",
                        legacy_wire=(request.param == "forced"),
                        datadir=str(tmp_path)))
    port = srv.start(0)
    cli = LegacyClient("127.0.0.1", port)
    yield cli, srv
    cli.close()
    srv.stop()


def test_legacy_client_full_session(legacy_server):
    """Every built-in + every classifier method parses under the old
    unpacker — including >=32-byte strings (get_config, get_status)."""
    cli, _srv = legacy_server
    cfg = cli.call("get_config", NAME)
    assert isinstance(cfg, bytes) and b"AROW" in cfg and len(cfg) > 32

    n = cli.call("train", NAME, [
        ["spam", _datum([["subject", "win money now"]], [])],
        ["ham", _datum([["subject", "meeting at noon"]], [])],
    ] * 5)
    assert n == 10

    res = cli.call("classify", NAME,
                   [_datum([["subject", "win money now"]], [])])
    # [[ [label, score], ... ]] — labels are old-raw bytes
    labels = {lbl: score for lbl, score in res[0]}
    assert b"spam" in labels and b"ham" in labels
    assert labels[b"spam"] > labels[b"ham"]

    labels = cli.call("get_labels", NAME)
    assert set(labels) == {b"spam", b"ham"}
    assert cli.call("set_label", NAME, "maybe") in (True, False)
    assert cli.call("delete_label", NAME, "maybe") in (True, False)

    st = cli.call("get_status", NAME)
    (node_status,) = st.values()
    assert b"classifier" == node_status[b"type"]
    # flags maps contain >=32-byte strings (paths) — must arrive as raw
    assert any(len(k) >= 32 or (isinstance(v, bytes) and len(v) >= 32)
               for k, v in node_status.items())

    paths = cli.call("save", NAME, "legacy_model")
    assert all(v.endswith(b".jubatus") for v in paths.values())
    assert cli.call("load", NAME, "legacy_model") is True
    assert cli.call("do_mix", NAME) is False  # standalone: no mixer
    assert cli.call("clear", NAME) is True


def test_autodetect_pins_modern_connection_modern(tmp_path):
    """A first request carrying a post-2013 type byte (str8) proves a
    modern client: that connection's responses stay modern (str8 present)
    — autodetection must not degrade modern clients' wire."""
    srv = EngineServer(
        "classifier", CLASSIFIER_CONF,
        args=ServerArgs(engine="classifier", datadir=str(tmp_path)))
    port = srv.start(0)
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        # use_bin_type=True + a >=32-char param emits str8 in the request;
        # the config string response is >32 bytes, so a MODERN response
        # must contain str8/bin and the legacy unpacker must reject it
        req = msgpack.packb([0, 1, "get_config", ["m" * 40]],
                            use_bin_type=True)
        sock.sendall(req)
        buf = b""
        while True:
            try:
                legacy._decode(memoryview(buf), 0)
                pytest.fail("response parsed as legacy — connection was "
                            "not pinned modern")
            except legacy.LegacyFormatError as e:
                if "truncated" not in str(e):
                    break  # forbidden modern type byte: exactly right
            chunk = sock.recv(65536)
            if not chunk:
                pytest.fail("no response")
            buf += chunk
    finally:
        sock.close()
        srv.stop()


def _read_one_frame(sock, buf=b""):
    """Accumulate bytes until one complete msgpack object; returns
    (frame_bytes, leftover)."""
    while True:
        if buf:
            u = msgpack.Unpacker()
            u.feed(buf)
            try:
                u.skip()
                end = u.tell()
                return buf[:end], buf[end:]
            except msgpack.OutOfData:
                pass
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed")
        buf += chunk


def _frame_is_legacy_format(frame: bytes) -> bool:
    try:
        legacy.unpackb(frame)
        return True
    except legacy.LegacyFormatError:
        return False


@pytest.mark.parametrize("transport", ["python", "native"])
def test_autodetect_upgrades_on_later_modern_byte(tmp_path, monkeypatch,
                                                  transport):
    """A modern client whose FIRST call is all-fixtype (short method, tiny
    args — zero post-2013 bytes) must not be latched legacy forever: the
    first request that does carry a modern type byte upgrades the
    connection, and it stays modern afterwards (ADVICE r3). Both
    transports share the rule."""
    if transport == "native":
        from jubatus_tpu.rpc import native_server
        if not native_server.available():
            pytest.skip("native rpc front-end unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC",
                       "1" if transport == "native" else "0")
    srv = EngineServer(
        "classifier", CLASSIFIER_CONF,
        args=ServerArgs(engine="classifier", datadir=str(tmp_path)))
    port = srv.start(0)
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    buf = b""
    try:
        # 1: a modern client's small first call — indistinguishable from
        # legacy on the wire, so the response is (provisionally) legacy
        sock.sendall(msgpack.packb([0, 1, "get_config", ["m"]],
                                   use_bin_type=True))
        frame, buf = _read_one_frame(sock, buf)
        assert _frame_is_legacy_format(frame)
        # 2: a later call carries str8 — proof of a modern client; the
        # connection upgrades and answers modern
        sock.sendall(msgpack.packb([0, 2, "get_config", ["m" * 40]],
                                   use_bin_type=True))
        frame, buf = _read_one_frame(sock, buf)
        assert not _frame_is_legacy_format(frame)
        # 3: modern latches: an all-fixtype request no longer downgrades
        sock.sendall(msgpack.packb([0, 3, "get_config", ["m"]],
                                   use_bin_type=True))
        frame, buf = _read_one_frame(sock, buf)
        assert not _frame_is_legacy_format(frame)
    finally:
        sock.close()
        srv.stop()


def test_native_str8_envelope_pins_modern(tmp_path, monkeypatch):
    """RpcClient.call_raw pins pooled proxy->backend connections modern by
    encoding the METHOD name as str8. The C++ front-end strips the
    envelope before Python sees the request, so it must forward the
    envelope's era evidence explicitly (ADVICE r3: without it, a legacy
    client's relayed first frame latches the pooled connection legacy)."""
    from jubatus_tpu.rpc import native_server
    if not native_server.available():
        pytest.skip("native rpc front-end unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1")
    srv = EngineServer(
        "classifier", CLASSIFIER_CONF,
        args=ServerArgs(engine="classifier", datadir=str(tmp_path)))
    port = srv.start(0)
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        # hand-built call_raw wire shape: [0, msgid, str8-method, params]
        # where the params span itself is pure legacy bytes
        m = b"get_config"
        req = (b"\x94\x00\x01\xd9" + bytes([len(m)]) + m
               + msgpack.packb(["m"], use_bin_type=False))
        sock.sendall(req)
        frame, _ = _read_one_frame(sock)
        assert not _frame_is_legacy_format(frame), \
            "str8 envelope must pin the native-transport connection modern"
    finally:
        sock.close()
        srv.stop()


def test_modern_mode_emits_str8_legacy_rejects():
    """Sanity: without --legacy-wire the same response DOES contain type
    bytes the old unpacker rejects (else the test above proves nothing)."""
    long_s = "x" * 64
    modern = build_response(1, None, long_s, legacy=False)
    with pytest.raises(legacy.LegacyFormatError):
        legacy.unpackb(modern)
    old = build_response(1, None, long_s, legacy=True)
    assert legacy.unpackb(old) == [1, 1, None, long_s.encode()]


def test_binary_methods_keep_modern_format():
    """Mixer internals ship packed bytes between OUR servers; they must
    keep the modern bin type even under legacy_wire (old clients never
    call them, and our peers need the str/bytes distinction)."""
    srv = RpcServer(legacy_wire=True)
    srv.register("mix_get_diff", lambda _n: b"\x00" * 40, binary=True)
    srv.register("get_config", lambda _n: "y" * 40)
    assert not srv.response_legacy("mix_get_diff")
    assert srv.response_legacy("get_config")
    payload = build_response(7, None, b"\x00" * 40,
                             legacy=srv.response_legacy("mix_get_diff"))
    out = msgpack.unpackb(payload, raw=False)
    assert out[3] == b"\x00" * 40  # bin type survived


def test_legacy_roundtrip_all_scalar_shapes():
    """The legacy packer/unpacker pair covers the whole old type system."""
    for v in [None, True, False, 0, 1, 127, 128, -1, -32, -33, 2**33,
              -(2**33), 0.5, "", "short", "y" * 31, "z" * 32, "w" * 70000,
              [1, [2, "three"]], {"k": [1.5, None]}, list(range(40))]:
        buf = msgpack.packb(v, use_bin_type=False)
        got = legacy.unpackb(buf)

        def norm(x):
            if isinstance(x, bytes):
                return x.decode()
            if isinstance(x, list):
                return [norm(i) for i in x]
            if isinstance(x, dict):
                return {norm(k): norm(val) for k, val in x.items()}
            return x
        assert norm(got) == v


def test_legacy_truncation_always_legacy_format_error():
    """Every truncation point raises LegacyFormatError (never struct.error)
    — the streaming framing loop keys on it to wait for more bytes."""
    for v in [3.14, 2**40, -7, "y" * 300, [1, 2, [3, "four"]], {"k": 1.5}]:
        buf = msgpack.packb(v, use_bin_type=False)
        for cut in range(len(buf)):
            with pytest.raises(legacy.LegacyFormatError):
                legacy.unpackb(buf[:cut])


def test_legacy_binary_datum_value_survives(legacy_server):
    """A legacy client packing a non-UTF8 binary datum value as old-raw
    must not kill the connection; the bytes must round-trip exactly
    (code-review round 2 finding: UnicodeDecodeError closed the socket
    with no reply)."""
    cli, srv = legacy_server
    blob = bytes(range(256))  # not valid UTF-8
    n = cli.call("train", NAME, [
        ["spam", [[["subject", "buy now"]], [], [["payload", blob]]]],
    ])
    assert n == 1
    # the connection is still alive and the server decoded the datum
    assert cli.call("get_labels", NAME)
    # direct check that surrogateescape restored the exact bytes
    from jubatus_tpu.core.datum import Datum
    via_wire = blob.decode("utf-8", "surrogateescape")
    d = Datum.from_msgpack([[["k", "v"]], [], [["bin", via_wire]]])
    assert d.binary_values == [("bin", blob)]


def test_legacy_surrogate_label_roundtrip(legacy_server):
    """A legacy client may store a non-UTF8 label (old-raw); every later
    response echoing it must re-encode to the ORIGINAL bytes, not raise
    UnicodeEncodeError after dispatch (code-review finding: the client
    would hang with no response)."""
    cli, _srv = legacy_server
    weird = b"\xff\xfelabel"
    assert cli.call("set_label", NAME, weird) in (True, False)
    labels = cli.call("get_labels", NAME)
    assert weird in set(labels)


def test_legacy_binary_datum_through_proxy():
    """The binary-datum fix must survive the proxy hop: the proxy decodes
    with surrogateescape and its forwarding client must re-encode the
    original bytes (code-review finding: UnicodeEncodeError in
    RpcClient.call was misclassified as a dead backend). The proxy runs
    with NO flags — autodetection must recognize the old client."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    store = _Store()
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name=NAME, listen_addr="127.0.0.1",
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", CLASSIFIER_CONF, args,
                       coord=MemoryCoordinator(store))
    srv.start(0)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    cli = LegacyClient("127.0.0.1", proxy.args.rpc_port)
    try:
        blob = bytes(range(256))
        n = cli.call("train", NAME, [
            ["spam", [[["subject", "buy now"]], [], [["payload", blob]]]],
        ])
        assert n == 1
        assert cli.call("get_labels", NAME)  # proxy + backend still alive
    finally:
        cli.close()
        proxy.stop()
        srv.stop()


def test_scan_is_legacy_matches_unpackb_verdict():
    """The skip-style fingerprint must agree with the full legacy decoder
    on every shape: legal-legacy buffers scan True, any post-2013 type
    byte scans False, truncation scans False."""
    legal = [None, True, 0, -5, 2**40, 0.5, "s", "y" * 31, "z" * 70000,
             [1, [2, "three"]], {"k": [1.5, None]}, list(range(40)),
             [0, 1, "train", ["c", [["lb", [[["k", "v"]], [["n", 1.0]]]]]]]]
    for v in legal:
        buf = msgpack.packb(v, use_bin_type=False)
        assert legacy.scan_is_legacy(buf), v
        for cut in range(1, len(buf)):
            assert not legacy.scan_is_legacy(buf[:cut])
    modern = [b"\x00" * 4, "z" * 40, ["x", b"\x01"], {"k": "w" * 64}]
    for v in modern:
        buf = msgpack.packb(v, use_bin_type=True)
        assert not legacy.scan_is_legacy(buf), v
    # hostile: huge claimed array length must not loop forever
    assert not legacy.scan_is_legacy(b"\xdd\x7f\xff\xff\xff" + b"\x01" * 8)
