"""Logging setup tests (≙ the reference's log_config + SIGHUP contract)."""

from __future__ import annotations

import json
import logging
import os
import signal

from jubatus_tpu.utils import logger as jlog


def _cleanup():
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)


def test_logdir_writes_file(tmp_path):
    try:
        jlog.setup("jubatest", "1.2.3.4", 9, logdir=str(tmp_path))
        logging.getLogger("x").info("hello-logdir")
        for h in logging.getLogger().handlers:
            h.flush()
        content = (tmp_path / "jubatest.log").read_text()
        assert "hello-logdir" in content
        assert "[jubatest:1.2.3.4:9]" in content
    finally:
        _cleanup()


def test_install_sighup_reload_noop_without_config():
    """install_sighup_reload("") must not claim the SIGHUP handler."""
    prev = signal.getsignal(signal.SIGHUP)
    try:
        jlog.install_sighup_reload("")
        assert signal.getsignal(signal.SIGHUP) is prev
    finally:
        signal.signal(signal.SIGHUP, prev)


def test_sighup_reload_keeps_old_config_on_error(tmp_path):
    """A broken config file at reload time must keep the previous logging
    config (the reference's contract: a bad rotate never mutes a server)."""
    conf = tmp_path / "log.json"
    conf.write_text(json.dumps({
        "version": 1, "root": {"level": "WARNING", "handlers": []}}))
    try:
        jlog.setup("jubatest", log_config=str(conf))
        jlog.install_sighup_reload(str(conf))
        assert logging.getLogger().level == logging.WARNING
        conf.write_text("{not json")
        os.kill(os.getpid(), signal.SIGHUP)  # must not raise
        assert logging.getLogger().level == logging.WARNING
        # and a later GOOD config applies again
        conf.write_text(json.dumps({
            "version": 1, "root": {"level": "ERROR", "handlers": []}}))
        os.kill(os.getpid(), signal.SIGHUP)
        assert logging.getLogger().level == logging.ERROR
    finally:
        signal.signal(signal.SIGHUP, signal.SIG_DFL)
        logging.getLogger().setLevel(logging.WARNING)
        _cleanup()


def test_sighup_reload_missing_file_keeps_old_config(tmp_path):
    conf = tmp_path / "log.json"
    conf.write_text(json.dumps({
        "version": 1, "root": {"level": "INFO", "handlers": []}}))
    try:
        jlog.setup("jubatest", log_config=str(conf))
        jlog.install_sighup_reload(str(conf))
        conf.unlink()
        os.kill(os.getpid(), signal.SIGHUP)  # must not raise
        assert logging.getLogger().level == logging.INFO
    finally:
        signal.signal(signal.SIGHUP, signal.SIG_DFL)
        logging.getLogger().setLevel(logging.WARNING)
        _cleanup()


def test_log_config_and_sighup_reload(tmp_path):
    conf = tmp_path / "log.json"

    def write(level):
        conf.write_text(json.dumps({
            "version": 1,
            "root": {"level": level, "handlers": []},
        }))

    try:
        write("WARNING")
        jlog.setup("jubatest", log_config=str(conf))
        assert logging.getLogger().level == logging.WARNING
        jlog.install_sighup_reload(str(conf))
        write("DEBUG")
        os.kill(os.getpid(), signal.SIGHUP)
        assert logging.getLogger().level == logging.DEBUG
    finally:
        signal.signal(signal.SIGHUP, signal.SIG_DFL)
        logging.getLogger().setLevel(logging.WARNING)
        _cleanup()
