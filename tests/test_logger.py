"""Logging setup tests (≙ the reference's log_config + SIGHUP contract)."""

from __future__ import annotations

import json
import logging
import os
import signal

from jubatus_tpu.utils import logger as jlog


def _cleanup():
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)


def test_logdir_writes_file(tmp_path):
    try:
        jlog.setup("jubatest", "1.2.3.4", 9, logdir=str(tmp_path))
        logging.getLogger("x").info("hello-logdir")
        for h in logging.getLogger().handlers:
            h.flush()
        content = (tmp_path / "jubatest.log").read_text()
        assert "hello-logdir" in content
        assert "[jubatest:1.2.3.4:9]" in content
    finally:
        _cleanup()


def test_log_config_and_sighup_reload(tmp_path):
    conf = tmp_path / "log.json"

    def write(level):
        conf.write_text(json.dumps({
            "version": 1,
            "root": {"level": level, "handlers": []},
        }))

    try:
        write("WARNING")
        jlog.setup("jubatest", log_config=str(conf))
        assert logging.getLogger().level == logging.WARNING
        jlog.install_sighup_reload(str(conf))
        write("DEBUG")
        os.kill(os.getpid(), signal.SIGHUP)
        assert logging.getLogger().level == logging.DEBUG
    finally:
        signal.signal(signal.SIGHUP, signal.SIG_DFL)
        logging.getLogger().setLevel(logging.WARNING)
        _cleanup()
