"""Mesh-sharded serving through NNBackend (models/_nn_backend.py
attach_mesh) — results must match the single-device dense path exactly,
including dead-slot masking and capacity padding, on the 8-device CPU
mesh."""

from __future__ import annotations

import numpy as np
import pytest

from jubatus_tpu.models._nn_backend import HASH_METHODS, NNBackend
from jubatus_tpu.parallel.mesh import grid_mesh

DIM = 1 << 12


@pytest.fixture(scope="module")
def mesh():
    return grid_mesh(replica=1, shard=8)


def _vec(rng, nnz=6):
    idx = rng.integers(1, DIM, size=nnz)
    val = rng.normal(size=nnz)
    return [(int(i), float(v)) for i, v in zip(idx, val)]


@pytest.mark.parametrize("method", HASH_METHODS)
def test_mesh_matches_dense(method, mesh, rng):
    dense = NNBackend(method, dim=DIM, hash_num=64)
    sharded = NNBackend(method, dim=DIM, hash_num=64)
    vecs = {f"r{i}": _vec(rng) for i in range(37)}  # odd count: padding path
    for rid, v in vecs.items():
        dense.set_row(rid, v)
        sharded.set_row(rid, v)
    sharded.attach_mesh(mesh)

    q = _vec(rng)
    want = dense.neighbors(q, 5)
    got = sharded.neighbors(q, 5)
    # tie order may differ between top-k implementations (hash distances
    # quantize); the distance sequence must match exactly and every
    # returned id must carry its true dense distance
    np.testing.assert_allclose([d for _, d in got], [d for _, d in want],
                               rtol=1e-5, atol=1e-6)
    true_d = dense.distances(q)
    slot = dense.store.slots
    for rid, d in got:
        np.testing.assert_allclose(d, true_d[slot[rid]], rtol=1e-5, atol=1e-6)


def test_mesh_masks_removed_rows(mesh, rng):
    b = NNBackend("lsh", dim=DIM, hash_num=64)
    vecs = {f"r{i}": _vec(rng) for i in range(16)}
    for rid, v in vecs.items():
        b.set_row(rid, v)
    b.attach_mesh(mesh)
    q = _vec(rng)
    first = b.neighbors(q, 3)[0][0]
    b.remove_row(first)
    after = [r for r, _ in b.neighbors(q, 16)]
    assert first not in after
    assert len(after) == 15


def test_mesh_neighbors_batch_and_similar(mesh, rng):
    b = NNBackend("minhash", dim=DIM, hash_num=32)
    for i in range(24):
        b.set_row(f"r{i}", _vec(rng))
    b.attach_mesh(mesh)
    qs = [_vec(rng) for _ in range(5)]
    batch = b.neighbors_batch(qs, 4)
    assert len(batch) == 5
    for q, row in zip(qs, batch):
        assert row == b.neighbors(q, 4)
    # similar() rides the mesh path too (same sign convention)
    sim = b.similar(qs[0], 4)
    assert [r for r, _ in sim] == [r for r, _ in batch[0]]


def test_mesh_rejects_exact_methods(mesh):
    b = NNBackend("inverted_index", dim=DIM)
    with pytest.raises(ValueError, match="hash methods"):
        b.attach_mesh(mesh)


def test_mesh_empty_store(mesh, rng):
    b = NNBackend("lsh", dim=DIM, hash_num=32)
    b.attach_mesh(mesh)
    assert b.neighbors(_vec(rng), 3) == []


def test_driver_level_mesh(mesh, rng):
    """nearest_neighbor driver serving from a sharded table end-to-end."""
    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.models.nearest_neighbor import NearestNeighborDriver

    cfg = {
        "method": "lsh",
        "parameter": {"hash_num": 64},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]},
    }
    d_dense = NearestNeighborDriver(cfg, dim_bits=12)
    d_mesh = NearestNeighborDriver(cfg, dim_bits=12)
    datums = {f"row{i}": Datum({"x": float(i), "y": float(i % 7)})
              for i in range(20)}
    for rid, dm in datums.items():
        d_dense.set_row(rid, dm)
        d_mesh.set_row(rid, dm)
    d_mesh.backend.attach_mesh(mesh)

    q = Datum({"x": 3.2, "y": 3.0})
    got = d_mesh.neighbor_row_from_datum(q, 5)
    want = d_dense.neighbor_row_from_datum(q, 5)
    np.testing.assert_allclose([d for _, d in got], [d for _, d in want],
                               rtol=1e-5, atol=1e-6)
    want_by_id = dict(d_dense.neighbor_row_from_datum(q, 20))
    for rid, d in got:
        np.testing.assert_allclose(d, want_by_id[rid], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("method", HASH_METHODS)
def test_mesh_full_distances_match_dense(method, mesh, rng):
    """sharded_distances (LOF's full-vector path) must reproduce the
    dense distances bit-for-bit per ROW, including the dead-slot +inf
    mask and the batched distances_from_slots cache fill. Slot numbers
    differ by design since ISSUE 13: attach_mesh re-places rows into
    their CHT-owned shard arenas (parallel/row_store.py), so alignment
    goes through each backend's own id→slot map."""
    dense = NNBackend(method, dim=DIM, hash_num=32)
    shard = NNBackend(method, dim=DIM, hash_num=32)
    for i in range(21):  # odd count exercises capacity padding
        v = _vec(rng)
        dense.set_row(f"r{i}", v)
        shard.set_row(f"r{i}", v)
    shard.attach_mesh(mesh)
    dense.remove_row("r7")
    shard.remove_row("r7")

    # euclid_lsh's batch kernel uses the expanded ||q||²-2qr+||r||² form
    # (one MXU matmul) whose cancellation error reaches ~1e-3 near zero;
    # the dense single-query path subtracts directly
    atol = 2e-3 if method == "euclid_lsh" else 1e-6
    q = _vec(rng)
    d_shard = shard.distances(q)
    d_dense = dense.distances(q)
    for rid, ds in dense.store.slots.items():
        np.testing.assert_allclose(d_shard[shard.store.slots[rid]],
                                   d_dense[ds], rtol=1e-4, atol=atol)
    # dead slots (including the removed row's) stay +inf on both
    assert np.all(np.isinf(d_shard[~shard.store.live_mask()]))
    assert np.all(np.isinf(d_dense[~dense.store.live_mask()]))
    rids = sorted(dense.store.slots)[:6]
    out_shard = shard.distances_from_slots(
        np.asarray([shard.store.slots[r] for r in rids]))
    out_dense = dense.distances_from_slots(
        np.asarray([dense.store.slots[r] for r in rids]))
    for rid2 in dense.store.slots:
        np.testing.assert_allclose(
            out_shard[:, shard.store.slots[rid2]],
            out_dense[:, dense.store.slots[rid2]], rtol=1e-4, atol=atol)


def test_anomaly_driver_sharded_lof(mesh, rng):
    """LOF scoring on a row-sharded backend matches the dense driver."""
    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.server.factory import create_driver

    # euclid_lsh, as the reference's lof.json defaults: sign-LSH is
    # magnitude-blind and cannot separate a directional outlier
    cfg = {"method": "lof",
           "parameter": {"nearest_neighbor_num": 5,
                         "reverse_nearest_neighbor_num": 10,
                         "method": "euclid_lsh",
                         "parameter": {"hash_num": 64}},
           "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    dense = create_driver("anomaly", cfg)
    shard = create_driver("anomaly", cfg, mesh=mesh)
    for i in range(30):
        d = Datum({"x": float(rng.normal(0, 0.1)),
                   "y": float(rng.normal(0, 0.1))})
        dense.add(d)
        shard.add(d)
    q_in = Datum({"x": 0.02, "y": -0.03})
    q_out = Datum({"x": 6.0, "y": -6.0})
    np.testing.assert_allclose(shard.calc_score(q_in),
                               dense.calc_score(q_in), rtol=1e-4)
    np.testing.assert_allclose(shard.calc_score(q_out),
                               dense.calc_score(q_out), rtol=1e-4)
    assert shard.calc_score(q_out) > shard.calc_score(q_in)
