"""Two-tier topology model + host-major device ordering (parallel/mesh.py).

The hierarchical mix (ISSUE 9) needs a topology the whole fleet agrees
on: ``host_topology()`` groups devices host-major into N hosts x M local
devices, ``host_mesh()`` is its 2-D (host, local) mesh, and the
pre-existing 1-D/2-D mesh builders must order devices host-major too —
``jax.devices()`` order is backend-defined and can interleave hosts, and
a mesh axis built over the interleaved order would put a "local" slice
across the network.
"""

from __future__ import annotations

import jax
import pytest

from jubatus_tpu.parallel.mesh import (
    HostTopology,
    grid_mesh,
    host_major,
    host_mesh,
    host_topology,
    replica_mesh,
)


class _FakeDevice:
    """Hashable stand-in (jax.sharding.Mesh keys on the device tuple)."""

    def __init__(self, proc: int, dev_id: int):
        self.process_index = proc
        self.id = dev_id

    def __repr__(self):
        return f"fake(p{self.process_index}/d{self.id})"


def _fake(proc: int, dev_id: int):
    return _FakeDevice(proc, dev_id)


def _interleaved(hosts: int, per_host: int):
    """The pathological jax.devices() order: round-robin across hosts
    (device 0 of every host first) — a flat 'first M' slice spans every
    host instead of one."""
    return [_fake(p, p * per_host + i)
            for i in range(per_host) for p in range(hosts)]


# -- host_major ordering (the satellite regression) ---------------------------

def test_host_major_groups_interleaved_hosts():
    devs = _interleaved(2, 4)
    ordered = host_major(devs)
    assert [(d.process_index, d.id) for d in ordered] == \
        [(0, 0), (0, 1), (0, 2), (0, 3), (1, 4), (1, 5), (1, 6), (1, 7)]


def test_replica_mesh_is_host_major():
    """replica_mesh over scrambled real devices must come out id-sorted
    (all test devices share process 0): 'the first n devices' means the
    first hosts' devices, never an interleaved sample."""
    devs = list(reversed(jax.devices()))
    mesh = replica_mesh(devices=devs)
    ids = [d.id for d in mesh.devices.reshape(-1)]
    assert ids == sorted(ids)


def test_grid_mesh_is_host_major():
    devs = list(reversed(jax.devices()))
    mesh = grid_mesh(2, 4, devices=devs)
    ids = [d.id for d in mesh.devices.reshape(-1)]
    assert ids == sorted(ids)
    assert mesh.shape == {"replica": 2, "shard": 4}


def test_grid_mesh_shard_axis_stays_on_host():
    """With 2 fake hosts x 4 devices handed over INTERLEAVED, each
    replica row (whose trailing shard axis all-gathers constantly) must
    land on ONE host — the regression that motivated host-major order."""
    devs = _interleaved(2, 4)
    mesh = grid_mesh(2, 4, devices=devs)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1


# -- host_topology derivation -------------------------------------------------

def test_host_topology_derived_groups_by_process():
    topo = host_topology(devices=_interleaved(3, 2))
    assert (topo.hosts, topo.locals) == (3, 2)
    assert topo.signature == "3x2"
    assert topo.source == "derived"
    for h, row in enumerate(topo.grid):
        assert [d.process_index for d in row] == [h, h]


def test_host_topology_nonuniform_degrades_to_one_per_host():
    devs = [_fake(0, 0), _fake(0, 1), _fake(1, 2)]  # ragged: 2 + 1
    topo = host_topology(devices=devs)
    assert (topo.hosts, topo.locals) == (2, 1)
    assert topo.source == "nonuniform"


def test_host_topology_override_single_process_regrid():
    """Single-process worlds regrid their local devices — the virtual
    8-device CPU test world exercising real two-tier collectives."""
    topo = host_topology(override="2x4")
    assert (topo.hosts, topo.locals) == (2, 4)
    assert topo.source == "override"
    assert topo.signature == "2x4"
    assert not topo.trivial
    flat = [d for row in topo.grid for d in row]
    assert len(flat) == 8 and len(set(flat)) == 8
    # tuple form resolves identically
    assert host_topology(override=(2, 4)).signature == "2x4"


def test_host_topology_override_multi_process_groups_processes():
    """With >1 process the participants are one device per process and
    HxM must tile the process count (co-located processes per host)."""
    devs = [_fake(p, 10 + p) for p in range(4)]
    topo = host_topology(devices=devs, override="2x2")
    assert (topo.hosts, topo.locals) == (2, 2)
    assert [[d.process_index for d in row] for row in topo.grid] == \
        [[0, 1], [2, 3]]
    with pytest.raises(ValueError, match="processes"):
        host_topology(devices=devs, override="3x2")


def test_host_topology_rejects_bad_specs():
    # NOTE: "" is not an error — it is the flat sentinel (_norm_topology)
    for bad in ("3x", "x3", "junk", "0x2", "2x0"):
        with pytest.raises(ValueError):
            host_topology(override=bad)
    with pytest.raises(ValueError, match="devices"):
        host_topology(override="4x4")  # needs 16, world has 8


def test_trivial_topology():
    assert HostTopology(1, 1, ((None,),)).trivial
    assert not HostTopology(2, 1, ((None,), (None,))).trivial


# -- host_mesh ----------------------------------------------------------------

def test_host_mesh_axes_and_shape():
    mesh = host_mesh(override="2x4")
    assert mesh.axis_names == ("host", "local")
    assert mesh.shape == {"host": 2, "local": 4}
    # rows are the topology's rows, host-major
    ids = [d.id for d in mesh.devices.reshape(-1)]
    assert ids == sorted(ids)
