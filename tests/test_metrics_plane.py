"""Metrics-plane tests (ISSUE 2): histogram edge cases, /metrics +
/healthz exposition, RPC error counters, trace propagation through the
proxy, the mix flight recorder + get_mix_history, and jubactl's merged
cluster views."""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from jubatus_tpu.utils import tracing

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- histogram edge cases -----------------------------------------------------


def test_histogram_empty():
    h = tracing.Histogram()
    assert h.quantile(0.5) is None
    assert h.count == 0 and h.max_s == 0.0


def test_histogram_single_sample_quantiles_exact():
    h = tracing.Histogram()
    h.record(0.005)
    # every quantile of a single sample is the sample (max-clamped)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(0.005)


def test_histogram_overflow_bucket():
    h = tracing.Histogram()
    h.record(1e6)  # way past the 128 s top bucket
    assert h.quantile(0.5) == pytest.approx(1e6)
    st = h.state()
    assert max(int(k) for k in st["buckets"]) == tracing._OVERFLOW


def test_histogram_underflow_clamps_to_first_bucket():
    h = tracing.Histogram()
    h.record(0.0)
    h.record(1e-12)
    assert h.count == 2
    assert h.quantile(0.5) is not None


def test_histogram_quantile_accuracy_bounded():
    """Bucket width is 2^(1/4) ≈ 19%: quantiles must land within one
    bucket of the true value."""
    h = tracing.Histogram()
    for i in range(1, 1001):
        h.record(i / 1000.0)  # uniform on (0, 1] s
    p50 = h.quantile(0.5)
    assert 0.5 / 1.2 <= p50 <= 0.5 * 1.2, p50
    p99 = h.quantile(0.99)
    assert 0.99 / 1.2 <= p99 <= 1.0, p99


def test_histogram_concurrent_record():
    reg = tracing.Registry()
    n, threads = 2000, 8

    def pump():
        for i in range(n):
            reg.record("conc", 1e-4 * (1 + i % 7))

    ts = [threading.Thread(target=pump) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = reg.trace_status()
    assert st["trace.conc.count"] == n * threads
    snap = reg.snapshot()
    assert sum(snap["hists"]["conc"]["buckets"].values()) == n * threads


def test_snapshot_merge_and_state_quantile():
    a, b = tracing.Registry(), tracing.Registry()
    for _ in range(100):
        a.record("x", 0.001)
    for _ in range(100):
        b.record("x", 0.1)
    a.count("errs", 2)
    b.count("errs", 3)
    merged = tracing.merge_snapshots([a.snapshot(), b.snapshot()])
    st = merged["hists"]["x"]
    assert st["count"] == 200
    assert merged["counters"]["errs"] == 5
    p25 = tracing.state_quantile(st, 0.25)
    p75 = tracing.state_quantile(st, 0.75)
    assert p25 == pytest.approx(0.001, rel=0.25)
    assert p75 == pytest.approx(0.1, rel=0.25)
    # merged max is the max of the parts
    assert st["max_s"] == pytest.approx(0.1, rel=0.01)


def test_trace_context_adopt_and_fresh():
    root = tracing.from_wire(None)
    assert root.trace_id and root.span_id and root.parent_id == ""
    child = tracing.from_wire(tracing.to_wire(root))
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    # bytes keys/values (legacy-decoded wire) are tolerated
    b = tracing.from_wire({"t": b"abc", "s": b"def"})
    assert b.trace_id == "abc" and b.parent_id == "def"


def test_use_trace_restores_previous():
    ctx = tracing.from_wire(None)
    assert tracing.current_trace() is None
    with tracing.use_trace(ctx):
        assert tracing.current_trace() is ctx
        with tracing.use_trace(None):
            assert tracing.current_trace() is None
        assert tracing.current_trace() is ctx
    assert tracing.current_trace() is None


# -- prometheus exposition ----------------------------------------------------


def _parse_prometheus(text: str):
    """Minimal format-0.0.4 validation: every non-comment line is
    ``name{labels} value``; histogram buckets are cumulative; returns the
    parsed samples."""
    import re

    samples = []
    pat = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([0-9eE.+-]+|NaN|\+Inf)$')
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = pat.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        samples.append((m.group(1), m.group(2) or "", float(m.group(3))))
    return samples


def test_prometheus_text_parses_and_buckets_cumulative():
    reg = tracing.Registry()
    for i in range(50):
        reg.record("rpc.unit", 1e-4 * (1 + i))
    reg.count("rpc.unit.errors", 3)
    text = reg.prometheus_text({"node": "127.0.0.1_1"})
    samples = _parse_prometheus(text)
    buckets = [v for n, lab, v in samples
               if n == "jubatus_span_duration_seconds_bucket"]
    assert buckets == sorted(buckets), "bucket counts must be cumulative"
    assert buckets[-1] == 50
    counts = {n: v for n, _l, v in samples}
    assert counts["jubatus_span_duration_seconds_count"] == 50
    assert counts["jubatus_events_total"] == 3
    assert 'node="127.0.0.1_1"' in text


def test_metrics_endpoint_smoke():
    """Tier-1 smoke (ISSUE 2 satellite): boot a server with
    --metrics_port 0 (ephemeral), scrape /metrics, and validate the
    Prometheus text format parses; /healthz answers JSON."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        metrics_port=0))
    port = srv.start(0)
    try:
        mport = srv.args.metrics_port
        assert mport > 0
        c = ClassifierClient("127.0.0.1", port, "")
        c.train([["a", Datum({"x": 1.0})]])
        c.close()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        samples = _parse_prometheus(body)
        spans = {lab for n, lab, _v in samples
                 if n == "jubatus_span_duration_seconds_count"}
        assert any('span="rpc.train"' in lab for lab in spans), spans
        assert any('engine="classifier"' in lab for lab in spans)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/healthz", timeout=10) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["status"] == "ok" and doc["engine"] == "classifier"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/nope", timeout=10)
    finally:
        srv.stop()


# -- rpc error counters -------------------------------------------------------


def test_rpc_error_counter_per_method():
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc.server import RpcServer

    srv = RpcServer()
    srv.register("boom", lambda: 1 / 0, arity=0)
    srv.register("ok", lambda: 1, arity=0)
    port = srv.serve_background(0, host="127.0.0.1")
    try:
        with RpcClient("127.0.0.1", port) as c:
            assert c.call("ok") == 1
            for _ in range(2):
                with pytest.raises(Exception):
                    c.call("boom")
        st = srv.trace.trace_status()
        assert st["trace.counter.rpc.boom.errors"] == 2
        assert "trace.counter.rpc.ok.errors" not in st
        # failures are still timed (identically to successes) AND counted
        assert st["trace.rpc.boom.count"] == 2
    finally:
        srv.stop()


# -- trace propagation --------------------------------------------------------


@pytest.fixture()
def one_node_cluster():
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    store = _Store()
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator="(shared)",
                        name="tr1", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30),
        coord=MemoryCoordinator(store))
    srv.start(0)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    yield srv, proxy
    proxy.stop()
    srv.stop()


def test_proxied_call_shares_one_trace_id(one_node_cluster):
    """ISSUE 2 acceptance: a proxied call yields ONE trace_id across the
    proxy's and the backend's status maps."""
    from jubatus_tpu.client import ClassifierClient, Datum

    srv, proxy = one_node_cluster
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, "tr1")
    c.train([["a", Datum({"x": 1.0})], ["b", Datum({"x": -1.0})]])
    c.classify([Datum({"x": 1.0})])
    c.close()
    pst = proxy.rpc.trace.trace_status()
    bst = srv.rpc.trace.trace_status()
    key = "trace.rpc.classify.last_trace_id"
    assert key in pst and key in bst
    assert pst[key] == bst[key]
    # and the same holds for the bulk (raw fast path) train relay
    tkey = "trace.rpc.train.last_trace_id"
    assert pst[tkey] == bst[tkey]


def test_proxy_fanout_broadcast_shares_trace(one_node_cluster):
    from jubatus_tpu.client import ClassifierClient

    srv, proxy = one_node_cluster
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, "tr1")
    st = c.get_status()
    assert st  # backend answered through the proxy
    c.close()
    key = "trace.rpc.get_status.last_trace_id"
    assert proxy.rpc.trace.trace_status()[key] == \
        srv.rpc.trace.trace_status()[key]


# -- flight recorder ----------------------------------------------------------


def test_flight_recorder_ring_and_fields():
    from jubatus_tpu.framework.mixer import MixFlightRecorder

    fr = MixFlightRecorder(capacity=4)
    fr.node = "me_1"
    for i in range(6):
        fr.record("collective", ok=(i % 2 == 0), round_id=f"r{i}",
                  phases={"ship_ms": 1.0, "reduce_ms": 2.0,
                          "readback_ms": 3.0, "chunks": 4},
                  members=3)
    snap = fr.snapshot()
    assert len(snap) == 4, "ring must stay bounded"
    assert [r["round_id"] for r in snap] == ["r2", "r3", "r4", "r5"]
    last = snap[-1]
    assert last["node"] == "me_1" and last["members"] == 3
    for key in ("ship_ms", "reduce_ms", "readback_ms", "chunks"):
        assert key in last["phases"]
    stats = fr.stats()
    assert stats["recorded"] == 6 and stats["retained"] == 4
    assert fr.snapshot(last=2) == snap[-2:]


def test_get_mix_history_rpc_after_round():
    """A 2-node linear-mixer cluster: one do_mix produces >= 1 structured
    flight record, queryable over the get_mix_history RPC."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    servers = []
    try:
        for _ in range(2):
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(engine="classifier", coordinator="(shared)",
                                name="fh", listen_addr="127.0.0.1",
                                interval_sec=1e9, interval_count=1 << 30),
                coord=MemoryCoordinator(store))
            srv.start(0)
            servers.append(srv)
        for s in servers:
            c = ClassifierClient("127.0.0.1", s.args.rpc_port, "fh")
            c.train([["a", Datum({"x": 1.0})]])
            c.close()
        assert servers[0].mixer.mix_now() is not None
        with RpcClient("127.0.0.1", servers[0].args.rpc_port) as c:
            hist = c.call("get_mix_history", "fh")
        assert len(hist) >= 1
        rec = hist[-1]
        assert rec["mode"] == "rpc" and rec["ok"] is True
        assert rec["members"] == 2 and rec["bytes"] > 0
        for key in ("schema_ms", "get_diff_ms", "fold_ms", "put_diff_ms"):
            assert key in rec["phases"], rec
        # jubadump --mix-history against the live server
        from jubatus_tpu.cmd import jubadump

        rc = jubadump.main([
            "--mix-history", f"127.0.0.1:{servers[0].args.rpc_port}",
            "-n", "fh"])
        assert rc == 0
    finally:
        for s in servers:
            s.stop()


# -- jubactl cluster views ----------------------------------------------------


@pytest.fixture()
def file_cluster(tmp_path):
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    for _ in range(3):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator=coord_dir,
                            name="jm", listen_addr="127.0.0.1",
                            interval_sec=1e9, interval_count=1 << 30))
        srv.start(0)
        servers.append(srv)
    for s in servers:
        c = ClassifierClient("127.0.0.1", s.args.rpc_port, "jm")
        c.train([["a", Datum({"x": 1.0})], ["b", Datum({"x": -1.0})]])
        c.close()
    assert servers[0].mixer.mix_now() is not None
    yield coord_dir, servers
    for s in servers:
        s.stop()


def test_jubactl_metrics_merged_view(file_cluster, capsys):
    """ISSUE 2 acceptance: jubactl metrics against a 3-process in-memory
    cluster prints merged p50/p99 for rpc.* and mix.round."""
    from jubatus_tpu.cmd import jubactl

    coord_dir, _servers = file_cluster
    rc = jubactl.main(["-c", "metrics", "-t", "classifier", "-n", "jm",
                       "-z", coord_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "merged metrics from 3 node(s)" in out
    assert "p50_ms" in out and "p99_ms" in out
    assert "rpc.train" in out
    assert "mix.round" in out


def test_jubactl_status_all(file_cluster, capsys):
    from jubatus_tpu.cmd import jubactl

    coord_dir, _servers = file_cluster
    rc = jubactl.main(["-c", "status", "--all", "-t", "classifier",
                       "-n", "jm", "-z", coord_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "3 node(s), 3 active" in out
    assert "trace.rpc.train.p99_ms" in out
    assert "mixer.mix_count" in out
