"""Microbatch coalescer tests (server/microbatch.py) — unit-level queue
semantics plus a live-server test showing concurrent train RPCs really
merge into fewer device flushes with no lost or double-counted items."""

from __future__ import annotations

import threading
import time

import pytest

from jubatus_tpu.server.microbatch import Coalescer


def test_lone_submit_is_passthrough():
    seen = []
    co = Coalescer(lambda b: (seen.append(list(b)), len(b))[1])
    assert co.submit([1, 2, 3]) == 3
    assert seen == [[1, 2, 3]]
    assert co.stats()["flush_count"] == 1


def test_empty_submit():
    co = Coalescer(lambda b: len(b))
    assert co.submit([]) == 0
    assert co.stats()["flush_count"] == 0


def test_concurrent_submits_coalesce_and_conserve():
    flushed = []
    gate = threading.Event()

    def flush(batch):
        if not gate.is_set():   # first flush blocks so the rest pile up
            gate.set()
            time.sleep(0.15)
        flushed.append(list(batch))
        return len(batch)

    co = Coalescer(flush)
    results = []

    def worker(base):
        results.append(co.submit([base * 10 + j for j in range(3)]))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
        time.sleep(0.005)
    for t in threads:
        t.join()

    all_items = [x for b in flushed for x in b]
    assert sorted(all_items) == sorted(i * 10 + j
                                       for i in range(12) for j in range(3))
    assert len(all_items) == 36
    # piling up must have produced real coalescing
    assert len(flushed) < 12
    assert co.stats()["item_count"] == 36
    assert max(len(b) for b in flushed) > 3


def test_max_batch_splits():
    sizes = []
    gate = threading.Event()

    def slow_first(batch):
        if not gate.is_set():
            gate.set()
            time.sleep(0.1)
        sizes.append(len(batch))

    co = Coalescer(slow_first, max_batch=5)
    threads = [threading.Thread(target=co.submit, args=([j, j, j],))
               for j in range(6)]
    for t in threads:
        t.start()
        time.sleep(0.005)
    for t in threads:
        t.join()
    assert sum(sizes) == 18
    assert all(s <= 5 or s == 3 for s in sizes)  # ≤ max, except lone-first


def test_oversized_single_submit_flushes_alone():
    sizes = []
    co = Coalescer(lambda b: sizes.append(len(b)), max_batch=4)
    co.submit(list(range(10)))
    assert sizes == [10]


def test_error_propagates_to_contributors_only():
    def flush(batch):
        if "bad" in batch:
            raise RuntimeError("poison")
        return len(batch)

    co = Coalescer(flush)
    with pytest.raises(RuntimeError, match="poison"):
        co.submit(["bad"])
    assert co.submit(["ok"]) == 1  # queue recovers after a failed flush


def test_timeout_withdraws_queued_items():
    """A timed-out submit whose items are still queued withdraws them —
    TimeoutError then guarantees the model was NOT updated."""
    gate = threading.Event()
    release = threading.Event()

    def flush(batch):
        gate.set()
        release.wait(5)
        return len(batch)

    co = Coalescer(flush)
    t = threading.Thread(target=co.submit, args=([1],))
    t.start()
    assert gate.wait(2)
    with pytest.raises(TimeoutError, match="NOT updated"):
        co.submit([2], timeout=0.1)
    release.set()
    t.join()
    assert co.stats()["item_count"] == 1  # withdrawn item never flushed


def test_zero_timeout_means_wait_forever():
    co = Coalescer(lambda b: len(b))
    assert co.submit([1, 2], timeout=0) == 2


@pytest.mark.slow
def test_server_train_rpcs_coalesce():
    """N concurrent clients training against one server: every example
    lands exactly once and the device saw fewer flushes than RPCs."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer

    conf = {
        "method": "PA",
        "parameter": {},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]},
    }
    srv = EngineServer("classifier", conf)
    port = srv.start(0)
    try:
        n_clients, per_client = 8, 5

        def client_work(ci):
            with ClassifierClient("127.0.0.1", port, "mb") as c:
                for j in range(per_client):
                    lbl = "pos" if (ci + j) % 2 == 0 else "neg"
                    got = c.train([(lbl, Datum({"x": float(ci - j)})),
                                   (lbl, Datum({"x": float(j - ci)}))])
                    assert got == 2

        threads = [threading.Thread(target=client_work, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        total = n_clients * per_client * 2
        assert srv.driver.update_count == total
        st = next(iter(srv.get_status().values()))
        # train traffic flows through the native-ingest fast coalescer
        # when eligible (train_raw), the converter path otherwise — the
        # combined counters must account for every example either way
        items = (st["microbatch.train.item_count"]
                 + st.get("microbatch.train_raw.item_count", 0))
        flushes = (st["microbatch.train.flush_count"]
                   + st.get("microbatch.train_raw.flush_count", 0))
        assert items == total
        assert flushes <= n_clients * per_client
        # model still serves
        with ClassifierClient("127.0.0.1", port, "mb") as c:
            assert len(c.classify([Datum({"x": 1.0}).to_msgpack()])) == 1
    finally:
        srv.stop()


def test_split_results_each_ticket_gets_its_slice():
    """Query-plane mode: the flush returns per-item results and every
    submitter receives exactly its own rows, under real concurrency."""
    import threading

    from jubatus_tpu.server.microbatch import Coalescer

    def flush(items):
        return [f"r{x}" for x in items]

    co = Coalescer(flush, max_batch=64, split_results=True)
    out = {}
    barrier = threading.Barrier(8)

    def worker(k):
        barrier.wait()
        out[k] = co.submit([k * 10 + j for j in range(3)])

    ts = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for k in range(8):
        assert out[k] == [f"r{k * 10 + j}" for j in range(3)], out[k]


def test_split_results_wrong_length_surfaces_error():
    from jubatus_tpu.server.microbatch import Coalescer

    co = Coalescer(lambda items: ["only-one"], max_batch=8,
                   split_results=True)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="split flush returned"):
        co.submit(["a", "b"])


# -- PipelinedCoalescer (ISSUE 5: host/device overlap) -----------------------

def test_pipelined_basic_result_delivery():
    from jubatus_tpu.server.microbatch import PipelinedCoalescer

    preps, flushes = [], []

    def prep(items):
        preps.append(list(items))
        return [x * 2 for x in items]

    def flush(prepared):
        flushes.append(list(prepared))
        return sum(prepared)

    co = PipelinedCoalescer(prep, flush, max_batch=64)
    assert co.submit([1, 2, 3]) == 12
    assert preps == [[1, 2, 3]] and flushes == [[2, 4, 6]]
    st = co.stats()
    assert st["flush_count"] == 1 and st["item_count"] == 3
    assert "overlap_fraction" in st


def test_pipelined_overlaps_prep_with_device():
    """While the device worker sleeps on batch N, the flusher must prep
    batch N+1 — overlap_seconds > 0 proves the stages really ran
    concurrently."""
    from jubatus_tpu.server.microbatch import PipelinedCoalescer

    order = []

    def prep(items):
        order.append(("prep", tuple(items)))
        time.sleep(0.05)
        return items

    def flush(prepared):
        order.append(("flush", tuple(prepared)))
        time.sleep(0.1)
        return len(prepared)

    co = PipelinedCoalescer(prep, flush, max_batch=4)
    results = []
    threads = [threading.Thread(
        target=lambda i=i: results.append(co.submit([i])))
        for i in range(6)]
    for t in threads:
        t.start()
        time.sleep(0.01)
    for t in threads:
        t.join()
    assert len(results) == 6
    st = co.stats()
    assert st["item_count"] == 6
    assert st["device_seconds"] > 0
    assert st["overlap_seconds"] > 0  # prep ran under an active flush
    assert 0 < st["overlap_fraction"] <= 1.0


def test_pipelined_prep_error_fails_only_that_batch():
    from jubatus_tpu.server.microbatch import PipelinedCoalescer

    def prep(items):
        if any(x < 0 for x in items):
            raise ValueError("bad featurize")
        return items

    co = PipelinedCoalescer(prep, lambda p: len(p), max_batch=64)
    with pytest.raises(ValueError, match="bad featurize"):
        co.submit([-1])
    assert co.submit([1, 2]) == 2  # queue recovered
    assert co.stats()["flush_count"] == 2


def test_pipelined_device_error_propagates():
    from jubatus_tpu.server.microbatch import PipelinedCoalescer

    def flush(prepared):
        raise RuntimeError("device on fire")

    co = PipelinedCoalescer(lambda i: i, flush, max_batch=64)
    with pytest.raises(RuntimeError, match="device on fire"):
        co.submit([1])
    # a later submit still works end to end after the error
    co2_calls = []
    co._flush = lambda p: (co2_calls.append(p), len(p))[1]
    assert co.submit([5, 6]) == 2


def test_pipelined_stamps_fv_spans():
    from jubatus_tpu.server.microbatch import PipelinedCoalescer
    from jubatus_tpu.utils.tracing import Registry

    reg = Registry()
    co = PipelinedCoalescer(lambda i: i, lambda p: len(p),
                            max_batch=64, trace=reg)
    assert co.submit([1, 2]) == 2
    status = reg.trace_status()
    assert any(k.startswith("trace.fv.convert.") for k in status)
    assert any(k.startswith("trace.fv.upload.") for k in status)


def test_pipelined_weigher_bounds_examples():
    """max_batch counts examples via the weigher, exactly like the
    single-stage coalescer."""
    import numpy as np

    from jubatus_tpu.server.microbatch import PipelinedCoalescer

    sizes = []

    def prep(items):
        return items

    def flush(prepared):
        sizes.append(sum(a.shape[0] for a in prepared))
        return sizes[-1]

    gate = threading.Event()

    def slow_first_flush(prepared):
        if not gate.is_set():
            gate.set()
            time.sleep(0.1)
        return flush(prepared)

    co = PipelinedCoalescer(prep, slow_first_flush, max_batch=8,
                            weigher=lambda a: a.shape[0])
    threads = [threading.Thread(
        target=lambda: co.submit([np.zeros((4, 2))]))
        for _ in range(6)]
    for t in threads:
        t.start()
        time.sleep(0.005)
    for t in threads:
        t.join()
    assert sum(sizes) == 24
    assert all(s <= 8 for s in sizes)


# -- backpressure gauges (ISSUE 12): the autoscaler's primary signal ---------

def test_queue_depth_and_arrival_rate_in_stats():
    release = threading.Event()
    started = threading.Event()

    def blocking_flush(batch):
        started.set()
        release.wait(5.0)
        return len(batch)

    co = Coalescer(blocking_flush, max_batch=4)
    t1 = threading.Thread(target=lambda: co.submit([1, 2]))
    t1.start()
    assert started.wait(5.0)
    # the flusher claimed its own items: queue is empty while it runs
    assert co.queue_depth() == 0
    t2 = threading.Thread(target=lambda: co.submit([3, 4, 5]))
    t2.start()
    deadline = time.monotonic() + 5.0
    while co.queue_depth() != 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    st = co.stats()
    assert st["queue_depth"] == 3          # queued behind the flush
    assert st["arrival_per_sec"] > 0.0     # 5 examples just arrived
    release.set()
    t1.join()
    t2.join()
    st = co.stats()
    assert st["queue_depth"] == 0          # drained back to idle
    assert co.queue_depth() == 0


def test_queue_depth_uses_weigher_examples():
    release = threading.Event()
    started = threading.Event()

    def blocking_flush(batch):
        started.set()
        release.wait(5.0)
        return len(batch)

    co = Coalescer(blocking_flush, max_batch=100,
                   weigher=lambda item: item["n"])
    t1 = threading.Thread(target=lambda: co.submit([{"n": 10}]))
    t1.start()
    assert started.wait(5.0)
    t2 = threading.Thread(target=lambda: co.submit([{"n": 7}, {"n": 5}]))
    t2.start()
    deadline = time.monotonic() + 5.0
    while co.queue_depth() != 12 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert co.queue_depth() == 12          # examples, not items
    release.set()
    t1.join()
    t2.join()


def test_timeout_withdrawal_returns_queue_depth():
    release = threading.Event()
    started = threading.Event()

    def blocking_flush(batch):
        started.set()
        release.wait(5.0)
        return len(batch)

    co = Coalescer(blocking_flush, max_batch=2)
    t1 = threading.Thread(target=lambda: co.submit([1, 2]))
    t1.start()
    assert started.wait(5.0)
    with pytest.raises(TimeoutError):
        co.submit([3, 4], timeout=0.05)
    assert co.queue_depth() == 0           # withdrawn items left no ghost
    release.set()
    t1.join()


def test_server_gauges_microbatch_signals(tmp_path):
    """The telemetry tick gauges microbatch.queue_depth /
    microbatch.arrival_per_sec into the server registry (-> /metrics,
    timeseries ring — what the autoscaler polls)."""
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.client import Datum
    from jubatus_tpu.rpc.client import RpcClient

    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    srv = EngineServer(
        "classifier", conf,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        telemetry_interval=0.0, datadir=str(tmp_path)))
    try:
        port = srv.start(0)
        with RpcClient("127.0.0.1", port, timeout=30.0) as c:
            c.call("train", "",
                   [["a", Datum({"f0": 1.0}).to_msgpack()]])
        srv._model_health_tick()
        g = srv.rpc.trace.gauges()
        assert g.get("microbatch.queue_depth") == 0.0
        assert "microbatch.arrival_per_sec" in g
        st = next(iter(srv.get_status().values()))
        mb = [k for k in st if k.startswith("microbatch.")
              and k.endswith(".queue_depth")]
        assert mb, "per-coalescer queue_depth missing from get_status"
    finally:
        srv.stop()
