"""Mix-plane compression tests: zlib payload compression for the DCN RPC
loop and bf16 quantized allreduce for the ICI collective (the EQuARX-style
wire-byte tradeoffs)."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from jubatus_tpu.framework.linear_mixer import (
    COMPRESS_THRESHOLD,
    pack_mix,
    unpack_mix,
)
from jubatus_tpu.parallel.mesh import replica_mesh
from jubatus_tpu.parallel.mix import allreduce_diffs
from jubatus_tpu.utils.serialization import pack_obj


def test_small_payload_uncompressed_roundtrip():
    obj = {"protocol": 1, "diffs": {"a": 1}}
    packed = pack_mix(obj)
    assert packed[:1] == b"R"
    assert unpack_mix(packed) == obj


def test_large_payload_compresses():
    # periodic/sparse diffs compress well; wire bytes must shrink
    obj = {"protocol": 1,
           "diffs": {"w": np.zeros(65536, dtype=np.float32)}}
    packed = pack_mix(obj)
    raw_len = len(pack_obj(obj))
    assert packed[:1] == b"Z"
    assert len(packed) < raw_len / 10
    out = unpack_mix(packed)
    np.testing.assert_array_equal(out["diffs"]["w"],
                                  np.zeros(65536, dtype=np.float32))


def test_incompressible_payload_stays_raw():
    rng = np.random.default_rng(0)
    obj = {"blob": rng.integers(0, 256, size=2 * COMPRESS_THRESHOLD,
                                dtype=np.uint8).tobytes()}
    packed = pack_mix(obj)
    assert packed[:1] == b"R"  # zlib couldn't win → raw
    assert unpack_mix(packed) == obj


def test_unprefixed_legacy_payload_accepted():
    obj = {"protocol": 1, "diffs": {}}
    assert unpack_mix(pack_obj(obj)) == obj


def test_bf16_allreduce_close_to_exact(rng):
    mesh = replica_mesh(4, devices=jax.devices()[:4])
    diffs = [{"w": rng.normal(size=256).astype(np.float32)} for _ in range(4)]
    exact = allreduce_diffs(diffs, mesh)
    quant = allreduce_diffs(diffs, mesh, compress=True)
    want = sum(d["w"].astype(np.float64) for d in diffs)
    np.testing.assert_allclose(np.asarray(exact["w"]), want,
                               rtol=1e-4, atol=1e-5)
    # bf16 wire: ~2-3 significant digits preserved
    np.testing.assert_allclose(np.asarray(quant["w"]), want,
                               rtol=0.05, atol=0.05)
    # int leaves pass through exactly even when compressing
    idiffs = [{"n": np.asarray([i + 1], dtype=np.int32)} for i in range(4)]
    iq = allreduce_diffs(idiffs, mesh, compress=True)
    assert int(iq["n"][0]) == 10
