"""IntervalMixer scheduling tests (the stabilizer-loop semantics,
linear_mixer.cpp:362-435) + regression/weight driver tests."""

import time

import pytest

from jubatus_tpu.core import Datum
from jubatus_tpu.framework import IntervalMixer
from jubatus_tpu.models import RegressionDriver, WeightDriver
from jubatus_tpu.parallel import LocalMixGroup


def test_mixer_fires_on_count_threshold():
    fired = []
    m = IntervalMixer(lambda: fired.append(time.monotonic()),
                      interval_sec=9999, interval_count=10)
    m.POLL_SEC = 0.01
    m.start()
    try:
        m.updated(10)
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.01)
    finally:
        m.stop()
    assert len(fired) == 1
    assert m.mix_count == 1
    assert m.get_status()["counter"] == 0


def test_mixer_fires_on_time_threshold_only_with_updates():
    fired = []
    m = IntervalMixer(lambda: fired.append(1), interval_sec=0.05, interval_count=10_000)
    m.POLL_SEC = 0.01
    m.start()
    try:
        time.sleep(0.2)
        assert not fired  # no updates -> no mix
        m.updated(1)
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.01)
    finally:
        m.stop()
    assert fired


def test_mixer_mix_now_and_failure_does_not_kill_loop():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("boom")

    m = IntervalMixer(flaky, interval_sec=9999, interval_count=1)
    m.POLL_SEC = 0.01
    m.start()
    try:
        m.updated(1)
        deadline = time.time() + 5
        while len(calls) < 1 and time.time() < deadline:
            time.sleep(0.01)
        m.updated(1)
        while len(calls) < 2 and time.time() < deadline:
            time.sleep(0.01)
    finally:
        m.stop()
    assert len(calls) >= 2


def test_mixer_stop_while_running_is_clean():
    m = IntervalMixer(lambda: None)
    m.start()
    m.stop()
    assert m._thread is None


REG_CFG = {
    "method": "PA1",
    "parameter": {"sensitivity": 0.01, "regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}], "string_rules": []},
}


def test_regression_driver_end_to_end(tmp_path, rng):
    d = RegressionDriver(REG_CFG, dim_bits=10)
    # no implicit intercept (reference parity): model it as a constant feature
    data = [(2.0 * x + 1.0, Datum({"x": x, "bias": 1.0})) for x in rng.uniform(-1, 1, 200)]
    for _ in range(5):
        d.train(data)
    pred = d.estimate([Datum({"x": 0.5, "bias": 1.0}), Datum({"x": -0.5, "bias": 1.0})])
    assert pred[0] == pytest.approx(2.0, abs=0.3)
    assert pred[1] == pytest.approx(0.0, abs=0.3)

    from jubatus_tpu.framework import load_model, save_model

    path = str(tmp_path / "r.jubatus")
    save_model(path, d, config=d.config_json)
    d2 = RegressionDriver(REG_CFG, dim_bits=10)
    load_model(path, d2, expected_config=d2.config_json)
    assert d2.estimate([Datum({"x": 0.5, "bias": 1.0})])[0] == pytest.approx(pred[0], abs=1e-5)

    d.clear()
    assert d.estimate([Datum({"x": 0.5, "bias": 1.0})])[0] == 0.0


def test_regression_mix(rng):
    ds = [RegressionDriver(REG_CFG, dim_bits=10) for _ in range(2)]
    xs = rng.uniform(-1, 1, 200)
    for i, d in enumerate(ds):
        for _ in range(5):
            d.train([(3.0 * x, Datum({"x": x})) for x in xs[i::2]])
    LocalMixGroup(ds).mix()
    for d in ds:
        assert d.estimate([Datum({"x": 1.0})])[0] == pytest.approx(3.0, abs=0.4)


WEIGHT_CFG = {
    "converter": {
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "tf", "global_weight": "idf"}
        ],
        "num_rules": [{"key": "*", "type": "num"}],
    }
}


def test_weight_driver_update_and_mix():
    d0 = WeightDriver(WEIGHT_CFG, dim_bits=10)
    d1 = WeightDriver(WEIGHT_CFG, dim_bits=10)
    for _ in range(4):
        d0.update(Datum({"t": "common rare0"}))
        d1.update(Datum({"t": "common rare1"}))
    # idf on d0 only knows its local docs pre-mix
    pre = dict(d0.calc_weight(Datum({"t": "common rare1"})))
    LocalMixGroup([d0, d1]).mix()
    post = dict(d0.calc_weight(Datum({"t": "common rare1"})))
    # after mix, d0 knows rare1 occurs in half the corpus -> finite idf < pre
    k = "t$rare1@space#tf/idf"
    assert post[k] < pre[k]
    common = "t$common@space#tf/idf"
    assert post[common] == pytest.approx(0.0, abs=1e-6)  # in every doc -> idf 0


def test_concurrent_train_and_mix_thread_safety():
    """Hammer train/classify from one thread while background mixes run —
    the model-lock discipline (driver.lock + group lock acquisition) must
    keep state consistent (the reference's rw_mutex, server_base.hpp:70-72)."""
    import threading
    from jubatus_tpu.models import ClassifierDriver
    from jubatus_tpu.framework import IntervalMixer

    cfg = {
        "method": "PA",
        "converter": {
            "string_rules": [
                {"key": "*", "type": "space", "sample_weight": "bin", "global_weight": "bin"}
            ],
            "num_rules": [],
        },
    }
    ds = [ClassifierDriver(cfg, dim_bits=10) for _ in range(2)]
    group = LocalMixGroup(ds)
    mixer = IntervalMixer(group.mix, interval_sec=9999, interval_count=4)
    mixer.POLL_SEC = 0.005
    errors = []

    def hammer(d, tag):
        try:
            for i in range(30):
                d.train([(f"l{i % 3}", Datum({"t": f"w{i} z{i % 5} {tag}"}))])
                mixer.updated(1)
                d.classify([Datum({"t": f"w{i}"})])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    mixer.start()
    threads = [threading.Thread(target=hammer, args=(d, i)) for i, d in enumerate(ds)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mixer.stop()
    assert not errors, errors
    assert mixer.mix_count >= 1
    # both replicas converged to the same schema
    group.mix()
    assert ds[0].get_schema() == ds[1].get_schema()


def test_tree_sum_pads_row_trimmed_diffs():
    """Row-trimmed label diffs can differ by a row when a replica trains
    a novel label between schema sync and get_diff; the fold zero-pads
    to the larger row count instead of aborting the round."""
    import numpy as np

    from jubatus_tpu.parallel.mix import tree_sum

    a = {"dw": np.ones((2, 4), np.float32), "count": np.float32(1.0)}
    b = {"dw": np.full((3, 4), 2.0, np.float32), "count": np.float32(1.0)}
    tot = tree_sum([a, b])
    assert tot["dw"].shape == (3, 4)
    np.testing.assert_allclose(tot["dw"][:2], 3.0)
    np.testing.assert_allclose(tot["dw"][2], 2.0)
    assert float(tot["count"]) == 2.0
