"""Model-integrity plane tests (ISSUE 15): finite/norm screens on
synthetic diffs, the quarantine breaker's trip + K-clean release, async
inbox admission, collective chunk CRC mismatch -> RPC fallback, the
rollback ring's bounds + CRC validation + auto-rollback on a non-finite
folded total, envelope compat on both transports, ingest hardening at
fv convert time, the codestyle guard-coverage gate, and a live 3-member
acceptance drill with an armed poisoner."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from jubatus_tpu.framework.model_guard import (
    DEFAULT_QUARANTINE_AFTER,
    DEFAULT_RELEASE_AFTER,
    MixGuard,
    ModelSnapshotRing,
    norm_outliers,
    payload_nonfinite,
    payload_norm,
)
from jubatus_tpu.utils import faults

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}

NAMES = ["w"]
GOOD = {"w": np.ones(8, np.float32)}
NAN = {"w": np.array([1.0, np.nan, 2.0], np.float32)}
INF = {"w": np.array([np.inf, 0.0], np.float32)}
BIG = {"w": np.ones(8, np.float32) * 1e6}


# -- pure units ---------------------------------------------------------------


def test_finite_screen_on_synthetic_diffs():
    assert not payload_nonfinite(GOOD, NAMES)
    assert payload_nonfinite(NAN, NAMES)
    assert payload_nonfinite(INF, NAMES)
    # only the named (summable) mixables are screened
    assert not payload_nonfinite({"other": NAN["w"]}, NAMES)
    # int leaves cannot carry NaN and must not break the screen
    assert not payload_nonfinite({"w": np.array([1, 2], np.int32)}, NAMES)
    # nested trees screen leaf-wise
    assert payload_nonfinite({"w": {"a": GOOD["w"], "b": NAN["w"]}}, NAMES)


def test_norm_screen_leave_one_out_median():
    assert payload_norm(GOOD, NAMES) == pytest.approx(np.sqrt(8.0))
    # 1e6-scaled member is judged against its PEERS, not a median it
    # dominates — robust at N=2
    out = norm_outliers({"a": 1.0, "b": 1e6}, 10.0)
    assert set(out) == {"b"}
    out = norm_outliers({"a": 1.0, "b": 1.1, "c": 1e6}, 10.0)
    assert set(out) == {"c"}
    # a quiet fleet (peer median 0) judges nothing
    assert norm_outliers({"a": 5.0, "b": 0.0}, 10.0) == {}
    # single contributor: no distribution, no verdict
    assert norm_outliers({"a": 1e9}, 10.0) == {}
    # bound <= 0 disables the screen
    assert norm_outliers({"a": 1.0, "b": 1e6}, 0.0) == {}


def test_guard_mode_ladder():
    off = MixGuard(mode="off")
    rep = off.screen({"a": GOOD, "b": NAN}, NAMES)
    assert set(rep.admitted) == {"a", "b"} and not rep.flagged

    warn = MixGuard(mode="warn")
    rep = warn.screen({"a": GOOD, "b": NAN}, NAMES)
    assert set(rep.admitted) == {"a", "b"}  # flags, folds anyway
    assert rep.flagged == {"b": "nonfinite"}

    q = MixGuard(mode="quarantine", norm_bound=4.0)
    rep = q.screen({"a": GOOD, "b": NAN, "c": BIG}, NAMES)
    assert set(rep.admitted) == {"a"}
    assert rep.flagged == {"b": "nonfinite", "c": "norm_outlier"}
    with pytest.raises(ValueError):
        MixGuard(mode="nonsense")


def test_quarantine_breaker_trip_and_k_clean_release():
    g = MixGuard(mode="quarantine", quarantine_after=2, release_after=3)
    # first offense: rejected but not yet behind the breaker
    rep = g.screen({"a": GOOD, "b": NAN}, NAMES)
    assert rep.flagged == {"b": "nonfinite"} and not rep.quarantined_now
    assert not g.is_quarantined("b")
    # second consecutive offense trips it
    rep = g.screen({"a": GOOD, "b": NAN}, NAMES)
    assert rep.quarantined_now == ["b"] and g.is_quarantined("b")
    # now clean payloads still stay OUT of the fold until K clean rounds
    for i in range(2):
        rep = g.screen({"a": GOOD, "b": GOOD}, NAMES)
        assert rep.flagged == {"b": "quarantined"}
        assert set(rep.admitted) == {"a"} and not rep.released
    # third clean round releases and re-admits
    rep = g.screen({"a": GOOD, "b": GOOD}, NAMES)
    assert rep.released == ["b"] and set(rep.admitted) == {"a", "b"}
    assert not g.is_quarantined("b")
    # a clean round between offenses resets the streak (no trip)
    g2 = MixGuard(mode="quarantine", quarantine_after=2)
    g2.screen({"a": GOOD, "b": NAN}, NAMES)
    g2.screen({"a": GOOD, "b": GOOD}, NAMES)
    rep = g2.screen({"a": GOOD, "b": NAN}, NAMES)
    assert not rep.quarantined_now and not g2.is_quarantined("b")
    assert DEFAULT_QUARANTINE_AFTER >= 2 and DEFAULT_RELEASE_AFTER >= 1


def test_screen_payload_inbox_semantics():
    g = MixGuard(mode="quarantine", quarantine_after=2, release_after=2)
    assert g.screen_payload("m", GOOD, NAMES) is None
    assert g.screen_payload("m", NAN, NAMES) == "nonfinite"
    assert g.screen_payload("m", NAN, NAMES) == "nonfinite"  # trips
    assert g.is_quarantined("m")
    # clean submissions count toward release even while refused
    assert g.screen_payload("m", GOOD, NAMES) == "quarantined"
    assert g.screen_payload("m", GOOD, NAMES) is None  # released
    assert not g.is_quarantined("m")
    # warn mode flags but never rejects / trips
    w = MixGuard(mode="warn", quarantine_after=1)
    assert w.screen_payload("m", NAN, NAMES) == "nonfinite"
    assert not w.is_quarantined("m")
    # off mode screens nothing
    assert MixGuard().screen_payload("m", NAN, NAMES) is None


def test_fault_mutation_modes():
    r = faults.parse_rule("mix.diff.poison*:nan")
    assert r.action == "nan"
    r = faults.parse_rule("mix.diff.poison*:scale:1e6")
    assert r.action == "scale" and r.arg == 1e6
    assert faults.parse_rule("mix.wire.corrupt:bitflip").action == "bitflip"
    with pytest.raises(ValueError):
        faults.parse_rule("site:scale")  # needs a factor
    with pytest.raises(ValueError):
        faults.parse_rule("site:frobnicate")
    with faults.armed("x.y:nan"):
        assert faults.fire("x.y") is False  # plain sites ignore mutations
        assert faults.fire_mutate("x.y") == ("nan", 0.0)
    assert faults.fire_mutate("x.y") is None  # disarmed
    # nan patches exactly ONE element of one float leaf (copies, never
    # the caller's array); ints are untouched
    tree = {"w": np.ones(16, np.float32), "n": np.array([3], np.int64)}
    out = faults.poison_tree(tree, ("nan", 0.0))
    assert int(np.isnan(out["w"]).sum()) == 1
    assert not np.isnan(tree["w"]).any()
    assert out["n"] is tree["n"]
    # scale multiplies every float leaf
    out = faults.poison_tree(tree, ("scale", 1e6))
    assert float(out["w"][0]) == 1e6
    # bitflip changes exactly the buffer, not its length
    flipped = faults.flip_byte(b"abcdef")
    assert len(flipped) == 6 and flipped != b"abcdef"


def test_snapshot_ring_bounds_and_crc():
    class FakeDriver:
        TYPE = "classifier"
        USER_DATA_VERSION = 1

        def __init__(self):
            self.state = {"w": [1.0, 2.0]}

        def pack(self):
            return dict(self.state)

        def unpack(self, data):
            self.state = dict(data)

    d = FakeDriver()
    ring = ModelSnapshotRing(capacity=3)
    assert ring.latest() is None
    with pytest.raises(RuntimeError):
        ring.restore(d)
    for v in range(5):
        d.state["w"] = [float(v)]
        ring.snapshot(d, model_version=v)
    # bounded: oldest two rotated out
    assert ring.stats()["count"] == 3 and ring.stats()["taken"] == 5
    assert [e["model_version"] for e in ring.list()] == [2, 3, 4]
    # restore newest, CRC-validated
    d.state["w"] = [999.0]
    assert ring.restore(d) == 4
    assert d.state["w"] == [4.0]
    assert ring.stats()["restored"] == 1
    # a rotted snapshot refuses to apply (envelope CRC catches it)
    from jubatus_tpu.framework.save_load import SaveLoadError

    entry = ring.latest()
    blob = bytearray(entry["blob"])
    blob[60] ^= 0xFF
    entry["blob"] = bytes(blob)
    with pytest.raises(SaveLoadError):
        ring.restore(d, entry)


def test_pack_envelope_matches_file_format(tmp_path):
    from jubatus_tpu.framework.save_load import (pack_envelope,
                                                 read_envelope,
                                                 write_envelope)

    blob = pack_envelope(b"sys", b"user")
    s, u = read_envelope(blob, "mem")
    assert s == b"sys" and u == b"user"
    path = str(tmp_path / "m.jubatus")
    write_envelope(path, b"sys", b"user")
    with open(path, "rb") as f:
        assert f.read() == blob


def test_server_args_guard_flags():
    from jubatus_tpu.server.args import parse_server_args

    args = parse_server_args(
        ["classifier", "-f", "/dev/null", "--mix-guard", "quarantine",
         "--mix-norm-bound", "6.5", "--model-snapshot-interval", "30",
         "--fault", "mix.diff.poison*:nan",
         "--fault", "mix.wire.corrupt:bitflip"])
    assert args.mix_guard == "quarantine"
    assert args.mix_norm_bound == 6.5
    assert args.model_snapshot_interval == 30.0
    assert parse_server_args(
        ["classifier", "-f", "/dev/null"]).mix_guard == "warn"
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--mix-guard", "nonsense"])
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--mix-norm-bound", "0"])
    with pytest.raises(SystemExit):
        parse_server_args(["classifier", "-f", "/dev/null",
                           "--model-snapshot-interval", "-1"])


def test_create_mixer_carries_guard():
    from jubatus_tpu.framework.push_mixer import create_mixer

    class FakeDriver:
        lock = threading.Lock()

    m = create_mixer("linear_mixer", FakeDriver(), None,
                     mix_guard="quarantine", mix_norm_bound=5.0)
    assert m.guard.mode == "quarantine" and m.guard.norm_bound == 5.0
    m = create_mixer("random_mixer", FakeDriver(), None, mix_guard="off")
    assert m.guard.mode == "off"
    m = create_mixer("linear_mixer", FakeDriver(), None, mix_async=True,
                     mix_guard="warn")
    assert m.guard.mode == "warn"


def test_rollback_classed_effectful():
    from jubatus_tpu.framework.idl import EFFECTFUL_BUILTINS

    assert "rollback" in EFFECTFUL_BUILTINS


# -- fv ingest hardening ------------------------------------------------------


def test_fv_rejects_nonfinite_num_values():
    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.core.fv import make_fv_converter
    from jubatus_tpu.utils import tracing

    conv = make_fv_converter(
        {"num_rules": [{"key": "*", "type": "num"}]}, dim_bits=16)
    before = tracing.default_registry().counters().get(
        "fv.nonfinite_rejected", 0)
    d = Datum(num_values=[("good", 2.0), ("bad", float("inf")),
                          ("worse", float("nan"))])
    named = conv.convert_named(d)
    assert named == {"good@num": 2.0}
    # batch path rides the same screen
    batch = conv.convert_batch([d, Datum(num_values=[("good", 1.0)])])
    assert batch.row_offsets.tolist() == [0, 1, 2]
    after = tracing.default_registry().counters().get(
        "fv.nonfinite_rejected", 0)
    assert after - before == 4  # 2 per conversion of d (convert_named +
    # convert_batch each screened the same two bad values)
    # finite-only data pays nothing and counts nothing
    fv = conv.convert(Datum(num_values=[("x", 1.5)]))
    assert len(fv) == 1
    assert tracing.default_registry().counters().get(
        "fv.nonfinite_rejected", 0) == after


def test_native_ingest_rejects_nonfinite_num_values():
    """The C++ ingest fast path never sees the Python converter's
    screen, so the [B,K] extraction zeroes non-finite entries into the
    padding slot and counts them (found by driving a real server: an
    inf feature flowed straight through the native plane)."""
    import msgpack

    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.native import ingest
    from jubatus_tpu.utils import tracing

    if not ingest.available():
        pytest.skip("native toolchain unavailable")
    conv = {"num_rules": [{"key": "*", "type": "num"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 16)
    before = tracing.default_registry().counters().get(
        "fv.nonfinite_rejected", 0)
    data = [("l0", Datum(num_values=[("x", 1.0), ("bad", float("inf"))])),
            ("l1", Datum(num_values=[("y", float("nan"))]))]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    labels, idx, val = p.parse(raw)
    assert np.isfinite(val).all()
    # the finite feature survived; bad entries landed in the pad slot
    kept = [(a, b) for a, b in zip(idx[0], val[0]) if a != 0]
    assert len(kept) == 1 and kept[0][1] == 1.0
    assert not [(a, b) for a, b in zip(idx[1], val[1]) if a != 0]
    after = tracing.default_registry().counters().get(
        "fv.nonfinite_rejected", 0)
    assert after - before == 2


# -- collective integrity -----------------------------------------------------


def test_psum_chunk_crc_and_finite_screens():
    from jubatus_tpu.parallel.collective import (ChunkIntegrityError,
                                                 psum_pytree)

    clean = {"big": np.ones(2 * 2**20, np.float32)}
    phases: dict = {}
    psum_pytree(dict(clean), phases=phases, chunk_mb=2, guard="warn")
    assert phases["finite_ok"] is True
    assert phases["crc_mismatch_chunks"] == 0

    poisoned = {"big": np.ones(2 * 2**20, np.float32)}
    poisoned["big"][777] = np.nan
    phases = {}
    psum_pytree(dict(poisoned), phases=phases, chunk_mb=2, guard="warn")
    assert phases["finite_ok"] is False and phases["nonfinite_chunks"] >= 1
    with pytest.raises(ChunkIntegrityError) as ei:
        psum_pytree(dict(poisoned), phases={}, chunk_mb=2,
                    guard="quarantine")
    assert ei.value.kind == "nonfinite"
    # prefer_device consumers get the same verdict (device-side screen)
    with pytest.raises(ChunkIntegrityError):
        psum_pytree(dict(poisoned), phases={}, chunk_mb=2,
                    guard="quarantine", prefer_device=True)

    # bitflip in the staging window: CRC catches it
    with faults.armed("mix.wire.corrupt:bitflip@1"):
        with pytest.raises(ChunkIntegrityError) as ei:
            psum_pytree(dict(clean), phases={}, chunk_mb=2,
                        guard="quarantine")
    assert ei.value.kind == "crc"
    with faults.armed("mix.wire.corrupt:bitflip@1"):
        phases = {}
        psum_pytree(dict(clean), phases=phases, chunk_mb=2, guard="warn")
    assert phases["crc_mismatch_chunks"] == 1
    # guard off: no screens, no phases noise
    phases = {}
    psum_pytree(dict(poisoned), phases=phases, chunk_mb=2, guard="off")
    assert phases["finite_ok"] is True


def test_psum_quarantine_preserves_ef_residuals():
    """A poisoned int8 round must leave the error-feedback chains of
    the last good round intact (the verdict fires before the commit)."""
    from jubatus_tpu.parallel.collective import (ChunkIntegrityError,
                                                 ErrorFeedback,
                                                 psum_pytree)

    rng = np.random.default_rng(7)
    clean = {"big": rng.normal(size=2 * 2**18).astype(np.float32)}
    ef = ErrorFeedback()
    psum_pytree(dict(clean), chunk_mb=0.5, compress="int8", feedback=ef,
                guard="quarantine")
    assert ef.rounds == 1
    keys = set(ef.contrib)
    poisoned = {"big": clean["big"].copy()}
    poisoned["big"][5] = np.inf
    with pytest.raises(ChunkIntegrityError):
        psum_pytree(poisoned, chunk_mb=0.5, compress="int8", feedback=ef,
                    guard="quarantine")
    assert ef.rounds == 1 and set(ef.contrib) == keys


def test_collective_chunk_integrity_forces_rpc_fallback(monkeypatch):
    """A ChunkIntegrityError inside the collective entry: counted,
    flight-recorded, nothing applied, and the NEXT round's prepare
    answers "unsupported" so the master mixes over RPC."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.parallel import collective as pcoll
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator="(shared)",
                        name="crc", listen_addr="127.0.0.1",
                        mixer="collective_mixer", mix_guard="quarantine",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0),
        coord=MemoryCoordinator(store))
    srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum

        c = ClassifierClient("127.0.0.1", srv.args.rpc_port, "crc")
        c.train([["a", Datum({"x": 1.0})], ["b", Datum({"x": -1.0})]])
        c.close()
        m = srv.mixer
        version_before = m.model_version

        class _Boom:
            def result(self):
                raise pcoll.ChunkIntegrityError("crc", "injected")

        monkeypatch.setattr(pcoll, "psum_pytree_start",
                            lambda *a, **k: _Boom())
        ver, sig = m.local_prepare("r1", [])
        assert sig != "unsupported"
        assert m._enter_collective("r1", int(ver), 1) is False
        assert m.model_version == version_before  # nothing applied
        assert m.integrity_failures == 1
        assert srv.rpc.trace.counters()[
            "mix.guard.chunk_crc_mismatch"] == 1
        recs = [r for r in m.flight.snapshot() if not r["ok"]]
        assert recs and recs[-1]["reason"] == "chunk_integrity_crc"
        evs = srv.rpc.trace.events.snapshot(
            grep="chunk_integrity_failure")
        assert evs and evs[-1]["kind"] == "crc"
        # next prepare routes the round to the RPC mix, exactly once
        _, sig = m.local_prepare("r2", [])
        assert sig == "unsupported"
        _, sig3 = m.local_prepare("r3", [])
        assert sig3 != "unsupported"
        m.local_abort("r3")
        m.local_abort("r2")
    finally:
        srv.stop()


# -- live clusters ------------------------------------------------------------


def _boot(tmp_path, sub, n=3, **kw):
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / sub)
    defaults = dict(engine="classifier", coordinator=coord_dir,
                    name="mg", listen_addr="127.0.0.1",
                    interval_sec=1e9, interval_count=1 << 30,
                    telemetry_interval=0, mix_guard="quarantine",
                    mix_norm_bound=8.0)
    defaults.update(kw)
    servers = []
    for _ in range(n):
        srv = EngineServer("classifier", CONF,
                           args=ServerArgs(**defaults))
        srv.start(0)
        servers.append(srv)
    return servers


def _train(srv, rows):
    from jubatus_tpu.client import ClassifierClient, Datum

    c = ClassifierClient("127.0.0.1", srv.args.rpc_port, "mg")
    c.train([[label, Datum(d)] for label, d in rows])
    c.close()


def _model_finite(srv) -> bool:
    import jax

    for leaf in jax.tree_util.tree_flatten(srv.driver.pack())[0]:
        a = np.asarray(leaf)
        if a.dtype != object and np.issubdtype(a.dtype, np.floating) \
                and not np.isfinite(a).all():
            return False
    return True


def test_live_poisoner_quarantined_and_released(tmp_path):
    """The acceptance drill: one member armed with a NaN poisoner is
    flagged + dropped from every fold (its staleness grows in the
    ledger), the breaker trips on the repeat offense, models stay
    finite everywhere, and K clean rounds after disarm the member folds
    again."""
    servers = _boot(tmp_path, "coord")
    victim = servers[2]
    try:
        rules = faults.arm(
            f"mix.diff.poison.{victim.self_nodeinfo().name}:nan")
        try:
            for rnd in range(3):
                for i, s in enumerate(servers):
                    _train(s, [(f"l{i % 2}", {"x": float(rnd + i + 1)})])
                r = servers[0].mixer.mix_now()
                assert r is not None
                assert r["contributors"] == 2
                assert r["quarantined"] == [victim.self_nodeinfo().name]
        finally:
            faults.disarm(rules)
        master = servers[0]
        counters = master.rpc.trace.counters()
        assert counters["mix.quarantined"] == 3
        assert counters["mix.guard.nonfinite"] == 3
        # breaker tripped on the repeat offense (event emitted once)
        assert master.mixer.guard.is_quarantined(
            victim.self_nodeinfo().name)
        evs = master.rpc.trace.events.snapshot(grep="member_quarantined")
        assert len(evs) == 1
        # quarantined member is NOT contributing: its ledger staleness
        # grew while the healthy members' stayed 0
        recs = [rec for rec in master.mixer.flight.snapshot()
                if rec.get("health")]
        stale = recs[-1]["health"]["staleness"]
        assert stale[victim.self_nodeinfo().name] >= 2
        # no non-finite weight anywhere, ever
        assert all(_model_finite(s) for s in servers)
        # victim still RECEIVES broadcasts (serves converged model)
        assert victim.mixer.model_version == master.mixer.model_version
        # guard state surfaces in get_status
        st = next(iter(master.get_status().values()))
        assert st["mixer.guard_mode"] == "quarantine"
        assert st["mixer.guard_quarantined"] == [
            victim.self_nodeinfo().name]
        # K clean rounds release the member back into the fold
        released_round = None
        for rnd in range(DEFAULT_RELEASE_AFTER + 1):
            for i, s in enumerate(servers):
                _train(s, [(f"l{i % 2}", {"x": 1.0})])
            r = servers[0].mixer.mix_now()
            if r["contributors"] == 3:
                released_round = rnd
                break
        assert released_round is not None
        assert not master.mixer.guard.is_quarantined(
            victim.self_nodeinfo().name)
        assert [e for e in master.rpc.trace.events.snapshot(
            grep="member_released")]
    finally:
        for s in servers:
            s.stop()


def test_live_scale_poisoner_trips_norm_screen(tmp_path):
    servers = _boot(tmp_path, "coord2")
    victim = servers[2]
    try:
        rules = faults.arm(
            f"mix.diff.poison.{victim.self_nodeinfo().name}:scale:1e6")
        try:
            for i, s in enumerate(servers):
                _train(s, [(f"l{i % 2}", {"x": float(i + 1)})])
            r = servers[0].mixer.mix_now()
        finally:
            faults.disarm(rules)
        assert r["contributors"] == 2
        assert servers[0].rpc.trace.counters()[
            "mix.guard.norm_outlier"] == 1
        assert all(_model_finite(s) for s in servers)
    finally:
        for s in servers:
            s.stop()


def test_live_warn_mode_flags_but_folds(tmp_path):
    servers = _boot(tmp_path, "coord3", mix_guard="warn")
    victim = servers[2]
    try:
        rules = faults.arm(
            f"mix.diff.poison.{victim.self_nodeinfo().name}:scale:1e6")
        try:
            for i, s in enumerate(servers):
                _train(s, [(f"l{i % 2}", {"x": float(i + 1)})])
            r = servers[0].mixer.mix_now()
        finally:
            faults.disarm(rules)
        # flagged + counted, but warn mode folds everything
        assert r["contributors"] == 3
        assert r["quarantined"] == [victim.self_nodeinfo().name]
        counters = servers[0].rpc.trace.counters()
        assert counters["mix.guard.norm_outlier"] == 1
        assert "mix.quarantined" not in counters
    finally:
        for s in servers:
            s.stop()


def test_async_inbox_admission(tmp_path):
    """A poisoned async submission is refused at the inbox in
    quarantine mode (counted + evented), and the sender is told."""
    from jubatus_tpu.framework.linear_mixer import pack_mix

    servers = _boot(tmp_path, "coord4", mix_async=True)
    try:
        master = servers[0]
        m = master.mixer
        good = {"protocol": 2, "schema": [], "version": 0,
                "diffs": {"weights": np.ones(4, np.float32)}}
        ack = m.local_submit_diff("peer_1", pack_mix(good))
        assert ack["accepted"] is True
        assert m.inbox.depth() == 1
        # mixable names gate the screen: use a summable name. The
        # classifier driver's mixables are what the screen iterates, so
        # poison one of ITS names.
        names = list(master.driver.get_mixables())
        bad = {"protocol": 2, "schema": [], "version": 0,
               "diffs": {names[0]: np.array([np.nan], np.float32)}}
        ack = m.local_submit_diff("peer_2", pack_mix(bad))
        assert ack["accepted"] is False and ack.get("quarantined")
        assert m.inbox.depth() == 1  # never occupied a slot
        counters = master.rpc.trace.counters()
        assert counters["mix.quarantined"] == 1
        assert counters["mix.guard.nonfinite"] == 1
        assert master.rpc.trace.events.snapshot(grep="inbox_rejected")
    finally:
        for s in servers:
            s.stop()


def test_rollback_ring_and_auto_rollback(tmp_path):
    """Snapshot → poison the apply path → put_diff refuses the
    non-finite total, auto-rolls back to last-good, and the model
    weights come back bit-identical."""
    import jax

    from jubatus_tpu.framework.linear_mixer import PROTOCOL_VERSION

    servers = _boot(tmp_path, "coord5", n=1,
                    model_snapshot_interval=3600.0)
    srv = servers[0]
    try:
        _train(srv, [("l0", {"x": 1.0}), ("l1", {"x": -2.0})])
        snap = srv.take_snapshot()
        assert snap["model_version"] == srv.mixer.model_version
        want = srv.driver.pack()
        _train(srv, [("l0", {"x": 5.0})])  # post-snapshot training

        def _nanify(x):
            a = np.asarray(x)
            if a.dtype != object and np.issubdtype(a.dtype, np.floating):
                return np.full_like(a, np.nan)
            return a

        with srv.driver.lock:
            diffs = {n: mx.get_diff()
                     for n, mx in srv.driver.get_mixables().items()}
        poisoned = {"protocol": PROTOCOL_VERSION,
                    "schema": srv.mixer.local_get_schema(),
                    "base_version": srv.mixer.model_version,
                    "diffs": jax.tree_util.tree_map(_nanify, diffs)}
        ok = srv.mixer.local_put_obj(poisoned)
        assert ok is False
        assert srv.rollbacks == 1
        assert srv.rpc.trace.counters()["mix.rollbacks"] == 1
        assert srv.rpc.trace.counters()["mix.guard.nonfinite_total"] == 1
        assert srv.rpc.trace.events.snapshot(grep="rollback")
        # refusal must NOT start the obsolete/recovery ladder
        assert srv.mixer._obsolete is False
        # weights restored bit-identically to the snapshot
        got = srv.driver.pack()
        for a, b in zip(jax.tree_util.tree_flatten(want)[0],
                        jax.tree_util.tree_flatten(got)[0]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # degraded reason is visible while the incident is fresh
        kinds = {r["kind"] for r in srv._degraded_reasons()}
        assert "model_rolled_back" in kinds
        # snapshot/rollback state in get_status + /healthz doc
        st = next(iter(srv.get_status().values()))
        assert st["snapshot.count"] == 1
        assert st["rollback.count"] == 1
        assert srv._health()["model_rollbacks"] == 1
    finally:
        srv.stop()


def test_rollback_without_snapshot_refuses(tmp_path):
    servers = _boot(tmp_path, "coord6", n=1)
    try:
        out = servers[0].rollback("mg", "operator")
        assert out["rolled_back"] is False and "no model snapshot" in \
            out["error"]
    finally:
        servers[0].stop()


@pytest.mark.parametrize("native", [False, True])
def test_rollback_rpc_envelope_compat(tmp_path, monkeypatch, native):
    """The rollback RPC answers plain 4-element AND traced/deadlined
    5/6-element envelopes on both transports."""
    from jubatus_tpu.rpc import native_server
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.utils import tracing

    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1" if native else "0")
    servers = _boot(tmp_path, f"coord7{int(native)}", n=1)
    srv = servers[0]
    try:
        _train(srv, [("l0", {"x": 1.0})])
        srv.take_snapshot()
        port = srv.args.rpc_port
        with RpcClient("127.0.0.1", port) as c:
            out = c.call("rollback", "mg", "drill")
        assert out[b"rolled_back" if isinstance(
            next(iter(out)), bytes) else "rolled_back"]
        ctx = tracing.new_root()
        from jubatus_tpu.rpc import deadline as deadlines

        with tracing.use_trace(ctx), deadlines.deadline_after(30.0):
            with RpcClient("127.0.0.1", port) as c:
                out = c.call("rollback", "mg", "drill")
        vals = {(k.decode() if isinstance(k, bytes) else k): v
                for k, v in out.items()}
        assert vals["rolled_back"] is True
        assert srv.rollbacks == 2
    finally:
        srv.stop()


# -- jubactl rendering --------------------------------------------------------


def test_jubactl_guard_render():
    from jubatus_tpu.cmd.jubactl import _fmt_guard, _watch_node_row

    assert _fmt_guard({"mixer.guard_mode": "off"}) == ""
    line = _fmt_guard({"mixer.guard_mode": "quarantine",
                       "mixer.guard_quarantined": ["10.0.0.1_9199"],
                       "snapshot.count": 2,
                       "snapshot.last_model_version": 7,
                       "rollback.count": 1})
    assert "quarantine" in line and "10.0.0.1_9199" in line
    assert "snapshots 2" in line and "rollbacks 1" in line
    row = _watch_node_row("n1", {"status": {
        "health.status": "ok",
        "mixer.guard_quarantined": ["a_1", "b_2"],
        "rollback.count": 3}}, active=True)
    assert "quar 2" in row and "rb 3" in row


# -- codestyle gate self-test -------------------------------------------------


def test_guard_coverage_gate():
    import ast
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "codestyle_check", os.path.join(repo, "tools", "codestyle",
                                        "check.py"))
    check = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(check)

    assert check._is_guard_gated("jubatus_tpu/framework/linear_mixer.py")
    assert check._is_guard_gated("jubatus_tpu/framework/async_mixer.py")
    assert not check._is_guard_gated("jubatus_tpu/framework/driver.py")
    assert not check._is_guard_gated("jubatus_tpu/server/base.py")

    bad = ("def fold(diffs):\n"
           "    return tree_sum(diffs)\n")
    probs = check._check_guard_coverage(
        "x.py", ast.parse(bad), bad.splitlines())
    assert len(probs) == 1 and "model-guard" in probs[0]

    good = ("def fold(self, diffs):\n"
            "    self.guard.screen(diffs, [])\n"
            "    return tree_sum(diffs)\n")
    assert check._check_guard_coverage(
        "x.py", ast.parse(good), good.splitlines()) == []

    pragma = ("def fold(diffs):\n"
              "    return tree_sum(diffs)  # no-guard — pre-screened\n")
    assert check._check_guard_coverage(
        "x.py", ast.parse(pragma), pragma.splitlines()) == []

    apply_site = ("def apply(m, diff):\n"
                  "    return m.put_diff(diff)\n")
    assert len(check._check_guard_coverage(
        "x.py", ast.parse(apply_site), apply_site.splitlines())) == 1

    # the real mixer modules are clean under the gate
    for mod in ("linear_mixer", "async_mixer", "collective_mixer",
                "push_mixer", "mixer"):
        path = os.path.join(repo, "jubatus_tpu", "framework",
                            f"{mod}.py")
        with open(path) as f:
            text = f.read()
        assert check._check_guard_coverage(
            path, ast.parse(text), text.splitlines()) == []
