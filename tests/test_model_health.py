"""Model-health plane tests (ISSUE 7): time-series ring bounds and
window math, SLO grammar + burn-rate fire/clear, mix-convergence
gauges on every member of a live cluster, concurrent /metrics scrape
under sampler + mix load, degraded /healthz, jubactl alerts/watch,
and the metrics-docs catalog gate."""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from jubatus_tpu.utils import timeseries, tracing
from jubatus_tpu.utils.slo import SloEngine, parse_slo
from jubatus_tpu.utils.timeseries import TimeSeriesRing, window_from_points

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- time-series ring ---------------------------------------------------------


def test_ring_bounds_and_eviction():
    ring = TimeSeriesRing(capacity=5)
    reg = tracing.Registry()
    for i in range(12):
        reg.count("evt")
        assert ring.sample(reg.snapshot(), ts=1000.0 + i)
    assert len(ring) == 5
    pts = ring.points()
    assert [p["ts"] for p in pts] == [1007.0, 1008.0, 1009.0, 1010.0, 1011.0]
    st = ring.stats()
    assert st["sampled"] == 12 and st["retained"] == 5
    assert st["oldest_ts"] == 1007.0 and st["newest_ts"] == 1011.0
    assert ring.points(last=2) == pts[-2:]


def test_ring_spacing_guard_and_force():
    ring = TimeSeriesRing(capacity=8, min_spacing_s=5.0)
    reg = tracing.Registry()
    assert ring.sample(reg.snapshot(), ts=100.0)
    assert not ring.sample(reg.snapshot(), ts=101.0)  # too close
    assert ring.sample(reg.snapshot(), ts=101.0, force=True)
    assert ring.sample(reg.snapshot(), ts=107.0)
    assert len(ring) == 3


def test_window_counter_rates_and_quantiles():
    reg = tracing.Registry()
    ring = TimeSeriesRing(capacity=16)
    for _ in range(100):
        reg.record("rpc.classify", 0.001)
    reg.count("rpc.classify.errors", 2)
    ring.sample(reg.snapshot(), ts=0.0)
    for _ in range(50):
        reg.record("rpc.classify", 0.2)
    reg.count("rpc.classify.errors", 8)
    ring.sample(reg.snapshot(), ts=10.0)
    win = ring.window(60.0)
    assert win is not None
    # only the BETWEEN-points traffic is in the window
    assert win.span_count("rpc.classify") == 50
    assert win.span_rate("rpc.classify") == pytest.approx(5.0)
    assert win.counter_delta("rpc.classify.errors") == 8
    assert win.counter_rate("rpc.classify.errors") == pytest.approx(0.8)
    # windowed p50 reflects the slow burst, not the lifetime histogram
    assert win.quantile_ms("rpc.classify", 0.5) == pytest.approx(200, rel=0.3)
    assert win.bad_fraction("rpc.classify", 0.05) == pytest.approx(1.0)
    assert win.counter_names("rpc.") == ["rpc.classify.errors"]
    assert win.spans("rpc.") == ["rpc.classify"]


def test_window_clamps_after_registry_reset():
    reg = tracing.Registry()
    ring = TimeSeriesRing(capacity=8)
    reg.count("c", 100)
    reg.record("s", 0.01)
    ring.sample(reg.snapshot(), ts=0.0)
    reg.reset()
    reg.count("c", 5)
    ring.sample(reg.snapshot(), ts=10.0)
    win = ring.window(60.0)
    assert win.counter_delta("c") == 0  # clamped, not negative
    assert win.span_count("s") == 0


def test_window_from_points_baseline_selection():
    pts = [{"ts": float(t), "hists": {}, "counters": {"c": t}, "gauges": {}}
           for t in (0, 10, 20, 30)]
    win = window_from_points(pts, 15.0)  # start at 30-15=15 -> baseline 10
    assert win.baseline["ts"] == 10.0
    assert win.counter_delta("c") == 20
    # window longer than the ring: falls back to the oldest point
    win = window_from_points(pts, 1000.0)
    assert win.baseline["ts"] == 0.0
    assert window_from_points(pts[:1], 10.0) is None


def test_hist_state_delta_is_window_histogram():
    a, b = tracing.Histogram(), None
    for _ in range(10):
        a.record(0.001)
    before = a.state()
    for _ in range(10):
        a.record(1.0)
    d = timeseries.hist_state_delta(a.state(), before)
    assert d["count"] == 10
    assert tracing.state_quantile(d, 0.5) == pytest.approx(1.0, rel=0.3)
    d0 = timeseries.hist_state_delta(a.state(), b)  # no baseline
    assert d0["count"] == 20


# -- slo grammar + burn math --------------------------------------------------


def test_parse_slo_grammar():
    s = parse_slo("latency:rpc.classify:p99:50")
    assert s.kind == "latency" and s.span == "rpc.classify"
    assert s.threshold_s == pytest.approx(0.05)
    assert s.objective == pytest.approx(0.01)
    assert s.name == "rpc.classify.p99"
    s = parse_slo("hot=latency:rpc.train:p90:20:0.2")
    assert s.name == "hot" and s.objective == pytest.approx(0.2)
    s = parse_slo("error_rate:*:0.01")
    assert s.kind == "error_rate" and s.span == "*"
    s = parse_slo("gauge:mix.ef_residual_drift_rate:0.05")
    assert s.kind == "gauge" and s.ceiling == pytest.approx(0.05)
    for bad in ("latency:rpc.x:q99:50", "latency:rpc.x:p99:0",
                "error_rate:*:1.5", "gauge:k:0", "nope:x:y",
                "latency:rpc.x:p99", "error_rate:*"):
        with pytest.raises(ValueError):
            parse_slo(bad)


def _ticked(reg, ring, ts):
    ring.sample(reg.snapshot(), ts=ts)


def test_burn_rate_fires_and_clears():
    """Multi-window burn math on a synthetic timeline: an error/latency
    burst fires (both windows above threshold), recovery clears (the
    fast window moves past the burst while the slow one still sees
    it)."""
    reg = tracing.Registry()
    ring = TimeSeriesRing(capacity=32)
    eng = SloEngine(
        [parse_slo("latency:rpc.classify:p99:50"),
         parse_slo("error_rate:*:0.01")],
        ring, reg, fast_window_s=30.0, slow_window_s=120.0,
        burn_threshold=2.0)
    t0 = 10_000.0
    for _ in range(200):
        reg.record("rpc.classify", 0.001)
    _ticked(reg, ring, t0)
    # quiet period: no burn
    for _ in range(100):
        reg.record("rpc.classify", 0.001)
    _ticked(reg, ring, t0 + 20)
    st = {s["name"]: s for s in eng.evaluate(now=t0 + 20)}
    assert not st["rpc.classify.p99"]["firing"]
    assert not st["errors.*"]["firing"]
    # burst: slow requests + errors
    for _ in range(50):
        reg.record("rpc.classify", 0.4)
    reg.count("rpc.classify.errors", 10)
    _ticked(reg, ring, t0 + 40)
    st = {s["name"]: s for s in eng.evaluate(now=t0 + 40)}
    assert st["rpc.classify.p99"]["firing"]
    assert st["errors.*"]["firing"]
    assert st["errors.*"]["burn_fast"] > 2.0
    assert reg.gauges()["slo.rpc.classify.p99.firing"] == 1.0
    assert reg.counters()["slo.transitions"] == 2
    assert len(eng.alerts()) == 2
    # recovery: healthy traffic, fast window moves past the burst
    for _ in range(300):
        reg.record("rpc.classify", 0.001)
    _ticked(reg, ring, t0 + 80)
    for _ in range(300):
        reg.record("rpc.classify", 0.001)
    _ticked(reg, ring, t0 + 110)
    st = {s["name"]: s for s in eng.evaluate(now=t0 + 110)}
    assert not st["rpc.classify.p99"]["firing"]
    assert not st["errors.*"]["firing"]
    assert eng.alerts() == []
    assert reg.gauges()["slo.rpc.classify.p99.firing"] == 0.0


def test_gauge_slo_burns_on_windowed_mean():
    reg = tracing.Registry()
    ring = TimeSeriesRing(capacity=8)
    eng = SloEngine([parse_slo("gauge:mix.drift:0.1")], ring, reg,
                    fast_window_s=30, slow_window_s=60, burn_threshold=2.0)
    reg.gauge("mix.drift", 0.05)
    _ticked(reg, ring, 0.0)
    reg.gauge("mix.drift", 0.05)
    _ticked(reg, ring, 10.0)
    st = eng.evaluate(now=10.0)[0]
    assert st["burn_fast"] == pytest.approx(0.5) and not st["firing"]
    reg.gauge("mix.drift", 0.4)
    _ticked(reg, ring, 20.0)
    st = eng.evaluate(now=20.0)[0]
    assert st["burn_fast"] > 2.0 and st["firing"]


def test_error_feedback_norms():
    from jubatus_tpu.parallel.collective import ErrorFeedback

    ef = ErrorFeedback()
    assert ef.norms() == {"contrib_residual_norm": 0.0,
                          "total_residual_norm": 0.0}
    ef.contrib[(0, 0)] = np.array([3.0, 4.0], dtype=np.float32)
    ef.total[(0, 0)] = np.array([6.0, 8.0], dtype=np.float32)
    n = ef.norms()
    assert n["contrib_residual_norm"] == pytest.approx(5.0)
    assert n["total_residual_norm"] == pytest.approx(10.0)


# -- cluster: convergence gauges on every member ------------------------------


@pytest.fixture()
def health_cluster(tmp_path):
    """3-member linear-mixer cluster with SLOs configured and manual
    telemetry ticks (telemetry_interval=0: no sampler thread races the
    assertions)."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    for _ in range(3):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator=coord_dir,
                            name="mh", listen_addr="127.0.0.1",
                            interval_sec=1e9, interval_count=1 << 30,
                            telemetry_interval=0,
                            slo=["latency:rpc.classify:p99:50",
                                 "error_rate:*:0.01"],
                            slo_fast_window=1.0, slo_slow_window=2.5,
                            metrics_port=0))
        srv.start(0)
        servers.append(srv)
    # train DIFFERENT data per node so contributions genuinely diverge
    for i, s in enumerate(servers):
        c = ClassifierClient("127.0.0.1", s.args.rpc_port, "mh")
        c.train([[f"l{i}", Datum({"x": float(i + 1)})],
                 [f"l{(i + 1) % 3}", Datum({"x": -2.0 * i - 1})]])
        for _ in range(20):
            c.classify([Datum({"x": 1.0})])
        c.close()
    yield coord_dir, servers
    for s in servers:
        s.stop()


def test_mix_round_gauges_on_every_member(health_cluster):
    """ISSUE 7 acceptance: one mix round -> divergence / staleness /
    update-norm gauges on EVERY member, health stamped in the flight
    record and get_status."""
    _coord, servers = health_cluster
    res = servers[0].mixer.mix_now()
    assert res is not None and res["health"]["contributors"] == 3
    assert res["health"]["premix_divergence_max"] > 0  # distinct data
    assert res["health"]["staleness_max"] == 0
    for s in servers:
        g = s.rpc.trace.gauges()
        assert g["mix.premix_divergence_mean"] > 0
        assert g["mix.premix_divergence_max"] >= g["mix.premix_divergence_mean"]
        assert g["mix.update_norm"] > 0
        assert g["mix.self_staleness"] == 0.0
        assert g["mix.staleness_max"] == 0.0
        assert g["mix.contributors"] == 3.0
    # flight record carries the same health dict
    rec = servers[0].mixer.flight.snapshot()[-1]
    assert rec["health"]["contributors"] == 3
    assert set(rec["health"]["staleness"]) == \
        {s.self_nodeinfo().name for s in servers}
    # get_status flattens it under mixer.health_*
    st = next(iter(servers[1].get_status().values()))
    assert st["mixer.health_update_norm"] > 0
    assert st["mixer.self_staleness"] == 0
    assert st["health.status"] == "ok" and st["health.reasons"] == []


def test_staleness_tracks_missing_member(health_cluster):
    """A member whose get_diff keeps failing goes stale in the master's
    ledger; the health dict every member receives says so."""
    _coord, servers = health_cluster
    assert servers[0].mixer.mix_now() is not None
    # wedge member 2's mix_get_diff by stopping its RPC plane
    servers[2].rpc.stop()
    res = None
    for _ in range(2):
        res = servers[0].mixer.mix_now()
    assert res is not None
    stale = res["health"]["staleness"]
    victim = servers[2].self_nodeinfo().name
    assert stale[victim] >= 2
    assert res["health"]["staleness_max"] >= 2
    assert res["degraded"] is True
    # survivors gauge the degraded round's staleness
    g = servers[0].rpc.trace.gauges()
    assert g["mix.staleness_max"] >= 2


def test_get_timeseries_rpc_and_windowed_rates(health_cluster):
    from jubatus_tpu.rpc.client import RpcClient

    _coord, servers = health_cluster
    srv = servers[0]
    srv._model_health_tick()
    time.sleep(0.05)
    for _ in range(10):
        srv.rpc.trace.record("rpc.classify", 0.002)
    srv._model_health_tick()
    with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
        ts = c.call("get_timeseries", "mh")
    node = srv.self_nodeinfo().name
    assert node in ts
    points = ts[node]["points"]
    assert len(points) >= 2
    assert ts[node]["stats"]["retained"] == len(points)
    win = window_from_points(points, 60.0)
    assert win.span_count("rpc.classify") >= 10
    st = next(iter(srv.get_status().values()))
    assert st["timeseries.retained"] >= 2
    assert st["slo.configured"] == 2


def test_slo_burst_fires_degrades_healthz_then_clears(health_cluster,
                                                      capsys):
    """ISSUE 7 acceptance: an injected latency/error burst fires a
    burn-rate alert that shows in jubactl -c alerts and degrades
    /healthz, and clears after recovery."""
    from jubatus_tpu.cmd import jubactl

    coord_dir, servers = health_cluster
    srv = servers[0]
    reg = srv.rpc.trace
    for _ in range(100):
        reg.record("rpc.classify", 0.001)
    srv._model_health_tick()
    time.sleep(0.3)
    # burst: slow requests + errors
    for _ in range(40):
        reg.record("rpc.classify", 0.5)
    reg.count("rpc.classify.errors", 10)
    srv._model_health_tick()
    assert len(srv.slo.alerts()) >= 1
    # /healthz degrades with a structured slo_firing reason
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.args.metrics_port}/healthz",
            timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    assert doc["status"] == "degraded"
    kinds = {r["kind"] for r in doc["degraded_reasons"]}
    assert "slo_firing" in kinds
    assert doc["slo_firing"] >= 1
    # jubactl -c alerts renders the firing row
    rc = jubactl.main(["-c", "alerts", "-t", "classifier", "-n", "mh",
                       "-z", coord_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "FIRING" in out and "rpc.classify.p99" in out
    # recovery: healthy traffic pushes the fast window past the burst
    time.sleep(1.2)
    for _ in range(400):
        reg.record("rpc.classify", 0.001)
    srv._model_health_tick()
    time.sleep(0.4)
    for _ in range(400):
        reg.record("rpc.classify", 0.001)
    srv._model_health_tick()
    assert srv.slo.alerts() == []
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.args.metrics_port}/healthz",
            timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    assert doc["status"] == "ok"


def test_jubactl_watch_once_renders_cluster_frame(health_cluster, capsys):
    """ISSUE 7 acceptance: jubactl -c watch --once renders ONE coherent
    frame: every node's row with rates/p99/mix health, plus the alerts
    line."""
    from jubatus_tpu.cmd import jubactl

    coord_dir, servers = health_cluster
    servers[0].mixer.mix_now()
    for s in servers:
        s._model_health_tick()
    time.sleep(0.05)
    for s in servers:
        for _ in range(5):
            s.rpc.trace.record("rpc.classify", 0.002)
        s._model_health_tick()
    rc = jubactl.main(["-c", "watch", "--once", "--window", "120",
                       "-t", "classifier", "-n", "mh", "-z", coord_dir])
    out = capsys.readouterr().out
    assert rc == 0
    lines = out.splitlines()
    assert "3 server(s)" in lines[0] and "window 120s" in lines[0]
    for s in servers:
        node = s.self_nodeinfo().name
        row = next(ln for ln in lines if ln.strip().startswith(node))
        assert "div " in row and "stale " in row  # mix health cell
    assert any("alerts firing:" in ln for ln in lines)
    assert "req/s" in out and "p99 ms" in out


def test_jubactl_status_all_renders_health_line(health_cluster, capsys):
    from jubatus_tpu.cmd import jubactl

    coord_dir, servers = health_cluster
    rc = jubactl.main(["-c", "status", "--all", "-t", "classifier",
                       "-n", "mh", "-z", coord_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("health: ok") == 3


def test_degraded_reasons_cover_mix_states(health_cluster):
    """Structured /healthz reasons: quorum-degraded round + obsolete
    model show up with their kinds (no SLO involvement)."""
    _coord, servers = health_cluster
    srv = servers[1]
    srv.mixer.last_round_degraded = True
    srv.mixer._obsolete = True
    kinds = {r["kind"] for r in srv._degraded_reasons()}
    assert {"mix_quorum_degraded", "model_obsolete"} <= kinds
    doc = srv._health()
    assert doc["status"] == "degraded"
    srv.mixer.last_round_degraded = False
    srv.mixer._obsolete = False
    assert srv._health()["status"] == "ok"


def test_proxy_folds_timeseries_and_alerts(tmp_path):
    """get_timeseries / get_alerts against a proxy return backend AND
    proxy entries in one call (broadcast + fold-own)."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    store = _Store()
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator="(shared)",
                        name="pf", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0,
                        slo=["error_rate:*:0.01"]),
        coord=MemoryCoordinator(store))
    srv.start(0)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1",
                            telemetry_interval=0,
                            slo=["latency:rpc.classify:p99:100"]),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    try:
        srv._model_health_tick()
        proxy._model_health_tick()
        time.sleep(0.02)
        srv._model_health_tick()
        proxy._model_health_tick()
        srv.slo.evaluate()
        proxy.slo.evaluate()
        with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
            ts = c.call("get_timeseries", "pf")
            al = c.call("get_alerts", "pf")
        assert len(ts) == 2 and len(al) == 2  # backend + proxy entries
        assert all("points" in v for v in ts.values())
        slo_names = {s["name"] for doc in al.values()
                     for s in doc.get("slos", [])}
        assert {"errors.*", "rpc.classify.p99"} <= slo_names
    finally:
        proxy.stop()
        srv.stop()


# -- concurrency: scrape vs sampler vs mix ------------------------------------


def _parse_prometheus_strict(text: str) -> int:
    """Every non-comment line must parse; bucket series must be
    cumulative per selector. Returns the sample count."""
    import re

    pat = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([0-9eE.+-]+|NaN|\+Inf)'
        r'( # \{.*\} [0-9eE.+-]+ [0-9.]+)?$')
    assert text.endswith("\n")
    buckets: dict = {}
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = pat.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        n += 1
        if m.group(1) == "jubatus_span_duration_seconds_bucket":
            sel = m.group(2).split('le="')[0]
            prev = buckets.get(sel, 0.0)
            assert float(m.group(3)) >= prev, f"non-cumulative at {line!r}"
            buckets[sel] = float(m.group(3))
    return n


@pytest.mark.slow
def test_concurrent_scrape_sampler_and_mix(health_cluster):
    """ISSUE 7 satellite: /metrics scraped concurrently with the
    telemetry sampler ticking and mix rounds running — every scrape
    parses as valid cumulative Prometheus text (no torn snapshots),
    nothing deadlocks."""
    _coord, servers = health_cluster
    srv = servers[0]
    stop = threading.Event()
    errors: list = []

    def pump_ticks():
        while not stop.is_set():
            try:
                srv._model_health_tick()
                srv.telemetry.sample()
            except Exception as e:  # noqa: BLE001 — fail the test below
                errors.append(repr(e))
                return

    def pump_mix():
        while not stop.is_set():
            try:
                servers[0].mixer.mix_now()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                return

    def pump_traffic():
        i = 0
        while not stop.is_set():
            srv.rpc.trace.record("rpc.classify", 0.001 * (1 + i % 5))
            i += 1

    threads = [threading.Thread(target=f, daemon=True)
               for f in (pump_ticks, pump_mix, pump_traffic)]
    for t in threads:
        t.start()
    try:
        total = 0
        for _ in range(25):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.args.metrics_port}/metrics",
                    timeout=10) as resp:
                assert resp.status == 200
                total += _parse_prometheus_strict(resp.read().decode())
        assert total > 0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert errors == []


# -- metrics-docs catalog gate ------------------------------------------------


def test_check_metrics_docs_clean():
    repo = pathlib.Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "tools" / "check_metrics_docs.py")],
        capture_output=True, text=True, cwd=str(repo))
    assert r.returncode == 0, \
        f"undocumented metric keys:\n{r.stdout}\n{r.stderr}"


def test_check_metrics_docs_detects_missing(tmp_path):
    repo = pathlib.Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools"))
    try:
        import check_metrics_docs as cmd
    finally:
        sys.path.pop(0)
    d = tmp_path / "jubatus_tpu" / "sub"
    d.mkdir(parents=True)
    (d / "victim.py").write_text(
        '"""doc."""\n'
        'self.rpc.trace.count("made.up_counter")\n'
        'registry.gauge(f"made.{k}.gauge", 1.0)\n'
        'ln.count("\\t")\n'  # string-method false positive: ignored
        'reg.count("rpc.retries")\n',  # documented: passes
        encoding="utf-8")
    found = cmd.scan_source_keys(str(tmp_path / "jubatus_tpu"))
    assert "made.up_counter" in found
    assert "made.*.gauge" in found
    assert "\t" not in found and not any("\t" in k for k in found)
    missing = cmd.missing_keys(found, cmd.doc_keys())
    names = {k for k, _ in missing}
    assert names == {"made.up_counter", "made.*.gauge"}
    # wildcard matching: <placeholders> in the doc cover f-string keys
    assert cmd._segments_match("rpc.*.errors", "rpc.*.errors")
    assert not cmd._segments_match("rpc.x.errors", "rpc.x")
