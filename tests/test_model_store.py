"""Durable model plane (ISSUE 18): shared snapshot store, diff chains,
warm-boot, point-in-time restore, chaos.

Covers the robustness acceptance story in-process:

- diff documents round-trip losslessly (``compress="off"``) and the
  int8 mode's quantization error telescopes to the LAST diff only
  (error feedback: each diff is computed against the replayer's
  belief, not the true state);
- chain replay refuses to cross a gap (a deleted middle diff truncates
  at the longest valid prefix — never skips records), and replaying a
  chain equals the compacted full bit-for-bit;
- the store refuses unstamped blobs at put and CRC-refuses corrupt
  bytes at get (counted, evented, never partially loaded);
- a flaky store degrades warm boot to a cold boot — counted + evented
  — and never serves a wrong model;
- the save/load RPCs ride the store: save replies carry a store id,
  load accepts one (and falls back to a store scan when the local
  checkpoint file is gone);
- reshard-on-restore: a 1-node fleet's store snapshot restores onto 8
  shards and an 8-node fleet's onto 2, row-parity and bit-exact
  against a direct checkpoint load.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.framework.model_store import (
    LocalDirBackend,
    ModelStore,
    StoreUploader,
    apply_diff,
    diff_tree,
)
from jubatus_tpu.framework.save_load import (
    SaveLoadError,
    pack_envelope,
    read_envelope,
)
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.server.factory import create_driver
from jubatus_tpu.utils import events, faults
from jubatus_tpu.utils.serialization import pack_obj, unpack_obj

CLF_CONF = {"method": "AROW", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
NN_CONF = {"method": "lsh", "parameter": {"hash_num": 8},
           "converter": {"num_rules": [{"key": "*", "type": "num"}]}}


class _Counts(dict):
    def __call__(self, name, n=1):
        self[name] = self.get(name, 0) + n


def _mkstore(tmp_path, counter=None, engine="classifier"):
    return ModelStore(LocalDirBackend(str(tmp_path / "store")),
                      cluster="t", engine=engine, counter=counter)


def _clf_driver(trained_rows=0, seed=0):
    d = create_driver("classifier", CLF_CONF)
    rng = np.random.default_rng(seed)
    for i in range(trained_rows):
        d.train([("pos" if rng.random() < 0.5 else "neg",
                  Datum({"f0": float(rng.normal()),
                         "f1": float(rng.normal())}))])
    return d


def _tree_equal(a, b):
    if isinstance(a, dict):
        return isinstance(b, dict) and set(a) == set(b) and \
            all(_tree_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)):
        return isinstance(b, (list, tuple)) and len(a) == len(b) and \
            all(_tree_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return isinstance(a, np.ndarray) and isinstance(b, np.ndarray) \
            and a.dtype == b.dtype and bool(np.array_equal(a, b))
    return a == b


# -- diff documents -----------------------------------------------------------


def test_diff_tree_lossless_roundtrip():
    base = {"w": np.arange(8, dtype=np.float32),
            "meta": {"n": 3, "tag": "x"},
            "rows": [np.ones(4, dtype=np.float32), "keep"]}
    new = {"w": np.arange(8, dtype=np.float32) * 1.7 + 0.1,
           "meta": {"n": 5, "tag": "x"},
           "rows": [np.ones(4, dtype=np.float32) * 2.0, "keep"]}
    doc, belief = diff_tree(base, new)
    replay = apply_diff(unpack_obj(pack_obj(base)), doc)
    assert _tree_equal(replay, new)
    assert _tree_equal(belief, new)
    # unchanged leaves don't appear in the doc
    paths = [tuple(p) for p, _ in doc["changed"]]
    assert ("rows", 1) not in paths and ("meta", "tag") not in paths


def test_diff_tree_structure_change_ships_raw():
    base = {"labels": {"pos": np.zeros(4, dtype=np.float32)}}
    new = {"labels": {"pos": np.zeros(4, dtype=np.float32),
                      "neg": np.ones(4, dtype=np.float32)}}
    doc, belief = diff_tree(base, new)
    replay = apply_diff(unpack_obj(pack_obj(base)), doc)
    assert _tree_equal(replay, new)
    # key-set change replaces the container whole
    (path, spec), = doc["changed"]
    assert spec["m"] == "raw"


def test_diff_chain_int8_error_feedback_telescopes():
    """In int8 mode, belief == what a replayer reconstructs (exactly),
    so chain error never accumulates past the last diff's quantization
    residual."""
    rng = np.random.default_rng(7)
    state = {"w": rng.normal(size=512).astype(np.float32)}
    belief = unpack_obj(pack_obj(state))
    replay = unpack_obj(pack_obj(state))
    for _ in range(5):
        new = {"w": (state["w"] + rng.normal(size=512).astype(np.float32)
                     * 0.01).astype(np.float32)}
        doc, belief = diff_tree(belief, new, compress="int8")
        replay = apply_diff(replay, doc)
        state = new
    # the invariant that bounds the tail: replayer state == belief
    assert _tree_equal(replay, belief)
    # and the residual vs truth is one quantization step, not five
    err = float(np.abs(replay["w"] - state["w"]).max())
    assert err < 1e-3


# -- chain semantics ----------------------------------------------------------


def _upload_chain(store, driver, ticks=3, rows_per_tick=20, seed=1):
    up = StoreUploader(store, "n1", config=json.dumps(CLF_CONF))
    rng = np.random.default_rng(seed)
    version = 0
    for _ in range(ticks):
        for _i in range(rows_per_tick):
            driver.train([("pos" if rng.random() < 0.5 else "neg",
                           Datum({"f0": float(rng.normal()),
                                  "f1": float(rng.normal())}))])
        version += rows_per_tick
        up.tick(driver, version)
    return up


def test_chain_gap_refused_truncates_at_prefix(tmp_path):
    store = _mkstore(tmp_path)
    d = _clf_driver()
    _upload_chain(store, d, ticks=4)
    recs = store.records(kind="diff")
    assert len(recs) == 3
    # replaying the intact chain reaches the head
    _, meta = store.materialize(node="n1")
    assert meta["chain_len"] == 3
    # lose the MIDDLE diff: replay must stop before it, not skip it
    store.backend.delete(recs[1].key)
    _, meta = store.materialize(node="n1")
    assert meta["chain_len"] == 1
    assert meta["model_version"] == recs[0].version


def test_chain_replay_equals_compacted_full(tmp_path):
    store = _mkstore(tmp_path)
    d = _clf_driver()
    _upload_chain(store, d, ticks=4)
    blob_replay, meta = store.materialize(node="n1")
    assert meta["chain_len"] == 3
    key = store.compact(node="n1")
    assert key is not None
    # the folded diffs are gone; the compacted full IS the replay
    assert store.records(kind="diff", node="n1") == []
    blob_compact, meta2 = store.materialize(node="n1")
    assert meta2["chain_len"] == 0
    _, user_replay = read_envelope(blob_replay, "replay")
    _, user_compact = read_envelope(blob_compact, "compact")
    assert _tree_equal(unpack_obj(user_replay), unpack_obj(user_compact))


def test_point_in_time_resolve_picks_newest_at_or_before(tmp_path):
    store = _mkstore(tmp_path)
    d = _clf_driver()
    _upload_chain(store, d, ticks=3)
    recs = store.records()
    mid_hlc = recs[1].hlc  # full + first diff
    _, meta = store.materialize(at=mid_hlc, node="n1")
    assert meta["chain_len"] == 1
    assert meta["hlc"] == mid_hlc
    _, meta_latest = store.materialize(node="n1")
    assert meta_latest["hlc"] == recs[-1].hlc


# -- CRC refusal + fault sites ------------------------------------------------


def test_put_blob_refuses_unstamped_bytes(tmp_path):
    store = _mkstore(tmp_path)
    with pytest.raises(SaveLoadError):
        store.put_blob(b"not an envelope", kind="full", node="n1",
                       model_version=1)
    assert store.records() == []


def test_corrupt_get_is_refused_counted_and_evented(tmp_path):
    counts = _Counts()
    store = _mkstore(tmp_path, counter=counts)
    blob = pack_envelope(pack_obj({"type": "classifier"}), pack_obj([1, {}]))
    key = store.put_blob(blob, kind="full", node="n1", model_version=1)
    before = events.hlc_now()
    with faults.armed("store.get:bitflip"):
        with pytest.raises(SaveLoadError):
            store.fetch(key)
        # a fully corrupt store yields NO snapshot — never a partial one
        assert store.latest() is None
    assert counts.get("store.crc_refused", 0) >= 1
    evs = events.default_journal().snapshot(since=before, grep="crc_refused")
    assert evs and evs[-1]["subsystem"] == "store"
    # disarmed, the same record reads back intact
    assert store.fetch(key) == blob


def test_put_fault_counted_chain_consistent(tmp_path):
    counts = _Counts()
    store = _mkstore(tmp_path, counter=counts)
    d = _clf_driver()
    up = _upload_chain(store, d, ticks=2)
    with faults.armed("store.put:error"):
        d.train([("pos", Datum({"f0": 1.0}))])
        with pytest.raises(faults.FaultInjected):
            up.tick(d, 999)
    assert counts.get("store.put_errors", 0) >= 1
    # the chain on disk still replays to its pre-fault head
    _, meta = store.materialize(node="n1")
    assert meta["chain_len"] == 1


def test_compact_fault_is_advisory(tmp_path):
    store = _mkstore(tmp_path)
    d = _clf_driver()
    _upload_chain(store, d, ticks=3)
    with faults.armed("store.compact:error"):
        with pytest.raises(faults.FaultInjected):
            store.compact(node="n1")
    # nothing was deleted; the chain replays exactly as before
    _, meta = store.materialize(node="n1")
    assert meta["chain_len"] == 2


# -- server integration: warm boot, save/load, degrade-to-cold ----------------


def _clf_args(tmp_path, **over):
    base = dict(engine="classifier", listen_addr="127.0.0.1",
                datadir=str(tmp_path / "data"), timeout=10.0,
                store_dir=str(tmp_path / "store"), store_interval=30.0,
                interval_sec=1e9, interval_count=1 << 30)
    base.update(over)
    os.makedirs(base["datadir"], exist_ok=True)
    return ServerArgs(**base)


def _train_and_tick(srv, rows=40, seed=3):
    rng = np.random.default_rng(seed)
    srv.driver.train([("pos" if rng.random() < 0.5 else "neg",
                       Datum({"f0": float(rng.normal()),
                              "f1": float(rng.normal())}))
                      for _ in range(rows)])
    # bypass the interval throttle: tests tick the uploader directly
    srv.store_uploader.tick(srv.driver, int(srv.driver.update_count))


def test_warm_boot_restores_identical_model(tmp_path):
    s1 = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
    s1.start(0)
    try:
        _train_and_tick(s1)
        probe = Datum({"f0": 0.5, "f1": -0.5})
        before = s1.driver.classify([probe])
    finally:
        s1.stop()  # hard kill: stop() persists nothing
    s2 = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
    s2.start(0)
    try:
        assert s2.warmboot["outcome"] == "warm"
        assert s2.warmboot["model_version"] == 40
        after = s2.driver.classify([probe])
        assert _tree_equal(before, after)
        st = list(s2.get_status().values())[0]
        assert st["warmboot.outcome"] == "warm"
        assert st["store.records_full"] >= 1
    finally:
        s2.stop()


def test_flaky_store_degrades_warm_to_cold_never_wrong(tmp_path):
    s1 = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
    s1.start(0)
    try:
        _train_and_tick(s1)
    finally:
        s1.stop()
    before = events.hlc_now()
    # every store read corrupts: warm boot must refuse the bytes and
    # fall back to a cold boot — never load a CRC-broken model
    with faults.armed("store.get:bitflip"):
        s2 = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
        s2.start(0)
        try:
            assert s2.warmboot["outcome"] == "degraded_to_cold"
            assert s2.driver.update_count == 0  # pristine, not partial
            counters = s2.rpc.trace.counters()
            assert counters.get("warmboot.degraded_to_cold", 0) == 1
            assert counters.get("store.crc_refused", 0) >= 1
            evs = s2.rpc.trace.events.snapshot(grep="degraded_to_cold")
            assert evs and evs[-1]["subsystem"] == "warmboot"
        finally:
            s2.stop()
    # the store's own CRC refusals ride the process journal
    evs = events.default_journal().snapshot(since=before, grep="crc_refused")
    assert evs and evs[-1]["subsystem"] == "store"


def test_no_snapshot_cold_boot_counted(tmp_path):
    srv = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
    srv.start(0)
    try:
        assert srv.warmboot["outcome"] == "cold"
        assert srv.rpc.trace.counters().get("warmboot.no_snapshot", 0) == 1
    finally:
        srv.stop()


def test_save_reply_carries_store_id_and_load_accepts_it(tmp_path):
    s1 = EngineServer("classifier", CLF_CONF, _clf_args(tmp_path))
    s1.start(0)
    try:
        _train_and_tick(s1)
        probe = Datum({"f0": 1.5, "f1": 0.25})
        want = s1.driver.classify([probe])
        reply = s1.save("t", "snap1")
        store_keys = [v for k, v in reply.items()
                      if str(k).startswith("store:")]
        assert len(store_keys) == 1 and store_keys[0].endswith(".jub")
    finally:
        s1.stop()
    # a FRESH node (empty datadir) loads by explicit store key...
    args2 = _clf_args(tmp_path, datadir=str(tmp_path / "data2"),
                      store_warmboot=False)
    s2 = EngineServer("classifier", CLF_CONF, args2)
    s2.start(0)
    try:
        assert s2.load("t", "store:" + store_keys[0])
        assert _tree_equal(s2.driver.classify([probe]), want)
        s2.driver.clear()
        # ...and by plain id, via the store-scan fallback when the
        # local checkpoint file does not exist
        assert s2.load("t", "snap1")
        assert _tree_equal(s2.driver.classify([probe]), want)
    finally:
        s2.stop()


# -- reshard-on-restore through the store -------------------------------------


def _nn_args(tmp_path, name="nn", **over):
    base = dict(engine="nearest_neighbor", coordinator="(shared)",
                name=name, listen_addr="127.0.0.1",
                datadir=str(tmp_path / "data"), timeout=30.0,
                store_dir=str(tmp_path / "store"), store_interval=30.0,
                interval_sec=1e9, interval_count=1 << 30)
    base.update(over)
    os.makedirs(base["datadir"], exist_ok=True)
    return ServerArgs(**base)


def _nn_boot(tmp_path, coord_store, **over):
    srv = EngineServer("nearest_neighbor", NN_CONF,
                       _nn_args(tmp_path, **over),
                       coord=MemoryCoordinator(coord_store))
    srv.start(0)
    return srv


def _nn_datum(i):
    return Datum({"f0": float(i) + 1.0, "f1": float(i % 7) + 1.0})


def _direct_rows(tmp_path, engine="nearest_neighbor"):
    """Ground truth: every row from every node's snapshot, loaded
    directly from the store's checkpoint envelopes (no server)."""
    store = ModelStore(LocalDirBackend(str(tmp_path / "store")),
                       cluster="nn", engine=engine)
    rows = {}
    for _node, (blob, _meta) in store.materialize_all().items():
        system_b, user_b = read_envelope(blob, "direct")
        system = unpack_obj(system_b)
        scratch = create_driver(engine, json.loads(system["config"]))
        _ver, state = unpack_obj(user_b)
        scratch.unpack(state)
        for row in scratch.get_rows():
            rows[row[0]] = pack_obj(row[1:])
    return rows


def _fleet_rows(servers):
    rows = {}
    for s in servers:
        for row in s.driver.get_rows():
            got = pack_obj(row[1:])
            assert rows.get(row[0], got) == got, \
                f"row {row[0]} differs between fleet members"
            rows[row[0]] = got
    return rows


def _reshard_cycle(tmp_path, n_from, n_to, rows=48):
    """Boot ``n_from`` NN servers on a shared store, spread rows across
    them, upload, hard-kill, boot ``n_to`` fresh servers on the SAME
    store, restore fleet-wide, and return (direct, restored) row maps."""
    coord = _Store()
    fleet = [_nn_boot(tmp_path, coord) for _ in range(n_from)]
    try:
        for i in range(rows):
            fleet[i % n_from].driver.set_row(f"row{i:03d}", _nn_datum(i))
        for s in fleet:
            s.store_uploader.tick(s.driver, int(s.driver.update_count))
    finally:
        for s in fleet:
            s.stop()
    direct = _direct_rows(tmp_path)
    assert len(direct) == rows
    coord2 = _Store()
    fleet2 = [_nn_boot(tmp_path, coord2, store_warmboot=False)
              for _ in range(n_to)]
    try:
        # wait until every member sees the full ring before restoring
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(len(s.cluster_cht().members) == n_to for s in fleet2):
                break
            time.sleep(0.05)
        for s in fleet2:
            with RpcClient("127.0.0.1", s.rpc.port, timeout=60.0) as c:
                doc = c.call("store_restore", "nn", 0)
            assert doc.get("restored"), doc
        restored = _fleet_rows(fleet2)
    finally:
        for s in fleet2:
            s.stop()
    return direct, restored


def test_reshard_restore_1_to_8(tmp_path):
    direct, restored = _reshard_cycle(tmp_path, 1, 8)
    assert restored == direct


def test_reshard_restore_8_to_2(tmp_path):
    direct, restored = _reshard_cycle(tmp_path, 8, 2)
    assert restored == direct
