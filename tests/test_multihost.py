"""Multi-host init helper tests — single-host no-op paths + endpoint
publication through the coordination store. (Real multi-process init
needs N hosts; the helper's resolution logic is what's testable here.)
"""

from __future__ import annotations

import pytest

from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.parallel import multihost


def test_single_host_noop():
    assert multihost.initialize() is False
    assert multihost.initialize(num_processes=1,
                                coordinator_address="x:1") is False


def test_process0_requires_address():
    store = _Store()
    coord = MemoryCoordinator(store)
    with pytest.raises(ValueError):
        multihost.initialize(coord=coord, process_id=0, num_processes=4)


def test_single_host_never_polls_or_publishes():
    store = _Store()
    coord = MemoryCoordinator(store)
    # num_processes=1 short-circuits before any publish/poll/raise
    assert multihost.initialize(coordinator_address="10.0.0.1:8476",
                                coord=coord, process_id=0,
                                num_processes=1) is False
    assert coord.read(multihost.JAX_COORD_PATH) is None


def test_publish_endpoint_and_failure():
    store = _Store()
    coord = MemoryCoordinator(store)
    multihost.publish_endpoint(coord, "10.0.0.1:8476")
    assert coord.read(multihost.JAX_COORD_PATH) == b"10.0.0.1:8476"
    coord.close()
    with pytest.raises(RuntimeError, match="publish"):
        multihost.publish_endpoint(coord, "10.0.0.1:9999")  # closed session


def test_worker_resolves_endpoint_from_store():
    store = _Store()
    coord = MemoryCoordinator(store)
    multihost.publish_endpoint(coord, "10.0.0.1:8476")
    # worker with no static address finds it; num_processes=1 keeps this a
    # no-op instead of blocking on a real distributed join
    assert multihost.initialize(coord=MemoryCoordinator(store), process_id=3,
                                num_processes=1) is False


def test_worker_times_out_loudly():
    """No published endpoint → raise, never a silent single-host split."""
    store = _Store()
    with pytest.raises(TimeoutError, match="process 0"):
        multihost.initialize(coord=MemoryCoordinator(store), process_id=2,
                             num_processes=4, resolve_timeout=0.1)


def test_endpoint_is_ephemeral():
    """A dead process 0's endpoint must vanish with its session."""
    store = _Store()
    p0 = MemoryCoordinator(store)
    multihost.publish_endpoint(p0, "10.0.0.1:8476")
    p0.close()  # fleet incarnation dies
    assert MemoryCoordinator(store).read(multihost.JAX_COORD_PATH) is None


def test_collective_capabilities_single_host():
    """The ops-facing capability probe (can this member ride
    --mix-compress int8?): a single-host world always can — one
    process, no cross-process collectives needed."""
    caps = multihost.collective_capabilities()
    assert caps["world"] == 1
    assert caps["distributed"] is False
    assert caps["quantized_transport"] is True
    assert isinstance(caps["backend"], str) and caps["backend"]
