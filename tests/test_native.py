"""Native library tests: compile-on-demand via g++, bit-parity with the
Python paths, and the C splitter plugin ABI (the dlopen seam of
SURVEY.md §2.8 done natively).
"""

from __future__ import annotations

import os
import subprocess
import zlib

import numpy as np
import pytest

from jubatus_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="g++ unavailable / native build failed"
)


def test_crc32_matches_zlib(rng):
    for size in (0, 1, 7, 256, 4096):
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        assert native.crc32(data) == (zlib.crc32(data) & 0xFFFFFFFF)


def test_hash_names_matches_python():
    from jubatus_tpu.core.fv.hashing import FeatureHasher

    hasher = FeatureHasher(dim_bits=16)
    names = [f"key${i}@space#bin/bin" for i in range(500)] + ["", "日本語テスト"]
    got = native.hash_names(names, hasher._mask)
    want = [hasher.index(n, remember=False) for n in names]
    assert got.tolist() == want


def test_index_many_uses_native_and_remembers(monkeypatch):
    from jubatus_tpu.core.fv.hashing import FeatureHasher

    monkeypatch.setenv("JUBATUS_TPU_NATIVE", "1")  # native path is opt-in
    hasher = FeatureHasher(dim_bits=16)
    names = ["alpha", "beta", "gamma"]
    idxs = hasher.index_many(names)
    assert idxs == [hasher.index(n, remember=False) for n in names]
    assert hasher.name_of(idxs[0]) == "alpha"


def test_converter_convert_same_with_and_without_native(monkeypatch):
    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.core.fv.converter import make_fv_converter

    conf = {
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "tf", "global_weight": "bin"}],
        "num_rules": [{"key": "*", "type": "num"}],
    }
    d = Datum({"txt": "a b a c", "x": 2.5})
    monkeypatch.setenv("JUBATUS_TPU_NATIVE", "1")
    with_native = make_fv_converter(conf).convert(d)
    monkeypatch.setenv("JUBATUS_TPU_NATIVE", "0")
    without = make_fv_converter(conf).convert(d)
    assert with_native == without


@pytest.fixture(scope="module")
def sample_splitter_so(tmp_path_factory):
    src = os.path.join(native.NATIVE_DIR, "sample_ngram_splitter.cpp")
    out = os.path.join(native.BUILD_DIR, "libsample_ngram_splitter.so")
    if native._stale(src, out) and not native._compile(src, out):
        pytest.skip("cannot build sample splitter")
    return out


def test_native_splitter_plugin(sample_splitter_so):
    split = native.load_native_splitter(sample_splitter_so, {"char_num": "2"})
    assert split("abcd") == ["ab", "bc", "cd"]
    assert split("a") == []


def test_native_splitter_through_converter(sample_splitter_so):
    from jubatus_tpu.core.datum import Datum
    from jubatus_tpu.core.fv.converter import make_fv_converter

    conf = {
        "string_types": {
            "bigram": {"method": "dynamic", "path": sample_splitter_so,
                       "char_num": "2"},
        },
        "string_rules": [{"key": "*", "type": "bigram",
                          "sample_weight": "bin", "global_weight": "bin"}],
    }
    named = make_fv_converter(conf).convert_named(Datum({"t": "abc"}))
    terms = {k.split("$")[1].split("@")[0] for k in named}
    assert terms == {"ab", "bc"}


def test_native_splitter_bad_params(sample_splitter_so):
    from jubatus_tpu.core.fv.converter import ConverterError

    with pytest.raises(ConverterError, match="rejected"):
        native.load_native_splitter(sample_splitter_so, {"char_num": "0"})


def test_make_builds_both_libraries():
    res = subprocess.run(["make", "-C", native.NATIVE_DIR],
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    assert os.path.exists(os.path.join(native.BUILD_DIR, "libjt_native.so"))
