"""Native ingest fast path (native/fast_ingest.cpp + rpc raw spans).

The C++ parser must be BIT-IDENTICAL to the Python converter pipeline
(feature names, crc32 hashing, dedupe/sort, f64 accumulation -> f32) —
these tests fuzz that parity and drive the full server fast path,
including fallback behavior for wire shapes the parser declines.
"""

from __future__ import annotations

import json
import random

import msgpack
import numpy as np
import pytest

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv.converter import make_fv_converter
from jubatus_tpu.native import ingest

pytestmark = pytest.mark.skipif(
    not ingest.available(), reason="native toolchain unavailable")

MIXED_CONV = {
    "string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "bin"},
        {"key": "s*", "type": "str", "sample_weight": "bin",
         "global_weight": "bin"},
    ],
    "num_rules": [
        {"key": "*", "type": "num"},
        {"key": "n*", "type": "log"},
        {"key": "*", "type": "str"},
    ],
}


def _rand_datum(rng):
    words = ["win", "money", "now", "meet", "lunch", "café", "日本語", ""]
    sv = [(rng.choice(["subject", "sbody", "txt"]),
           " ".join(rng.choice(words) for _ in range(rng.randint(0, 6))))
          for _ in range(rng.randint(0, 3))]
    nv = [(rng.choice(["n1", "num2", "f3"]),
           rng.choice([0.0, 1.0, -2.5, 3.25, 7, 123456, 0.1, 1e16,
                       -0.0001, rng.uniform(-10, 10)]))
          for _ in range(rng.randint(0, 4))]
    return Datum(string_values=sv, num_values=nv)


def _expected(pyconv, datum):
    return [(int(a), float(np.float32(b))) for a, b in pyconv.convert(datum)]


def _got(idx_row, val_row):
    return [(int(a), float(b)) for a, b in zip(idx_row, val_row) if a != 0]


def test_parity_mixed_workload():
    p = ingest.IngestParser(
        ingest.spec_from_converter_config(MIXED_CONV), 20)
    pyconv = make_fv_converter(MIXED_CONV, dim_bits=20)
    rng = random.Random(7)
    data = [("lab%d" % rng.randint(0, 3), _rand_datum(rng))
            for _ in range(400)]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    labels, idx, val = p.parse(raw)
    for i, (l, d) in enumerate(data):
        assert labels[i] == l
        assert _got(idx[i], val[i]) == _expected(pyconv, d), (i, l)


def test_parity_legacy_wire_and_num_formats():
    conv = {"num_rules": [{"key": "*", "type": "str"}],
            "string_rules": [{"key": "*", "type": "space",
                              "sample_weight": "log_tf",
                              "global_weight": "bin"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 18)
    pyconv = make_fv_converter(conv, dim_bits=18)
    vals = [0.0, -0.0, 1.0, -1.0, 0.5, -0.0001, 0.0001, 1e-5, -1e-5, 1e16,
            1e15 + 0.5, 123456789.125, 3.141592653589793, 2.5e-10, 9.9e15,
            1.00000000001, 1e16 + 2.0, 4.5e18]
    rng = random.Random(9)
    vals += [rng.uniform(-1, 1) * 10 ** rng.randint(-15, 15)
             for _ in range(200)]
    data = [("x", Datum(num_values=[("k", v)],
                        string_values=[("t", "a b b a")])) for v in vals]
    for use_bin in (True, False):  # modern + legacy request wire
        raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]],
                            use_bin_type=use_bin)
        labels, idx, val = p.parse(raw)
        for i, (_, d) in enumerate(data):
            assert _got(idx[i], val[i]) == _expected(pyconv, d), vals[i]


def test_numeric_targets_regression_wire():
    conv = {"num_rules": [{"key": "*", "type": "num"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 16)
    data = [[1.5, Datum({"x": 2.0}).to_msgpack()],
            [-0.25, Datum({"x": -1.0}).to_msgpack()]]
    labels, idx, val = p.parse(msgpack.packb(["c", data]))
    assert isinstance(labels, np.ndarray)
    np.testing.assert_allclose(labels, [1.5, -0.25])
    assert idx.shape == (2, 8)


def test_huge_integral_and_mixed_labels_fall_back():
    conv = {"num_rules": [{"key": "*", "type": "str"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 16)
    raw = msgpack.packb(
        ["c", [["x", Datum(num_values=[("k", 1e100)]).to_msgpack()]]])
    assert p.parse(raw) is None  # str(int(1e100)) not reproducible in C++
    mixed = msgpack.packb(
        ["c", [["x", Datum({"k": 1.0}).to_msgpack()],
               [3, Datum({"k": 1.0}).to_msgpack()]]])
    assert p.parse(mixed) is None  # mixed label kinds


def test_spec_rejects_unsupported_configs():
    assert ingest.spec_from_converter_config(None) is None
    assert ingest.spec_from_converter_config({}) is None
    # idf IS supported since round 3 (the parser takes the WeightManager's
    # dense df tables); user "weight" still needs the user-weight map
    assert ingest.spec_from_converter_config({
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "bin",
                          "global_weight": "idf"}]}) is not None
    assert ingest.spec_from_converter_config({
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "bin",
                          "global_weight": "weight"}]}) is None
    # filters change the datum before rules run
    assert ingest.spec_from_converter_config({
        "num_rules": [{"key": "*", "type": "num"}],
        "num_filter_rules": [{"key": "*", "type": "x", "suffix": "y"}],
    }) is None
    # combination rules ARE supported since round 4 (named cross product
    # in C++); unknown combination methods still decline
    assert ingest.spec_from_converter_config({
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_rules": [{"key_left": "*", "key_right": "*",
                               "type": "mul"}]}) is not None
    assert ingest.spec_from_converter_config({
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_types": {"odd": {"method": "concat"}},
        "combination_rules": [{"key_left": "*", "key_right": "*",
                               "type": "odd"}]}) is None
    # ngram IS supported since round 3 (utf-8 code-point slicing in C++);
    # regexp splitters still are not
    assert ingest.spec_from_converter_config({
        "string_types": {"bigram": {"method": "ngram", "char_num": "2"}},
        "string_rules": [{"key": "*", "type": "bigram",
                          "sample_weight": "bin",
                          "global_weight": "bin"}]}) is not None
    assert ingest.spec_from_converter_config({
        "string_types": {"rx": {"method": "regexp", "pattern": "a+"}},
        "string_rules": [{"key": "*", "type": "rx",
                          "sample_weight": "bin",
                          "global_weight": "bin"}]}) is None


# -- server integration -------------------------------------------------------

SERVER_CONV = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "bin", "global_weight": "bin"}],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}


def _train_data():
    return [["spam", Datum({"t": "win money now", "n": 1.0})],
            ["ham", Datum({"t": "meet at noon", "n": -1.0})]] * 8


def test_server_fast_path_matches_converter_path():
    """The same traffic through the fast server and a converter-only
    server must produce identical models (classify scores equal)."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    fast = EngineServer("classifier", SERVER_CONV,
                        args=ServerArgs(engine="classifier"))
    fast_port = fast.start(0)
    slow = EngineServer("classifier", SERVER_CONV,
                        args=ServerArgs(engine="classifier"))
    slow_port = slow.start(0)
    slow.rpc._raw_methods.clear()  # force the converter path
    try:
        with ClassifierClient("127.0.0.1", fast_port, "t") as cf, \
                ClassifierClient("127.0.0.1", slow_port, "t") as cs:
            assert cf.train(_train_data()) == 16
            assert cs.train(_train_data()) == 16
            probe = [Datum({"t": "win money", "n": 0.5})]
            (rf,), (rs,) = cf.classify(probe), cs.classify(probe)
            assert sorted(rf) == sorted(rs)
        st = next(iter(fast.get_status().values()))
        assert st["microbatch.train_raw.item_count"] == 16
        assert st["microbatch.train.item_count"] == 0
        st2 = next(iter(slow.get_status().values()))
        assert st2["microbatch.train.item_count"] == 16
    finally:
        fast.stop()
        slow.stop()


def test_server_fast_path_regression():
    from jubatus_tpu.client import RegressionClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "PA", "parameter": {"sensitivity": 0.1,
                                          "regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    srv = EngineServer("regression", conf,
                       args=ServerArgs(engine="regression"))
    port = srv.start(0)
    try:
        with RegressionClient("127.0.0.1", port, "t") as c:
            data = [[float(2 * x), Datum({"x": float(x)})]
                    for x in range(-8, 9)] * 4
            assert c.train(data) == len(data)
            (est,) = c.estimate([Datum({"x": 3.0})])
            assert 2.0 < est < 10.0
        st = next(iter(srv.get_status().values()))
        assert st["microbatch.train_raw.item_count"] == len(data) * 1
    finally:
        srv.stop()


def test_server_ineligible_config_uses_converter_path():
    """A config the parser cannot express (regexp splitter) must keep the
    converter path (no raw registration)."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "PA", "parameter": {},
            "converter": {
                "string_types": {"rx": {"method": "regexp",
                                        "pattern": "[a-z]+"}},
                "string_rules": [
                    {"key": "*", "type": "rx", "sample_weight": "tf",
                     "global_weight": "bin"}]}}
    srv = EngineServer("classifier", conf,
                       args=ServerArgs(engine="classifier"))
    port = srv.start(0)
    try:
        assert "train" not in srv.rpc._raw_methods
        with ClassifierClient("127.0.0.1", port, "t") as c:
            assert c.train([["a", Datum({"t": "x y"})],
                            ["b", Datum({"t": "y z"})]]) == 2
        st = next(iter(srv.get_status().values()))
        assert st["microbatch.train.item_count"] == 2
    finally:
        srv.stop()


def test_server_idf_fast_path_matches_converter_path():
    """An idf config rides the fast path now — and its model must stay
    IDENTICAL to a converter-only server fed the same traffic (df
    observation order and idf scaling replayed exactly in C++)."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "AROW", "parameter": {"regularization_weight": 1.0},
            "converter": {"string_rules": [
                {"key": "*", "type": "space", "sample_weight": "tf",
                 "global_weight": "idf"}]}}
    fast = EngineServer("classifier", conf,
                        args=ServerArgs(engine="classifier"))
    fast_port = fast.start(0)
    slow = EngineServer("classifier", conf,
                        args=ServerArgs(engine="classifier"))
    slow_port = slow.start(0)
    slow.rpc._raw_methods.clear()  # force the converter path
    try:
        assert "train" in fast.rpc._raw_methods
        data = [["spam", Datum({"t": "win money now now"})],
                ["ham", Datum({"t": "meet at noon"})],
                ["spam", Datum({"t": "money money fast"})],
                ["ham", Datum({"t": "noon lunch plan"})]]
        with ClassifierClient("127.0.0.1", fast_port, "t") as cf, \
                ClassifierClient("127.0.0.1", slow_port, "t") as cs:
            for _ in range(5):
                assert cf.train(data) == 4
                assert cs.train(data) == 4
            probe = [Datum({"t": "money now"}), Datum({"t": "noon plan"}),
                     Datum({"t": "unseen words"})]
            assert [sorted(r) for r in cf.classify(probe)] == \
                [sorted(r) for r in cs.classify(probe)]
        # fast server really used the raw path, and df state converged
        assert fast.coalescers["train_raw"].stats()["item_count"] == 20
        np.testing.assert_array_equal(
            fast.driver.converter.weights._df_diff,
            slow.driver.converter.weights._df_diff)
    finally:
        fast.stop()
        slow.stop()


def test_server_fallback_on_undecodable_fast_wire():
    """A train request whose first slot kind defies the engine (numeric
    label on a classifier) must fall back to the generic path and behave
    exactly as before the fast path existed."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer("classifier", SERVER_CONV,
                       args=ServerArgs(engine="classifier"))
    port = srv.start(0)
    try:
        with ClassifierClient("127.0.0.1", port, "t") as c:
            n = c.client.call("train", "t", [[3, Datum({"n": 1.0}).to_msgpack()],
                                             [4, Datum({"n": -1.0}).to_msgpack()]])
            assert n == 2  # generic path accepts any hashable label
            labels = c.get_labels()
            assert set(labels) == {3, 4}
    finally:
        srv.stop()


def test_hostile_lengths_error_not_abort():
    """A tiny request claiming 2^32 array elements must return a parse
    error (-> RPC error reply), never bad_alloc/terminate (code-review:
    the pre-allocation aborted the whole server)."""
    conv = {"num_rules": [{"key": "*", "type": "num"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 16)
    # [name, [[label, [sv_claiming_4B_pairs ...]]]]
    hostile = (b"\x92\xa1c\x91\x92\xa1x\x92"
               b"\xdd\xff\xff\xff\xff")  # array32 len 0xffffffff, no body
    assert p.parse(hostile) is None
    hostile2 = b"\x92\xa1c\x91\x92\xa1x\x92\x90\xdd\xff\xff\xff\xff"
    assert p.parse(hostile2) is None
    # the handle still works afterwards
    ok = msgpack.packb(["c", [["x", Datum({"k": 1.0}).to_msgpack()]]])
    assert p.parse(ok) is not None


def test_unicode_whitespace_tokenizes_like_python():
    """str.split() splits on Unicode whitespace; the fast path must hash
    the same tokens (code-review: isspace over bytes diverged on NBSP,
    U+3000, \\x1c — silently different models per path)."""
    conv = {"string_rules": [{"key": "*", "type": "space",
                              "sample_weight": "tf", "global_weight": "bin"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 20)
    pyconv = make_fv_converter(conv, dim_bits=20)
    texts = ["a\x1cb", "a\xa0b", "a　b", "a b c", "x\x85y",
             " lead", "trail ", "mixed \t 　 runs",
             "café\xa0日本語", "plain space only"]
    data = [("t", Datum(string_values=[("k", s)])) for s in texts]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    labels, idx, val = p.parse(raw)
    for i, (_, d) in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), repr(texts[i])


def test_fallback_counts_trace_span_once():
    """A RAW_FALLBACK request must appear once in trace.rpc.<m>.count
    (code-review: fast attempt + generic invoke double-counted)."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer("classifier", SERVER_CONV,
                       args=ServerArgs(engine="classifier"))
    port = srv.start(0)
    try:
        with ClassifierClient("127.0.0.1", port, "t") as c:
            # numeric labels -> parser declines -> generic path
            c.client.call("train", "t", [[3, Datum({"n": 1.0}).to_msgpack()]])
            (st,) = c.get_status().values()
        assert st["trace.rpc.train.count"] == 1
    finally:
        srv.stop()


def test_parse_datums_matches_converter():
    """The classify/estimate wire ([name, [datum, ...]]) parses to the
    same hashed batch the Python converter produces."""
    p = ingest.IngestParser(
        ingest.spec_from_converter_config(MIXED_CONV), 20)
    pyconv = make_fv_converter(MIXED_CONV, dim_bits=20)
    rng = random.Random(11)
    data = [_rand_datum(rng) for _ in range(100)]
    raw = msgpack.packb(["c", [d.to_msgpack() for d in data]])
    parsed = p.parse_datums(raw)
    assert parsed is not None
    idx, val = parsed
    for i, d in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), i
    # a train-shaped wire is NOT a datum list
    train_raw = msgpack.packb(["c", [["lb", data[0].to_msgpack()]]])
    assert p.parse_datums(train_raw) is None


def test_server_fast_classify_and_estimate_match_slow_path():
    from jubatus_tpu.client import ClassifierClient, RegressionClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer("classifier", SERVER_CONV,
                       args=ServerArgs(engine="classifier"))
    port = srv.start(0)
    slow = EngineServer("classifier", SERVER_CONV,
                        args=ServerArgs(engine="classifier"))
    sport = slow.start(0)
    slow.rpc._raw_methods.clear()
    try:
        assert "classify" in srv.rpc._raw_methods
        with ClassifierClient("127.0.0.1", port, "t") as cf, \
                ClassifierClient("127.0.0.1", sport, "t") as cs:
            cf.train(_train_data())
            cs.train(_train_data())
            probe = [Datum({"t": "win money", "n": 0.5}),
                     Datum({"t": "meet at noon"})]
            assert [sorted(r) for r in cf.classify(probe)] == \
                [sorted(r) for r in cs.classify(probe)]
    finally:
        srv.stop()
        slow.stop()

    conf = {"method": "PA", "parameter": {"sensitivity": 0.1,
                                          "regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    rsrv = EngineServer("regression", conf,
                        args=ServerArgs(engine="regression"))
    rport = rsrv.start(0)
    try:
        assert "estimate" in rsrv.rpc._raw_methods
        with RegressionClient("127.0.0.1", rport, "t") as c:
            c.train([[float(2 * x), Datum({"x": float(x)})]
                     for x in range(-8, 9)] * 4)
            ests = c.estimate([Datum({"x": 3.0}), Datum({"x": -2.0})])
            assert 2.0 < ests[0] < 10.0 and -8.0 < ests[1] < -1.0
    finally:
        rsrv.stop()


def test_parser_survives_mutation_fuzz():
    """Randomly mutated request bytes must yield a clean parse or a clean
    None — never a crash (the parser handles attacker-controlled bytes
    before any auth layer)."""
    p = ingest.IngestParser(
        ingest.spec_from_converter_config(MIXED_CONV), 16)
    rng = random.Random(13)
    base = msgpack.packb(
        ["c", [["lbl%d" % i, _rand_datum(rng).to_msgpack()]
               for i in range(8)]])
    for trial in range(1500):
        raw = bytearray(base)
        for _ in range(rng.randint(1, 6)):
            pos = rng.randrange(len(raw))
            raw[pos] = rng.randrange(256)
        if rng.random() < 0.3:
            raw = raw[:rng.randrange(len(raw))]
        out = p.parse(bytes(raw))
        if out is not None:
            labels, idx, val = out
            assert idx.shape == val.shape
        out2 = p.parse_datums(bytes(raw))
        if out2 is not None:
            assert out2[0].shape == out2[1].shape


def test_parity_ngram_splitter():
    """ngram string types (round-3 coverage extension): the C++ sliding
    window must match converter.py's text[i:i+n] over a surrogateescape-
    decoded str — code points, not bytes, including malformed UTF-8."""
    conv = {
        "string_types": {"bigram": {"method": "ngram", "char_num": "2"},
                         "tri": {"method": "ngram", "char_num": "3"}},
        "string_rules": [
            {"key": "*", "type": "bigram", "sample_weight": "tf",
             "global_weight": "bin"},
            {"key": "t*", "type": "tri", "sample_weight": "log_tf",
             "global_weight": "bin"},
        ],
    }
    spec = ingest.spec_from_converter_config(conv)
    assert spec is not None
    p = ingest.IngestParser(spec, 18)
    pyconv = make_fv_converter(conv, dim_bits=18)
    texts = ["", "a", "ab", "abc", "ababab", "café au lait", "日本語のテキスト",
             "mixed 日本 text", "aa" * 40,
             b"bad\xffutf8\xc3(seq".decode("utf-8", "surrogateescape"),
             b"\xe2\x82".decode("utf-8", "surrogateescape"),  # truncated
             # shortest-form violations: CPython decodes each byte as one
             # surrogate; the C++ walker must count the same code points
             b"\xc0\x80a".decode("utf-8", "surrogateescape"),   # overlong NUL
             b"\xe0\x80\x80b".decode("utf-8", "surrogateescape"),
             b"\xed\xa0\x80c".decode("utf-8", "surrogateescape"),  # surrogate
             b"\xf0\x80\x80\x80d".decode("utf-8", "surrogateescape"),
             b"\xf4\x90\x80\x80e".decode("utf-8", "surrogateescape"),  # >10FFFF
             b"\xf5\x80\x80\x80f".decode("utf-8", "surrogateescape"),
             b"a\xc2 b\xe1\x80 c\xf3\x80\x80".decode("utf-8",
                                                     "surrogateescape"),
             # overlong-encoded SPACE (0xC0 0xA0): must NOT split as space
             b"x\xc0\xa0y".decode("utf-8", "surrogateescape")]
    rng = random.Random(21)
    alphabet = "abφ語 \t"
    texts += ["".join(rng.choice(alphabet) for _ in range(rng.randint(0, 30)))
              for _ in range(120)]
    data = [("L", Datum(string_values=[(rng.choice(["txt", "body"]), t)]))
            for t in texts]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]],
                        use_bin_type=True, unicode_errors="surrogateescape")
    labels, idx, val = p.parse(raw)
    for i, (_, d) in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), texts[i]


def test_parity_space_splitter_hostile_utf8():
    """The SPACE splitter shares the validated decoder: overlong-encoded
    whitespace (e.g. 0xC0 0xA0 for SPACE) must be treated as non-space
    surrogates exactly like Python does."""
    conv = {"string_rules": [{"key": "*", "type": "space",
                              "sample_weight": "tf",
                              "global_weight": "bin"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 18)
    pyconv = make_fv_converter(conv, dim_bits=18)
    texts = [b"x\xc0\xa0y".decode("utf-8", "surrogateescape"),
             b"a\xe0\x80\x85b".decode("utf-8", "surrogateescape"),
             b"u\xc2\x85v".decode("utf-8", "surrogateescape"),  # real NEL
             b"q\xed\xa0\x80 r".decode("utf-8", "surrogateescape")]
    data = [("L", Datum(string_values=[("t", t)])) for t in texts]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]],
                        use_bin_type=True, unicode_errors="surrogateescape")
    labels, idx, val = p.parse(raw)
    for i, (_, d) in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), texts[i]


def test_ngram_bad_char_num_not_expressible():
    for bad in ("0", "-1", "x", None, "4294967297"):
        conv = {"string_types": {"g": {"method": "ngram", "char_num": bad}},
                "string_rules": [{"key": "*", "type": "g",
                                  "sample_weight": "bin",
                                  "global_weight": "bin"}]}
        assert ingest.spec_from_converter_config(conv) is None


def test_parity_idf_global_weight():
    """idf rides the fast path (round 3): jt_ingest_parse_w must replay
    converter.convert(update_weights=True)'s EXACT per-document protocol —
    observe distinct idf indices first, then scale by log(ndocs/df), then
    merge by hashed index — so a request-by-request sequence stays
    bit-identical to the Python converter fed the same stream."""
    conv = {"string_rules": [{"key": "*", "type": "space",
                              "sample_weight": "tf",
                              "global_weight": "idf"}],
            "num_rules": [{"key": "*", "type": "num"}]}
    spec = ingest.spec_from_converter_config(conv)
    assert spec is not None
    p = ingest.IngestParser(spec, 18)
    assert p.needs_weights
    pyconv = make_fv_converter(conv, dim_bits=18)
    fast = make_fv_converter(conv, dim_bits=18)  # owns the fast path's df

    rng = random.Random(33)
    words = ["alpha", "beta", "gamma", "delta", "eps", "zeta", "eta"]
    for req in range(6):
        data = []
        for _ in range(rng.randint(1, 30)):
            text = " ".join(rng.choice(words)
                            for _ in range(rng.randint(0, 8)))
            nv = [("n", rng.uniform(-2, 2))] if rng.random() < 0.5 else []
            data.append(("L%d" % rng.randint(0, 2),
                         Datum(string_values=[("t", text)], num_values=nv)))
        raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
        with fast.weights.lock:
            out = p.parse(raw, weights=fast.weights)
        assert out is not None
        labels, idx, val = out
        for i, (_, d) in enumerate(data):
            exp = [(int(a), float(np.float32(b)))
                   for a, b in pyconv.convert(d, update_weights=True)]
            assert _got(idx[i], val[i]) == exp, (req, i)
    # df state identical after the whole stream
    np.testing.assert_array_equal(fast.weights._df_diff,
                                  pyconv.weights._df_diff)
    assert fast.weights.ndocs == pyconv.weights.ndocs

    # the QUERY path reads idf without observing
    before = fast.weights.ndocs
    qraw = msgpack.packb(["c", [Datum({"t": "alpha beta"}).to_msgpack()]])
    with fast.weights.lock:
        qi, qv = p.parse_datums(qraw, weights=fast.weights)
    assert fast.weights.ndocs == before
    exp = [(int(a), float(np.float32(b)))
           for a, b in pyconv.convert(Datum({"t": "alpha beta"}))]
    assert _got(qi[0], qv[0]) == exp

    # an idf spec without weights must decline, not crash
    assert p.parse(raw) is None
    assert p.parse_datums(qraw) is None


def test_parity_num_filters():
    """num filters ride the fast path (round 3): every builtin transform,
    applied sequentially over the GROWING kv list (a later filter sees an
    earlier filter's appended output), bit-identical to converter.py."""
    conv = {
        "num_filter_types": {
            "a5": {"method": "add", "value": "5.5"},
            "lin": {"method": "linear_normalization", "min": "-2",
                    "max": "3"},
            "gz": {"method": "gaussian_normalization", "average": "0.5",
                   "standard_deviation": "2.0"},
            "sig": {"method": "sigmoid_normalization", "gain": "1.5",
                    "bias": "0.25"},
        },
        "num_filter_rules": [
            {"key": "x*", "type": "a5", "suffix": "+5"},
            {"key": "*+5", "type": "sig", "suffix": "$s"},  # chained
            {"key": "y", "type": "lin", "suffix": "_n"},
            {"key": "*", "type": "gz", "suffix": "@g"},
        ],
        "num_rules": [{"key": "*", "type": "num"},
                      {"key": "*_n", "type": "str"}],
    }
    spec = ingest.spec_from_converter_config(conv)
    assert spec is not None
    p = ingest.IngestParser(spec, 18)
    pyconv = make_fv_converter(conv, dim_bits=18)
    rng = random.Random(44)
    data = []
    for _ in range(150):
        nv = [(rng.choice(["x1", "x2", "y", "z"]),
               rng.choice([0.0, -3.0, 2.5, 7.25,
                           rng.uniform(-10, 10)]))
              for _ in range(rng.randint(0, 5))]
        data.append(("L", Datum(num_values=nv)))
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    out = p.parse(raw)
    assert out is not None
    labels, idx, val = out
    for i, (_, d) in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), (i, d.num_values)


def test_num_filter_unknown_method_declines():
    conv = {"num_filter_types": {"w": {"method": "wavelet"}},
            "num_filter_rules": [{"key": "*", "type": "w", "suffix": "#"}],
            "num_rules": [{"key": "*", "type": "num"}]}
    assert ingest.spec_from_converter_config(conv) is None


def test_sigmoid_overflow_falls_back_like_python_raises():
    """math.exp raises OverflowError past ~709; the C++ path must decline
    (fall back) so both paths fail the request identically instead of the
    fast path silently emitting 0.0."""
    conv = {"num_filter_types": {"s": {"method": "sigmoid_normalization",
                                       "gain": "1.5", "bias": "0"}},
            "num_filter_rules": [{"key": "*", "type": "s", "suffix": "#"}],
            "num_rules": [{"key": "*", "type": "num"}]}
    p = ingest.IngestParser(ingest.spec_from_converter_config(conv), 16)
    pyconv = make_fv_converter(conv, dim_bits=16)
    ok = msgpack.packb(["c", [["x", Datum({"k": -400.0}).to_msgpack()]]])
    assert p.parse(ok) is not None  # exp(600) is finite
    bad = msgpack.packb(["c", [["x", Datum({"k": -500.0}).to_msgpack()]]])
    assert p.parse(bad) is None     # exp(750) overflows -> decline
    with pytest.raises(OverflowError):
        pyconv.convert(Datum({"k": -500.0}))


COMBO_CONV = {
    "string_rules": [
        {"key": "*", "type": "str", "sample_weight": "bin",
         "global_weight": "bin"},
    ],
    "num_rules": [{"key": "*", "type": "num"}],
    "combination_rules": [
        {"key_left": "*", "key_right": "*", "type": "mul"},
    ],
}


def test_parity_combination_rules():
    """The reference's arow_combinational_feature.json converter block
    rides the fast path bit-identically (VERDICT r3 item 6): cross
    product over named features, canonical pair order, mul values."""
    spec = ingest.spec_from_converter_config(COMBO_CONV)
    assert spec is not None and "combo\tmul" in spec
    p = ingest.IngestParser(spec, 20)
    pyconv = make_fv_converter(COMBO_CONV, dim_bits=20)
    rng = random.Random(11)
    data = [("l%d" % rng.randint(0, 2), _rand_datum(rng))
            for _ in range(200)]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    labels, idx, val = p.parse(raw)
    for i, (l, d) in enumerate(data):
        assert labels[i] == l
        assert _got(idx[i], val[i]) == _expected(pyconv, d), (i, l)


def test_parity_combination_add_and_matchers():
    conv = {
        "string_rules": [
            {"key": "s*", "type": "space", "sample_weight": "tf",
             "global_weight": "bin"},
        ],
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_types": {"plus": {"method": "add"}},
        "combination_rules": [
            {"key_left": "*@num", "key_right": "*", "type": "plus"},
            {"key_left": "s*", "key_right": "*#tf/bin", "type": "mul"},
        ],
    }
    spec = ingest.spec_from_converter_config(conv)
    assert spec is not None
    p = ingest.IngestParser(spec, 18)
    pyconv = make_fv_converter(conv, dim_bits=18)
    rng = random.Random(13)
    data = [("x", _rand_datum(rng)) for _ in range(200)]
    raw = msgpack.packb(["c", [[l, d.to_msgpack()] for l, d in data]])
    _labels, idx, val = p.parse(raw)
    for i, (_l, d) in enumerate(data):
        assert _got(idx[i], val[i]) == _expected(pyconv, d), i


def test_combo_with_idf_declines():
    conv = {
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "tf",
             "global_weight": "idf"},
        ],
        "combination_rules": [
            {"key_left": "*", "key_right": "*", "type": "mul"},
        ],
    }
    assert ingest.spec_from_converter_config(conv) is None


def test_parity_combo_plan_replay_fixed_schema():
    """The combo plan (round 5): a request whose datums repeat one key
    schema replays the recorded cross product — names/hashes computed
    once — and must stay bit-identical to the Python converter for
    every datum, including across a mid-request schema CHANGE (plan
    rebuild) and a schema that collides a combined name with a base
    name (terms accumulate into the base slot)."""
    spec = ingest.spec_from_converter_config(COMBO_CONV)
    p = ingest.IngestParser(spec, 20)
    pyconv = make_fv_converter(COMBO_CONV, dim_bits=20)
    rng = random.Random(17)
    data = []
    # phase 1: fixed 6-key schema, varying values (plan hit after datum 0)
    for _ in range(60):
        data.append(("a", Datum(num_values=[
            (f"f{j}", rng.uniform(-5, 5)) for j in range(6)])))
    # phase 2: schema change (extra key) -> rebuild, then hits again
    for _ in range(60):
        data.append(("b", Datum(num_values=[
            (f"f{j}", rng.uniform(-5, 5)) for j in range(7)])))
    # phase 3: collision shape — a base key named like a combined pair
    # ("x@num&y@num" as a LITERAL key) plus x, y
    for _ in range(30):
        data.append(("c", Datum(num_values=[
            ("x", rng.uniform(-2, 2)), ("y", rng.uniform(-2, 2)),
            ("x@num&y@num", rng.uniform(-2, 2))])))
    raw = msgpack.packb(["c", [[lab, d.to_msgpack()] for lab, d in data]])
    labels, idx, val = p.parse(raw)
    for i, (lab, d) in enumerate(data):
        assert labels[i] == lab
        assert _got(idx[i], val[i]) == _expected(pyconv, d), i


TEXT_FILTER_CONV = {
    "string_filter_types": {
        "strip_digits": {"method": "regexp", "pattern": "[0-9]+",
                         "replace": ""}},
    "string_filter_rules": [
        {"key": "*", "type": "strip_digits", "suffix": "-nodigit"}],
    "string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "bin"}],
}


def test_parity_string_filters_hybrid():
    """String-filter configs ride the HYBRID fast path (round 5, VERDICT
    r4 #4): Python applies the regex (memoized per distinct input) by
    rewriting the request; tokenize/tf/hash stay in C++. Output must be
    bit-identical to the Python converter, including cascaded filters
    (a later rule matching an earlier rule's appended key)."""
    p = ingest.IngestParser.from_converter_config(TEXT_FILTER_CONV, 20)
    assert p is not None and p._prefilters is not None
    pyconv = make_fv_converter(TEXT_FILTER_CONV, dim_bits=20)
    rng = random.Random(23)
    words = ["abc123", "x9y", "2024", "plain", "日本7語", ""]
    data = []
    for _ in range(120):
        body = " ".join(rng.choice(words)
                        for _ in range(rng.randint(0, 8)))
        data.append((rng.choice("ab"), Datum({"body": body})))
    raw = msgpack.packb(["c", [[lab, d.to_msgpack()] for lab, d in data]])
    labels, idx, val = p.parse(raw)
    for i, (lab, d) in enumerate(data):
        assert labels[i] == lab
        assert _got(idx[i], val[i]) == _expected(pyconv, d), i
    # query path too
    rawq = msgpack.packb(["c", [d.to_msgpack() for _l, d in data]])
    qidx, qval = p.parse_datums(rawq)
    for i, (_lab, d) in enumerate(data):
        assert _got(qidx[i], qval[i]) == _expected(pyconv, d), i


def test_parity_cascaded_string_filters():
    conv = {
        "string_filter_types": {
            "strip_digits": {"method": "regexp", "pattern": "[0-9]+",
                             "replace": ""},
            "dash": {"method": "regexp", "pattern": " ",
                     "replace": "-"}},
        "string_filter_rules": [
            {"key": "*", "type": "strip_digits", "suffix": "-nd"},
            # matches the FIRST rule's appended key too (cascade)
            {"key": "*-nd", "type": "dash", "suffix": "-dashed"}],
        "string_rules": [
            {"key": "*", "type": "space", "sample_weight": "bin",
             "global_weight": "bin"}],
    }
    p = ingest.IngestParser.from_converter_config(conv, 18)
    assert p is not None
    pyconv = make_fv_converter(conv, dim_bits=18)
    d = Datum({"body": "a1 b2 c3"})
    raw = msgpack.packb(["c", [["x", d.to_msgpack()]]])
    _labels, idx, val = p.parse(raw)
    assert _got(idx[0], val[0]) == _expected(pyconv, d)


def test_string_filter_unknown_method_declines():
    conv = dict(TEXT_FILTER_CONV,
                string_filter_types={"odd": {"method": "mystery"}},
                string_filter_rules=[
                    {"key": "*", "type": "odd", "suffix": "-x"}])
    assert ingest.IngestParser.from_converter_config(conv, 20) is None
