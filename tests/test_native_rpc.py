"""Native C++ RPC front-end tests: same wire behavior as the Python
transport, exercised with the ordinary Python client (the transport is
invisible to callers, like the reference's mpio layer).
"""

from __future__ import annotations

import threading

import pytest

from jubatus_tpu.client import ClassifierClient, Datum
from jubatus_tpu.rpc import native_server
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.errors import RpcMethodNotFound, RpcTypeError

pytestmark = pytest.mark.skipif(
    not native_server.available(),
    reason="g++ unavailable / native rpc front-end build failed",
)


@pytest.fixture()
def srv():
    s = native_server.NativeRpcServer()
    s.register("echo", lambda x: x, arity=1)
    s.register("add", lambda a, b: a + b, arity=2)
    s.register("boom", lambda: 1 / 0, arity=0)
    s.serve_background(0, host="127.0.0.1")
    yield s
    s.stop()


def test_roundtrip_types(srv):
    with RpcClient("127.0.0.1", srv.port) as c:
        assert c.call("add", 2, 3) == 5
        assert c.call("echo", "héllo") == "héllo"
        assert c.call("echo", [1, [2, {"k": "v"}], b"\x00\xff"]) == \
            [1, [2, {"k": "v"}], b"\x00\xff"]
        assert c.call("echo", None) is None
        assert c.call("echo", 3.5) == 3.5


def test_error_taxonomy(srv):
    with RpcClient("127.0.0.1", srv.port) as c:
        with pytest.raises(RpcMethodNotFound):
            c.call("nope")
        with pytest.raises(RpcTypeError):
            c.call("add", 1)  # arity error
        with pytest.raises(Exception, match="division"):
            c.call("boom")
        assert c.call("add", 1, 1) == 2  # connection survives errors


def test_pipelining_same_connection(srv):
    """Many requests down one connection; responses correlate by msgid."""
    with RpcClient("127.0.0.1", srv.port) as c:
        for i in range(200):
            assert c.call("add", i, i) == 2 * i


def test_concurrent_clients(srv):
    errors = []

    def hammer(n):
        try:
            with RpcClient("127.0.0.1", srv.port) as c:
                for i in range(50):
                    assert c.call("add", n, i) == n + i
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(j,)) for j in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors


def test_notify_no_response(srv):
    hits = []
    srv.register("note", lambda x: hits.append(x), arity=1)
    with RpcClient("127.0.0.1", srv.port) as c:
        c.notify("note", "fire-and-forget")
        # a request after the notify proves framing stayed aligned
        assert c.call("add", 1, 2) == 3
    assert hits == ["fire-and-forget"]


def test_trace_spans_recorded(srv):
    with RpcClient("127.0.0.1", srv.port) as c:
        c.call("echo", "x")
    assert srv.trace.trace_status()["trace.rpc.echo.count"] >= 1


def test_engine_server_over_native_transport(monkeypatch):
    """Full engine stack on the C++ transport via JUBATUS_TPU_NATIVE_RPC."""
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1")
    from jubatus_tpu.server import EngineServer

    conf = {"method": "PA", "parameter": {},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    s = EngineServer("classifier", conf)
    assert isinstance(s.rpc, native_server.NativeRpcServer)
    port = s.start(0)
    try:
        c = ClassifierClient("127.0.0.1", port, "")
        assert c.train([["pos", Datum({"x": 1.0})],
                        ["neg", Datum({"x": -1.0})]]) == 2
        (res,) = c.classify([Datum({"x": 1.0})])
        assert max(res, key=lambda sc: sc[1])[0] == "pos"
        (st,) = c.get_status().values()
        assert st["trace.rpc.train.count"] == 1
        # the microbatch coalescer serves the native transport too — the
        # binders are transport-agnostic (server/microbatch.py)
        items = (st["microbatch.train.item_count"]
                 + st.get("microbatch.train_raw.item_count", 0))
        flushes = (st["microbatch.train.flush_count"]
                   + st.get("microbatch.train_raw.flush_count", 0))
        assert items == 2
        assert flushes == 1
        c.close()
    finally:
        s.stop()


def test_proxy_over_native_transport(monkeypatch):
    """The proxy tier honors JUBATUS_TPU_NATIVE_RPC like the engine
    servers (same create_rpc_server factory)."""
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1")
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    conf = {"method": "PA", "parameter": {},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    store = _Store()
    srv = EngineServer(
        "classifier", conf,
        ServerArgs(engine="classifier", coordinator="(shared)", name="np",
                   listen_addr="127.0.0.1", interval_sec=1e9,
                   interval_count=1 << 30),
        coord=MemoryCoordinator(store))
    proxy = None
    try:
        srv.start(0)
        proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                      coord=MemoryCoordinator(store))
        assert isinstance(proxy.rpc, native_server.NativeRpcServer)
        pport = proxy.start(0)
        c = ClassifierClient("127.0.0.1", pport, "np")
        assert c.train([["pos", Datum({"x": 1.0})]]) == 1
        (res,) = c.classify([Datum({"x": 1.0})])
        assert res
        c.close()
    finally:
        if proxy is not None:
            proxy.stop()
        srv.stop()
