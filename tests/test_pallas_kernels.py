"""Pallas kernel tests — interpret mode on CPU (SURVEY.md §4 lesson: TPU
kernel logic must be testable without the chip). Ground truth is the XLA
formulation in ops/knn.py plus numpy bit-counting.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from jubatus_tpu.ops import knn, pallas_kernels


@pytest.fixture
def sigs(rng):
    q = rng.integers(0, 2**32, size=(5, 4), dtype=np.uint32)
    rows = rng.integers(0, 2**32, size=(700, 4), dtype=np.uint32)
    return jnp.asarray(q), jnp.asarray(rows)


def test_popcount32_matches_numpy(rng):
    v = rng.integers(0, 2**32, size=(64,), dtype=np.uint32)
    got = np.asarray(pallas_kernels._popcount32(jnp.asarray(v)))
    want = np.array([bin(x).count("1") for x in v], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_hamming_matches_xla(sigs):
    q, rows = sigs
    hash_num = 128
    got = pallas_kernels.hamming_distances_batch(q, rows, hash_num=hash_num)
    want = knn._hamming_distances_batch_xla(q, rows, hash_num=hash_num)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_hamming_single_query(sigs):
    q, rows = sigs
    got = pallas_kernels.hamming_distances(q[0], rows, hash_num=128)
    want = knn._hamming_distances_xla(q[0], rows, hash_num=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_hamming_non_multiple_block(sigs):
    """Candidate count not divisible by the block size: padded tail must not
    corrupt real outputs."""
    q, rows = sigs
    got = pallas_kernels.hamming_distances_batch(q, rows[:513], hash_num=128,
                                                 block=256)
    want = knn._hamming_distances_batch_xla(q, rows[:513], hash_num=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_minhash_matches_xla(rng):
    q = rng.integers(0, 50, size=(3, 8), dtype=np.uint32)
    rows = rng.integers(0, 50, size=(300, 8), dtype=np.uint32)
    got = pallas_kernels.minhash_distances_batch(jnp.asarray(q), jnp.asarray(rows))
    want = knn._minhash_distances_batch_xla(jnp.asarray(q), jnp.asarray(rows))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    single = pallas_kernels.minhash_distances(jnp.asarray(q[1]), jnp.asarray(rows))
    np.testing.assert_allclose(np.asarray(single), np.asarray(want)[1], atol=1e-6)


def test_identical_sig_distance_zero(rng):
    rows = rng.integers(0, 2**32, size=(32, 2), dtype=np.uint32)
    d = pallas_kernels.hamming_distances(jnp.asarray(rows[7]),
                                         jnp.asarray(rows), hash_num=64)
    assert float(d[7]) == 0.0
    m = pallas_kernels.minhash_distances(jnp.asarray(rows[7]), jnp.asarray(rows))
    assert float(m[7]) == 0.0


def test_enabled_env_override(monkeypatch):
    monkeypatch.setenv("JUBATUS_TPU_PALLAS", "1")
    assert pallas_kernels.enabled()
    monkeypatch.setenv("JUBATUS_TPU_PALLAS", "0")
    assert not pallas_kernels.enabled()


def test_knn_dispatch_uses_pallas(monkeypatch, sigs):
    """With the flag forced on, the public knn entry points route through
    the kernels and still agree with the XLA math."""
    monkeypatch.setenv("JUBATUS_TPU_PALLAS", "1")
    q, rows = sigs
    got = knn.hamming_distances_batch(q, rows, hash_num=128)
    want = knn._hamming_distances_batch_xla(q, rows, hash_num=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
