"""Self-tuning performance plane (ISSUE 20): tuner decision logic on
synthetic telemetry timelines, the observe/on ladder, and the chaos
drills against the ``tune.*.apply`` fault sites.

Everything here is pure-core or fake-adapter driven: the clock is
injected, the timelines are synthetic (phase-ratio shifts, arrival
bursts, divergence spikes), and no server boots — the cluster-level
plan-change coherence proof lives in tests/test_collective_mixer.py."""

from __future__ import annotations

import pytest

from jubatus_tpu.coord.perf_tuner import (CadenceCore, CoalescerCore,
                                          MixPlanCore, PerfTuner,
                                          TunerConfig)
from jubatus_tpu.utils import faults
from jubatus_tpu.utils.tracing import Registry


def cfg(**kw) -> TunerConfig:
    base = dict(mode="on", confirm=1, cooldown_s=0.0, settle_rounds=1,
                backoff_initial_s=0.25, backoff_max_s=2.0)
    base.update(kw)
    return TunerConfig(**base)


# -- MixPlanCore ---------------------------------------------------------------

def synth_cost(plan, optimum=("bf16", 16.0)):
    """Synthetic round-time surface: unimodal around the optimum — the
    shape a real chunk sweep shows (too-small chunks pay per-collective
    overhead, too-large ones lose pipeline overlap; the wrong wire mode
    ships 2-4x the bytes)."""
    mode, chunk = plan
    base = {"off": 100.0, "bf16": 60.0, "int8": 70.0}[mode]
    return base + abs(chunk - optimum[1]) * 1.5


def drive(core, cost_fn, rounds=30, ship_frac=0.7, ef_drift=None):
    """Run the propose→commit loop against a synthetic cost surface;
    returns the number of observe() rounds consumed."""
    used = 0
    plan = core.plan
    while used < rounds:
        used += 1
        prop = core.observe(cost_fn(plan), ship_frac=ship_frac,
                            ef_drift=ef_drift)
        if prop is not None:
            plan = prop["plan"]
            core.commit(plan)
        elif core.converged:
            break
    return used


def test_mix_core_converges_to_synthetic_optimum():
    core = MixPlanCore(cfg(), mode="off", chunk_mb=8.0)
    used = drive(core, synth_cost)
    assert core.plan == ("bf16", 16.0)
    assert core.converged
    # the regret-bench budget: settle within the 12-round envelope
    assert used <= 12, used


def test_mix_core_chunk_first_when_not_ship_dominated():
    """A round whose time is NOT dominated by the ship phase probes the
    chunk ladder before the wire ladder (compression can't win when the
    wire isn't the bottleneck)."""
    core = MixPlanCore(cfg(), mode="off", chunk_mb=8.0)
    core.observe(100.0, ship_frac=0.2)         # scores the seed plan
    prop = core.observe(100.0, ship_frac=0.2)  # hmm settle_rounds=1
    # with settle_rounds=1 the FIRST observe already proposes
    first = core._next_probe(0.2)
    assert first is not None and first[0] == "off"  # chunk move, same wire


def test_mix_core_wire_first_when_ship_dominated():
    core = MixPlanCore(cfg(), mode="off", chunk_mb=8.0)
    core.observe(100.0, ship_frac=0.9)
    first = core._next_probe(0.9)
    assert first == ("bf16", 8.0)  # wire move, same chunk


def test_mix_core_int8_guardrail_blacklists_and_steps_down():
    """EF residual drift above the bound while on int8: int8 is
    blacklisted (purged from scores, never proposed again) and the plan
    steps back down the wire ladder."""
    c = cfg()
    core = MixPlanCore(c, mode="int8", chunk_mb=8.0)
    core.scores[("int8", 8.0)] = 10.0  # looks great — drift still kills it
    prop = core.observe(10.0, ef_drift=c.ef_drift_max * 10)
    assert prop == {"action": "retune", "plan": ("bf16", 8.0),
                    "reason": "ef_drift_guardrail"}
    assert core.int8_blacklisted
    assert all(p[0] != "int8" for p in core.scores)
    core.commit(prop["plan"])
    drive(core, lambda p: synth_cost(p, optimum=("bf16", 8.0)))
    assert all(p[0] != "int8" for p in core.scores)
    assert core.plan[0] != "int8"


def test_mix_core_settles_back_on_best_after_bad_probe():
    """A probe that lands on a worse plan must retune back to the best
    scored plan, not stay where it wandered."""
    core = MixPlanCore(cfg(chunk_ladder=(4.0, 8.0), wire_ladder=("off",)),
                       mode="off", chunk_mb=8.0)
    assert core.observe(50.0) == {"action": "probe", "plan": ("off", 4.0),
                                  "reason": "hill_climb"}
    core.commit(("off", 4.0))
    prop = core.observe(90.0)  # the probe was worse
    assert prop == {"action": "retune", "plan": ("off", 8.0),
                    "reason": "settle_on_best"}


# -- CoalescerCore -------------------------------------------------------------

def test_coalescer_arrival_burst_deepens_with_bounded_step():
    c = cfg(confirm=2, residency_target_s=0.1, depth_step_max=2.0)
    core = CoalescerCore(c)
    # arrival 10000/s x 0.1s residency => target 1000, current depth 64
    assert core.observe(1.0, 10000.0, 64) is None   # first hot tick: streak
    d = core.observe(2.0, 10000.0, 64)
    assert d is not None and d["action"] == "deepen"
    assert d["depth"] == 128  # bounded: one 2x step, not the full jump
    assert d["target"] == 1000.0


def test_coalescer_quiescent_shrinks_but_never_below_one():
    c = cfg(confirm=1, residency_target_s=0.05, depth_step_max=4.0)
    core = CoalescerCore(c)
    d = core.observe(1.0, 10.0, 8)  # target 0.5 -> floor 1
    assert d is not None and d["action"] == "shallow"
    assert d["depth"] >= 1
    d2 = core.observe(2.0, 10.0, d["depth"])
    while d2 is not None and d2["action"] == "shallow":
        assert d2["depth"] >= 1
        d2 = core.observe(3.0, 10.0, d2["depth"])


def test_coalescer_idle_holds():
    """Arrival 0 must HOLD, not shrink — an idle queue's depth is free,
    and shrinking it would punish the next burst."""
    core = CoalescerCore(cfg(confirm=1))
    for t in range(1, 5):
        assert core.observe(float(t), 0.0, 512) is None
    assert core.cold_streak == 0


def test_coalescer_dead_band_suppresses_noise():
    c = cfg(confirm=1, residency_target_s=0.05, depth_band=0.5)
    core = CoalescerCore(c)
    # target = 100*0.05 = 5 vs depth 6: inside the band -> hold
    assert core.observe(1.0, 100.0, 6) is None
    assert core.hot_streak == 0 and core.cold_streak == 0


def test_coalescer_cooldown_gates_consecutive_moves():
    c = cfg(confirm=1, cooldown_s=10.0, residency_target_s=0.1)
    core = CoalescerCore(c)
    d = core.observe(1.0, 10000.0, 4)
    assert d is not None
    assert core.observe(2.0, 10000.0, d["depth"]) is None  # in cooldown
    assert core.observe(12.0, 10000.0, d["depth"]) is not None


# -- CadenceCore ---------------------------------------------------------------

def test_cadence_divergence_spike_quickens_to_floor():
    c = cfg(confirm=2, interval_floor_s=2.0, interval_ceiling_s=64.0)
    core = CadenceCore(c)
    assert core.observe(1.0, 0.9, 16.0) is None  # first hot tick
    d = core.observe(2.0, 0.9, 16.0)
    assert d is not None and d["action"] == "quicken"
    assert d["interval_sec"] == 8.0
    # keep spiking: halves again but never below the floor
    core.observe(3.0, 0.9, 8.0)
    d = core.observe(4.0, 0.9, 8.0)
    assert d["interval_sec"] == 4.0
    core.observe(5.0, 0.9, 2.0)
    assert core.observe(6.0, 0.9, 2.0) is None  # at the floor: hold


def test_cadence_quiescence_relaxes_to_ceiling():
    c = cfg(confirm=1, interval_floor_s=2.0, interval_ceiling_s=32.0)
    core = CadenceCore(c)
    d = core.observe(1.0, 0.0, 16.0)
    assert d is not None and d["action"] == "relax"
    assert d["interval_sec"] == 32.0
    assert core.observe(2.0, 0.0, 32.0) is None  # at the ceiling


def test_cadence_mid_band_holds_and_resets_streaks():
    c = cfg(confirm=2)
    core = CadenceCore(c)
    core.observe(1.0, 0.9, 16.0)
    assert core.hot_streak == 1
    core.observe(2.0, 0.1, 16.0)  # between cold and hot thresholds
    assert core.hot_streak == 0 and core.cold_streak == 0


# -- PerfTuner (assembled loop, fake adapter) ---------------------------------

class FakeAdapter:
    """Synthetic fleet: a mix plane whose round time follows synth_cost
    for the currently-applied plan, one coalescer, one cadence plane.
    Tests mutate the signal fields to build timelines."""

    def __init__(self):
        self.wire = "off"
        self.chunk = 8.0
        self.rounds = 0
        self.ef_drift = 0.0
        self.ship_frac = 0.7
        self.depth = 64
        self.arrival = 0.0
        self.divergence = 0.0
        self.interval = 16.0
        self.mix_applies = []
        self.coalescer_applies = []
        self.cadence_applies = []

    def mix_signals(self):
        if self.rounds <= 0:
            return None
        return {"rounds": self.rounds,
                "round_ms": synth_cost((self.wire, self.chunk)),
                "wire": self.wire, "chunk_mb": self.chunk,
                "ef_drift": self.ef_drift, "ship_frac": self.ship_frac}

    def apply_mix(self, wire, chunk_mb):
        self.mix_applies.append((wire, chunk_mb))
        self.wire, self.chunk = wire, chunk_mb

    def coalescer_signals(self):
        return [{"name": "train", "arrival_per_sec": self.arrival,
                 "depth": self.depth}]

    def apply_coalescer(self, name, depth):
        self.coalescer_applies.append((name, depth))
        self.depth = depth

    def cadence_signals(self):
        return {"divergence": self.divergence,
                "interval_sec": self.interval}

    def apply_cadence(self, sec):
        self.cadence_applies.append(sec)
        self.interval = sec


def mk_tuner(adapter, **kw):
    reg = Registry()
    return PerfTuner(cfg(**kw), adapter, registry=reg), reg


def test_tuner_converges_fleet_to_optimum_and_journals():
    ad = FakeAdapter()
    tuner, reg = mk_tuner(ad)
    now = 0.0
    for _ in range(30):
        now += 1.0
        ad.rounds += 1  # one mix round completed per tick
        tuner.tick(now)
    assert (ad.wire, ad.chunk) == ("bf16", 16.0)
    assert tuner.mix is not None and tuner.mix.converged
    counters = reg.counters()
    assert counters["tune.decisions"] == len(tuner.journal_tail(10**6))
    assert counters["tune.applies"] == (len(ad.mix_applies)
                                        + len(ad.coalescer_applies)
                                        + len(ad.cadence_applies))
    assert reg.gauges()["tune.mix.chunk_mb"] == 16.0
    assert reg.gauges()["tune.mix.wire_mode"] == 1.0  # bf16 ladder index
    actions = {r["action"] for r in tuner.journal_tail(10**6)}
    assert "probe" in actions
    # every journal record cross-links a timeline event
    for rec in tuner.journal_tail(10**6):
        assert rec["hlc"]
        assert rec["event_hlc"]


def test_tuner_stale_round_count_feeds_no_sample():
    """No new mix round between ticks => no observation consumed (the
    tuner must never score a plan on a repeated stale measurement)."""
    ad = FakeAdapter()
    ad.rounds = 1
    tuner, _ = mk_tuner(ad, settle_rounds=2)
    tuner.tick(1.0)   # anchor
    for t in range(2, 10):
        tuner.tick(float(t))  # rounds never advances
    assert tuner.mix is not None
    assert tuner.mix.scores == {}  # nothing settled


def test_tuner_coalescer_burst_timeline():
    ad = FakeAdapter()
    tuner, reg = mk_tuner(ad, confirm=2, residency_target_s=0.1)
    ad.arrival = 10000.0  # burst: target 1000 vs depth 64
    tuner.tick(1.0)
    assert ad.coalescer_applies == []  # confirm streak not met yet
    tuner.tick(2.0)
    assert ad.coalescer_applies == [("train", 128)]  # bounded 2x step
    assert reg.gauges()["tune.coalescer.max_batch"] == 128.0


def test_tuner_cadence_divergence_timeline():
    ad = FakeAdapter()
    tuner, reg = mk_tuner(ad, confirm=1)
    ad.divergence = 0.9
    tuner.tick(1.0)
    assert ad.cadence_applies == [8.0]
    assert reg.gauges()["tune.cadence.interval_s"] == 8.0


def test_observe_mode_journals_dry_run_and_touches_nothing():
    ad = FakeAdapter()
    ad.arrival = 10000.0
    ad.divergence = 0.9
    tuner, reg = mk_tuner(ad, mode="observe", confirm=1)
    for t in range(1, 8):
        ad.rounds += 1
        tuner.tick(float(t))
    # recommendations journaled...
    recs = tuner.journal_tail(10**6)
    assert recs and all(r.get("dry_run") for r in recs)
    # ...but nothing actuated and no knob moved
    assert ad.mix_applies == []
    assert ad.coalescer_applies == []
    assert ad.cadence_applies == []
    assert (ad.wire, ad.chunk, ad.depth, ad.interval) == \
        ("off", 8.0, 64, 16.0)
    # dry-run intent counts decisions, never applies
    counters = reg.counters()
    assert counters["tune.decisions"] == len(recs)
    assert "tune.applies" not in counters


def test_off_mode_never_reads_signals():
    class Exploding:
        def __getattr__(self, name):
            raise AssertionError("off-mode tuner touched the adapter")

    tuner = PerfTuner(TunerConfig(mode="off"), Exploding(),
                      registry=Registry())
    tuner.tick(1.0)  # must not raise


def test_tuner_status_shape():
    ad = FakeAdapter()
    tuner, _ = mk_tuner(ad)
    ad.rounds = 1
    tuner.tick(1.0)
    st = tuner.status()
    assert st["mode"] == "on"
    assert "backoff_s" in st and "journal" in st and "cadence" in st
    assert st["mix"]["wire"] == "off" and st["mix"]["chunk_mb"] == 8.0


# -- chaos: the tune.*.apply fault sites --------------------------------------

def test_mix_apply_fault_blocks_backs_off_and_leaves_plan_coherent():
    """A failing mix actuation journals ``blocked``, arms exponential
    backoff (no hot-loop), and leaves BOTH the fleet knob and the
    core's belief on the previous plan — never a half-applied plan."""
    ad = FakeAdapter()
    tuner, reg = mk_tuner(ad, settle_rounds=1)
    ad.rounds = 1
    tuner.tick(1.0)  # anchor
    with faults.armed("tune.mix.apply:error"):
        ad.rounds = 2
        tuner.tick(2.0)
    blocked = [r for r in tuner.journal_tail(10) if r["action"] == "blocked"]
    assert len(blocked) == 1
    assert blocked[0]["backoff_s"] == 0.25
    assert "FaultInjected" in blocked[0]["error"]
    assert tuner.backoff_until == 2.25
    # knob untouched, belief untouched — coherent
    assert (ad.wire, ad.chunk) == ("off", 8.0)
    assert tuner.mix.plan == ("off", 8.0)
    assert reg.counters()["tune.blocked"] == 1
    # ticks inside the backoff window do nothing at all
    with faults.armed("tune.mix.apply:error"):
        ad.rounds = 3
        tuner.tick(2.1)
    assert len([r for r in tuner.journal_tail(10)
                if r["action"] == "blocked"]) == 1
    # backoff doubles on the next failure after the window
    with faults.armed("tune.mix.apply:error"):
        ad.rounds = 4
        tuner.tick(3.0)
    blocked = [r for r in tuner.journal_tail(10) if r["action"] == "blocked"]
    assert len(blocked) == 2
    assert blocked[-1]["backoff_s"] == 0.5
    # and a later successful apply clears the backoff
    ad.rounds = 5
    tuner.tick(10.0)
    assert ad.mix_applies  # actuated now
    assert tuner.backoff_until == 0.0


def test_coalescer_apply_fault_leaves_depth_unchanged():
    ad = FakeAdapter()
    ad.arrival = 10000.0
    tuner, _ = mk_tuner(ad, confirm=1)
    with faults.armed("tune.coalescer.apply:error"):
        tuner.tick(1.0)
    assert ad.depth == 64
    assert ad.coalescer_applies == []
    blocked = tuner.journal_tail(5)[-1]
    assert blocked["action"] == "blocked"
    assert blocked["target"] == "train"
    assert tuner.in_backoff(1.1)


def test_cadence_apply_fault_delay_rule_does_not_block():
    """A delay rule (slow actuation path) is not an error: the apply
    still lands, nothing journals blocked."""
    ad = FakeAdapter()
    ad.divergence = 0.9
    tuner, _ = mk_tuner(ad, confirm=1)
    with faults.armed("tune.cadence.apply:delay:0.01"):
        tuner.tick(1.0)
    assert ad.cadence_applies == [8.0]
    assert not any(r["action"] == "blocked" for r in tuner.journal_tail(10))


def test_cadence_apply_fault_blocks():
    ad = FakeAdapter()
    ad.divergence = 0.9
    tuner, _ = mk_tuner(ad, confirm=1)
    with faults.armed("tune.cadence.apply:error"):
        tuner.tick(1.0)
    assert ad.cadence_applies == []
    assert ad.interval == 16.0
    assert tuner.journal_tail(5)[-1]["action"] == "blocked"


def test_sick_adapter_never_kills_the_tick():
    class Sick:
        def mix_signals(self):
            raise RuntimeError("boom")

        def coalescer_signals(self):
            raise RuntimeError("boom")

        def cadence_signals(self):
            raise RuntimeError("boom")

    tuner = PerfTuner(cfg(), Sick(), registry=Registry())
    tuner.tick(1.0)  # must not raise


# -- config validation ---------------------------------------------------------

def test_config_rejects_bad_mode_and_bounds():
    with pytest.raises(ValueError):
        TunerConfig(mode="sometimes")
    with pytest.raises(ValueError):
        TunerConfig(interval_floor_s=10.0, interval_ceiling_s=1.0)
    with pytest.raises(ValueError):
        TunerConfig(depth_floor=0)


# -- server wiring + jubactl surface ------------------------------------------

def test_server_tuner_wiring_and_jubactl_tune_view():
    """--auto-tune observe boots a PerfTuner riding the telemetry tick,
    get_tune serves its status over the RPC (idempotent builtin — safe
    through proxies/retries), and jubactl's renderer turns the doc into
    the operator view."""
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.framework.idl import IDEMPOTENT_BUILTINS
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    assert "get_tune" in IDEMPOTENT_BUILTINS
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name="tunesrv", listen_addr="127.0.0.1",
                      interval_sec=1e9, interval_count=1 << 30,
                      auto_tune="observe")
    srv = EngineServer("classifier", conf, args,
                       coord=MemoryCoordinator(_Store()))
    srv.start(0)
    try:
        assert srv.tuner is not None
        assert srv.tuner.dry_run
        srv._tune_tick()  # the telemetry hook, driven by hand
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            docs = c.call("get_tune", "tunesrv")
        assert len(docs) == 1
        (st,) = docs.values()
        assert st["mode"] == "observe"
        text = jubactl.render_tune("classifier", "tunesrv", docs)
        assert "auto-tune across 1 node(s)" in text
        assert "mode observe" in text
    finally:
        srv.stop()


def test_server_without_auto_tune_has_no_tuner():
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    args = ServerArgs(engine="classifier", coordinator="(shared)",
                      name="tunesrv", listen_addr="127.0.0.1",
                      interval_sec=1e9, interval_count=1 << 30)
    srv = EngineServer("classifier", conf, args,
                       coord=MemoryCoordinator(_Store()))
    srv.start(0)
    try:
        assert srv.tuner is None
        srv._tune_tick()  # hook stays a no-op, never raises
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            docs = c.call("get_tune", "tunesrv")
        (st,) = docs.values()
        assert st == {}
        text = jubactl.render_tune("classifier", "tunesrv", docs)
        assert "tuner off (--auto-tune off)" in text
    finally:
        srv.stop()


def test_render_tune_journal_lines():
    """The renderer is pure — feed it a canned doc and pin the shape
    operators read (plan line, blacklist flag, journal rows, dry-run
    and error tags)."""
    from jubatus_tpu.cmd import jubactl

    docs = {"n1:9200": {
        "mode": "on", "backoff_s": 4.0,
        "mix": {"wire": "bf16", "chunk_mb": 16.0, "trials": 3,
                "converged": True, "int8_blacklisted": True,
                "best_wire": "bf16", "best_chunk_mb": 16.0,
                "best_ms": 57.5},
        "coalescers": {"train": {"hot_streak": 1, "cold_streak": 0}},
        "cadence": {"hot_streak": 0, "cold_streak": 2},
        "journal": [
            {"ts": 12.0, "action": "probe", "reason": "hill_climb",
             "target": "mix",
             "signals": {"wire": "bf16", "chunk_mb": 16.0}},
            {"ts": 13.0, "action": "deepen", "reason": "littles_law",
             "target": "train", "dry_run": True,
             "signals": {"depth": 128}},
            {"ts": 14.0, "action": "blocked", "reason": "littles_law",
             "target": "train", "error": "FaultInjected",
             "signals": {"depth": 256}},
        ]},
        "n2:9201": {}}
    text = jubactl.render_tune("classifier", "x", docs, last=8)
    assert "auto-tune across 2 node(s)" in text
    assert "mode on  backoff 4s" in text
    assert "plan bf16/16MB" in text and "converged" in text
    assert "int8 BLACKLISTED" in text
    assert "best bf16/16MB 57.5ms" in text
    assert "coalescer train: streaks hot 1 / cold 0" in text
    assert "-> bf16/16.0MB" in text
    assert "[dry-run]" in text
    assert "(FaultInjected)" in text
    assert "-> depth 128" in text
    assert "n2:9201: tuner off" in text
