"""Continuous-profiling plane tests (ISSUE 8): the always-on stack
sampler (bounded store, hz=0 off, folding determinism), slowlog
tail-triggered snapshots (once per breach window, trace_id stamping),
device capture bounds, get_profile/profile_device envelope compat on
both transports, and the cluster acceptance: ``jubactl -c profile
--folded`` against a live proxy + 2-backend topology emits a non-empty
cluster-folded collapsed-stack profile containing frames from both
backends."""

from __future__ import annotations

import threading
import time

import pytest

from jubatus_tpu.utils import tracing
from jubatus_tpu.utils.profiler import (
    OTHER_KEY,
    DeviceCapture,
    SamplingProfiler,
    collapse_frame,
    fold_profiles,
    folded_lines,
    render_top,
    top_table,
)
from jubatus_tpu.utils.slowlog import SlowLog

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- the sampler itself -------------------------------------------------------


def test_sampler_collects_stacks_with_thread_roots():
    reg = tracing.Registry()
    prof = SamplingProfiler(reg, hz=250)
    stop = threading.Event()

    def busy_beaver():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=busy_beaver, name="prof-busy", daemon=True)
    t.start()
    prof.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            doc = prof.profile(60)
            if any("busy_beaver" in k for k in doc["folded"]):
                break
            time.sleep(0.05)
    finally:
        stop.set()
        prof.stop()
        t.join(timeout=2)
    doc = prof.profile(60)
    assert doc["folded"], "sampler collected nothing"
    assert doc["stats"]["enabled"] and doc["stats"]["samples"] > 0
    busy = [k for k in doc["folded"] if "busy_beaver" in k]
    assert busy, sorted(doc["folded"])[:5]
    # thread name roots the stack; the sampler's own thread is excluded
    assert any(k.startswith("thread:prof-busy;") for k in busy)
    assert not any("stack-profiler" in k.split(";", 1)[0]
                   for k in doc["folded"])


def test_bounded_store_under_churn():
    prof = SamplingProfiler(None, hz=0, max_stacks=8)
    with prof._lock:
        for i in range(100):
            prof._ingest_locked(f"thread:t;mod.py:f{i}")
    doc = prof.profile(0)
    # bound holds: max_stacks distinct keys + the overflow bucket
    assert len(doc["folded"]) <= 8 + 1
    assert doc["folded"][OTHER_KEY] == 100 - 8
    assert doc["stats"]["truncated"] == 100 - 8
    # counts stay honest: every ingested sample is accounted somewhere
    assert sum(doc["folded"].values()) == 100


def test_window_rotation_bounds_history():
    prof = SamplingProfiler(None, hz=0, bucket_s=0.5, ring_capacity=4)
    now = time.time()
    with prof._lock:
        prof._ingest_locked("thread:t;a.py:f")
        for i in range(10):  # force rotations far past ring capacity
            prof._rotate_locked(now + i)
            prof._ingest_locked("thread:t;a.py:f")
    assert prof.stats()["ring_buckets"] <= 4
    # a short window excludes evicted/out-of-window buckets but always
    # includes the live bucket
    doc = prof.profile(0.001)
    assert doc["folded"].get("thread:t;a.py:f", 0) >= 1


def test_hz_zero_fully_off():
    prof = SamplingProfiler(None, hz=0)
    prof.start()
    assert prof._thread is None  # no thread at all
    assert not prof.enabled
    doc = prof.profile(60)
    assert doc["folded"] == {}
    assert doc["stats"]["enabled"] is False
    # the tail trigger degrades to a no-op, not a crash
    assert prof.tail_snapshot("rpc.x", ["t1"]) is None
    assert prof.snapshots() == []
    prof.stop()


def test_hz_zero_server_has_no_sampler_thread():
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        profile_hz=0.0))
    port = srv.start(0)
    try:
        assert srv.profiler._thread is None
        assert not any(t.name == "stack-profiler"
                       for t in threading.enumerate())
        (doc,) = srv.get_profile("", 60).values()
        assert doc["folded"] == {} and doc["stats"]["enabled"] is False
        assert port
    finally:
        srv.stop()


def test_collapse_frame_shape():
    import sys

    frame = sys._getframe()
    key = collapse_frame(frame, "tname")
    parts = key.split(";")
    assert parts[0] == "thread:tname"
    assert parts[-1].endswith(":test_collapse_frame_shape")
    # file.py:function tokens, no line numbers
    assert all(":" in p for p in parts)


def test_folding_determinism_and_order_invariance():
    d1 = {"folded": {"t;a": 3, "t;b": 1}}
    d2 = {"folded": {"t;a": 2, "t;c": 5}}
    once = fold_profiles([d1, d2])
    assert once == {"t;a": 5, "t;b": 1, "t;c": 5}
    assert fold_profiles([d2, d1]) == once          # order-invariant
    assert fold_profiles([d1, d2]) == once          # repeatable
    # bare folded dicts fold too (jubactl folds mixed shapes)
    assert fold_profiles([{"t;a": 1}, d1])["t;a"] == 4
    lines = folded_lines(once)
    assert lines == sorted(lines)
    assert "t;a 5" in lines


def test_top_table_self_cum_math():
    folded = {"t;a;b": 6, "t;a;c": 4, "t;a": 2,
              "t;r;r": 3}  # recursion: r counted once per stack
    rows = {r["frame"]: r for r in top_table(folded)}
    assert rows["b"]["self"] == 6 and rows["b"]["cum"] == 6
    assert rows["a"]["self"] == 2 and rows["a"]["cum"] == 12
    assert rows["t"]["cum"] == 15
    assert rows["r"]["self"] == 3 and rows["r"]["cum"] == 3
    text = render_top(folded, top=3)
    assert "frame" in text and "total: 15 sample(s)" in text


def test_concurrent_get_profile_during_sampling():
    reg = tracing.Registry()
    prof = SamplingProfiler(reg, hz=500, bucket_s=0.5)
    prof.start()
    errors = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                doc = prof.profile(1.0)
                assert isinstance(doc["folded"], dict)
                prof.tail_snapshot("rpc.x", ["tid"])
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.6)
    stop.set()
    for t in threads:
        t.join(timeout=2)
    prof.stop()
    assert not errors, errors
    assert prof.stats()["samples"] > 0
    # the snapshot ring stayed bounded under the hammering
    assert len(prof.snapshots()) <= SamplingProfiler(None).\
        _snapshots.maxlen


# -- tail trigger (slowlog -> snapshot) ---------------------------------------


def test_slowlog_trigger_fires_once_per_window():
    sl = SlowLog()
    fired = []
    sl.set_trigger(lambda span, ids: fired.append((span, ids)),
                   breaches=3, window_s=10.0)
    t0 = 1000.0
    for i in range(5):  # 5 breaches in one window -> ONE fire at the 3rd
        sl._note_breach("rpc.classify", f"tid{i}", now=t0 + i)
    assert len(fired) == 1
    span, ids = fired[0]
    assert span == "rpc.classify"
    assert ids == ["tid0", "tid1", "tid2"]
    # window expires -> breaches count fresh, can fire again
    for i in range(3):
        sl._note_breach("rpc.classify", f"late{i}", now=t0 + 20 + i)
    assert len(fired) == 2
    assert fired[1][1] == ["late0", "late1", "late2"]
    # distinct spans keep independent windows
    sl._note_breach("rpc.train", "x", now=t0 + 21)
    assert len(fired) == 2
    assert sl.stats()["trigger_fired"] == 2


def test_slowlog_trigger_disabled_and_error_isolated():
    sl = SlowLog()
    # disabled by default: no callback, nothing fires
    assert sl._note_breach("rpc.x", "t", now=1.0) is False
    # a raising callback must not break capture
    sl.set_trigger(lambda *_: 1 / 0, breaches=1, window_s=10.0)
    sl.add({"method": "rpc.x", "trace_id": "t1", "duration_ms": 1.0})
    assert sl.stats()["captured"] == 1
    assert sl.stats()["trigger_fired"] == 1


def test_breach_snapshot_carries_trace_id_through_registry():
    """Acceptance: a slowlog breach auto-captures a profiler snapshot
    stamped with the breaching trace_id, through the REAL wiring
    (Registry.record -> slow capture -> slowlog.add -> trigger)."""
    reg = tracing.Registry()
    prof = SamplingProfiler(reg, hz=100)
    reg.slowlog.configure(min_count=1, quantile=0.5)
    reg.slowlog.set_trigger(prof.tail_snapshot, breaches=3, window_s=30.0)
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        for _ in range(4):  # equal durations: every record >= threshold
            reg.record("rpc.classify", 0.25)
    snaps = prof.snapshots()
    assert len(snaps) == 1, snaps  # once per window despite 4 breaches
    assert snaps[0]["span"] == "rpc.classify"
    assert ctx.trace_id in snaps[0]["trace_ids"]
    # the snapshot rides the get_profile doc
    doc = prof.profile(60)
    assert doc["snapshots"] and \
        ctx.trace_id in doc["snapshots"][0]["trace_ids"]


def test_server_breach_snapshot_end_to_end():
    """Server-level: slow spans breach -> snapshot appears in the
    get_profile RPC reply with the breaching trace_id."""
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1"))
    srv.rpc.trace.slowlog.configure(min_count=1, quantile=0.5)
    port = srv.start(0)
    try:
        ctx = tracing.new_root()
        with tracing.use_trace(ctx):
            for _ in range(4):
                srv.rpc.trace.record("rpc.classify", 0.25)
        with RpcClient("127.0.0.1", port) as rc:
            (doc,) = rc.call("get_profile", "", 60.0).values()
        assert doc["snapshots"], doc["stats"]
        snap = doc["snapshots"][0]
        assert snap["span"] == "rpc.classify"
        assert ctx.trace_id in snap["trace_ids"]
        # slowlog stats surface the trigger state in get_status
        (st,) = srv.get_status().values()
        assert st["slowlog.trigger_fired"] >= 1
        assert st["profiler.snapshots_taken"] >= 1
        assert st["profiler.enabled"] is True
    finally:
        srv.stop()


# -- device capture -----------------------------------------------------------


def test_device_capture_capped_and_listed(tmp_path):
    cap = DeviceCapture(str(tmp_path / "prof"), max_captures=2)
    results = [cap.capture(0.05) for _ in range(3)]
    oks = [r for r in results if "artifact" in r]
    errs = [r for r in results if "error" in r]
    # jax's CPU profiler works in this container; if a backend quirk
    # breaks it the API must degrade to a structured error, not raise
    assert not errs or all("dir" in r for r in errs)
    listing = cap.list()
    assert len(listing["artifacts"]) <= 2  # pruned past the cap
    if oks:
        assert listing["artifacts"], listing
        # the newest artifact survives the prune
        assert any(a["path"] == oks[-1]["artifact"]
                   for a in listing["artifacts"])


def test_profile_device_rpc_list_and_capture(tmp_path):
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        profile_dir=str(tmp_path / "artifacts")))
    port = srv.start(0)
    try:
        with RpcClient("127.0.0.1", port) as rc:
            (empty,) = rc.call("profile_device", "", 0.0).values()
            assert empty["artifacts"] == []
            (cap,) = rc.call("profile_device", "", 0.1).values()
            assert "artifact" in cap or "error" in cap
            (after,) = rc.call("profile_device", "", 0.0).values()
            if "artifact" in cap:
                assert len(after["artifacts"]) == 1
    finally:
        srv.stop()


# -- envelope compat on both transports ---------------------------------------


@pytest.mark.parametrize("native", [False, True])
def test_profile_rpcs_envelope_compat(monkeypatch, tmp_path, native):
    """get_profile / profile_device answer 4-element (plain msgpack-rpc)
    AND 5/6-element (traced/deadlined) envelopes on both transports —
    mirroring the get_spans/get_timeseries coverage."""
    from jubatus_tpu.rpc import deadline as deadlines
    from jubatus_tpu.rpc import native_server
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1" if native else "0")
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        profile_dir=str(tmp_path / "artifacts")))
    port = srv.start(0)
    try:
        deadline = time.monotonic() + 5.0
        while srv.profiler.stats()["samples"] == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        with RpcClient("127.0.0.1", port) as rc:
            # plain 4-element envelope
            (doc,) = rc.call("get_profile", "", 60.0).values()
            assert doc["folded"], doc["stats"]
            assert doc["stats"]["hz"] == 67.0
            (dev,) = rc.call("profile_device", "", 0.0).values()
            assert dev["artifacts"] == []
        # traced + deadlined (5/6-element) envelope
        probe = tracing.new_root()
        with tracing.use_trace(probe), deadlines.deadline_after(30.0):
            with RpcClient("127.0.0.1", port) as rc:
                (traced,) = rc.call("get_profile", "", 60.0).values()
                (tdev,) = rc.call("profile_device", "", 0.0).values()
        assert traced["folded"] and tdev["artifacts"] == []
    finally:
        srv.stop()


def test_profile_methods_registered_idempotent():
    from jubatus_tpu.framework.idl import (
        CLIENT_SAFE_RETRY,
        IDEMPOTENT_BUILTINS,
        idempotent_methods,
    )

    for m in ("get_profile", "profile_device", "get_proxy_profile"):
        assert m in IDEMPOTENT_BUILTINS
        assert m in idempotent_methods("classifier")
        assert m in CLIENT_SAFE_RETRY


# -- cluster acceptance -------------------------------------------------------


@pytest.fixture()
def profile_cluster(tmp_path):
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    proxy = None
    try:
        for _ in range(2):
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(engine="classifier", coordinator=coord_dir,
                                name="pf", listen_addr="127.0.0.1",
                                interval_sec=1e9, interval_count=1 << 30))
            srv.start(0)
            servers.append(srv)
        proxy = Proxy(ProxyArgs(engine="classifier",
                                listen_addr="127.0.0.1",
                                coordinator=coord_dir))
        proxy.start(0)
        # let every node's sampler land at least one sample
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
                n.profiler.stats()["samples"] > 0
                for n in servers + [proxy]):
            time.sleep(0.05)
        yield coord_dir, servers, proxy
    finally:
        if proxy is not None:
            proxy.stop()
        for s in servers:
            s.stop()


def test_cluster_folded_profile_acceptance(profile_cluster, capsys):
    """ISSUE 8 acceptance: ``jubactl -c profile --folded`` against a
    live proxy + 2-backend cluster emits a non-empty, cluster-folded
    collapsed-stack profile containing frames from BOTH backends."""
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.rpc.client import RpcClient

    coord_dir, servers, proxy = profile_cluster
    # one get_profile against the PROXY returns proxy + both backends,
    # each contributing frames
    with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
        prof = c.call("get_profile", "pf", 60.0)
    assert len(prof) == 3, sorted(prof)
    for node, doc in prof.items():
        assert doc["folded"], f"{node} contributed no frames"
        assert sum(doc["folded"].values()) > 0
    backend_nodes = {f"127.0.0.1_{s.args.rpc_port}" for s in servers}
    assert backend_nodes <= set(prof)
    rc = jubactl.main(["-c", "profile", "-t", "classifier", "-n", "pf",
                       "-z", coord_dir, "--folded"])
    cap = capsys.readouterr()
    assert rc == 0
    # stdout is pure collapsed-stack lines, each "stack count"
    lines = [ln for ln in cap.out.splitlines() if ln.strip()]
    assert lines, cap.err
    for ln in lines:
        stack, _, count = ln.rpartition(" ")
        assert stack and int(count) > 0
    # cluster-wide fold: totals cover every node's samples
    total = sum(int(ln.rpartition(" ")[2]) for ln in lines)
    assert total >= sum(
        sum(d["folded"].values()) for d in prof.values()) * 0.5
    # the header (stderr) attributes every node, both backends included
    for node in backend_nodes:
        assert node in cap.err


def test_jubactl_profile_table_and_device(profile_cluster, capsys):
    from jubatus_tpu.cmd import jubactl

    coord_dir, servers, proxy = profile_cluster
    rc = jubactl.main(["-c", "profile", "-t", "classifier", "-n", "pf",
                       "-z", coord_dir, "--top", "5"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "frame" in out and "self%" in out
    assert "folded from 3 node(s)" in out
    rc = jubactl.main(["-c", "profile", "-t", "classifier", "-n", "pf",
                       "-z", coord_dir, "--device"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "capture(s)" in out


def test_jubadump_profile_live(profile_cluster, capsys):
    import json

    from jubatus_tpu.cmd import jubadump

    _coord, servers, _proxy = profile_cluster
    rc = jubadump.main(["--profile",
                        f"127.0.0.1:{servers[0].args.rpc_port}",
                        "-n", "pf", "--seconds", "60"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    (node_doc,) = doc.values()
    assert node_doc["folded"]
    assert node_doc["stats"]["enabled"] is True
