"""Proxy tier tests (≙ the routing behavior baked into generated *_proxy.cpp
and exercised by jubatest cluster runs — here in-process).

Covers: random routing reaches exactly one backend, broadcast folds with the
method's aggregator, cht routing pins a key to the same backend(s) across
calls, built-ins (save broadcast+merge, get_status merge, get_proxy_status),
dead-backend tolerance, and clients talking *through* the proxy unchanged
(same wire protocol either way, client/common/client.hpp).
"""

from __future__ import annotations

import pytest

from jubatus_tpu.client import ClassifierClient, Datum, StatClient
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.server.proxy import Proxy, ProxyArgs

NAME = "pcl"

CLASSIFIER_CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


def _boot(engine, conf, n, store):
    servers = []
    for _ in range(n):
        args = ServerArgs(
            engine=engine, coordinator="(shared)", name=NAME,
            listen_addr="127.0.0.1", interval_sec=1e9, interval_count=1 << 30,
        )
        srv = EngineServer(engine, conf, args, coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    return servers


def _proxy(engine, store, **kw):
    args = ProxyArgs(engine=engine, listen_addr="127.0.0.1", **kw)
    p = Proxy(args, coord=MemoryCoordinator(store))
    p.start(0)
    return p


@pytest.fixture()
def classifier_cluster():
    store = _Store()
    servers = _boot("classifier", CLASSIFIER_CONF, 3, store)
    proxy = _proxy("classifier", store)
    yield servers, proxy, store
    proxy.stop()
    for s in servers:
        s.stop()


def test_random_routing_single_backend(classifier_cluster):
    servers, proxy, _ = classifier_cluster
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    # train goes to exactly ONE backend per call (random routing)
    assert c.train([["pos", Datum({"x": 1.0})]]) == 1
    total = sum(s.driver.update_count for s in servers)
    assert total == 1
    c.close()


def test_broadcast_clear_reaches_all(classifier_cluster):
    servers, proxy, _ = classifier_cluster
    # seed every backend directly
    for s in servers:
        d = ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
        d.train([["pos", Datum({"x": 1.0})]])
        d.close()
    assert all(s.driver.update_count == 1 for s in servers)
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    assert c.clear() is True  # all_and over 3 backends
    for s in servers:
        d = ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
        assert d.get_labels() == {}
        d.close()
    c.close()


def test_get_status_merges_all_nodes(classifier_cluster):
    servers, proxy, _ = classifier_cluster
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    st = c.get_status()
    assert len(st) == 3  # one entry per backend, merged
    assert {int(k.rsplit("_", 1)[1]) for k in st} == {
        s.args.rpc_port for s in servers
    }
    c.close()


def test_save_broadcast_merge_and_load(classifier_cluster, tmp_path):
    servers, proxy, _ = classifier_cluster
    for s in servers:
        s.args.datadir = str(tmp_path)
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    c.train([["pos", Datum({"x": 1.0})]])
    paths = c.save("m1")
    assert len(paths) == 3  # per-server path map, merged (proxy.cpp:48-54)
    # clear the cluster, then broadcast load restores every node's OWN
    # snapshot (all_and) — only the node that got the random-routed train
    # has the label again, exactly per-node save/load semantics
    assert c.clear() is True
    assert c.load("m1") is True
    assert sum("pos" in s.driver.get_labels() for s in servers) == 1
    c.close()


def test_proxy_status_counters(classifier_cluster):
    _, proxy, _ = classifier_cluster
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    c.train([["a", Datum({"x": 1.0})]])
    c.get_labels()
    st = c.get_proxy_status()
    (node_st,) = st.values()
    assert node_st["type"] == "classifier_proxy"
    assert node_st["request.train"] == 1
    assert node_st["request.get_labels"] == 1
    assert node_st["forward_count"] >= 2
    c.close()


def test_dead_backend_tolerated_on_broadcast(classifier_cluster):
    servers, proxy, store = classifier_cluster
    # kill one backend but leave its actives entry: proxy must still answer
    dead = servers.pop()
    dead.rpc.stop()
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME)
    st = c.get_status()
    assert len(st) == 2  # merged over the 2 live nodes, error tolerated
    c.close()
    dead.stop()


def test_no_actives_raises(tmp_path):
    store = _Store()
    proxy = _proxy("classifier", store)
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME, timeout=2.0)
    with pytest.raises(Exception) as ei:
        c.get_labels()
    assert "no active" in str(ei.value)
    c.close()
    proxy.stop()


def test_cht_routing_pins_key():
    """stat push/sum route by key: the same key must land on the same
    backend every time (stat_proxy.cpp:21-36, #@cht(1))."""
    store = _Store()
    servers = _boot("stat", {"window_size": 64}, 3, store)
    proxy = _proxy("stat", store)
    try:
        c = StatClient("127.0.0.1", proxy.args.rpc_port, NAME)
        for v in (1.0, 2.0, 3.0):
            c.push("alpha", v)
        # all three pushes hit one backend; sum through the proxy sees them
        assert c.sum("alpha") == pytest.approx(6.0)
        holders = [s for s in servers if s.driver.update_count == 3]
        assert len(holders) == 1
        assert all(s.driver.update_count in (0, 3) for s in servers)
        # a different key may land elsewhere but must also be consistent
        c.push("beta", 10.0)
        assert c.sum("beta") == pytest.approx(10.0)
        c.close()
    finally:
        proxy.stop()
        for s in servers:
            s.stop()


def test_member_cache_invalidation():
    """New server joining becomes visible to the proxy (cached_zk watch or
    TTL refresh)."""
    store = _Store()
    servers = _boot("classifier", CLASSIFIER_CONF, 1, store)
    proxy = _proxy("classifier", store)
    try:
        assert len(proxy.members.actives(NAME)) == 1
        servers += _boot("classifier", CLASSIFIER_CONF, 1, store)
        proxy.members.invalidate(NAME)
        assert len(proxy.members.actives(NAME)) == 2
    finally:
        proxy.stop()
        for s in servers:
            s.stop()


def _skip_unless_native():
    """Shared gate for the C++ relay tests (one owner for the condition)."""
    import os

    if os.environ.get("JUBATUS_TPU_NATIVE_RPC", "") in ("0", "false", "no"):
        pytest.skip("python transport forced")
    from jubatus_tpu.rpc import native_server

    if not native_server.available():
        pytest.skip("native rpc front-end unavailable")


def test_cpp_relay_plane_serves_and_counts():
    """Native transport: after the refresher's first table push, random-
    routed raw traffic forwards entirely in C++ (rpc_frontend.cpp relay)
    — results identical, counts folded into get_proxy_status, and a dead
    backend degrades to the Python path instead of wedging."""
    import time

    _skip_unless_native()
    store = _Store()
    servers = _boot("classifier", CLASSIFIER_CONF, 2, store)
    proxy = _proxy("classifier", store)
    if not hasattr(proxy.rpc, "relay_config"):
        proxy.stop()
        for s in servers:
            s.stop()
        pytest.skip("proxy not on native transport")
    cli = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME,
                           timeout=30)
    try:
        # first call goes the Python path and seeds the cluster table
        cli.train([("a", Datum({"x": 1.0})), ("b", Datum({"x": -1.0}))])
        deadline = time.time() + 8.0
        relayed = {}
        while time.time() < deadline:
            time.sleep(0.5)
            cli.train([("a", Datum({"x": 1.0}))])
            relayed = proxy.rpc.relay_stats()
            if relayed.get("train"):
                break
        assert relayed.get("train"), "relay never engaged"
        # classify rides the relay too, with a correct answer
        for _ in range(6):
            cli.train([("a", Datum({"x": 1.0})), ("b", Datum({"x": -1.0}))])
        res = cli.classify([Datum({"x": 1.0})])
        assert max(res[0], key=lambda e: e[1])[0] == "a"
        st = proxy.get_proxy_status()
        (node,) = st.values()
        assert node["relay_count"] >= relayed["train"]
        assert node["request.train"] >= relayed["train"]
        # kill both backends: relayed calls must surface an error (no
        # hang), then the Python fallback path reports no actives
        for s in servers:
            s.stop()
        with pytest.raises(Exception):
            for _ in range(20):  # pipes + membership drain within a few
                cli.train([("a", Datum({"x": 1.0}))])
                time.sleep(0.3)
    finally:
        cli.close()
        proxy.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — already stopped above
                pass


def test_cpp_relay_reroutes_on_membership_change():
    """A backend that leaves the routing table retires its pipes via the
    config generation: traffic re-pins to the survivor without client
    reconnects, and the dead backend's last in-flight calls surface as
    errors, not hangs."""
    import time

    _skip_unless_native()
    store = _Store()
    servers = _boot("classifier", CLASSIFIER_CONF, 2, store)
    proxy = _proxy("classifier", store)
    if not hasattr(proxy.rpc, "relay_config"):
        proxy.stop()
        for s in servers:
            s.stop()
        pytest.skip("proxy not on native transport")
    cli = ClassifierClient("127.0.0.1", proxy.args.rpc_port, NAME,
                           timeout=30)
    try:
        cli.train([("a", Datum({"x": 1.0})), ("b", Datum({"x": -1.0}))])
        deadline = time.time() + 8.0
        while time.time() < deadline:
            time.sleep(0.5)
            cli.train([("a", Datum({"x": 1.0}))])
            if proxy.rpc.relay_stats().get("train"):
                break
        assert proxy.rpc.relay_stats().get("train"), "relay never engaged"
        # drop ONE backend; keep calling through the same client conn —
        # within a few refresher ticks every call must succeed again via
        # the survivor (transient errors during the window are expected)
        servers[0].stop()
        deadline = time.time() + 12.0
        streak = 0
        while time.time() < deadline and streak < 5:
            try:
                cli.train([("a", Datum({"x": 1.0}))])
                streak += 1
            except Exception:
                streak = 0
                time.sleep(0.3)
        assert streak >= 5, "traffic never re-pinned to the survivor"
    finally:
        cli.close()
        proxy.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass


def test_cpp_relay_survives_garbage_backend():
    """A backend that answers garbage (non-msgpack bytes) must break only
    its pipe: outstanding calls error, the client connection survives,
    and traffic re-establishes through the Python path / a fresh pipe."""
    import socket
    import threading
    import time

    _skip_unless_native()
    from jubatus_tpu.rpc import native_server

    # hand-rolled "backend": accepts, reads a bit, spews garbage, closes
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    gport = lsock.getsockname()[1]

    def evil():
        try:
            conn, _ = lsock.accept()
            conn.recv(4096)
            conn.sendall(b"\xc1\xc1\xc1garbage\xff\xff")  # 0xc1 = never valid
            time.sleep(0.2)
            conn.close()
        except OSError:
            pass

    threading.Thread(target=evil, daemon=True).start()

    srv = native_server.NativeRpcServer()
    served = []
    srv.register("probe", lambda n: served.append(n) or "py", arity=1)
    srv.serve_background(0, host="127.0.0.1")
    assert srv.relay_config(["probe"], {"c": [("127.0.0.1", gport)]},
                            timeout=5.0)
    from jubatus_tpu.rpc.client import RpcClient

    try:
        with RpcClient("127.0.0.1", srv.port, timeout=10) as cli:
            # relayed into the garbage backend: must ERROR, not hang
            with pytest.raises(Exception):
                cli.call("probe", "c")
            # the refresher's job in production: the dead backend drops
            # out of the table; the C++ then declines and Python serves
            assert srv.relay_config(["probe"], {}, timeout=5.0) is True
            assert cli.call("probe", "c") == "py"
            assert served == ["c"]
            stats = srv.relay_stats()
            assert stats.get("__errors__", 0) >= 1, stats
    finally:
        srv.stop()
        lsock.close()


def test_cpp_relay_exactly_one_response_under_backend_churn():
    """Pipelined relayed traffic while the backend dies and returns: every
    msgid gets EXACTLY one response (backend result or synthesized/Python
    error) — never zero (hang) and never two (the double-apply hazard the
    relay's msgid-ownership handoff exists to prevent)."""
    import socket
    import threading
    import time

    import msgpack

    _skip_unless_native()
    from jubatus_tpu.rpc import native_server

    # flapping backend: a real native rpc server we stop/start; a port
    # listener vacuum between generations makes connects fail cleanly
    backend_port = {"srv": None}

    def start_backend(port=0):
        b = native_server.NativeRpcServer()
        b.register("probe", lambda n, i: i, arity=2)
        p = b.serve_background(port, host="127.0.0.1")
        backend_port["srv"] = b
        return p

    bport = start_backend()
    front = native_server.NativeRpcServer()
    front.register("probe", lambda n, i: -1, arity=2)  # python fallback
    front.serve_background(0, host="127.0.0.1")
    assert front.relay_config(["probe"], {"c": [("127.0.0.1", bport)]},
                              timeout=3.0)
    sock = socket.create_connection(("127.0.0.1", front.port), timeout=30)
    unp = msgpack.Unpacker(raw=False)
    got: dict = {}
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                data = sock.recv(65536)
            except OSError:
                return
            if not data:
                return
            unp.feed(data)
            for msg in unp:
                got[msg[1]] = got.get(msg[1], 0) + 1

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    total = 400
    mid = 0
    try:
        for wave in range(8):
            for _ in range(total // 8):
                mid += 1
                sock.sendall(msgpack.packb([0, mid, "probe", ["c", mid]],
                                           use_bin_type=True))
            if wave % 3 == 1:  # churn: kill the backend mid-wave
                backend_port["srv"].stop()
                time.sleep(0.2)
                newp = start_backend()
                assert front.relay_config(
                    ["probe"], {"c": [("127.0.0.1", newp)]}, timeout=3.0)
            time.sleep(0.15)
        deadline = time.time() + 20.0
        while time.time() < deadline and len(got) < total:
            time.sleep(0.2)
        assert len(got) == total, f"missing responses: {total - len(got)}"
        time.sleep(1.0)  # settle: a LATE duplicate must not escape
        assert len(got) == total
        dupes = {k: v for k, v in got.items() if v != 1}
        assert not dupes, f"duplicated responses: {dupes}"
    finally:
        stop.set()
        sock.close()
        front.stop()
        if backend_port["srv"] is not None:
            backend_port["srv"].stop()
