"""Proxy routing e2e for the CHT-heavy engines: recommender, anomaly,
graph, burst — the routing classes the simpler engines don't exercise
(cht-with-replication writes, broadcast+merge reads, internal methods
excluded from the proxy surface).
"""

from __future__ import annotations

import pytest

from jubatus_tpu.client import (
    AnomalyClient,
    BurstClient,
    Datum,
    GraphClient,
    RecommenderClient,
)
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.server.proxy import Proxy, ProxyArgs

NAME = "pe"

CONV = {"num_rules": [{"key": "*", "type": "num"}]}


def _stack(engine, conf, n=3):
    store = _Store()
    servers = []
    for _ in range(n):
        args = ServerArgs(engine=engine, coordinator="(shared)", name=NAME,
                          listen_addr="127.0.0.1", interval_sec=1e9,
                          interval_count=1 << 30)
        s = EngineServer(engine, conf, args, coord=MemoryCoordinator(store))
        s.start(0)
        servers.append(s)
    proxy = Proxy(ProxyArgs(engine=engine, listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    return servers, proxy


def _teardown(servers, proxy):
    proxy.stop()
    for s in servers:
        s.stop()


def test_recommender_cht_replication_and_queries():
    conf = {"method": "inverted_index", "parameter": {}, "converter": CONV}
    servers, proxy = _stack("recommender", conf)
    try:
        c = RecommenderClient("127.0.0.1", proxy.args.rpc_port, NAME)
        for i in range(8):
            assert c.update_row(f"r{i}", Datum({"x": float(i), "y": 1.0})) is True
        # cht(2) writes: each row must exist on EXACTLY 2 backends
        for i in range(8):
            holders = sum(1 for s in servers
                          if f"r{i}" in s.driver.backend.store)
            assert holders == 2, f"r{i} on {holders} backends"
        # cht-routed read hits a replica that has the row
        sim = c.similar_row_from_id("r3", 3)
        assert sim and sim[0][0] == "r3"
        # broadcast clear wipes every backend
        assert c.clear() is True
        assert all(len(s.driver.backend.store) == 0 for s in servers)
        c.close()
    finally:
        _teardown(servers, proxy)


def test_anomaly_add_random_then_cht_update():
    conf = {"method": "lof",
            "parameter": {"nearest_neighbor_num": 3, "method": "euclid_lsh",
                          "parameter": {"hash_num": 64}},
            "converter": CONV}
    servers, proxy = _stack("anomaly", conf)
    try:
        c = AnomalyClient("127.0.0.1", proxy.args.rpc_port, NAME)
        ids = set()
        for i in range(6):
            rid, score = c.add(Datum({"x": float(i)}))
            ids.add(rid)
            assert isinstance(score, float)
        assert len(ids) == 6  # cluster idgen: no collisions through proxy
        # rows landed somewhere; calc_score routes random and answers
        assert isinstance(c.calc_score(Datum({"x": 2.5})), float)
        assert c.clear() is True
        c.close()
    finally:
        _teardown(servers, proxy)


def test_graph_global_ids_and_broadcast_queries():
    conf = {"method": "graph_wo_index",
            "parameter": {"damping_factor": 0.9, "landmark_num": 3}}
    servers, proxy = _stack("graph", conf)
    try:
        c = GraphClient("127.0.0.1", proxy.args.rpc_port, NAME)
        nids = [c.create_node() for _ in range(4)]
        assert len(set(nids)) == 4  # cluster-unique ids via coordinator
        # shortest-path preset query is broadcast+all_and
        assert c.add_shortest_path_query([[], []]) is True
        c.close()
    finally:
        _teardown(servers, proxy)


def test_burst_broadcast_add_and_keyword_registry():
    conf = {"parameter": {"window_batch_size": 4, "batch_interval": 10,
                          "max_reuse_batch_num": 5, "costcut_threshold": -1,
                          "result_window_rotate_size": 4}}
    servers, proxy = _stack("burst", conf)
    try:
        c = BurstClient("127.0.0.1", proxy.args.rpc_port, NAME)
        assert c.add_keyword(["fire", 2.0, 0.1]) is True
        # broadcast keyword registration reaches every node
        assert all(list(s.driver.keywords) == ["fire"] for s in servers)
        # add_documents broadcasts; #@pass returns ONE node's count, and
        # every node ingested the batch
        n = c.add_documents([[10.0, "fire in the hall"], [10.0, "all calm"]])
        assert n == 2
        st = c.get_status()
        assert len(st) == 3
        kw = c.get_all_keywords()
        assert kw and kw[0][0] == "fire"
        c.close()
    finally:
        _teardown(servers, proxy)


def test_internal_methods_not_exposed_on_proxy():
    conf = {"method": "graph_wo_index",
            "parameter": {"damping_factor": 0.9, "landmark_num": 3}}
    servers, proxy = _stack("graph", conf, n=1)
    try:
        from jubatus_tpu.rpc.client import RpcClient
        from jubatus_tpu.rpc.errors import RpcMethodNotFound

        with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
            with pytest.raises(RpcMethodNotFound):
                c.call("create_node_here", NAME, "x")  # #@internal
    finally:
        _teardown(servers, proxy)


def test_burst_cht_keyword_partitioning_and_rehash():
    """Full stack (VERDICT r1 item 7): keywords are processed only by
    their CHT(2) owners; a membership change re-hashes and the cluster
    still answers correctly after back-fill."""
    import time

    from jubatus_tpu.coord.cht import CHT

    conf = {"parameter": {"window_batch_size": 4, "batch_interval": 10,
                          "max_reuse_batch_num": 5, "costcut_threshold": -1,
                          "result_window_rotate_size": 4}}
    servers, proxy = _stack("burst", conf)
    try:
        c = BurstClient("127.0.0.1", proxy.args.rpc_port, NAME)
        kws = [f"kw{i}" for i in range(8)]
        for kw in kws:
            assert c.add_keyword([kw, 2.0, 1.0]) is True
        docs = [[25.0, " ".join(kws)]] * 3  # every doc mentions every kw
        assert c.add_documents(docs) == 3

        coord = MemoryCoordinator(servers[0].coord._store) \
            if hasattr(servers[0].coord, "_store") else servers[0].coord
        cht = CHT.from_coordinator(coord, "burst", NAME, actives_only=False)
        by_name = {s.self_nodeinfo().name: s for s in servers}
        for kw in kws:
            owners = {n.name for n in cht.find(kw, 2)}
            for nm, srv in by_name.items():
                counts = srv.driver._rel_d.get(kw, {})
                if nm in owners:
                    assert counts, f"{kw} not counted on its owner {nm}"
                else:
                    assert not counts, f"{kw} counted on non-owner {nm}"
        # queries route cht(2) to an owner and see the counts
        res = c.get_result("kw3")
        assert res[1][-1][1] == 3  # relevant_data_count of last batch

        # membership change: kill one server -> remaining re-hash
        victim = servers.pop()
        victim_name = victim.self_nodeinfo().name
        victim.stop()
        deadline = time.time() + 10
        while time.time() < deadline:
            cht2 = CHT.from_coordinator(coord, "burst", NAME,
                                        actives_only=False)
            if victim_name not in {m.name for m in cht2.members}:
                break
            time.sleep(0.1)
        time.sleep(0.3)  # let child watchers deliver the re-hash
        assert c.add_documents(docs) == 3  # broadcast reaches survivors
        cht2 = CHT.from_coordinator(coord, "burst", NAME, actives_only=False)
        for kw in kws:
            owners = {n.name for n in cht2.find(kw, 2)}
            assert victim_name not in owners
            for nm, srv in by_name.items():
                if nm == victim_name:
                    continue
                if nm in owners:
                    assert srv.driver._rel_d.get(kw) or \
                        srv.driver._rel_m.get(kw), \
                        f"{kw} not re-assigned to {nm} after re-hash"
        for kw in kws:
            res = c.get_result(kw)
            assert res[1][-1][0] >= 3  # all_data_count of the last batch
        c.close()
    finally:
        _teardown(servers, proxy)
