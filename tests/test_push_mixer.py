"""Push (gossip) mixer tests (≙ push_mixer_test / skip_mixer_test) plus
cluster-unique id minting for anomaly/graph.

Strategy selection is pure-function tested (the reference's
skip_mixer_test verifies stride candidates the same way); full rounds run
against real in-process clusters like the linear-mixer tests.
"""

from __future__ import annotations

import pytest

from jubatus_tpu.client import AnomalyClient, ClassifierClient, Datum
from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
from jubatus_tpu.framework.push_mixer import (
    DummyMixer,
    broadcast_candidates,
    create_mixer,
    random_candidates,
    skip_candidates,
)
from jubatus_tpu.server import EngineServer
from jubatus_tpu.server.args import ServerArgs

NAME = "pm"

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


def _members(n):
    return [NodeInfo("10.0.0.1", 9000 + i) for i in range(n)]


def test_broadcast_candidates_excludes_self():
    ms = _members(4)
    assert broadcast_candidates(ms, ms[1]) == [ms[0], ms[2], ms[3]]


def test_random_candidates_one_other():
    ms = _members(5)
    for _ in range(20):
        (pick,) = random_candidates(ms, ms[0])
        assert pick.name != ms[0].name
    assert random_candidates([ms[0]], ms[0]) == []


def test_skip_candidates_fingers():
    """8 members sorted by name; node 0's fingers are offsets +1 +2 +4
    (skip_mixer.hpp stride pattern)."""
    ms = _members(8)  # names sort by port
    picks = skip_candidates(ms, ms[0])
    assert [p.port for p in picks] == [9001, 9002, 9004]
    # wrap-around from the last member
    picks = skip_candidates(ms, ms[7])
    assert [p.port for p in picks] == [9000, 9001, 9003]


def test_skip_candidates_unknown_self_falls_back():
    ms = _members(3)
    stranger = NodeInfo("9.9.9.9", 1)
    assert skip_candidates(ms, stranger) == broadcast_candidates(ms, stranger)


def test_factory_selects():
    from jubatus_tpu.framework.linear_mixer import RpcLinearMixer
    from jubatus_tpu.framework.push_mixer import RpcPushMixer

    class _C:  # minimal comm stand-in
        pass

    class _D:
        def get_mixables(self):
            return {}

    assert isinstance(create_mixer("linear_mixer", _D(), _C()), RpcLinearMixer)
    m = create_mixer("skip_mixer", _D(), _C())
    assert isinstance(m, RpcPushMixer) and m.strategy == "skip_mixer"
    assert isinstance(create_mixer("dummy_mixer", _D(), _C()), DummyMixer)
    with pytest.raises(ValueError, match="unknown mixer"):
        create_mixer("nope", _D(), _C())


# -- full gossip rounds over real servers ------------------------------------


def _cluster(engine, conf, n, store, mixer):
    servers = []
    for _ in range(n):
        args = ServerArgs(
            engine=engine, coordinator="(shared)", name=NAME, mixer=mixer,
            listen_addr="127.0.0.1", interval_sec=1e9, interval_count=1 << 30,
        )
        srv = EngineServer(engine, conf, args, coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    return servers


@pytest.mark.parametrize("strategy", ["broadcast_mixer", "random_mixer",
                                      "skip_mixer"])
def test_push_mix_propagates(strategy):
    store = _Store()
    servers = _cluster("classifier", CONF, 2, store, strategy)
    try:
        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        c1 = ClassifierClient("127.0.0.1", servers[1].args.rpc_port, NAME)
        for _ in range(10):
            c0.train([["pos", Datum({"x": 1.0, "y": 0.2})]])
            c1.train([["neg", Datum({"x": -1.0, "y": -0.2})]])
        assert c0.do_mix() is True  # node 0 gossips with node 1
        for c in (c0, c1):
            assert set(c.get_labels()) == {"pos", "neg"}
            (res,) = c.classify([Datum({"x": 1.0, "y": 0.2})])
            assert max(res, key=lambda ls: ls[1])[0] == "pos"
        c0.close(), c1.close()
    finally:
        for s in servers:
            s.stop()


def test_push_mix_three_nodes_broadcast_converges():
    store = _Store()
    servers = _cluster("classifier", CONF, 3, store, "broadcast_mixer")
    try:
        clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, NAME)
                   for s in servers]
        labels = ["a", "b", "c"]
        for c, lab, x in zip(clients, labels, (1.0, -1.0, 0.0)):
            for _ in range(5):
                c.train([[lab, Datum({"x": x, "y": x * 0.5 + 1.0})]])
        # gossip is eventually consistent: one broadcast round per node
        # guarantees full propagation (first exchange may predate later
        # nodes' knowledge)
        for c in clients:
            c.do_mix()
        for c in clients:
            assert set(c.get_labels()) == set(labels)
        for c in clients:
            c.close()
    finally:
        for s in servers:
            s.stop()


def test_push_late_joiner_adopts_full_model():
    """A node joining after gossip rounds ran is version-behind: when ITS
    round initiates, it adopts the peer's full model before folding —
    no actives demotion, no recovery storm (push_mixer phase 2.5)."""
    store = _Store()
    servers = _cluster("classifier", CONF, 2, store, "broadcast_mixer")
    try:
        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        for _ in range(10):
            c0.train([["pos", Datum({"x": 1.0, "y": 0.2})]])
            c0.train([["neg", Datum({"x": -1.0, "y": -0.2})]])
        assert c0.do_mix() is True  # pair now at model version 1
        late = _cluster("classifier", CONF, 1, store, "broadcast_mixer")[0]
        servers.append(late)
        cl = ClassifierClient("127.0.0.1", late.args.rpc_port, NAME)
        assert cl.do_mix() is True  # late node initiates → adopts
        (res,) = cl.classify([Datum({"x": 1.0, "y": 0.2})])
        assert max(res, key=lambda s: s[1])[0] == "pos"
        (st,) = cl.get_status().values()
        assert st["mixer.model_version"] >= 1
        assert st["mixer.obsolete"] is False
        c0.close(), cl.close()
    finally:
        for s in servers:
            s.stop()


# -- cluster-unique id minting -------------------------------------------------


def test_anomaly_ids_unique_across_nodes():
    conf = {"method": "lof",
            "parameter": {"nearest_neighbor_num": 3, "method": "euclid_lsh",
                          "parameter": {"hash_num": 64}},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    store = _Store()
    servers = _cluster("anomaly", conf, 2, store, "linear_mixer")
    try:
        a0 = AnomalyClient("127.0.0.1", servers[0].args.rpc_port, NAME)
        a1 = AnomalyClient("127.0.0.1", servers[1].args.rpc_port, NAME)
        ids = set()
        for a in (a0, a1):
            for i in range(5):
                rid, _score = a.add(Datum({"x": float(i)}))
                ids.add(rid)
        assert len(ids) == 10, "id collision across cluster nodes"
        a0.close(), a1.close()
    finally:
        for s in servers:
            s.stop()


def test_standalone_keeps_local_ids():
    from jubatus_tpu.server.factory import create_driver

    conf = {"method": "lof",
            "parameter": {"nearest_neighbor_num": 3, "method": "euclid_lsh",
                          "parameter": {"hash_num": 64}},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    d = create_driver("anomaly", conf)
    rid, _ = d.add(Datum({"x": 1.0}))
    assert rid == "0"  # local counter, standalone semantics unchanged
