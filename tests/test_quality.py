"""Data-quality plane tests (ISSUE 17): PSI/KL scores, prequential +
calibration math, QualityPlane windowing/drift/gauges under an injected
clock, the fleet fold (merge_quality), incident forensics slice, and
the idempotent get_quality RPC folded through a proxy on BOTH
transports."""

from __future__ import annotations

import time

import numpy as np
import pytest

from jubatus_tpu.utils import quality, sketches, tracing
from jubatus_tpu.utils.quality import (
    QualityPlane, calibration_ece, group_of, kl_from_freqs,
    merge_prequential, merge_quality, prequential_accuracy,
    prequential_mae, psi_from_freqs, psi_value_states, value_freqs,
)

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


def _value_state(values) -> dict:
    sk = sketches.ValueSketch()
    sk.observe_array(np.asarray(values, dtype=np.float64))
    return sk.state()


# -- drift scores ------------------------------------------------------------


def test_psi_zero_on_identical_and_grows_with_shift():
    rng = np.random.default_rng(1)
    base = rng.uniform(0.0, 1.0, size=2000)
    same = psi_value_states(_value_state(base), _value_state(base))
    assert same == pytest.approx(0.0, abs=1e-9)
    small = psi_value_states(_value_state(base),
                             _value_state(base * 1.05))
    big = psi_value_states(_value_state(base),
                           _value_state(base + 0.8))
    assert 0.0 <= small < big
    assert big > quality.DEFAULT_DRIFT_THRESHOLD


def test_psi_symmetric_kl_not():
    p = {"a": 0.8, "b": 0.2}
    q = {"a": 0.3, "b": 0.7}
    assert psi_from_freqs(p, q) == pytest.approx(psi_from_freqs(q, p))
    assert psi_from_freqs(p, q) == pytest.approx(
        kl_from_freqs(p, q) + kl_from_freqs(q, p))
    assert psi_from_freqs({}, {}) == 0.0
    # disjoint support stays finite (smoothing)
    assert np.isfinite(psi_from_freqs({"a": 1.0}, {"b": 1.0}))


def test_value_freqs_coarsens_and_normalizes():
    st = _value_state([1.0] * 60 + [-1.0] * 40)
    fr = value_freqs(st)
    assert sum(fr.values()) == pytest.approx(1.0)
    # octave coarsening: strictly fewer support points than raw bins
    assert len(fr) <= len(st["bins"])


def test_group_of_prefix_rules():
    assert group_of("ch003") == "ch"
    assert group_of("user@str$tokyo") == "user"
    assert group_of("age") == "age"
    assert group_of("7seas") == "other"
    assert group_of("") == "other"


# -- prequential + calibration -----------------------------------------------


def test_prequential_merge_and_scores():
    a = quality._empty_prequential()
    a.update(n=10, correct=7, abs_err=2.0, sq_err=1.0)
    b = quality._empty_prequential()
    b.update(n=30, correct=18, abs_err=6.0, sq_err=3.0)
    m = merge_prequential([a, b, {}])
    assert m["n"] == 40 and m["correct"] == 25
    assert prequential_accuracy(m) == pytest.approx(25 / 40)
    assert prequential_mae(m) == pytest.approx(8.0 / 40)
    assert prequential_accuracy({"n": 0}) is None


def test_calibration_ece_weighted_gap():
    st = quality._empty_prequential()
    # bin 9: 100 rows at conf 0.95, 60% right -> gap 0.35
    st["conf"][9] = [100, 60, 95.0]
    # bin 5: 100 rows at conf 0.55, 55% right -> gap 0.0
    st["conf"][5] = [100, 55, 55.0]
    assert calibration_ece(st) == pytest.approx(0.5 * 0.35 + 0.5 * 0.0)
    assert calibration_ece(quality._empty_prequential()) is None


def test_record_classified_uses_top_ranked_and_bins_confidence():
    plane = QualityPlane(sample=1.0, window_s=60.0)
    plane.record_classified("a", [("a", 5.0), ("b", 0.0)])
    plane.record_classified("a", [("b", 5.0), ("a", 0.0)])
    snap = plane.snapshot()
    preq = snap["prequential"]
    assert preq["n"] == 2 and preq["correct"] == 1
    assert sum(r[0] for r in preq["conf"]) == 2
    # the prediction-output sketch saw both winners
    assert snap["live"]["predictions"]["total"] == 2


# -- sampling gate -----------------------------------------------------------


def test_admit_stride_sampler_is_deterministic():
    plane = QualityPlane(sample=0.25, window_s=60.0)
    hits = [plane.admit("fv") for _ in range(100)]
    assert sum(hits) == 25
    # a second gate strides independently
    assert sum(plane.admit("train") for _ in range(8)) == 2
    off = QualityPlane(sample=0.0, window_s=60.0)
    assert not any(off.admit("fv") for _ in range(10))


# -- plane windowing + drift gauges ------------------------------------------


def _plane(reg=None, **kw):
    kw.setdefault("sample", 1.0)
    kw.setdefault("window_s", 1.0)
    kw.setdefault("ref_windows", 1)
    kw.setdefault("drift_min_count", 10)
    return QualityPlane(registry=reg, **kw)


def test_plane_rolls_windows_pins_reference_and_scores_drift():
    reg = tracing.Registry()
    plane = _plane(reg)
    rng = np.random.default_rng(2)
    plane.tick(now=1000.0)  # stamps the live window start
    names = ["ch%d" % i for i in range(100)]
    plane.record_named(names, rng.uniform(0.0, 1.0, size=100))
    g = plane.tick(now=1002.0)  # rolls window 1 -> reference pinned
    assert plane.ring.reference is not None
    assert g["quality.drift.max"] == 0.0  # nothing to compare yet
    plane.record_named(names, rng.uniform(0.0, 1.0, size=100) + 0.8)
    g = plane.tick(now=1004.0)  # rolls the shifted window, scores it
    assert g["quality.drift.ch"] > quality.DEFAULT_DRIFT_THRESHOLD
    assert g["quality.drift.max"] == g["quality.drift.ch"]
    gauges = reg.gauges()
    assert gauges["quality.drift.ch"] == g["quality.drift.ch"]
    assert gauges["quality.drift.max"] == g["quality.drift.max"]
    assert reg.counters()["quality.recorded_values"] == 200
    snap = plane.snapshot()
    assert snap["drift"]["ch"] == g["quality.drift.ch"]
    assert snap["stats"]["reference_pinned"]
    assert [p["drift_max"] for p in snap["trend"]][-1] > 0.2


def test_drift_max_rollup_excludes_model_output_keys():
    """quality.drift.max pages on INPUT drift only: a cold model's
    prediction mix swinging between windows moves its own
    quality.drift.label_predictions gauge but must not move the
    roll-up the input-drift SLO rides."""
    plane = _plane()
    names = ["ch%d" % i for i in range(50)]
    vals = np.linspace(0.05, 0.95, 50)  # byte-identical both windows
    plane.tick(now=1000.0)
    plane.record_named(names, vals)
    for _ in range(20):
        plane.record_classified("a", [("a", 3.0), ("b", 0.0)])
    plane.tick(now=1002.0)  # reference: stable inputs, all-"a" outputs
    plane.record_named(names, vals)
    for _ in range(20):
        plane.record_classified("a", [("b", 3.0), ("a", 0.0)])
    g = plane.tick(now=1004.0)  # output mix flipped, inputs identical
    assert g["quality.drift.label_predictions"] > 1.0
    assert g["quality.drift.ch"] == 0.0
    assert g["quality.drift.max"] == 0.0


def test_plane_prequential_gauges_publish_on_tick():
    reg = tracing.Registry()
    plane = _plane(reg)
    for i in range(20):
        truth = "a" if i < 15 else "b"
        plane.record_classified(truth, [("a", 3.0), ("b", 0.0)])
    g = plane.tick(now=50.0)
    assert g["quality.prequential.accuracy"] == pytest.approx(0.75)
    assert g["quality.prequential.error_rate"] == pytest.approx(0.25)
    assert "quality.calibration.ece" in g
    assert reg.gauges()["quality.prequential.accuracy"] == \
        pytest.approx(0.75)
    assert reg.counters()["quality.scored_rows"] == 20
    st = plane.stats()
    assert st["scored_rows"] == 20
    assert st["prequential_accuracy"] == pytest.approx(0.75)


def test_plane_group_cap_overflows_not_grows():
    plane = _plane()
    for i in range(quality.MAX_GROUPS + 20):
        plane.record_named(["grp%s@x" % chr(97 + i % 26) * (i // 26 + 1)],
                           np.array([1.0]))
    snap = plane.snapshot()
    assert snap["stats"]["groups"] <= quality.MAX_GROUPS + 1
    # past the cap new names fold into the overflow group
    plane2 = _plane()
    for i in range(quality.MAX_GROUPS):
        plane2._group_sketch("g%s" % i if False else "u" + "x" * i)
    assert plane2._group_sketch("brand_new") is \
        plane2._groups[quality.OVERFLOW_GROUP]


def test_plane_small_live_window_holds_last_drift_via_ring():
    """Mid-window (too few live values) the tick scores the NEWEST
    completed window instead of noise."""
    plane = _plane(drift_min_count=50)
    rng = np.random.default_rng(4)
    plane.tick(now=0.0)
    plane.record_named(["v%d" % i for i in range(200)],
                       rng.uniform(size=200))
    plane.tick(now=2.0)  # reference
    plane.record_named(["v%d" % i for i in range(200)],
                       rng.uniform(size=200) + 1.0)
    g1 = plane.tick(now=4.0)  # shifted window rolled
    assert g1["quality.drift.v"] > 0.2
    # 3 live values < min_count: drift keeps scoring the rolled window
    plane.record_named(["v1", "v2", "v3"], np.array([9.0, 9.0, 9.0]))
    g2 = plane.tick(now=4.5)
    assert g2["quality.drift.v"] == g1["quality.drift.v"]


def test_incident_doc_names_top_group_with_sketch_pair():
    plane = _plane()
    rng = np.random.default_rng(6)
    plane.tick(now=0.0)
    plane.record_named(["se%d" % i for i in range(100)],
                       rng.uniform(size=100))
    plane.record_named(["ch%d" % i for i in range(100)],
                       rng.uniform(size=100))
    plane.tick(now=2.0)
    plane.record_named(["se%d" % i for i in range(100)],
                       rng.uniform(size=100))          # stable group
    plane.record_named(["ch%d" % i for i in range(100)],
                       rng.uniform(size=100) + 2.0)    # shifted group
    plane.tick(now=4.0)
    doc = plane.incident_doc()
    assert doc["top_drift_group"] == "ch"
    assert doc["drift"]["ch"] > doc["drift"]["se"]
    assert doc["reference_sketch"]["count"] == 100
    assert doc["live_sketch"]["count"] == 100


# -- fleet folds -------------------------------------------------------------


def test_merge_quality_recomputes_drift_from_merged_sketches():
    """Two half-fleet nodes with opposite half-shifts: the fold merges
    sketches and rescoring sees the TRUE fleet drift, not an average of
    node scores."""
    rng = np.random.default_rng(8)
    docs = []
    for shift in (0.0, 0.8):
        plane = _plane()
        plane.tick(now=0.0)
        plane.record_named(["ad%d" % i for i in range(200)],
                           rng.uniform(size=200))
        plane.tick(now=2.0)
        plane.record_named(["ad%d" % i for i in range(200)],
                           rng.uniform(size=200) + shift)
        for i in range(10):
            plane.record_classified("a", [("a", 1.0), ("b", 0.0)])
        plane.tick(now=2.5)  # mid-window: live sketches stay populated
        docs.append(plane.snapshot())
    fleet = merge_quality(docs)
    assert fleet["nodes"] == 2
    # node gauges score COMPLETED windows only, so mid-window both
    # still read 0.0 — while the fold, rescoring the MERGED live
    # sketches (clean half + shifted half), already sees the fleet
    # truth no per-node score carries yet: recomputed, not averaged
    per_node = [d["drift"].get("ad", 0.0) for d in docs]
    assert per_node == [0.0, 0.0]
    assert fleet["drift"]["ad"] > quality.DEFAULT_DRIFT_THRESHOLD
    assert fleet["prequential"]["n"] == 20
    assert fleet["reference"]["features"]["ad"]["count"] == 400
    assert fleet["live"]["features"]["ad"]["count"] == 400
    assert len(fleet["trend"]) > 0


def test_merge_quality_falls_back_to_node_drift_mid_window():
    """A node whose live window just rolled ships empty live sketches;
    its last computed drift still reaches the fleet doc (per-key max)."""
    doc = {"reference": None, "live": None,
           "drift": {"ch": 3.1, "labels": 0.4},
           "prequential": quality._empty_prequential(),
           "trend": [], "sample": 0.1}
    worse = dict(doc, drift={"ch": 5.2})
    fleet = merge_quality([doc, {}, worse])
    assert fleet["drift"] == {"ch": 5.2, "labels": 0.4}  # per-key max
    assert fleet["nodes"] == 2
    assert fleet["sample"] == 0.1


# -- wire: get_quality through server + proxy on both transports -------------


@pytest.mark.parametrize("native", [False, True])
def test_get_quality_rpc_and_proxy_fold(monkeypatch, native, tmp_path):
    """get_quality is served by every member and folded through the
    proxy (broadcast + fold) on the python AND native transports."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.rpc import native_server
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1" if native else "0")
    store = _Store()
    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", coordinator="(shared)",
                        name="ql", listen_addr="127.0.0.1",
                        interval_sec=1e9, interval_count=1 << 30,
                        telemetry_interval=0, quality_sample=1.0,
                        quality_window=1.0, quality_ref_windows=1),
        coord=MemoryCoordinator(store))
    srv.start(0)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1",
                            telemetry_interval=0),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    try:
        rng = np.random.default_rng(9)
        q = srv.quality
        assert q is not None
        base = time.time()
        q.tick(now=base)
        q.record_named(["ch%d" % i for i in range(100)],
                       rng.uniform(size=100))
        q.tick(now=base + 2.0)
        q.record_named(["ch%d" % i for i in range(100)],
                       rng.uniform(size=100) + 0.9)
        q.record_classified("a", [("a", 1.0), ("b", 0.0)])
        q.tick(now=base + 4.0)
        node = srv.self_nodeinfo().name
        # direct member call
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            direct = c.call("get_quality", "ql")
        assert direct[node]["drift"]["ch"] > 0.2
        assert direct[node]["stats"]["reference_pinned"]
        # proxied call: broadcast + fold; the proxy's own (empty) doc
        # folds away, the backend doc survives
        with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
            folded = c.call("get_quality", "ql")
        assert node in folded
        assert folded[node]["drift"]["ch"] == direct[node]["drift"]["ch"]
        assert folded[node]["prequential"]["n"] == 1
        fleet = merge_quality(list(folded.values()))
        assert fleet["drift"]["ch"] > 0.2
        # get_status carries the flat quality.* rows
        with RpcClient("127.0.0.1", srv.args.rpc_port) as c:
            st = c.call("get_status", "ql")
        rows = list(st.values())[0]
        assert rows["quality.recorded_rows"] == 200
        assert rows["quality.reference_pinned"] is True
    finally:
        proxy.stop()
        srv.stop()
