"""PA regression kernel tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from jubatus_tpu.core.sparse import SparseBatch
from jubatus_tpu.ops import regression as R

DIM = 1 << 12


def make_linear(rng, n, n_features=8, noise=0.01):
    feat_idx = rng.choice(np.arange(1, DIM), size=n_features, replace=False)
    w_true = rng.normal(size=n_features)
    x = rng.normal(size=(n, n_features))
    y = x @ w_true + noise * rng.normal(size=n)
    vectors = [
        [(int(feat_idx[j]), float(x[i, j])) for j in range(n_features)]
        for i in range(n)
    ]
    return vectors, y


@pytest.mark.parametrize("method", R.METHODS)
def test_regression_learns(method, rng):
    vectors, y = make_linear(rng, 400)
    sb = SparseBatch.from_vectors(vectors)
    idx, val = jnp.asarray(sb.idx), jnp.asarray(sb.val)
    targets = jnp.asarray(y, jnp.float32)
    state = R.init_state(DIM)
    for _ in range(5):
        state = R.train_batch(state, idx, val, targets, 0.01, 1.0, method=method)
    pred = R.estimate(state, idx, val)
    rmse = float(jnp.sqrt(jnp.mean((pred - targets) ** 2)))
    assert rmse < 0.25, f"{method}: rmse={rmse}"


def test_mix_two_replicas(rng):
    vectors, y = make_linear(rng, 400)
    states = []
    for lo, hi in ((0, 200), (200, 400)):
        sb = SparseBatch.from_vectors(vectors[lo:hi])
        st = R.init_state(DIM)
        for _ in range(3):
            st = R.train_batch(
                st, jnp.asarray(sb.idx), jnp.asarray(sb.val),
                jnp.asarray(y[lo:hi], jnp.float32), 0.01, 1.0, method="PA1",
            )
        states.append(st)
    total = R.mix_diffs(R.get_diff(states[0]), R.get_diff(states[1]))
    mixed = R.put_diff(states[0], total)
    sb = SparseBatch.from_vectors(vectors)
    pred = R.estimate(mixed, jnp.asarray(sb.idx), jnp.asarray(sb.val))
    rmse = float(jnp.sqrt(jnp.mean((pred - jnp.asarray(y, jnp.float32)) ** 2)))
    assert rmse < 0.5
