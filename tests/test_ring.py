"""Ring all-to-all scan tests (parallel/ring.py) on the 8-device CPU mesh.

The ring result must exactly match a dense single-device scan: same
distances, same winner set — the rotation is an execution strategy, not
an approximation. Also checks the generic ring_scan visits every block
exactly once with correct origin attribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map

from jubatus_tpu.ops import knn
from jubatus_tpu.parallel.mesh import grid_mesh
from jubatus_tpu.parallel.ring import (
    ring_euclid_topk,
    ring_hamming_topk,
    ring_scan,
    shard_rows,
)

S = 8  # conftest forces an 8-device CPU platform


@pytest.fixture(scope="module")
def mesh():
    return grid_mesh(replica=1, shard=S)


def test_ring_scan_visits_every_block_once(mesh):
    """Each device must accumulate sum over ALL blocks, with origin ids
    summing to 0+1+...+S-1 — catches rotation/origin bookkeeping bugs."""
    blocks = jnp.arange(S, dtype=jnp.float32).reshape(S, 1) * 10.0

    def shard_fn(blk):
        def step(carry, block, origin):
            total, origin_sum = carry
            return total + block.sum(), origin_sum + origin

        total, origin_sum = ring_scan(
            step, (jnp.float32(0), jnp.int32(0)), blk, "shard")
        return total[None], origin_sum[None]

    total, origin_sum = shard_map(
        shard_fn, mesh=mesh, in_specs=(P("shard", None),),
        out_specs=(P("shard"), P("shard")), check_vma=False,
    )(blocks)
    np.testing.assert_allclose(np.asarray(total), np.full(S, 10.0 * sum(range(S))))
    assert np.asarray(origin_sum).tolist() == [sum(range(S))] * S


def _sparse_rows(rng, n, nnz, dim):
    idx = rng.integers(1, dim, size=(n, nnz)).astype(np.int32)
    val = rng.normal(size=(n, nnz)).astype(np.float32)
    return jnp.asarray(idx), jnp.asarray(val)


def test_ring_hamming_matches_dense(mesh, rng):
    hash_num, dim, nnz = 64, 1 << 12, 8
    B, C, k = 16, 64, 5
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_sigs = knn.lsh_signature(qi, qv, hash_num=hash_num)
    row_sigs = knn.lsh_signature(ri, rv, hash_num=hash_num)

    d, gidx = ring_hamming_topk(
        mesh,
        shard_rows(mesh, q_sigs),
        shard_rows(mesh, row_sigs),
        hash_num=hash_num, k=k,
    )
    d, gidx = np.asarray(d), np.asarray(gidx)

    dense = np.asarray(
        knn._hamming_distances_batch_xla(q_sigs, row_sigs, hash_num=hash_num))
    for b in range(B):
        want = np.sort(dense[b])[:k]
        np.testing.assert_allclose(np.sort(d[b]), want, rtol=1e-6)
        # returned ids really score those distances
        np.testing.assert_allclose(
            np.sort(dense[b][gidx[b]]), want, rtol=1e-6)


def test_ring_euclid_matches_dense(mesh, rng):
    dim, nnz = 1 << 10, 6
    B, C, k = 8, 32, 4
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_dense = jnp.stack([knn.densify(qi[b], qv[b], dim=dim) for b in range(B)])

    d, gidx = ring_euclid_topk(
        mesh,
        shard_rows(mesh, q_dense),
        shard_rows(mesh, ri),
        shard_rows(mesh, rv),
        k=k,
    )
    d, gidx = np.asarray(d), np.asarray(gidx)

    for b in range(B):
        dense = np.asarray(knn.euclid_distances(ri, rv, q_dense[b]))
        want = np.sort(dense)[:k]
        np.testing.assert_allclose(np.sort(d[b]), want, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.sort(dense[gidx[b]]), want,
                                   rtol=1e-5, atol=1e-5)


def test_ring_k_exceeding_table_clamps(mesh, rng):
    """k > C must clamp to C (no +inf/fabricated-id padding columns)."""
    hash_num, dim, nnz = 32, 1 << 10, 4
    B, C = 8, 16
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_sigs = knn.lsh_signature(qi, qv, hash_num=hash_num)
    row_sigs = knn.lsh_signature(ri, rv, hash_num=hash_num)
    d, gidx = ring_hamming_topk(
        mesh, shard_rows(mesh, q_sigs), shard_rows(mesh, row_sigs),
        hash_num=hash_num, k=24,
    )
    assert d.shape == (B, C) and gidx.shape == (B, C)
    assert np.isfinite(np.asarray(d)).all()
    for b in range(B):
        assert sorted(np.asarray(gidx)[b].tolist()) == list(range(C))


def test_ring_k_larger_than_local_block(mesh, rng):
    """k spanning multiple blocks: the running merge must keep candidates
    from several origins (c_local = 2 here, k = 6)."""
    hash_num, dim, nnz = 32, 1 << 10, 4
    B, C, k = 8, 16, 6
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_sigs = knn.lsh_signature(qi, qv, hash_num=hash_num)
    row_sigs = knn.lsh_signature(ri, rv, hash_num=hash_num)

    d, gidx = ring_hamming_topk(
        mesh, shard_rows(mesh, q_sigs), shard_rows(mesh, row_sigs),
        hash_num=hash_num, k=k,
    )
    d, gidx = np.asarray(d), np.asarray(gidx)
    dense = np.asarray(
        knn._hamming_distances_batch_xla(q_sigs, row_sigs, hash_num=hash_num))
    for b in range(B):
        np.testing.assert_allclose(np.sort(d[b]), np.sort(dense[b])[:k],
                                   rtol=1e-6)
        assert len(set(gidx[b].tolist())) == k  # no duplicate winners


def test_ring_euclid_valid_mask_hides_dead_rows(mesh, rng):
    """Deleted/padding rows must never surface as finite euclid hits
    (ADVICE round 1: ring_euclid_topk had no valid mask)."""
    dim, nnz = 1 << 10, 6
    B, C, k = 8, 32, 4
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_dense = jnp.stack([knn.densify(qi[b], qv[b], dim=dim) for b in range(B)])
    valid = np.ones(C, bool)
    valid[::3] = False

    d, gidx = ring_euclid_topk(
        mesh,
        shard_rows(mesh, q_dense),
        shard_rows(mesh, ri),
        shard_rows(mesh, rv),
        k=k,
        valid=shard_rows(mesh, jnp.asarray(valid)),
    )
    d, gidx = np.asarray(d), np.asarray(gidx)
    finite = np.isfinite(d)
    assert valid[gidx[finite]].all(), "masked row surfaced as a finite hit"
    for b in range(B):
        dense = np.asarray(knn.euclid_distances(ri, rv, q_dense[b]))
        want = np.sort(np.where(valid, dense, np.inf))[:k]
        np.testing.assert_allclose(np.sort(d[b]), want, rtol=1e-5, atol=1e-5)


def test_ring_rejects_indivisible_row_count(mesh, rng):
    """C % shards != 0 must raise, not silently drop rows."""
    dim, nnz, hash_num = 1 << 10, 4, 32
    B, C = 8, 13  # 13 % 8 != 0
    qi, qv = _sparse_rows(rng, B, nnz, dim)
    ri, rv = _sparse_rows(rng, C, nnz, dim)
    q_sigs = knn.lsh_signature(qi, qv, hash_num=hash_num)
    row_sigs = knn.lsh_signature(ri, rv, hash_num=hash_num)
    with pytest.raises(ValueError, match="not divisible"):
        ring_hamming_topk(mesh, q_sigs, row_sigs, hash_num=hash_num, k=4)
