"""Sharded row store tests (parallel/row_store.py, ISSUE 13): CHT-stable
shard placement, arena growth/eviction parity with the flat store, the
log-depth on-device top-k merge, migration-plane landing (PR 10 wire
format rows arrive in the owning shard and stay out of the next mix
diff), and serve_range walking shards without touching the device
table."""

from __future__ import annotations

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jubatus_tpu.coord.cht import CHT, shard_for
from jubatus_tpu.coord.base import NodeInfo
from jubatus_tpu.core.row_store import RowStore
from jubatus_tpu.models._nn_backend import NNBackend
from jubatus_tpu.parallel.row_store import ShardedRowStore

DIM = 1 << 10


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("shard",))


def _vec(rng, nnz=6):
    idx = rng.integers(1, DIM, size=nnz)
    val = rng.normal(size=nnz)
    return [(int(i), float(v)) for i, v in zip(idx, val)]


# -- store semantics ---------------------------------------------------------

def test_placement_is_cht_stable(rng):
    s = ShardedRowStore(n_shards=4)
    for i in range(300):
        rid = f"row{i}"
        s.set_row(rid, _vec(rng))
        shard, local = s.shard_slot(rid)
        assert shard == shard_for(rid, 4)
        assert 0 <= local < s.cap_per_shard
        assert s.slots[rid] == shard * s.cap_per_shard + local
    assert sum(s.rows_per_shard()) == 300


def test_growth_preserves_rows_and_shards(rng):
    s = ShardedRowStore(n_shards=3, capacity_per_shard=4)
    vecs = {f"r{i}": _vec(rng) for i in range(200)}   # forces many doublings
    for rid, v in vecs.items():
        s.set_row(rid, v)
    assert s.cap_per_shard > 4
    for rid, v in vecs.items():
        got = s.get_row(rid)
        assert [i for i, _ in got] == [i for i, _ in v]
        np.testing.assert_allclose([x for _, x in got], [x for _, x in v],
                                   rtol=1e-6)   # f32 round-trip
        assert s.shard_slot(rid)[0] == shard_for(rid, 3)
    live = s.live_mask()
    assert live.sum() == 200
    assert len(live) == s.capacity


def test_remove_reuses_slots_and_lru_eviction(rng):
    s = ShardedRowStore(n_shards=2, max_size=10)
    for i in range(10):
        s.set_row(f"r{i}", _vec(rng))
    s.get_row("r0")
    s.touch("r0")   # refresh r0; r1 becomes the LRU victim
    s.set_row("r10", _vec(rng))
    assert len(s) == 10 and "r1" not in s and "r0" in s
    cap_before = s.capacity
    s.remove_row("r2")
    s.set_row("r11", _vec(rng))
    assert s.capacity == cap_before   # freed slots are reused


def test_flat_parity_and_pack_interchange(rng):
    """Same rows, same pack format: flat and sharded stores interchange
    checkpoints, and a 4-shard pack re-places into a 2-shard store
    (reshard-on-restore for the instance engines)."""
    flat, sh4 = RowStore(), ShardedRowStore(n_shards=4)
    vecs = {f"r{i}": _vec(rng) for i in range(64)}
    for rid, v in vecs.items():
        flat.set_row(rid, v)
        sh4.set_row(rid, v)
    assert sorted(flat.all_ids()) == sorted(sh4.all_ids())
    p = sh4.pack()
    assert set(p["rows"]) == set(flat.pack()["rows"])
    sh2 = ShardedRowStore(n_shards=2)
    sh2.unpack(p)
    for rid, v in vecs.items():
        got = sh2.get_row(rid)
        assert [i for i, _ in got] == [i for i, _ in v]
        np.testing.assert_allclose([x for _, x in got], [x for _, x in v],
                                   rtol=1e-6)   # f32 round-trip
        assert sh2.shard_slot(rid)[0] == shard_for(rid, 2)
    back = RowStore()
    back.unpack(p)
    assert sorted(back.all_ids()) == sorted(flat.all_ids())


def test_per_shard_update_diffs(rng):
    s = ShardedRowStore(n_shards=4)
    for i in range(40):
        s.set_row(f"r{i}", _vec(rng))
    per = s.pop_update_diff_sharded()
    assert len(per) == 4
    assert sum(len(d) for d in per) == 40
    for k, d in enumerate(per):
        for rid in d:
            assert shard_for(rid, 4) == k
    assert not s.updated_since_mix   # tracker drained
    # applying a diff does not re-enter the next diff
    s.apply_update_diff({"rx": ([1, 2], [0.5, 0.5], None)})
    assert s.pop_update_diff() == {}


# -- sharded top-k via the backend -------------------------------------------

@pytest.mark.parametrize("n_shards", (2, 3, 8))
def test_backend_topk_matches_dense(n_shards, rng):
    dense = NNBackend("lsh", dim=DIM, hash_num=64)
    shard = NNBackend("lsh", dim=DIM, hash_num=64)
    shard.attach_mesh(_mesh(n_shards))
    assert isinstance(shard.store, ShardedRowStore) or n_shards == 1
    vecs = {f"r{i}": _vec(rng) for i in range(120)}
    for rid, v in vecs.items():
        dense.set_row(rid, v)
        shard.set_row(rid, v)
    q = _vec(rng)
    want = dense.neighbors(q, 9)
    got = shard.neighbors(q, 9)
    np.testing.assert_allclose([d for _, d in got], [d for _, d in want],
                               rtol=1e-5, atol=1e-6)
    want_by_id = dict(want)
    for rid, d in got:
        if rid in want_by_id:   # hash ties may swap equal-distance ids
            np.testing.assert_allclose(d, want_by_id[rid],
                                       rtol=1e-5, atol=1e-6)
    assert shard.last_topk_ms is not None and shard.last_topk_ms > 0
    st = shard.shard_stats()
    assert st["count"] == n_shards and st["rows"] == 120
    assert st["topk_merge_ms"] == round(shard.last_topk_ms, 3)


def test_merge_topk_matches_flat_selection(rng):
    """The log-depth tree merge must pick exactly the global top-k the
    flat [B, S*kk] selection picks (distinct scores: no tie ambiguity),
    including non-power-of-two shard counts (odd-carry path)."""
    import jax.numpy as jnp

    from jubatus_tpu.parallel.sharded_knn import merge_topk

    for s_count in (2, 3, 5, 8):
        scores = rng.permutation(s_count * 4 * 7).reshape(
            s_count, 4, 7).astype(np.float32)
        ids = np.arange(s_count * 4 * 7).reshape(s_count, 4, 7)
        got_s, got_i = merge_topk(jnp.asarray(scores), jnp.asarray(ids), 5)
        flat_s = scores.transpose(1, 0, 2).reshape(4, -1)
        flat_i = ids.transpose(1, 0, 2).reshape(4, -1)
        order = np.argsort(-flat_s, axis=1)[:, :5]
        np.testing.assert_allclose(np.asarray(got_s),
                                   np.take_along_axis(flat_s, order, 1))
        np.testing.assert_array_equal(np.asarray(got_i),
                                      np.take_along_axis(flat_i, order, 1))


# -- migration plane ---------------------------------------------------------

def _nn_driver(mesh=None):
    from jubatus_tpu.server.factory import create_driver

    cfg = {"method": "lsh", "parameter": {"hash_num": 64},
           "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    return create_driver("nearest_neighbor", cfg, mesh=mesh)


def test_migrated_rows_land_in_owning_shard_and_skip_next_diff(rng):
    """ISSUE 13 satellite: a row pushed via the PR 10 wire format
    (NNRowMigration.put_rows: [id, idx, val, datum]) lands in the
    CHT-owned shard arena and is excluded from the next mix diff."""
    drv = _nn_driver(mesh=_mesh(4))
    store = drv.backend.store
    assert isinstance(store, ShardedRowStore)
    rows = [[f"m{i}",
             [int(j) for j in rng.integers(1, DIM, size=5)],
             [float(v) for v in rng.normal(size=5)], None]
            for i in range(24)]
    n = drv.put_rows(rows)
    assert n == 24
    for row in rows:
        rid = row[0]
        shard, _local = store.shard_slot(rid)
        assert shard == shard_for(rid, 4)
    # migrated rows already live on their owners: next diff must be empty
    diff = drv.get_mixables()["rows"].get_diff()
    assert diff == {}
    # a LOCAL write after migration does enter the diff
    from jubatus_tpu.core.datum import Datum

    drv.set_row("local1", Datum({"f0": 1.0}))
    diff = drv.get_mixables()["rows"].get_diff()
    assert set(diff) == {"local1"}


def test_serve_range_walks_shards_without_device_table(rng):
    """serve_range over a sharded store must stay on host metadata —
    the device table (device_view / the mesh signature upload) must
    never be materialized by a migration walk."""
    from jubatus_tpu.framework.migration import serve_range

    drv = _nn_driver(mesh=_mesh(4))
    from jubatus_tpu.core.datum import Datum

    for i in range(60):
        drv.set_row(f"r{i:03d}", Datum(
            {f"f{j}": float(v) for j, v in enumerate(rng.normal(size=5))}))
    store = drv.backend.store

    def boom(*a, **k):   # any device materialization fails the test
        raise AssertionError("serve_range touched the device table")

    store.device_view = boom
    drv.backend._mesh_view = boom
    members = [NodeInfo("10.0.0.1", 9199), NodeInfo("10.0.0.2", 9199)]
    ring = CHT(members, epoch=1)
    target = members[0].name
    got, cursor, rounds = [], "", 0
    while True:
        doc = serve_range(drv, ring, target, cursor, limit_bytes=512)
        got.extend(doc["rows"])
        rounds += 1
        if doc["done"]:
            break
        cursor = doc["cursor"]
    assert rounds > 1   # byte budget actually chunked the walk
    ids = [r[0] for r in got]
    assert ids == sorted(ids)   # cursor-exact sorted walk
    from jubatus_tpu.framework.migration import row_owned_by

    want = [rid for rid in sorted(store.all_ids())
            if row_owned_by(ring, rid, target)]
    assert ids == want


def test_shard_ids_covers_all_rows(rng):
    s = ShardedRowStore(n_shards=4)
    for i in range(50):
        s.set_row(f"q{i}", _vec(rng))
    seen = []
    for k in range(4):
        ids = s.shard_ids(k)
        for rid in ids:
            assert shard_for(rid, 4) == k
        seen.extend(ids)
    assert sorted(seen) == sorted(s.all_ids())
