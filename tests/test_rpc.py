"""Loopback RPC tests (≙ mprpc/rpc_client_test.cpp, SURVEY.md §4 tier 3).

Real server on an ephemeral port; typed calls, arity errors, method-not-found,
fan-out with reducers, per-host error collection.
"""

from __future__ import annotations

import pytest

from jubatus_tpu.rpc import (
    RpcCallError,
    RpcClient,
    RpcIoError,
    RpcMClient,
    RpcMethodNotFound,
    RpcServer,
    RpcTypeError,
)
from jubatus_tpu.rpc import aggregators


@pytest.fixture()
def server():
    srv = RpcServer()
    srv.register("echo", lambda x: x)
    srv.register("add2", lambda a, b: a + b)
    srv.register("boom", lambda: (_ for _ in ()).throw(ValueError("kaboom")))
    srv.register("dict_of", lambda k, v: {k: v})
    port = srv.serve_background(0, nthreads=4, host="127.0.0.1")
    yield ("127.0.0.1", port), srv
    srv.stop()


def test_typed_calls(server):
    (host, port), _ = server
    with RpcClient(host, port) as c:
        assert c.call("echo", "hello") == "hello"
        assert c.call("add2", 2, 3) == 5
        assert c.call("dict_of", "k", [1, 2]) == {"k": [1, 2]}


def test_pipelined_calls_one_connection(server):
    (host, port), _ = server
    with RpcClient(host, port) as c:
        for i in range(50):
            assert c.call("add2", i, i) == 2 * i


def test_method_not_found(server):
    (host, port), _ = server
    with RpcClient(host, port) as c:
        with pytest.raises(RpcMethodNotFound):
            c.call("nope")


def test_arity_error(server):
    (host, port), _ = server
    with RpcClient(host, port) as c:
        with pytest.raises(RpcTypeError):
            c.call("add2", 1)


def test_call_error(server):
    (host, port), _ = server
    with RpcClient(host, port) as c:
        with pytest.raises(RpcCallError, match="kaboom"):
            c.call("boom")


def test_connect_refused():
    c = RpcClient("127.0.0.1", 1)  # nothing listens on port 1
    with pytest.raises(RpcIoError):
        c.call("echo", 1)


def _spawn(value):
    srv = RpcServer()
    srv.register("value", lambda: value)
    srv.register("concat_val", lambda: [value])
    port = srv.serve_background(0, host="127.0.0.1")
    return srv, ("127.0.0.1", port)


def test_mclient_fold_order():
    """Fold order matches the reference: (((1+2)+3)+4) left fold over the
    host list (linear_mixer_test.cpp '(4+(3+(2+1)))' is the same associativity
    seen from the other end)."""
    servers = [_spawn(v) for v in (1, 2, 3, 4)]
    try:
        mc = RpcMClient([hp for _, hp in servers])
        assert mc.call_fold("value", reducer=aggregators.add) == 10
        got = mc.call_fold("concat_val", reducer=aggregators.concat)
        assert sorted(got) == [1, 2, 3, 4]
    finally:
        for srv, _ in servers:
            srv.stop()


def test_mclient_partial_failure():
    srv, hp = _spawn(7)
    try:
        mc = RpcMClient([hp, ("127.0.0.1", 1)], timeout=2.0)
        # fold skips failed hosts (linear_mixer.cpp:470-504 semantics)
        assert mc.call_fold("value", reducer=aggregators.add) == 7
        results, errors = mc.call_collect("value")
        assert [r for _, r in results] == [7]
        assert len(errors) == 1 and errors[0].port == 1
    finally:
        srv.stop()


def test_aggregators():
    assert aggregators.merge({"a": 1}, {"b": 2}) == {"a": 1, "b": 2}
    assert aggregators.concat([1], [2]) == [1, 2]
    assert aggregators.pass_("x", "y") == "x"
    assert aggregators.all_and(True, False) is False
    assert aggregators.all_or(True, False) is True
