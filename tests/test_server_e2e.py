"""End-to-end server/client tests (≙ client_test/*.cpp, SURVEY.md §4 tier 6).

A real EngineServer on an ephemeral port, driven through the typed client
over the wire protocol — train/query round-trips, built-ins, save/load.
"""

from __future__ import annotations

import pytest

from jubatus_tpu.client import (
    BanditClient,
    ClassifierClient,
    Datum,
    NearestNeighborClient,
    RecommenderClient,
    RegressionClient,
    StatClient,
    WeightClient,
)
from jubatus_tpu.server import EngineServer

NAME = "e2e"

CLASSIFIER_CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_rules": [
            {"key": "*", "type": "str", "sample_weight": "bin", "global_weight": "bin"}
        ],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}


def _serve(engine, conf):
    srv = EngineServer(engine, conf)
    port = srv.start(0)
    return srv, port


@pytest.fixture()
def classifier():
    srv, port = _serve("classifier", CLASSIFIER_CONF)
    with ClassifierClient("127.0.0.1", port, NAME) as c:
        yield c, srv
    srv.stop()


def test_classifier_roundtrip(classifier):
    c, _srv = classifier
    n = c.train(
        [
            ["spam", Datum({"subject": "win money now"})],
            ["ham", Datum({"subject": "meeting at noon"})],
        ]
        * 5
    )
    assert n == 10
    results = c.classify([Datum({"subject": "win money"})])
    assert len(results) == 1
    best = max(results[0], key=lambda ls: ls[1])
    assert best[0] == "spam"
    labels = c.get_labels()
    assert set(labels) == {"spam", "ham"}
    assert c.set_label("neutral") is True
    assert c.delete_label("neutral") is True
    assert c.clear() is True
    assert c.get_labels() == {}


def test_builtins_and_save_load(classifier, tmp_path):
    c, srv = classifier
    srv.args.datadir = str(tmp_path)
    import json

    assert json.loads(c.get_config())["method"] == "AROW"
    c.train([["a", Datum({"x": 1.0})], ["b", Datum({"x": -1.0})]])
    status = c.get_status()
    (node_status,) = status.values()
    assert node_status["type"] == "classifier"
    assert node_status["update_count"] >= 2
    assert "RSS" in node_status
    paths = c.save("m1")
    assert len(paths) == 1 and list(paths.values())[0].endswith(".jubatus")
    before = c.classify([Datum({"x": 1.0})])
    assert c.clear()
    assert c.load("m1") is True
    after = c.classify([Datum({"x": 1.0})])
    assert [r[:][0] for r in before] == [r[:][0] for r in after]
    # standalone server: do_mix is a no-op returning False
    assert c.do_mix() is False


def test_regression_roundtrip():
    srv, port = _serve(
        "regression",
        {
            "method": "PA",
            "parameter": {"sensitivity": 0.1, "regularization_weight": 3.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]},
        },
    )
    try:
        with RegressionClient("127.0.0.1", port, NAME) as r:
            data = [[float(2 * x), Datum({"x": float(x)})] for x in range(1, 30)]
            assert r.train(data) == 29
            (est,) = r.estimate([Datum({"x": 10.0})])
            assert est == pytest.approx(20.0, rel=0.35)
            assert r.clear() is True
    finally:
        srv.stop()


def test_recommender_roundtrip():
    srv, port = _serve(
        "recommender",
        {
            "method": "inverted_index",
            "converter": {"num_rules": [{"key": "*", "type": "num"}]},
        },
    )
    try:
        with RecommenderClient("127.0.0.1", port, NAME) as r:
            assert r.update_row("r1", Datum({"a": 1.0, "b": 0.5}))
            assert r.update_row("r2", Datum({"a": 0.9, "b": 0.6}))
            assert r.update_row("r3", Datum({"a": -1.0, "c": 2.0}))
            assert sorted(r.get_all_rows()) == ["r1", "r2", "r3"]
            sims = r.similar_row_from_id("r1", 2)
            assert sims[0][0] == "r1"
            assert {s[0] for s in sims[:2]} == {"r1", "r2"}
            assert r.calc_similarity(
                Datum({"a": 1.0}), Datum({"a": 1.0})
            ) == pytest.approx(1.0, abs=1e-5)
            assert r.clear_row("r3")
            assert sorted(r.get_all_rows()) == ["r1", "r2"]
    finally:
        srv.stop()


def test_nearest_neighbor_roundtrip():
    srv, port = _serve(
        "nearest_neighbor",
        {
            "method": "euclid_lsh",
            "parameter": {"hash_num": 128},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]},
        },
    )
    try:
        with NearestNeighborClient("127.0.0.1", port, NAME) as nn:
            nn.set_row("p1", Datum({"x": 0.0, "y": 0.0}))
            nn.set_row("p2", Datum({"x": 1.0, "y": 0.0}))
            nn.set_row("p3", Datum({"x": 10.0, "y": 10.0}))
            got = nn.neighbor_row_from_datum(Datum({"x": 0.1, "y": 0.0}), 2)
            assert got[0][0] == "p1"
            assert {g[0] for g in got} == {"p1", "p2"}
    finally:
        srv.stop()


def test_stat_roundtrip():
    srv, port = _serve("stat", {"window_size": 100})
    try:
        with StatClient("127.0.0.1", port, NAME) as s:
            for v in (1.0, 2.0, 3.0, 4.0):
                assert s.push("k", v)
            assert s.sum("k") == pytest.approx(10.0)
            assert s.max("k") == pytest.approx(4.0)
            assert s.min("k") == pytest.approx(1.0)
            assert s.stddev("k") == pytest.approx(1.118, abs=1e-2)
            assert s.moment("k", 1, 0.0) == pytest.approx(2.5)
    finally:
        srv.stop()


def test_bandit_roundtrip():
    srv, port = _serve(
        "bandit",
        {"method": "epsilon_greedy", "parameter": {"epsilon": 0.0,
                                                   "assume_unrewarded": False}},
    )
    try:
        with BanditClient("127.0.0.1", port, NAME) as b:
            assert b.register_arm("a1")
            assert b.register_arm("a2")
            for _ in range(5):
                arm = b.select_arm("p")
                b.register_reward("p", arm, 1.0 if arm == "a1" else 0.0)
            info = b.get_arm_info("p")
            assert set(info) == {"a1", "a2"}
            assert all(len(v) == 2 for v in info.values())
            assert b.reset("p")
    finally:
        srv.stop()


def test_weight_roundtrip():
    srv, port = _serve(
        "weight",
        {"converter": {"num_rules": [{"key": "*", "type": "num"}]}},
    )
    try:
        with WeightClient("127.0.0.1", port, NAME) as w:
            feats = w.update(Datum({"x": 2.0}))
            assert feats and feats[0][1] == pytest.approx(2.0)
            feats = w.calc_weight(Datum({"x": 3.0}))
            assert feats and feats[0][1] == pytest.approx(3.0)
    finally:
        srv.stop()


def test_wrong_engine_method_404(classifier):
    c, _ = classifier
    from jubatus_tpu.rpc import RpcMethodNotFound

    with pytest.raises(RpcMethodNotFound):
        c.client.call("similar_row_from_id", NAME, "x", 3)
