"""Sharded checkpoint/resume tests (framework/sharded_checkpoint.py) on
the 8-device CPU mesh — save writes only shards, restore re-places by the
template's NamedShardings, metadata validation mirrors the envelope
loader's checks."""

from __future__ import annotations

import json

import numpy as np
import pytest

from jubatus_tpu.framework.save_load import SaveLoadError
from jubatus_tpu.framework.sharded_checkpoint import (
    abstract_like,
    checkpoint_metadata,
    load_sharded,
    save_sharded,
)
from jubatus_tpu.parallel.mesh import grid_mesh
from jubatus_tpu.parallel.spmd import init_spmd_state

CONFIG = json.dumps({"method": "AROW", "parameter": {}})


@pytest.fixture(scope="module")
def mesh():
    return grid_mesh(replica=2, shard=4)


@pytest.fixture()
def saved(mesh, tmp_path):
    st = init_spmd_state(mesh, 4, 64)
    st = st._replace(w=st.w + 3.25, dprec=st.dprec + 0.5)
    path = str(tmp_path / "ckpt")
    save_sharded(path, st, engine_type="classifier", model_id="m1",
                 config=CONFIG)
    return path, st


def test_roundtrip_preserves_values_and_sharding(mesh, saved):
    path, st = saved
    tmpl = abstract_like(init_spmd_state(mesh, 4, 64))
    system, st2 = load_sharded(path, tmpl, expected_type="classifier",
                               expected_config=CONFIG)
    assert system["id"] == "m1"
    assert system["sharded"] is True
    for a, b in zip(st, st2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding


def test_live_state_as_template(mesh, saved):
    path, st = saved
    fresh = init_spmd_state(mesh, 4, 64)
    _, st2 = load_sharded(path, fresh)
    np.testing.assert_allclose(np.asarray(st2.w), np.asarray(st.w))


def test_type_and_config_validation(mesh, saved):
    path, _ = saved
    tmpl = abstract_like(init_spmd_state(mesh, 4, 64))
    with pytest.raises(SaveLoadError, match="model type"):
        load_sharded(path, tmpl, expected_type="recommender")
    with pytest.raises(SaveLoadError, match="config"):
        load_sharded(path, tmpl, expected_type="classifier",
                     expected_config=json.dumps({"method": "CW"}))
    # semantic equality: different key order / whitespace still matches
    reordered = json.dumps(json.loads(CONFIG), indent=2)
    load_sharded(path, tmpl, expected_type="classifier",
                 expected_config=reordered)


def test_overwrite_existing(mesh, saved):
    path, st = saved
    st3 = st._replace(w=st.w * 2.0)
    save_sharded(path, st3, engine_type="classifier", model_id="m2",
                 config=CONFIG)
    system, st4 = load_sharded(path, abstract_like(st3))
    assert system["id"] == "m2"
    np.testing.assert_allclose(np.asarray(st4.w), np.asarray(st3.w))


def test_metadata_without_reading_arrays(saved):
    path, _ = saved
    md = checkpoint_metadata(path)
    assert md["system"]["type"] == "classifier"
    assert md["arrays"]["w"]["shape"] == [2, 4, 64]
    assert md["arrays"]["w"]["dtype"] == "float32"
    assert md["arrays"]["w"]["partition_spec"] == ["replica", "None", "shard"]


def test_jubadump_reads_checkpoint_dirs(saved, capsys):
    from jubatus_tpu.cmd import jubadump

    path, _ = saved
    assert jubadump.main(["-i", path]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["system"]["type"] == "classifier"
    assert out["system"]["config"]["method"] == "AROW"
    assert out["arrays"]["dw"]["shape"] == [2, 4, 64]


def test_torn_overwrite_detected(mesh, saved):
    """New state + stale sidecar (crash between the two commits) must be
    rejected via the pairing token, not silently mispaired."""
    import os
    import shutil

    path, st = saved
    sidecar = os.path.join(path, "system.jubatus")
    stale = sidecar + ".stale"
    shutil.copy(sidecar, stale)
    save_sharded(path, st._replace(w=st.w * 7.0), engine_type="classifier",
                 model_id="m-new", config=CONFIG)
    shutil.copy(stale, sidecar)  # simulate: state committed, sidecar not
    with pytest.raises(SaveLoadError, match="pairing mismatch"):
        load_sharded(path, abstract_like(st))


def test_jubadump_cli_corrupt_dir_exits_cleanly(saved, capsys):
    import os

    from jubatus_tpu.cmd import jubadump

    path, _ = saved
    sysfile = os.path.join(path, "system.jubatus")
    open(sysfile, "wb").write(b"not a container")
    assert jubadump.main(["-i", path]) == 1
    err = capsys.readouterr().err
    assert "truncated" in err or "magic" in err


@pytest.mark.parametrize("n_from,n_to", [(4, 1), (1, 4), (4, 2), (2, 8)])
def test_reshard_on_restore(tmp_path, n_from, n_to):
    """ISSUE 13: a checkpoint saved at N shards restores BIT-EXACT onto
    an M-shard template (N→1, 1→M, N→M) — the template's shardings
    govern placement, the bytes are layout-independent."""
    import jax

    from jubatus_tpu.ops.classifier import init_state
    from jubatus_tpu.parallel import sharded_model as sm

    dim = 64

    def featured(n):
        st = init_state(4, dim, True)
        if n > 1:
            return sm.place_state(sm.feature_shard_mesh(n), st, dim)
        return st

    rng = np.random.default_rng(7)
    src = featured(n_from)
    src = src._replace(
        w=src.w + jax.numpy.asarray(rng.normal(size=(4, dim)),
                                    dtype=jax.numpy.float32),
        dprec=src.dprec + 0.25)
    path = str(tmp_path / "ckpt")
    save_sharded(path, src, engine_type="classifier", model_id="rs",
                 config=CONFIG)
    md = checkpoint_metadata(path)
    if n_from > 1:
        assert md["system"]["shard_layout"] == {"shard": n_from}
    tmpl = abstract_like(featured(n_to))
    system, out = load_sharded(path, tmpl, expected_type="classifier",
                               expected_config=CONFIG)
    for name, (a, b) in zip(("w", "dw", "prec", "dprec"), zip(src, out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)   # bit-exact
    # restored placement follows the TEMPLATE's layout, not the source's
    for leaf, want in zip(out, tmpl):
        assert leaf.sharding == want.sharding
        if n_to > 1:
            for shard in leaf.addressable_shards:
                assert shard.data.shape[-1] == dim // n_to


def test_reshard_on_restore_grid(mesh, saved, tmp_path):
    """The 2-D (replica, shard) pod state reshards too: saved at
    (2, 4), restored at (2, 2) and (1, 1)-degenerate layouts."""
    path, st = saved
    # replica count is part of the stacked shape [R, L, D]; only the
    # shard axis reshapes freely
    for r, s in ((2, 2), (2, 1)):
        tmpl = abstract_like(init_spmd_state(grid_mesh(replica=r, shard=s),
                                             4, 64))
        _, out = load_sharded(path, tmpl)
        np.testing.assert_array_equal(np.asarray(out.w), np.asarray(st.w))
        assert out.w.sharding == tmpl.w.sharding


def test_corrupt_system_sidecar(mesh, saved, tmp_path):
    path, _ = saved
    import os

    sysfile = os.path.join(path, "system.jubatus")
    raw = bytearray(open(sysfile, "rb").read())
    raw[-1] ^= 0xFF
    open(sysfile, "wb").write(bytes(raw))
    # NB: match must not be a word appearing in tmp_path (the test name is
    # part of the path pytest puts in the message)
    with pytest.raises(SaveLoadError, match="CRC32 mismatch"):
        load_sharded(path, abstract_like(init_spmd_state(mesh, 4, 64)))
