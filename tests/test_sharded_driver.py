"""Feature-sharded classifier driver (models/classifier.py mesh mode):
one server's [L, D] tables span a local device mesh via GSPMD — results
must match the single-device driver through the full lifecycle (train,
classify, label churn, schema sync, save/load), and the state must
actually be sharded."""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.models.classifier import ClassifierConfigError, ClassifierDriver

CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {
        "string_rules": [{"key": "*", "type": "space",
                          "sample_weight": "bin", "global_weight": "bin"}],
        "num_rules": [{"key": "*", "type": "num"}],
    },
}


@pytest.fixture(scope="module")
def mesh():
    return Mesh(jax.devices()[:8], axis_names=("shard",))



def _train_both(a, b, rng, n=40):
    for i in range(n):
        x = float(rng.normal())
        lbl = "pos" if x > 0 else "neg"
        d = Datum({"x": x, "b": 1.0, "w": f"tok{i % 7}"})
        a.train([(lbl, d)])
        b.train([(lbl, d)])


def test_sharded_matches_dense_lifecycle(mesh, rng):
    dense = ClassifierDriver(CONF, dim_bits=12)
    shard = ClassifierDriver(CONF, dim_bits=12, mesh=mesh)
    # state really lives sharded
    assert "shard" in str(shard.state.w.sharding)
    assert len(shard.state.w.addressable_shards) == 8
    _train_both(dense, shard, rng)
    assert dense.get_labels() == shard.get_labels()
    q = [Datum({"x": 0.7, "b": 1.0}), Datum({"x": -0.7, "b": 1.0})]
    for rd, rs in zip(dense.classify(q), shard.classify(q)):
        assert [l for l, _ in rd] == [l for l, _ in rs]
        np.testing.assert_allclose([s for _, s in rd], [s for _, s in rs],
                                   rtol=1e-5, atol=1e-6)

    # label churn: grow past capacity (8) and delete — sharding must stick
    for i in range(10):
        shard.set_label(f"extra{i}")
        dense.set_label(f"extra{i}")
    assert shard.capacity == dense.capacity > 8
    assert "shard" in str(shard.state.w.sharding)
    shard.delete_label("extra3")
    dense.delete_label("extra3")
    assert dense.get_labels().keys() == shard.get_labels().keys()

    # schema sync rebuild keeps placement
    union = sorted(shard.get_labels())
    shard.sync_schema(union)
    dense.sync_schema(union)
    assert "shard" in str(shard.state.w.sharding)
    for rd, rs in zip(dense.classify(q), shard.classify(q)):
        np.testing.assert_allclose(sorted(s for _, s in rd),
                                   sorted(s for _, s in rs),
                                   rtol=1e-5, atol=1e-6)


def test_sharded_save_load_roundtrip(mesh, rng, tmp_path):
    from jubatus_tpu.framework import load_model, save_model

    shard = ClassifierDriver(CONF, dim_bits=12, mesh=mesh)
    dense = ClassifierDriver(CONF, dim_bits=12)
    _train_both(dense, shard, rng, n=20)
    path = str(tmp_path / "s.jubatus")
    save_model(path, shard, config=json.dumps(CONF))
    # a sharded checkpoint loads into a DENSE driver (envelope is host-side)
    dense2 = ClassifierDriver(CONF, dim_bits=12)
    load_model(path, dense2, expected_config=json.dumps(CONF))
    q = [Datum({"x": 0.4, "b": 1.0})]
    np.testing.assert_allclose(
        [s for _, s in dense.classify(q)[0]],
        [s for _, s in dense2.classify(q)[0]], rtol=1e-5, atol=1e-6)
    # ... and back into a sharded one, which re-places the arrays
    shard2 = ClassifierDriver(CONF, dim_bits=12, mesh=mesh)
    load_model(path, shard2, expected_config=json.dumps(CONF))
    assert "shard" in str(shard2.state.w.sharding)
    np.testing.assert_allclose(
        [s for _, s in shard.classify(q)[0]],
        [s for _, s in shard2.classify(q)[0]], rtol=1e-5, atol=1e-6)


def test_indivisible_dim_rejected():
    import jax
    from jax.sharding import Mesh

    mesh3 = Mesh(np.array(jax.devices()[:3]), axis_names=("shard",))
    with pytest.raises(ClassifierConfigError, match="not divisible"):
        ClassifierDriver(CONF, dim_bits=4, mesh=mesh3)  # 16 features / 3 devs


def test_server_level_shard_devices(rng):
    """EngineServer --shard-devices: full RPC stack on a sharded model."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        ServerArgs(engine="classifier", shard_devices=4))
    assert len(srv.driver.state.w.addressable_shards) == 4
    port = srv.start(0)
    try:
        with ClassifierClient("127.0.0.1", port, "sd") as c:
            assert c.train([["up", Datum({"x": 1.0}).to_msgpack()],
                            ["down", Datum({"x": -1.0}).to_msgpack()]]) == 2
            (res,) = c.classify([Datum({"x": 0.9}).to_msgpack()])
            assert max(res, key=lambda e: e[1])[0] == "up"
    finally:
        srv.stop()


def test_sharded_regression_matches_dense(mesh, rng):
    from jubatus_tpu.models.regression import RegressionDriver

    cfg = {"method": "PA1",
           "parameter": {"sensitivity": 0.1, "regularization_weight": 1.0},
           "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    dense = RegressionDriver(cfg, dim_bits=12)
    shard = RegressionDriver(cfg, dim_bits=12, mesh=mesh)
    assert len(shard.state.w.addressable_shards) == 8
    for _ in range(30):
        x = float(rng.uniform(-1, 1))
        d = Datum({"x": x, "b": 1.0})
        dense.train([(2.0 * x + 1.0, d)])
        shard.train([(2.0 * x + 1.0, d)])
    q = [Datum({"x": 0.5, "b": 1.0}), Datum({"x": -0.5, "b": 1.0})]
    np.testing.assert_allclose(shard.estimate(q), dense.estimate(q),
                               rtol=1e-5, atol=1e-6)
    shard.clear()
    assert "shard" in str(shard.state.w.sharding)
    assert shard.estimate(q) == [0.0, 0.0]


def test_factory_mesh_routing(mesh):
    """--shard-devices routes per engine family: feature-sharding for the
    linear engines, NNBackend row-sharding for instance engines with hash
    methods, a clear error for everything else."""
    from jubatus_tpu.server.factory import create_driver

    with pytest.raises(ValueError, match="not supported"):
        create_driver("stat", {"window_size": 10}, mesh=mesh)
    # instance engine + hash method → backend mesh attached
    nn = create_driver("nearest_neighbor", {
        "method": "lsh", "parameter": {"hash_num": 16},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]},
    }, mesh=mesh)
    assert nn.backend._mesh is mesh
    # instance-classifier hash method too
    cnn = create_driver("classifier", {
        "method": "NN", "parameter": {"method": "lsh",
                                      "parameter": {"hash_num": 8}},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]},
    }, mesh=mesh)
    assert cnn.backend._mesh is mesh
    # exact methods have no sharded scan → NNBackend rejects
    with pytest.raises(ValueError, match="hash methods"):
        create_driver("recommender", {
            "method": "inverted_index", "parameter": {},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]},
        }, mesh=mesh)
    # anomaly rides sharded_distances (LOF needs full vectors)
    an = create_driver("anomaly", {
        "method": "lof",
        "parameter": {"nearest_neighbor_num": 5,
                      "reverse_nearest_neighbor_num": 10,
                      "method": "lsh", "parameter": {"hash_num": 8}},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]},
    }, mesh=mesh)
    assert an.backend._mesh is mesh


def test_sharded_nn_server_end_to_end(rng):
    """--shard-devices on a nearest_neighbor server: rows are served from
    the row-sharded table over RPC."""
    from jubatus_tpu.client import NearestNeighborClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    conf = {"method": "lsh", "parameter": {"hash_num": 64},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    srv = EngineServer("nearest_neighbor", conf,
                       ServerArgs(engine="nearest_neighbor", shard_devices=8))
    assert srv.driver.backend._mesh is not None
    port = srv.start(0)
    try:
        with NearestNeighborClient("127.0.0.1", port, "snn") as c:
            for i in range(20):
                c.set_row(f"r{i}", Datum({"x": float(i), "y": float(i % 5)}))
            near = c.neighbor_row_from_id("r3", 5)
            assert any(r == "r3" for r, _ in near)
            assert len(near) == 5
    finally:
        srv.stop()


@pytest.mark.slow
def test_sharded_servers_mix_across_cluster(rng):
    """Intra-server feature sharding composes with cross-server mixing:
    two servers, each spanning 4 local devices, average models over the
    RPC mix plane and converge to shared knowledge."""
    from jubatus_tpu.client import ClassifierClient
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    servers = []
    for _ in range(2):
        args = ServerArgs(
            engine="classifier", coordinator="(shared)", name="shmix",
            listen_addr="127.0.0.1", shard_devices=4,
            interval_sec=1e9, interval_count=1 << 30,
        )
        srv = EngineServer("classifier", CONF, args,
                           coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    clients = [ClassifierClient("127.0.0.1", s.args.rpc_port, "shmix")
               for s in servers]
    try:
        for _ in range(10):
            clients[0].train([["pos", Datum({"x": 1.0}).to_msgpack()]])
            clients[1].train([["neg", Datum({"x": -1.0}).to_msgpack()]])
        assert clients[0].do_mix() is True
        for c in clients:
            assert set(c.get_labels()) == {"pos", "neg"}
            (r,) = c.classify([Datum({"x": 1.0}).to_msgpack()])
            assert max(r, key=lambda e: e[1])[0] == "pos"
        # sharding survived the mix round's put_diff
        for s in servers:
            assert "shard" in str(s.driver.state.w.sharding)
    finally:
        for c in clients:
            c.close()
        for s in servers:
            s.stop()
