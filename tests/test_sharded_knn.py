"""Mesh-sharded similarity scan tests on the virtual 8-device CPU mesh
(the CHT-row-sharding replacement, SURVEY.md §5)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jubatus_tpu.ops import knn
from jubatus_tpu.parallel.mesh import replica_mesh
from jubatus_tpu.parallel.sharded_knn import (
    replicate,
    shard_table,
    sharded_hamming_topk,
)
from jax.sharding import Mesh


@pytest.fixture(scope="module")
def shard_mesh():
    devices = np.asarray(jax.devices()[:8])
    return Mesh(devices, axis_names=("shard",))


def test_sharded_topk_matches_single_device(shard_mesh, rng):
    B, C, W, k = 4, 1024, 4, 8
    hash_num = W * 32
    q = jnp.asarray(rng.integers(0, 2**32, size=(B, W), dtype=np.uint32))
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))

    dist, gidx = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=hash_num, k=k)

    # ground truth: unsharded full scan
    full = np.asarray(knn._hamming_distances_batch_xla(q, rows,
                                                       hash_num=hash_num))
    want = np.sort(full, axis=1)[:, :k]
    np.testing.assert_allclose(np.sort(np.asarray(dist), axis=1), want,
                               atol=1e-6)
    # indices must actually point at rows with those distances
    d = np.asarray(dist)
    g = np.asarray(gidx)
    for b in range(B):
        for j in range(k):
            assert full[b, g[b, j]] == pytest.approx(d[b, j], abs=1e-6)


def test_sharded_topk_exact_match_row(shard_mesh, rng):
    B, C, W = 1, 512, 2
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))
    q = rows[137:138]  # exact row → distance 0 at global index 137
    dist, gidx = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=64, k=3)
    assert float(dist[0, 0]) == 0.0
    assert int(gidx[0, 0]) == 137


def test_sharded_topk_k_larger_than_shard(shard_mesh, rng):
    """k greater than any single shard's row count still yields the global
    best k (merge must not truncate per-shard)."""
    B, C, W, k = 2, 64, 2, 16  # 8 rows per shard < k
    q = jnp.asarray(rng.integers(0, 2**32, size=(B, W), dtype=np.uint32))
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))
    dist, _ = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=64, k=k)
    full = np.asarray(knn._hamming_distances_batch_xla(q, rows, hash_num=64))
    np.testing.assert_allclose(np.sort(np.asarray(dist), axis=1),
                               np.sort(full, axis=1)[:, :k], atol=1e-6)
