"""Mesh-sharded similarity scan tests on the virtual 8-device CPU mesh
(the CHT-row-sharding replacement, SURVEY.md §5)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jubatus_tpu.ops import knn
from jubatus_tpu.parallel.mesh import replica_mesh
from jubatus_tpu.parallel.sharded_knn import (
    replicate,
    shard_table,
    sharded_hamming_topk,
)
from jax.sharding import Mesh


@pytest.fixture(scope="module")
def shard_mesh():
    devices = np.asarray(jax.devices()[:8])
    return Mesh(devices, axis_names=("shard",))


def test_sharded_topk_matches_single_device(shard_mesh, rng):
    B, C, W, k = 4, 1024, 4, 8
    hash_num = W * 32
    q = jnp.asarray(rng.integers(0, 2**32, size=(B, W), dtype=np.uint32))
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))

    dist, gidx = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=hash_num, k=k)

    # ground truth: unsharded full scan
    full = np.asarray(knn._hamming_distances_batch_xla(q, rows,
                                                       hash_num=hash_num))
    want = np.sort(full, axis=1)[:, :k]
    np.testing.assert_allclose(np.sort(np.asarray(dist), axis=1), want,
                               atol=1e-6)
    # indices must actually point at rows with those distances
    d = np.asarray(dist)
    g = np.asarray(gidx)
    for b in range(B):
        for j in range(k):
            assert full[b, g[b, j]] == pytest.approx(d[b, j], abs=1e-6)


def test_sharded_topk_exact_match_row(shard_mesh, rng):
    B, C, W = 1, 512, 2
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))
    q = rows[137:138]  # exact row → distance 0 at global index 137
    dist, gidx = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=64, k=3)
    assert float(dist[0, 0]) == 0.0
    assert int(gidx[0, 0]) == 137


def test_sharded_topk_k_larger_than_shard(shard_mesh, rng):
    """k greater than any single shard's row count still yields the global
    best k (merge must not truncate per-shard)."""
    B, C, W, k = 2, 64, 2, 16  # 8 rows per shard < k
    q = jnp.asarray(rng.integers(0, 2**32, size=(B, W), dtype=np.uint32))
    rows = jnp.asarray(rng.integers(0, 2**32, size=(C, W), dtype=np.uint32))
    dist, _ = sharded_hamming_topk(
        shard_mesh, replicate(shard_mesh, q),
        shard_table(shard_mesh, rows), hash_num=64, k=k)
    full = np.asarray(knn._hamming_distances_batch_xla(q, rows, hash_num=64))
    np.testing.assert_allclose(np.sort(np.asarray(dist), axis=1),
                               np.sort(full, axis=1)[:, :k], atol=1e-6)


# -- merge_topk edge cases (ISSUE 16 satellite) ------------------------------

def _merge(scores, ids, k):
    from jubatus_tpu.parallel.sharded_knn import merge_topk
    s, i = merge_topk(jnp.asarray(scores, jnp.float32),
                      jnp.asarray(ids, jnp.int32), k)
    return np.asarray(s), np.asarray(i)


def test_merge_topk_k_exceeds_live_rows():
    """k past the live-candidate count: the dead (-inf) sentinels fill
    the tail slots and every live candidate still surfaces, ordered."""
    ninf = -np.inf
    scores = np.array([[[5.0, ninf, ninf, ninf]],
                       [[3.0, 2.0, ninf, ninf]],
                       [[ninf, ninf, ninf, ninf]],
                       [[9.0, ninf, ninf, ninf]]])  # [S=4, B=1, kk=4]
    ids = np.arange(16, dtype=np.int32).reshape(4, 1, 4)
    s, i = _merge(scores, ids, k=10)
    assert s.shape == (1, 10)
    live = s[0][np.isfinite(s[0])]
    np.testing.assert_allclose(live, [9.0, 5.0, 3.0, 2.0])
    assert list(i[0][:4]) == [12, 0, 4, 5]
    assert not np.isfinite(s[0][4:]).any()


def test_merge_topk_all_dead_shards():
    """Every slot dead (fresh/empty arenas): the merge must return a
    full [B, k] frame of non-finite scores, not crash or fabricate."""
    scores = np.full((8, 2, 4), -np.inf)
    ids = np.zeros((8, 2, 4), np.int32)
    s, i = _merge(scores, ids, k=4)
    assert s.shape == (2, 4) and i.shape == (2, 4)
    assert not np.isfinite(s).any()


def test_merge_topk_cross_shard_ties_pin_ascending_id():
    """Equal scores arriving from different shards order by ascending
    id regardless of shard pairing — the determinism contract the ANN
    and exact paths both lean on for reproducible answers."""
    scores = np.array([[[1.0, 0.5]], [[1.0, 0.25]],
                       [[1.0, 0.125]], [[1.0, 0.0625]]])  # 4-way tie at 1.0
    ids = np.array([[[30, 31]], [[10, 11]],
                    [[20, 21]], [[0, 1]]], np.int32)
    s, i = _merge(scores, ids, k=4)
    np.testing.assert_allclose(s[0], [1.0, 1.0, 1.0, 1.0])
    assert list(i[0]) == [0, 10, 20, 30]
    # shard order reversed → identical answer
    s2, i2 = _merge(scores[::-1].copy(), ids[::-1].copy(), k=4)
    np.testing.assert_allclose(s2[0], s[0])
    assert list(i2[0]) == list(i[0])
