"""Feature-sharded linear model tests (parallel/sharded_model.py,
ISSUE 13 tentpole): shard_map'd train/classify must match the
single-device kernels to f32 rounding across shard counts, the drivers
must route through the sharded path transparently, and the per-shard
diff chunks must fold/apply without ever materializing the matrix."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.ops import classifier as cops
from jubatus_tpu.ops import regression as rops
from jubatus_tpu.parallel import sharded_model as sm

D, L, B, K = 512, 4, 48, 8
SHARD_COUNTS = (2, 4, 8)   # >= 3 shard counts per the acceptance criteria


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("shard",))


def _batch(rng, b=B, k=K, dim=D):
    idx = rng.integers(0, dim, (b, k)).astype(np.int32)
    val = rng.normal(size=(b, k)).astype(np.float32)
    labels = rng.integers(0, 3, b).astype(np.int32)
    mask = np.zeros(L, bool)
    mask[:3] = True
    return (jnp.asarray(idx), jnp.asarray(val), jnp.asarray(labels),
            jnp.asarray(mask))


@pytest.mark.parametrize("n_shards", SHARD_COUNTS)
@pytest.mark.parametrize("method", ("AROW", "PA1", "CW"))
def test_train_and_scores_parity(method, n_shards, rng):
    conf = method in cops.CONFIDENCE_METHODS
    mesh = _mesh(n_shards)
    idx, val, labels, mask = _batch(rng)
    ref = cops.train_batch(cops.init_state(L, D, conf), idx, val, labels,
                           mask, 1.0, method=method)
    st = sm.place_state(mesh, cops.init_state(L, D, conf), D)
    # two consecutive batches: the second trains against the first's
    # diffs, so divergence would compound — parity must hold after both
    idx2, val2, labels2, _ = _batch(rng)
    ref = cops.train_batch(ref, idx2, val2, labels2, mask, 1.0,
                           method=method)
    st = sm.train_batch(mesh, st, idx, val, labels, mask, 1.0,
                        method=method)
    st = sm.train_batch(mesh, st, idx2, val2, labels2, mask, 1.0,
                        method=method)
    for name, (a, b) in zip(("w", "dw", "prec", "dprec"), zip(ref, st)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5, err_msg=name)
    qi, qv, _, _ = _batch(rng)
    np.testing.assert_allclose(
        np.asarray(sm.scores(mesh, st, qi, qv, mask)),
        np.asarray(cops.scores(ref, qi, qv, mask)),
        rtol=3e-5, atol=3e-5)


def test_per_device_footprint_is_sliced(rng):
    """The acceptance criterion's memory shape: each device holds
    exactly D/S columns of every feature-spanning leaf — never the
    full matrix."""
    mesh = _mesh(4)
    st = sm.place_state(mesh, cops.init_state(L, D, True), D)
    for leaf in st:
        for shard in leaf.addressable_shards:
            assert shard.data.shape[-1] == D // 4


@pytest.mark.parametrize("n_shards", SHARD_COUNTS)
@pytest.mark.parametrize("method", ("PA", "PA1", "PA2"))
def test_regression_parity(method, n_shards, rng):
    mesh = _mesh(n_shards)
    idx = jnp.asarray(rng.integers(0, D, (24, K)).astype(np.int32))
    val = jnp.asarray(rng.normal(size=(24, K)).astype(np.float32))
    tgt = jnp.asarray(rng.normal(size=24).astype(np.float32))
    ref = rops.train_batch(rops.init_state(D), idx, val, tgt, 0.1, 1.0,
                           method=method)
    st = sm.place_state(mesh, rops.init_state(D), D)
    st = sm.regression_train_batch(mesh, st, idx, val, tgt, 0.1, 1.0,
                                   method=method)
    np.testing.assert_allclose(np.asarray(ref.dw), np.asarray(st.dw),
                               rtol=3e-5, atol=3e-5)
    np.testing.assert_allclose(
        np.asarray(sm.regression_estimate(mesh, st, idx, val)),
        np.asarray(rops.estimate(ref, idx, val)), rtol=3e-5, atol=3e-5)


def test_chunk_roundtrip_and_layout_validation(rng):
    mesh = _mesh(4)
    st = sm.place_state(mesh, cops.init_state(L, D, True), D)
    idx, val, labels, mask = _batch(rng)
    st = sm.train_batch(mesh, st, idx, val, labels, mask, 1.0,
                        method="AROW")
    chunks = sm.shard_chunks(st.dw)
    assert set(chunks) == {f"c{i * (D // 4)}" for i in range(4)}
    assert all(c.shape == (L, D // 4) for c in chunks.values())
    assert sm.is_chunked(chunks) and not sm.is_chunked({"x": 1}) \
        and not sm.is_chunked(np.zeros(3))
    back = sm.assemble_chunks(chunks, sm.chunk_sharding(mesh, rank=2))
    np.testing.assert_allclose(np.asarray(back), np.asarray(st.dw))
    # row trimming rides the chunker
    trimmed = sm.shard_chunks(st.dw, rows=2)
    assert all(c.shape == (2, D // 4) for c in trimmed.values())
    # a peer with a different layout must be rejected, not mis-folded
    wrong = dict(chunks)
    wrong.pop(f"c{D // 4}")
    with pytest.raises(ValueError, match="layout mismatch"):
        sm.assemble_chunks(wrong, sm.chunk_sharding(mesh, rank=2))


def _driver(conf, **kw):
    from jubatus_tpu.server.factory import create_driver

    return create_driver("classifier", dict(conf), **kw)


CONF = {"method": "AROW", "parameter": {"regularization_weight": 1.0},
        "converter": {"num_rules": [{"key": "*", "type": "num"}]}}


def _datum(rng):
    return Datum({f"f{j}": float(v)
                  for j, v in enumerate(rng.normal(size=8))})


@pytest.mark.parametrize("n_shards", SHARD_COUNTS)
def test_driver_classify_parity_across_shard_counts(n_shards, rng):
    plain = _driver(CONF)
    shard = _driver(CONF, mesh=_mesh(n_shards))
    data = [("a" if i % 2 else "b", _datum(rng)) for i in range(64)]
    plain.train(data)
    shard.train(data)
    q = [_datum(rng) for _ in range(8)]
    for ra, rb in zip(plain.classify(q), shard.classify(q)):
        for (la, sa), (lb, sb) in zip(ra, rb):
            assert la == lb
            np.testing.assert_allclose(sa, sb, rtol=1e-4, atol=1e-4)
    stats = shard.shard_stats()
    assert stats["count"] == n_shards
    assert stats["bytes_per_shard"] == stats["bytes_in_use"] // n_shards
    assert shard.get_status()["shard.count"] == n_shards


def test_mix_round_through_sharded_layout(rng):
    """One full get_diff→fold→put_diff round with per-shard chunks:
    two sharded replicas fold to the same model an unsharded pair does,
    and the wire carries chunk dicts (never one full-matrix leaf)."""
    a, b = _driver(CONF, mesh=_mesh(4)), _driver(CONF, mesh=_mesh(4))
    pa, pb = _driver(CONF), _driver(CONF)
    data_a = [("a" if i % 2 else "b", _datum(rng)) for i in range(32)]
    data_b = [("b" if i % 3 else "a", _datum(rng)) for i in range(32)]
    for d, data in ((a, data_a), (b, data_b), (pa, data_a), (pb, data_b)):
        d.train(data)
        d.sync_schema(["a", "b"])   # the mix round's schema phase
    mix_a = a.get_mixables()["classifier"]
    mix_b = b.get_mixables()["classifier"]
    da, db = mix_a.get_diff(), mix_b.get_diff()
    assert sm.is_chunked(da["dw"]) and sm.is_chunked(db["dw"])
    total = {
        "dw": {k: da["dw"][k] + db["dw"][k] for k in da["dw"]},
        "dprec": {k: da["dprec"][k] + db["dprec"][k] for k in da["dprec"]},
        "count": np.float32(da["count"] + db["count"]),
        "label_counts": da["label_counts"] + db["label_counts"],
    }
    mix_a.put_diff(total)
    mix_b.put_diff(total)
    # the unsharded control round
    pma = pa.get_mixables()["classifier"]
    pmb = pb.get_mixables()["classifier"]
    pda, pdb = pma.get_diff(), pmb.get_diff()
    ptotal = {k: (pda[k] + pdb[k] if not isinstance(pda[k], dict) else pda[k])
              for k in pda}
    pma.put_diff(ptotal)
    q = [_datum(rng) for _ in range(6)]
    for ra, rb, rc in zip(a.classify(q), b.classify(q), pa.classify(q)):
        da_, db_, dc_ = dict(ra), dict(rb), dict(rc)
        assert set(da_) == set(db_) == set(dc_)
        for lab in da_:
            np.testing.assert_allclose(da_[lab], db_[lab],
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(da_[lab], dc_[lab],
                                       rtol=1e-4, atol=1e-4)


def test_unsharded_member_applies_sharded_diff(rng):
    """Mixed fleets: an unsharded replica receiving per-shard chunks
    reassembles on host and stays score-identical."""
    shard = _driver(CONF, mesh=_mesh(4))
    plain = _driver(CONF)
    data = [("a" if i % 2 else "b", _datum(rng)) for i in range(32)]
    shard.train(data)
    plain.set_label("a")
    plain.set_label("b")
    for d in (shard, plain):
        d.sync_schema(["a", "b"])   # the mix round's schema phase
    diff = shard.get_mixables()["classifier"].get_diff()
    plain.get_mixables()["classifier"].put_diff(diff)
    shard.get_mixables()["classifier"].put_diff(diff)
    q = [_datum(rng) for _ in range(6)]
    for ra, rb in zip(plain.classify(q), shard.classify(q)):
        da_, db_ = dict(ra), dict(rb)
        assert set(da_) == set(db_)
        for lab in da_:
            np.testing.assert_allclose(da_[lab], db_[lab],
                                       rtol=1e-4, atol=1e-4)


def test_shard_features_flag_resolution():
    from jubatus_tpu.parallel.sharded_model import mesh_for_features

    # dim 2^18 (driver default) / 2^16 per shard = 4 shards
    drv = _driver(CONF, shard_features=1 << 16)
    assert drv._mesh is not None and drv._mesh.shape["shard"] == 4
    assert mesh_for_features(256, 256) is None      # one shard = no mesh
    with pytest.raises(ValueError, match="does not divide"):
        mesh_for_features(256, 100)
    with pytest.raises(ValueError, match="local devices"):
        mesh_for_features(256, 16)  # 16 shards > 8 virtual devices


def test_jubactl_renders_shard_layout():
    """ISSUE 13 satellite: status --all and the watch view surface the
    shard layout from the shard.* gauges."""
    from jubatus_tpu.cmd.jubactl import _fmt_shard_layout, _watch_node_row

    st = {"driver.shard.count": 8, "driver.shard.rows": 1200,
          "driver.shard.rows_per_shard": [150] * 8,
          "driver.shard.bytes_in_use": 256 * 2 ** 20,
          "driver.shard.topk_merge_ms": 12.5,
          "health.status": "ok"}
    line = _fmt_shard_layout(st)
    assert line.startswith("shards: 8 ×")
    assert "150/150" in line and "topk_merge 12.5 ms" in line
    row = _watch_node_row("n1", {"status": st}, active=True)
    assert "sh 8x1200r" in row
    # feature-sharded (no rows_per_shard): MB-per-shard form
    st2 = {"driver.shard.count": 4,
           "driver.shard.bytes_in_use": 2048 * 2 ** 20,
           "health.status": "ok"}
    assert "512MB" in _watch_node_row("n2", {"status": st2}, active=True)
    assert _fmt_shard_layout({"health.status": "ok"}) == ""


def test_sequential_mode_keeps_gspmd_path(rng):
    """train_mode="sequential" (exact per-datum semantics) still works
    under a mesh — the GSPMD-partitioned kernels serve it."""
    from jubatus_tpu.models.classifier import ClassifierDriver

    drv = ClassifierDriver(dict(CONF), train_mode="sequential",
                           mesh=_mesh(4))
    ref = ClassifierDriver(dict(CONF), train_mode="sequential")
    data = [("a" if i % 2 else "b", _datum(rng)) for i in range(16)]
    drv.train(data)
    ref.train(data)
    q = [_datum(rng) for _ in range(4)]
    for ra, rb in zip(ref.classify(q), drv.classify(q)):
        for (la, sa), (lb, sb) in zip(ra, rb):
            assert la == lb
            np.testing.assert_allclose(sa, sb, rtol=1e-4, atol=1e-4)
