"""Streaming-sketch tests (ISSUE 17): signed log-bucket geometry,
merge algebra (commutativity + merge-vs-single-stream equivalence),
bounded memory under cardinality churn, quantile walks, and the
reference-vs-live snapshot ring."""

from __future__ import annotations

import numpy as np
import pytest

from jubatus_tpu.utils import sketches
from jubatus_tpu.utils.sketches import (
    NBINS, ZERO_BIN, CategoricalSketch, SnapshotRing, ValueSketch,
    bin_rep, categorical_freqs, merge_categorical_states,
    merge_value_states, value_bin, value_bins, value_quantile,
)


# -- signed log-bucket geometry ----------------------------------------------


def test_value_bins_order_along_real_line():
    """Bins are ordered like the reals: more negative -> lower bin,
    zero -> ZERO_BIN, larger positive -> higher bin."""
    vals = [-100.0, -1.0, -0.001, 0.0, 0.001, 1.0, 100.0]
    bins = [value_bin(v) for v in vals]
    assert bins == sorted(bins)
    assert value_bin(0.0) == ZERO_BIN
    assert value_bin(1.0) > ZERO_BIN > value_bin(-1.0)
    assert all(0 <= b < NBINS for b in bins)


def test_bin_rep_roundtrip_sign_and_magnitude():
    for v in (-7.3, -0.02, 0.5, 3.0, 90.0):
        rep = bin_rep(value_bin(v))
        assert np.sign(rep) == np.sign(v)
        # quarter-octave buckets: representative within ~2x of the value
        assert 0.5 <= abs(rep) / abs(v) <= 2.0
    assert bin_rep(ZERO_BIN) == 0.0


def test_value_bins_vectorized_matches_scalar():
    rng = np.random.default_rng(7)
    v = rng.normal(scale=10.0, size=256)
    v[::17] = 0.0
    assert list(value_bins(v)) == [value_bin(float(x)) for x in v]


# -- value sketch + merge algebra --------------------------------------------


def _sketch_of(values) -> ValueSketch:
    sk = ValueSketch()
    sk.observe_array(np.asarray(values, dtype=np.float64))
    return sk


def test_value_sketch_moments_and_nonfinite_mask():
    sk = ValueSketch()
    n = sk.observe_array(np.array([1.0, -2.0, np.nan, np.inf, 0.0]))
    assert n == 3 and sk.count == 3
    assert sk.min == -2.0 and sk.max == 1.0
    st = sk.state()
    assert sum(st["bins"].values()) == 3
    assert st["min"] == -2.0 and st["max"] == 1.0


def test_value_merge_commutative_and_equals_single_stream():
    """merge(a, b) == merge(b, a) == sketch(a ++ b): bins/count/min/max
    exact; float sums may differ in the last ulp (accumulation order)."""
    rng = np.random.default_rng(11)
    a = rng.normal(loc=1.0, size=500)
    b = rng.exponential(size=300) - 0.5
    ab = merge_value_states([_sketch_of(a).state(), _sketch_of(b).state()])
    ba = merge_value_states([_sketch_of(b).state(), _sketch_of(a).state()])
    one = _sketch_of(np.concatenate([a, b])).state()
    for merged in (ab, ba):
        assert merged["bins"] == one["bins"]
        assert merged["count"] == one["count"] == 800
        assert merged["min"] == one["min"]
        assert merged["max"] == one["max"]
        assert merged["sum"] == pytest.approx(one["sum"], abs=1e-9)


def test_value_merge_string_keys_and_empty_states():
    """msgpack map keys may arrive as strings; empty states fold away."""
    st = _sketch_of([1.0, 2.0]).state()
    wired = dict(st, bins={str(k): v for k, v in st["bins"].items()})
    merged = merge_value_states([{}, wired, {"bins": {}, "count": 0}])
    assert merged["bins"] == st["bins"] and merged["count"] == 2


def test_value_quantile_walk():
    rng = np.random.default_rng(3)
    v = rng.uniform(1.0, 100.0, size=4000)
    st = _sketch_of(v).state()
    for q in (0.1, 0.5, 0.9):
        exact = float(np.quantile(v, q))
        got = value_quantile(st, q)
        assert got == pytest.approx(exact, rel=0.25)
    assert value_quantile({"count": 0, "bins": {}}, 0.5) is None
    # quantiles clamp into the observed range
    assert value_quantile(st, 0.0) >= st["min"]
    assert value_quantile(st, 1.0) <= st["max"]


def test_value_sketch_memory_is_fixed():
    """The dense array never grows: 219 bins regardless of stream size
    or value range."""
    sk = ValueSketch()
    rng = np.random.default_rng(5)
    for _ in range(10):
        sk.observe_array(rng.normal(scale=1e6, size=1000))
    assert sk.bins.shape == (NBINS,)
    assert sk.count == 10000


# -- categorical sketch ------------------------------------------------------


def test_categorical_freqs_and_other_residual():
    sk = CategoricalSketch(k=2)
    for item, n in (("a", 50), ("b", 30), ("c", 15), ("d", 5)):
        sk.observe(item, n)
    fr = categorical_freqs(sk.state())
    assert fr["a"] == pytest.approx(0.5)
    assert fr["b"] == pytest.approx(0.3)
    # only k=2 heavy hitters kept; the tail mass lands in __other__
    assert set(fr) == {"a", "b", "__other__"}
    assert sum(fr.values()) == pytest.approx(1.0)


def test_categorical_merge_commutative_and_equals_single_stream():
    a, b, one = CategoricalSketch(), CategoricalSketch(), CategoricalSketch()
    for i in range(200):
        item = "lab%d" % (i % 7)
        (a if i % 2 else b).observe(item)
        one.observe(item)
    ab = merge_categorical_states([a.state(), b.state()])
    ba = merge_categorical_states([b.state(), a.state()])
    assert ab == ba
    assert ab["total"] == one.state()["total"] == 200
    assert ab["rows"] == one.state()["rows"]
    assert categorical_freqs(ab) == categorical_freqs(one.state())


def test_categorical_bounded_under_cardinality_churn():
    """10k distinct labels through a k=16 sketch: the matrix stays at
    its fixed geometry and the top-k dict never exceeds k."""
    sk = CategoricalSketch()
    for i in range(10000):
        sk.observe("u%d" % i)
    st = sk.state()
    assert sk.rows.shape == (sk.depth, sk.width)
    assert len(st["topk"]) <= sk.k
    assert st["total"] == 10000
    # heavy hitter injected after churn still surfaces
    for _ in range(2000):
        sk.observe("whale")
    assert "whale" in sk.state()["topk"]


def test_categorical_merge_geometry_mismatch_skipped():
    a = CategoricalSketch(width=512)
    b = CategoricalSketch(width=64)
    a.observe("x", 10)
    b.observe("y", 99)
    merged = merge_categorical_states([a.state(), b.state()])
    assert merged["total"] == 10  # mismatched matrix skipped, not corrupted


# -- snapshot ring -----------------------------------------------------------


def test_snapshot_ring_eviction_and_pinned_reference():
    ring = SnapshotRing(capacity=3)
    ring.pin_reference({"win": "ref"}, ts=100.0)
    for i in range(6):
        ring.push({"win": i}, ts=200.0 + i)
    assert [p["doc"]["win"] for p in ring.points()] == [3, 4, 5]
    assert ring.newest() == {"win": 5}
    assert ring.points(last=2)[0]["doc"]["win"] == 4
    # the reference survives ring eviction
    assert ring.reference == {"win": "ref"}
    st = ring.stats()
    assert st["pushed"] == 6 and st["retained"] == 3
    assert st["reference_pinned"] and st["reference_ts"] == 100.0


def test_top_bins_rendering_helper():
    st = _sketch_of([5.0] * 90 + [-1.0] * 10).state()
    top = sketches.top_bins(st, n=2)
    assert len(top) == 2
    assert top[0][1] == 90 and top[0][0] == pytest.approx(5.0, rel=0.5)
    assert top[1][1] == 10 and top[1][0] < 0
