"""SPMD train+mix step tests on the virtual 8-device mesh — the multi-chip
path the driver dry-runs (dp psum mix x feature-shard partial-score psum)."""

import numpy as np
import jax.numpy as jnp
import pytest

from jubatus_tpu.parallel.mesh import grid_mesh, replica_mesh
from jubatus_tpu.parallel.spmd import init_spmd_state, make_spmd_train_step
from jubatus_tpu.ops import classifier as C


def _data(rng, r, b, k, dim, labels_n):
    idx = jnp.asarray(rng.integers(1, dim, size=(r, b, k), dtype=np.int32))
    val = jnp.asarray(rng.normal(size=(r, b, k)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, labels_n, size=(r, b), dtype=np.int32))
    return idx, val, y


@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
def test_spmd_step_matches_single_device_reference(mesh_kind, rng):
    """The sharded step must produce exactly the model a single device would:
    train each replica's batch on one state copy, sum diffs, average."""
    mesh = grid_mesh(4, 2) if mesh_kind == "2d" else replica_mesh(4)
    r, dim, L, B, K = 4, 128, 4, 8, 4
    mask = jnp.ones(L, dtype=bool)
    idx, val, y = _data(rng, r, B, K, dim, L)

    state = init_spmd_state(mesh, L, dim, confidence=True)
    step = make_spmd_train_step(mesh, method="AROW", param=1.0, mix=True)
    out = step(state, idx, val, y, mask)
    w_spmd = np.asarray(out.w)

    # reference: per-replica local training from the same zero state
    diffs = []
    for i in range(r):
        st = C.init_state(L, dim, True)
        st = C.train_batch(st, idx[i], val[i], y[i], mask, 1.0, method="AROW")
        diffs.append(C.get_diff(st))
    total = diffs[0]
    for d in diffs[1:]:
        total = C.mix_diffs(total, d)
    w_ref = np.asarray(total["dw"]) / r
    prec_ref = 1.0 + np.asarray(total["dprec"])

    for i in range(r):
        np.testing.assert_allclose(w_spmd[i], w_ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out.prec)[i], prec_ref, rtol=1e-5, atol=1e-6)


def test_spmd_no_mix_keeps_local_diffs(rng):
    mesh = replica_mesh(2)
    r, dim, L, B, K = 2, 64, 2, 4, 2
    mask = jnp.ones(L, dtype=bool)
    idx, val, y = _data(rng, r, B, K, dim, L)
    state = init_spmd_state(mesh, L, dim)
    step = make_spmd_train_step(mesh, method="PA", param=1.0, mix=False)
    out = step(state, idx, val, y, mask)
    dw = np.asarray(out.dw)
    assert np.abs(dw).sum() > 0
    assert np.abs(np.asarray(out.w)).sum() == 0.0  # masters untouched until mix
    # replicas trained different data -> different local diffs
    assert not np.allclose(dw[0], dw[1])
