"""Stat + bandit engine tests (API parity with stat.idl / bandit.idl,
mix semantics via the LocalMixGroup stub seam — SURVEY.md §4 tier 2)."""

import math

import numpy as np
import pytest

from jubatus_tpu.models.bandit import BanditConfigError, BanditDriver
from jubatus_tpu.models.stat import StatDriver
from jubatus_tpu.parallel import LocalMixGroup


# ---------------------------------------------------------------------------
# stat
# ---------------------------------------------------------------------------
def test_stat_basic_reductions():
    s = StatDriver({"window_size": 128})
    for v in [1.0, 2.0, 3.0, 4.0]:
        s.push("x", v)
    assert s.sum("x") == 10.0
    assert s.max("x") == 4.0
    assert s.min("x") == 1.0
    assert s.stddev("x") == pytest.approx(np.std([1, 2, 3, 4]))
    assert s.moment("x", 1, 0.0) == pytest.approx(2.5)
    assert s.moment("x", 2, 2.5) == pytest.approx(np.mean((np.arange(1, 5) - 2.5) ** 2))


def test_stat_window_eviction():
    s = StatDriver({"window_size": 3})
    for v in [1, 2, 3, 4, 5]:
        s.push("k", v)
    # window holds the last 3 values
    assert s.sum("k") == 12.0
    assert s.min("k") == 3.0


def test_stat_entropy_across_keys():
    s = StatDriver({"window_size": 16})
    for _ in range(2):
        s.push("a", 1.0)
    for _ in range(2):
        s.push("b", 1.0)
    # two keys, equal counts -> H = log 2
    assert s.entropy() == pytest.approx(math.log(2))
    assert s.entropy("a") == s.entropy("b")  # key is routing-only


def test_stat_missing_key_raises():
    s = StatDriver({"window_size": 4})
    with pytest.raises(KeyError):
        s.sum("nope")


def test_stat_save_load_roundtrip():
    s = StatDriver({"window_size": 4})
    for v in [1, 2, 3, 4, 5]:
        s.push("k", v)
    s.push("j", 7.0)
    packed = s.pack()
    s2 = StatDriver({"window_size": 4})
    s2.unpack(packed)
    assert s2.sum("k") == s.sum("k")
    assert s2.min("k") == s.min("k")
    assert s2.sum("j") == 7.0


def test_stat_mix_entropy_uses_cluster_counts():
    a = StatDriver({"window_size": 16})
    b = StatDriver({"window_size": 16})
    for _ in range(4):
        a.push("x", 1.0)
    for _ in range(4):
        b.push("y", 1.0)
    LocalMixGroup([a, b]).mix()
    # cluster-wide: two keys with 4 each -> log 2 on BOTH replicas
    assert a.entropy() == pytest.approx(math.log(2))
    assert b.entropy() == pytest.approx(math.log(2))


# ---------------------------------------------------------------------------
# bandit
# ---------------------------------------------------------------------------
def _cfg(method, **param):
    return {"method": method, "parameter": {"assume_unrewarded": False, **param}}


def test_bandit_register_and_info():
    b = BanditDriver(_cfg("ucb1"))
    assert b.register_arm("a")
    assert b.register_arm("b")
    assert not b.register_arm("a")
    b.register_reward("p1", "a", 1.0)
    info = b.get_arm_info("p1")
    assert info["a"] == {"trial_count": 1, "weight": 1.0}
    assert info["b"] == {"trial_count": 0, "weight": 0.0}
    assert b.delete_arm("b")
    assert "b" not in b.get_arm_info("p1")


def test_bandit_ucb1_tries_all_then_exploits():
    b = BanditDriver(_cfg("ucb1"))
    for a in ("a", "b", "c"):
        b.register_arm(a)
    seen = set()
    for _ in range(3):
        arm = b.select_arm("p")
        seen.add(arm)
        b.register_reward("p", arm, 1.0 if arm == "b" else 0.0)
    assert seen == {"a", "b", "c"}
    # equalize trial counts so the exploration bonus cancels; b's mean wins
    for _ in range(20):
        b.register_reward("p", "a", 0.0)
        b.register_reward("p", "b", 1.0)
        b.register_reward("p", "c", 0.0)
    assert b.select_arm("p") == "b"


def test_bandit_epsilon_greedy_zero_eps_is_greedy():
    b = BanditDriver(_cfg("epsilon_greedy", epsilon=0.0))
    b.register_arm("bad")
    b.register_arm("good")
    b.register_reward("p", "good", 5.0)
    b.register_reward("p", "bad", 0.1)
    for _ in range(5):
        assert b.select_arm("p") == "good"


def test_bandit_assume_unrewarded_counts_trials_on_select():
    b = BanditDriver({"method": "ucb1",
                      "parameter": {"assume_unrewarded": True}})
    b.register_arm("a")
    b.select_arm("p")
    assert b.get_arm_info("p")["a"]["trial_count"] == 1
    b.register_reward("p", "a", 2.0)
    info = b.get_arm_info("p")
    assert info["a"]["trial_count"] == 1  # reward does not double-count
    assert info["a"]["weight"] == 2.0


def test_bandit_softmax_and_exp3_prefer_rewarded_arm():
    for method, param in (("softmax", {"tau": 0.05}), ("exp3", {"gamma": 0.3})):
        b = BanditDriver(_cfg(method, **param), seed=1)
        b.register_arm("x")
        b.register_arm("y")
        for _ in range(30):
            b.register_reward("p", "y", 1.0)
        picks = [b.select_arm("p") for _ in range(50)]
        assert picks.count("y") > picks.count("x")


def test_bandit_reset_and_clear():
    b = BanditDriver(_cfg("ucb1"))
    b.register_arm("a")
    b.register_reward("p", "a", 1.0)
    b.reset("p")
    assert b.get_arm_info("p")["a"]["trial_count"] == 0
    b.clear()
    assert b.arms == []


def test_bandit_bad_config():
    with pytest.raises(BanditConfigError):
        BanditDriver({"method": "thompson"})
    with pytest.raises(BanditConfigError):
        BanditDriver(_cfg("softmax", tau=0.0))


def test_bandit_mix_merges_player_stats():
    a = BanditDriver(_cfg("ucb1"))
    b = BanditDriver(_cfg("ucb1"))
    for d in (a, b):
        d.register_arm("arm")
    a.register_reward("p", "arm", 1.0)
    b.register_reward("p", "arm", 2.0)
    b.register_reward("q", "arm", 5.0)
    LocalMixGroup([a, b]).mix()
    for d in (a, b):
        info = d.get_arm_info("p")
        assert info["arm"]["trial_count"] == 2
        assert info["arm"]["weight"] == pytest.approx(3.0)
        assert d.get_arm_info("q")["arm"]["weight"] == pytest.approx(5.0)
    # second mix must not double-apply (diffs cleared)
    LocalMixGroup([a, b]).mix()
    assert a.get_arm_info("p")["arm"]["weight"] == pytest.approx(3.0)


def test_bandit_save_load_roundtrip():
    b = BanditDriver(_cfg("exp3", gamma=0.2), seed=3)
    b.register_arm("a")
    b.register_arm("b")
    for _ in range(5):
        arm = b.select_arm("p")
        b.register_reward("p", arm, 1.0)
    packed = b.pack()
    b2 = BanditDriver(_cfg("exp3", gamma=0.2))
    b2.unpack(packed)
    assert b2.get_arm_info("p") == b.get_arm_info("p")
