"""Concurrency stress tests: many client threads hammering one server with
mixed train/classify/status/mix traffic. The reference's locking story is
decorators + convention (SURVEY.md §5 'race detection: by convention');
this is the test the convention never had.
"""

from __future__ import annotations

import threading

import pytest

from jubatus_tpu.client import ClassifierClient, Datum, StatClient
from jubatus_tpu.server import EngineServer

CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


@pytest.mark.slow
def test_concurrent_train_classify_status_mix():
    srv = EngineServer("classifier", CONF)
    port = srv.start(0)
    errors = []
    stop = threading.Event()

    def worker(kind: str, n: int) -> None:
        try:
            c = ClassifierClient("127.0.0.1", port, "", timeout=30.0)
            for i in range(n):
                if stop.is_set():
                    break
                if kind == "train":
                    c.train([["pos", Datum({"x": 1.0, "i": float(i)})],
                             ["neg", Datum({"x": -1.0, "i": -float(i)})]])
                elif kind == "classify":
                    c.classify([Datum({"x": 1.0})])
                elif kind == "status":
                    st = c.get_status()
                    assert st
                else:  # mix (standalone → returns False, must not crash)
                    c.do_mix()
            c.close()
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append((kind, e))
            stop.set()

    threads = [threading.Thread(target=worker, args=(k, n)) for k, n in [
        ("train", 40), ("train", 40), ("classify", 60), ("classify", 60),
        ("status", 30), ("mix", 15),
    ]]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads), "stress worker hung"
    try:
        # model is intact and usable after the storm
        c = ClassifierClient("127.0.0.1", port, "")
        (res,) = c.classify([Datum({"x": 1.0})])
        assert max(res, key=lambda s: s[1])[0] == "pos"
        total = c.get_labels()
        assert total["pos"] == total["neg"] == 80
        c.close()
    finally:
        srv.stop()


@pytest.mark.slow
def test_concurrent_cluster_mix_and_train():
    """Trains racing against background mixes across a 2-node cluster."""
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    servers = []
    for _ in range(2):
        args = ServerArgs(engine="stat", coordinator="(shared)", name="st",
                          listen_addr="127.0.0.1",
                          interval_sec=0.2, interval_count=5)  # mix hard
        s = EngineServer("stat", {"window_size": 256}, args,
                         coord=MemoryCoordinator(store))
        s.start(0)
        s.mixer.start()
        servers.append(s)
    errors = []
    try:
        def pusher(port: int, key: str) -> None:
            try:
                c = StatClient("127.0.0.1", port, "st", timeout=30.0)
                for i in range(150):
                    c.push(key, float(i % 7))
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=pusher,
                                    args=(s.args.rpc_port, f"k{j}"))
                   for j, s in enumerate(servers) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        # data survived the mixing storm: each key answers sum() on the
        # node that ingested it (stat is key-sharded; the proxy's cht
        # routing pins queries there, test_proxy.py covers that hop)
        for j, s in enumerate(servers):
            c = StatClient("127.0.0.1", s.args.rpc_port, "st")
            assert c.sum(f"k{j}") > 0
            c.close()
    finally:
        for s in servers:
            s.stop()
